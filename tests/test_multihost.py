"""Two-process jax.distributed integration test (VERDICT.md round-1 #9).

Round 1 only exercised `init_distributed`/`hybrid_mesh` in a single process.
Here a real 2-process × 4-virtual-CPU-device cluster is launched via
subprocesses, and the full multi-host path runs end to end:
`init_distributed` (explicit coordinator args) → `hybrid_mesh` with the data
axis spanning DCN (process granules) → `process_local_batch` feeding
per-host shards → `jax.make_array_from_process_local_data` → one jitted
sharded reduction whose collective crosses the process boundary. Each worker
checks the global result against the analytic value.

SURVEY.md §5.8; runs on CPU only (no TPU needed).
"""

import pytest
import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow


_REPO = Path(__file__).resolve().parent.parent

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, {repo!r})
    from wam_tpu.parallel.multihost import (
        hybrid_mesh, init_distributed, process_local_batch,
    )

    pid = int(sys.argv[1])
    info = init_distributed(
        coordinator_address={coord!r}, num_processes=2, process_id=pid
    )
    assert info["process_count"] == 2, info
    assert info["global_devices"] == 8, info

    mesh = hybrid_mesh({{"data": -1, "sample": 2}}, dcn_axis="data")
    assert mesh.shape["data"] == 4 and mesh.shape["sample"] == 2

    # per-host input pipeline: each process materializes only its shard
    global_batch = 8
    local = process_local_batch(global_batch)
    assert local == 4
    local_rows = np.arange(local, dtype=np.float32) + pid * local  # 0..3 / 4..7
    local_data = np.tile(local_rows[:, None], (1, 16))

    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec("data", None))
    garr = jax.make_array_from_process_local_data(sharding, local_data)
    assert garr.shape == (global_batch, 16)

    @jax.jit
    def total(a):
        return (a * 2.0).sum()

    got = float(total(garr))
    want = 2.0 * 16 * sum(range(global_batch))
    assert got == want, (got, want)
    print(f"WORKER{{pid}}_OK", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Real-WAM cluster worker (VERDICT.md round-2 next #4): the actual
# attribution pipeline (sharded SmoothGrad over a WamEngine step on a tiny
# ResNet) and a mesh-attached Eval2DWAM insertion run ACROSS the process
# boundary, and every process checks the gathered global result against the
# single-process 8-device golden the pytest process computed beforehand.
_WAM_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    sys.path.insert(0, {repo!r})
    from wam_tpu.parallel.multihost import hybrid_mesh, init_distributed

    pid = int(sys.argv[1])
    golden_path = sys.argv[2]
    init_distributed(
        coordinator_address={coord!r}, num_processes=2, process_id=pid
    )
    mesh = hybrid_mesh({{"data": -1, "sample": 2}}, dcn_axis="data")
    assert mesh.shape == {{"data": 4, "sample": 2}}

    from tests.multihost_wam_case import build_case

    case = build_case()
    out = case["smoothgrad_runner"](mesh)
    full = np.asarray(multihost_utils.process_allgather(out, tiled=True))

    ins = case["insertion_runner"](mesh)

    golden = np.load(golden_path)
    # not bitwise: the 2-process partitioner lowers the cross-host mean with
    # a different reduction tree than single-process (measured max diff
    # 1.8e-7); everything else in the step is identical
    np.testing.assert_allclose(full, golden["mosaic"], atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ins), golden["ins"], atol=1e-6)

    # long-context machinery across the DCN boundary: the analysis ring
    # ppermute, the reversed synthesis ring, and the replicated tails of the
    # default-mode gradient loop all span the two processes on a pure
    # {{"data": 8}} mesh; every process checks its addressable shards
    # against the single-process golden slices
    from tests.multihost_wam_case import build_halo_case

    seq_mesh = hybrid_mesh({{"data": -1}}, dcn_axis="data")
    assert dict(seq_mesh.shape) == {{"data": 8}}
    halo = build_halo_case()
    for i, leaf in enumerate(halo["dec_runner"](seq_mesh)):
        want = golden[f"dec_{{i}}"]
        for shard in leaf.addressable_shards:
            np.testing.assert_allclose(
                np.asarray(shard.data), want[shard.index], atol=1e-6
            )
    for i, g in enumerate(halo["mode_grads_runner"](seq_mesh)):
        wc, wt = golden[f"gcore_{{i}}"], golden[f"gtail_{{i}}"]
        for shard in g.core.addressable_shards:
            np.testing.assert_allclose(
                np.asarray(shard.data), wc[shard.index], atol=1e-5
            )
        for shard in g.tail.addressable_shards:
            np.testing.assert_allclose(np.asarray(shard.data), wt, atol=1e-5)
    print(f"WAMWORKER{{pid}}_OK", flush=True)
    """
)


def test_two_process_real_wam_matches_single_process(tmp_path):
    """sharded_smoothgrad + Eval2DWAM.insertion on a 2-process hybrid mesh
    reproduce the single-process 8-device result exactly."""
    import numpy as np

    from tests.multihost_wam_case import build_case
    from wam_tpu.parallel import hybrid_mesh

    # golden: same global mesh shape, one process, 8 devices
    from tests.multihost_wam_case import build_halo_case

    case = build_case()
    mesh = hybrid_mesh({"data": 4, "sample": 2})
    golden_mosaic = np.asarray(case["smoothgrad_runner"](mesh))
    golden_ins = np.asarray(case["insertion_runner"](mesh))
    halo = build_halo_case()
    seq_mesh = hybrid_mesh({"data": 8})
    extras = {}
    for i, leaf in enumerate(halo["dec_runner"](seq_mesh)):
        extras[f"dec_{i}"] = np.asarray(leaf)
    for i, g in enumerate(halo["mode_grads_runner"](seq_mesh)):
        extras[f"gcore_{i}"] = np.asarray(g.core)
        extras[f"gtail_{i}"] = np.asarray(g.tail)
    golden_path = tmp_path / "golden.npz"
    np.savez(golden_path, mosaic=golden_mosaic, ins=golden_ins, **extras)

    coord = f"127.0.0.1:{_free_port()}"
    code = _WAM_WORKER.format(repo=str(_REPO), coord=coord)
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(pid), str(golden_path)],
            cwd=str(_REPO),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"WAMWORKER{pid}_OK" in out, out[-2000:]


def test_two_process_distributed_end_to_end():
    coord = f"127.0.0.1:{_free_port()}"
    code = _WORKER.format(repo=str(_REPO), coord=coord)
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(pid)],
            cwd=str(_REPO),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"WORKER{pid}_OK" in out, out[-2000:]


def test_init_distributed_raises_on_unreachable_coordinator():
    """ADVICE.md round-1 item 3: a genuine bring-up failure must raise, not
    silently degrade to single-process."""
    code = textwrap.dedent(
        f"""
        import sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, {str(_REPO)!r})
        from wam_tpu.parallel.multihost import init_distributed
        try:
            init_distributed(
                coordinator_address="127.0.0.1:1", num_processes=2, process_id=1,
                initialization_timeout=5,
            )
        except Exception as e:
            print("RAISED", type(e).__name__, flush=True)
        else:
            print("SWALLOWED", flush=True)
        """
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    # worker id 1 connects to the (dead) coordinator and must fail fast
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(_REPO),
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    # The coordination client either raises (caught → RAISED) or hard-aborts
    # the process (absl LOG(FATAL) on RegisterTask deadline). Both are
    # acceptable; what must NEVER happen is init_distributed returning as if
    # single-process (SWALLOWED).
    assert "SWALLOWED" not in proc.stdout, (proc.stdout + proc.stderr)[-3000:]
    assert "RAISED" in proc.stdout or proc.returncode != 0, (
        proc.stdout + proc.stderr
    )[-3000:]
