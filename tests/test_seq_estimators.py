"""Sequence-sharded estimator composition (round-5 verdict #2).

The long-context gradient cores (`parallel.halo`, `parallel.halo_modes`)
compose with SmoothGrad / IG and surface through the class API
(`WaveletAttribution{1,2,3}D(mesh=, seq_axis=)`). Parity is asserted against
the single-device estimators on the virtual 8-device mesh; the HLO audits
mirror tests/test_halo_modes.py (no signal-sized all-gather in the sharded
gradient step; the noise draw is shard-local — no all-gather at all).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import (
    need_devices as _need_devices,
    need_modern_shard_map as _need_modern_shard_map,
    scan_gathers as _scan_gathers,
)
from wam_tpu.parallel.mesh import make_mesh


def _pool_model_2d(n_classes=5, channels=3, shape=(64, 32), seed=0):
    """Sequence-partitionable toy vision model with NON-degenerate
    gradients: per-class spatial templates contracted over (C, H, W) — the
    contraction over the sharded row axis is an all-reduce, never a gather,
    and ∂logit/∂x varies spatially so detail-coefficient gradients are
    nonzero (a global-average-pool model's are ~0, which turns the
    normalized mosaic into amplified float noise)."""
    w = jax.random.normal(jax.random.PRNGKey(seed),
                          (n_classes, channels) + shape)

    def model(x):  # (B, C, H, W)
        return jnp.einsum("bchw,kchw->bk", x, w)

    return model


def _pool_model_3d(n_classes=4, seed=0):
    w = jax.random.normal(jax.random.PRNGKey(seed), (8, n_classes))

    def model(x):  # (B, 1, D, H, W)
        pooled = x[:, 0].mean(axis=(2, 3))  # (B, D)
        feat = pooled.reshape(pooled.shape[0], 8, -1).mean(axis=-1)  # (B, 8)
        return feat @ w

    return model


def _mel_model_1d(n_classes=4, n_mels=32, seed=0):
    w = jax.random.normal(jax.random.PRNGKey(seed), (n_mels, n_classes))

    def model(mel):  # (N, 1, T, n_mels)
        return mel[:, 0].mean(axis=1) @ w  # pool time -> (N, n_mels) @ w

    return model


def _put_seq(x, mesh, ndim):
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * x.ndim
    spec[x.ndim - ndim] = "data"
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


# ---------------------------------------------------------------------------
# class-level parity: one call, sequence-sharded, vs the single-device class
# ---------------------------------------------------------------------------


@pytest.fixture
def matmul_stft():
    """The mesh path pins the matmul STFT (the partitionable form); pin it
    globally so the single-device twin computes the same values."""
    from wam_tpu.ops.melspec import get_stft_impl, set_stft_impl

    prev = get_stft_impl()
    set_stft_impl("matmul")
    yield
    set_stft_impl(prev)


def test_wam1d_class_mesh_smooth_parity(matmul_stft):
    _need_devices(8)
    from wam_tpu.wam1d import WaveletAttribution1D

    mesh = make_mesh({"data": 8})
    model = _mel_model_1d()
    kw = dict(wavelet="db2", J=2, mode="symmetric", n_fft=256, n_mels=32,
              sample_rate=8000, n_samples=3, stdev_spread=0.05,
              random_seed=7)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 2048))
    y = jnp.array([1, 2])

    sharded = WaveletAttribution1D(model, mesh=mesh, **kw)
    mel_s, coeff_s = sharded.smooth_wam(_put_seq(x, mesh, 1), y)

    single = WaveletAttribution1D(model, stream_noise=True,
                                  sample_batch_size=None, **kw)
    mel_1, coeff_1 = single.smooth_wam(x, y)

    np.testing.assert_allclose(np.asarray(mel_s), np.asarray(mel_1), atol=1e-5)
    assert len(coeff_s) == len(coeff_1)
    for g, w in zip(coeff_s, coeff_1):
        assert g.shape == w.shape
        assert len(g.sharding.device_set) == 8  # grads stay sharded
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)


def test_wam1d_class_mesh_smooth_periodization(matmul_stft):
    """mode='periodization' is a mesh-path extension (the single-device
    class is expansive-modes only): parity vs a hand-built periodized
    single-device smoothgrad twin with the same fold_in noise stream."""
    _need_devices(8)
    from wam_tpu.core.estimators import smoothgrad
    from wam_tpu.ops.melspec import melspectrogram
    from wam_tpu.wam1d import WaveletAttribution1D, normalize_waveforms
    from wam_tpu.wavelets.periodized import wavedec_per, waverec_per

    mesh = make_mesh({"data": 8})
    model = _mel_model_1d()
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 2048))
    y = jnp.array([1, 2])
    kw = dict(wavelet="db2", J=2, mode="periodization", n_fft=256, n_mels=32,
              sample_rate=8000, n_samples=3, stdev_spread=0.05, random_seed=7)

    sharded = WaveletAttribution1D(model, mesh=mesh, **kw)
    mel_s, coeff_s = sharded.smooth_wam(_put_seq(x, mesh, 1), y)

    xn = normalize_waveforms(x)

    def front(wave):
        return melspectrogram(wave, sample_rate=8000, n_fft=256, n_mels=32,
                              impl="matmul")[:, None]

    def step(noisy):
        coeffs = wavedec_per(noisy, "db2", 2)
        tap0 = jnp.zeros(jax.eval_shape(
            lambda c: front(waverec_per(c, "db2")), coeffs).shape)

        def loss(cs, tap):
            mel = front(waverec_per(cs, "db2")) + tap
            out = model(mel)
            return jnp.take_along_axis(out, y[:, None], axis=1)[:, 0].mean()

        g_cs, g_tap = jax.grad(loss, argnums=(0, 1))(coeffs, tap0)
        return g_cs, g_tap

    want_cs, want_tap = smoothgrad(
        step, xn, jax.random.PRNGKey(7), n_samples=3, stdev_spread=0.05,
        materialize_noise=False)
    np.testing.assert_allclose(np.asarray(mel_s), np.asarray(want_tap[:, 0]),
                               atol=1e-5)
    for g, w in zip(coeff_s, want_cs):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)


def test_wam1d_class_mesh_ig_parity(matmul_stft):
    _need_devices(8)
    from wam_tpu.wam1d import WaveletAttribution1D

    mesh = make_mesh({"data": 8})
    model = _mel_model_1d()
    kw = dict(wavelet="haar", J=3, mode="symmetric", n_fft=256, n_mels=32,
              sample_rate=8000, n_samples=4, method="integratedgrad")
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 2048))
    y = jnp.array([0, 3])

    sharded = WaveletAttribution1D(model, mesh=mesh, **kw)
    mel_s, coeff_s = sharded(_put_seq(x, mesh, 1), y)
    single = WaveletAttribution1D(model, sample_batch_size=None, **kw)
    mel_1, coeff_1 = single(x, y)

    np.testing.assert_allclose(np.asarray(mel_s), np.asarray(mel_1), atol=1e-5)
    for g, w in zip(coeff_s, coeff_1):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)


def test_wam2d_class_mesh_smooth_parity():
    _need_devices(8)
    from wam_tpu.wam2d import WaveletAttribution2D

    mesh = make_mesh({"data": 8})
    model = _pool_model_2d()
    kw = dict(wavelet="haar", J=2, mode="reflect", n_samples=3,
              stdev_spread=0.1, random_seed=11)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 3, 64, 32))
    y = jnp.array([1, 4])

    sharded = WaveletAttribution2D(model, mesh=mesh, **kw)
    got = sharded.smooth_wam(_put_seq(x, mesh, 2), y)
    single = WaveletAttribution2D(model, stream_noise=True,
                                  sample_batch_size=None, **kw)
    want = single.smooth_wam(x, y)

    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_wam2d_class_mesh_ig_parity():
    _need_devices(8)
    from wam_tpu.wam2d import WaveletAttribution2D

    mesh = make_mesh({"data": 8})
    model = _pool_model_2d()
    kw = dict(wavelet="haar", J=2, mode="reflect", n_samples=4,
              method="integratedgrad")
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 3, 64, 32))
    y = jnp.array([0, 2])

    sharded = WaveletAttribution2D(model, mesh=mesh, **kw)
    got = sharded(_put_seq(x, mesh, 2), y)
    single = WaveletAttribution2D(model, sample_batch_size=None, **kw)
    want = single(x, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_wam2d_class_mesh_ig_single_step_parity():
    """n_samples=1 IG: the lone path point is both trapezoid endpoints
    (weight 1.0, not 0.5) — regression for the round-5 review finding."""
    _need_devices(8)
    from wam_tpu.wam2d import WaveletAttribution2D

    mesh = make_mesh({"data": 8})
    model = _pool_model_2d()
    kw = dict(wavelet="haar", J=2, mode="reflect", n_samples=1,
              method="integratedgrad")
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 3, 64, 32))
    y = jnp.array([0, 2])

    got = WaveletAttribution2D(model, mesh=mesh, **kw)(_put_seq(x, mesh, 2), y)
    want = WaveletAttribution2D(model, sample_batch_size=None, **kw)(x, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("label", [True, False])
def test_wam3d_class_mesh_smooth_parity(label):
    """Depth-sharded 3D SmoothGrad, labelled and representation (y=None)
    modes, vs the single-device class."""
    _need_devices(8)
    from wam_tpu.wam3d import WaveletAttribution3D

    mesh = make_mesh({"data": 8})
    model = _pool_model_3d()
    kw = dict(wavelet="haar", J=1, mode="symmetric", n_samples=3,
              stdev_spread=0.05, random_seed=13)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 1, 32, 8, 8))
    y = jnp.array([1, 3]) if label else None

    sharded = WaveletAttribution3D(model, mesh=mesh, **kw)
    got = sharded.smooth(_put_seq(x, mesh, 3), y)
    single = WaveletAttribution3D(model, stream_noise=True,
                                  sample_batch_size=None, **kw)
    want = single.smooth(x, y)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_wam2d_class_mesh_nhwc_parity():
    """mesh= + model_layout='nhwc' (gate lifted this PR): the channel-last
    model is wrapped with an in-graph NCHW→NHWC transpose, so the sharded
    NCHW pipeline feeds it its native layout. Same mesh + the equivalent
    NCHW model must produce the same attribution (identical draws)."""
    _need_devices(8)
    from wam_tpu.wam2d import WaveletAttribution2D

    mesh = make_mesh({"data": 8})
    model_nchw = _pool_model_2d()
    model_nhwc = lambda x: model_nchw(jnp.transpose(x, (0, 3, 1, 2)))
    kw = dict(wavelet="db2", J=2, mode="reflect", n_samples=3,
              stdev_spread=0.1, random_seed=11)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 3, 64, 32))
    y = jnp.array([1, 4])

    want = WaveletAttribution2D(model_nchw, mesh=mesh, **kw).smooth_wam(
        _put_seq(x, mesh, 2), y)
    got = WaveletAttribution2D(model_nhwc, mesh=mesh, model_layout="nhwc",
                               **kw).smooth_wam(_put_seq(x, mesh, 2), y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_wam2d_class_mesh_nhwc_ig_parity_vs_single():
    """mesh= + nhwc against the SINGLE-DEVICE nhwc engine (IG — no noise, so
    the two implementations are directly comparable): the sharded NCHW
    pipeline with the transpose-wrapped model must match the nhwc-native
    engine (`wavelets/nhwc.py`)."""
    _need_devices(8)
    from wam_tpu.wam2d import WaveletAttribution2D

    mesh = make_mesh({"data": 8})
    model_nchw = _pool_model_2d()
    model_nhwc = lambda x: model_nchw(jnp.transpose(x, (0, 3, 1, 2)))
    kw = dict(wavelet="haar", J=2, mode="reflect", n_samples=4,
              method="integratedgrad", model_layout="nhwc")
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 3, 64, 32))
    y = jnp.array([0, 2])

    got = WaveletAttribution2D(model_nhwc, mesh=mesh, **kw)(
        _put_seq(x, mesh, 2), y)
    want = WaveletAttribution2D(model_nhwc, sample_batch_size=None, **kw)(x, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_wam2d_class_mesh_dwt_bf16_parity(mode="reflect"):
    """mesh= + dwt_bf16 (gate lifted this PR): the fused step casts the
    noisy input to bf16 at the decompose boundary; both the sharded and the
    single-device analyses then upcast and accumulate f32 (the framework
    bf16-in / f32-accumulate convention), so parity holds at the normal
    tolerance — the only bf16 effect is the shared input rounding."""
    _need_devices(8)
    from wam_tpu.wam2d import WaveletAttribution2D

    mesh = make_mesh({"data": 8})
    model = _pool_model_2d()
    kw = dict(wavelet="db2", J=2, mode=mode, n_samples=3,
              stdev_spread=0.1, random_seed=11, dwt_bf16=True)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 3, 64, 32))
    y = jnp.array([1, 4])

    got = WaveletAttribution2D(model, mesh=mesh, **kw).smooth_wam(
        _put_seq(x, mesh, 2), y)
    want = WaveletAttribution2D(model, stream_noise=True,
                                sample_batch_size=None, **kw).smooth_wam(x, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


# ---------------------------------------------------------------------------
# HLO audits: gather-free gradient step, shard-local noise
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("chunk", [2, 3, 8])
def test_seq_sharded_smoothgrad_sample_chunk_parity(chunk):
    """sample_chunk flattens g samples into the batch axis (one dispatch,
    g·B model rows): identical draws and per-sample gradients as the
    sequential path — including a non-dividing chunk (remainder group)."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    mesh = make_mesh({"data": 8})
    sw = SeqShardedWam(mesh, toy_wave_model(jax.random.PRNGKey(0)), ndim=1,
                       wavelet="db3", level=2, mode="symmetric")
    x = _put_seq(jax.random.normal(jax.random.PRNGKey(1), (2, 2048)), mesh, 1)
    y = jnp.array([1, 3])
    key = jax.random.PRNGKey(9)
    # n=5: chunk=2 → three balanced chunks of g=2 with ONE pad slot (the
    # weight-0 masking branch), chunk=3 → g=3 with one pad, chunk=8 → one
    # full-vmap group — sequential/chunked/pad paths all covered
    seq = sw.smoothgrad(x, y, key, n_samples=5, stdev_spread=0.1)
    chunked = sw.smoothgrad(x, y, key, n_samples=5, stdev_spread=0.1,
                            sample_chunk=chunk)
    for a, b in zip(seq, chunked):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    # representation mode through the chunked path too
    rep_seq = sw.smoothgrad(x, None, key, n_samples=2, stdev_spread=0.1)
    rep_ch = sw.smoothgrad(x, None, key, n_samples=2, stdev_spread=0.1,
                           sample_chunk=2)
    for a, b in zip(rep_seq, rep_ch):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize("chunk", [2, 8])
def test_seq_sharded_ig_sample_chunk_parity(chunk):
    """IG α-chunking (broadcast coeffs × per-group α, trapezoid weights
    with 0 pads): identical to the sequential path — n=5 with chunk=2
    exercises the pad slot."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    mesh = make_mesh({"data": 8})
    sw = SeqShardedWam(mesh, toy_wave_model(jax.random.PRNGKey(0)), ndim=1,
                       wavelet="db3", level=2, mode="symmetric")
    x = _put_seq(jax.random.normal(jax.random.PRNGKey(1), (2, 2048)), mesh, 1)
    y = jnp.array([1, 3])
    _, seq = sw.integrated(x, y, n_steps=5)
    _, chunked = sw.integrated(x, y, n_steps=5, sample_chunk=chunk)
    for a, b in zip(seq, chunked):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_seq_sharded_batch_axis_parity_and_split():
    """batch_axis= shards the leading axis over the remaining mesh (round-5:
    sample/batch-parallel sequence sharding, periodized path). Values must
    match the seq-only-mesh estimator exactly, and the per-device
    executable must carry SPLIT batch rows (compute not replicated across
    the batch axis — checked via the compiled argument shardings)."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    model = toy_wave_model(jax.random.PRNGKey(0))
    x_host = jax.random.normal(jax.random.PRNGKey(1), (8, 2048))
    y = jnp.arange(8, dtype=jnp.int32) % 4
    key = jax.random.PRNGKey(9)

    mesh1 = make_mesh({"data": 8})
    sw1 = SeqShardedWam(mesh1, model, ndim=1, wavelet="db2", level=2,
                        mode="periodization")
    want = sw1.smoothgrad(_put_seq(x_host, mesh1, 1), y, key,
                          n_samples=4, stdev_spread=0.1, sample_chunk=2)

    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh2 = make_mesh({"batch": 2, "data": 4})
    sw2 = SeqShardedWam(mesh2, model, ndim=1, wavelet="db2", level=2,
                        mode="periodization", batch_axis="batch")
    x2 = jax.device_put(x_host, NamedSharding(mesh2, P("batch", "data")))
    got = sw2.smoothgrad(x2, y, key, n_samples=4, stdev_spread=0.1,
                         sample_chunk=2)
    for a, b in zip(got, want):
        assert len(a.sharding.device_set) == 8
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    # the dec stage's compiled input must be sharded over BOTH axes (batch
    # split = compute split; replicated batch would show P(None, 'data'))
    noisy = sw2._noisy_chunk(x2, key, jnp.int32(0),
                             jnp.asarray(0.1, x2.dtype), g=2)
    in_shardings = sw2.dec._apply.lower(noisy).compile().input_shardings[0]
    spec = in_shardings[0].spec
    assert tuple(spec) == ("batch", "data"), spec

    # the 2D/3D expansive gate is LIFTED (halo_modes threads batch_axis;
    # tails stay replicated — see test_seq_sharded_batch_axis_expansive_2d)
    SeqShardedWam(mesh2, model, ndim=2, wavelet="db2", level=2,
                  mode="symmetric", batch_axis="batch")


@pytest.mark.parametrize("wavelet,mode", [("db2", "symmetric"),
                                          ("db6", "reflect")])
def test_seq_sharded_batch_axis_expansive_1d(wavelet, mode):
    """batch_axis through the 1D EXPANSIVE (core+tail) path: parity vs the
    seq-only mesh, cores and tails both carrying the batch sharding."""
    _need_devices(8)
    if (wavelet, mode) == ("db6", "reflect"):
        # legacy check_rep=False transpose double-counts the long-filter tail
        # cotangents under batch sharding (exact 2x); check_vma fixes it
        _need_modern_shard_map("legacy transpose 2x on db6 tails")
    from jax.sharding import NamedSharding, PartitionSpec as P
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    model = toy_wave_model(jax.random.PRNGKey(0))
    x_host = jax.random.normal(jax.random.PRNGKey(1), (8, 4096))
    y = jnp.arange(8, dtype=jnp.int32) % 4
    key = jax.random.PRNGKey(9)

    mesh1 = make_mesh({"data": 8})
    sw1 = SeqShardedWam(mesh1, model, ndim=1, wavelet=wavelet, level=2,
                        mode=mode)
    want = sw1.smoothgrad(_put_seq(x_host, mesh1, 1), y, key,
                          n_samples=4, stdev_spread=0.1, sample_chunk=2)

    mesh2 = make_mesh({"batch": 2, "data": 4})
    sw2 = SeqShardedWam(mesh2, model, ndim=1, wavelet=wavelet, level=2,
                        mode=mode, batch_axis="batch")
    x2 = jax.device_put(x_host, NamedSharding(mesh2, P("batch", "data")))
    got = sw2.smoothgrad(x2, y, key, n_samples=4, stdev_spread=0.1,
                         sample_chunk=2)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    # the split must be REAL: the dec stage's compiled input carries the
    # batch axis (a regression to P(None, seq) is numerically invisible)
    noisy = sw2._noisy_chunk(x2, key, jnp.int32(0),
                             jnp.asarray(0.1, x2.dtype), g=2)
    spec = sw2.dec._apply.lower(noisy).compile().input_shardings[0][0].spec
    assert tuple(spec)[:2] == ("batch", "data"), spec


def test_seq_sharded_grads_hlo_no_signal_sized_gather():
    """The estimator's per-sample gradient step (reconstruct → model → VJP)
    moves only O(L)-sized buffers: ring halos ride collective-permute, and
    no all-gather approaches signal size (mirror of
    test_sharded_coeff_grads_mode_hlo_no_signal_sized_gather, but through
    the estimator class)."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    mesh = make_mesh({"data": 8})
    sw = SeqShardedWam(mesh, toy_wave_model(jax.random.PRNGKey(0)), ndim=1,
                       wavelet="db4", level=3, mode="symmetric")
    x = _put_seq(jnp.zeros((2, 1 << 14)), mesh, 1)
    y = jnp.array([0, 1])
    coeffs = sw.dec(x)
    hlo = sw._grads.lower(coeffs, y, spatial=(1 << 14,)).compile().as_text()
    assert " collective-permute(" in hlo
    offenders = _scan_gathers(hlo, gather_cap=512)
    assert not offenders, f"signal-sized all-gather(s) in seq grads: {offenders}"


def test_seq_sharded_noise_is_shard_local():
    """The SmoothGrad draw must generate each shard's noise locally:
    partitionable threefry + the output sharding constraint mean the
    compiled noise graph contains NO all-gather at any size (the σ min/max
    reduction is an all-reduce, which is allowed)."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    assert jax.config.jax_threefry_partitionable, (
        "shard-local noise relies on partitionable threefry"
    )
    mesh = make_mesh({"data": 8})
    sw = SeqShardedWam(mesh, toy_wave_model(jax.random.PRNGKey(0)), ndim=1,
                       wavelet="db4", level=3, mode="symmetric")
    x = _put_seq(jnp.zeros((2, 1 << 14)), mesh, 1)
    hlo = sw._noisy.lower(
        x, jax.random.PRNGKey(0), jnp.int32(0), jnp.float32(0.1)
    ).compile().as_text()
    assert "all-gather" not in hlo, "noise draw must be shard-local"
    # the noisy output keeps the sequence sharding
    noisy = sw._noisy(x, jax.random.PRNGKey(0), jnp.int32(0), jnp.float32(0.1))
    assert len(noisy.sharding.device_set) == 8


# ---------------------------------------------------------------------------
# fused one-dispatch steps: bit-exactness vs the split loop, dispatch counts,
# batch_axis through the 2D/3D expansive paths
# ---------------------------------------------------------------------------


def _seq_case(ndim, wavelet, mode):
    """Small (model, x, y, level) fixture tuple per modality."""
    from wam_tpu.models.audio import toy_wave_model

    if ndim == 1:
        return (toy_wave_model(jax.random.PRNGKey(0)),
                jax.random.normal(jax.random.PRNGKey(1), (2, 2048)),
                jnp.array([1, 3]), 2)
    if ndim == 2:
        return (_pool_model_2d(),
                jax.random.normal(jax.random.PRNGKey(1), (2, 3, 64, 32)),
                jnp.array([1, 4]), 2)
    return (_pool_model_3d(),
            jax.random.normal(jax.random.PRNGKey(1), (2, 1, 32, 8, 8)),
            jnp.array([1, 3]), 1)


@pytest.mark.parametrize("ndim,wavelet,mode", [
    (1, "db3", "symmetric"),
    (1, "db2", "periodization"),
    (2, "db2", "reflect"),
    (2, "haar", "periodization"),
    (3, "db2", "symmetric"),
])
def test_seq_fused_vs_split_bitexact(ndim, wavelet, mode):
    """The fused one-jit step must be BIT-IDENTICAL to the split loop —
    same primitives, same summation order; only the jit boundary moves.
    Covers the sequential loop, the padded chunk path (n=3, chunk=2 → one
    weight-0 pad slot), and the IG trapezoid, for every modality and both
    boundary families."""
    _need_devices(8)
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    model, x_host, y, level = _seq_case(ndim, wavelet, mode)
    mesh = make_mesh({"data": 8})
    x = _put_seq(x_host, mesh, ndim)
    key = jax.random.PRNGKey(7)
    kw = dict(ndim=ndim, wavelet=wavelet, level=level, mode=mode)
    sw_f = SeqShardedWam(mesh, model, fused=True, **kw)
    sw_s = SeqShardedWam(mesh, model, fused=False, **kw)

    for chunk in (1, 2):
        got = sw_f.smoothgrad(x, y, key, n_samples=3, stdev_spread=0.1,
                              sample_chunk=chunk)
        want = sw_s.smoothgrad(x, y, key, n_samples=3, stdev_spread=0.1,
                               sample_chunk=chunk)
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    _, ig_f = sw_f.integrated(x, y, n_steps=3, sample_chunk=2)
    _, ig_s = sw_s.integrated(x, y, n_steps=3, sample_chunk=2)
    for a, b in zip(jax.tree_util.tree_leaves(ig_f),
                    jax.tree_util.tree_leaves(ig_s)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    cs_f, g_f = sw_f.attribute(x, y)
    cs_s, g_s = sw_s.attribute(x, y)
    for a, b in zip(jax.tree_util.tree_leaves((cs_f, g_f)),
                    jax.tree_util.tree_leaves((cs_s, g_s))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_seq_fused_one_dispatch_per_sample():
    """The one-dispatch contract, probed via the estimator's dispatch
    counter: fused smoothgrad launches exactly n_samples + 1 (final scale)
    dispatches, the chunked loop n_chunks + 1, attribute exactly 1,
    integrated 1 (dec) + n_steps — while the split path launches ~4× more."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    mesh = make_mesh({"data": 8})
    model = toy_wave_model(jax.random.PRNGKey(0))
    x = _put_seq(jax.random.normal(jax.random.PRNGKey(1), (2, 2048)), mesh, 1)
    y = jnp.array([1, 3])
    key = jax.random.PRNGKey(7)
    kw = dict(ndim=1, wavelet="db3", level=2, mode="symmetric")

    sw = SeqShardedWam(mesh, model, fused=True, **kw)
    sw.dispatch_count = 0
    sw.smoothgrad(x, y, key, n_samples=4, stdev_spread=0.1, sample_chunk=1)
    assert sw.dispatch_count == 4 + 1, sw.dispatch_count

    sw.dispatch_count = 0
    sw.smoothgrad(x, y, key, n_samples=4, stdev_spread=0.1, sample_chunk=2)
    assert sw.dispatch_count == 2 + 1, sw.dispatch_count

    sw.dispatch_count = 0
    sw.attribute(x, y)
    assert sw.dispatch_count == 1, sw.dispatch_count

    sw.dispatch_count = 0
    sw.integrated(x, y, n_steps=4)
    assert sw.dispatch_count == 1 + 4, sw.dispatch_count

    split = SeqShardedWam(mesh, model, fused=False, **kw)
    split.dispatch_count = 0
    split.smoothgrad(x, y, key, n_samples=4, stdev_spread=0.1,
                     sample_chunk=1)
    # noisy + dec + grads per sample, accum from the second on, final scale
    assert split.dispatch_count == 4 * 3 + 3 + 1, split.dispatch_count


@pytest.mark.parametrize("ndim", [2, 3])
def test_seq_sharded_batch_axis_expansive_23d(ndim):
    """batch_axis through the 2D/3D EXPANSIVE (core+tail) paths — the gate
    this PR lifts. Values must match the seq-only-mesh estimator, the cores
    must actually carry the batch sharding, and the O(L) tails stay fully
    replicated (constraining them batch-sharded miscompiles the synthesis
    under legacy shard_map — DESIGN.md 'Sequence-sharded fusion')."""
    _need_devices(8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from wam_tpu.parallel.halo_modes import TailedLeaf
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    if ndim == 2:
        model = _pool_model_2d()
        x_host = jax.random.normal(jax.random.PRNGKey(1), (8, 3, 64, 32))
        spec2 = P("batch", None, "data", None)
        level = 2
    else:
        model = _pool_model_3d()
        x_host = jax.random.normal(jax.random.PRNGKey(1), (8, 1, 32, 8, 8))
        spec2 = P("batch", None, "data", None, None)
        level = 1
    y = jnp.arange(8, dtype=jnp.int32) % 4
    key = jax.random.PRNGKey(9)
    kw = dict(ndim=ndim, wavelet="db2",
              mode="reflect" if ndim == 2 else "symmetric", level=level)

    mesh1 = make_mesh({"data": 8})
    sw1 = SeqShardedWam(mesh1, model, **kw)
    want = sw1.smoothgrad(_put_seq(x_host, mesh1, ndim), y, key,
                          n_samples=2, stdev_spread=0.1)

    mesh2 = make_mesh({"batch": 2, "data": 4})
    sw2 = SeqShardedWam(mesh2, model, batch_axis="batch", **kw)
    x2 = jax.device_put(x_host, NamedSharding(mesh2, spec2))
    got = sw2.smoothgrad(x2, y, key, n_samples=2, stdev_spread=0.1)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    # the batch split must be REAL on the cores, and absent on the tails
    cs = sw2.dec(x2)
    for leaf in jax.tree_util.tree_leaves(
            cs, is_leaf=lambda t: isinstance(t, TailedLeaf)):
        if not isinstance(leaf, TailedLeaf):
            continue
        assert tuple(leaf.core.sharding.spec)[:1] == ("batch",), \
            leaf.core.sharding.spec
        if leaf.tail is not None:
            assert "batch" not in tuple(
                s for s in leaf.tail.sharding.spec if s), \
                leaf.tail.sharding.spec
