"""Static-analysis subsystem (`wam_tpu.lint`): per-rule fixture corpora
(a bad file each rule MUST flag — these tests fail if detection is
disabled — and a good twin it must stay silent on), pragma and
baseline-ratchet semantics, the JSON/SARIF emitter schemas, the
`scripts/check_host_syncs.py` shim's byte-level output contract, the
env-knob audit gate, and the live-tree gates (`--all` exits 0; shim
parity against the modern host-sync rule on the real checkout).

Everything here is pure-AST — no fixture module is ever imported — so
the tests run identically with or without a device."""

import ast
import importlib.util
import json
import os
import textwrap

import pytest

from wam_tpu.lint import compat, core, knobs
from wam_tpu.lint.__main__ import main as lint_main
from wam_tpu.lint.emitters import emit_json, emit_sarif, emit_text
from wam_tpu.lint.registry import all_rules, get_rule, rule_ids
from wam_tpu.lint.rules.host_sync import LEGACY_SCOPE

REPO = core.repo_root()

ALL_RULE_IDS = {"donation-safety", "host-sync", "lock-discipline",
                "precision-flow", "retrace-risk", "schema-drift"}


def _src(source, rel="wam_tpu/fixture.py"):
    text = textwrap.dedent(source)
    tree, err = None, None
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        err = e
    return core.SourceFile(path="/fix/" + rel, rel=rel, text=text,
                           tree=tree, error=err)


def _run(source, rule_id, config=None, rel="wam_tpu/fixture.py",
         apply_pragmas=True):
    """Run one rule over one in-memory fixture; returns the LintResult."""
    ctx = core.LintContext(root=REPO, config=config or {})
    rule = get_rule(rule_id)(ctx.rule_config(rule_id))
    return core.run_rules([rule], [_src(source, rel)], ctx,
                          respect_scope=False, apply_pragmas=apply_pragmas)


def _lines(result):
    return sorted((f.rule, f.line) for f in result.findings)


# -- registry ----------------------------------------------------------------

def test_registry_has_all_rules():
    assert set(rule_ids()) == ALL_RULE_IDS
    for cls in all_rules():
        assert cls.description, cls.id
        assert cls.severity in ("error", "warning")


# -- host-sync ---------------------------------------------------------------

HOST_SYNC_BAD = '''\
import time
import numpy as np
import jax

@jax.jit
def traced(x):
    a = np.asarray(x)          # line 7
    b = x.item()               # line 8
    c = float(x)               # line 9
    d = jax.device_get(x)      # line 10
    t = time.perf_counter()    # line 11
    return a, b, c, d, t
'''

HOST_SYNC_GOOD = '''\
import numpy as np
import jax

def untraced(x):
    return float(np.asarray(x))   # host code: fine

@jax.jit
def traced(x):
    return x * 2.0
'''


def test_host_sync_bad_fixture():
    res = _run(HOST_SYNC_BAD, "host-sync")
    assert _lines(res) == [("host-sync", n) for n in (7, 8, 9, 10, 11)]
    msgs = {f.line: f.message for f in res.findings}
    assert msgs[7] == "np.asarray() in traced function"
    assert msgs[8] == ".item() in traced function"
    assert msgs[9] == "float() on a value in traced function"
    assert "device_get()" in msgs[10] and "run_fan" in msgs[10]
    assert msgs[11].startswith("time.perf_counter()")


def test_host_sync_good_fixture():
    assert _run(HOST_SYNC_GOOD, "host-sync").findings == []


def test_host_sync_traced_by_reference_and_partial():
    src = '''\
    from functools import partial
    import numpy as np

    def step(x):
        return np.asarray(x)       # line 5: traced via jit(partial(step))

    w = jit(partial(step, 1))
    '''
    res = _run(src, "host-sync")
    assert _lines(res) == [("host-sync", 5)]


def test_host_sync_nested_def_reported_once():
    src = '''\
    import numpy as np
    import jax

    @jax.jit
    def outer(x):
        def inner(y):
            return np.asarray(y)   # line 7: inside the traced body
        return inner(x)
    '''
    res = _run(src, "host-sync")
    assert _lines(res) == [("host-sync", 7)]


# -- retrace-risk ------------------------------------------------------------

RETRACE_BAD = '''\
import jax
import jax.numpy as jnp

def serve_loop(batches, f):
    for b in batches:
        g = jax.jit(f)             # line 6: wrapper rebuilt per iteration
        yield g(b)

def per_call(f, x):
    return jax.jit(f)(x)           # line 10: construct-and-invoke

@jax.jit
def traced(x, w=jnp.zeros(3)):     # line 13: array default on traced fn
    return x + w
'''

RETRACE_GOOD = '''\
import jax

g = jax.jit(lambda x: x * 2)       # module-level: cached once

def serve(batches):
    return [g(b) for b in batches]
'''


def test_retrace_bad_fixture():
    res = _run(RETRACE_BAD, "retrace-risk")
    assert _lines(res) == [("retrace-risk", n) for n in (6, 10, 13)]


def test_retrace_good_fixture():
    assert _run(RETRACE_GOOD, "retrace-risk").findings == []


def test_retrace_no_double_report_in_loop():
    src = '''\
    import jax

    def f(batches, fn):
        for b in batches:
            y = jax.jit(fn)(b)     # ONE finding, not two
        return y
    '''
    res = _run(src, "retrace-risk")
    assert _lines(res) == [("retrace-risk", 5)]


# -- donation-safety ---------------------------------------------------------

DONATION_BAD = '''\
def bad(f, x):
    g = donating_jit(f)
    out = g(x)
    return x + out                 # line 4: x was donated on line 3

def bad_inline(f, x):
    y = jit(f, donate_argnums=(0,))(x)
    return x - y                   # line 8
'''

DONATION_GOOD = '''\
from wam_tpu.pipeline.donation import donation_safe

def rebind(f, x):
    x = donating_jit(f)(x)         # donate + rebind in ONE statement
    return x                       # fresh buffer: fine

def chained(f, x):
    w = jit(f, donate_argnums=(0,))
    x = w(x)
    x = w(x)                       # each call donates the rebound x
    return x

def safe(f, x):
    g = donating_jit(f)
    out = g(donation_safe(x))      # sanctioned keep-alive wrapper
    return x + out

def no_donation(f, x):
    g = jit(f, donate_argnums=())  # empty tuple donates nothing
    out = g(x)
    return x + out
'''


def test_donation_bad_fixture():
    res = _run(DONATION_BAD, "donation-safety")
    assert _lines(res) == [("donation-safety", 4), ("donation-safety", 8)]
    assert "donated" in res.findings[0].message
    assert "donation_safe" in res.findings[0].message


def test_donation_good_fixture():
    assert _run(DONATION_GOOD, "donation-safety").findings == []


def test_donation_reports_once_per_donation():
    src = '''\
    def f(g, x):
        w = donating_jit(g)
        y = w(x)
        a = x + 1                  # line 4: first read -> finding
        b = x + 2                  # same donation: not re-reported
        return a, b, y
    '''
    res = _run(src, "donation-safety")
    assert _lines(res) == [("donation-safety", 4)]


# -- lock-discipline ---------------------------------------------------------

LOCKS_BAD = '''\
import threading

class Server:
    _GUARDED_BY = {"_queue": "_lock", "_closed": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []           # __init__ is exempt (happens-before)
        self._closed = False

    def submit(self, item):
        self._queue.append(item)   # line 12: mutator without the lock

    def close(self):
        self._closed = True        # line 15: assign without the lock
'''

LOCKS_GOOD = '''\
import threading

class Server:
    _GUARDED_BY = {"_queue": "_lock", "_closed": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._closed = False

    def submit(self, item):
        with self._lock:
            self._queue.append(item)

    def close(self):
        with self._lock:
            self._closed = True

    def unrelated(self):
        self._scratch = 1          # not in _GUARDED_BY: fine
'''


def test_locks_bad_fixture():
    res = _run(LOCKS_BAD, "lock-discipline")
    assert _lines(res) == [("lock-discipline", 12), ("lock-discipline", 15)]
    assert "_GUARDED_BY" in res.findings[0].message
    assert "self._lock" in res.findings[0].message


def test_locks_good_fixture():
    assert _run(LOCKS_GOOD, "lock-discipline").findings == []


def test_locks_nested_def_does_not_inherit_lock():
    src = '''\
    import threading

    class S:
        _GUARDED_BY = {"_rows": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._rows = []

        def spawn(self):
            with self._lock:
                def cb():
                    self._rows.append(1)   # line 13: closure outlives block
                return cb
    '''
    res = _run(src, "lock-discipline")
    assert _lines(res) == [("lock-discipline", 13)]


# -- precision-flow ----------------------------------------------------------

PRECISION_BAD = '''\
import jax.numpy as jnp

def kernel(x, w):
    xb = x.astype(jnp.bfloat16)
    return jnp.matmul(xb, w)       # line 5: bf16 contraction, no f32 accum

def op(x, w):
    xb = x.astype(jnp.bfloat16)
    return xb @ w                  # line 9: @ cannot request an accumulator
'''

PRECISION_GOOD = '''\
import jax.numpy as jnp

def kernel(x, w):
    xb = x.astype(jnp.bfloat16)
    return jnp.matmul(xb, w, preferred_element_type=jnp.float32)

def upcast_clears(x, w):
    xb = x.astype(jnp.bfloat16)
    xf = xb.astype(jnp.float32)    # back to f32: taint cleared
    return jnp.matmul(xf, w)

def f32_only(x, w):
    return jnp.matmul(x, w)        # no bf16 in sight
'''


def test_precision_bad_fixture():
    res = _run(PRECISION_BAD, "precision-flow")
    assert _lines(res) == [("precision-flow", 5), ("precision-flow", 9)]
    assert "preferred_element_type" in res.findings[0].message


def test_precision_good_fixture():
    assert _run(PRECISION_GOOD, "precision-flow").findings == []


def test_precision_taint_flows_through_branches():
    src = '''\
    import jax.numpy as jnp

    def f(x, w, flag):
        xb = x.astype(jnp.bfloat16)
        if flag:
            return jnp.dot(xb, w)          # line 6
        return jnp.dot(xb, w, preferred_element_type=jnp.float32)
    '''
    res = _run(src, "precision-flow")
    assert _lines(res) == [("precision-flow", 6)]


# -- schema-drift ------------------------------------------------------------

SCHEMA_CONFIG = {"schema-drift": {
    "metric_names": ["wam_tpu_good_total"],
    "row_types": ["good_row"],
}}

SCHEMA_BAD = '''\
def report(obs):
    obs.counter("wam_tpu_rogue_total", 1)      # line 2: undeclared metric
    obs.ledger({"metric": "rogue_row", "v": 1})  # line 3: undeclared row
'''

SCHEMA_GOOD = '''\
def report(obs):
    obs.counter("wam_tpu_good_total", 1)
    obs.gauge("wam_tpu_good_total", 2.0)
    obs.counter("other_prefix_total", 1)       # not a wam_tpu_ metric
    obs.ledger({"metric": "good_row", "v": 1})
'''


def test_schema_drift_bad_fixture():
    res = _run(SCHEMA_BAD, "schema-drift", config=SCHEMA_CONFIG)
    assert _lines(res) == [("schema-drift", 2), ("schema-drift", 3)]


def test_schema_drift_good_fixture():
    assert _run(SCHEMA_GOOD, "schema-drift",
                config=SCHEMA_CONFIG).findings == []


def test_schema_registry_parses_from_live_tree():
    """The declared registry (wam_tpu/obs/schema.py) AST-parses without
    importing and is non-trivially populated."""
    from wam_tpu.lint.rules.precision import _load_declared
    ctx = core.LintContext(root=REPO, config={})
    metrics, rows = _load_declared(ctx)
    assert len(metrics) >= 40 and len(rows) >= 10
    assert all(m.startswith("wam_tpu_") for m in metrics)


# -- parse errors ------------------------------------------------------------

def test_parse_error_becomes_finding(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def oops(:\n")
    files = core.load_files([str(bad)], root=str(tmp_path))
    ctx = core.LintContext(root=str(tmp_path))
    res = core.run_rules([get_rule("host-sync")()], files, ctx,
                         respect_scope=False)
    assert [f.rule for f in res.findings] == ["parse-error"]
    assert "syntax error" in res.findings[0].message


# -- pragmas -----------------------------------------------------------------

def test_pragma_same_line_suppresses():
    src = HOST_SYNC_BAD.replace(
        "a = np.asarray(x)          # line 7",
        "a = np.asarray(x)  # wamlint: disable=host-sync")
    res = _run(src, "host-sync")
    assert ("host-sync", 7) not in _lines(res)
    # a pragma covers its own line AND the line below (the "line above"
    # placement seen from line 8's side) — so .item() on 8 is covered too
    assert ("host-sync", 8) not in _lines(res)
    assert res.suppressed == 2
    assert len(res.findings) == 3


def test_pragma_line_above_suppresses():
    src = '''\
    import numpy as np

    @jit
    def traced(x):
        # wamlint: disable=host-sync
        return np.asarray(x)
    '''
    res = _run(src, "host-sync")
    assert res.findings == [] and res.suppressed == 1


def test_pragma_disable_file():
    src = "# wamlint: disable-file=host-sync\n" + HOST_SYNC_BAD
    res = _run(src, "host-sync")
    assert res.findings == [] and res.suppressed == 5


def test_pragma_only_disables_named_rule():
    src = HOST_SYNC_BAD.replace(
        "a = np.asarray(x)          # line 7",
        "a = np.asarray(x)  # wamlint: disable=retrace-risk")
    res = _run(src, "host-sync")
    assert ("host-sync", 7) in _lines(res) and res.suppressed == 0


# -- baseline ratchet --------------------------------------------------------

def test_baseline_roundtrip_and_ratchet(tmp_path):
    res = _run(HOST_SYNC_BAD, "host-sync")
    assert len(res.findings) == 5
    path = str(tmp_path / "baseline.json")
    core.write_baseline(path, res.findings)
    baseline = core.load_baseline(path)
    assert sum(baseline.values()) == 5

    # everything baselined -> nothing reported
    kept, absorbed = core.apply_baseline(res.findings, baseline)
    assert kept == [] and absorbed == 5

    # ratchet: the same key may absorb only up to its recorded count —
    # a file getting WORSE than its baseline is reported
    doubled = res.findings + res.findings
    kept, absorbed = core.apply_baseline(doubled, baseline)
    assert absorbed == 5 and len(kept) == 5

    # keys are line-number-free: shifting the finding down keeps it absorbed
    import dataclasses
    shifted = [dataclasses.replace(f, line=f.line + 100)
               for f in res.findings]
    kept, absorbed = core.apply_baseline(shifted, baseline)
    assert kept == [] and absorbed == 5


def test_checked_in_baseline_is_valid_and_empty():
    """The live tree is clean; the committed ratchet must stay empty (it
    may only ever shrink — new findings are fixed, not baselined)."""
    path = os.path.join(REPO, core.DEFAULT_BASELINE)
    with open(path) as f:
        data = json.load(f)
    assert data["version"] == 1
    assert data["findings"] == {}


# -- emitters ----------------------------------------------------------------

def _result():
    return _run(HOST_SYNC_BAD, "host-sync")


def test_text_emitter_summary():
    out = emit_text(_result())
    assert out.splitlines()[-1] == (
        "wam_tpu.lint: 1 files, 5 findings (0 pragma-suppressed, "
        "0 baselined)")
    assert "wam_tpu/fixture.py:7: [host-sync] np.asarray()" in out


def test_json_emitter_schema():
    doc = json.loads(emit_json(_result()))
    assert doc["version"] == 1
    assert doc["files"] == 1
    assert doc["suppressed"] == 0 and doc["baselined"] == 0
    assert len(doc["findings"]) == 5
    for f in doc["findings"]:
        assert set(f) == {"rule", "severity", "path", "line", "message"}
        assert f["rule"] == "host-sync" and f["severity"] == "error"
        assert f["path"] == "wam_tpu/fixture.py"


def test_sarif_emitter_schema():
    doc = json.loads(emit_sarif(_result()))
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    run = doc["runs"][0]
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == ALL_RULE_IDS
    assert len(run["results"]) == 5
    r0 = run["results"][0]
    assert r0["ruleId"] == "host-sync" and r0["level"] == "error"
    loc = r0["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uriBaseId"] == "SRCROOT"
    assert loc["region"]["startLine"] >= 1


# -- legacy shim parity ------------------------------------------------------

def _load_shim():
    p = os.path.join(REPO, "scripts", "check_host_syncs.py")
    spec = importlib.util.spec_from_file_location("check_host_syncs", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_shim_output_contract_on_fixture(tmp_path, capsys):
    bad = tmp_path / "mod.py"
    bad.write_text(HOST_SYNC_BAD)
    shim = _load_shim()
    rc = shim.main([str(bad)])
    out = capsys.readouterr().out.splitlines()
    assert rc == 1
    # legacy format: absolute paths, `path:line: message`, trailing summary
    assert out[0] == f"{bad}:7: np.asarray() in traced function"
    assert len(out) == 6
    assert out[-1] == "check_host_syncs: 1 files, 5 findings"

    good = tmp_path / "ok.py"
    good.write_text(HOST_SYNC_GOOD)
    rc = shim.main([str(good)])
    out = capsys.readouterr().out.splitlines()
    assert rc == 0
    assert out == ["check_host_syncs: 1 files, 0 findings"]


def test_shim_interleaves_syntax_errors(tmp_path, capsys):
    (tmp_path / "a_broken.py").write_text("def oops(:\n")
    (tmp_path / "b_bad.py").write_text(HOST_SYNC_BAD)
    shim = _load_shim()
    rc = shim.main([str(tmp_path)])
    out = capsys.readouterr().out.splitlines()
    assert rc == 1
    assert out[0].startswith(f"{tmp_path / 'a_broken.py'}: syntax error:")
    assert out[1].startswith(f"{tmp_path / 'b_bad.py'}:7:")
    assert out[-1] == "check_host_syncs: 2 files, 6 findings"


def test_live_tree_parity_shim_vs_rule():
    """The shim and the modern host-sync rule must agree finding-for-
    finding on the real checkout (pragma/baseline filtering excluded —
    the legacy contract predates both)."""
    legacy_lines, nfiles = compat.legacy_host_sync_lines(None)
    assert nfiles > 50  # the legacy scope really was walked

    files = core.load_files(list(LEGACY_SCOPE), root=REPO)
    ctx = core.LintContext(root=REPO)
    res = core.run_rules([get_rule("host-sync")()], files, ctx,
                         respect_scope=True, apply_pragmas=False)
    modern = [f"{f.abspath}:{f.line}: {f.message}" for f in res.findings
              if f.rule == "host-sync"]
    assert sorted(modern) == sorted(legacy_lines)


# -- knob audit --------------------------------------------------------------

def test_knob_scan_finds_direct_and_const_reads(tmp_path):
    pkg = tmp_path / "wam_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text(textwrap.dedent('''\
        import os
        KEY_ENV = "WAM_TPU_FIXTURE_KEY"
        a = os.getenv("WAM_TPU_FIXTURE_DIRECT")
        b = os.environ.get(KEY_ENV)
        c = os.environ["WAM_TPU_FIXTURE_SUB"]
    '''))
    reads = knobs.scan_knob_reads(str(tmp_path))
    assert set(reads) == {"WAM_TPU_FIXTURE_DIRECT", "WAM_TPU_FIXTURE_KEY",
                          "WAM_TPU_FIXTURE_SUB"}
    assert reads["WAM_TPU_FIXTURE_KEY"] == ["wam_tpu/m.py:4"]


def test_knob_audit_clean_on_live_tree():
    problems, report = knobs.audit(REPO, write_docs=False)
    assert problems == []
    assert len(report) >= 10  # the knob surface really was scanned
    for knob in knobs.scan_knob_reads(REPO):
        assert knob in knobs.KNOB_DOCS, knob


def test_knob_table_write_roundtrip(tmp_path):
    (tmp_path / "README.md").write_text(
        f"# x\n\n{knobs.BEGIN_MARK}\nstale\n{knobs.END_MARK}\n\ntail\n")
    pkg = tmp_path / "wam_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        'import os\nv = os.getenv("WAM_TPU_AOT_CACHE")\n')
    table = knobs.render_table(knobs.scan_knob_reads(str(tmp_path)))
    assert knobs.write_table(str(tmp_path), table)
    assert knobs.current_table(str(tmp_path)) == table
    assert "WAM_TPU_AOT_CACHE" in table
    assert knobs.KNOB_DOCS["WAM_TPU_AOT_CACHE"] in table


# -- CLI ---------------------------------------------------------------------

def test_cli_all_clean_on_live_tree(capsys):
    """THE gate: every rule over its own scope, current checkout, zero
    non-baselined findings."""
    rc = lint_main(["--all"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 findings" in out.splitlines()[-1]


def test_cli_explicit_path_json(tmp_path, capsys):
    bad = tmp_path / "wam_tpu_fixture.py"
    bad.write_text(RETRACE_BAD)
    rc = lint_main([str(bad), "--rules", "retrace-risk", "--format", "json",
                    "--no-baseline"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["line"] for f in doc["findings"]] == [6, 10, 13]


def test_cli_baseline_write_then_absorb(tmp_path, capsys):
    bad = tmp_path / "wam_tpu_fixture.py"
    bad.write_text(RETRACE_BAD)
    base = str(tmp_path / "baseline.json")
    rc = lint_main([str(bad), "--rules", "retrace-risk",
                    "--write-baseline", "--baseline", base])
    capsys.readouterr()
    assert rc == 0
    rc = lint_main([str(bad), "--rules", "retrace-risk",
                    "--baseline", base])
    out = capsys.readouterr().out
    assert rc == 0
    assert "3 baselined" in out.splitlines()[-1]


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ALL_RULE_IDS:
        assert rid in out


def test_cli_unknown_rule_errors():
    with pytest.raises(KeyError):
        lint_main(["--rules", "nonesuch"])
