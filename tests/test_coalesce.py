"""Round-13 admission layer: the coalescing window, QoS lanes, the
content-addressed result cache, per-bucket backpressure estimates, the
occupancy histogram, and class-dimensioned SLOs.

Timing-sensitive window tests use WIDE margins (a 300 ms window asserted
against a <100 ms fast path) so they stay deterministic on loaded CI
hosts; everything queue-shaped goes through the gated-entry handshake
idiom from tests/test_serve.py instead of sleeps."""

import threading
import time

import numpy as np
import pytest

from wam_tpu.serve import (
    AttributionServer,
    DeadlineExceededError,
    QueueFullError,
    ResultCache,
    ServeMetrics,
    result_cache_key,
)


class _RecordingEntry:
    """Instant entry that records each dispatched batch's labels."""

    def __init__(self):
        self.batches = []

    def __call__(self, xs, ys):
        self.batches.append(None if ys is None else [int(y) for y in ys])
        return np.asarray(xs) * 2.0


class _GateEntry:
    """Parks the worker inside the dispatch until released (the
    deterministic queue-buildup handshake)."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.batches = []

    def __call__(self, xs, ys):
        self.batches.append(None if ys is None else [int(y) for y in ys])
        self.entered.set()
        assert self.release.wait(timeout=10), "test gate never released"
        return np.asarray(xs) * 2.0


def _x(fill=0.0, n=4):
    return np.full((n,), fill, np.float32)


# -- coalescing window --------------------------------------------------------


def test_full_batch_releases_before_window():
    """A full bucket dispatches immediately — the window is a cap on
    waiting for fill, never a tax on already-full batches."""
    entry = _RecordingEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=2, coalesce_ms=5000.0, warmup=False)
    try:
        t0 = time.perf_counter()
        a = server.submit(_x(1.0), 0)
        b = server.submit(_x(2.0), 1)
        a.result(timeout=10), b.result(timeout=10)
        assert time.perf_counter() - t0 < 2.0  # nowhere near the 5 s window
        assert entry.batches == [[0, 1]]  # one coalesced dispatch
    finally:
        server.close()


def test_partial_batch_held_for_the_window():
    entry = _RecordingEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=8, coalesce_ms=300.0, warmup=False)
    try:
        t0 = time.perf_counter()
        fut = server.submit(_x(), 0)
        fut.result(timeout=10)
        elapsed = time.perf_counter() - t0
        assert elapsed >= 0.25  # held ~the window before dispatching alone
    finally:
        server.close()
    # control: coalesce_ms=0 is the historical immediate-ish path
    entry0 = _RecordingEntry()
    server0 = AttributionServer(
        entry0, [(4,)], max_batch=8, coalesce_ms=0.0, max_wait_ms=0.0,
        warmup=False)
    try:
        t0 = time.perf_counter()
        server0.submit(_x(), 0).result(timeout=10)
        assert time.perf_counter() - t0 < 0.25
    finally:
        server0.close()


def test_deadline_pressure_releases_window_early():
    """A tight queued deadline collapses the window: the dispatch goes as
    soon as waiting longer would risk the deadline, not at window expiry."""
    entry = _RecordingEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=8, coalesce_ms=10_000.0, warmup=False)
    try:
        t0 = time.perf_counter()
        fut = server.submit(_x(), 0, deadline_ms=200.0)
        np.testing.assert_array_equal(fut.result(timeout=10), _x() * 2.0)
        assert time.perf_counter() - t0 < 5.0  # far inside the 10 s window
        assert entry.batches  # actually dispatched, not expired
    finally:
        server.close()


def test_deadline_expiring_inside_window_fails_before_dispatch():
    """Satellite: a request whose deadline lapses while the window holds
    it fails with DeadlineExceededError at pop time — it never burns a
    batch slot and the worker never dispatches for it."""
    entry = _RecordingEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=8, coalesce_ms=10_000.0, warmup=False)
    try:
        fut = server.submit(_x(), 0, deadline_ms=0.001)  # lapses instantly
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=10)
        assert entry.batches == []  # no dispatch happened for the expiry
        assert server.metrics.expired == 1
        assert server.metrics.completed == 0
    finally:
        server.close()


# -- QoS lanes ----------------------------------------------------------------


def test_interactive_lane_drains_first_batch_backfills():
    entry = _GateEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=4, max_wait_ms=0.0, warmup=False)
    try:
        first = server.submit(_x(), 9, qos="batch")
        assert entry.entered.wait(timeout=10)  # worker parked in dispatch
        lag = server.submit(_x(), 1, qos="batch")
        pri = server.submit(_x(), 2, qos="interactive")
        assert server.qos_depths() == {"interactive": 1, "batch": 1}
        entry.release.set()
        for f in (first, lag, pri):
            f.result(timeout=10)
        # second dispatch: the younger interactive row leads, batch
        # backfills (trailing rows are replicate-batch padding)
        assert entry.batches[0][0] == 9
        assert entry.batches[1][:2] == [2, 1]
    finally:
        entry.release.set()
        server.close()


def test_submit_rejects_unknown_qos_class():
    server = AttributionServer(
        _RecordingEntry(), [(4,)], max_batch=2, warmup=False)
    try:
        with pytest.raises(ValueError, match="qos"):
            server.submit(_x(), 0, qos="bulk")
    finally:
        server.close()


def test_retry_after_reflects_target_bucket_not_fleet_sum():
    """Satellite: QueueFullError.retry_after_s is the REJECTED bucket's
    projected drain, not the sum over every bucket — a rejection against
    a nearly-empty bucket must not quote the busy bucket's backlog."""
    entry = _GateEntry()
    server = AttributionServer(
        entry, [(4,), (8,)], max_batch=1, max_wait_ms=0.0, queue_depth=4,
        warmup=False)
    try:
        server.submit(_x(), 0)  # bucket (4,): worker parks here
        assert entry.entered.wait(timeout=10)
        for _ in range(3):
            server.submit(_x(), 0)  # bucket (4,) backlog
        server.submit(_x(n=8), 0)  # bucket (8,): depth limit reached
        with pytest.raises(QueueFullError) as ei:
            server.submit(_x(n=8), 0)
        # (8,)-drain: 1 queued batch at the 50 ms EMA seed. The all-bucket
        # sum (>= 4 batches + in-flight) would quote >= 4x that.
        assert 0.0 < ei.value.retry_after_s <= 0.12
    finally:
        entry.release.set()
        server.close()


# -- result cache: unit level -------------------------------------------------


def test_result_cache_lru_respects_byte_budget():
    cache = ResultCache(max_bytes=3 * 400, cache_id="unit")
    rows = {f"k{i}": np.full((100,), float(i), np.float32) for i in range(5)}
    for k, v in rows.items():
        assert cache.put(k, v)
    assert len(cache) == 3 and cache.total_bytes <= 3 * 400
    assert cache.stats()["evictions"] == 2
    assert cache.get("k0") is None and cache.get("k1") is None  # LRU'd out
    np.testing.assert_array_equal(cache.get("k4"), rows["k4"])
    # a get refreshes recency: k2 survives the next insert, k3 does not
    cache.get("k2")
    cache.put("k5", np.zeros((100,), np.float32))
    assert cache.get("k3") is None
    assert cache.get("k2") is not None


def test_result_cache_refuses_oversized_value():
    cache = ResultCache(max_bytes=100, cache_id="unit")
    assert not cache.put("big", np.zeros((1000,), np.float32))
    assert len(cache) == 0 and cache.total_bytes == 0


def test_result_cache_key_separates_shape_dtype_label_and_id():
    x = np.arange(4, dtype=np.float32)
    base = result_cache_key(x, 0, "m1")
    assert result_cache_key(x.copy(), 0, "m1") == base  # content-addressed
    assert result_cache_key(x.reshape(2, 2), 0, "m1") != base
    assert result_cache_key(x.astype(np.float64), 0, "m1") != base
    assert result_cache_key(x, 1, "m1") != base
    assert result_cache_key(x, 0, "m2") != base


def test_result_cache_key_tracks_schedule_fingerprint(tmp_path, monkeypatch):
    """A tuned schedule landing (or the schedule kill switch flipping)
    changes every key — stale-schedule hits are structurally impossible."""
    from wam_tpu.tune import invalidate_process_cache, record_schedule

    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE", str(tmp_path / "sched.json"))
    monkeypatch.delenv("WAM_TPU_NO_SCHEDULE_CACHE", raising=False)
    invalidate_process_cache()
    try:
        x = np.arange(4, dtype=np.float32)
        before = result_cache_key(x, 0, "m")
        record_schedule("wam2d", (1, 4, 4), 8, {"sample_batch_size": 4})
        after = result_cache_key(x, 0, "m")
        assert after != before
        monkeypatch.setenv("WAM_TPU_NO_SCHEDULE_CACHE", "1")
        assert result_cache_key(x, 0, "m") not in (before, after)
    finally:
        invalidate_process_cache()


def test_result_cache_kill_switch(monkeypatch):
    cache = ResultCache(max_bytes=1 << 20, cache_id="unit")
    monkeypatch.setenv("WAM_TPU_NO_RESULT_CACHE", "1")
    assert not cache.put("k", np.zeros((4,), np.float32))
    assert cache.get("k") is None
    assert cache.stats()["disabled"]
    monkeypatch.setenv("WAM_TPU_NO_RESULT_CACHE", "0")  # read per call
    assert cache.put("k", np.zeros((4,), np.float32))
    assert cache.get("k") is not None


# -- result cache: through the server -----------------------------------------


def test_repeat_submit_hits_cache_bit_identically():
    entry = _RecordingEntry()
    metrics = ServeMetrics()
    server = AttributionServer(
        entry, [(4,)], max_batch=2, warmup=False, metrics=metrics,
        result_cache=1 << 20, cache_id="toy")
    try:
        x = _x(3.0)
        r1 = server.submit(x, 1).result(timeout=10)
        r2 = server.submit(x, 1).result(timeout=10)
        np.testing.assert_array_equal(r1, r2)  # bit-identical replay
        assert len(entry.batches) == 1  # second submit never dispatched
        assert metrics.cache_hits == 1
        assert server.describe()["result_cache"]["hits"] == 1
        # different label: a real miss, not a collision
        server.submit(x, 2).result(timeout=10)
        assert len(entry.batches) == 2
    finally:
        server.close()
    snap = metrics.snapshot()
    assert snap["cache_hits"] == 1
    assert snap["completed"] == 2  # hits resolve outside the dispatch path


def test_server_cache_kill_switch_forces_recompute(monkeypatch):
    monkeypatch.setenv("WAM_TPU_NO_RESULT_CACHE", "1")
    entry = _RecordingEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=2, warmup=False,
        result_cache=1 << 20, cache_id="toy")
    try:
        x = _x(3.0)
        server.submit(x, 1).result(timeout=10)
        server.submit(x, 1).result(timeout=10)
        assert len(entry.batches) == 2  # both computed
        assert server.metrics.cache_hits == 0
    finally:
        server.close()


# -- occupancy metric ---------------------------------------------------------


def test_batch_rows_carry_occupancy_and_histogram(tmp_path):
    from wam_tpu import obs

    obs.reset()
    path = tmp_path / "serve.jsonl"
    metrics = ServeMetrics()
    server = AttributionServer(
        _RecordingEntry(), [(4,)], max_batch=4, max_wait_ms=0.0,
        warmup=False, metrics=metrics, metrics_path=str(path))
    try:
        server.submit(_x(), 0).result(timeout=10)
    finally:
        server.close()
    import json

    rows = [json.loads(line) for line in path.read_text().splitlines()]
    batch = next(r for r in rows if r["metric"] == "serve_batch")
    assert batch["occupancy"] == pytest.approx(0.25)  # 1 real row of 4
    assert batch["fill_ratio"] == batch["occupancy"]
    summary = next(r for r in rows if r["metric"] == "serve_summary")
    assert summary["occupancy_mean"] == pytest.approx(0.25)
    assert "wam_tpu_serve_batch_occupancy" in obs.render_prom()


# -- class-dimensioned SLOs ---------------------------------------------------


def test_parse_slo_accepts_class_keys_and_rejects_empty_class():
    from wam_tpu.obs.slo import parse_slo

    policy = parse_slo("4@interactive: p99_ms=10; *@batch: p99_ms=100; "
                       "*: p99_ms=50")
    assert policy["4@interactive"].p99_ms == 10.0
    assert policy["*@batch"].p99_ms == 100.0
    with pytest.raises(ValueError, match="QoS class"):
        parse_slo("4@: p99_ms=5")


def test_slo_objective_ladder_and_class_penalty():
    from wam_tpu.obs.slo import SLOTracker

    t = SLOTracker("4@interactive: p99_ms=10, window_s=60; "
                   "*@batch: p99_ms=500; *: p99_ms=100")
    # ladder: exact -> *@class -> bare bucket -> *
    assert t.objectives_for("4@interactive").p99_ms == 10.0
    assert t.objectives_for("8@batch").p99_ms == 500.0
    assert t.objectives_for("8@interactive").p99_ms == 100.0
    assert t.objectives_for("8").p99_ms == 100.0
    # every interactive sample blows its 10 ms target; the batch class is
    # comfortably inside 500 ms — the per-class window must still penalize
    # the bucket (max over class windows, not the diluted aggregate)
    now = 1000.0
    for i in range(20):
        t.note("4", latency_s=0.05, qos="interactive", now=now + i * 0.01)
        t.note("4", latency_s=0.05, qos="batch", now=now + i * 0.01)
    assert t.burn_rate("4@interactive", now=now + 1) > 1.0
    assert t.burn_rate("4@batch", now=now + 1) <= 1.0
    assert t.penalty_s("4", now=now + 1) > 0.0
