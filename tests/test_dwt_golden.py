"""Independent DWT golden values and algebraic invariants (VERDICT.md #4).

Round 1's pywt-parity tests compared against `tests/reference_dwt.py`,
written by the same author from the same understanding — a shared
convention misconception would pass everything. This file pins the
transform from OUTSIDE that shared code path, with no import of
`tests/reference_dwt.py`:

1. literal closed-form Daubechies filter values (db2 exact radicals, db4's
   published D8 decimals — standard tables, e.g. Daubechies 1992, Table 6.1);
2. the worked examples printed in pywt's own documentation
   (`pywt.dwt([1,2,3,4],'haar')`, `pywt.wavedec([1..8],'db1',level=2)`);
3. a definitional oracle: pywt's dwt is the FULL convolution with the
   decomposition filter downsampled at odd indices — reproduced here with
   nothing but `np.convolve` and the closed-form filters, and compared to
   our zero-padding mode over the whole output (zero padding == plain full
   convolution);
4. algebraic invariants no padding convention can fake: double-shift
   orthonormality, QMF relation, vanishing moments, periodized perfect
   reconstruction and Parseval energy at odd lengths;
5. cross-mode interior agreement: away from the boundary all padding modes
   must agree exactly (boundary handling only touches the edges).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from wam_tpu.wavelets.filters import build_wavelet
from wam_tpu.wavelets.transform import dwt, wavedec, waverec

SQRT2 = np.sqrt(2.0)
SQRT3 = np.sqrt(3.0)

# Closed-form db2 decomposition low-pass in pywt's ascending-index order
# (Daubechies D4: h = [(1±√3), (3±√3)]/(4√2)).
DB2_DEC_LO = np.array(
    [(1 - SQRT3), (3 - SQRT3), (3 + SQRT3), (1 + SQRT3)]
) / (4 * SQRT2)

# Published Daubechies D8 (pywt 'db4') scaling coefficients h0..h7
# (Daubechies 1992, Table 6.1; identical digits in the pywt wavelet browser),
# listed here in pywt dec_lo order (reversed h).
DB4_H = np.array(
    [
        0.2303778133088964,
        0.7148465705529154,
        0.6308807679298587,
        -0.0279837694168599,
        -0.1870348117190931,
        0.0308413818355607,
        0.0328830116668852,
        -0.0105974017850690,
    ]
)
DB4_DEC_LO = DB4_H[::-1]


def test_db2_filters_match_closed_form():
    wav = build_wavelet("db2")
    np.testing.assert_allclose(np.asarray(wav.dec_lo), DB2_DEC_LO, atol=1e-12)


def test_db4_filters_match_published_table():
    wav = build_wavelet("db4")
    np.testing.assert_allclose(np.asarray(wav.dec_lo), DB4_DEC_LO, atol=1e-10)


@pytest.mark.parametrize("name,N", [("db2", 2), ("db4", 4), ("sym4", 4), ("haar", 1)])
def test_orthonormality_qmf_and_vanishing_moments(name, N):
    """Double-shift orthonormality, Σlo=√2, QMF high-pass, and N vanishing
    moments — properties of the true Daubechies/Symlet filters that any
    transcription error would break."""
    wav = build_wavelet(name)
    lo = np.asarray(wav.dec_lo, dtype=np.float64)
    hi = np.asarray(wav.dec_hi, dtype=np.float64)
    L = len(lo)
    np.testing.assert_allclose(lo.sum(), SQRT2, atol=1e-10)
    np.testing.assert_allclose(hi.sum(), 0.0, atol=1e-10)
    for m in range(1, L // 2):
        np.testing.assert_allclose(np.dot(lo[2 * m :], lo[: L - 2 * m]), 0.0, atol=1e-10)
        np.testing.assert_allclose(np.dot(hi[2 * m :], hi[: L - 2 * m]), 0.0, atol=1e-10)
    np.testing.assert_allclose(np.dot(lo, lo), 1.0, atol=1e-10)
    np.testing.assert_allclose(np.dot(hi, hi), 1.0, atol=1e-10)
    # QMF, pywt sign convention: hi[k] = (-1)^(k+1) lo[L-1-k]
    # (e.g. haar dec_hi = [-1/√2, +1/√2], db2 dec_hi starts at -0.4830)
    np.testing.assert_allclose(
        hi, np.array([(-1) ** (k + 1) * lo[L - 1 - k] for k in range(L)]), atol=1e-10
    )
    # vanishing moments: Σ k^p hi[k] = 0 for p < N
    for p in range(N):
        np.testing.assert_allclose(
            np.dot(np.arange(L, dtype=np.float64) ** p, hi), 0.0, atol=1e-7
        )


def test_pywt_doc_example_haar_dwt():
    """pywt documentation worked example: dwt([1,2,3,4], 'haar') →
    cA=[2.12132034, 4.94974747], cD=[-0.70710678, -0.70710678]."""
    cA, cD = dwt(jnp.asarray([[1.0, 2.0, 3.0, 4.0]]), "haar", mode="symmetric")
    np.testing.assert_allclose(
        np.asarray(cA)[0], [2.12132034, 4.94974747], atol=1e-7
    )
    np.testing.assert_allclose(
        np.asarray(cD)[0], [-0.70710678, -0.70710678], atol=1e-7
    )


def test_pywt_doc_example_db1_wavedec_level2():
    """pywt documentation worked example: wavedec([1..8], 'db1', level=2) →
    cA2=[5., 13.], cD2=[-2., -2.], cD1=[-0.707..x4]."""
    x = jnp.asarray(np.arange(1.0, 9.0))[None]
    cA2, cD2, cD1 = wavedec(x, "db1", level=2, mode="symmetric")
    np.testing.assert_allclose(np.asarray(cA2)[0], [5.0, 13.0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(cD2)[0], [-2.0, -2.0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(cD1)[0], [-0.70710678] * 4, atol=1e-6)


@pytest.mark.parametrize("name,filt", [("db2", DB2_DEC_LO), ("db4", DB4_DEC_LO)])
@pytest.mark.parametrize("n", [16, 37, 63])
def test_zero_mode_equals_definitional_full_convolution(name, filt, n):
    """pywt's dwt in 'zero' mode IS the full convolution of the signal with
    the decomposition filter, downsampled at odd indices, trimmed to
    floor((n+L-1)/2) — reproduced with np.convolve and the closed-form
    filters only (no shared helper code)."""
    rng = np.random.default_rng(n)
    x = rng.standard_normal(n)
    L = len(filt)
    out_len = (n + L - 1) // 2

    lo_full = np.convolve(x, filt)[1::2][:out_len]
    wav = build_wavelet(name)
    hi_filt = np.asarray(wav.dec_hi, dtype=np.float64)
    # independent QMF construction of the high-pass from the closed form
    # (pywt sign convention: leading coefficient negative)
    hi_closed = np.array([(-1) ** (k + 1) * filt[L - 1 - k] for k in range(L)])
    np.testing.assert_allclose(hi_filt, hi_closed, atol=1e-10)
    hi_full = np.convolve(x, hi_closed)[1::2][:out_len]

    cA, cD = dwt(jnp.asarray(x, dtype=jnp.float32)[None], name, mode="zero")
    np.testing.assert_allclose(np.asarray(cA)[0], lo_full, atol=2e-5)
    np.testing.assert_allclose(np.asarray(cD)[0], hi_full, atol=2e-5)


@pytest.mark.parametrize("name", ["db2", "db4", "sym4"])
@pytest.mark.parametrize("n", [37, 61])
def test_interior_agrees_across_all_modes(name, n):
    """Padding only affects the edges: coefficients more than one filter
    length from either end must be bitwise-equal across zero / symmetric /
    reflect / periodic — a shared boundary-convention misconception cannot
    fake this, and the interior itself is pinned by the zero-mode
    definitional test above."""
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)[None]
    wav = build_wavelet(name)
    L = wav.filt_len
    outs = {m: dwt(x, name, mode=m) for m in ("zero", "symmetric", "reflect", "periodic")}
    sl = slice(L, -L)
    base_cA = np.asarray(outs["zero"][0])[0][sl]
    base_cD = np.asarray(outs["zero"][1])[0][sl]
    assert base_cA.size > 4  # the interior must be non-trivial
    for m, (cA, cD) in outs.items():
        np.testing.assert_allclose(np.asarray(cA)[0][sl], base_cA, atol=1e-6, err_msg=m)
        np.testing.assert_allclose(np.asarray(cD)[0][sl], base_cD, atol=1e-6, err_msg=m)


@pytest.mark.parametrize("name", ["haar", "db2", "db4", "sym4"])
@pytest.mark.parametrize("n", [32, 100])
def test_periodized_perfect_reconstruction_and_parseval(name, n):
    """For the periodized orthonormal transform: synthesis∘analysis is the
    identity and total energy is conserved (Parseval) — including a length
    (100) whose level-2 coefficient count is odd."""
    from wam_tpu.wavelets.periodized import wavedec_per, waverec_per

    rng = np.random.default_rng(n)
    x = jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)[None]
    coeffs = wavedec_per(x, name, 2)
    rec = waverec_per(coeffs, name)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=2e-6)
    ex = float((np.asarray(x) ** 2).sum())
    ec = sum(float((np.asarray(c) ** 2).sum()) for c in coeffs)
    np.testing.assert_allclose(ec, ex, rtol=1e-5)
