"""Shared WAM workload for the 2-process multihost parity test.

Everything here is deterministic given the fixed seeds (Flax init, input
draw, SmoothGrad noise via threefry), so two cluster processes and the
single-process golden build IDENTICAL computations over the same global
(4 data × 2 sample) mesh — making exact-equality assertions meaningful.
Used by tests/test_multihost.py (VERDICT.md round-2 next #4).
"""

import numpy as np

import jax
import jax.numpy as jnp


def build_case():
    from wam_tpu.core.engine import WamEngine
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.models import bind_inference, resnet18
    from wam_tpu.ops.packing2d import mosaic2d
    from wam_tpu.parallel import sharded_smoothgrad

    model = resnet18(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))
    model_fn = bind_inference(model, variables, nchw=True)
    engine = WamEngine(model_fn, ndim=2, wavelet="haar", level=2, mode="reflect")

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((4, 3, 16, 16)), dtype=jnp.float32)
    y = jnp.arange(4) % 5

    def step(noisy):
        _, grads = engine.attribute(noisy, y)
        return mosaic2d(grads, True)

    def smoothgrad_runner(mesh):
        runner = sharded_smoothgrad(step, mesh, n_samples=4, stdev_spread=0.25)
        return runner(x, jax.random.PRNGKey(7))

    fixed_maps = jnp.asarray(rng.standard_normal((2, 16, 16)), dtype=jnp.float32)
    x_eval = x[:2]
    y_eval = [1, 3]

    def insertion_runner(mesh):
        ev = Eval2DWAM(
            model_fn,
            explainer=lambda xx, yy: fixed_maps,
            wavelet="haar",
            J=2,
            batch_size=8,
            mesh=mesh,
        )
        return ev.insertion(x_eval, y_eval, n_iter=4)

    return {
        "smoothgrad_runner": smoothgrad_runner,
        "insertion_runner": insertion_runner,
    }


def build_halo_case():
    """Sequence-sharded long-context machinery for the 2-process test: the
    analysis ring ppermute, the reversed synthesis ring, and the replicated
    tails all CROSS the DCN process boundary on a {"data": 8} hybrid mesh.
    Deterministic seeds make single-process golden vs cluster comparisons
    meaningful (same convention as build_case)."""
    from wam_tpu.models.audio import toy_wave_model

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((2, 2048)), dtype=jnp.float32)
    model = toy_wave_model(jax.random.PRNGKey(0))
    y = jnp.array([1, 3])

    def dec_runner(mesh):
        from wam_tpu.parallel import sharded_wavedec_per

        return sharded_wavedec_per(mesh, "db3", 3, seq_axis="data")(x)

    def mode_grads_runner(mesh):
        from wam_tpu.parallel import sharded_coeff_grads_mode

        step = sharded_coeff_grads_mode(mesh, "db3", 3, model, "symmetric")
        return step(x, y)

    return {"dec_runner": dec_runner, "mode_grads_runner": mode_grads_runner}
