"""Independent torch ResNet-18 (torchvision-compatible naming) used ONLY as a
cross-implementation oracle for checkpoint-ingestion and architecture-parity
tests. Written from the standard ResNet recipe (He et al. 2016)."""

import torch
import torch.nn as nn


class TorchBasicBlock(nn.Module):
    def __init__(self, in_ch, out_ch, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(in_ch, out_ch, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(out_ch)
        self.conv2 = nn.Conv2d(out_ch, out_ch, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(out_ch)
        self.relu = nn.ReLU(inplace=True)
        if stride != 1 or in_ch != out_ch:
            self.downsample = nn.Sequential(
                nn.Conv2d(in_ch, out_ch, 1, stride, bias=False), nn.BatchNorm2d(out_ch)
            )
        else:
            self.downsample = None

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return self.relu(y + idn)


class TorchResNet18(nn.Module):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        chans = [64, 128, 256, 512]
        layers = []
        in_ch = 64
        for stage, ch in enumerate(chans):
            blocks = []
            for i in range(2):
                stride = 2 if stage > 0 and i == 0 else 1
                blocks.append(TorchBasicBlock(in_ch, ch, stride))
                in_ch = ch
            layers.append(nn.Sequential(*blocks))
        self.layer1, self.layer2, self.layer3, self.layer4 = layers
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = self.avgpool(x).flatten(1)
        return self.fc(x)


class TorchTinyViT(nn.Module):
    """timm-style naming (cls_token, pos_embed, patch_embed.proj, blocks.{i},
    norm, head) — oracle for torch_vit_to_flax."""

    def __init__(self, num_classes=10, img=32, patch=8, dim=64, depth=2, heads=4, mlp=128):
        super().__init__()
        self.heads = heads
        n_patches = (img // patch) ** 2
        self.cls_token = nn.Parameter(torch.zeros(1, 1, dim))
        self.pos_embed = nn.Parameter(torch.randn(1, n_patches + 1, dim) * 0.02)
        self.patch_embed = nn.Module()
        self.patch_embed.proj = nn.Conv2d(3, dim, patch, patch)
        self.blocks = nn.ModuleList()
        for _ in range(depth):
            blk = nn.Module()
            blk.norm1 = nn.LayerNorm(dim, eps=1e-6)
            blk.attn = nn.Module()
            blk.attn.qkv = nn.Linear(dim, 3 * dim)
            blk.attn.proj = nn.Linear(dim, dim)
            blk.norm2 = nn.LayerNorm(dim, eps=1e-6)
            blk.mlp = nn.Module()
            blk.mlp.fc1 = nn.Linear(dim, mlp)
            blk.mlp.fc2 = nn.Linear(mlp, dim)
            self.blocks.append(blk)
        self.norm = nn.LayerNorm(dim, eps=1e-6)
        self.head = nn.Linear(dim, num_classes)

    def forward(self, x):
        B = x.shape[0]
        x = self.patch_embed.proj(x).flatten(2).transpose(1, 2)  # (B, N, D)
        x = torch.cat([self.cls_token.expand(B, -1, -1), x], dim=1) + self.pos_embed
        for blk in self.blocks:
            y = blk.norm1(x)
            B_, N, D = y.shape
            qkv = blk.attn.qkv(y).reshape(B_, N, 3, self.heads, D // self.heads)
            q, k, v = qkv.permute(2, 0, 3, 1, 4)
            att = (q @ k.transpose(-2, -1)) / (D // self.heads) ** 0.5
            att = att.softmax(dim=-1)
            y = (att @ v).transpose(1, 2).reshape(B_, N, D)
            x = x + blk.attn.proj(y)
            y = blk.norm2(x)
            x = x + blk.mlp.fc2(torch.nn.functional.gelu(blk.mlp.fc1(y)))
        x = self.norm(x)
        return self.head(x[:, 0])


class _TorchLayerNorm2d(nn.LayerNorm):
    def forward(self, x):  # (B, C, H, W): normalize over C
        x = x.permute(0, 2, 3, 1)
        x = super().forward(x)
        return x.permute(0, 3, 1, 2)


class TorchTinyConvNeXt(nn.Module):
    """torchvision-style naming (features.0 stem, features.{2s} downsample,
    features.{2s+1}.{i}.block.{0,2,3,5} + layer_scale, classifier.{0,2}) —
    oracle for torch_convnext_to_flax."""

    def __init__(self, num_classes=10, depths=(1, 1), dims=(16, 32)):
        super().__init__()
        feats = []
        feats.append(nn.Sequential(nn.Conv2d(3, dims[0], 4, 4), _TorchLayerNorm2d(dims[0], eps=1e-6)))
        for s, (depth, dim) in enumerate(zip(depths, dims)):
            if s > 0:
                feats.append(nn.Sequential(
                    _TorchLayerNorm2d(dims[s - 1], eps=1e-6), nn.Conv2d(dims[s - 1], dim, 2, 2)))
            blocks = []
            for _ in range(depth):
                blocks.append(_TorchCNBlock(dim))
            feats.append(nn.Sequential(*blocks))
        self.features = nn.Sequential(*feats)
        self.classifier = nn.Sequential(
            nn.LayerNorm(dims[-1], eps=1e-6), nn.Flatten(1), nn.Linear(dims[-1], num_classes))

    def forward(self, x):
        x = self.features(x)
        x = x.mean(dim=(2, 3))
        return self.classifier(x)


class _TorchCNBlock(nn.Module):
    def __init__(self, dim):
        super().__init__()
        self.block = nn.Sequential(
            nn.Conv2d(dim, dim, 7, padding=3, groups=dim),
            nn.Identity(),  # index placeholder (torchvision uses Permute here)
            nn.LayerNorm(dim, eps=1e-6),
            nn.Linear(dim, 4 * dim),
            nn.GELU(),
            nn.Linear(4 * dim, dim),
        )
        self.layer_scale = nn.Parameter(torch.full((dim, 1, 1), 1e-6))

    def forward(self, x):
        y = self.block[0](x).permute(0, 2, 3, 1)
        y = self.block[5](self.block[4](self.block[3](self.block[2](y))))
        y = y.permute(0, 3, 1, 2)
        return x + self.layer_scale * y
