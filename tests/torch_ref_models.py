"""Independent torch ResNet-18 (torchvision-compatible naming) used ONLY as a
cross-implementation oracle for checkpoint-ingestion and architecture-parity
tests. Written from the standard ResNet recipe (He et al. 2016)."""

import torch
import torch.nn as nn


class TorchBasicBlock(nn.Module):
    def __init__(self, in_ch, out_ch, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(in_ch, out_ch, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(out_ch)
        self.conv2 = nn.Conv2d(out_ch, out_ch, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(out_ch)
        self.relu = nn.ReLU(inplace=True)
        if stride != 1 or in_ch != out_ch:
            self.downsample = nn.Sequential(
                nn.Conv2d(in_ch, out_ch, 1, stride, bias=False), nn.BatchNorm2d(out_ch)
            )
        else:
            self.downsample = None

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return self.relu(y + idn)


class TorchResNet18(nn.Module):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        chans = [64, 128, 256, 512]
        layers = []
        in_ch = 64
        for stage, ch in enumerate(chans):
            blocks = []
            for i in range(2):
                stride = 2 if stage > 0 and i == 0 else 1
                blocks.append(TorchBasicBlock(in_ch, ch, stride))
                in_ch = ch
            layers.append(nn.Sequential(*blocks))
        self.layer1, self.layer2, self.layer3, self.layer4 = layers
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = self.avgpool(x).flatten(1)
        return self.fc(x)
