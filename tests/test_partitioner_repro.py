"""Standalone repro of the XLA SPMD zero-size-tail partitioner failure.

`halo_modes` omits statically-empty tails from the coefficient pytree
(``TailedLeaf.tail is None``) instead of carrying ``(B, 0)`` arrays,
because on some XLA versions a zero-size operand feeding a concat/reshape
chain inside a sharded one-jit graph trips the partitioner's reshape
verifier ("reshape element count mismatch, failed after
spmd-partitioning") — the bug that historically forced the expansive-mode
decompose → grads split. This file pins the raw trigger patterns with NO
wam_tpu machinery: each test builds the minimal sharded graph, runs it,
and

- PASSES where the toolchain partitions it cleanly (this repo's jax/XLA
  does — which is why `sharded_coeff_grads_mode(fused=True)` and the
  `SeqShardedWam` fused loops are safe to default on), and
- XFAILS (not hard-fails) where the historical bug still fires, so a
  toolchain bump that regresses shows up as a loud xfail with the
  verifier message attached rather than an unrelated-looking red in the
  estimator suite.

Any OTHER exception still fails the test — the gate is specific to the
known failure, not a blanket excuse.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from conftest import need_devices

def _run_gated(fn, *args):
    """Run a jitted grad graph; xfail ONLY on the known partitioner bug
    (the compile-time verifier message names spmd-partitioning, or the
    reshape element-count mismatch it reports)."""
    try:
        out = fn(*args)
        jax.block_until_ready(out)
        return out
    except Exception as e:  # noqa: BLE001 - re-raised unless it's the bug
        msg = str(e).lower()
        if "spmd-partitioning" in msg or (
            "reshape" in msg and "element count" in msg
        ):
            pytest.xfail(
                f"historical XLA SPMD zero-size-tail partitioner bug fired: "
                f"{type(e).__name__}: {str(e)[:200]}"
            )
        raise


def _mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))


def test_zero_size_tail_concat_reshape_grad():
    """The core trigger: a sharded (B, core) buffer concatenated with a
    zero-size (B, 0) tail along the SHARDED axis, reshaped so the sharded
    axis merges, differentiated — the exact shape of the fused
    dec→rec→model→VJP graph when empty tails are carried as arrays."""
    need_devices(8)
    mesh = _mesh()
    sh = NamedSharding(mesh, P(None, "data"))

    def f(core, tail):
        full = jnp.concatenate([core, tail], axis=-1)
        return (full.reshape((4, 256)) ** 2).sum()

    core = jax.device_put(jnp.ones((2, 512)), sh)
    tail = jnp.zeros((2, 0))
    g_core, g_tail = _run_gated(jax.jit(jax.grad(f, argnums=(0, 1))), core, tail)
    np.testing.assert_array_equal(np.asarray(g_core), 2.0 * np.ones((2, 512)))
    assert g_tail.shape == (2, 0)


def test_zero_size_tail_sharded_operand_grad():
    """Variant with the zero-size operand itself COMMITTED sharded (a (B, 0)
    array split 8 ways) — the partitioner must assign per-device zero-size
    tiles and still verify the merged reshape."""
    need_devices(8)
    mesh = _mesh()
    sh = NamedSharding(mesh, P(None, "data"))

    def f(core, tail):
        tail = lax.with_sharding_constraint(tail, sh)
        full = lax.with_sharding_constraint(
            jnp.concatenate([core, tail], axis=-1), sh)
        return (full.reshape((4, 256)) ** 2).sum()

    core = jax.device_put(jnp.ones((2, 512)), sh)
    tail = jax.device_put(jnp.zeros((2, 0)), sh)
    g_core, g_tail = _run_gated(jax.jit(jax.grad(f, argnums=(0, 1))), core, tail)
    np.testing.assert_array_equal(np.asarray(g_core), 2.0 * np.ones((2, 512)))
    assert g_tail.shape == (2, 0)


def test_zero_size_conv_partitions_grad():
    """Sub-shard-count conv output forced sharded (length 3 over 8 devices
    → five zero-size partitions) feeding a reshape, under grad — the
    boundary-conv analogue of a short tail kept as a live buffer."""
    need_devices(8)
    mesh = _mesh()
    sh = NamedSharding(mesh, P(None, "data"))

    def f(x):
        seg = x[:, -16:]
        k = jnp.ones((1, 1, 12), x.dtype)
        out = lax.conv_general_dilated(
            seg[:, None, :], k, window_strides=(2,), padding=[(0, 0)],
            dimension_numbers=lax.conv_dimension_numbers(
                (1, 1, 1), (1, 1, 1), ("NCH", "OIH", "NCH")),
        )  # (2, 1, 3): shorter than the device count
        out = lax.with_sharding_constraint(
            out, NamedSharding(mesh, P(None, None, "data")))
        return out.reshape((2, 3)).sum() + (x ** 2).sum()

    x = jax.device_put(jnp.ones((2, 4096)), sh)
    g = _run_gated(jax.jit(jax.grad(f)), x)
    assert g.shape == (2, 4096)
    assert bool(jnp.isfinite(g).all())


def test_none_tail_form_never_exposes_the_pattern():
    """The mitigation itself: with the empty tail dropped from the pytree
    BEFORE the jit boundary (`tail=None` — an empty pytree node), the
    traced graph contains no zero-size operand at all, so the gated
    patterns above cannot arise regardless of toolchain. Differentiating
    through the None-tail structure must work unconditionally."""
    need_devices(8)
    mesh = _mesh()
    sh = NamedSharding(mesh, P(None, "data"))

    def f(tree):
        core, tail = tree["core"], tree["tail"]  # tail is None: not traced
        assert tail is None
        return (core.reshape((4, 256)) ** 2).sum()

    tree = {"core": jax.device_put(jnp.ones((2, 512)), sh), "tail": None}
    g = jax.jit(jax.grad(f))(tree)
    jax.block_until_ready(g)
    np.testing.assert_array_equal(np.asarray(g["core"]), 2.0 * np.ones((2, 512)))
    assert g["tail"] is None
