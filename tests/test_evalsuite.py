"""Evaluation-suite tests: closed-form metric cases (SURVEY.md §4d),
pack/unpack round-trips, mask nesting invariants, baseline methods on a
linear oracle, end-to-end evaluators with tiny models."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.evalsuite.metrics import compute_auc, generate_masks, spearman
from wam_tpu.evalsuite.packing import (
    array_to_coeffs1d,
    array_to_coeffs2d,
    coeffs_to_array1d,
    coeffs_to_array2d,
    packed2d_shape,
)
from wam_tpu.wavelets import wavedec, wavedec2, waverec2

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



def test_compute_auc_closed_form():
    probs = jnp.array([0.5, 1.0, 0.5, 1.0])
    # sum=3, max=1, len=4 -> 0.75
    np.testing.assert_allclose(compute_auc(probs), 0.75)


def test_generate_masks_nesting():
    attr = jnp.asarray(np.random.default_rng(0).random((8, 8)), dtype=jnp.float32)
    ins, dele = generate_masks(4, attr)
    assert ins.shape == (5, 8, 8)
    ins_n = np.asarray(ins)
    dele_n = np.asarray(dele)
    # nesting: each insertion mask contains the previous one
    for i in range(4):
        assert np.all(ins_n[i + 1] >= ins_n[i])
        assert np.all(dele_n[i + 1] <= dele_n[i])
    # boundary masks
    assert ins_n[0].sum() == 0 and ins_n[-1].sum() == 64
    assert dele_n[0].sum() == 64 and dele_n[-1].sum() == 0
    # insertion masks grow by n_components, adding the most-important first
    order = np.argsort(-np.asarray(attr), axis=None)
    top16 = np.unravel_index(order[:16], (8, 8))
    assert np.all(ins_n[1][top16] == 1)


def test_spearman_perfect_and_reverse():
    a = jnp.array([1.0, 2.0, 3.0, 4.0])
    np.testing.assert_allclose(spearman(a, a * 10), 1.0, atol=1e-6)
    np.testing.assert_allclose(spearman(a, -a), -1.0, atol=1e-6)


def test_spearman_ties_match_scipy():
    """Tie-averaged ranks must match scipy.stats.spearmanr to 1e-6 —
    μ-fidelity Δprobs tie routinely (VERDICT.md round-1 weak #6)."""
    scipy_stats = pytest.importorskip("scipy.stats")
    rng = np.random.default_rng(5)
    # heavy deliberate ties in both vectors
    a = np.round(rng.standard_normal(200), 1).astype(np.float32)
    b = np.round(rng.standard_normal(200), 1).astype(np.float32)
    b[:50] = 0.0
    a[100:130] = 0.5
    want = scipy_stats.spearmanr(a, b).statistic
    got = float(spearman(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_superpixel_sum_keeps_edges_and_aligns_with_mask_upsample():
    """Non-divisible maps keep edge mass instead of silently truncating
    (VERDICT.md round-1 weak #7), and the cell partition matches the
    `upsample_nearest` mapping that builds the μ-fidelity masks — so each
    attribution cell sums exactly the pixels its mask cell perturbs."""
    from wam_tpu.ops.filters import superpixel_sum, upsample_nearest

    img = jnp.ones((2, 30, 30))
    cells = superpixel_sum(img, 4)
    assert cells.shape == (2, 4, 4)
    np.testing.assert_allclose(np.asarray(cells).sum(), 2 * 30 * 30, rtol=1e-6)

    # alignment: summing per cell must equal masking with the upsampled
    # one-cell mask and summing the surviving pixels, for every cell
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((30, 30)).astype(np.float32))
    got = np.asarray(superpixel_sum(a, 4))
    for gi in range(4):
        for gj in range(4):
            m = jnp.zeros((4, 4)).at[gi, gj].set(1.0)
            up = upsample_nearest(m, (30, 30))
            np.testing.assert_allclose(
                got[gi, gj], float((a * up).sum()), rtol=1e-5, atol=1e-5
            )
    # divisible path unchanged
    np.testing.assert_allclose(
        np.asarray(superpixel_sum(jnp.ones((8, 8)), 4)), np.full((4, 4), 4.0)
    )


def test_pack1d_roundtrip():
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 64)), dtype=jnp.float32)
    coeffs = wavedec(x, "db2", level=3)
    lengths = [c.shape[-1] for c in coeffs]
    packed = coeffs_to_array1d(coeffs)
    assert packed.shape == (2, sum(lengths))
    back = array_to_coeffs1d(packed, lengths)
    for a, b in zip(coeffs, back):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("wavelet,size", [("haar", 32), ("db2", 32), ("haar", 48)])
def test_pack2d_roundtrip(wavelet, size):
    x = jnp.asarray(np.random.default_rng(2).standard_normal((3, size, size)), dtype=jnp.float32)
    coeffs = wavedec2(x, wavelet, level=3)
    shapes = [tuple(coeffs[0].shape[-2:])] + [tuple(d.diagonal.shape[-2:]) for d in coeffs[1:]]
    packed = coeffs_to_array2d(coeffs)
    assert packed.shape[-2:] == packed2d_shape(coeffs)
    back = array_to_coeffs2d(packed, shapes)
    rec_orig = waverec2(coeffs, wavelet)
    rec_back = waverec2(back, wavelet)
    np.testing.assert_allclose(np.asarray(rec_orig), np.asarray(rec_back), atol=1e-5)


def test_pack2d_identity_mask_reconstructs():
    """All-ones mask through pack→mask→unpack→waverec2 = original image."""
    x = jnp.asarray(np.random.default_rng(3).standard_normal((3, 32, 32)), dtype=jnp.float32)
    coeffs = wavedec2(x, "haar", level=3)
    shapes = [tuple(coeffs[0].shape[-2:])] + [tuple(d.diagonal.shape[-2:]) for d in coeffs[1:]]
    packed = coeffs_to_array2d(coeffs)
    masked = packed * jnp.ones(packed.shape[-2:])
    rec = waverec2(array_to_coeffs2d(masked, shapes), "haar")
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=1e-5)


# -- baselines on a linear oracle ------------------------------------------


def _linear_model(W, C=3, H=16):
    def fn(x):
        return x.reshape(x.shape[0], -1) @ W

    return fn


def test_saliency_linear_oracle():
    from wam_tpu.evalsuite.baselines import saliency

    rng = np.random.default_rng(4)
    W = jnp.asarray(rng.standard_normal((3 * 16 * 16, 4)), dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 3, 16, 16)), dtype=jnp.float32)
    y = jnp.array([1, 2])
    sal = saliency(_linear_model(W), x, y)
    for i in range(2):
        expected = np.abs(np.asarray(W[:, int(y[i])]).reshape(3, 16, 16)).mean(0) / 2
        np.testing.assert_allclose(np.asarray(sal[i]), expected, atol=1e-5)


def test_integrated_gradients_linear_completeness():
    """For a linear model, IG = x ⊙ grad exactly (path-independent)."""
    from wam_tpu.evalsuite.baselines import integrated_gradients

    rng = np.random.default_rng(5)
    W = jnp.asarray(rng.standard_normal((3 * 16 * 16, 4)), dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((1, 3, 16, 16)), dtype=jnp.float32)
    y = jnp.array([0])
    ig = integrated_gradients(_linear_model(W), x, y, n_steps=8)
    expected = (np.asarray(x[0]) * np.asarray(W[:, 0]).reshape(3, 16, 16)).mean(0)
    np.testing.assert_allclose(np.asarray(ig[0]), expected, atol=1e-5)


def test_smoothgrad_zero_noise_equals_saliency_sign():
    from wam_tpu.evalsuite.baselines import smoothgrad_pixel

    rng = np.random.default_rng(6)
    W = jnp.asarray(rng.standard_normal((3 * 16 * 16, 4)), dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((1, 3, 16, 16)), dtype=jnp.float32)
    y = jnp.array([3])
    sg = smoothgrad_pixel(_linear_model(W), x, y, jax.random.PRNGKey(0), n_samples=3, stdev_spread=0.0)
    # implementation: abs of sample-mean grads, then channel mean
    expected = np.abs(np.asarray(W[:, 3]).reshape(3, 16, 16)).mean(0)
    np.testing.assert_allclose(np.asarray(sg[0]), expected, atol=1e-5)


def test_gradcam_resnet():
    from wam_tpu.evalsuite.baselines import gradcam, gradcam_pp, layercam
    from wam_tpu.models import resnet18

    model = resnet18(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.asarray(np.random.default_rng(7).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = jnp.array([0, 4])
    for fn in (gradcam, gradcam_pp, layercam):
        cam = fn(model, variables, x, y, layer="stage3")
        assert cam.shape == (2, 32, 32)
        assert np.all(np.asarray(cam) >= 0)
        assert np.all(np.isfinite(np.asarray(cam)))


def test_guided_relu_backward_rule():
    """Backward passes g only where input>0 AND g>0."""
    from wam_tpu.evalsuite.baselines import guided_relu

    x = jnp.array([-1.0, 2.0, 3.0, 0.5])
    w = jnp.array([1.0, -1.0, 2.0, 0.5])  # cotangents via dot
    g = jax.grad(lambda v: jnp.sum(guided_relu(v) * w))(x)
    # x=-1: input<0 -> 0; x=2: g=-1<0 -> 0; x=3: g=2>0 -> 2; x=0.5: g=0.5>0
    np.testing.assert_allclose(np.asarray(g), [0.0, 0.0, 2.0, 0.5])


def test_guided_backprop_resnet():
    from wam_tpu.evalsuite.baselines import guided_backprop, saliency
    from wam_tpu.models import bind_inference, resnet18

    model = resnet18(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.asarray(np.random.default_rng(8).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = jnp.array([1, 3])
    gb = guided_backprop(model, variables, x, y)
    assert gb.shape == (2, 32, 32)
    assert np.all(np.asarray(gb) >= 0) and np.all(np.isfinite(np.asarray(gb)))
    # the guided rule must actually change the map vs plain saliency
    sal = saliency(bind_inference(model, variables, nchw=True), x, y)
    assert not np.allclose(np.asarray(gb), np.asarray(sal), atol=1e-6)


class _MiniReLUNet(nn.Module):
    """Tiny conv-relu-dense net with the `post_linear` hook the real ε-LRP
    rides on (wam_tpu/evalsuite/baselines.py:lrp)."""

    classes: int = 4
    use_bias: bool = False
    post_linear: object = staticmethod(lambda z: z)

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(8, (3, 3), use_bias=self.use_bias, name="c1")(x)
        x = self.post_linear(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.classes, use_bias=self.use_bias, name="d1")(x)
        return self.post_linear(x)


def test_lrp_biasfree_equals_gradxinput_and_conserves():
    """VERDICT.md round-1 #3 criterion (a): on a bias-free ReLU net, ε→0
    LRP equals gradient x input (scaled by 1/logit — one-hot output seed),
    and relevance is conserved (Σ R_in = output relevance = 1). Exercises
    the non-ResNet `post_linear` tap fallback of `lrp` (→ lrp_eps)."""
    from wam_tpu.evalsuite.baselines import gradient_x_input, lrp

    model = _MiniReLUNet(use_bias=False)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 12, 12, 3)))
    x = jnp.asarray(np.random.default_rng(9).standard_normal((1, 3, 12, 12)), dtype=jnp.float32)
    y = jnp.array([2])
    r = lrp(model, variables, x, y, eps=1e-9)

    def model_fn(v):
        return model.apply(variables, jnp.transpose(v, (0, 2, 3, 1)))

    # gradient_x_input channel-MEANS and lrp channel-SUMS; batch of 1 so the
    # diag-mean loss scale matches up to the channel count; the one-hot seed
    # divides the whole map by the picked logit.
    gxi = gradient_x_input(model_fn, x, y)
    logit = float(model_fn(x)[0, 2])
    np.testing.assert_allclose(
        np.asarray(r), np.asarray(gxi) * 3 / logit, atol=1e-4, rtol=1e-4
    )
    np.testing.assert_allclose(float(np.asarray(r).sum()), 1.0, rtol=1e-4)


def test_lrp_bias_absorption_single_layer():
    """VERDICT.md round-1 #3 criterion (c): per-layer ε-rule conservation —
    with a biased linear layer the bias absorbs exactly its share of
    relevance: with the one-hot seed passed through the fc tap,
    Σ R_in = z_y·(z_y − b_y)/(z_y + ε·sign z_y)²."""
    from wam_tpu.evalsuite.baselines import lrp

    class OneDense(nn.Module):
        post_linear: object = staticmethod(lambda z: z)

        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            return self.post_linear(nn.Dense(4, use_bias=True, name="d")(x))

    model = OneDense()
    variables = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 6, 6, 3)))
    # nontrivial bias
    variables = jax.tree_util.tree_map(lambda a: a, variables)
    b = jnp.asarray(np.random.default_rng(3).standard_normal(4), dtype=jnp.float32)
    variables = {"params": {"d": {"kernel": variables["params"]["d"]["kernel"], "bias": b}}}
    x = jnp.asarray(np.random.default_rng(4).standard_normal((1, 3, 6, 6)), dtype=jnp.float32)
    y = jnp.array([1])
    eps = 1e-6
    r = lrp(model, variables, x, y, eps=eps)
    z = model.apply(variables, jnp.transpose(x, (0, 2, 3, 1)))[0]
    zy, by = float(z[1]), float(b[1])
    stab = zy + eps * np.sign(zy)
    expect = zy * (zy - by) / stab**2
    np.testing.assert_allclose(float(np.asarray(r).sum()), expect, rtol=1e-4)


def test_lrp_resnet_walker_validates_against_autodiff():
    """The lrp_resnet walker with composite='epsilon' at ε→0 must reproduce
    gradient x input exactly (Ancona et al. 2018 identity for ReLU nets) —
    this validates every stage of the structural walker (stem, blocks,
    residual splits, pools, fc) against autodiff."""
    from wam_tpu.evalsuite.baselines import gradient_x_input
    from wam_tpu.evalsuite.lrp import lrp_resnet
    from wam_tpu.models import bind_inference, resnet18

    model = resnet18(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.asarray(np.random.default_rng(11).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = jnp.array([1, 3])
    r = lrp_resnet(model, variables, x, y, eps=1e-9, composite="epsilon")
    logits = bind_inference(model, variables, nchw=True)(x)
    picked = np.take_along_axis(np.asarray(logits), np.asarray(y)[:, None], 1)[:, 0]
    gxi = gradient_x_input(bind_inference(model, variables, nchw=True), x, y)
    # lrp channel-sums with a one-hot seed (per-sample divide by the picked
    # logit); gxi channel-means with a batch-mean loss: scale = C * B / z_y
    np.testing.assert_allclose(
        np.asarray(r), np.asarray(gxi) * 3 * 2 / picked[:, None, None], atol=2e-5
    )


def test_lrp_resnet_epf_conserves_and_differs_from_gradxinput():
    """VERDICT.md round-1 #3 criteria (b) + (c) on the faithful
    EpsilonPlusFlat composite: relevance is conserved through every layer
    (Σ R_in = picked logit on a bias-free net, to ~1e-4) and the map is NOT
    gradient x input."""
    from wam_tpu.evalsuite.baselines import gradient_x_input, lrp
    from wam_tpu.models import bind_inference, resnet18

    model = resnet18(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.asarray(np.random.default_rng(11).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = jnp.array([1, 3])
    r = lrp(model, variables, x, y)  # ResNet → EpsilonPlusFlat walker
    assert r.shape == (2, 32, 32)
    assert np.all(np.isfinite(np.asarray(r)))
    # one-hot seed: conserved relevance is 1 per sample (bias-free init)
    np.testing.assert_allclose(
        np.asarray(r.sum(axis=(1, 2))), np.ones(2), rtol=1e-4, atol=1e-5
    )
    gxi = gradient_x_input(bind_inference(model, variables, nchw=True), x, y)
    rn = np.asarray(r) / (np.abs(np.asarray(r)).max() + 1e-12)
    gn = np.asarray(gxi) / (np.abs(np.asarray(gxi)).max() + 1e-12)
    assert float(np.abs(rn - gn).max()) > 0.1


# -- end-to-end evaluators -------------------------------------------------


class TinyImgModel(nn.Module):
    classes: int = 5

    @nn.compact
    def __call__(self, x):
        x = jnp.transpose(x, (0, 2, 3, 1))
        x = nn.Conv(8, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x).mean(axis=(1, 2))
        return nn.Dense(self.classes)(x)


@pytest.fixture(scope="module")
def img_model_fn():
    model = TinyImgModel()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    return lambda x: model.apply(params, x)


def test_eval2dwam_insertion_deletion(img_model_fn):
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.wam2d import WaveletAttribution2D

    expl = WaveletAttribution2D(img_model_fn, wavelet="haar", J=2, n_samples=2)
    ev = Eval2DWAM(img_model_fn, expl, wavelet="haar", J=2, batch_size=16)
    x = jnp.asarray(np.random.default_rng(8).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = [1, 3]
    ins = ev.insertion(x, y, n_iter=8)
    dele = ev.deletion(x, y, n_iter=8)
    assert len(ins) == 2 and len(dele) == 2
    assert all(0 <= s <= 1 for s in ins + dele)
    assert len(ev.insertion_curves[0]) == 9


def test_eval2dwam_mu_fidelity(img_model_fn):
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.wam2d import WaveletAttribution2D

    expl = WaveletAttribution2D(img_model_fn, wavelet="haar", J=2, n_samples=2)
    ev = Eval2DWAM(img_model_fn, expl, wavelet="haar", J=2, batch_size=16)
    x = jnp.asarray(np.random.default_rng(9).standard_normal((1, 3, 32, 32)), dtype=jnp.float32)
    mus = ev.mu_fidelity(x, [2], grid_size=8, sample_size=6, subset_size=12)
    assert len(mus) == 1
    assert -1.0 <= mus[0] <= 1.0


def test_eval_image_baselines(img_model_fn):
    from wam_tpu.evalsuite.eval_baselines import EvalImageBaselines

    model = TinyImgModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))

    # TinyImgModel consumes NCHW directly
    ev = EvalImageBaselines(model, variables, method="saliency", batch_size=16, nchw=False)
    x = jnp.asarray(np.random.default_rng(10).standard_normal((1, 3, 32, 32)), dtype=jnp.float32)
    ins = ev.insertion(x, [0], n_iter=8)
    assert len(ins) == 1
    mus = ev.mu_fidelity(x, [0], grid_size=8, sample_size=5, subset_size=10)
    assert len(mus) == 1


def test_batched_auc_matches_per_image_loop():
    """VERDICT.md round-1 #6: the single-dispatch batched AUC path must
    reproduce the round-1 per-image host loop exactly."""
    from wam_tpu.evalsuite.metrics import (
        batched_auc_runner,
        compute_auc,
        generate_masks,
        softmax_probs,
    )

    model = TinyImgModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))

    def model_fn(v):
        return model.apply(variables, jnp.transpose(v, (0, 2, 3, 1)))

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((5, 3, 16, 16)), dtype=jnp.float32)
    expl = jnp.asarray(rng.standard_normal((5, 16, 16)), dtype=jnp.float32)
    y = np.array([0, 1, 2, 3, 4])
    n_iter = 8

    def inputs_fn(x_s, e_s):
        ins, _ = generate_masks(n_iter, e_s)
        return x_s[None] * ins[:, None]

    runner = batched_auc_runner(inputs_fn, model_fn, images_per_chunk=2)
    out = runner(x, expl, jnp.asarray(y))  # one [score | curve] array per image
    scores, curves = out[:, 0], out[:, 1:]

    for s in range(5):
        inputs = inputs_fn(x[s], expl[s])
        probs = softmax_probs(model_fn(inputs))[:, int(y[s])]
        np.testing.assert_allclose(np.asarray(curves[s]), np.asarray(probs), atol=1e-6)
        np.testing.assert_allclose(float(scores[s]), float(compute_auc(probs)), atol=1e-6)


def test_eval2d_auc_runner_cache_reused():
    """The jitted batch runner is memoized per (mode, n_iter, shapes)."""
    from wam_tpu.evalsuite.eval2d import Eval2DWAM

    model = TinyImgModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))

    def model_fn(v):
        return model.apply(variables, jnp.transpose(v, (0, 2, 3, 1)))

    ev = Eval2DWAM(model_fn, explainer=lambda x, y: jnp.ones(x.shape[:1] + x.shape[-2:]),
                   wavelet="haar", J=2, batch_size=32)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 3, 16, 16)), dtype=jnp.float32)
    y = np.array([0, 1])
    ev.insertion(x, y, n_iter=4)
    assert len(ev._auc_runners) == 1
    ev.insertion(x, y, n_iter=4)
    assert len(ev._auc_runners) == 1
    ev.deletion(x, y, n_iter=4)
    assert len(ev._auc_runners) == 2


def test_gradcam_on_vit_token_grid():
    """VERDICT.md round-1 #10: GradCAM over the ViT token tap — class token
    dropped, patch tokens folded to the √N grid, (B, H, W) map out."""
    from wam_tpu.evalsuite.baselines import gradcam, gradcam_pp, layercam
    from wam_tpu.models.vit import vit_tiny_test

    model = vit_tiny_test(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    assert "perturbations" in variables  # the tap exists
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = jnp.array([1, 4])
    for fn in (gradcam, gradcam_pp, layercam):
        cam = fn(model, variables, x, y, layer="tokens")
        assert cam.shape == (2, 32, 32)
        arr = np.asarray(cam)
        assert np.all(np.isfinite(arr)) and np.all(arr >= 0)
    # the token adapter itself: acts/grads come back on the 4x4 patch grid
    # (32/8 patches per side), class token dropped, and the activations vary
    # with the input
    from wam_tpu.evalsuite.baselines import _acts_and_grads

    acts, grads = _acts_and_grads(model, variables, x, y, "tokens", nchw=True)
    assert acts.shape == (2, 4, 4, 64)
    assert grads.shape == (2, 4, 4, 64)
    acts2, _ = _acts_and_grads(model, variables, x.at[0].multiply(-1.0), y, "tokens", nchw=True)
    assert not np.allclose(np.asarray(acts[0]), np.asarray(acts2[0]))


def test_guided_backprop_rejects_models_without_act():
    """VERDICT.md round-1 weak #8: the documented error path for non-ReLU
    models (no swappable `act`) must actually raise."""
    from wam_tpu.evalsuite.baselines import guided_backprop
    from wam_tpu.models.vit import vit_tiny_test

    model = vit_tiny_test(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.zeros((1, 3, 32, 32))
    with pytest.raises(ValueError, match="act"):
        guided_backprop(model, variables, x, jnp.array([0]))


def test_gradcam_batch_matches_per_sample():
    """Gradient taps must be per-sample even when variables were initialized
    at batch 1 — the stored perturbation variable's init batch must not
    batch-sum the CAM weights (regression: round-2 fix in _acts_and_grads)."""
    from wam_tpu.evalsuite.baselines import gradcam
    from wam_tpu.models import resnet18

    model = resnet18(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.asarray(np.random.default_rng(9).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = jnp.array([1, 3])
    both = np.asarray(gradcam(model, variables, x, y))
    for s in range(2):
        one = np.asarray(gradcam(model, variables, x[s : s + 1], y[s : s + 1]))
        np.testing.assert_allclose(both[s], one[0], atol=1e-4)


def test_lrp_resnet_walker_bottleneck_validates_against_autodiff():
    """Same autodiff validation for the Bottleneck branch — the path the
    production ResNet-50/101 'lrp' evaluations take (3-conv main branch,
    stride on conv2, downsample shortcut)."""
    from wam_tpu.evalsuite.baselines import gradient_x_input
    from wam_tpu.evalsuite.lrp import lrp_resnet
    from wam_tpu.models import bind_inference
    from wam_tpu.models.resnet import Bottleneck, ResNet

    model = ResNet(stage_sizes=(1, 2), block_cls=Bottleneck, num_classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.asarray(np.random.default_rng(13).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = jnp.array([0, 3])
    r = lrp_resnet(model, variables, x, y, eps=1e-9, composite="epsilon")
    logits = bind_inference(model, variables, nchw=True)(x)
    picked = np.take_along_axis(np.asarray(logits), np.asarray(y)[:, None], 1)[:, 0]
    gxi = gradient_x_input(bind_inference(model, variables, nchw=True), x, y)
    np.testing.assert_allclose(
        np.asarray(r), np.asarray(gxi) * 3 * 2 / picked[:, None, None], atol=2e-5
    )
    # EpsilonPlusFlat on the same net: finite + conserving (bias-free init,
    # one-hot seed → Σ R = 1 per sample)
    repf = lrp_resnet(model, variables, x, y)
    np.testing.assert_allclose(
        np.asarray(repf.sum(axis=(1, 2))), np.ones(2), rtol=1e-4, atol=1e-5
    )


def test_batched_auc_fan_chunked_matches_unchunked():
    """When one sample's fan exceeds batch_size, the runner chunks the model
    forward within the fan (memory cap honored) with identical results."""
    from wam_tpu.evalsuite.metrics import batched_auc_runner, generate_masks

    model = TinyImgModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))

    def model_fn(v):
        return model.apply(variables, jnp.transpose(v, (0, 2, 3, 1)))

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((3, 3, 16, 16)), dtype=jnp.float32)
    expl = jnp.asarray(rng.standard_normal((3, 16, 16)), dtype=jnp.float32)
    y = jnp.array([0, 1, 2])

    def inputs_fn(x_s, e_s):
        ins, _ = generate_masks(8, e_s)
        return x_s[None] * ins[:, None]

    plain = batched_auc_runner(inputs_fn, model_fn, images_per_chunk=1)
    chunked = batched_auc_runner(inputs_fn, model_fn, images_per_chunk=1, fan_chunk=4)
    out0 = plain(x, expl, y)     # one [score | curve] array per image
    out1 = chunked(x, expl, y)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out1), atol=1e-6)


# -- round-3 batched-evaluator regressions (VERDICT.md round-2 weak #3) ----


def test_eval2dwam_mu_fidelity_batched_matches_loop(img_model_fn):
    """The one-dispatch μ-fidelity must reproduce the per-image host loop
    (exercised here via the mesh path, which still loops)."""
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("requires 2 virtual devices")

    rng = np.random.default_rng(21)
    fixed = jnp.asarray(rng.standard_normal((2, 32, 32)), dtype=jnp.float32)
    explainer = lambda x, y: fixed
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = [1, 4]

    ev = Eval2DWAM(img_model_fn, explainer, wavelet="haar", J=2, batch_size=16)
    mus = ev.mu_fidelity(x, y, grid_size=8, sample_size=6, subset_size=12)

    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    evm = Eval2DWAM(img_model_fn, explainer, wavelet="haar", J=2, batch_size=16,
                    mesh=mesh)
    mus_loop = evm.mu_fidelity(x, y, grid_size=8, sample_size=6, subset_size=12)
    np.testing.assert_allclose(mus, mus_loop, atol=1e-5)


def test_eval_image_baselines_mu_fidelity_batched_matches_loop(img_model_fn):
    from wam_tpu.evalsuite.eval_baselines import EvalImageBaselines
    from wam_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("requires 2 virtual devices")

    model = TinyImgModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    rng = np.random.default_rng(22)
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = [0, 3]

    ev = EvalImageBaselines(model, variables, method="saliency", batch_size=16,
                            nchw=False)
    mus = ev.mu_fidelity(x, y, grid_size=8, sample_size=5, subset_size=10)
    evm = EvalImageBaselines(model, variables, method="saliency", batch_size=16,
                             nchw=False, mesh=make_mesh({"data": 2}, devices=jax.devices()[:2]))
    mus_loop = evm.mu_fidelity(x, y, grid_size=8, sample_size=5, subset_size=10)
    np.testing.assert_allclose(mus, mus_loop, atol=1e-5)


class TinyAudioModel(nn.Module):
    """Melspec classifier stub: (B, 1, T, M) → logits."""

    classes: int = 3

    @nn.compact
    def __call__(self, x):
        x = jnp.transpose(x, (0, 2, 3, 1))
        x = nn.Conv(4, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x).mean(axis=(1, 2))
        return nn.Dense(self.classes)(x)


def test_eval_audio_baselines_batched_matches_loop():
    """Audio AUC + argmax (input-fidelity) now route through the batched
    runner off-mesh; both must reproduce the per-sample loop (the mesh
    path) exactly."""
    from wam_tpu.evalsuite.eval_baselines import EvalAudioBaselines
    from wam_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("requires 2 virtual devices")

    model = TinyAudioModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1, 16, 12)))
    rng = np.random.default_rng(23)
    x = jnp.asarray(rng.standard_normal((2, 1, 16, 12)), dtype=jnp.float32)
    y = [0, 2]

    ev = EvalAudioBaselines(model, variables, method="saliency", batch_size=8)
    evm = EvalAudioBaselines(model, variables, method="saliency", batch_size=8,
                             mesh=make_mesh({"data": 2}, devices=jax.devices()[:2]))

    ins = ev.insertion(x, y, n_iter=4)
    ins_loop = evm.insertion(x, y, n_iter=4)
    np.testing.assert_allclose(ins, ins_loop, atol=1e-6)

    fos = ev.faithfulness_of_spectra(x, y)
    fos_loop = evm.faithfulness_of_spectra(x, y)
    np.testing.assert_allclose(fos, fos_loop, atol=1e-6)

    fid = ev.input_fidelity(x, y)
    fid_loop = evm.input_fidelity(x, y)
    assert fid == fid_loop


def test_eval_baselines_compute_dtype_bf16(img_model_fn):
    """compute_dtype=jnp.bfloat16 casts params once and runs every path at
    bf16 with f32 logits out; scores track the f32 evaluator closely."""
    model = TinyImgModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    rng = np.random.default_rng(31)
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = [0, 3]

    from wam_tpu.evalsuite.eval_baselines import EvalImageBaselines

    ev32 = EvalImageBaselines(model, variables, method="saliency",
                              batch_size=16, nchw=False)
    evbf = EvalImageBaselines(model, variables, method="saliency",
                              batch_size=16, nchw=False,
                              compute_dtype=jnp.bfloat16)
    assert evbf.variables["params"]["Conv_0"]["kernel"].dtype == jnp.bfloat16
    logits = evbf.model_fn(x)
    assert logits.dtype == jnp.float32
    ins32 = ev32.insertion(x, y, n_iter=8)
    insbf = evbf.insertion(x, y, n_iter=8)
    np.testing.assert_allclose(insbf, ins32, atol=0.15)


def test_lrp_under_bf16_evaluator_runs_f32(img_model_fn):
    """`method='lrp'` with compute_dtype=bf16 must work: the walker upcasts
    to f32 internally (the ε-stabilizer vanishes in bf16) and produces the
    same relevance as the f32 evaluator."""
    from wam_tpu.evalsuite.eval_baselines import EvalImageBaselines
    from wam_tpu.models import resnet18

    model = resnet18(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jnp.asarray(np.random.default_rng(41).standard_normal((1, 3, 32, 32)), jnp.float32)
    y = [2]
    r32 = EvalImageBaselines(model, variables, method="lrp",
                             batch_size=16).precompute(x, jnp.asarray(y))
    rbf = EvalImageBaselines(model, variables, method="lrp", batch_size=16,
                             compute_dtype=jnp.bfloat16).precompute(x, jnp.asarray(y))
    assert np.isfinite(np.asarray(rbf)).all()
    # params were cast to bf16 at evaluator init (lossy) before the walker
    # upcasts — agreement is bounded by that one rounding, not exactness
    np.testing.assert_allclose(np.asarray(rbf), np.asarray(r32), atol=3e-4)


def test_eval1dwam_auc_mesh_matches_single_device():
    """Eval1DWAM (previously untested directly) through both targets, and
    the mesh path must reproduce the single-device batched runner — the
    round-4 one-dispatch shard_map fan (no per-sample loop on-mesh)."""
    from wam_tpu.evalsuite.eval1d import Eval1DWAM
    from wam_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("requires 2 virtual devices")

    model = TinyAudioModel()
    # waveform length 2048 -> melspec frames under the tiny config below
    n_fft, n_mels, sr = 256, 12, 8000
    import wam_tpu.ops.melspec as ms

    probe = ms.melspectrogram(jnp.zeros((1, 2048)), sample_rate=sr,
                              n_fft=n_fft, n_mels=n_mels)
    t_frames = probe.shape[-2]
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1, t_frames, n_mels)))
    model_fn = lambda m: model.apply(variables, m)

    rng = np.random.default_rng(31)
    x = jnp.asarray(rng.standard_normal((3, 2048)), dtype=jnp.float32)
    y = [0, 2, 1]

    # fixed fake explainer with the documented interface:
    # (mel grads (B, T, M), coefficient-grad list)
    from wam_tpu.wavelets import wavedec

    coeffs = wavedec(x, "db2", level=3, mode="reflect")
    mel_grads = jnp.asarray(rng.standard_normal((3, t_frames, n_mels)), jnp.float32)
    coeff_grads = [jnp.asarray(rng.standard_normal(c.shape), jnp.float32) for c in coeffs]
    explainer = lambda xx, yy: (mel_grads, coeff_grads)

    def build(mesh=None):
        return Eval1DWAM(model_fn, explainer, wavelet="db2", J=3,
                         n_mels=n_mels, n_fft=n_fft, sample_rate=sr,
                         batch_size=16, mesh=mesh)

    ev = build()
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    evm = build(mesh)

    for target in ("wavelet", "melspec"):
        ins = ev.insertion(x, y, target=target, n_iter=4)
        ins_m = evm.insertion(x, y, target=target, n_iter=4)
        np.testing.assert_allclose(ins, ins_m, atol=1e-5, err_msg=target)
    fid = ev.input_fidelity(x, y)
    fid_m = evm.input_fidelity(x, y)
    assert fid == fid_m


def test_eval2dwam_auc_mesh_matches_single_device(img_model_fn):
    """Insertion/deletion through Eval2DWAM's mesh path (now the sharded
    one-dispatch runner) must equal the single-device scores, including a
    batch size that does not divide the mesh axis (cyclic pad + slice)."""
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("requires 2 virtual devices")

    rng = np.random.default_rng(33)
    fixed = jnp.asarray(rng.standard_normal((3, 32, 32)), dtype=jnp.float32)
    explainer = lambda x, y: fixed
    x = jnp.asarray(rng.standard_normal((3, 3, 32, 32)), dtype=jnp.float32)  # 3 % 2 != 0
    y = [1, 4, 0]

    ev = Eval2DWAM(img_model_fn, explainer, wavelet="haar", J=2, batch_size=16)
    mesh = make_mesh({"data": 2}, devices=jax.devices()[:2])
    evm = Eval2DWAM(img_model_fn, explainer, wavelet="haar", J=2, batch_size=16,
                    mesh=mesh)
    for metric in ("insertion", "deletion"):
        a = getattr(ev, metric)(x, y, n_iter=4)
        b = getattr(evm, metric)(x, y, n_iter=4)
        np.testing.assert_allclose(a, b, atol=1e-5, err_msg=metric)
