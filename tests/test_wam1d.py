"""End-to-end WAM-1D tests: dual-tap gradients (melspec + wavelet coeffs),
scaleogram layout, filtering, SmoothGrad/IG estimators, plus AudioCNN and
PointNet/Voxel model smoke tests."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.wam1d import (
    BaseWAM1D,
    VisualizerWAM1D,
    WaveletAttribution1D,
    normalize_waveforms,
    scaleogram,
)

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow

SR, NFFT, NMELS, WLEN = 8000, 256, 32, 4096


class TinyAudioModel(nn.Module):
    classes: int = 6

    @nn.compact
    def __call__(self, x):  # (B, 1, T, M)
        x = jnp.transpose(x, (0, 2, 3, 1))
        x = nn.Conv(8, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.classes)(x)


@pytest.fixture(scope="module")
def model_fn():
    model = TinyAudioModel()
    T = 1 + WLEN // (NFFT // 2)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1, T, NMELS)))
    return lambda x: model.apply(params, x)


def _wam_kwargs():
    return dict(n_mels=NMELS, n_fft=NFFT, sample_rate=SR)


def test_normalize_waveforms_list():
    wfs = [np.array([1, 2, 4], dtype=np.int16), np.array([2, 8, 4], dtype=np.int16)]
    out = np.asarray(normalize_waveforms(wfs))
    np.testing.assert_allclose(out[0], [0.25, 0.5, 1.0])
    np.testing.assert_allclose(out[1], [0.25, 1.0, 0.5])


def test_base_wam1d_dual_taps(model_fn):
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, WLEN)), dtype=jnp.float32)
    wam = BaseWAM1D(model_fn, wavelet="db2", J=3, mode="symmetric", **_wam_kwargs())
    mel_g, coeff_g = wam(x, jnp.array([1, 3]))
    T = 1 + WLEN // (NFFT // 2)
    assert mel_g.shape == (2, T, NMELS)
    assert len(coeff_g) == 4
    assert float(jnp.abs(mel_g).max()) > 0
    assert float(jnp.abs(coeff_g[0]).max()) > 0
    # gradient chain rule consistency: coeff grads nonzero across levels
    for g in coeff_g:
        assert np.all(np.isfinite(np.asarray(g)))


def test_scaleogram_layout():
    coeffs = [np.ones((2, 4)), np.ones((2, 4)) * 2, np.ones((2, 8)) * 3]
    s = scaleogram(coeffs, J=2)
    assert s.shape == (2, 3, 8)
    # approx row: first 4 filled (normalized to 1), rest NaN
    np.testing.assert_allclose(s[0, 0, :4], 1.0)
    assert np.all(np.isnan(s[0, 0, 4:]))
    np.testing.assert_allclose(s[0, 2], 1.0)  # finest fills whole row


def test_filter_reconstruction(model_fn):
    x = jnp.asarray(np.random.default_rng(1).standard_normal((1, WLEN)), dtype=jnp.float32)
    wam = BaseWAM1D(model_fn, wavelet="haar", J=2, **_wam_kwargs())
    wam(x, jnp.array([0]))
    filtered = wam.filter(EPS=0.5)
    assert filtered.shape[-1] >= WLEN
    # EPS=0 keeps everything -> exact reconstruction
    full = wam.filter(EPS=-1.0)
    np.testing.assert_allclose(np.asarray(full)[..., :WLEN], np.asarray(x), atol=1e-4)


def test_smooth_wam1d(model_fn):
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, WLEN)), dtype=jnp.float32)
    expl = WaveletAttribution1D(
        model_fn, wavelet="haar", J=2, method="smooth", n_samples=4, **_wam_kwargs()
    )
    mel_avg, grads = expl(x, jnp.array([0, 2]))
    assert mel_avg.shape[0] == 2 and len(grads) == 3
    mel_avg2, _ = expl(x, jnp.array([0, 2]))
    np.testing.assert_allclose(np.asarray(mel_avg), np.asarray(mel_avg2), atol=1e-6)


def test_integrated_wam1d(model_fn):
    x = jnp.asarray(np.random.default_rng(3).standard_normal((1, WLEN)), dtype=jnp.float32)
    expl = WaveletAttribution1D(
        model_fn, wavelet="db2", J=2, method="integratedgrad", n_samples=6, **_wam_kwargs()
    )
    mel_attr, coeff_attr = expl(x, jnp.array([4]))
    assert np.all(np.isfinite(np.asarray(mel_attr)))
    assert len(coeff_attr) == 3


def test_visualizer_filters(model_fn):
    rng = np.random.default_rng(4)
    x = rng.standard_normal((2, WLEN)).astype(np.float32)
    viz = VisualizerWAM1D(
        model_fn, x, wavelet="haar", J=2, method="smooth", n_samples=2, **_wam_kwargs()
    )
    mel_g, grads = viz(x, jnp.array([0, 1]))
    src, filt = viz.filtered_spectrogram_from_wavelet_coefficients(grads, "ht", EPS=0.3)
    assert src.shape == filt.shape
    src2, filt2 = viz.filtered_spectrogram_from_wavelet_coefficients(grads, "st", EPS=0.2)
    assert np.all(np.isfinite(filt2))
    src3, filt3 = viz.filtered_spectrogram_from_wavelet_coefficients(grads, "modulation")
    assert np.all(np.isfinite(filt3))
    msrc, mfilt = viz.filtered_spectrogram_from_melspec(np.asarray(mel_g), "ht", EPS=0.2)
    assert msrc.shape == mfilt.shape
    _, mfilt2 = viz.filtered_spectrogram_from_melspec(np.asarray(mel_g), "modulation")
    assert np.all(np.isfinite(mfilt2))


def test_audio_cnn_smoke():
    from wam_tpu.models.audio import AudioCNN

    model = AudioCNN(num_classes=50)
    x = jnp.zeros((1, 1, 128, 128))
    variables = model.init(jax.random.PRNGKey(0), x)
    out, state = model.apply(variables, x, mutable=["intermediates"])
    assert out.shape == (1, 50)
    assert set(state["intermediates"]) == {"out0", "out1", "out2", "out3"}
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) <= 1))  # sigmoid head


def test_pointnet_smoke():
    from wam_tpu.models.pointnet import PointNetCls, feature_transform_regularizer

    model = PointNetCls(k=10, feature_transform=True)
    x = jnp.asarray(np.random.default_rng(5).standard_normal((2, 3, 64)), dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    logp, trans, trans_feat = model.apply(variables, x)
    assert logp.shape == (2, 10)
    np.testing.assert_allclose(np.exp(np.asarray(logp)).sum(axis=1), 1.0, atol=1e-4)
    assert trans.shape == (2, 3, 3)
    assert trans_feat.shape == (2, 64, 64)
    reg = feature_transform_regularizer(trans)
    assert float(reg) >= 0


def test_pointnet_dense_smoke():
    from wam_tpu.models.pointnet import PointNetDenseCls

    model = PointNetDenseCls(k=4)
    x = jnp.asarray(np.random.default_rng(6).standard_normal((1, 3, 32)), dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    logp, _, _ = model.apply(variables, x)
    assert logp.shape == (1, 32, 4)


def test_voxel_model_smoke():
    from wam_tpu.models.voxel import VoxelModel

    model = VoxelModel(num_classes=10)
    x = jnp.zeros((2, 1, 16, 16, 16))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (2, 10)


def test_stream_noise_1d_matches_engine_composition(model_fn):
    """stream_noise=True on the 1D class equals the engine-level
    smoothgrad(materialize_noise=False) composition with the same key."""
    from wam_tpu.core.estimators import smoothgrad

    expl = WaveletAttribution1D(
        model_fn, wavelet="haar", J=2, n_samples=3, n_fft=NFFT, n_mels=NMELS,
        sample_rate=SR, stream_noise=True, random_seed=5, stdev_spread=0.01,
    )
    wave = jnp.asarray(
        np.random.default_rng(8).standard_normal((2, WLEN)), jnp.float32
    )
    wave = wave / wave.max(axis=-1, keepdims=True)
    y = jnp.array([0, 1])
    g_mel, g_coeffs = expl(wave, y)

    want = smoothgrad(
        lambda noisy: expl._tap_grads(noisy, y), wave, jax.random.PRNGKey(5),
        n_samples=3, stdev_spread=0.01, materialize_noise=False,
    )
    np.testing.assert_allclose(np.asarray(g_mel), np.asarray(want[0]), atol=1e-6)
    for a, b in zip(g_coeffs, want[1]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_auto_schedule_matches_explicit_chunk(model_fn):
    """1D counterpart of the round-4 "auto" default: numerically identical
    to an explicit chunk; bad strings rejected eagerly."""
    x = jnp.asarray(np.random.default_rng(8).standard_normal((2, WLEN)),
                    jnp.float32)
    y = jnp.array([0, 2])
    kw = dict(wavelet="db4", J=3, n_samples=4, stdev_spread=0.001,
              n_mels=NMELS, n_fft=NFFT, sample_rate=SR)
    m1, _ = WaveletAttribution1D(model_fn, **kw)(x, y)  # "auto" default
    m2, _ = WaveletAttribution1D(model_fn, sample_batch_size=2, **kw)(x, y)
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=1e-5)

    with pytest.raises(ValueError):
        WaveletAttribution1D(model_fn, sample_batch_size="none")
