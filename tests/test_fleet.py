"""Multi-chip fleet serving (`wam_tpu/serve/fleet.py`): load-aware routing,
shared admission backpressure, oversize data-parallel dispatch exactness,
replica-death failover, the per-replica compile invariant, and the v2
fleet ledger schema.

Same discipline as tests/test_serve.py: the operational tests drive worker
loops with GATED fake entries (threading.Event handshakes, no sleeps) so
the queue/routing states they assert are deterministic. Runs on the
virtual 8-device CPU mesh the conftest forces."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import need_devices
from wam_tpu.serve import (
    FleetMetrics,
    FleetServer,
    NoBucketError,
    QueueFullError,
    ServeMetrics,
    bucket_key,
    fleet_aot_key,
)


class _GateEntry:
    """Fake entry that parks its replica's worker inside the dispatch until
    released — deterministic in-flight state without sleeps."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.calls = 0

    def __call__(self, xs, ys):
        self.calls += 1
        self.entered.set()
        assert self.release.wait(timeout=10), "test gate never released"
        return np.asarray(xs) * 2.0


def _gated_fleet(n, **kw):
    gates = {rid: _GateEntry() for rid in range(n)}
    fleet = FleetServer(
        lambda rid, m: gates.get(rid, lambda xs, ys: np.asarray(xs) * 2.0),
        [(4,)],
        replicas=n,
        max_batch=1,
        max_wait_ms=0.0,
        warmup=False,
        oversize="fanout",
        **kw,
    )
    return fleet, gates


# -- routing ------------------------------------------------------------------


def test_routing_picks_idle_replica():
    """With replica A parked mid-dispatch (one in-flight batch), the next
    submit must route to idle replica B: A's projected drain includes the
    in-flight batch, so its score is strictly higher."""
    need_devices(2)
    fleet, gates = _gated_fleet(2)
    x = np.zeros((4,), np.float32)
    try:
        f0 = fleet.submit(x, 0)  # both idle -> tie-break to replica 0
        assert gates[0].entered.wait(timeout=10)
        f1 = fleet.submit(x, 0)  # 0 busy -> must land on 1
        assert gates[1].entered.wait(timeout=10)
        assert gates[0].calls == 1 and gates[1].calls == 1
        for g in gates.values():
            g.release.set()
        np.testing.assert_array_equal(f0.result(timeout=10), x * 2.0)
        np.testing.assert_array_equal(f1.result(timeout=10), x * 2.0)
    finally:
        for g in gates.values():
            g.release.set()
        fleet.close()


def test_shared_admission_rejects_only_when_all_full():
    """The fleet turns work away only when EVERY live replica's bounded
    queue rejected; the QueueFullError carries a positive retry estimate."""
    need_devices(2)
    fleet, gates = _gated_fleet(2, queue_depth=1)
    x = np.zeros((4,), np.float32)
    futs = []
    try:
        futs.append(fleet.submit(x, 0))  # in flight on 0
        assert gates[0].entered.wait(timeout=10)
        futs.append(fleet.submit(x, 0))  # in flight on 1
        assert gates[1].entered.wait(timeout=10)
        futs.append(fleet.submit(x, 0))  # queued (depth 1) on one replica
        futs.append(fleet.submit(x, 0))  # queued on the other
        with pytest.raises(QueueFullError) as ei:
            fleet.submit(x, 0)  # every queue full -> fleet-level reject
        assert ei.value.retry_after_s > 0
        for g in gates.values():
            g.release.set()
        for f in futs:
            np.testing.assert_array_equal(f.result(timeout=10), x * 2.0)
    finally:
        for g in gates.values():
            g.release.set()
        fleet.close()


def test_fleet_submit_validation():
    need_devices(2)
    fleet, gates = _gated_fleet(2)
    try:
        with pytest.raises(ValueError, match="label"):
            fleet.submit(np.zeros((4,), np.float32))
        with pytest.raises(NoBucketError):
            fleet.submit(np.zeros((5,), np.float32), 0)  # before any queueing
    finally:
        for g in gates.values():
            g.release.set()
        fleet.close()


# -- oversize data-parallel dispatch ------------------------------------------


def test_oversize_pjit_bit_exact():
    """A 16-row batch on a 4-replica fleet (bucket cap 2) dispatches
    data-parallel over the fleet mesh and must come back BIT-identical to
    the same jitted entry run unsharded on the same rows."""
    need_devices(4)

    def impl(xs, ys):
        return xs * 2.0 + ys[:, None]

    fleet = FleetServer(
        lambda rid, m: jax.jit(impl),
        [(4,)],
        replicas=4,
        max_batch=2,
        warmup=False,
        oversize="pjit",
    )
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((16, 4)).astype(np.float32)
    ys = np.arange(16, dtype=np.int32)
    try:
        got = fleet.attribute_batch(xs, ys)
    finally:
        fleet.close()
    ref = np.asarray(jax.jit(impl)(xs, ys))
    np.testing.assert_array_equal(got, ref)  # bit-exact, not allclose
    assert fleet.metrics.oversize.completed == 16
    assert fleet.metrics.oversize.batch_rows  # the oversize ledger saw it


def test_oversize_partial_chunk_and_fanout_small_batch():
    """Oversize rows that don't fill the fleet-wide batch are replicate-
    padded (and sliced off); a batch within one chip's cap takes the plain
    routed per-item path, not the pjit one."""
    need_devices(2)

    def impl(xs, ys):
        return xs * 3.0

    fleet = FleetServer(
        lambda rid, m: jax.jit(impl),
        [(4,)],
        replicas=2,
        max_batch=2,
        max_wait_ms=0.0,
        warmup=False,
        oversize="pjit",
    )
    rng = np.random.default_rng(1)
    try:
        # 7 rows, rows_per = 4: one full chunk + a 3-row replicate-padded one
        xs = rng.standard_normal((7, 4)).astype(np.float32)
        ys = np.zeros((7,), np.int32)
        np.testing.assert_array_equal(fleet.attribute_batch(xs, ys), xs * 3.0)
        assert fleet.metrics.oversize.completed == 7
        # 2 rows fit one chip: fan-out path, oversize ledger untouched
        small = rng.standard_normal((2, 4)).astype(np.float32)
        np.testing.assert_array_equal(
            fleet.attribute_batch(small, np.zeros((2,), np.int32)), small * 3.0
        )
        assert fleet.metrics.oversize.completed == 7
    finally:
        fleet.close()


# -- oversize-item sequence-sharded route -------------------------------------


def test_fleet_seq_sharded_route():
    """An ITEM shape no bucket admits resolves through the sequence-sharded
    route (instead of the historical NoBucketError): the result matches the
    same estimator on a single device, the warm second call runs with
    sentinel-verified ZERO compiles (the seq jits self-report, so the check
    is non-vacuous), and the dispatch lands a v2 ledger row on the shared
    oversize ledger."""
    need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.obs import sentinel as obs_sentinel
    from wam_tpu.parallel.mesh import make_mesh
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    model = toy_wave_model(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(7)
    est_kw = dict(ndim=1, wavelet="db2", level=2, mode="symmetric")
    sg_kw = dict(n_samples=2, stdev_spread=0.05)

    def seq_factory(mesh):
        sw = SeqShardedWam(mesh, model, **est_kw)
        return lambda xs, ys: sw.smoothgrad(
            jnp.asarray(xs), jnp.asarray(ys), key, **sg_kw)

    fleet = FleetServer(
        lambda rid, m: (lambda xs, ys: np.asarray(xs) * 2.0),
        [(64,)],
        replicas=8,
        max_batch=2,
        max_wait_ms=0.0,
        warmup=False,
        oversize="fanout",  # no pjit mesh up front: the seq route builds its own
        seq_factory=seq_factory,
    )
    xs = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (2, 2048)),
                    np.float32)
    ys = np.array([1, 3], np.int32)
    try:
        # per-item submit keeps the historical rejection (route is batch-level)
        with pytest.raises(NoBucketError):
            fleet.submit(xs[0], 1)
        traces_before = obs_sentinel.trace_count()
        warm = fleet.attribute_batch(xs, ys)
        assert obs_sentinel.trace_count() > traces_before  # jits self-reported
        seq_events = [e for e in obs_sentinel.compile_events()
                      if e["phase"] == "seq_sharded"]
        assert seq_events and all(e["replica"] == "fleet" for e in seq_events)
        with obs_sentinel.assert_no_retrace():  # warm path: zero compiles
            got = fleet.attribute_batch(xs, ys)
        assert fleet.metrics.oversize.completed == 4  # 2 items x 2 calls
        assert "2048" in fleet.metrics.oversize.ema_service_s()
        assert fleet.describe()["seq_route"] is True
    finally:
        fleet.close()

    ref_mesh = make_mesh({"data": 1}, jax.devices()[:1])
    ref = SeqShardedWam(ref_mesh, model, **est_kw).smoothgrad(
        jnp.asarray(xs), jnp.asarray(ys), key, **sg_kw)
    for g, w in zip(got, jax.device_get(ref)):
        np.testing.assert_allclose(g, np.asarray(w), atol=1e-5)
    for g, w in zip(got, warm):
        np.testing.assert_array_equal(g, w)  # route is deterministic


def test_fleet_no_seq_factory_keeps_rejecting():
    need_devices(2)
    fleet, gates = _gated_fleet(2)
    try:
        assert fleet.describe()["seq_route"] is False
        with pytest.raises(NoBucketError):
            fleet.attribute_batch(np.zeros((2, 4096), np.float32),
                                  np.zeros((2,), np.int32))
    finally:
        for g in gates.values():
            g.release.set()
        fleet.close()


# -- replica death ------------------------------------------------------------


def test_replica_death_routes_to_survivors():
    """A replica whose entry raises a non-ServeError is marked dead and its
    requests (the failed one and everything queued behind it) re-route to
    the survivors; the death lands in the fleet ledger."""
    need_devices(2)

    def make_entry(rid, m):
        if rid == 0:
            def dying(xs, ys):
                raise RuntimeError("chip 0 gone")

            return dying
        return lambda xs, ys: np.asarray(xs) * 2.0

    fleet = FleetServer(
        make_entry,
        [(4,)],
        replicas=2,
        max_batch=1,
        max_wait_ms=0.0,
        warmup=False,
        oversize="fanout",
    )
    x = np.ones((4,), np.float32)
    try:
        # both idle -> tie-break routes to replica 0, whose entry dies
        futs = [fleet.submit(x, 0) for _ in range(4)]
        for f in futs:
            np.testing.assert_array_equal(f.result(timeout=10), x * 2.0)
        assert [r.rid for r in fleet._replicas if not r.alive] == [0]
        deaths = fleet.metrics.fleet_summary()["deaths"]
        assert [d["replica_id"] for d in deaths] == [0]
        # post-death traffic goes straight to the survivor
        np.testing.assert_array_equal(fleet.attribute(x, 1), x * 2.0)
        # ... and oversize batches degrade to routed fan-out, still correct
        xs = np.stack([x] * 3)
        np.testing.assert_array_equal(
            fleet.attribute_batch(xs, np.zeros((3,), np.int32)), xs * 2.0
        )
    finally:
        fleet.close()


# -- compile invariant --------------------------------------------------------


def test_fleet_compiles_once_per_bucket_per_replica():
    """Each replica owns its own jitted entry: warmup compiles every bucket
    on every replica exactly once, and the mixed-shape hot path adds zero
    compiles (fleet_summary.compile_count == buckets × replicas)."""
    need_devices(2)
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.wam2d import BaseWAM2D

    toy = toy_conv_model(jax.random.PRNGKey(0), ndim=2)
    wam = BaseWAM2D(lambda x: toy(x.mean(axis=1)), J=2)
    shapes = [(1, 8, 8), (1, 16, 16)]
    fleet = FleetServer(
        lambda rid, m: wam.serve_entry(on_trace=m.note_compile),
        shapes,
        replicas=2,
        max_batch=2,
        warmup=True,
        oversize="fanout",
    )
    try:
        for rep in fleet._replicas:
            assert rep.metrics.compile_count == len(shapes)
            assert set(rep.metrics.warmup_s) == {bucket_key(s) for s in shapes}
        stream = [(1, 8, 8), (1, 16, 16), (1, 6, 6), (1, 12, 12), (1, 8, 8)]
        for i, shape in enumerate(stream):
            x = np.asarray(jax.random.normal(jax.random.PRNGKey(i), shape))
            out = fleet.attribute(x, i % 4)
            assert out.shape[-1] == out.shape[-2]  # a mosaic came back
        summary = fleet.metrics.fleet_summary()
        assert summary["compile_count"] == len(shapes) * 2  # zero hot-path
        assert summary["completed"] == len(stream)
    finally:
        fleet.close()


# -- ledger schema ------------------------------------------------------------


def test_fleet_ledger_schema(tmp_path):
    need_devices(2)
    path = str(tmp_path / "fleet.jsonl")

    def impl(xs, ys):
        return np.asarray(xs) * 1.0

    fleet = FleetServer(
        lambda rid, m: (jax.jit(lambda xs, ys: xs * 1.0) if rid == "fleet" else impl),
        [(4,)],
        replicas=2,
        max_batch=2,
        max_wait_ms=0.0,
        warmup=True,
        metrics_path=path,
        oversize="pjit",
    )
    for i in range(6):
        fleet.attribute(np.zeros((4,), np.float32), i % 4)
    fleet.attribute_batch(
        np.zeros((8, 4), np.float32), np.zeros((8,), np.int32)
    )  # oversize -> the "fleet" ledger
    fleet.close()  # drains + emits the merged ledger

    rows = [json.loads(line) for line in open(path)]
    batches = [r for r in rows if r["metric"] == "serve_batch"]
    summaries = [r for r in rows if r["metric"] == "serve_summary"]
    fleet_rows = [r for r in rows if r["metric"] == "fleet_summary"]
    assert len(fleet_rows) == 1
    assert all("replica_id" in r for r in batches)  # v2: identity on rows
    assert {r["replica_id"] for r in summaries} >= {0, 1, "fleet"}
    for s in summaries:
        assert s["schema_version"] == 2
        assert isinstance(s["ema_service_s"], dict)
        # v1 keys preserved verbatim for old JSONL consumers
        for key in ("completed", "batches", "latency_p50_ms", "attributions_per_s"):
            assert key in s
    per_replica = {str(r["replica_id"]) for r in fleet_rows[0]["per_replica"]}
    assert per_replica == {"0", "1"}
    assert fleet_rows[0]["oversize_completed"] == 8
    assert fleet_rows[0]["completed"] == 6 + 8
    assert all("utilization" in r for r in fleet_rows[0]["per_replica"])
    warm = [s for s in summaries if s["replica_id"] in (0, 1)]
    assert all(s["warmup_s"].get("4", 0.0) > 0.0 for s in warm)


# -- helpers ------------------------------------------------------------------


def test_fleet_aot_key_tagging():
    assert fleet_aot_key("m|3x224x224", 4) == "m|3x224x224|fleet4"
    assert fleet_aot_key("m|3x224x224", 1) == "m|3x224x224"  # single-chip: stable
    assert fleet_aot_key("m|3x224x224", None) == "m|3x224x224"
    assert fleet_aot_key(None, 8) is None


def test_per_bucket_ema_seed_and_update():
    """Satellite 1: the retry-after / routing EMA is per bucket — an unseen
    bucket reads the seed, an observed one its own blended history, and the
    snapshot exports the whole map."""
    from wam_tpu.serve.metrics import EMA_SEED_S

    m = ServeMetrics()
    assert m.ema_service_s((4,)) == EMA_SEED_S
    kw = dict(n_real=1, max_batch=1, pad_waste=0.0, queue_depth=0,
              queue_waits_s=[0.0], latencies_s=[0.2])
    m.note_batch(bucket_shape=(4,), service_s=0.2, **kw)
    assert m.ema_service_s((4,)) == pytest.approx(0.2)  # first obs seeds
    m.note_batch(bucket_shape=(4,), service_s=0.4, **kw)
    assert m.ema_service_s((4,)) == pytest.approx(0.8 * 0.2 + 0.2 * 0.4)
    assert m.ema_service_s((8,)) == EMA_SEED_S  # other buckets untouched
    snap = m.snapshot()
    assert snap["ema_service_s"] == {"4": pytest.approx(0.24)}
    assert snap["replica_id"] is None and snap["schema_version"] == 2


def test_fleet_metrics_replica_get_or_create():
    fm = FleetMetrics()
    a = fm.replica(0)
    assert fm.replica(0) is a and a.replica_id == 0
    fm.note_replica_death(0, "test")
    s = fm.fleet_summary()
    assert s["replicas"] == 1 and len(s["deaths"]) == 1
