"""Test harness configuration.

Runs the suite on a virtual 8-device CPU mesh — the JAX idiom for exercising
pjit/shard_map parallelism without TPU hardware (SURVEY.md §4e).

Note: this environment ships an `axon` TPU plugin that force-selects itself
via `jax.config.update("jax_platforms", ...)` at registration, so the
JAX_PLATFORMS env var alone is not enough — we must override the config knob
after importing jax, before any backend is initialized.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
