"""Test harness configuration.

Runs the suite on a virtual 8-device CPU mesh — the JAX idiom for exercising
pjit/shard_map parallelism without TPU hardware (SURVEY.md §4e).

Note: this environment ships an `axon` TPU plugin that force-selects itself
via `jax.config.update("jax_platforms", ...)` at registration, so the
JAX_PLATFORMS env var alone is not enough — we must override the config knob
after importing jax, before any backend is initialized.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Shard-local noise (parallel.seq_estimators) and the sharded-RNG HLO audits
# require the partitionable threefry lowering — the default on jax >= 0.5
# but off on the 0.4.x line. Flip it before any test draws a key so the
# whole suite sees ONE consistent RNG stream (the flag changes generated
# values; all in-suite comparisons are self-consistent under either state).
jax.config.update("jax_threefry_partitionable", True)


# -- shared test helpers ------------------------------------------------------

# jax < 0.6 ships shard_map only under jax.experimental, with the legacy
# check_rep machinery instead of check_vma (wam_tpu.compat papers over the
# API gap). Two test families assert properties of the MODERN stack and are
# gated on this flag rather than rewritten against legacy semantics:
#   - the sharded-DWT HLO audits: the old GSPMD partitioner inserts a
#     signal-sized all-gather the modern one does not (a compiler property,
#     not a property of our graphs);
#   - db6/reflect expansive-1D batch_axis parity: the legacy check_rep=False
#     transpose double-counts long-filter tail cotangents under batch
#     sharding (exact 2x), fixed by the check_vma rewrite.
LEGACY_SHARD_MAP = not hasattr(jax, "shard_map")


def need_modern_shard_map(what):
    """Skip on jax < 0.6 for tests asserting modern-partitioner properties."""
    import pytest

    if LEGACY_SHARD_MAP:
        pytest.skip(f"legacy (pre-jax.shard_map) stack: {what}")


def need_devices(n=8):
    """Skip unless the (virtual) device count is at least n."""
    import pytest

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def scan_gathers(hlo, gather_cap):
    """Offending all-gathers (sync or async-start, tuple-typed or plain)
    whose any result shape exceeds ``gather_cap`` elements — the shared
    scanner behind the sharded-DWT HLO audits (a signal-sized all-gather
    means sequence sharding silently degraded to replication)."""
    import re

    import numpy as np

    offenders = []
    for m in re.finditer(r"= (\([^)]*\)|\S+) all-gather(?:-start)?\(", hlo):
        for shape in re.finditer(r"\[([\d,]*)\]", m.group(1)):
            dims = [int(d) for d in shape.group(1).split(",") if d] or [1]
            if int(np.prod(dims)) > gather_cap:
                offenders.append(m.group(0)[:120])
    return offenders
