"""Test harness configuration.

Runs the suite on a virtual 8-device CPU mesh — the JAX idiom for exercising
pjit/shard_map parallelism without TPU hardware (SURVEY.md §4e).

Note: this environment ships an `axon` TPU plugin that force-selects itself
via `jax.config.update("jax_platforms", ...)` at registration, so the
JAX_PLATFORMS env var alone is not enough — we must override the config knob
after importing jax, before any backend is initialized.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# -- shared test helpers ------------------------------------------------------


def need_devices(n=8):
    """Skip unless the (virtual) device count is at least n."""
    import pytest

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def scan_gathers(hlo, gather_cap):
    """Offending all-gathers (sync or async-start, tuple-typed or plain)
    whose any result shape exceeds ``gather_cap`` elements — the shared
    scanner behind the sharded-DWT HLO audits (a signal-sized all-gather
    means sequence sharding silently degraded to replication)."""
    import re

    import numpy as np

    offenders = []
    for m in re.finditer(r"= (\([^)]*\)|\S+) all-gather(?:-start)?\(", hlo):
        for shape in re.finditer(r"\[([\d,]*)\]", m.group(1)):
            dims = [int(d) for d in shape.group(1).split(",") if d] or [1]
            if int(np.prod(dims)) > gather_cap:
                offenders.append(m.group(0)[:120])
    return offenders
