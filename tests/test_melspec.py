"""Differentiable melspec front-end tests: STFT frequency localization,
shapes, dB clamping, filterbank geometry, differentiability, approximate
invertibility (SURVEY.md §7.2 'differentiating through the melspec')."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from wam_tpu.ops.melspec import (
    amplitude_to_db,
    mel_filterbank,
    mel_to_stft_magnitude,
    melspectrogram,
    stft_power,
)


def test_stft_shape():
    x = jnp.zeros((2, 4096))
    p = stft_power(x, n_fft=256)
    # center padding: n_frames = 1 + L // hop
    assert p.shape == (2, 1 + 4096 // 128, 129)


def test_stft_sine_peak():
    """A pure tone must concentrate power at its FFT bin."""
    sr, n_fft = 8192, 256
    f = 32 * sr / n_fft  # exactly bin 32
    t = np.arange(sr) / sr
    x = jnp.asarray(np.sin(2 * np.pi * f * t), dtype=jnp.float32)[None]
    p = np.asarray(stft_power(x, n_fft=n_fft))[0]
    mid = p[p.shape[0] // 2]
    assert mid.argmax() == 32


def test_stft_matches_numpy_reference():
    """Cross-check one non-centered frame against a direct numpy rFFT."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal(512).astype(np.float32)
    p = np.asarray(stft_power(jnp.asarray(x)[None], n_fft=256, hop=128, center=False))[0]
    win = np.hanning(257)[:-1]
    for frame_i in range(3):
        seg = x[frame_i * 128 : frame_i * 128 + 256] * win
        expected = np.abs(np.fft.rfft(seg)) ** 2
        np.testing.assert_allclose(p[frame_i], expected, rtol=1e-4, atol=1e-4)


def test_mel_filterbank_geometry():
    fb = mel_filterbank(129, 32, 8000)
    assert fb.shape == (129, 32)
    assert np.all(fb >= 0)
    # every filter has some support and a single peak region
    assert np.all(fb.max(axis=0) > 0)


def test_amplitude_to_db_clamp():
    out = np.asarray(amplitude_to_db(jnp.array([0.0, 1.0, 100.0])))
    np.testing.assert_allclose(out, [-100.0, 0.0, 20.0], atol=1e-4)


@pytest.mark.slow
def test_melspectrogram_shape_and_grad():
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 2048)), dtype=jnp.float32)
    mel = melspectrogram(x, sample_rate=8000, n_fft=256, n_mels=32)
    assert mel.shape == (2, 1 + 2048 // 128, 32)

    g = jax.grad(lambda v: melspectrogram(v, 8000, 256, 32).sum())(x)
    assert g.shape == x.shape
    assert np.all(np.isfinite(np.asarray(g)))
    assert float(jnp.abs(g).max()) > 0


def test_mel_inversion_approximate():
    """pinv inversion recovers the coarse spectral shape of a tone."""
    sr, n_fft, n_mels = 8192, 512, 64
    t = np.arange(sr) / sr
    x = jnp.asarray(np.sin(2 * np.pi * 440 * t), dtype=jnp.float32)[None]
    mel = np.asarray(melspectrogram(x, sr, n_fft, n_mels, to_db=False))
    mag = mel_to_stft_magnitude(mel, sr, n_fft, n_mels)
    true_mag = np.sqrt(np.asarray(stft_power(x, n_fft=n_fft)))
    # peak bin of the reconstruction must be near the true peak
    got = mag[0, mag.shape[1] // 2].argmax()
    want = true_mag[0, true_mag.shape[1] // 2].argmax()
    assert abs(int(got) - int(want)) <= 2


def test_mel_inversion_nnls_beats_pinv_and_is_nonnegative():
    """VERDICT.md round-1 #8: NNLS inversion — residual no worse than the
    clipped-pinv start, strictly non-negative power."""
    from wam_tpu.ops.melspec import _nnls_projected_gradient, mel_filterbank

    sr, n_fft, n_mels = 8192, 512, 64
    t = np.arange(sr) / sr
    x = jnp.asarray(
        np.sin(2 * np.pi * 440 * t) + 0.3 * np.sin(2 * np.pi * 1500 * t), dtype=jnp.float32
    )[None]
    mel = np.asarray(melspectrogram(x, sr, n_fft, n_mels, to_db=False))
    fb = mel_filterbank(n_fft // 2 + 1, n_mels, sr)
    B = mel.reshape(-1, n_mels)
    x0 = np.clip(B @ np.linalg.pinv(fb), 0.0, None)
    nnls = _nnls_projected_gradient(fb, B, x0)
    assert np.all(nnls >= 0)
    r_pinv = float(np.square(x0 @ fb - B).sum())
    r_nnls = float(np.square(nnls @ fb - B).sum())
    assert r_nnls <= r_pinv * (1 + 1e-6)
    assert r_nnls < r_pinv * 0.9  # and it genuinely improves on this signal


def test_nnls_closed_form_small_case():
    """Exact solution recovered when it is feasible (x >= 0): A orthogonal
    columns, B generated from a known non-negative x."""
    from wam_tpu.ops.melspec import _nnls_projected_gradient

    rng = np.random.default_rng(3)
    A = np.abs(rng.standard_normal((5, 8))).astype(np.float64)
    x_true = np.abs(rng.standard_normal((4, 5)))
    B = x_true @ A
    x = _nnls_projected_gradient(A, B, np.zeros_like(x_true), iters=20000, tol=0.0)
    np.testing.assert_allclose(x @ A, B, atol=1e-5)


def test_stft_matmul_impl_matches_fft():
    """The windowed-DFT matmul backend must reproduce the rfft power
    spectrogram (same framing, window folded into the matrices) and be
    differentiable — round-4's +34% audio STFT path."""
    import wam_tpu.ops.melspec as ms

    x = jax.random.normal(jax.random.PRNGKey(5), (2, 8192))
    prev = ms.get_stft_impl()
    try:
        ms.set_stft_impl("fft")
        ref = ms.stft_power(x, n_fft=512)
        ms.set_stft_impl("matmul")
        got = ms.stft_power(x, n_fft=512)
        # CPU matmul default precision is f32-exact; tolerance covers
        # summation-order drift only
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-3, atol=1e-3)

        # gradients flow through the matmul form
        g = jax.grad(lambda t: ms.stft_power(t, n_fft=512).sum())(x)
        assert np.isfinite(np.asarray(g)).all() and float(jnp.abs(g).max()) > 0

        # melspec end to end, AND the non-divisible-hop gather framing
        # (hop=160 does not divide n_fft=512)
        for hop in (None, 160):
            ms.set_stft_impl("fft")
            mel_ref = ms.melspectrogram(x, sample_rate=16000, n_fft=512,
                                        n_mels=32, hop=hop)
            ms.set_stft_impl("matmul")
            mel_got = ms.melspectrogram(x, sample_rate=16000, n_fft=512,
                                        n_mels=32, hop=hop)
            np.testing.assert_allclose(np.asarray(mel_got), np.asarray(mel_ref),
                                       atol=0.05, err_msg=f"hop={hop}")  # dB
    finally:
        ms.set_stft_impl(prev)


def test_stft_impl_selector_validates():
    import wam_tpu.ops.melspec as ms

    with pytest.raises(ValueError):
        ms.set_stft_impl("dct")
    assert ms.get_stft_impl() in ("auto", "fft", "matmul")
