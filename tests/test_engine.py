"""Core engine + estimator tests: analytic gradients through the IDWT for a
linear model, y=None representation mode, SmoothGrad/IG semantics
(SURVEY.md §4b)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.core.engine import WamEngine, target_loss
from wam_tpu.core.estimators import integrated_path, noise_sigma, smoothgrad, trapezoid
from wam_tpu.wavelets import wavedec2

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



def _linear_model(W):
    """x (B,C,H,W) -> logits (B,K) via flattened matmul."""

    def fn(x):
        return x.reshape(x.shape[0], -1) @ W

    return fn


def test_target_loss_picks_diag():
    out = jnp.arange(12.0).reshape(3, 4)
    y = jnp.array([1, 0, 3])
    np.testing.assert_allclose(target_loss(out, y), (1.0 + 4.0 + 11.0) / 3.0)


def test_target_loss_none_is_mean():
    out = jnp.arange(6.0).reshape(2, 3)
    np.testing.assert_allclose(target_loss(out, None), out.mean())


def test_engine_linear_model_analytic():
    """For model(x) = <w, x>, the coefficient gradient must equal the DWT of
    the (reshaped) weight, because the adjoint of the orthogonal IDWT is the
    DWT."""
    rng = np.random.default_rng(0)
    B, C, H, Wd, K = 2, 1, 16, 16, 5
    W = jnp.asarray(rng.standard_normal((C * H * Wd, K)), dtype=jnp.float32)
    eng = WamEngine(_linear_model(W), ndim=2, wavelet="haar", level=2, mode="reflect")
    x = jnp.asarray(rng.standard_normal((B, C, H, Wd)), dtype=jnp.float32)
    y = jnp.array([3, 1])
    _, grads = eng.attribute(x, y)

    # grad for sample i = wavedec2(w_{y_i}) / B
    for i in range(B):
        w_img = W[:, int(y[i])].reshape(1, C, H, Wd)
        expected = wavedec2(w_img, "haar", 2, "reflect")
        got_flat = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda g: g[i : i + 1], grads)
        )
        exp_flat = jax.tree_util.tree_leaves(expected)
        for g, e in zip(got_flat, exp_flat):
            np.testing.assert_allclose(np.asarray(g), np.asarray(e) / B, atol=1e-5)


def test_engine_representation_mode():
    """y=None differentiates the output mean (lib/wam_3D.py:226-232)."""
    W = jnp.ones((16, 4), dtype=jnp.float32)
    eng = WamEngine(_linear_model(W), ndim=2, wavelet="haar", level=1, mode="zero")
    x = jnp.ones((1, 1, 4, 4))
    _, grads = eng.attribute(x, None)
    assert jax.tree_util.tree_leaves(grads)[0] is not None


def test_engine_1d_and_3d():
    rng = np.random.default_rng(1)
    W1 = jnp.asarray(rng.standard_normal((32, 3)), dtype=jnp.float32)
    eng1 = WamEngine(_linear_model(W1), ndim=1, wavelet="db2", level=2, mode="symmetric")
    x1 = jnp.asarray(rng.standard_normal((2, 1, 32)), dtype=jnp.float32)
    c1, g1 = eng1.attribute(x1, jnp.array([0, 2]))
    assert len(c1) == 3 and jax.tree_util.tree_leaves(g1)[0].shape == c1[0].shape

    W3 = jnp.asarray(rng.standard_normal((8 * 8 * 8, 2)), dtype=jnp.float32)
    eng3 = WamEngine(_linear_model(W3), ndim=3, wavelet="haar", level=1, mode="symmetric")
    x3 = jnp.asarray(rng.standard_normal((1, 1, 8, 8, 8)), dtype=jnp.float32)
    c3, g3 = eng3.attribute(x3, jnp.array([1]))
    assert set(c3[1].keys()) == {"aad", "ada", "add", "daa", "dad", "dda", "ddd"}
    assert g3[1]["ddd"].shape == c3[1]["ddd"].shape


def test_front_grads_tap():
    """Front-end gradient tap = the melspec retain_grad analogue."""
    W = jnp.asarray(np.random.default_rng(2).standard_normal((64, 3)), dtype=jnp.float32)

    def front(x):  # some differentiable front-end
        return jnp.tanh(x) * 2.0

    eng = WamEngine(
        _linear_model(W), ndim=1, wavelet="haar", level=1, mode="zero", front_fn=front
    )
    x = jnp.asarray(np.random.default_rng(3).standard_normal((1, 1, 64)), dtype=jnp.float32)
    coeffs, g_coeffs, g_front = eng.attribute_with_front_grads(x, jnp.array([0]))
    assert g_front.shape == (1, 1, 64)
    # front grad = W[:, y] reshaped (linear model): d loss / d front = W col
    np.testing.assert_allclose(
        np.asarray(g_front).ravel(), np.asarray(W[:, 0]).ravel(), atol=1e-5
    )


def test_noise_sigma_per_image():
    x = jnp.stack([jnp.zeros((1, 4, 4)), jnp.ones((1, 4, 4)) * 2.0])
    x = x.at[1, 0, 0, 0].set(0.0)
    s = noise_sigma(x, 0.5)
    np.testing.assert_allclose(s, [0.0, 1.0])


def test_smoothgrad_zero_noise_equals_step():
    x = jnp.asarray(np.random.default_rng(4).standard_normal((2, 1, 8, 8)), dtype=jnp.float32)
    step = lambda v: v * 2.0
    out = smoothgrad(step, x, jax.random.PRNGKey(0), n_samples=4, stdev_spread=0.0)
    np.testing.assert_allclose(out, x * 2.0, atol=1e-6)


def test_smoothgrad_reduces_variance_and_is_deterministic():
    x = jnp.ones((1, 1, 8, 8))
    step = lambda v: v
    a = smoothgrad(step, x, jax.random.PRNGKey(7), n_samples=50, stdev_spread=0.3)
    b = smoothgrad(step, x, jax.random.PRNGKey(7), n_samples=50, stdev_spread=0.3)
    np.testing.assert_allclose(a, b)  # same key -> same result
    # mean of x + noise ≈ x
    assert float(jnp.abs(a - x).mean()) < 0.2


def test_trapezoid_matches_numpy():
    rng = np.random.default_rng(5)
    path = rng.standard_normal((7, 3, 4)).astype(np.float32)
    got = trapezoid(jnp.asarray(path))
    expected = np.trapezoid(path, axis=0) if hasattr(np, "trapezoid") else np.trapz(path, axis=0)
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_integrated_path_linear_grad():
    """For grad_fn(c) = c (identity), the path integral of α·c over α∈[0,1]
    with dx=1 equals c · (n-1)/2 · dα-free trapz = c · (n-1)/2."""
    c = {"a": jnp.ones((2, 2))}
    n = 5
    out = integrated_path(lambda cs: cs["a"], c, n_steps=n)
    # trapz of α over linspace(0,1,5) with dx=1: mean-ish = (0+.25+.5+.75+1) with ends halved = 2.0
    np.testing.assert_allclose(out, np.full((2, 2), 2.0), atol=1e-6)


def test_smoothgrad_streaming_noise_semantics():
    """materialize_noise=False: deterministic per key, exact mean-of-steps
    at zero noise, and the same ESTIMATOR (different, equally valid draws)
    as the materialized path — means converge with n_samples."""
    x = jnp.asarray(np.random.default_rng(6).standard_normal((2, 1, 8, 8)), dtype=jnp.float32)
    step = lambda v: v * 3.0
    # zero noise: identical to the materialized path and to step(x)
    out0 = smoothgrad(step, x, jax.random.PRNGKey(0), n_samples=4,
                      stdev_spread=0.0, materialize_noise=False)
    np.testing.assert_allclose(out0, x * 3.0, atol=1e-6)
    # deterministic per key; different stream than materialized
    a = smoothgrad(step, x, jax.random.PRNGKey(7), n_samples=32,
                   stdev_spread=0.3, materialize_noise=False)
    b = smoothgrad(step, x, jax.random.PRNGKey(7), n_samples=32,
                   stdev_spread=0.3, materialize_noise=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    m = smoothgrad(step, x, jax.random.PRNGKey(7), n_samples=32,
                   stdev_spread=0.3)
    # linear step: both estimators are unbiased around 3x — their difference
    # is 3·(mean of 2·32 indep draws · σ); bound at 6 joint std devs
    sig = float(noise_sigma(x, 0.3).max())
    bound = 6.0 * 3.0 * sig * np.sqrt(2.0 / 32.0)
    assert float(jnp.abs(a - m).max()) < bound
    assert float(jnp.abs(a - m).max()) > 0.0  # genuinely different stream
    # chunked streaming == unchunked streaming (same draws, same mean)
    c = smoothgrad(step, x, jax.random.PRNGKey(7), n_samples=32,
                   stdev_spread=0.3, batch_size=4, materialize_noise=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=1e-6)
