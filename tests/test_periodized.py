"""Periodized DWT + sequence-sharded halo-exchange tests (long-context
path, SURVEY.md §5.7): orthogonality, exact adjoint inverse, bit-parity of
the sharded transform with the single-device one on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.parallel import make_mesh
from wam_tpu.parallel.halo import sharded_dwt_per, sharded_wavedec_per
from wam_tpu.wavelets.periodized import dwt_per, idwt_per, wavedec_per, waverec_per

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



@pytest.mark.parametrize("wavelet", ["haar", "db2", "db4", "sym4"])
def test_periodized_roundtrip_and_energy(wavelet):
    x = jnp.asarray(np.random.default_rng(0).standard_normal((3, 64)), dtype=jnp.float32)
    cA, cD = dwt_per(x, wavelet)
    assert cA.shape == (3, 32) and cD.shape == (3, 32)
    # exact orthogonality: energy preserved
    e_in = float((x**2).sum())
    e_out = float((cA**2).sum() + (cD**2).sum())
    np.testing.assert_allclose(e_out, e_in, rtol=1e-5)
    rec = idwt_per(cA, cD, wavelet)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=1e-5)


def test_periodized_haar_values():
    x = jnp.array([[1.0, 2.0, 3.0, 4.0]])
    cA, cD = dwt_per(x, "haar")
    s2 = np.sqrt(2.0)
    np.testing.assert_allclose(cA[0], [3 / s2, 7 / s2], atol=1e-6)
    np.testing.assert_allclose(cD[0], [-1 / s2, -1 / s2], atol=1e-6)


@pytest.mark.parametrize("level", [1, 2, 3])
def test_periodized_multilevel_roundtrip(level):
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 128)), dtype=jnp.float32)
    coeffs = wavedec_per(x, "db3", level)
    rec = waverec_per(coeffs, "db3")
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=1e-4)


def test_periodized_odd_length_raises():
    with pytest.raises(ValueError):
        dwt_per(jnp.zeros((1, 7)), "haar")


@pytest.mark.parametrize("wavelet", ["haar", "db2", "db4"])
def test_sharded_dwt_matches_single_device(wavelet):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 8})
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 256)), dtype=jnp.float32)
    run = sharded_dwt_per(mesh, wavelet, seq_axis="data")
    cA_s, cD_s = run(x)
    cA, cD = dwt_per(x, wavelet)
    np.testing.assert_allclose(np.asarray(cA_s), np.asarray(cA), atol=1e-5)
    np.testing.assert_allclose(np.asarray(cD_s), np.asarray(cD), atol=1e-5)


def test_sharded_multilevel_matches_single_device():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 8})
    x = jnp.asarray(np.random.default_rng(3).standard_normal((1, 512)), dtype=jnp.float32)
    run = sharded_wavedec_per(mesh, "db2", level=3, seq_axis="data")
    sharded = run(x)
    single = wavedec_per(x, "db2", 3)
    assert len(sharded) == len(single)
    for s, d in zip(sharded, single):
        np.testing.assert_allclose(np.asarray(s), np.asarray(d), atol=1e-5)


def test_sharded_contains_collective():
    """The lowered HLO must contain a collective-permute (the halo ride)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 8})
    run = sharded_dwt_per(mesh, "db4", seq_axis="data")
    x = jnp.zeros((1, 256))
    hlo = jax.jit(run).lower(x).compile().as_text()
    assert "collective-permute" in hlo


@pytest.mark.parametrize("wavelet", ["haar", "db3", "sym4"])
def test_wavedec2_per_roundtrip(wavelet):
    from wam_tpu.wavelets.periodized import wavedec2_per, waverec2_per

    x = jax.random.normal(jax.random.PRNGKey(5), (2, 32, 64))
    coeffs = wavedec2_per(x, wavelet, 3)
    rec = waverec2_per(coeffs, wavelet)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=1e-5)


def test_dwt2_per_energy_preservation():
    from wam_tpu.wavelets.periodized import dwt2_per

    x = jax.random.normal(jax.random.PRNGKey(6), (1, 16, 16))
    cA, det = dwt2_per(x, "db2")
    e_in = float(jnp.sum(x**2))
    e_out = float(
        jnp.sum(cA**2)
        + jnp.sum(det.horizontal**2)
        + jnp.sum(det.vertical**2)
        + jnp.sum(det.diagonal**2)
    )
    assert abs(e_in - e_out) < 1e-4 * e_in


def test_dwt2_per_directional_subband_mapping():
    """A signal oscillating only along W must put its detail energy in the
    'vertical' (a-along-H, d-along-W) subband — pins the letter-axis map."""
    from wam_tpu.wavelets.periodized import dwt2_per

    w = jnp.tile(jnp.array([1.0, -1.0] * 8), (16, 1))  # (H=16, W=16), varies in W only
    cA, det = dwt2_per(w[None], "haar")
    e = {k: float(jnp.sum(getattr(det, k) ** 2)) for k in ("horizontal", "vertical", "diagonal")}
    assert e["vertical"] > 1.0
    assert e["horizontal"] < 1e-8 and e["diagonal"] < 1e-8


def test_dwt3_per_directional_subband_mapping():
    """Oscillation only along W → all detail energy in 'aad' (a-D, a-H, d-W);
    only along D → 'daa'. Pins D,H,W letter order against transform.dwt3."""
    from wam_tpu.wavelets.periodized import dwt3_per

    osc = jnp.array([1.0, -1.0] * 4)
    vol_w = jnp.broadcast_to(osc, (8, 8, 8))  # varies along W only
    _, det = dwt3_per(vol_w[None], "haar")
    for k, v in det.items():
        e = float(jnp.sum(v**2))
        assert (e > 1.0) == (k == "aad"), (k, e)

    vol_d = jnp.broadcast_to(osc[:, None, None], (8, 8, 8))  # varies along D only
    _, det = dwt3_per(vol_d[None], "haar")
    for k, v in det.items():
        e = float(jnp.sum(v**2))
        assert (e > 1.0) == (k == "daa"), (k, e)
