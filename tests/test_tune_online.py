"""Online schedule learning (`wam_tpu/tune/mix.py`, `wam_tpu/tune/online.py`)
plus its serving hooks: ledger mining under torn lines, the two-sided drift
alarm (fires on a shifted mix, quiet on the unshifted control — the round-19
acceptance pin), the mix-synthesized ``wamlive`` preset's determinism, the
pure canary verdict, `plan_serve_schedule` grow/shrink with replica-count
keying, the `OnlineTuner` kill switch, fingerprint stamping on ``serve_batch``
rows, `FleetServer.pin_canary` routing + report, the autoscaler's cache-hit
drain discount, and promote → bundle → hydrate reproducibility.

Mining/drift/verdict tests are pure (synthetic rows, no fleet, no clocks
beyond row timestamps); the fleet tests use gated fake entries per
tests/test_fleet.py discipline."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from conftest import need_devices
from wam_tpu.results import JsonlWriter, LedgerCorruptWarning
from wam_tpu.serve import FleetServer, ServeMetrics
from wam_tpu.tune.cache import (
    entries_fingerprint,
    invalidate_process_cache,
    load_schedule_cache,
    resolve_bucket_cap,
    schedule_fingerprint,
    schedule_key,
)
from wam_tpu.tune.mix import (
    DEFAULT_DRIFT_THRESHOLD,
    drift_report,
    mine_ledger,
    mine_rows,
)
from wam_tpu.tune.online import (
    ONLINE_TUNE_ENV,
    OnlineTuneConfig,
    OnlineTuner,
    canary_verdict,
    plan_serve_schedule,
)


@pytest.fixture
def sched_cache(tmp_path, monkeypatch):
    """Isolated user-layer schedule cache (same fixture as test_tune.py)."""
    path = tmp_path / "schedules.json"
    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE", str(path))
    monkeypatch.delenv("WAM_TPU_NO_SCHEDULE_CACHE", raising=False)
    monkeypatch.delenv(ONLINE_TUNE_ENV, raising=False)
    invalidate_process_cache()
    yield path
    invalidate_process_cache()


def _row(ts, n_real=4, service_s=0.054, shape=(1, 16, 16), max_batch=4,
         queue_depth=0, qos=None, fp=None):
    r = {
        "metric": "serve_batch",
        "bucket": list(shape),
        "n_real": n_real,
        "fill_ratio": n_real / max_batch,
        "occupancy": n_real / max_batch,
        "pad_waste": 0.0,
        "queue_depth": queue_depth,
        "service_s": service_s,
        "timestamp": ts,
    }
    if qos:
        r["qos"] = qos
    if fp:
        r["schedule_fingerprint"] = fp
    return r


def _shifted_rows(n_light=30, n_heavy=10, t0=1000.0):
    """A light-era run (1-row batches, 4 ms/item) that re-skews heavy
    (full 4-row batches, 13.5 ms/item, standing queue) — the same shape as
    the bench's --mix-shift trace."""
    rows = [_row(t0 + i, n_real=1, service_s=0.004, queue_depth=0)
            for i in range(n_light)]
    rows += [_row(t0 + n_light + i, n_real=4, service_s=0.054,
                  queue_depth=8) for i in range(n_heavy)]
    return rows


# -- ledger mining ------------------------------------------------------------


def test_mine_rows_histograms_single_bucket():
    rows = [
        _row(1.0, n_real=2, service_s=0.02, qos={"interactive": 1, "batch": 1},
             fp="aaaa"),
        _row(2.0, n_real=4, service_s=0.04, qos={"batch": 4}, fp="aaaa"),
        _row(3.0, n_real=4, service_s=0.04, qos={"batch": 4}, fp="bbbb"),
    ]
    mix = mine_rows(rows)
    assert mix.rows == 3 and mix.corrupt_lines == 0
    assert mix.window == (1.0, 3.0)
    assert set(mix.buckets) == {"1x16x16"}
    b = mix.buckets["1x16x16"]
    assert b.batches == 3 and b.items == 10
    assert b.mean_batch == pytest.approx(10 / 3)
    assert b.mean_per_item_s == pytest.approx(0.01)
    assert b.qos == {"interactive": 1, "batch": 9}
    assert mix.qos == {"interactive": 1, "batch": 9}
    assert mix.fingerprints == {"aaaa": 2, "bbbb": 1}
    assert mix.weights() == {"1x16x16": 1.0}
    # to_dict is the JSON body the tuner reports — must round-trip json
    assert json.loads(json.dumps(mix.to_dict()))["total_items"] == 10


def test_mine_rows_skips_foreign_and_incomplete_rows():
    rows = [
        {"metric": "serve_summary", "timestamp": 1.0},
        {"metric": "serve_batch", "timestamp": 2.0},  # no n_real
        {"metric": "serve_batch", "n_real": 3},  # no timestamp
        _row(5.0),
    ]
    mix = mine_rows(rows)
    assert mix.rows == 1
    assert mine_rows([{"metric": "serve_summary"}]) is None
    assert mine_rows([]) is None


def test_mine_rows_window_anchored_at_latest_row():
    rows = [_row(float(t)) for t in (0.0, 50.0, 95.0, 100.0)]
    mix = mine_rows(rows, window_s=10.0)
    # the window is the ledger's own clock: [latest - 10, latest]
    assert mix.rows == 2 and mix.window == (95.0, 100.0)


def test_mine_ledger_tolerates_torn_lines(tmp_path):
    path = tmp_path / "serve.jsonl"
    w = JsonlWriter(str(path))
    for r in (_row(1.0), _row(2.0)):
        w.write(r)
    with open(path, "a") as f:
        f.write('{"metric": "serve_batch", "n_real": 4, "torn...\n')
    with pytest.warns(LedgerCorruptWarning):
        mix = mine_ledger(str(path))
    assert mix.rows == 2 and mix.corrupt_lines == 1


def test_mine_ledger_missing_or_empty(tmp_path):
    assert mine_ledger(str(tmp_path / "absent.jsonl")) is None
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert mine_ledger(str(empty)) is None


# -- drift detection ----------------------------------------------------------


def test_drift_fires_on_shift_quiet_on_control():
    """The acceptance pin: the same detector that alarms on the re-skewed
    window must stay quiet on the unshifted prefix of the SAME ledger."""
    rows = _shifted_rows()
    shifted = drift_report(mine_rows(rows))
    assert shifted["drifted"] == ["1x16x16"]
    b = shifted["buckets"]["1x16x16"]
    assert b["source"] == "self" and b["ratio"] > DEFAULT_DRIFT_THRESHOLD
    assert shifted["worst_ratio"] == b["ratio"]
    control = drift_report(mine_rows(rows[:30]))
    assert control["drifted"] == []
    assert control["buckets"]["1x16x16"]["ratio"] == pytest.approx(1.0)


def test_drift_is_two_sided():
    # heavy era first, then light: observed/baseline < 1/threshold is
    # drift too (the schedule now over-provisions)
    rows = [_row(float(i), n_real=4, service_s=0.054) for i in range(30)]
    rows += [_row(30.0 + i, n_real=1, service_s=0.004) for i in range(10)]
    rep = drift_report(mine_rows(rows))
    assert rep["drifted"] == ["1x16x16"]
    assert rep["buckets"]["1x16x16"]["ratio"] < 1.0 / DEFAULT_DRIFT_THRESHOLD


def test_drift_needs_min_batches():
    rep = drift_report(mine_rows(_shifted_rows(n_light=3, n_heavy=2)),
                       min_batches=6)
    b = rep["buckets"]["1x16x16"]
    assert rep["drifted"] == [] and b["source"] == "insufficient"
    assert b["ratio"] == 1.0


def test_drift_against_tuned_prediction():
    rows = [_row(float(i), n_real=4, service_s=0.054) for i in range(12)]
    rep = drift_report(mine_rows(rows),
                       predictions={"1x16x16": 0.0045})
    b = rep["buckets"]["1x16x16"]
    assert b["source"] == "tuned"
    assert b["baseline_s"] == pytest.approx(0.0045)
    assert b["ratio"] == pytest.approx(0.0135 / 0.0045)
    assert rep["drifted"] == ["1x16x16"]


def test_drift_threshold_must_exceed_one():
    with pytest.raises(ValueError):
        drift_report(mine_rows([_row(1.0)]), threshold=1.0)


# -- wamlive preset -----------------------------------------------------------


def test_wamlive_requires_mix():
    from wam_tpu.tune.workloads import get_workload

    with pytest.raises(ValueError, match="mix"):
        get_workload("wamlive")


def test_wamlive_preset_deterministic_for_a_mix():
    """The same mix must build the same sweep: candidate list, observed
    geometry, and the runner's actual numerics (rank-keyed PRNG draws, no
    wall-clock or global state in the body)."""
    import jax

    from wam_tpu.tune.workloads import get_workload

    mix = mine_rows(_shifted_rows())
    a = get_workload("wamlive", mix=mix, n_samples=2)
    b = get_workload("wamlive", mix=mix, n_samples=2)
    assert a.shape == b.shape == (16, 16)
    assert a.batch == b.batch
    assert a.items == b.items
    assert [(c.sample_chunk, c.stream_noise) for c in a.candidates] == \
           [(c.sample_chunk, c.stream_noise) for c in b.candidates]
    run_a, args_a = a.build(a.candidates[0])
    run_b, args_b = b.build(b.candidates[0])
    out_a = jax.block_until_ready(run_a(*args_a))
    out_b = jax.block_until_ready(run_b(*args_b))
    assert float(out_a) == float(out_b)


# -- canary verdict (pure) ----------------------------------------------------


def test_canary_verdict_insufficient_then_win_then_hold():
    champ = [_row(10.0 + i, n_real=4, service_s=0.054, fp="champ")
             for i in range(8)]
    chall = [_row(10.0 + i, n_real=8, service_s=0.07, fp="chall")
             for i in range(8)]
    few = canary_verdict(champ + chall[:3], "champ", "chall")
    assert few["verdict"] == "insufficient" and not few["win"]
    win = canary_verdict(champ + chall, "champ", "chall")
    assert win["verdict"] == "challenger" and win["win"]
    # 13.5 ms/item -> 8.75 ms/item
    assert win["improvement"] == pytest.approx(1 - 0.00875 / 0.0135)
    # a challenger inside the margin holds the champion
    near = [_row(10.0 + i, n_real=4, service_s=0.053, fp="chall")
            for i in range(8)]
    hold = canary_verdict(champ + near, "champ", "chall", margin=0.05)
    assert hold["verdict"] == "champion" and not hold["win"]


def test_canary_verdict_since_drops_prewindow_champion_history():
    # light-era champion history before the window opened would let the
    # champion coast; ``since`` must exclude it
    old = [_row(float(i), n_real=4, service_s=0.004, fp="champ")
           for i in range(20)]
    champ = [_row(100.0 + i, n_real=4, service_s=0.054, fp="champ")
             for i in range(8)]
    chall = [_row(100.0 + i, n_real=8, service_s=0.07, fp="chall")
             for i in range(8)]
    without = canary_verdict(old + champ + chall, "champ", "chall")
    assert not without["win"]  # polluted champion mean looks unbeatable
    windowed = canary_verdict(old + champ + chall, "champ", "chall",
                              since=100.0)
    assert windowed["win"] and windowed["champion_batches"] == 8


# -- serve-plane planning -----------------------------------------------------


def test_plan_serve_schedule_grow_shrink_hold():
    hot = mine_rows([_row(float(i), n_real=4, max_batch=4, queue_depth=6)
                     for i in range(10)])
    plan = plan_serve_schedule(hot, current_cap=4, max_cap=16, replicas=2)
    shape, replicas, entry = plan["1x16x16"]
    assert shape == (1, 16, 16) and replicas == 2
    assert entry["bucket_cap"] == 8  # saturated + queued -> double
    cold = mine_rows([_row(float(i), n_real=1, max_batch=8, queue_depth=0)
                      for i in range(10)])
    plan = plan_serve_schedule(cold, current_cap=16, default_cap=4)
    assert plan["1x16x16"][2]["bucket_cap"] == 8  # occ < 0.35 -> halve
    warm = mine_rows([_row(float(i), n_real=3, max_batch=4, queue_depth=0)
                      for i in range(10)])
    plan = plan_serve_schedule(warm, current_cap=4)
    assert plan["1x16x16"][2]["bucket_cap"] == 4  # in between holds
    # growth respects the ceiling
    plan = plan_serve_schedule(hot, current_cap=12, max_cap=16)
    assert plan["1x16x16"][2]["bucket_cap"] == 16


def test_plan_keys_by_replica_count(sched_cache):
    """The promoted cap must be found by the width that tuned it: a
    2-replica entry steers 2-replica resolution only."""
    mix = mine_rows([_row(float(i), n_real=4, max_batch=4, queue_depth=6)
                     for i in range(10)])
    plan = plan_serve_schedule(mix, current_cap=4, replicas=2)
    shape, replicas, entry = plan["1x16x16"]
    cache = load_schedule_cache()
    cache.put(schedule_key("serve", shape, replicas), entry)
    cache.save()
    invalidate_process_cache()
    assert resolve_bucket_cap("auto", shape, replicas=2, default=4) == 8
    assert resolve_bucket_cap("auto", shape, replicas=1, default=4) == 4


# -- OnlineTuner --------------------------------------------------------------


def test_online_tuner_kill_switch(tmp_path, monkeypatch):
    ledger = tmp_path / "serve.jsonl"
    w = JsonlWriter(str(ledger))
    for r in _shifted_rows():
        w.write(r)
    out = tmp_path / "rows.jsonl"
    monkeypatch.setenv(ONLINE_TUNE_ENV, "1")
    tuner = OnlineTuner(OnlineTuneConfig(ledger=str(ledger),
                                         out_ledger=str(out)))
    assert tuner.step() == {"disabled": True}
    assert not out.exists()


def test_detect_drift_writes_schedule_drift_rows(tmp_path, sched_cache):
    from wam_tpu.serve.metrics import SCHEMA_VERSION

    ledger = tmp_path / "serve.jsonl"
    out = tmp_path / "rows.jsonl"
    w = JsonlWriter(str(ledger))
    for r in _shifted_rows():
        w.write(r)
    tuner = OnlineTuner(OnlineTuneConfig(ledger=str(ledger),
                                         out_ledger=str(out)))
    mix = tuner.mine()
    report = tuner.detect_drift(mix)
    assert report["drifted"] == ["1x16x16"]
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(rows) == 1
    row = rows[0]
    assert row["metric"] == "schedule_drift"
    assert row["schema_version"] == SCHEMA_VERSION
    assert row["bucket"] == "1x16x16"
    assert row["ratio"] > DEFAULT_DRIFT_THRESHOLD
    assert row["baseline_source"] == "self"
    # quiet mix -> no new rows
    tuner.detect_drift(mine_rows(_shifted_rows(n_light=30, n_heavy=0)))
    assert len(out.read_text().splitlines()) == 1


def test_promote_installs_publishes_and_hydrates(tmp_path, sched_cache,
                                                 monkeypatch):
    """Promotion end state is reproducible from the bundle ALONE: a fresh
    schedule cache hydrated from the published bundle resolves the promoted
    cap under the promoted fingerprint (the round-19 acceptance repro)."""
    from wam_tpu.registry import RegistryClient
    from wam_tpu.serve.metrics import SCHEMA_VERSION

    shape = (1, 16, 16)
    skey = schedule_key("serve", shape, 2)
    entry = {"bucket_cap": 8, "source": "online:plan_serve_schedule"}
    merged = dict(load_schedule_cache().entries)
    merged[skey] = entry
    challenger = {"entries": {skey: entry}, "keys": [skey],
                  "fingerprint": entries_fingerprint(merged)}
    out = tmp_path / "rows.jsonl"
    bundle_dir = tmp_path / "bundle"
    tuner = OnlineTuner(OnlineTuneConfig(
        ledger=str(tmp_path / "unused.jsonl"), out_ledger=str(out),
        replicas=2, bundle_dir=str(bundle_dir), bundle_aot_keys=[]))
    verdict = {"verdict": "challenger", "win": True, "improvement": 0.35,
               "champion_fp": "champ", "champion_batches": 9,
               "challenger_batches": 9}
    promoted = tuner.promote(challenger, verdict)
    # installed live: the serve path resolves the promoted cap
    assert resolve_bucket_cap("auto", shape, replicas=2, default=4) == 8
    assert promoted["live_fingerprint"] == challenger["fingerprint"]
    assert promoted["bundle"]["artifacts"] == 0  # schedules-only
    row = json.loads(out.read_text().splitlines()[-1])
    assert row["metric"] == "schedule_promotion"
    assert row["schema_version"] == SCHEMA_VERSION
    assert row["challenger_fp"] == challenger["fingerprint"]
    assert row["live_fp"] == promoted["live_fingerprint"]
    assert row["keys"] == [skey] and row["improvement"] == 0.35
    # fresh cache + bundle alone == the promoted table
    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE",
                       str(tmp_path / "hydrated.json"))
    invalidate_process_cache()
    assert resolve_bucket_cap("auto", shape, replicas=2, default=4) == 4
    report = RegistryClient(str(bundle_dir)).hydrate()
    assert report.schedules_added >= 1
    assert resolve_bucket_cap("auto", shape, replicas=2, default=4) == 8
    assert schedule_fingerprint() == promoted["live_fingerprint"]


def test_online_cli_once(tmp_path):
    """--once exits 0 (emitting the mix JSON) on a minable ledger and 1 on
    one with no serve_batch rows — the verify-skill smoke contract."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["WAM_TPU_SCHEDULE_CACHE"] = str(tmp_path / "schedules.json")
    ledger = tmp_path / "serve.jsonl"
    w = JsonlWriter(str(ledger))
    # steady mix: mine succeeds, nothing drifts, no sweep -> fast pass
    for r in _shifted_rows(n_light=12, n_heavy=0):
        w.write(r)
    ok = subprocess.run(
        [sys.executable, "-m", "wam_tpu.tune.online", "--once",
         "--ledger", str(ledger), "--device", "cpu"],
        capture_output=True, text=True, timeout=300, env=env)
    assert ok.returncode == 0, ok.stderr
    out = json.loads(ok.stdout.splitlines()[-1])
    assert out["mix"]["rows"] == 12 and out["drift"]["drifted"] == []
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    bad = subprocess.run(
        [sys.executable, "-m", "wam_tpu.tune.online", "--once",
         "--ledger", str(empty), "--device", "cpu"],
        capture_output=True, text=True, timeout=300, env=env)
    assert bad.returncode == 1, bad.stdout


# -- serve_batch fingerprint stamping (satellite 1) ---------------------------


def test_serve_batch_rows_stamp_fingerprint_and_qos(sched_cache):
    m = ServeMetrics()
    m.note_batch(bucket_shape=(1, 16, 16), n_real=2, max_batch=4,
                 pad_waste=0.5, queue_depth=1, service_s=0.02,
                 queue_waits_s=[0.0, 0.0], latencies_s=[0.02, 0.02],
                 qos=["interactive", "batch"])
    row = m.batch_sample()[0]
    assert row["schedule_fingerprint"] == schedule_fingerprint()
    assert row["qos"] == {"interactive": 1, "batch": 1}
    # the canary hook overrides the process-global champion fingerprint
    m.schedule_fingerprint = "challenger-fp"
    m.note_batch(bucket_shape=(1, 16, 16), n_real=1, max_batch=4,
                 pad_waste=0.75, queue_depth=0, service_s=0.01,
                 queue_waits_s=[0.0], latencies_s=[0.01], qos=["batch"])
    assert m.batch_sample()[1]["schedule_fingerprint"] == "challenger-fp"


# -- fleet canary hook --------------------------------------------------------


class _GateEntry:
    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()

    def __call__(self, xs, ys):
        self.entered.set()
        assert self.release.wait(timeout=10), "test gate never released"
        return np.asarray(xs) * 2.0


def test_pin_canary_routes_batch_lane_to_challenger():
    need_devices(2)
    gates = {rid: _GateEntry() for rid in range(2)}
    fallback = lambda xs, ys: np.asarray(xs)
    fleet = FleetServer(lambda rid, m: gates.get(rid, fallback), [(4,)],
                        replicas=2, max_batch=1, max_wait_ms=0.0,
                        warmup=False)
    x = np.zeros((4,), np.float32)
    try:
        rid = fleet.pin_canary("chall-fp")
        assert rid == 1  # defaults to the highest live rid
        with pytest.raises(ValueError):
            fleet.pin_canary("other-fp")  # one canary at a time
        f0 = fleet.submit(x, 0, qos="batch")  # batch lane -> canary
        assert gates[1].entered.wait(timeout=10)
        f1 = fleet.submit(x, 0, qos="interactive")  # -> champion
        assert gates[0].entered.wait(timeout=10)
        assert fleet.metrics.replica(1).schedule_fingerprint == "chall-fp"
        for g in gates.values():
            g.release.set()
        f0.result(timeout=10), f1.result(timeout=10)
        fleet.clear_canary()
        assert fleet.metrics.replica(1).schedule_fingerprint is None
        assert fleet.canary_report()["verdict"] == "none"
    finally:
        for g in gates.values():
            g.release.set()
        fleet.close()


def test_canary_report_windows_out_prepin_history():
    need_devices(2)
    fleet = FleetServer(lambda rid, m: (lambda xs, ys: np.asarray(xs)),
                        [(4,)], replicas=2, max_batch=1, max_wait_ms=0.0,
                        warmup=False)

    def _note(rid, service_s, n=4):
        fleet.metrics.replica(rid).note_batch(
            bucket_shape=(4,), n_real=n, max_batch=8, pad_waste=0.0,
            queue_depth=0, service_s=service_s,
            queue_waits_s=[0.0] * n, latencies_s=[service_s] * n)

    try:
        # light-era history on the future champion: must NOT count
        for _ in range(8):
            _note(0, 0.004)
        time.sleep(0.02)  # rows strictly before the pin's t0
        fleet.pin_canary("chall-fp")
        report = fleet.canary_report(min_batches=4)
        assert report["verdict"] == "insufficient"
        assert report["champion_batches"] == 0
        for _ in range(6):
            _note(0, 0.054)  # champion at 13.5 ms/item
            _note(1, 0.07, n=8)  # challenger at 8.75 ms/item
        report = fleet.canary_report(min_batches=4, margin=0.05)
        assert report["champion_batches"] == 6
        assert report["challenger_batches"] == 6
        assert report["verdict"] == "challenger" and report["win"]
        assert report["improvement"] == pytest.approx(1 - 0.00875 / 0.0135)
    finally:
        fleet.close()


def test_pin_canary_needs_two_live_replicas():
    fleet = FleetServer(lambda rid, m: (lambda xs, ys: np.asarray(xs)),
                        [(4,)], replicas=1, max_batch=1, warmup=False)
    try:
        with pytest.raises(ValueError, match="2 live replicas"):
            fleet.pin_canary("fp")
    finally:
        fleet.close()


# -- autoscaler cache-hit drain discount (satellite 3) ------------------------


def test_autoscaler_discounts_grow_drain_by_cache_hit_rate():
    from wam_tpu.pod.autoscaler import AutoscaleConfig, decide
    from wam_tpu.pod.protocol import WorkerSnapshot

    def snap(drain, hit=-1.0, penalty=0.0):
        return WorkerSnapshot(worker_id=0, pid=0, t_worker=0.0,
                              projected_drain_s=drain,
                              slo_penalty_s=penalty, cache_hit_rate=hit)

    cfg = AutoscaleConfig(min_workers=1, max_workers=4,
                          grow_drain_s=0.5, shrink_drain_s=0.05)
    # deep queue but a hot cache serves most of it: phantom load, hold
    assert decide(cfg, [snap(2.0, hit=0.9)], 2) == 0
    # same queue, cold cache -> genuine pressure, grow
    assert decide(cfg, [snap(2.0, hit=0.0)], 2) == 1
    # pre-round-19 worker (hit unknown = -1) keeps the raw drain
    assert decide(cfg, [snap(2.0)], 2) == 1
    # shrink reads the RAW drain: a hot cache must not shrink away
    # capacity that real traffic still needs (0.2 raw > shrink_drain_s)
    assert decide(cfg, [snap(0.2, hit=0.9)], 2) == 0
    # SLO burn still grows regardless of the discount
    assert decide(cfg, [snap(2.0, hit=0.9, penalty=0.1)], 2) == 1
