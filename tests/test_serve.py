"""Serving runtime (`wam_tpu/serve/`): bucket routing and padding
correctness, the one-compile-per-bucket guarantee, backpressure, deadline
timeouts, CPU-fallback degradation, and the metrics ledger schema.

The operational tests (backpressure/deadline/fallback) drive the worker
loop with GATED fake entries — a threading.Event handshake instead of
sleeps, so the queue states they assert are deterministic and the tests
stay inside the tier-1 time budget."""

import json
import threading

import jax
import numpy as np
import pytest

from wam_tpu.serve import (
    AttributionServer,
    Bucket,
    BucketTable,
    DeadlineExceededError,
    NoBucketError,
    QueueFullError,
    ServeMetrics,
    ServerClosedError,
    pad_item,
)


# -- shape bucketing ----------------------------------------------------------


def test_bucket_table_selects_smallest_fit():
    table = BucketTable([(1, 64, 64), (1, 32, 32), (1, 48, 48)])
    assert table.select((1, 32, 32)).shape == (1, 32, 32)
    assert table.select((1, 20, 20)).shape == (1, 32, 32)  # least pad waste
    assert table.select((1, 33, 32)).shape == (1, 48, 48)  # every dim must fit
    assert table.select((1, 64, 64)).shape == (1, 64, 64)
    with pytest.raises(NoBucketError):
        table.select((1, 65, 64))  # too big for every bucket
    with pytest.raises(NoBucketError):
        table.select((32, 32))  # rank mismatch never fits
    with pytest.raises(ValueError):
        BucketTable([(1, 32, 32), (1, 32, 32)])  # duplicates
    with pytest.raises(ValueError):
        BucketTable([])


def test_pad_item_and_waste():
    b = Bucket.of((1, 8, 8))
    x = np.arange(2 * 3, dtype=np.float32).reshape(1, 2, 3)
    padded = pad_item(x, b)
    assert padded.shape == (1, 8, 8)
    np.testing.assert_array_equal(padded[:, :2, :3], x)
    assert padded.sum() == x.sum()  # zero fill
    assert b.pad_waste(x.shape) == pytest.approx(1.0 - 6 / 64)
    assert b.pad_waste((1, 8, 8)) == 0.0
    assert pad_item(padded, b) is padded  # exact fit: no copy


def test_serve_config_bucket_parsing():
    from wam_tpu.config import ServeConfig

    cfg = ServeConfig(buckets="3x224x224, 3x256x256,32768")
    assert cfg.bucket_shapes() == [(3, 224, 224), (3, 256, 256), (32768,)]
    assert ServeConfig().bucket_shapes() == []


# -- padding correctness through a real engine --------------------------------


def _toy_wam2d():
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.wam2d import BaseWAM2D

    toy = toy_conv_model(jax.random.PRNGKey(0), ndim=2)
    return BaseWAM2D(lambda x: toy(x.mean(axis=1)), J=2)


def test_batch_pad_matches_unbatched_reference():
    """A lone request in a replicate-padded max_batch=4 batch must come back
    identical to the unbatched engine call: duplicate rows cannot move the
    mosaic's per-block max-normalizer (serve.buckets docstring)."""
    wam = _toy_wam2d()
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16)))
    ref = np.asarray(wam(x[None], np.asarray([2])))[0]

    server = AttributionServer(
        wam.serve_entry(), [(1, 16, 16)], max_batch=4, warmup=False
    )
    try:
        got = server.attribute(x, 2)
    finally:
        server.close()
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_spatial_pad_matches_padded_reference():
    """A spatially padded request equals the engine run on the zero-padded
    input — the serve result IS the padded input's attribution (the
    documented trade; it is not the unpadded input's)."""
    wam = _toy_wam2d()
    bucket = Bucket.of((1, 16, 16))
    x_small = np.asarray(jax.random.normal(jax.random.PRNGKey(2), (1, 12, 12)))
    ref = np.asarray(wam(pad_item(x_small, bucket)[None], np.asarray([1])))[0]

    server = AttributionServer(
        wam.serve_entry(), [bucket.shape], max_batch=4, warmup=False
    )
    try:
        got = server.attribute(x_small, 1)
    finally:
        server.close()
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_mixed_stream_compiles_once_per_bucket():
    """A >= 3-shape request stream (exact and undersized fits) compiles
    exactly once per bucket — at warmup — asserted via the jit cache-miss
    counter wired through serve_entry(on_trace=...)."""
    wam = _toy_wam2d()
    metrics = ServeMetrics()
    shapes = [(1, 8, 8), (1, 16, 16), (1, 24, 24)]
    server = AttributionServer(
        wam.serve_entry(on_trace=metrics.note_compile),
        shapes,
        max_batch=2,
        metrics=metrics,
    )
    assert metrics.compile_count == len(shapes)  # warmup compiled each bucket
    stream = [(1, 8, 8), (1, 16, 16), (1, 24, 24), (1, 6, 6), (1, 12, 12),
              (1, 20, 20), (1, 8, 8), (1, 24, 24)]
    try:
        for i, shape in enumerate(stream):
            x = np.asarray(jax.random.normal(jax.random.PRNGKey(i), shape))
            out = server.attribute(x, i % 4)
            assert out.shape[-1] == out.shape[-2]  # a mosaic came back
    finally:
        server.close()
    assert metrics.compile_count == len(shapes)  # zero hot-path compiles
    assert metrics.completed == len(stream)


# -- operational semantics (gated fake entries) -------------------------------


class _GateEntry:
    """Fake entry that parks the worker thread inside the dispatch until
    released — deterministic queue buildup without sleeps."""

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.calls = 0

    def __call__(self, xs, ys):
        self.calls += 1
        self.entered.set()
        assert self.release.wait(timeout=10), "test gate never released"
        return np.asarray(xs) * 2.0


def test_backpressure_rejects_with_retry_after():
    entry = _GateEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=1, max_wait_ms=0.0, queue_depth=2,
        warmup=False,
    )
    x = np.zeros((4,), np.float32)
    try:
        first = server.submit(x, 0)
        assert entry.entered.wait(timeout=10)  # worker is parked in dispatch
        server.submit(x, 0)
        server.submit(x, 0)  # queue now holds queue_depth items
        with pytest.raises(QueueFullError) as ei:
            server.submit(x, 0)
        assert ei.value.retry_after_s > 0
        assert server.metrics.rejected == 1
        entry.release.set()
        np.testing.assert_array_equal(first.result(timeout=10), x * 2.0)
    finally:
        entry.release.set()
        server.close()
    assert server.metrics.completed == 3  # the admitted requests all served


def test_deadline_lapses_while_queued():
    entry = _GateEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=1, max_wait_ms=0.0, queue_depth=8,
        warmup=False,
    )
    x = np.zeros((4,), np.float32)
    try:
        first = server.submit(x, 0)
        assert entry.entered.wait(timeout=10)
        doomed = server.submit(x, 0, deadline_ms=30.0)
        threading.Event().wait(0.1)  # let the deadline lapse while queued
        entry.release.set()
        first.result(timeout=10)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=10)
    finally:
        entry.release.set()
        server.close()
    assert server.metrics.expired == 1


def test_submit_validation_and_close():
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs), [(4,)], max_batch=1, warmup=False
    )
    x = np.zeros((4,), np.float32)
    with pytest.raises(ValueError, match="label"):
        server.submit(x)  # labeled server needs y
    with pytest.raises(NoBucketError):
        server.submit(np.zeros((5,), np.float32), 0)
    server.close()
    with pytest.raises(ServerClosedError):
        server.submit(x, 0)


def test_unlabeled_server():
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs) + (0.0 if ys is None else 1.0),
        [(4,)], max_batch=2, labeled=False, warmup=False,
    )
    x = np.arange(4, dtype=np.float32)
    try:
        with pytest.raises(ValueError, match="unlabeled"):
            server.submit(x, 3)
        np.testing.assert_array_equal(server.attribute(x), x)  # ys stayed None
    finally:
        server.close()


def test_cpu_fallback_on_device_loss(monkeypatch):
    """Entry raises mid-run + forced re-probe says the accelerator is gone
    -> the server swaps in the fallback entry once, replays the batch on
    it, and keeps serving degraded."""
    from wam_tpu import config as wconfig

    calls = {"probe": 0}

    def fake_probe(timeout_s: float = 180.0, force: bool = False):
        calls["probe"] += 1
        assert force  # the runtime must force a re-probe, not read the cache
        return False  # accelerator is gone

    monkeypatch.setattr(wconfig, "probe_accelerator", fake_probe)

    def dying_entry(xs, ys):
        raise RuntimeError("device lost")

    server = AttributionServer(
        dying_entry, [(4,)], max_batch=1, warmup=False,
        fallback_factory=lambda: (lambda xs, ys: np.asarray(xs) * 3.0),
    )
    x = np.ones((4,), np.float32)
    try:
        out = server.attribute(x, 0)
        np.testing.assert_array_equal(out, x * 3.0)
        assert server.degraded
        assert calls["probe"] == 1
        assert server.metrics.fallbacks >= 1
        # later batches go straight to the fallback — no re-probe, no raise
        np.testing.assert_array_equal(server.attribute(x, 1), x * 3.0)
        assert calls["probe"] == 1
    finally:
        server.close()


def test_healthy_accelerator_reraises(monkeypatch):
    """An in-process bug with a HEALTHY accelerator must re-raise to the
    caller, not silently degrade."""
    from wam_tpu import config as wconfig

    monkeypatch.setattr(
        wconfig, "probe_accelerator", lambda timeout_s=180.0, force=False: True
    )

    def buggy_entry(xs, ys):
        raise RuntimeError("actual bug")

    server = AttributionServer(
        buggy_entry, [(4,)], max_batch=1, warmup=False,
        fallback_factory=lambda: (lambda xs, ys: np.asarray(xs)),
    )
    try:
        with pytest.raises(RuntimeError, match="actual bug"):
            server.attribute(np.ones((4,), np.float32), 0)
        assert not server.degraded
        assert server.metrics.failed == 1
    finally:
        server.close()


def test_projected_drain_is_per_bucket():
    """The retry-after / routing signal sums (queued + in-flight batches) ×
    EMA per bucket — work in one bucket never inflates another's estimate
    (the v1 global-EMA bug this round fixed)."""
    from wam_tpu.serve.metrics import EMA_SEED_S

    entry = _GateEntry()
    server = AttributionServer(
        entry, [(4,), (8,)], max_batch=1, max_wait_ms=0.0, queue_depth=8,
        warmup=False,
    )
    x4 = np.zeros((4,), np.float32)
    try:
        assert server.projected_drain_s() == 0.0  # idle
        first = server.submit(x4, 0)
        assert entry.entered.wait(timeout=10)
        # one in-flight batch, bucket (4,) only: exactly its seeded EMA —
        # the untouched (8,) bucket contributes nothing
        assert server.projected_drain_s() == pytest.approx(EMA_SEED_S)
        server.submit(x4, 0)  # one queued batch more of the same bucket
        assert server.projected_drain_s() == pytest.approx(2 * EMA_SEED_S)
        entry.release.set()
        first.result(timeout=10)
    finally:
        entry.release.set()
        server.close()


def test_warmup_ledger_and_per_bucket_ema():
    """Parallel warmup records per-bucket warmup seconds; the snapshot's
    EMA map carries exactly the buckets that served traffic."""
    metrics = ServeMetrics()
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs), [(4,), (8,)], max_batch=2,
        warmup=True, metrics=metrics,
    )
    try:
        server.attribute(np.zeros((4,), np.float32), 0)
    finally:
        server.close()
    snap = metrics.snapshot()
    assert set(snap["warmup_s"]) == {"4", "8"}
    assert all(v > 0.0 for v in snap["warmup_s"].values())
    assert set(snap["ema_service_s"]) == {"4"}  # warmup doesn't fake an EMA
    assert snap["schema_version"] == 2 and snap["replica_id"] is None


# -- metrics ledger -----------------------------------------------------------


def test_metrics_ledger_schema(tmp_path):
    path = str(tmp_path / "serve.jsonl")
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs), [(4,), (8,)], max_batch=2,
        warmup=False, metrics_path=path,
    )
    for i in range(5):
        server.attribute(np.zeros((4 if i % 2 else 8,), np.float32), 0)
    server.close()  # drains + emits

    rows = [json.loads(line) for line in open(path)]
    batches = [r for r in rows if r["metric"] == "serve_batch"]
    summaries = [r for r in rows if r["metric"] == "serve_summary"]
    assert batches and len(summaries) == 1
    for r in batches:
        assert 0.0 < r["fill_ratio"] <= 1.0
        assert 0.0 <= r["pad_waste"] < 1.0
        assert r["service_s"] >= 0.0 and r["queue_depth"] >= 0
    s = summaries[0]
    assert s["completed"] == 5 and s["submitted"] == 5
    assert s["latency_p50_ms"] > 0.0 and s["latency_p99_ms"] >= s["latency_p50_ms"]
    assert s["attributions_per_s"] > 0.0
    assert s["compile_count"] == 0  # plain-python entry never traces
    assert "assemble" in s["stages"] and "dispatch" in s["stages"]
    assert s["config"]["max_batch"] == 2  # describe() rode along


def test_percentile_ms_empty_is_nan():
    from wam_tpu.serve import percentile_ms

    assert np.isnan(percentile_ms([], 50))
    assert percentile_ms([0.1], 50) == pytest.approx(100.0)
