"""Streaming pipeline layer (`wam_tpu/pipeline/`): the double-buffered
device stager, the TPU-only buffer-donation policy, and the AOT executable
cache — plus its consumers (serve warmup, the eval AUC runner cache) and
the evaluators' explanation fingerprinting that rides in the same PR.

AOT assertions use the trace-count probe, never wall time: `on_trace`
fires once per jit cache miss (at export time on an AOT miss) and never on
an AOT hit, so "the warm process skipped the retrace" is a counter == 0
check that cannot flake (VERDICT-style honest measurement)."""

import json
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.pipeline import (
    DeviceStager,
    aot_entry_path,
    aval_signature,
    cached_entry,
    cached_jit,
    donating_jit,
    donation_safe,
    load_aot,
    put_committed,
    resolve_donate,
    stage_to_device,
)


# -- device stager ------------------------------------------------------------


def _slow_batches(n, delay, fail_at=None):
    for i in range(n):
        if fail_at is not None and i == fail_at:
            raise ValueError(f"host iterator died at {i}")
        time.sleep(delay)
        yield np.full((4,), float(i), dtype=np.float32)


def test_stager_preserves_order_and_values():
    got = [np.asarray(b) for b in stage_to_device(_slow_batches(5, 0.0))]
    assert len(got) == 5
    for i, b in enumerate(got):
        np.testing.assert_array_equal(b, np.full((4,), float(i)))


def test_stager_overlaps_host_production_with_consumption():
    """Producer sleeps DELAY per batch, consumer works DELAY per batch:
    serial cost is 2*N*DELAY, the staged loop ~ (N+1)*DELAY. The bound is
    deliberately loose (1.75x the ideal) so scheduler noise can't flake it
    while still rejecting a serial implementation."""
    n, delay = 4, 0.06
    t0 = time.perf_counter()
    for batch in stage_to_device(_slow_batches(n, delay)):
        jax.block_until_ready(batch)
        time.sleep(delay)  # consumer-side work
    elapsed = time.perf_counter() - t0
    serial = 2 * n * delay
    assert elapsed < serial * 0.9, (
        f"staged loop took {elapsed:.3f}s, serial is {serial:.3f}s — no overlap"
    )


def test_stager_propagates_host_iterator_error():
    stager = DeviceStager(_slow_batches(5, 0.0, fail_at=2))
    assert np.asarray(next(stager))[0] == 0.0
    assert np.asarray(next(stager))[0] == 1.0
    with pytest.raises(ValueError, match="host iterator died"):
        next(stager)
    stager.close()


def test_stager_close_mid_stream_joins_producer():
    stager = DeviceStager(_slow_batches(50, 0.01), depth=2)
    next(stager)
    stager.close()
    assert stager._thread is None or not stager._thread.is_alive()


def test_put_committed_honors_sharding():
    dev = jax.devices()[1]  # conftest forces an 8-device CPU host
    sharding = jax.sharding.SingleDeviceSharding(dev)
    out = put_committed((np.zeros((4, 4), np.float32), np.zeros((4,), np.int32)),
                        sharding=sharding)
    for leaf in jax.tree_util.tree_leaves(out):
        assert leaf.sharding.device_set == {dev}


# -- donation policy ----------------------------------------------------------


def test_resolve_donate_default_is_tpu_only():
    assert resolve_donate(None) is (jax.default_backend() == "tpu")
    assert resolve_donate(True) is True
    assert resolve_donate(False) is False


def test_donating_jit_default_emits_no_cpu_donation_warnings():
    fn = donating_jit(lambda x: x * 2.0)
    x = jnp.arange(8.0)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        out = fn(x)
        jax.block_until_ready(out)
    assert not [w for w in rec if "donated" in str(w.message).lower()]
    np.testing.assert_allclose(out, np.arange(8.0) * 2.0)
    # the default policy left the caller's buffer alive on CPU
    np.testing.assert_allclose(x, np.arange(8.0))


def test_donating_jit_explicit_true_consumes_the_buffer():
    """Forced donation really donates: the caller's handle is deleted
    after the call — exactly the hazard `donation_safe` guards instance
    caches against."""
    fn = donating_jit(lambda x: x + 1.0, donate=True)
    x = jnp.arange(4.0)
    out = jax.block_until_ready(fn(x))
    np.testing.assert_allclose(out, np.arange(4.0) + 1.0)
    with pytest.raises(RuntimeError, match="deleted"):
        x[0].block_until_ready()


def test_donation_safe_copies_only_when_donating():
    x = jnp.arange(6.0)
    assert donation_safe(x, False) is x  # passthrough: no copy
    guarded = donation_safe(x, True)
    assert guarded is not x
    np.testing.assert_allclose(guarded, x)
    tree = donation_safe({"a": np.ones(3), "b": None and x}, True)
    np.testing.assert_allclose(tree["a"], np.ones(3))


# -- AOT executable cache -----------------------------------------------------


def _mul_add(a, b):
    return a * 2.0 + b


_ARGS = (jnp.arange(8.0), jnp.ones((8,)))


def test_aval_signature():
    assert aval_signature(_ARGS) == "float32[8];float32[8]"
    assert aval_signature((jnp.zeros((2, 3), jnp.int32), None)) == "int32[2,3];-"


def test_aot_miss_traces_once_hit_traces_zero(tmp_path):
    traces = []
    fn1 = cached_jit(_mul_add, _ARGS, "k1", on_trace=lambda: traces.append("a"),
                     cache_dir=str(tmp_path))
    out1 = fn1(*_ARGS)
    assert traces == ["a"]  # miss: exactly one export trace
    assert load_aot("k1", str(tmp_path)) is not None

    # a fresh consumer (the "new process" equivalent — nothing shared but
    # the cache dir) must splice the stored module without ever tracing
    fn2 = cached_jit(_mul_add, _ARGS, "k1", on_trace=lambda: traces.append("b"),
                     cache_dir=str(tmp_path))
    out2 = fn2(*_ARGS)
    assert traces == ["a"]
    np.testing.assert_allclose(out1, out2)


def test_aot_stale_version_invalidates_wholesale(tmp_path):
    cached_jit(_mul_add, _ARGS, "k2", cache_dir=str(tmp_path))(*_ARGS)
    path = aot_entry_path("k2", str(tmp_path))
    raw = open(path, "rb").read()
    header_line, _, payload = raw.partition(b"\n")
    header = json.loads(header_line)
    header["version"] += 1
    with open(path, "wb") as f:
        f.write(json.dumps(header).encode() + b"\n" + payload)

    assert load_aot("k2", str(tmp_path)) is None  # stale: wholesale miss
    traces = []
    cached_jit(_mul_add, _ARGS, "k2", on_trace=lambda: traces.append(1),
               cache_dir=str(tmp_path))(*_ARGS)
    assert traces == [1]  # re-exported, not errored


def test_aot_corrupt_payload_is_a_miss(tmp_path):
    cached_jit(_mul_add, _ARGS, "k3", cache_dir=str(tmp_path))(*_ARGS)
    path = aot_entry_path("k3", str(tmp_path))
    with open(path, "wb") as f:
        f.write(b"not a cache entry")
    assert load_aot("k3", str(tmp_path)) is None


def test_aot_kill_switch(tmp_path, monkeypatch):
    monkeypatch.setenv("WAM_TPU_NO_AOT_CACHE", "1")
    traces = []
    fn = cached_jit(_mul_add, _ARGS, "k4", on_trace=lambda: traces.append(1),
                    cache_dir=str(tmp_path))
    jax.block_until_ready(fn(*_ARGS))
    assert traces == [1]  # plain jit: traced normally
    assert not list(tmp_path.iterdir())  # and nothing was written


def test_cached_entry_dispatches_per_signature(tmp_path):
    traces = []
    entry = cached_entry(lambda x: x * 3.0, "base",
                         on_trace=lambda: traces.append(1),
                         cache_dir=str(tmp_path))
    entry(jnp.ones((4,)))
    entry(jnp.ones((8,)))
    entry(jnp.ones((4,)))  # same signature: no new executable
    assert len(traces) == 2
    assert len(list(tmp_path.iterdir())) == 2

    fresh = cached_entry(lambda x: x * 3.0, "base",
                         on_trace=lambda: traces.append(1),
                         cache_dir=str(tmp_path))
    np.testing.assert_allclose(fresh(jnp.ones((4,))), np.full((4,), 3.0))
    assert len(traces) == 2  # both signatures hit the cache


# -- consumers: serve warmup + eval runner cache ------------------------------


def _toy_wam2d():
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.wam2d import BaseWAM2D

    toy = toy_conv_model(jax.random.PRNGKey(0), ndim=2)
    return BaseWAM2D(lambda x: toy(x.mean(axis=1)), J=2)


def test_serve_warmup_hits_aot_cache(tmp_path, monkeypatch):
    """Second server with the same aot_key (the fresh-process stand-in:
    nothing shared but the on-disk cache) warms up with ZERO traces and
    still serves bit-correct results."""
    from wam_tpu.serve import AttributionServer

    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(tmp_path))
    wam = _toy_wam2d()
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16)))
    ref = np.asarray(wam(x[None], np.asarray([2])))[0]

    cold = []
    server = AttributionServer(
        wam.serve_entry(on_trace=lambda: cold.append(1), aot_key="toy-serve"),
        [(1, 16, 16)], max_batch=2,
    )
    server.close()
    assert cold == [1]  # warmup exported the bucket's executable

    warm = []
    server = AttributionServer(
        wam.serve_entry(on_trace=lambda: warm.append(1), aot_key="toy-serve"),
        [(1, 16, 16)], max_batch=2,
    )
    try:
        got = server.attribute(x, 2)
    finally:
        server.close()
    assert warm == []  # warmup + hot path: never retraced
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_run_cached_auc_aot_skips_model_retrace(tmp_path, monkeypatch):
    from wam_tpu.evalsuite.metrics import run_cached_auc

    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(tmp_path))
    traced = []

    def model_fn(batch):
        traced.append(1)  # fires at trace time only
        return batch.reshape(batch.shape[0], -1)[:, :4]

    def inputs_fn(x_s, expl_s):
        masks = jnp.linspace(0.0, 1.0, 4)[:, None, None, None]  # n_iter+1
        return x_s[None] * masks + expl_s[None]

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 1, 4, 4))
    expl = jnp.ones((2, 4, 4)) * 0.1  # batched like x (vmapped together)
    y = np.array([1, 3])

    def run(cache):
        scores, curves = run_cached_auc(
            cache, ("insertion",), inputs_fn, model_fn, 16, 3, x, expl, y,
            aot_key="toy-auc",
        )
        return np.asarray(scores), np.asarray(curves)

    s1, c1 = run({})
    n_cold = len(traced)
    assert n_cold >= 1
    s2, c2 = run({})  # fresh runner cache: only the AOT entry is shared
    assert len(traced) == n_cold  # model body never re-traced
    np.testing.assert_allclose(s1, s2, atol=1e-6)
    np.testing.assert_allclose(c1, c2, atol=1e-6)


# -- evaluator satellites -----------------------------------------------------


def test_eval1d_auto_batch_size_resolves_fan_cap(monkeypatch):
    from wam_tpu.evalsuite.eval1d import Eval1DWAM
    from wam_tpu.tune import invalidate_process_cache

    ev = Eval1DWAM(model_fn=None, explainer=None, batch_size=7)
    assert ev._fan_cap(65) == 7  # explicit ints pass through
    monkeypatch.setenv("WAM_TPU_NO_SCHEDULE_CACHE", "1")
    invalidate_process_cache()
    try:
        auto = Eval1DWAM(model_fn=None, explainer=None, batch_size="auto")
        assert auto._fan_cap(65) == 128  # law fallback without a tuned entry
    finally:
        invalidate_process_cache()


def test_eval2d_precompute_fingerprints_the_batch():
    from wam_tpu.evalsuite.eval2d import Eval2DWAM

    calls = []

    def explainer(x, y):
        calls.append(np.asarray(x).shape)
        return jnp.ones((x.shape[0], 8, 8))

    ev = Eval2DWAM(model_fn=None, explainer=explainer, J=2)
    x1, y1 = jnp.zeros((2, 3, 8, 8)), np.array([0, 1])
    ev.precompute(x1, y1)
    ev.precompute(x1, y1)
    assert len(calls) == 1  # same batch: cached

    ev.precompute(jnp.zeros((3, 3, 8, 8)), np.array([0, 1, 2]))
    assert len(calls) == 2  # different shape: recomputed, not reused stale

    ev.precompute(x1, np.array([1, 0]))
    assert len(calls) == 3  # same shape, different labels: recomputed

    ev.reset()
    ev.precompute(x1, y1)
    assert len(calls) == 4


def test_eval2d_directly_assigned_explanations_adopt_first_fingerprint():
    from wam_tpu.evalsuite.eval2d import Eval2DWAM

    calls = []

    def explainer(x, y):
        calls.append(1)
        return jnp.ones((x.shape[0], 8, 8))

    ev = Eval2DWAM(model_fn=None, explainer=explainer, J=2)
    handed = jnp.full((2, 8, 8), 0.5)
    ev.grad_wams = handed  # the bench_eval.py cross-evaluator handoff
    x1, y1 = jnp.zeros((2, 3, 8, 8)), np.array([0, 1])
    assert ev.precompute(x1, y1) is handed  # adopted, no explainer call
    assert ev.precompute(x1, y1) is handed
    assert calls == []

    ev.precompute(jnp.zeros((4, 3, 8, 8)), np.array([0, 1, 2, 3]))
    assert calls == [1]  # a DIFFERENT batch may not reuse the handoff
