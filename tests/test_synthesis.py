"""The fused synthesis path (ISSUE 4): `idwt2_pallas`, level-collapsed
waverec2, the 3D matmul synthesis form, and the `set_synth2_impl` knob.

Golden values come from an independent numpy oracle (zero-stuffed full
convolution with the rec filters, trimmed L-2 per side — the pywt upcoef
definition; pywt itself is not installed here) so pallas/collapsed parity
is never checked against the code under test. AOT assertions use the
trace-count probe (`on_trace` fires once per jit miss, never on an AOT
hit), so "the collapsed path hits the cache warm" is a counter check that
cannot flake."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.wavelets import matmul as mm
from wam_tpu.wavelets import transform as tf
from wam_tpu.wavelets.filters import build_wavelet


@pytest.fixture(autouse=True)
def _restore_impls():
    yield
    tf.set_dwt2_impl("auto")
    tf.set_dwt1_impl("auto")
    tf.set_synth2_impl("auto")


# -- numpy oracle -------------------------------------------------------------


def _up_conv(c: np.ndarray, f: np.ndarray, axis: int) -> np.ndarray:
    """pywt upcoef along one axis: zero-stuff, full convolution, trim L-2
    per side -> length 2n - L + 2."""
    L = len(f)
    n = c.shape[axis]
    shp = list(c.shape)
    shp[axis] = 2 * n - 1
    z = np.zeros(shp, dtype=np.float64)
    sl = [slice(None)] * c.ndim
    sl[axis] = slice(None, None, 2)
    z[tuple(sl)] = c
    y = np.apply_along_axis(lambda v: np.convolve(v, f, mode="full"), axis, z)
    out = [slice(None)] * c.ndim
    out[axis] = slice(L - 2, L - 2 + 2 * n - L + 2)
    return y[tuple(out)]


def _oracle_idwt2(sub: np.ndarray, wavelet: str) -> np.ndarray:
    """sub: (4, h, w), quadrant order aa/ad/da/dd (row filter, col filter)."""
    wav = build_wavelet(wavelet)
    lo = np.asarray(wav.rec_lo, dtype=np.float64)
    hi = np.asarray(wav.rec_hi, dtype=np.float64)
    pairs = [(lo, lo), (lo, hi), (hi, lo), (hi, hi)]
    out = None
    for q, (fr, fc) in enumerate(pairs):
        t = _up_conv(_up_conv(sub[q].astype(np.float64), fr, 0), fc, 1)
        out = t if out is None else out + t
    return out


# -- idwt2_pallas golden parity (interpret mode — CPU tier-1) -----------------


@pytest.mark.parametrize("wavelet", ["haar", "db4", "sym3"])
@pytest.mark.parametrize("size", [(9, 9), (12, 10)])
def test_idwt2_pallas_matches_numpy_oracle(wavelet, size):
    sub = np.asarray(
        jax.random.normal(jax.random.PRNGKey(0), (2, 4, *size)))
    got = mm.idwt2_pallas(jnp.asarray(sub), wavelet)
    for b in range(sub.shape[0]):
        np.testing.assert_allclose(
            got[b], _oracle_idwt2(sub[b], wavelet), atol=1e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db4"])
@pytest.mark.parametrize("mode", ["reflect", "zero", "periodic", "symmetric"])
def test_idwt2_pallas_roundtrip_and_conv_parity(wavelet, mode):
    """dwt2 -> idwt2(pallas) round-trips, and the pallas synthesis equals
    the conv synthesis on the same subbands for every boundary mode (the
    synthesis operator itself is mode-independent; modes only change the
    analysis — but the round-trip exercises the real coefficient shapes
    each mode produces)."""
    wav = build_wavelet(wavelet)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 24, 24))
    cA, det = tf.dwt2(x, wav, mode)
    sub = jnp.stack([cA, det.vertical, det.horizontal, det.diagonal], axis=-3)
    ref = tf._synthesis(sub, wav, 2, (24, 24))
    got = mm.idwt2_pallas(sub, wav, (24, 24))
    np.testing.assert_allclose(got, ref, atol=1e-5)
    if mode in ("reflect", "periodic"):
        np.testing.assert_allclose(got, x, atol=1e-4)


def test_idwt2_pallas_vjp_matches_matmul():
    """The custom VJP (backward = the fused analysis kernel) agrees with
    the plain-XLA synthesis gradient, including through the output trim."""
    wav = build_wavelet("db4")
    sub = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 11, 11))
    w = jax.random.normal(jax.random.PRNGKey(3), (2, 13, 13))

    def loss_pallas(s):
        return jnp.sum(mm.idwt2_pallas(s, wav, (13, 13)) * w)

    def loss_mm(s):
        return jnp.sum(mm.synthesis2_mm(s, wav, (13, 13)) * w)

    np.testing.assert_allclose(
        jax.grad(loss_pallas)(sub), jax.grad(loss_mm)(sub), atol=1e-5)


# -- level-collapsed waverec2 -------------------------------------------------


@pytest.mark.parametrize("wavelet", ["haar", "db4", "sym3"])
@pytest.mark.parametrize("mode", ["reflect", "periodic"])
@pytest.mark.parametrize("size,level", [(64, 3), (96, 4)])
def test_waverec2_collapsed_matches_per_level(wavelet, mode, size, level):
    """The host-composed banded operator pair reproduces the per-level conv
    reconstruction across wavelet x mode x depth."""
    x = jax.random.normal(jax.random.PRNGKey(4), (2, size, size))
    coeffs = tf.wavedec2(x, wavelet, level, mode)
    ref = tf.waverec2(coeffs, wavelet)  # conv path (CPU auto)
    got = mm.waverec2_collapsed(coeffs[0], coeffs[1:], wavelet)
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_waverec2_partial_collapse_dispatch(monkeypatch):
    """With the crossover BETWEEN level sides, waverec2 collapses only the
    coarse tail and runs the fine levels per-level — output still matches
    the all-conv reconstruction."""
    x = jax.random.normal(jax.random.PRNGKey(5), (1, 3, 64, 64))
    coeffs = tf.wavedec2(x, "db4", 3, "reflect")
    ref = tf.waverec2(coeffs, "db4")
    # db4 level sides at 64: 35 / 21 / 14 -> crossover 30 collapses 2 of 3
    monkeypatch.setattr(tf, "_SYNTH_COLLAPSE", 30)
    assert tf._collapse_count(coeffs[1:]) == 2
    tf.set_synth2_impl("pallas")
    got = jax.jit(lambda c: tf.waverec2(c, "db4"))(coeffs)
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_waverec2_collapsed_vjp_matches_conv():
    """Gradients through the collapsed operator pair match the per-level
    conv reconstruction for the approximation AND every detail leaf."""
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 48, 48))
    coeffs = tf.wavedec2(x, "db4", 3, "reflect")
    w = jax.random.normal(jax.random.PRNGKey(7), (1, 48, 48))

    def loss_conv(c):
        return jnp.sum(tf.waverec2(c, "db4")[..., :48, :48] * w)

    def loss_collapsed(c):
        return jnp.sum(
            mm.waverec2_collapsed(c[0], c[1:], "db4")[..., :48, :48] * w)

    g_ref = jax.grad(loss_conv)(coeffs)
    g_got = jax.grad(loss_collapsed)(coeffs)
    for r, g in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_got)):
        np.testing.assert_allclose(g, r, atol=1e-4)


def test_waverec2_collapsed_aot_zero_trace(tmp_path, monkeypatch):
    """The collapsed + pallas synthesis graph exports through the AOT
    executable cache and a warm consumer runs it with ZERO traces — the
    operator matrices are host-composed constants, so nothing in the path
    defeats `jax.export` (coeffs are passed as FLAT leaves: Exported
    signatures cannot carry the Detail2D NamedTuple)."""
    from wam_tpu.pipeline import cached_jit

    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(tmp_path))
    # crossover between level sides: 2 levels collapse, 1 runs per-level
    # through idwt2_pallas — the export covers BOTH new paths
    monkeypatch.setattr(tf, "_SYNTH_COLLAPSE", 30)
    tf.set_synth2_impl("pallas")
    x = jax.random.normal(jax.random.PRNGKey(8), (1, 64, 64))
    coeffs = tf.wavedec2(x, "db4", 3, "reflect")
    assert tf._collapse_count(coeffs[1:]) == 2
    flat, treedef = jax.tree_util.tree_flatten(coeffs)

    def rec_flat(*leaves):
        return tf.waverec2(
            jax.tree_util.tree_unflatten(treedef, list(leaves)), "db4")

    traces = []
    fn1 = cached_jit(rec_flat, tuple(flat), "synth-aot",
                     on_trace=lambda: traces.append(1),
                     cache_dir=str(tmp_path))
    out1 = np.asarray(fn1(*flat))
    assert traces == [1]  # cold: exactly one export trace

    fn2 = cached_jit(rec_flat, tuple(flat), "synth-aot",
                     on_trace=lambda: traces.append(2),
                     cache_dir=str(tmp_path))
    out2 = np.asarray(fn2(*flat))
    assert traces == [1]  # warm: ZERO traces — spliced from the cache
    np.testing.assert_allclose(out2, out1)
    np.testing.assert_allclose(out1[..., :64, :64],
                               np.asarray(x), atol=1e-4)


# -- bf16-in / f32-accumulate parity (satellite bugfix) -----------------------


@pytest.mark.parametrize("impl", ["conv", "matmul", "pallas"])
def test_idwt2_bf16_coeffs_return_f32(impl):
    """dwt2 -> idwt2 round-trip with bf16 coefficients returns FLOAT32
    pixels on every synthesis impl, tracking the f32 path — the mirror of
    dwt2's bf16-in/f32-accumulate contract."""
    tf.set_synth2_impl(impl)
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 32, 32), jnp.float32)
    cA, det = tf.dwt2(x, "db4", "reflect")
    ref = tf.idwt2(cA, det, "db4", (32, 32))
    got = tf.idwt2(
        cA.astype(jnp.bfloat16),
        tf.Detail2D(*(d.astype(jnp.bfloat16) for d in det)),
        "db4", (32, 32))
    assert ref.dtype == jnp.float32 and got.dtype == jnp.float32
    scale = float(jnp.abs(ref).max())
    assert float(jnp.abs(got - ref).max()) < 0.02 * scale


@pytest.mark.parametrize("impl", ["conv", "matmul"])
def test_idwt3_bf16_coeffs_return_f32(impl):
    """Same contract in 3D, on both the conv path and the new matmul
    (`synthesis3_mm`) path."""
    tf.set_synth2_impl(impl)
    x = jax.random.normal(jax.random.PRNGKey(10), (1, 12, 12, 12), jnp.float32)
    cA, det = tf.dwt3(x, "db2", "reflect")
    ref = tf.idwt3(cA, det, "db2", (12, 12, 12))
    got = tf.idwt3(
        cA.astype(jnp.bfloat16),
        {k: v.astype(jnp.bfloat16) for k, v in det.items()},
        "db2", (12, 12, 12))
    assert ref.dtype == jnp.float32 and got.dtype == jnp.float32
    scale = float(jnp.abs(ref).max())
    assert float(jnp.abs(got - ref).max()) < 0.02 * scale


# -- 3D matmul synthesis ------------------------------------------------------


@pytest.mark.parametrize("wavelet", ["haar", "db2"])
def test_synthesis3_mm_matches_conv(wavelet):
    wav = build_wavelet(wavelet)
    sub = jax.random.normal(jax.random.PRNGKey(11), (2, 8, 7, 7, 7))
    L = wav.filt_len
    out_shape = (2 * 7 - L + 2,) * 3
    ref = tf._synthesis(sub, wav, 3, out_shape)
    got = mm.synthesis3_mm(sub, wav, out_shape)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_idwt3_matmul_dispatch_roundtrip():
    tf.set_synth2_impl("matmul")
    x = jax.random.normal(jax.random.PRNGKey(12), (1, 16, 16, 16))
    coeffs = tf.wavedec3(x, "haar", 2, "periodic")
    rec = tf.waverec3(coeffs, "haar")
    np.testing.assert_allclose(rec[..., :16, :16, :16], x, atol=1e-4)


# -- 1D folded synthesis at intermediate levels (satellite fix) ---------------


def test_waverec_folds_on_full_length_every_level(monkeypatch):
    """`idwt` decides the folded1d kernel on the COEFFICIENT-determined full
    reconstruction length at EVERY level — waverec's intermediate trims
    must not disqualify the fold (the pre-fix code folded only the top
    level, whose out_len is None)."""
    calls = []
    orig = tf._use_folded1d
    monkeypatch.setattr(
        tf, "_use_folded1d", lambda n: (calls.append(n), orig(n))[1])
    x = jax.random.normal(jax.random.PRNGKey(13), (1, 64))
    coeffs = tf.wavedec(x, "db4", 3, "reflect")
    calls.clear()
    tf.waverec(coeffs, "db4")
    L = build_wavelet("db4").filt_len
    expected = [2 * coeffs[i].shape[-1] - L + 2
                for i in range(1, len(coeffs))]
    assert calls == expected


def test_waverec_folded_matches_conv():
    """Multi-level waverec under the folded 1D impl (now engaged at every
    level) equals the conv impl."""
    x = jax.random.normal(jax.random.PRNGKey(14), (2, 128))
    coeffs = tf.wavedec(x, "db4", 3, "reflect")
    tf.set_dwt1_impl("conv")
    ref = tf.waverec(coeffs, "db4")
    tf.set_dwt1_impl("folded")
    got = tf.waverec(coeffs, "db4")
    np.testing.assert_allclose(got, ref, atol=1e-5)


# -- knob + schedule plumbing -------------------------------------------------


def test_bad_synth_impl_rejected():
    with pytest.raises(ValueError):
        tf.set_synth2_impl("cuda")


def test_resolved_synth2_impl_follows_analysis_off_tpu():
    """auto off-TPU pairs the synthesis with the resolved analysis impl, so
    the seed's conv-with-conv CPU graphs stay byte-identical by default."""
    assert tf.get_synth2_impl() == "auto"
    if jax.default_backend() != "tpu":
        tf.set_dwt2_impl("conv")
        assert tf.resolved_synth2_impl() == "conv"
        tf.set_dwt2_impl("matmul")
        assert tf.resolved_synth2_impl() == "matmul"
    tf.set_synth2_impl("pallas")
    assert tf.resolved_synth2_impl() == "pallas"


def test_candidate_synth_impl_in_label_and_entry():
    from wam_tpu.tune.autotuner import Candidate

    cand = Candidate(sample_chunk=4, synth_impl="pallas")
    assert "synth=pallas" in cand.label()
    assert cand.entry()["synth_impl"] == "pallas"
    assert "synth_impl" not in Candidate(sample_chunk=4).entry()


def test_default_schedules_pin_synth_impl():
    """The flagship TPU entries ship with the fused synthesis path pinned,
    so prewarm/serve bake it into their AOT keys out of the box."""
    path = os.path.join(os.path.dirname(tf.__file__), os.pardir, "tune",
                        "default_schedules.json")
    with open(path) as f:
        data = json.load(f)
    for dtype in ("bf16", "f32"):
        ent = data["schedules"][f"wam2d|3x224x224|b32|{dtype}|pallas|tpu"]
        assert ent["synth_impl"] == "pallas"


def test_apply_tuned_synth_impl_sets_knob():
    from wam_tpu.tune import apply_tuned_synth_impl
    from wam_tpu.tune.cache import invalidate_process_cache, record_schedule

    try:
        # no entry -> None, knob untouched
        assert apply_tuned_synth_impl("nosuch", (1, 8, 8), 2) is None
        assert tf.get_synth2_impl() == "auto"
        record_schedule("synthtest", (1, 8, 8), 2,
                        {"sample_chunk": 1, "synth_impl": "matmul"},
                        persist=False)
        assert apply_tuned_synth_impl("synthtest", (1, 8, 8), 2) == "matmul"
        assert tf.get_synth2_impl() == "matmul"
    finally:
        invalidate_process_cache()
