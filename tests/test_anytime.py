"""Anytime attribution (`wam_tpu.anytime`): progressive refinement with
confidence-gated deadline serving.

Pins the three contracts the subsystem is built on:
- **bit-equal checkpoints** — the checkpointed estimators reuse the exact
  fused dispatch chain, so at completion (any stride, including k=n) the
  map is bit-identical to the non-checkpointed path (1D/2D/3D ×
  SmoothGrad/IG);
- **zero-extra-fetch** — per-stride progress is a control-plane
  `device_get` of the tiny conf vector; the attribution crosses host-ward
  exactly once per request (`fetch_scope` count == 1);
- **deadline semantics** — `submit(deadline_ms=, min_confidence=)` on an
  anytime server delivers best-so-far `AnytimeResult`s instead of raising
  `DeadlineExceededError`, zero/negative deadlines fail at admission with
  a typed error on both runtime and fleet, and convergence early exit
  stays rank-correlated ≥ 0.99 with the full-n oracle.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import need_devices as _need_devices
from wam_tpu.parallel.mesh import make_mesh


# -- shared toy fixtures (the test_seq_estimators conventions) ----------------


def _pool_model_2d(n_classes=5, channels=3, shape=(64, 32), seed=0):
    w = jax.random.normal(jax.random.PRNGKey(seed),
                          (n_classes, channels) + shape)

    def model(x):  # (B, C, H, W)
        return jnp.einsum("bchw,kchw->bk", x, w)

    return model


def _pool_model_3d(n_classes=4, seed=0):
    w = jax.random.normal(jax.random.PRNGKey(seed), (8, n_classes))

    def model(x):  # (B, 1, D, H, W)
        pooled = x[:, 0].mean(axis=(2, 3))  # (B, D)
        feat = pooled.reshape(pooled.shape[0], 8, -1).mean(axis=-1)
        return feat @ w

    return model


def _put_seq(x, mesh, ndim):
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * x.ndim
    spec[x.ndim - ndim] = "data"
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def _seq_case(ndim):
    from wam_tpu.models.audio import toy_wave_model

    if ndim == 1:
        return (toy_wave_model(jax.random.PRNGKey(0)),
                jax.random.normal(jax.random.PRNGKey(1), (2, 2048)),
                jnp.array([1, 3]), 2, "db3", "symmetric")
    if ndim == 2:
        return (_pool_model_2d(),
                jax.random.normal(jax.random.PRNGKey(1), (2, 3, 64, 32)),
                jnp.array([1, 4]), 2, "db2", "reflect")
    return (_pool_model_3d(),
            jax.random.normal(jax.random.PRNGKey(1), (2, 1, 32, 8, 8)),
            jnp.array([1, 3]), 1, "db2", "symmetric")


def _grad_sample_fn(model, key, sigma=0.05):
    """SmoothGrad-style per-sample contribution for `make_anytime_entry`."""

    def sample_fn(x, y, i):
        k = jax.random.fold_in(key, i)
        noisy = x + sigma * jax.random.normal(k, x.shape, x.dtype)

        def loss(v):
            return model(v)[jnp.arange(v.shape[0]), y].sum()

        return jax.grad(loss)(noisy)

    return sample_fn


def _assert_tree_bitequal(got, want):
    ga = jax.tree_util.tree_leaves(got)
    wa = jax.tree_util.tree_leaves(want)
    assert len(ga) == len(wa)
    for a, b in zip(ga, wa):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- bit-equal checkpoints (the tentpole invariant) ---------------------------


@pytest.mark.parametrize("ndim", [1, 2, 3])
def test_checkpointed_bitequal_smooth_and_ig(ndim):
    """k=n (one checkpoint at completion) AND a mid-run stride must both
    finish bit-identical to the non-checkpointed fused path: the
    checkpointed loops replay the SAME jitted dispatch chain, the M2/conf
    side-channel never re-enters the accumulator graph."""
    _need_devices(8)
    from wam_tpu.anytime.state import ANYTIME_VEC_SIZE, SLOT_COUNT
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    model, x_host, y, level, wavelet, mode = _seq_case(ndim)
    mesh = make_mesh({"data": 8})
    x = _put_seq(x_host, mesh, ndim)
    key = jax.random.PRNGKey(7)
    sw = SeqShardedWam(mesh, model, ndim=ndim, wavelet=wavelet, level=level,
                       mode=mode, fused=True)
    n = 4

    plain = sw.smoothgrad(x, y, key, n_samples=n, stdev_spread=0.1)
    for stride in (n, 2):  # k=n pinned, plus mid-run checkpoints
        ck, info = sw.smoothgrad_checkpointed(
            x, y, key, n_samples=n, stdev_spread=0.1, stride=stride)
        _assert_tree_bitequal(ck, plain)
        assert info["complete"] and info["n_used"] == n
        assert info["conf"].shape == (x.shape[0], ANYTIME_VEC_SIZE)
        assert int(info["conf"][0, SLOT_COUNT]) == n

    _, ig_plain = sw.integrated(x, y, n_steps=n)
    for stride in (n, 2):
        _, ig_ck, info = sw.integrated_checkpointed(
            x, y, n_steps=n, stride=stride)
        _assert_tree_bitequal(ig_ck, ig_plain)
        assert info["complete"] and info["n_used"] == n


def test_smoothgrad_checkpointed_early_exit_and_floor():
    """Plateau convergence stops the loop early and frees the remaining
    samples; an unreachable confidence floor vetoes the same early exit."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    mesh = make_mesh({"data": 8})
    sw = SeqShardedWam(mesh, toy_wave_model(jax.random.PRNGKey(0)), ndim=1,
                       wavelet="db2", level=2, mode="symmetric", fused=True)
    x = _put_seq(jax.random.normal(jax.random.PRNGKey(1), (2, 2048)), mesh, 1)
    y = jnp.array([1, 3])
    key = jax.random.PRNGKey(9)

    seen = []
    _, info = sw.smoothgrad_checkpointed(
        x, y, key, n_samples=24, stdev_spread=0.1, stride=4,
        plateau_tol=10.0, on_checkpoint=lambda c, conf: seen.append(c))
    assert info["converged"] and not info["complete"]
    assert info["n_used"] == 4 and seen == [4]  # tol above the pinned 1.0

    # a tol under the pinned first-checkpoint delta (exactly 1.0) cannot
    # fire until a REAL delta exists: converges at the second checkpoint
    seen2 = []
    _, info_b = sw.smoothgrad_checkpointed(
        x, y, key, n_samples=24, stdev_spread=0.1, stride=4,
        plateau_tol=0.99, on_checkpoint=lambda c, conf: seen2.append(c))
    assert info_b["converged"] and seen2 == [4, 8]

    _, info2 = sw.smoothgrad_checkpointed(
        x, y, key, n_samples=12, stdev_spread=0.1, stride=4,
        plateau_tol=10.0, min_confidence=1.0)
    assert not info2["converged"] and info2["n_used"] == 12


# -- stride resolution and the tune sweep axis --------------------------------


@pytest.fixture
def sched_cache(tmp_path, monkeypatch):
    from wam_tpu.tune import invalidate_process_cache

    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE",
                       str(tmp_path / "schedules.json"))
    monkeypatch.delenv("WAM_TPU_NO_SCHEDULE_CACHE", raising=False)
    invalidate_process_cache()
    yield
    invalidate_process_cache()


def test_resolve_checkpoint_stride(sched_cache):
    from wam_tpu.core.estimators import resolve_checkpoint_stride
    from wam_tpu.tune import record_schedule

    assert resolve_checkpoint_stride(3, 25) == 3
    assert resolve_checkpoint_stride(100, 25) == 25  # clamp to n
    assert resolve_checkpoint_stride("7", 25) == 7
    with pytest.raises(ValueError, match="stride"):
        resolve_checkpoint_stride(0, 25)
    with pytest.raises(ValueError, match="stride"):
        resolve_checkpoint_stride(-2, 25)
    # auto: built-in default, clamped
    assert resolve_checkpoint_stride("auto", 25) == 5
    assert resolve_checkpoint_stride("auto", 3) == 3
    # auto + a tuned anytime_stride entry for the identified workload
    record_schedule("wam2d", (3, 32, 32), 4, {"anytime_stride": 2})
    assert resolve_checkpoint_stride(
        "auto", 25, workload="wam2d", shape=(3, 32, 32), batch=4) == 2
    # unknown workload keys fall back to the default
    assert resolve_checkpoint_stride(
        "auto", 25, workload="wam2d", shape=(3, 8, 8), batch=4) == 5


def test_tune_candidate_anytime_stride_axis():
    from wam_tpu.tune.autotuner import Candidate
    from wam_tpu.tune.workloads import _seq_candidates

    c = Candidate(sample_chunk=1, seq_fused=True, anytime_stride=3)
    assert "k=3" in c.label()
    assert c.entry()["anytime_stride"] == 3
    assert "anytime_stride" not in Candidate(sample_chunk=1).entry()
    strides = [c.anytime_stride for c in _seq_candidates()
               if c.anytime_stride is not None]
    assert strides, "seq sweep space must carry anytime stride candidates"


# -- checkpoint math ----------------------------------------------------------


def test_m2_and_conf_stats_match_numpy():
    """`m2_update` over consecutive SUM accumulators reproduces the
    population M2 of the per-sample stream; `conf_stats` slots match the
    hand-computed rel-SEM / delta / confidence."""
    from wam_tpu.anytime.state import (
        SLOT_CONFIDENCE, SLOT_COUNT, SLOT_DELTA, SLOT_REL_SEM, conf_stats,
        m2_update)

    rng = np.random.RandomState(0)
    g = rng.randn(6, 3, 10).astype(np.float32)  # n samples × (B, D)
    acc = jnp.zeros((3, 10), jnp.float32)
    m2 = jnp.zeros((3,), jnp.float32)
    for i in range(g.shape[0]):
        acc_new = acc + g[i]
        m2 = m2_update(m2, acc, acc_new, jnp.asarray(i, jnp.float32))
        acc = acc_new
    want_m2 = (g - g.mean(axis=0)).reshape(6, 3, 10) ** 2
    np.testing.assert_allclose(np.asarray(m2), want_m2.sum(axis=(0, 2)),
                               rtol=2e-4)

    prev = jnp.asarray(g[:4].sum(axis=0))
    cv = np.asarray(conf_stats(acc, m2, 6.0, prev, 4.0))
    assert cv.shape == (3, 4)
    np.testing.assert_allclose(cv[:, SLOT_COUNT], 6.0)
    mean = np.asarray(acc) / 6.0
    rms = np.sqrt((mean ** 2).mean(axis=1))
    sem = np.sqrt(np.asarray(m2) / 5.0 / 10.0 / 6.0)
    np.testing.assert_allclose(cv[:, SLOT_REL_SEM], sem / rms, rtol=1e-4)
    move = np.sqrt(((mean - np.asarray(prev) / 4.0) ** 2).mean(axis=1))
    np.testing.assert_allclose(cv[:, SLOT_DELTA], move / rms, rtol=1e-4)
    np.testing.assert_allclose(
        cv[:, SLOT_CONFIDENCE],
        1.0 / (1.0 + cv[:, SLOT_REL_SEM] + cv[:, SLOT_DELTA]), rtol=1e-6)

    # first sample contributes zero M2 (textbook Welford), delta pins at
    # 1.0 with no previous checkpoint -> confidence can never exceed 0.5
    z = jnp.zeros((3, 10), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(m2_update(jnp.zeros((3,)), z, z + 1.0, 0.0)), 0.0)
    cv0 = np.asarray(conf_stats(acc, m2, 6.0, z, 0.0))
    np.testing.assert_allclose(cv0[:, SLOT_DELTA], 1.0)
    assert (cv0[:, SLOT_CONFIDENCE] <= 0.5).all()


# -- entries, the stride driver, and the one-fetch contract -------------------


def test_anytime_entry_driver_and_fetch_contract():
    from wam_tpu.anytime import make_anytime_entry, run_anytime
    from wam_tpu.evalsuite.fan import fetch_scope
    from wam_tpu.models.audio import toy_wave_model

    model = toy_wave_model(jax.random.PRNGKey(0))
    ent = make_anytime_entry(
        _grad_sample_fn(model, jax.random.PRNGKey(5)), n_total=11, stride=4)
    assert ent.wam_anytime and ent.n_strides() == 3
    xs = jax.random.normal(jax.random.PRNGKey(2), (2, 256))
    ys = jnp.array([0, 1])

    res = run_anytime(ent, xs, ys)
    assert res.complete and res.n_used == 11 and res.strides == 3
    # the non-dividing tail stride is weight-masked: count stops at n_total
    _assert_tree_bitequal(res.out, ent(xs, ys))

    with fetch_scope() as fs:
        run_anytime(ent, xs, ys)
    assert fs.count == 1  # conf reads are control syncs, not fetches

    # convergence early exit frees the remaining strides
    lax_ent = make_anytime_entry(
        _grad_sample_fn(model, jax.random.PRNGKey(5)), n_total=40, stride=4,
        plateau_tol=10.0)
    res2 = run_anytime(lax_ent, xs, ys)
    assert res2.converged and res2.n_used < 40

    with pytest.raises(ValueError, match="stride"):
        make_anytime_entry(lambda x, y, i: x, n_total=4, stride=5)
    with pytest.raises(ValueError, match="n_total"):
        make_anytime_entry(lambda x, y, i: x, n_total=0)


def test_convergence_fidelity_rank_correlation():
    """Early-exit fidelity gate: the converged best-so-far map must rank
    features like the full-n oracle (Spearman >= 0.99 per row)."""
    from wam_tpu.anytime import make_anytime_entry, run_anytime

    w = jax.random.normal(jax.random.PRNGKey(0), (32, 4))

    def model(v):
        return jnp.tanh(v) @ w

    sample_fn = _grad_sample_fn(model, jax.random.PRNGKey(5), sigma=0.1)
    xs = jax.random.normal(jax.random.PRNGKey(2), (2, 32))
    ys = jnp.array([0, 3])
    n = 64

    oracle = run_anytime(
        make_anytime_entry(sample_fn, n_total=n, stride=8, plateau_tol=0.0),
        xs, ys)
    assert oracle.complete and not oracle.converged

    early = run_anytime(
        make_anytime_entry(sample_fn, n_total=n, stride=8,
                           plateau_tol=5e-2),
        xs, ys)
    assert early.converged and early.n_used < n

    def _ranks(v):
        return np.argsort(np.argsort(v))

    for row in range(xs.shape[0]):
        a = _ranks(np.asarray(early.out)[row].ravel())
        b = _ranks(np.asarray(oracle.out)[row].ravel())
        rho = np.corrcoef(a, b)[0, 1]
        assert rho >= 0.99, rho


# -- serving semantics --------------------------------------------------------


def _linear_entry_model(x):
    w = jnp.arange(np.prod(x.shape[1:]), dtype=jnp.float32).reshape(
        x.shape[1:])
    return jnp.stack([(x * w).sum(axis=tuple(range(1, x.ndim))),
                      (x * (w + 1.0)).sum(axis=tuple(range(1, x.ndim)))],
                     axis=1)


def test_serve_anytime_results_partials_and_ledger(tmp_path):
    from wam_tpu.anytime import AnytimeResult, make_anytime_entry
    from wam_tpu.evalsuite.fan import fetch_count
    from wam_tpu.serve import AttributionServer

    ent = make_anytime_entry(
        _grad_sample_fn(_linear_entry_model, jax.random.PRNGKey(5)),
        n_total=20, stride=5)
    ledger = tmp_path / "anytime.jsonl"
    srv = AttributionServer(ent, [(16,)], max_batch=2, max_wait_ms=1.0,
                            warmup=True, metrics_path=str(ledger))
    try:
        f0 = fetch_count()
        res = srv.attribute(np.ones(16, np.float32), 1)
        assert isinstance(res, AnytimeResult)
        # linear model: constant grads -> converges at the second checkpoint
        assert res.converged and res.n_used == 10 and res.n_total == 20
        assert res.meets(0.9) and not res.complete
        assert fetch_count() - f0 == 1  # one harvest per served request

        # a ~zero window still delivers the first stride, never raises
        res2 = srv.attribute(np.ones(16, np.float32) * 2.0, 1,
                             deadline_ms=0.001)
        assert isinstance(res2, AnytimeResult)
        assert 0 < res2.n_used < res2.n_total
    finally:
        srv.close()

    snap = srv.metrics.snapshot()["anytime"]
    assert snap["batches"] == 2 and snap["early_exits"] >= 1
    assert snap["deadline_partials"] >= 1
    assert 0.0 < snap["samples_fraction_mean"] < 1.0

    rows = [json.loads(line) for line in open(ledger)]
    partial = [r for r in rows if r.get("metric") == "partial_result"]
    assert partial, "partial deliveries must land v2 ledger rows"
    for r in partial:
        assert r["schema_version"] == 2
        assert r["n_used"] < r["n_total"]
        assert 0.0 < r["confidence_mean"] <= 1.0
        assert {"bucket", "samples_fraction", "converged",
                "deadline_hit"} <= set(r)


def test_invalid_deadline_typed_admission_runtime_and_fleet():
    """Satellite bugfix: zero/negative deadlines die AT ADMISSION with a
    typed error carrying the offending value — runtime and fleet."""
    from wam_tpu.serve import (
        AttributionServer, FleetServer, InvalidDeadlineError, ServeError)

    srv = AttributionServer(lambda xs, ys: xs * 2.0, [(4,)], max_batch=1,
                            max_wait_ms=0.0, warmup=False)
    try:
        for bad in (0, -5.0):
            with pytest.raises(InvalidDeadlineError) as ei:
                srv.submit(np.ones(4, np.float32), 1, deadline_ms=bad)
            assert ei.value.deadline_ms == bad
            assert isinstance(ei.value, ValueError)
            assert isinstance(ei.value, ServeError)
        # min_confidence needs an anytime entry behind the server
        with pytest.raises(ValueError, match="anytime"):
            srv.submit(np.ones(4, np.float32), 1, min_confidence=0.5)
        with pytest.raises(ValueError, match="min_confidence"):
            srv.submit(np.ones(4, np.float32), 1, min_confidence=1.5)
    finally:
        srv.close()

    fleet = FleetServer(lambda rid, m: (lambda xs, ys: xs * 2.0), [(4,)],
                        replicas=1, max_batch=1, max_wait_ms=0.0,
                        warmup=False)
    try:
        with pytest.raises(InvalidDeadlineError) as ei:
            fleet.submit(np.ones(4, np.float32), 1, deadline_ms=0)
        assert ei.value.deadline_ms == 0
    finally:
        fleet.close()


def test_anytime_kill_switch(monkeypatch):
    from wam_tpu.anytime import AnytimeResult, make_anytime_entry
    from wam_tpu.serve import AttributionServer

    ent = make_anytime_entry(
        _grad_sample_fn(_linear_entry_model, jax.random.PRNGKey(5)),
        n_total=8, stride=4)
    monkeypatch.setenv("WAM_TPU_NO_ANYTIME", "1")
    srv = AttributionServer(ent, [(8,)], max_batch=1, max_wait_ms=0.0,
                            warmup=False)
    try:
        res = srv.attribute(np.ones(8, np.float32), 1)
        assert not isinstance(res, AnytimeResult)  # full-n fallback rows
        assert res.shape == (8,)
    finally:
        srv.close()


# -- SLO confidence objectives ------------------------------------------------


def test_slo_confidence_objective_and_burn():
    from wam_tpu.obs.slo import SLOTracker, parse_slo

    policy = parse_slo("*@interactive:min_confidence=0.9,window_s=60")
    assert policy["*@interactive"].min_confidence == 0.9
    with pytest.raises(ValueError, match="unknown SLO objective"):
        parse_slo("*:confidence=0.9")

    t = SLOTracker(policy)
    now = 100.0
    for c in (0.95, 0.97, 0.4):  # one delivery under the floor
        t.note("1x32x32", latency_s=0.01, confidence=c, qos="interactive",
               now=now)
    st = t.bucket_stats("1x32x32@interactive", now=now)
    assert st["n"] == 3
    np.testing.assert_allclose(st["mean_confidence"],
                               (0.95 + 0.97 + 0.4) / 3)
    # 1/3 under floor against the 1% budget
    np.testing.assert_allclose(st["burn_rate"], (1 / 3) / 0.01)

    # errors deliver nothing: confidence 0, and they burn via error paths
    t2 = SLOTracker(parse_slo("*:min_confidence=0.5"))
    t2.note("k", confidence=0.8, now=now)
    t2.note_error("k", now=now)
    st2 = t2.bucket_stats("k", now=now)
    assert st2["mean_confidence"] == 0.8  # only ok samples carry confidence
    assert st2["error_rate"] == 0.5


# -- engine surface -----------------------------------------------------------


def test_wam2d_anytime_serve_entry():
    from wam_tpu.anytime import run_anytime
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.wam2d import WaveletAttribution2D

    toy = toy_conv_model(jax.random.PRNGKey(0), ndim=2)
    wam = WaveletAttribution2D(lambda x: toy(x.mean(axis=1)), J=2,
                               n_samples=6, random_seed=3)
    ent = wam.anytime_serve_entry(stride=3)
    assert ent.n_total == 6 and ent.stride == 3

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 1, 16, 16))
    y = jnp.array([1, 2])
    res = run_anytime(ent, x, y)
    assert res.complete and res.n_used == 6
    assert np.asarray(res.out).shape == (2, 16, 16)  # the serving mosaic
    _assert_tree_bitequal(res.out, ent(x, y))  # full-n determinism

    ig = WaveletAttribution2D(lambda x: toy(x.mean(axis=1)), J=2,
                              method="integratedgrad")
    with pytest.raises(ValueError, match="smooth"):
        ig.anytime_serve_entry()
    wam.mesh = object()
    with pytest.raises(ValueError, match="mesh"):
        wam.anytime_serve_entry()
