"""Mode-general sequence-sharded DWT (`parallel/halo_modes.py`) on the
virtual 8-device CPU mesh: exact parity with the single-device
`transform.wavedec{,2,3}` for the engines' default boundary modes, the
core+tail sharding contract, and an HLO audit proving the graph never
all-gathers a signal-sized buffer (the naive GSPMD-constraint formulation
does — that failure is what motivated the core+tail design)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import need_devices, need_modern_shard_map, scan_gathers
from wam_tpu.parallel import make_mesh
from wam_tpu.parallel.halo_modes import (
    gather_coeffs,
    sharded_wavedec2_mode,
    sharded_wavedec3_mode,
    sharded_wavedec_mode,
)
from wam_tpu.wavelets.transform import wavedec, wavedec2, wavedec3


_need_devices = need_devices
_need_modern_shard_map = need_modern_shard_map


@pytest.mark.parametrize("wavelet", ["haar", "db4", "sym3"])
@pytest.mark.parametrize("mode", ["symmetric", "reflect", "zero", "constant"])
def test_sharded_wavedec_mode_matches_single_device(wavelet, mode):
    _need_devices(8)
    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 1024))
    got = gather_coeffs(sharded_wavedec_mode(mesh, wavelet, 3, mode)(x))
    want = wavedec(x, wavelet, 3, mode)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g.shape == w.shape
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=2e-5)


def test_sharded_wavedec_mode_core_tail_contract():
    _need_devices(8)
    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 1024))
    out = sharded_wavedec_mode(mesh, "db4", 2, "symmetric")(x)
    # L=8: tail grows 0 -> 3 at level 1, (3+7)//2 = 5 at level 2
    assert out[-1].tail.shape[-1] == 3  # cD_1
    assert out[0].tail.shape[-1] == 5  # cA_2
    assert out[0].core.shape[-1] == 256
    for leaf in out:
        assert len(leaf.core.sharding.device_set) == 8
        # tail stays O(L), never signal-sized
        assert leaf.tail.shape[-1] <= 8


def test_sharded_wavedec_mode_rejects_periodic_and_bad_shapes():
    _need_devices(8)
    mesh = make_mesh({"data": 8})
    with pytest.raises(ValueError, match="ring"):
        sharded_wavedec_mode(mesh, "db2", 1, "periodization")
    with pytest.raises(ValueError, match="divisible"):
        sharded_wavedec_mode(mesh, "db2", 2, "symmetric")(jnp.zeros((8, 24)))
    with pytest.raises(ValueError, match="filter"):
        # level-3 per-shard block = 128/8/4 = 4 < L=6
        sharded_wavedec_mode(mesh, "db3", 3, "symmetric")(jnp.zeros((1, 128)))


def test_sharded_wavedec_mode_bf16_policy():
    _need_devices(8)
    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 512)).astype(jnp.bfloat16)
    out = sharded_wavedec_mode(mesh, "db2", 1, "symmetric")(x)
    assert out[0].core.dtype == jnp.float32
    want = wavedec(x, "db2", 1, "symmetric")
    np.testing.assert_allclose(
        np.asarray(gather_coeffs(out)[0]), np.asarray(want[0]), atol=2e-5
    )


@pytest.mark.parametrize("wavelet,mode", [("haar", "reflect"), ("db4", "reflect"), ("db2", "zero")])
def test_sharded_wavedec2_mode_matches_single_device(wavelet, mode):
    _need_devices(8)
    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 256, 48))
    got = gather_coeffs(sharded_wavedec2_mode(mesh, wavelet, 2, mode)(x), ndim=2)
    want = wavedec2(x, wavelet, 2, mode)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]), atol=2e-5)
    for g, w in zip(got[1:], want[1:]):
        for field in ("horizontal", "vertical", "diagonal"):
            gf, wf = getattr(g, field), getattr(w, field)
            assert gf.shape == wf.shape
            np.testing.assert_allclose(np.asarray(gf), np.asarray(wf), atol=2e-5)


def test_sharded_wavedec2_mode_arbitrary_leading_dims():
    _need_devices(8)
    mesh = make_mesh({"data": 8})
    run = sharded_wavedec2_mode(mesh, "db2", 1, "reflect")
    x4 = jax.random.normal(jax.random.PRNGKey(4), (2, 3, 128, 20))
    got = gather_coeffs(run(x4), ndim=2)
    want = wavedec2(x4, "db2", 1, "reflect")
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]), atol=2e-5)
    x2 = jax.random.normal(jax.random.PRNGKey(5), (128, 20))
    got2 = gather_coeffs(run(x2), ndim=2)
    want2 = wavedec2(x2, "db2", 1, "reflect")
    np.testing.assert_allclose(np.asarray(got2[0]), np.asarray(want2[0]), atol=2e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db3"])
def test_sharded_wavedec3_mode_matches_single_device(wavelet):
    _need_devices(8)
    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 128, 12, 10))
    got = gather_coeffs(sharded_wavedec3_mode(mesh, wavelet, 2, "symmetric")(x), ndim=3)
    want = wavedec3(x, wavelet, 2, "symmetric")
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]), atol=2e-5)
    for g, w in zip(got[1:], want[1:]):
        assert sorted(g) == sorted(w)
        for k in g:
            assert g[k].shape == w[k].shape
            np.testing.assert_allclose(np.asarray(g[k]), np.asarray(w[k]), atol=2e-5)


_scan_gathers = scan_gathers  # shared scanner, tests/conftest.py


def _audit_hlo(run, x, mesh, spec, gather_cap):
    """Compile the builder's jitted body with a sharded input and assert the
    graph moves only O(L)-sized buffers between devices: the ring halo rides
    collective-permute; every all-gather output (tail segments, end slices)
    must stay far below signal/leaf size. A signal-sized all-gather means
    sequence sharding silently degraded to replication — the naive
    with_sharding_constraint formulation does exactly that via the boundary
    pad, and an `_analysis` reshape that merges the sharded axis as a minor
    batch factor does it for batch > 1."""
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, spec)
    xs = jax.device_put(x, sh)
    hlo = run._apply.lower(xs).compile().as_text()
    assert " collective-permute(" in hlo  # the ring halo
    offenders = _scan_gathers(hlo, gather_cap)
    assert not offenders, f"signal-sized all-gather(s) in sharded wavedec HLO: {offenders}"


def test_sharded_wavedec_mode_hlo_no_signal_sized_gather():
    _need_devices(8)
    _need_modern_shard_map("old GSPMD inserts a signal-sized all-gather here")
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"data": 8})
    run = sharded_wavedec_mode(mesh, "db4", 4, "symmetric")
    x = jnp.zeros((2, 1 << 14), jnp.float32)
    run(x)  # eager shape check + end-to-end execution
    _audit_hlo(run, x, mesh, P(None, "data"), gather_cap=512)


def test_sharded_wavedec2_mode_hlo_no_signal_sized_gather():
    """Batch > 1 is the regression trigger: a jit-level `_analysis` on the
    (B, H_sharded, W) core merges the sharded axis as a minor batch factor,
    which GSPMD cannot represent — it replicates the whole signal. The
    local W analysis must therefore run inside shard_map."""
    _need_devices(8)
    _need_modern_shard_map("old GSPMD inserts a signal-sized all-gather here")
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"data": 8})
    run = sharded_wavedec2_mode(mesh, "db4", 3, "reflect")
    x = jnp.zeros((2, 2048, 128), jnp.float32)  # smallest core leaf 11264 elems
    run(x)
    _audit_hlo(run, x, mesh, P(None, "data", None), gather_cap=8192)


def test_sharded_wavedec3_mode_hlo_no_signal_sized_gather():
    _need_devices(8)
    _need_modern_shard_map("old GSPMD inserts a signal-sized all-gather here")
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"data": 8})
    run = sharded_wavedec3_mode(mesh, "db2", 2, "symmetric")
    x = jnp.zeros((2, 512, 16, 16), jnp.float32)  # smallest core leaf 9216 elems
    run(x)
    _audit_hlo(run, x, mesh, P(None, "data", None, None), gather_cap=8192)


@pytest.mark.parametrize("wavelet,mode,level", [
    ("haar", "symmetric", 3), ("db4", "symmetric", 3),
    ("db6", "reflect", 2), ("sym3", "zero", 3), ("db2", "constant", 2),
    # db6 J>=3 regression: without the explicit replicated constraint on the
    # tails, the partitioner sharded a length-6 tail conv over 8 devices
    # (zero-size partitions -> invalid reshape, "failed after
    # spmd-partitioning")
    ("db6", "symmetric", 3),
])
def test_sharded_waverec_mode_matches_single_device(wavelet, mode, level):
    _need_devices(8)
    from wam_tpu.parallel.halo_modes import gather_leaf, sharded_waverec_mode
    from wam_tpu.wavelets.transform import waverec

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 3, 1024))
    coeffs = sharded_wavedec_mode(mesh, wavelet, level, mode)(x)
    rec_leaf = sharded_waverec_mode(mesh, wavelet)(coeffs)
    # the top-level tail is always empty (2*((L-1)//2) - L + 2 == 0 for the
    # even-length filters) and statically-empty tails are OMITTED (None),
    # so the reconstruction is fully evenly sharded
    assert rec_leaf.tail is None
    rec = gather_leaf(rec_leaf)
    want = waverec(gather_coeffs(coeffs), wavelet)
    assert rec.shape == want.shape
    np.testing.assert_allclose(np.asarray(rec), np.asarray(want), atol=2e-5)
    # ...and wavedec->waverec round-trips to the signal itself
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=2e-5)


def test_sharded_coeff_grads_mode_end_to_end():
    """Default-mode long-context loop: sharded decompose -> reconstruct ->
    model -> per-coefficient grads, exact parity with the single-device
    wavedec/waverec pipeline, gradient leaves sharded."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.halo_modes import sharded_coeff_grads_mode
    from wam_tpu.wavelets.transform import wavedec, waverec

    mesh = make_mesh({"data": 8})
    model_fn = toy_wave_model(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 2048))
    y = jnp.array([1, 3])
    got = sharded_coeff_grads_mode(mesh, "db3", 3, model_fn, "symmetric")(x, y)

    def objective(cs):
        out = model_fn(waverec(cs, "db3"))
        return jnp.take_along_axis(out, y[:, None], axis=1).sum()

    want = jax.grad(objective)(wavedec(x, "db3", 3, "symmetric"))
    for g, w in zip(got, want):
        full = jnp.concatenate([g.core, g.tail], axis=-1)
        assert full.shape == w.shape
        assert len(g.core.sharding.device_set) == 8
        np.testing.assert_allclose(np.asarray(full), np.asarray(w), atol=1e-5)

    # representation mode
    got_rep = sharded_coeff_grads_mode(mesh, "db3", 3, model_fn, "symmetric")(x, None)
    want_rep = jax.grad(lambda cs: model_fn(waverec(cs, "db3")).mean())(
        wavedec(x, "db3", 3, "symmetric"))
    for g, w in zip(got_rep, want_rep):
        np.testing.assert_allclose(
            np.asarray(jnp.concatenate([g.core, g.tail], axis=-1)),
            np.asarray(w), atol=1e-5)


def test_sharded_coeff_grads_mode_hlo_no_signal_sized_gather():
    """The full default-mode gradient graph — analysis ring, synthesis ring
    (reversed), model, backward — must move only O(L)-sized buffers plus the
    model's own collectives; the reconstruction feeding the model is evenly
    sharded because the top-level tail is empty."""
    _need_devices(8)
    _need_modern_shard_map("old GSPMD inserts a signal-sized all-gather here")
    from jax.sharding import NamedSharding, PartitionSpec as P
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.halo_modes import sharded_coeff_grads_mode

    mesh = make_mesh({"data": 8})
    step = sharded_coeff_grads_mode(mesh, "db4", 4, toy_wave_model(), "symmetric")
    x = jax.device_put(jnp.zeros((2, 1 << 14), jnp.float32),
                       NamedSharding(mesh, P(None, "data")))
    y = jnp.array([1, 2])
    step(x, y)  # executes
    # audit both dispatches: the decompose half and the grads half
    coeffs = step._dec(x)
    for label, hlo in [
        ("dec", step._dec._apply.lower(x).compile().as_text()),
        ("grads", step._grads.lower(coeffs, y).compile().as_text()),
    ]:
        assert " collective-permute(" in hlo, label
        offenders = _scan_gathers(hlo, 512)
        assert not offenders, f"signal-sized all-gather(s) in {label}: {offenders}"


@pytest.mark.parametrize("wavelet,mode,level", [
    ("haar", "reflect", 2), ("db4", "reflect", 2), ("db2", "zero", 3),
    ("db6", "reflect", 2),
])
def test_sharded_waverec2_mode_matches_single_device(wavelet, mode, level):
    _need_devices(8)
    from wam_tpu.parallel.halo_modes import gather_leaf, sharded_waverec2_mode
    from wam_tpu.wavelets.transform import waverec2

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 256, 48))
    coeffs = sharded_wavedec2_mode(mesh, wavelet, level, mode)(x)
    rec_leaf = sharded_waverec2_mode(mesh, wavelet)(coeffs)
    assert rec_leaf.tail is None  # top-level row tail statically empty
    rec = gather_leaf(rec_leaf, axis=-2)
    want = waverec2(gather_coeffs(coeffs, ndim=2), wavelet)
    assert rec.shape == want.shape
    np.testing.assert_allclose(np.asarray(rec), np.asarray(want), atol=2e-5)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=2e-5)


@pytest.mark.parametrize("wavelet,shape,level", [
    ("haar", (2, 128, 12, 10), 2), ("db3", (2, 128, 12, 10), 2),
    # db2 J=3 at B=1 regression: the tail D-synthesis conv got spatially
    # partitioned into zero-size pieces until the conv was bracketed with
    # replicated constraints on BOTH operand and result sides
    ("db2", (1, 512, 32, 32), 3),
])
def test_sharded_waverec3_mode_matches_single_device(wavelet, shape, level):
    _need_devices(8)
    from wam_tpu.parallel.halo_modes import gather_leaf, sharded_waverec3_mode
    from wam_tpu.wavelets.transform import waverec3

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(10), shape)
    coeffs = sharded_wavedec3_mode(mesh, wavelet, level, "symmetric")(x)
    rec_leaf = sharded_waverec3_mode(mesh, wavelet)(coeffs)
    assert rec_leaf.tail is None  # top-level depth tail statically empty
    rec = gather_leaf(rec_leaf, axis=-3)
    want = waverec3(gather_coeffs(coeffs, ndim=3), wavelet)
    assert rec.shape == want.shape
    np.testing.assert_allclose(np.asarray(rec), np.asarray(want), atol=2e-5)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=2e-5)


def test_sharded_waverec2_mode_hlo_no_signal_sized_gather():
    _need_devices(8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from wam_tpu.parallel.halo_modes import sharded_waverec2_mode

    mesh = make_mesh({"data": 8})
    dec = sharded_wavedec2_mode(mesh, "db4", 3, "reflect")
    rec = sharded_waverec2_mode(mesh, "db4")
    x = jax.device_put(jnp.zeros((2, 2048, 128), jnp.float32),
                       NamedSharding(mesh, P(None, "data", None)))
    coeffs = dec(x)
    rec(coeffs)  # executes
    hlo = rec._apply.lower(coeffs).compile().as_text()
    assert " collective-permute(" in hlo
    offenders = _scan_gathers(hlo, 8192)
    assert not offenders, f"signal-sized all-gather(s) in waverec2: {offenders}"


@pytest.mark.parametrize("ndim,shape,wavelet,level", [
    (2, (2, 128, 24), "db2", 2),
    (3, (2, 128, 12, 10), "db2", 2),
])
def test_sharded_coeff_grads_mode_2d_3d(ndim, shape, wavelet, level):
    """The default-mode end-to-end loop generalizes to image rows and
    volume depth: exact gradient parity with the single-device
    wavedec/waverec pipeline, leaves sharded."""
    _need_devices(8)
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.parallel.halo_modes import sharded_coeff_grads_mode
    from wam_tpu.wavelets import transform as tf

    mesh = make_mesh({"data": 8})
    model_fn = toy_conv_model(jax.random.PRNGKey(0), ndim=ndim)
    x = jax.random.normal(jax.random.PRNGKey(1), shape)
    y = jnp.array([1, 3])
    mode = "symmetric"
    step = sharded_coeff_grads_mode(mesh, wavelet, level, model_fn, mode, ndim=ndim)
    got = step(x, y)

    dec = {2: tf.wavedec2, 3: tf.wavedec3}[ndim]
    rec = {2: tf.waverec2, 3: tf.waverec3}[ndim]

    def objective(cs):
        out = model_fn(rec(cs, wavelet))
        return jnp.take_along_axis(out, y[:, None], axis=1).sum()

    want = jax.grad(objective)(dec(x, wavelet, level, mode))
    got_full = gather_coeffs(got, ndim=ndim)
    want_leaves = jax.tree_util.tree_leaves(want)
    got_leaves = jax.tree_util.tree_leaves(got_full)
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        assert g.shape == w.shape
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)


def test_sharded_waverec3_mode_hlo_no_signal_sized_gather():
    _need_devices(8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from wam_tpu.parallel.halo_modes import sharded_waverec3_mode

    mesh = make_mesh({"data": 8})
    dec = sharded_wavedec3_mode(mesh, "db2", 2, "symmetric")
    rec = sharded_waverec3_mode(mesh, "db2")
    x = jax.device_put(jnp.zeros((2, 512, 16, 16), jnp.float32),
                       NamedSharding(mesh, P(None, "data", None, None)))
    coeffs = dec(x)
    rec(coeffs)  # executes
    hlo = rec._apply.lower(coeffs).compile().as_text()
    assert " collective-permute(" in hlo
    offenders = _scan_gathers(hlo, 8192)
    assert not offenders, f"signal-sized all-gather(s) in waverec3: {offenders}"
