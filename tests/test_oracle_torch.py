"""End-to-end cross-framework attribution oracle (VERDICT.md round-2
missing #1 / next-round #2).

Restates the REFERENCE pipeline semantics — conv-based wavedec2/waverec2
with reflect padding, requires_grad coefficient leaves, diag-logit-mean
backward, and the dyadic gradient mosaic (`lib/wam_2D.py:79-131,200-264`) —
entirely in torch, on weights shared with the Flax model, sharing NO code
with `wam_tpu`'s JAX path. A convention drift anywhere in the chain
(detail-orientation swap, mosaic quadrant layout, normalization order,
padding phase) fails these tests even if wam_tpu stays self-consistent.

Wavelet filter banks are hard-coded from their published values (Daubechies
1988 / pywt's tables) rather than imported, so the oracle also pins
`wam_tpu.wavelets.filters` against an independent source.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow


# -- independent filter constants (pywt's printed db4/haar banks) -----------

SQ2 = 1.0 / np.sqrt(2.0)
HAAR = {
    "dec_lo": [SQ2, SQ2],
    "dec_hi": [-SQ2, SQ2],
    "rec_lo": [SQ2, SQ2],
    "rec_hi": [SQ2, -SQ2],
}
_DB4_DEC_LO = [
    -0.010597401784997278,
    0.032883011666982945,
    0.030841381835986965,
    -0.18703481171888114,
    -0.02798376941698385,
    0.6308807679295904,
    0.7148465705525415,
    0.23037781330885523,
]
DB4 = {
    "dec_lo": _DB4_DEC_LO,
    # orthogonal QMF relations (pywt's sign convention): rec_lo =
    # reverse(dec_lo), dec_hi[k] = (-1)^(k+1) · dec_lo[L-1-k],
    # rec_hi = reverse(dec_hi)
    "rec_lo": _DB4_DEC_LO[::-1],
    "dec_hi": [((-1) ** (k + 1)) * _DB4_DEC_LO[-1 - k] for k in range(8)],
}
DB4["rec_hi"] = DB4["dec_hi"][::-1]
BANKS = {"haar": HAAR, "db4": DB4}


def test_filter_tables_match_independent_constants():
    from wam_tpu.wavelets.filters import build_wavelet

    for name, bank in BANKS.items():
        wav = build_wavelet(name)
        for part in ("dec_lo", "dec_hi", "rec_lo", "rec_hi"):
            np.testing.assert_allclose(
                np.asarray(getattr(wav, part), dtype=np.float64),
                np.asarray(bank[part], dtype=np.float64),
                atol=1e-12,
                err_msg=f"{name}.{part}",
            )


# -- torch restatement of the reference DWT pipeline ------------------------


def _kernels(wavelet: str):
    bank = BANKS[wavelet]
    # analysis: pywt correlates with the REVERSED decomposition filter
    lo = torch.tensor(bank["dec_lo"][::-1], dtype=torch.float32)
    hi = torch.tensor(bank["dec_hi"][::-1], dtype=torch.float32)
    akern = torch.stack([torch.outer(a, b) for a in (lo, hi) for b in (lo, hi)])[
        :, None
    ]  # (4, 1, L, L) — channel order (row, col): aa, ad, da, dd
    rlo = torch.tensor(bank["rec_lo"], dtype=torch.float32)
    rhi = torch.tensor(bank["rec_hi"], dtype=torch.float32)
    skern = torch.stack([torch.outer(a, b) for a in (rlo, rhi) for b in (rlo, rhi)])[
        :, None
    ]
    return akern, skern, len(bank["dec_lo"])


def torch_wavedec2(x, wavelet: str, J: int):
    """ptwt.wavedec2 semantics (reflect mode): per level pad L-1 per side
    with reflect, correlate the flipped filters at stride 2 keeping odd
    phases. x: (B, C, H, W) → [cA, (cH, cV, cD)_J, ..., (cH, cV, cD)_1],
    each (B, C, h, w); shapes list for the inverse."""
    akern, _, L = _kernels(wavelet)
    B, C = x.shape[:2]
    a = x.reshape(B * C, 1, *x.shape[2:])
    details, shapes = [], []
    for _ in range(J):
        shapes.append(a.shape[-2:])
        xp = F.pad(a, (L - 1,) * 4, mode="reflect")[:, :, 1:, 1:]
        c = F.conv2d(xp, akern, stride=2)
        a = c[:, :1]
        h, w = c.shape[-2:]
        # (row, col) channels: 1 = lo-row/hi-col = vertical detail,
        # 2 = hi-row/lo-col = horizontal, 3 = diagonal (pywt cH/cV/cD)
        details.append(
            (
                c[:, 2].reshape(B, C, h, w),
                c[:, 1].reshape(B, C, h, w),
                c[:, 3].reshape(B, C, h, w),
            )
        )
    cA = a[:, 0].reshape(B, C, *a.shape[-2:])
    return [cA] + details[::-1], shapes[::-1]


def torch_waverec2(coeffs, shapes, wavelet: str):
    """Inverse: conv_transpose2d of the zero-stuffed subbands (true
    convolution), trimming the full convolution by L-2 and cropping each
    level to the recorded analysis input shape."""
    _, skern, L = _kernels(wavelet)
    cA = coeffs[0]
    B, C = cA.shape[:2]
    a = cA.reshape(B * C, 1, *cA.shape[-2:])
    for (cH, cV, cD), hw in zip(coeffs[1:], shapes):
        h, w = cH.shape[-2:]
        a = a[:, :, :h, :w]
        sub = torch.cat(
            [
                a,
                cV.reshape(B * C, 1, h, w),
                cH.reshape(B * C, 1, h, w),
                cD.reshape(B * C, 1, h, w),
            ],
            dim=1,
        )
        a = F.conv_transpose2d(sub, skern, stride=2, padding=L - 2)
        a = a[:, :, : hw[0], : hw[1]]
    return a.reshape(B, C, *a.shape[-2:])


def torch_mosaic(grad_coeffs, normalize: bool = True):
    """`BaseWAM2D.visualize_grad_wam` (`lib/wam_2D.py:200-264`): channel-mean
    → abs → per-block /max; approx top-left, per level (finest i=0):
    diagonal [s:e, s:e], vertical [s:e, :s], horizontal [:s, s:e] with
    s = S/2^{i+1}, e = S/2^i (the reference hard-codes S=224 at :238-239;
    restated with the generic S its formula encodes)."""
    size = 2 * grad_coeffs[-1][0].shape[-1]
    B = grad_coeffs[0].shape[0]
    out = np.zeros((B, size, size), dtype=np.float64)

    def prep(t):
        m = np.abs(np.asarray(t.detach().numpy(), dtype=np.float64).mean(axis=1))
        return m / m.max() if (normalize and m.max() > 0) else m

    approx = prep(grad_coeffs[0])
    out[:, : approx.shape[1], : approx.shape[2]] = approx
    for i, (cH, cV, cD) in enumerate(grad_coeffs[1:][::-1]):
        e = size // (2**i)
        s = size // (2 ** (i + 1))
        b = e - s
        out[:, s:e, s:e] = prep(cD)[:, :b, :b]
        out[:, s:e, :s] = prep(cV)[:, :b, :s]
        out[:, :s, s:e] = prep(cH)[:, :s, :b]
    return out


def torch_wam2d(tmodel, x, y, wavelet: str, J: int):
    """The full reference single pass (`lib/wam_2D.py:79-131`): decompose,
    require grads on every coefficient leaf, reconstruct, forward,
    diag-logit-mean backward, mosaic of the coefficient gradients."""
    coeffs, shapes = torch_wavedec2(x, wavelet, J)
    leaves = [coeffs[0].detach().requires_grad_(True)]
    for (cH, cV, cD) in coeffs[1:]:
        leaves.append(
            (
                cH.detach().requires_grad_(True),
                cV.detach().requires_grad_(True),
                cD.detach().requires_grad_(True),
            )
        )
    rec = torch_waverec2(leaves, shapes, wavelet)
    out = tmodel(rec)
    loss = torch.diag(out[:, y]).mean()
    loss.backward()
    grads = [leaves[0].grad] + [
        (h.grad, v.grad, d.grad) for (h, v, d) in leaves[1:]
    ]
    return torch_mosaic(grads), rec


# -- shared-weights fixtures ------------------------------------------------


@pytest.fixture(scope="module")
def shared_resnet():
    from tests.torch_ref_models import TorchResNet18
    from wam_tpu.models import bind_inference, resnet18, torch_resnet_to_flax

    torch.manual_seed(7)
    tmodel = TorchResNet18(num_classes=10).eval()
    variables = torch_resnet_to_flax(tmodel.state_dict())
    fmodel = resnet18(num_classes=10)
    model_fn = bind_inference(fmodel, variables, nchw=True)
    return tmodel, model_fn


@pytest.mark.slow
@pytest.mark.parametrize("wavelet,J", [("haar", 2), ("db4", 2)])
def test_wam2d_mosaic_matches_torch_reference(shared_resnet, wavelet, J):
    """Base-pass mosaic parity torch↔JAX on shared ResNet-18 weights."""
    from wam_tpu.wam2d import BaseWAM2D

    tmodel, model_fn = shared_resnet
    rng = np.random.default_rng(31)
    x = rng.standard_normal((2, 3, 64, 64)).astype(np.float32)
    y = np.array([3, 7])

    wam = BaseWAM2D(model_fn, wavelet=wavelet, J=J, mode="reflect")
    ours = np.asarray(wam(jnp.asarray(x), jnp.asarray(y)), dtype=np.float64)

    theirs, rec = torch_wam2d(tmodel, torch.tensor(x), torch.tensor(y), wavelet, J)

    # the reconstruction must be a faithful inverse in both frameworks
    np.testing.assert_allclose(rec.detach().numpy(), x, atol=1e-4)
    assert ours.shape == theirs.shape
    np.testing.assert_allclose(ours, theirs, atol=1e-4)


@pytest.mark.slow
def test_wam2d_mosaic_matches_torch_reference_at_224(shared_resnet):
    """The production geometry — 224², db4, J=3 (BASELINE.json north star).
    Pins padding phase, mosaic quadrant arithmetic, and normalization at the
    exact flagship size (the reference hard-codes 224 in its mosaic; this is
    the one size where its formula and the generic one must agree
    everywhere). Tolerance 2e-3: at this depth/size ~0.2% of cells differ by
    up to ~8e-4 from f32 accumulation-order drift between XLA and torch —
    far below the O(1) whole-quadrant error any convention fault produces."""
    from wam_tpu.wam2d import BaseWAM2D

    tmodel, model_fn = shared_resnet
    rng = np.random.default_rng(37)
    x = rng.standard_normal((2, 3, 224, 224)).astype(np.float32)
    y = np.array([2, 9])

    wam = BaseWAM2D(model_fn, wavelet="db4", J=3, mode="reflect")
    ours = np.asarray(wam(jnp.asarray(x), jnp.asarray(y)), dtype=np.float64)
    theirs, rec = torch_wam2d(tmodel, torch.tensor(x), torch.tensor(y), "db4", 3)
    np.testing.assert_allclose(rec.detach().numpy(), x, atol=1e-4)
    assert ours.shape == theirs.shape
    np.testing.assert_allclose(ours, theirs, atol=2e-3)


@pytest.mark.slow
def test_wam2d_smoothgrad_step_matches_torch_reference(shared_resnet):
    """One SmoothGrad step with FIXED injected noise (not RNG-matched): the
    reference's per-image σ = spread·(max−min) noisy pass
    (`lib/wam_2D.py:379-415`) run through both pipelines."""
    from wam_tpu.wam2d import BaseWAM2D

    tmodel, model_fn = shared_resnet
    rng = np.random.default_rng(33)
    x = rng.standard_normal((2, 3, 64, 64)).astype(np.float32)
    y = np.array([1, 5])
    noise = rng.standard_normal(x.shape).astype(np.float32)
    sigma = 0.25 * (x.max(axis=(1, 2, 3)) - x.min(axis=(1, 2, 3)))
    noisy = x + noise * sigma[:, None, None, None]

    wam = BaseWAM2D(model_fn, wavelet="db4", J=2, mode="reflect")
    ours = np.asarray(wam(jnp.asarray(noisy), jnp.asarray(y)), dtype=np.float64)
    theirs, _ = torch_wam2d(tmodel, torch.tensor(noisy), torch.tensor(y), "db4", 2)
    np.testing.assert_allclose(ours, theirs, atol=1e-4)


# -- 1D melspec-tap oracle (`lib/wam_1D.py:88-150`) -------------------------


def _np_mel_fbank(n_freqs, n_mels, sr):
    """HTK triangular filterbank, written independently from the formula
    (torchaudio defaults: f_min=0, f_max=sr/2, no norm)."""
    def hz2mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel2hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    freqs = np.linspace(0.0, sr / 2.0, n_freqs)
    pts = mel2hz(np.linspace(hz2mel(0.0), hz2mel(sr / 2.0), n_mels + 2))
    fb = np.zeros((n_freqs, n_mels))
    for m in range(n_mels):
        rising = (freqs - pts[m]) / (pts[m + 1] - pts[m])
        falling = (pts[m + 2] - freqs) / (pts[m + 2] - pts[m + 1])
        fb[:, m] = np.maximum(0.0, np.minimum(rising, falling))
    return fb.astype(np.float32)


def torch_melspec_db(wave, sr, n_fft, n_mels):
    """torchaudio ``MelSpectrogram`` defaults + ``AmplitudeToDB('power')``
    restated (`lib/wam_1D.py:194-219`): hop = n_fft//2, centered reflect
    pad, periodic Hann, |rfft|², HTK fbank, 10·log10(max(x, 1e-10)).
    Returns (N, T, n_mels), time-major like the reference's transpose."""
    hop = n_fft // 2
    x = F.pad(wave[:, None], (n_fft // 2, n_fft // 2), mode="reflect")[:, 0]
    frames = x.unfold(-1, n_fft, hop)  # (N, T, n_fft)
    win = torch.hann_window(n_fft, periodic=True, dtype=wave.dtype)
    spec = torch.fft.rfft(frames * win, dim=-1)
    power = spec.real**2 + spec.imag**2
    fb = torch.tensor(_np_mel_fbank(n_fft // 2 + 1, n_mels, sr), dtype=wave.dtype)
    mel = power @ fb
    return 10.0 * torch.log10(torch.clamp(mel, min=1e-10))


def torch_wavedec1(x, wavelet, J):
    bank = BANKS[wavelet]
    L = len(bank["dec_lo"])
    akern = torch.stack(
        [
            torch.tensor(bank["dec_lo"][::-1], dtype=torch.float32),
            torch.tensor(bank["dec_hi"][::-1], dtype=torch.float32),
        ]
    )[:, None]
    a = x[:, None]  # (N, 1, W)
    details, lengths = [], []
    for _ in range(J):
        lengths.append(a.shape[-1])
        xp = F.pad(a, (L - 1, L - 1), mode="reflect")[:, :, 1:]
        c = F.conv1d(xp, akern, stride=2)
        a = c[:, :1]
        details.append(c[:, 1])
    return [a[:, 0]] + details[::-1], lengths[::-1]


def torch_waverec1(coeffs, lengths, wavelet):
    bank = BANKS[wavelet]
    L = len(bank["rec_lo"])
    skern = torch.stack(
        [
            torch.tensor(bank["rec_lo"], dtype=torch.float32),
            torch.tensor(bank["rec_hi"], dtype=torch.float32),
        ]
    )[:, None]
    a = coeffs[0]
    for d, n in zip(coeffs[1:], lengths):
        a = a[..., : d.shape[-1]]
        sub = torch.stack([a, d], dim=1)  # (N, 2, len)
        a = F.conv_transpose1d(sub, skern, stride=2, padding=L - 2)[:, 0]
        a = a[..., :n]
    return a


class _TorchAudioNet(torch.nn.Module):
    """Tiny melspec classifier used on both sides with shared weights."""

    def __init__(self, n_classes=4):
        super().__init__()
        self.conv = torch.nn.Conv2d(1, 6, 3, stride=2, padding=1)
        self.fc = torch.nn.Linear(6, n_classes)

    def forward(self, mel):  # (N, 1, T, M)
        h = torch.relu(self.conv(mel))
        return self.fc(h.mean(dim=(2, 3)))


@pytest.mark.slow
def test_wam1d_melspec_tap_matches_torch_reference():
    """The 1D pipeline (`lib/wam_1D.py:88-150`): wavedec → requires_grad
    leaves → waverec → melspec (retain_grad tap) → diag-logit-mean backward.
    Compares BOTH gradient families (melspec tap and every coefficient
    level) across frameworks on shared weights."""
    import flax.linen as nn

    from wam_tpu.wam1d import BaseWAM1D

    torch.manual_seed(11)
    tnet = _TorchAudioNet().eval()

    class FlaxAudioNet(nn.Module):
        @nn.compact
        def __call__(self, mel):  # (N, 1, T, M) NCHW-style like the torch net
            x = jnp.transpose(mel, (0, 2, 3, 1))
            x = nn.Conv(6, (3, 3), strides=(2, 2), padding=1, name="conv")(x)
            x = nn.relu(x).mean(axis=(1, 2))
            return nn.Dense(4, name="fc")(x)

    params = {
        "conv": {
            "kernel": jnp.asarray(
                tnet.conv.weight.detach().numpy().transpose(2, 3, 1, 0)
            ),
            "bias": jnp.asarray(tnet.conv.bias.detach().numpy()),
        },
        "fc": {
            "kernel": jnp.asarray(tnet.fc.weight.detach().numpy().T),
            "bias": jnp.asarray(tnet.fc.bias.detach().numpy()),
        },
    }
    fnet = FlaxAudioNet()
    model_fn = lambda mel: fnet.apply({"params": params}, mel)

    sr, n_fft, n_mels, J = 8000, 256, 32, 2
    rng = np.random.default_rng(41)
    wave = rng.standard_normal((2, 2048)).astype(np.float32)
    wave /= wave.max(axis=-1, keepdims=True)  # pre-normalized on both sides
    y = np.array([1, 3])

    wam = BaseWAM1D(model_fn, wavelet="db4", J=J, mode="reflect",
                    n_mels=n_mels, n_fft=n_fft, sample_rate=sr)
    g_mel, g_coeffs = wam(jnp.asarray(wave), jnp.asarray(y))

    # torch restatement
    coeffs, lengths = torch_wavedec1(torch.tensor(wave), "db4", J)
    leaves = [c.detach().requires_grad_(True) for c in coeffs]
    rec = torch_waverec1(leaves, lengths, "db4")
    mel = torch_melspec_db(rec, sr, n_fft, n_mels)[:, None]  # (N, 1, T, M)
    mel.retain_grad()
    out = tnet(mel)
    loss = torch.diag(out[:, torch.tensor(y)]).mean()
    loss.backward()

    np.testing.assert_allclose(rec.detach().numpy(), wave, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(g_mel), mel.grad[:, 0].numpy(), atol=1e-5
    )
    assert len(g_coeffs) == len(leaves)
    for ours, theirs in zip(g_coeffs, leaves):
        np.testing.assert_allclose(
            np.asarray(ours), theirs.grad.numpy(), atol=1e-5
        )



# -- 3D engine oracle (`lib/wam_3D.py:194-238`) -----------------------------


def _kernels3d(wavelet: str):
    """8 analysis / synthesis outer-product kernels; channel order = binary
    a/d counting over (axis0, axis1, axis2), matching DETAIL3D_KEYS."""
    bank = BANKS[wavelet]
    L = len(bank["dec_lo"])
    lo = torch.tensor(bank["dec_lo"][::-1], dtype=torch.float32)
    hi = torch.tensor(bank["dec_hi"][::-1], dtype=torch.float32)

    def outer3(a, b, c):
        return torch.einsum("i,j,k->ijk", a, b, c)

    akern = torch.stack([
        outer3(hi if (code >> 2) & 1 else lo,
               hi if (code >> 1) & 1 else lo,
               hi if code & 1 else lo)
        for code in range(8)
    ])[:, None]  # (8, 1, L, L, L)
    rlo = torch.tensor(bank["rec_lo"], dtype=torch.float32)
    rhi = torch.tensor(bank["rec_hi"], dtype=torch.float32)
    skern = torch.stack([
        outer3(rhi if (code >> 2) & 1 else rlo,
               rhi if (code >> 1) & 1 else rlo,
               rhi if code & 1 else rlo)
        for code in range(8)
    ])[:, None]  # (in=8 stacked later, 1, L, L, L)
    return akern, skern, L


def torch_wavedec3(x, wavelet, J):
    """x: (B, D, H, W) mono volume → [cA, {aad..ddd}_J, ..., _1]."""
    akern, _, L = _kernels3d(wavelet)
    keys = ("aad", "ada", "add", "daa", "dad", "dda", "ddd")
    a = x[:, None]  # (B, 1, D, H, W)
    details, shapes = [], []
    for _ in range(J):
        shapes.append(a.shape[-3:])
        xp = F.pad(a, (L - 1,) * 6, mode="reflect")[:, :, 1:, 1:, 1:]
        c = F.conv3d(xp, akern, stride=2)
        a = c[:, :1]
        details.append({k: c[:, i + 1] for i, k in enumerate(keys)})
    return [a[:, 0]] + details[::-1], shapes[::-1]


def torch_waverec3(coeffs, shapes, wavelet):
    _, skern, L = _kernels3d(wavelet)
    keys = ("aad", "ada", "add", "daa", "dad", "dda", "ddd")
    a = coeffs[0]
    for det, hw in zip(coeffs[1:], shapes):
        tgt = det["ddd"].shape[-3:]
        a = a[..., : tgt[0], : tgt[1], : tgt[2]]
        sub = torch.stack([a] + [det[k] for k in keys], dim=1)  # (B, 8, ...)
        a = F.conv_transpose3d(sub, skern, stride=2, padding=L - 2)[:, 0]
        a = a[..., : hw[0], : hw[1], : hw[2]]
    return a


@pytest.mark.slow
@pytest.mark.parametrize("wavelet,J", [("haar", 2), ("db4", 1)])
def test_wam3d_coeff_grads_match_torch_reference(wavelet, J):
    """3D engine oracle: decompose → requires_grad leaves → reconstruct →
    shared linear model → diag-logit-mean backward; every subband's
    gradient must match across frameworks (pins the 3D axis order and
    orientation naming end to end)."""
    from wam_tpu.core.engine import WamEngine

    rng = np.random.default_rng(53)
    D = 16
    W = rng.standard_normal((D**3, 4)).astype(np.float32)
    x = rng.standard_normal((2, D, D, D)).astype(np.float32)
    y = np.array([1, 3])

    fn = lambda v: v.reshape(v.shape[0], -1) @ jnp.asarray(W)
    eng = WamEngine(fn, ndim=3, wavelet=wavelet, level=J, mode="reflect")
    _, grads = eng.attribute(jnp.asarray(x), jnp.asarray(y))

    coeffs, shapes = torch_wavedec3(torch.tensor(x), wavelet, J)
    leaves = [coeffs[0].detach().requires_grad_(True)]
    for det in coeffs[1:]:
        leaves.append({k: v.detach().requires_grad_(True) for k, v in det.items()})
    rec = torch_waverec3(leaves, shapes, wavelet)
    np.testing.assert_allclose(rec.detach().numpy(), x, atol=1e-4)
    out = rec.reshape(rec.shape[0], -1) @ torch.tensor(W)
    loss = torch.diag(out[:, torch.tensor(y)]).mean()
    loss.backward()

    np.testing.assert_allclose(
        np.asarray(grads[0]), leaves[0].grad.numpy(), atol=1e-5
    )
    for ours_det, theirs_det in zip(grads[1:], leaves[1:]):
        for k in ("aad", "ada", "add", "daa", "dad", "dda", "ddd"):
            np.testing.assert_allclose(
                np.asarray(ours_det[k]), theirs_det[k].grad.numpy(),
                atol=1e-5, err_msg=f"subband {k}",
            )


# -- round-4: full IoU / variance experiment parity (VERDICT r3 #3) ---------
#
# The reference's only published quantitative results are the cross-wavelet
# IoU table (results/iou.csv, produced by compare_iou_models.ipynb cells
# 2+5-6) and the per-level variance shares (results_variance.csv, utils.py).
# The real weights/images are unavailable here (zero egress), but the
# PIPELINE can be validated: restate the whole experiment in torch on the
# shared-weights ResNet-18 and fixed random images, run wam_tpu's
# `analysis` pipeline on the same inputs, and require the output rows to
# match.


def torch_wam2d_ig(tmodel, x, y, wavelet, J, n_steps):
    """Reference integrated-gradients WAM (`lib/wam_2D.py:417-459`):
    baseline mosaic of the input coefficients × np.trapz over the α-path of
    gradient mosaics (trapezoid with dx=1, NOT normalized by n-1)."""
    coeffs, shapes = torch_wavedec2(x, wavelet, J)
    baseline = torch_mosaic(
        [coeffs[0].detach()] + [tuple(t.detach() for t in lvl) for lvl in coeffs[1:]],
        normalize=True,
    )
    path = []
    for alpha in np.linspace(0.0, 1.0, n_steps):
        a = float(alpha)
        leaves = [(coeffs[0] * a).detach().requires_grad_(True)]
        for (cH, cV, cD) in coeffs[1:]:
            leaves.append(
                tuple((t * a).detach().requires_grad_(True) for t in (cH, cV, cD))
            )
        rec = torch_waverec2(leaves, shapes, wavelet)
        out = tmodel(rec)
        loss = torch.diag(out[:, y]).mean()
        loss.backward()
        grads = [leaves[0].grad] + [
            (h.grad, v.grad, d.grad) for (h, v, d) in leaves[1:]
        ]
        path.append(torch_mosaic(grads))
    integral = np.trapz(np.stack(path, axis=1), axis=1)
    return baseline * integral  # (B, S, S)


def torch_reprojection_map(mosaic, J, out_size):
    """Notebook cell 2 `get_grad_reprojection`: reference `reproject_wam`
    (cv2.INTER_LINEAR == half-pixel bilinear == F.interpolate
    align_corners=False) summed over orientations, then MEAN over levels."""
    S = mosaic.shape[-1]

    def up(block):
        t = torch.tensor(block, dtype=torch.float64)[None, None]
        return F.interpolate(t, size=(out_size, out_size), mode="bilinear",
                             align_corners=False)[0, 0].numpy()

    levels = []
    for j in range(J):
        e, s = S // (2**j), S // (2 ** (j + 1))
        levels.append(
            up(mosaic[s:e, s:e]) + up(mosaic[s:e, :s]) + up(mosaic[:s, s:e])
        )
    return np.mean(np.stack(levels), axis=0)


@pytest.mark.slow
def test_iou_experiment_pipeline_matches_torch(shared_resnet):
    """compare_iou_models.ipynb cells 2+5-6 end to end on shared weights:
    per-percentage mean cross-wavelet IoU rows must match between the torch
    restatement and wam_tpu.analysis.cross_wavelet_* (the iou.csv
    producer)."""
    from wam_tpu.analysis import (
        cross_wavelet_reprojection_maps,
        iou_from_reprojection_maps,
        mean_pairwise_iou,
        top_percentage_mask,
    )
    from wam_tpu.wam2d import WaveletAttribution2D

    tmodel, model_fn = shared_resnet
    J, n_steps = 3, 6
    wavelets = ["haar", "db4"]
    rng = np.random.default_rng(41)
    images = [rng.standard_normal((1, 3, 64, 64)).astype(np.float32) for _ in range(2)]
    percentages = [0.05, 0.1, 0.2, 0.3, 0.5]

    def make_explainer(wave):
        return WaveletAttribution2D(
            model_fn, wavelet=wave, J=J, method="integratedgrad",
            n_samples=n_steps, mode="reflect",
        )

    ours_maps, theirs_maps = [], []
    for img in images:
        ours_maps.append(
            cross_wavelet_reprojection_maps(
                img, make_explainer, wavelets, model_fn,
                preprocess=lambda t: jnp.asarray(t), J=J,
            )
        )
        tx = torch.tensor(img)
        ty = int(tmodel(tx).argmax())
        t_maps = []
        for wave in wavelets:
            mosaic = torch_wam2d_ig(tmodel, tx, torch.tensor([ty]), wave, J, n_steps)[0]
            mosaic = mosaic[:64, :64]  # reference hard-crop to image size
            t_maps.append(torch_reprojection_map(mosaic, J, 64))
        theirs_maps.append(t_maps)

    # the reprojection maps themselves must agree cross-framework
    for om, tm in zip(ours_maps, theirs_maps):
        for a, b in zip(om, tm):
            assert a.shape == b.shape
            np.testing.assert_allclose(a, b, atol=2e-3)

    # and therefore the published-experiment rows (mean IoU per percentage)
    for p in percentages:
        ours_row = float(np.mean([iou_from_reprojection_maps(m, p) for m in ours_maps]))
        theirs_row = float(np.mean([
            mean_pairwise_iou([top_percentage_mask(m, p) for m in tm])
            for tm in theirs_maps
        ]))
        assert abs(ours_row - theirs_row) < 0.02, (p, ours_row, theirs_row)


@pytest.mark.slow
def test_variance_experiment_pipeline_matches_torch(shared_resnet):
    """utils.py:45-110 (get_mean_pixelwise_variance + rank_images) and the
    per-level attribution shares (utils.py:112-151) on both frameworks'
    base-pass mosaics from shared weights: values, shares, and the image
    RANKING must agree."""
    from wam_tpu.analysis import (
        get_gradients_attribution_on_levels,
        get_mean_pixelwise_variance,
        rank_images,
    )
    from wam_tpu.wam2d import BaseWAM2D

    tmodel, model_fn = shared_resnet
    J = 3
    rng = np.random.default_rng(43)
    x = rng.standard_normal((3, 3, 64, 64)).astype(np.float32)
    y = np.array([1, 5, 8])

    wam = BaseWAM2D(model_fn, wavelet="haar", J=J, mode="reflect")
    ours = np.asarray(wam(jnp.asarray(x), jnp.asarray(y)), dtype=np.float64)
    theirs, _ = torch_wam2d(tmodel, torch.tensor(x), torch.tensor(y), "haar", J)
    np.testing.assert_allclose(ours, theirs, atol=1e-4)

    # torch-side restatement of the variance analysis, scipy zoom like the
    # reference (utils.py:74-78)
    from scipy.ndimage import zoom

    def t_variance(mosaic):
        S = mosaic.shape[0]
        details = []
        for j in range(J):
            e, s = S // (2**j), S // (2 ** (j + 1))
            details.append(mosaic[s:e, s:e])
        target = max(d.shape[0] for d in details)
        stack = np.stack([
            zoom(d.astype(np.float64), target / d.shape[0], order=1)[:target, :target]
            for d in details
        ])
        return float(stack.var(axis=0).mean())

    for i in range(3):
        v_ours = get_mean_pixelwise_variance(ours[i], J)[0]
        v_theirs = t_variance(theirs[i])
        np.testing.assert_allclose(v_ours, v_theirs, rtol=1e-6)

    rank_ours = [r["image_index"] for r in rank_images(list(ours), J)]
    rank_theirs = np.argsort([-t_variance(m) for m in theirs]).tolist()
    assert rank_ours == rank_theirs

    # per-level attribution shares (results_variance.csv rows)
    shares_ours = get_gradients_attribution_on_levels(list(ours), J)
    for i in range(3):
        S = theirs[i].shape[0]
        diag_sums = []
        for j in range(J):
            e, s = S // (2**j), S // (2 ** (j + 1))
            diag_sums.append(np.abs(theirs[i][s:e, s:e]).sum())
        diag_sums.append(np.abs(theirs[i][: S // 2**J, : S // 2**J]).sum())
        shares_theirs = np.asarray(diag_sums) / np.sum(diag_sums)
        np.testing.assert_allclose(shares_ours[i], shares_theirs, atol=1e-6)
