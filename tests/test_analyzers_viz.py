"""Analyzer + viewer + fork-analytics tests."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import matplotlib

matplotlib.use("Agg")
import numpy as np
import pytest

from wam_tpu.analyzers import (
    WAMAnalyzer2D,
    compute_levelized_masks,
    generate_disentangled_images,
    generate_partial_image,
)
from wam_tpu.analysis import (
    get_diagonal,
    get_gradients_attribution_on_levels,
    get_mean_across_images,
    get_mean_pixelwise_variance,
    iou,
    mean_pairwise_iou,
    rank_images,
    reprojection_map,
    top_percentage_mask,
)


def test_levelized_masks_partition():
    """The level masks partition the mosaic: their sum recovers it."""
    wam = jnp.asarray(np.random.default_rng(0).random((16, 16)), dtype=jnp.float32)
    masks = compute_levelized_masks(wam, J=2)
    assert masks.shape == (3, 16, 16)
    np.testing.assert_allclose(np.asarray(masks.sum(axis=0)), np.asarray(wam), atol=1e-6)
    # disjoint supports
    support = (np.asarray(masks) != 0).astype(int).sum(axis=0)
    assert support.max() <= 1


def test_generate_partial_image_full_quantile():
    """q=0 keeps every coefficient -> reconstruction equals the image."""
    img = jnp.asarray(np.random.default_rng(1).random((3, 16, 16)), dtype=jnp.float32)
    wam = jnp.asarray(np.random.default_rng(2).random((16, 16)), dtype=jnp.float32)
    rec, filtered = generate_partial_image(img, wam, q=0.0, J=2)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(img), atol=1e-5)
    assert filtered.shape == (16, 16)


def test_generate_disentangled_images_shapes():
    img = jnp.asarray(np.random.default_rng(3).random((3, 16, 16)), dtype=jnp.float32)
    wam = jnp.asarray(np.random.default_rng(4).random((16, 16)), dtype=jnp.float32)
    partial, masks = generate_disentangled_images(wam, img, J=2, EPS=0.1)
    assert partial.shape == (3, 3, 16, 16)
    assert masks.shape == (3, 16, 16)


class TinyImg(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = jnp.transpose(x, (0, 2, 3, 1))
        x = nn.relu(nn.Conv(8, (3, 3), strides=(2, 2))(x)).mean(axis=(1, 2))
        return nn.Dense(5)(x)


@pytest.fixture(scope="module")
def model_fn():
    m = TinyImg()
    p = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    return lambda x: m.apply(p, x)


@pytest.mark.slow
def test_analyzer_necessary_components(model_fn):
    from wam_tpu.wam2d import WaveletAttribution2D

    expl = WaveletAttribution2D(model_fn, wavelet="haar", J=2, n_samples=2)
    an = WAMAnalyzer2D(model_fn, expl, wavelet="haar", J=2)
    x = jnp.asarray(np.random.default_rng(5).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = [0, 1]
    outs = an.isolate_necessary_components(x, y, qs=[0.9, 0.5, 0.0], mode="insertion")
    assert len(outs) == 2
    for (imgs, mask, wam, (probs, idx)) in outs:
        assert wam.shape == (32, 32)
        if imgs[0] is not None:
            assert probs.shape == (3, 5)
    scales = an.isolate_scales(x, y, EPS=0.05)
    assert len(scales) == 2
    assert scales[0][0].shape == (3, 3, 32, 32)


def test_fork_analytics_roundtrip():
    wam = np.random.default_rng(6).random((32, 32))
    d = get_diagonal(wam, 3)
    assert set(d) == {"level_0", "level_1", "level_2", "approx"}
    assert d["level_0"].shape == (16, 16)
    assert d["approx"].shape == (4, 4)

    mv, vmap_ = get_mean_pixelwise_variance(wam, 3)
    assert vmap_.shape == (16, 16) and mv >= 0
    mv_min, vmap_min = get_mean_pixelwise_variance(wam, 3, size="minimal")
    assert vmap_min.shape == (4, 4)

    ranking = rank_images([wam, wam * 2], 3)
    assert ranking[0]["mean_pixelwise_variance"] >= ranking[1]["mean_pixelwise_variance"]

    shares = get_gradients_attribution_on_levels([wam], 3)
    np.testing.assert_allclose(shares[0].sum(), 1.0, atol=1e-6)
    means = get_mean_across_images([shares])
    assert means[0].shape == (4,)


def test_iou_helpers():
    m1 = np.zeros((8, 8), bool)
    m1[:4] = True
    m2 = np.zeros((8, 8), bool)
    m2[2:6] = True
    np.testing.assert_allclose(iou(m1, m2), 16 / 48)
    assert mean_pairwise_iou([m1, m1]) == 1.0

    a = np.arange(16.0).reshape(4, 4)
    mask = top_percentage_mask(a, 0.25)
    assert mask.sum() == 4
    assert mask[-1, -1]


def test_reprojection_map():
    wam = np.random.default_rng(7).random((16, 16)).astype(np.float32)
    m = reprojection_map(wam, J=2)
    assert m.shape == (16, 16)


def test_viewers_render():
    import matplotlib.pyplot as plt

    from wam_tpu.viz import (
        plot_diagonal,
        plot_wam,
        visualize_explanations_basic,
        visualize_gradients_at_levels,
    )

    wam = np.random.default_rng(8).random((32, 32))
    fig, ax = plt.subplots()
    plot_wam(ax, wam, levels=3, smooth=True, normalize_approx=True)
    assert len(ax.lines) == 6  # 2 lines per level
    plt.close(fig)

    fig2 = plot_diagonal(get_diagonal(wam, 2))
    plt.close(fig2)

    figs = visualize_explanations_basic([wam], [np.random.random((32, 32, 3))], levels=3)
    for f in figs:
        plt.close(f)

    f = visualize_gradients_at_levels([[0.4, 0.3, 0.2, 0.1]], "test", names=["m"])
    plt.close(f)


def test_viz3d_render():
    import matplotlib.pyplot as plt

    from wam_tpu.viz import (
        scatter3d,
        scatter3d_batch,
        scatter3d_colors,
        scatter3d_explanation_batch,
        scatter3d_superpose,
        voxel_figure,
        voxel_superpose,
    )

    rng = np.random.default_rng(9)
    cloud = rng.standard_normal((3, 50))
    ax, _ = scatter3d(cloud)
    plt.close(ax.figure)
    fig = scatter3d_batch([cloud, cloud], titles=["a", "b"])
    plt.close(fig)
    fig = scatter3d_superpose(cloud, cloud + 1)
    plt.close(fig)
    fig = scatter3d_colors(cloud, rng.random(50))
    plt.close(fig)
    fig = scatter3d_explanation_batch([cloud], [rng.random(50)])
    plt.close(fig)

    vol = (rng.random((8, 8, 8)) > 0.7).astype(float)
    fig = voxel_figure(vol)
    plt.close(fig)
    fig = voxel_superpose(vol, rng.random((8, 8, 8)), heat_threshold=0.8)
    plt.close(fig)


def test_voxel_surface_mesh_invariants():
    """Exposed-face extraction (`src/utils_viz3D.py:331-456` restated
    vectorized): a lone voxel is a closed cube — 6 faces, 24 verts, 12
    triangles; two adjacent voxels share an interior face pair — 10 faces;
    winding is outward; intensity carries the voxel value."""
    from wam_tpu.viz import voxel_surface_mesh

    vol = np.zeros((4, 4, 4))
    vol[1, 2, 1] = 7.0
    v, t, inten = voxel_surface_mesh(vol)
    assert v.shape == (24, 3) and t.shape == (12, 3)
    assert np.all(inten == 7.0)
    # every vertex is a corner of the occupied cell
    assert v.min(0).tolist() == [1, 2, 1] and v.max(0).tolist() == [2, 3, 2]
    # outward winding: signed volume of the closed surface = +1 voxel
    a, b, c = v[t[:, 0]], v[t[:, 1]], v[t[:, 2]]
    signed = np.sum(np.einsum("ij,ij->i", a, np.cross(b, c))) / 6.0
    assert np.isclose(signed, 1.0)

    vol2 = np.zeros((4, 4, 4))
    vol2[1, 1, 1] = 1.0
    vol2[2, 1, 1] = 2.0  # +x neighbor: the shared face pair is interior
    v2, t2, i2 = voxel_surface_mesh(vol2)
    assert v2.shape == (40, 3) and t2.shape == (20, 3)  # 10 exposed faces
    assert set(np.unique(i2)) == {1.0, 2.0}
    # triangles index valid vertices
    assert t2.min() >= 0 and t2.max() < len(v2)

    # empty volume -> empty mesh, consistent shapes
    v0, t0, i0 = voxel_surface_mesh(np.zeros((3, 3, 3)))
    assert v0.shape == (0, 3) and t0.shape == (0, 3) and i0.shape == (0,)


def test_plotly_functions_gate_cleanly():
    """Without plotly installed the plotly entry points must raise a clear
    ImportError (not AttributeError — the round-3 phantom-API finding)."""
    import wam_tpu.viz.viz3d as v3

    rng = np.random.default_rng(3)
    vol = (rng.random((4, 4, 4)) > 0.6).astype(float)
    for call in (
        lambda: v3.scatter3d_plotly(rng.standard_normal((3, 10))),
        lambda: v3.voxels_plotly(vol),
        lambda: v3.voxel_superpose_plotly(vol, rng.random((4, 4, 4))),
    ):
        if v3.HAS_PLOTLY:
            call()  # real figure construction must not raise
        else:
            with pytest.raises(ImportError):
                call()


def test_plot_wavelet_regions_reference_shape():
    """Reference-shaped (h, v) dicts (`src/viewers.py:39-63`): level 0 spans
    the full mosaic at size/2; each subsequent level halves the coordinates."""
    from wam_tpu.viz.viewers import plot_wavelet_regions

    h, v = plot_wavelet_regions(64, 3)
    assert set(h) == set(v) == {0, 1, 2}
    np.testing.assert_array_equal(h[0], [[0, 32], [64, 32]])
    np.testing.assert_array_equal(v[0], [[32, 64], [32, 0]])
    np.testing.assert_array_equal(h[1], h[0] // 2)
    np.testing.assert_array_equal(v[2], v[0] // 4)


def test_srd_exclusion_is_explicit():
    from wam_tpu.evalsuite.eval_baselines import EvalImageBaselines

    with pytest.raises(NotImplementedError, match="lib.srd"):
        EvalImageBaselines(None, {}, method="srd")
