"""Schedule autotuner + fused backward kernels (`wam_tpu/tune/`).

Covers the round-6 tentpole end to end on CPU:

- schedule cache round-trip, stale-version invalidation, env kill switch;
- chunk-override plumbing: a tuned entry steers
  `core.estimators.resolve_sample_chunk("auto")`, the 2D class API, the
  sharded sequence estimator, and the serve warmup path;
- fused ReLU-VJP parity (values AND gradients) vs `jax.nn.relu` for the
  portable "xla" impl and the Pallas kernels under interpret mode — the
  kernel *code path* regression-tested without a TPU;
- attribution parity of `bind_inference(fused_relu_vjp=True)` — the gate
  that must hold before the flag may default on;
- μ-fidelity fused single-upload draws match the pre-fusion per-tensor
  construction bit for bit;
- the autotuner's toy dry-run (measure + pick a winner, no persistence).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.tune import (
    SCHEDULE_CACHE_VERSION,
    ScheduleCache,
    invalidate_process_cache,
    load_schedule_cache,
    lookup_schedule,
    record_schedule,
    resolve_fan_cap,
    schedule_key,
)
from wam_tpu.tune.fused_relu import (
    fused_relu,
    pack_mask,
    set_fused_relu_impl,
    unpack_mask,
)


@pytest.fixture
def sched_cache(tmp_path, monkeypatch):
    """Isolated user-layer schedule cache: env-pointed file + fresh process
    singleton, restored after the test."""
    path = tmp_path / "schedules.json"
    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE", str(path))
    monkeypatch.delenv("WAM_TPU_NO_SCHEDULE_CACHE", raising=False)
    invalidate_process_cache()
    yield path
    invalidate_process_cache()


# -- cache round-trip and versioning ------------------------------------------


def test_schedule_key_canonical_form():
    key = schedule_key("wam2d", (3, 224, 224), 32, "bf16", "pallas", "tpu")
    assert key == "wam2d|3x224x224|b32|bf16|pallas|tpu"
    assert schedule_key("eval2d", (), 128, "f32", "conv", "cpu").startswith(
        "eval2d|-|b128|"
    )


def test_cache_round_trip(sched_cache):
    key = record_schedule(
        "wam2d", (3, 64, 64), 8,
        {"sample_chunk": 16, "stream_noise": True, "items_per_s": 101.5},
        dtype="f32", dwt_impl="conv", backend="cpu",
    )
    assert sched_cache.exists()
    # a FRESH process (singleton dropped) reads the same entry back
    invalidate_process_cache()
    ent = lookup_schedule("wam2d", (3, 64, 64), 8, "f32", "conv", "cpu")
    assert ent == {"sample_chunk": 16, "stream_noise": True, "items_per_s": 101.5}
    assert load_schedule_cache().get(key) == ent
    # the file carries the schema version
    data = json.loads(sched_cache.read_text())
    assert data["version"] == SCHEDULE_CACHE_VERSION
    assert key in data["schedules"]


def test_stale_version_file_is_ignored_wholesale(sched_cache):
    key = schedule_key("wam2d", (3, 64, 64), 8, "f32", "conv", "cpu")
    sched_cache.write_text(json.dumps({
        "version": SCHEDULE_CACHE_VERSION + 1,
        "schedules": {key: {"sample_chunk": 999}},
    }))
    invalidate_process_cache()
    cache = load_schedule_cache()
    assert str(sched_cache) in cache.stale_files
    assert lookup_schedule("wam2d", (3, 64, 64), 8, "f32", "conv", "cpu") is None
    # the next save overwrites the stale file with the current schema
    record_schedule("wam2d", (3, 64, 64), 8, {"sample_chunk": 4},
                    dtype="f32", dwt_impl="conv", backend="cpu")
    assert json.loads(sched_cache.read_text())["version"] == SCHEDULE_CACHE_VERSION


def test_corrupt_file_is_ignored(sched_cache):
    sched_cache.write_text("{not json")
    invalidate_process_cache()
    assert lookup_schedule("nope", (1,), 1) is None  # no raise


def test_kill_switch_disables_lookup(sched_cache, monkeypatch):
    record_schedule("wam2d", (3, 64, 64), 8, {"sample_chunk": 16},
                    dtype="f32", dwt_impl="conv", backend="cpu")
    monkeypatch.setenv("WAM_TPU_NO_SCHEDULE_CACHE", "1")
    assert lookup_schedule("wam2d", (3, 64, 64), 8, "f32", "conv", "cpu") is None


def test_pinned_defaults_overlaid_by_user_entry(sched_cache):
    # the repo ships the benched flagship schedule
    key = "wam2d|3x224x224|b32|bf16|pallas|tpu"
    cache = load_schedule_cache()
    pinned = cache.get(key)
    assert pinned is not None and pinned["sample_chunk"] == 4
    # a tuned user entry for the same key wins after reload
    record_schedule("wam2d", (3, 224, 224), 32, {"sample_chunk": 8},
                    dtype="bf16", dwt_impl="pallas", backend="tpu")
    invalidate_process_cache()
    assert load_schedule_cache().get(key)["sample_chunk"] == 8
    # save() wrote ONLY the diff vs pinned
    data = json.loads(sched_cache.read_text())
    assert list(data["schedules"]) == [key]


def test_resolve_fan_cap(sched_cache):
    assert resolve_fan_cap(64, 129) == 64  # ints pass through
    assert resolve_fan_cap("auto", 129) == 128  # no entry: default
    record_schedule("eval2d", (129,), 129, {"fan_cap": 256})
    assert resolve_fan_cap("auto", 129) == 256


# -- chunk-override plumbing --------------------------------------------------


def test_resolve_sample_chunk_prefers_tuned_entry(sched_cache):
    from wam_tpu.core.estimators import resolve_sample_chunk

    # no entry: CPU "auto" keeps the legacy full-vmap behavior
    assert resolve_sample_chunk("auto", 8, 25, workload="wam2d",
                                shape=(3, 64, 64)) is None
    record_schedule("wam2d", (3, 64, 64), 8, {"sample_chunk": 16},
                    dtype="f32", backend=jax.default_backend())
    got = resolve_sample_chunk("auto", 8, 25, workload="wam2d",
                               shape=(3, 64, 64))
    assert got == 16
    # explicit values still pass through untouched
    assert resolve_sample_chunk(5, 8, 25, workload="wam2d",
                                shape=(3, 64, 64)) == 5
    # tuned chunk >= n_samples collapses to full vmap (the law's convention)
    assert resolve_sample_chunk("auto", 8, 3, workload="wam2d",
                                shape=(3, 64, 64)) is None


def test_wam2d_resolves_tuned_chunk_and_stream(sched_cache):
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.wam2d import WaveletAttribution2D

    record_schedule("wam2d", (1, 8, 8), 2,
                    {"sample_chunk": 2, "stream_noise": True},
                    dtype="f32", backend=jax.default_backend())
    toy = toy_conv_model(jax.random.PRNGKey(0), ndim=2)
    wam = WaveletAttribution2D(lambda x: toy(x.mean(axis=1)),
                               wavelet="haar", J=1, n_samples=4)
    assert wam._resolve_chunk((2, 1, 8, 8)) == 2
    assert wam._resolve_stream((2, 1, 8, 8)) is True
    # attributions still come back under the tuned schedule
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 1, 8, 8))
    out = wam(x, np.asarray([0, 1]))
    assert out.shape == (2, 8, 8) and bool(jnp.isfinite(out).all())


def test_seq_sharded_resolves_tuned_chunk(sched_cache):
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    record_schedule("wamseq2d", (8, 8), 4, {"sample_chunk": 3},
                    dtype="f32", backend=jax.default_backend())
    x = jnp.zeros((4, 8, 8))
    sw = SeqShardedWam.__new__(SeqShardedWam)  # scheduling needs only ndim
    sw.ndim = 2
    assert sw._resolve_seq_chunk("auto", x, 8) == 3
    sw.ndim = 1  # no entry for wamseq1d: sequential default
    assert sw._resolve_seq_chunk("auto", x, 8) == 1
    assert sw._resolve_seq_chunk(2, x, 8) == 2  # explicit passes through


def test_seq_sharded_resolves_tuned_fused(sched_cache):
    """``fused="auto"`` reads the ``seq_fused`` key of the same schedule
    entry the chunk resolver uses; no entry (or an entry without the key)
    defaults to the one-jit step."""
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    record_schedule("wamseq1d", (2048,), 2,
                    {"sample_chunk": 2, "seq_fused": False},
                    dtype="f32", backend=jax.default_backend())
    x = jnp.zeros((2, 2048))
    sw = SeqShardedWam.__new__(SeqShardedWam)
    sw.ndim = 1
    sw.fused = "auto"
    assert sw._resolve_fused(x) is False  # the tuned split-loop verdict
    sw.ndim = 2  # no wamseq2d entry: fused default
    assert sw._resolve_fused(x) is True
    sw.fused = True  # explicit wins over the cache
    sw.ndim = 1
    assert sw._resolve_fused(x) is True


def test_serve_warmup_loads_schedule_cache(sched_cache):
    """`AttributionServer.start()` must load the schedule cache BEFORE the
    bucket warmup compiles, so tuned chunks are visible to the first trace
    (serve/runtime.py round-6 wiring)."""
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.serve import AttributionServer
    from wam_tpu.tune import cache as tcache
    from wam_tpu.wam2d import BaseWAM2D

    invalidate_process_cache()
    assert tcache._process_cache is None
    toy = toy_conv_model(jax.random.PRNGKey(0), ndim=2)
    wam = BaseWAM2D(lambda x: toy(x.mean(axis=1)), J=1)
    server = AttributionServer(wam.serve_entry(), [(1, 8, 8)], max_batch=2,
                               warmup=True)
    try:
        assert tcache._process_cache is not None
    finally:
        server.close()


# -- fused ReLU-VJP -----------------------------------------------------------


def test_pack_unpack_round_trip():
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 128))
    gate = unpack_mask(pack_mask(x))
    np.testing.assert_array_equal(np.asarray(gate), np.asarray(x) > 0)


@pytest.fixture(params=["xla", "pallas_interpret"])
def relu_impl(request):
    set_fused_relu_impl(request.param)
    yield request.param
    set_fused_relu_impl("auto")


def test_fused_relu_matches_jax_nn_relu(relu_impl):
    # odd, non-tile-aligned shape exercises the pad/unpad seam; explicit
    # zeros pin the subgradient-at-0 convention (gate x > 0, like
    # jax.nn.relu — NOT jnp.maximum's 0.5/0.5 tie split)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 3, 17, 19))
    x = x.at[0, 0, 0, :5].set(0.0)

    np.testing.assert_array_equal(np.asarray(fused_relu(x)),
                                  np.asarray(jax.nn.relu(x)))

    g = jax.random.normal(jax.random.PRNGKey(4), x.shape)
    ref = jax.grad(lambda a: (jax.nn.relu(a) * g).sum())(x)
    got = jax.grad(lambda a: (fused_relu(a) * g).sum())(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_fused_relu_bf16_grads(relu_impl):
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 37), jnp.bfloat16)
    ref = jax.grad(lambda a: jax.nn.relu(a).astype(jnp.float32).sum())(x)
    got = jax.grad(lambda a: fused_relu(a).astype(jnp.float32).sum())(x)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(got, np.float32),
                                  np.asarray(ref, np.float32))


def test_fused_relu_impl_validation():
    with pytest.raises(ValueError):
        set_fused_relu_impl("cuda")


def test_bind_inference_fused_relu_attribution_parity(relu_impl):
    """The gate for fused_relu_vjp=True: input-gradient attributions of the
    bound model must match the stock binding exactly (same values, same
    gate), on a real residual network."""
    from wam_tpu.models import bind_inference, resnet18

    model = resnet18(num_classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 32, 32))
    y = jnp.asarray([1, 3])

    def saliency(fn):
        def loss(a):
            return jnp.take_along_axis(fn(a), y[:, None], axis=1).sum()
        return jax.grad(loss)(x)

    ref = saliency(bind_inference(model, variables, nchw=True))
    got = saliency(bind_inference(model, variables, nchw=True,
                                  fused_relu_vjp=True))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    cos = float(
        (got * ref).sum()
        / (jnp.linalg.norm(got.ravel()) * jnp.linalg.norm(ref.ravel()))
    )
    assert cos > 0.9999


def test_bind_inference_fused_relu_requires_act_attr():
    from wam_tpu.models import bind_inference

    class NoAct:
        pass

    with pytest.raises(ValueError, match="act"):
        bind_inference(NoAct(), {}, fused_relu_vjp=True)


# -- fused μ-fidelity draws ---------------------------------------------------


def test_mu_fidelity_draws_fusion_matches_reference():
    """The single-upload (B, 2, S, g²) fusion must reproduce the exact
    per-tensor draws (same rng call order) the evaluators consumed before
    round 6."""
    from wam_tpu.evalsuite.metrics import mu_fidelity_draws

    seed, B, g, S, subset = 7, 2, 4, 6, 5
    rand, onehot = mu_fidelity_draws({}, seed, B, g, S, subset,
                                     with_rand_masks=True)
    assert rand.shape == (B, S, g, g)
    assert onehot.shape == (B, S, g * g)

    rng = np.random.default_rng(seed)
    for b in range(B):
        ref_rand = rng.uniform(size=(S, g, g)).astype(np.float32)
        subsets = np.stack([rng.choice(g * g, size=subset, replace=False)
                            for _ in range(S)])
        ref_onehot = np.zeros((S, g * g), dtype=np.float32)
        np.put_along_axis(ref_onehot, subsets, 1.0, axis=1)
        np.testing.assert_array_equal(np.asarray(rand[b]), ref_rand)
        np.testing.assert_array_equal(np.asarray(onehot[b]), ref_onehot)
    assert np.all(np.asarray(onehot).sum(axis=-1) == subset)

    # the cache returns the same device buffers without redrawing
    cache = {}
    first = mu_fidelity_draws(cache, seed, B, g, S, subset, with_rand_masks=True)
    again = mu_fidelity_draws(cache, seed, B, g, S, subset, with_rand_masks=True)
    assert first[0] is again[0] and first[1] is again[1]


# -- autotuner ----------------------------------------------------------------


def test_chunk_candidates_ladder():
    from wam_tpu.tune.autotuner import chunk_candidates

    cands = chunk_candidates(32, 25)
    # 128/256/512-row targets at b32 → chunks 4, 8, 16, plus full vmap
    assert cands == [4, 8, 16, None]
    assert chunk_candidates(4, 3) == [None]  # every target >= n_samples


def test_autotune_toy_dry_run(sched_cache):
    """The CI smoke the verify skill runs: measure the toy candidate set on
    CPU, crown a winner, persist nothing."""
    from wam_tpu.tune.autotuner import autotune
    from wam_tpu.tune.workloads import get_workload

    out = autotune(get_workload("toy"), k=1, laps=1, persist=False)
    assert out["persisted"] is False
    assert not sched_cache.exists()
    assert out["key"].startswith("wam2d_toy|32x32|b4|f32|")
    ent = out["entry"]
    assert ent["sample_chunk"] is None or ent["sample_chunk"] >= 1
    assert ent["items_per_s"] > 0
    assert ent["plane"] in ("device", "wall")
    assert len(out["results"]) >= 2
    # a dry run must leave the live schedule untouched
    assert load_schedule_cache().get(out["key"]) is None


def test_autotune_wamseq1d_dry_run(sched_cache):
    """The seq-sharded preset sweeps sample_chunk × fused-vs-split with
    explicit knobs and crowns a winner whose entry carries ``seq_fused`` —
    the key `SeqShardedWam._resolve_fused("auto")` reads back."""
    from conftest import need_devices
    from wam_tpu.tune.autotuner import autotune
    from wam_tpu.tune.workloads import get_workload

    need_devices(2)
    wl = get_workload("wamseq1d", n_samples=2, length=1024)
    labels = [c.label() for c in wl.candidates]
    assert any("fused" in l for l in labels)
    assert any("split" in l for l in labels)
    out = autotune(wl, k=1, laps=1, persist=False)
    assert out["key"].startswith("wamseq1d|1024|b2|f32|")
    assert out["entry"]["seq_fused"] in (True, False)
    assert out["entry"]["items_per_s"] > 0
    assert load_schedule_cache().get(out["key"]) is None
