"""Model zoo tests: ResNet forward shapes, sow taps, and torch→flax
checkpoint ingestion with logit parity against an independent torch
implementation (SURVEY.md §7.2 'validate by logit parity')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.models import bind_inference, resnet18, resnet50, torch_resnet_to_flax

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



def test_resnet18_forward_shape():
    model = resnet18(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    out = model.apply(variables, jnp.zeros((2, 64, 64, 3)))
    assert out.shape == (2, 10)


def test_resnet50_forward_shape():
    model = resnet50(num_classes=7)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    out = model.apply(variables, jnp.zeros((1, 64, 64, 3)))
    assert out.shape == (1, 7)


def test_resnet_intermediate_taps():
    model = resnet18(num_classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    out, state = model.apply(variables, jnp.zeros((1, 64, 64, 3)), mutable=["intermediates"])
    inter = state["intermediates"]
    assert set(inter) == {"stage1", "stage2", "stage3", "stage4"}
    assert inter["stage4"][0].shape[-1] == 512


def test_bind_inference_nchw():
    model = resnet18(num_classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    fn = bind_inference(model, variables, nchw=True)
    out = fn(jnp.zeros((2, 3, 32, 32)))
    assert out.shape == (2, 4)


def test_bind_inference_compute_dtype_bf16():
    model = resnet18(num_classes=4)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 32, 32), jnp.float32)
    ref = bind_inference(model, variables, nchw=True)(x)
    out = bind_inference(model, variables, nchw=True, compute_dtype=jnp.bfloat16)(x)
    assert out.dtype == jnp.float32
    assert out.shape == ref.shape
    # bf16 fwd tracks the f32 logits to bf16 resolution
    scale = float(jnp.abs(ref).max())
    assert float(jnp.abs(out - ref).max()) < 0.1 * max(scale, 1.0)
    # gradients flow through the cast boundary
    g = jax.grad(lambda xx: bind_inference(
        model, variables, nchw=True, compute_dtype=jnp.bfloat16
    )(xx).sum())(x)
    assert g.dtype == jnp.float32
    assert bool(jnp.isfinite(g).all())


def test_torch_ingestion_logit_parity():
    """Random-init torch ResNet-18 → converted Flax weights must reproduce
    torch logits to float32 tolerance on random input."""
    torch = pytest.importorskip("torch")
    from tests.torch_ref_models import TorchResNet18

    tmodel = TorchResNet18(num_classes=13).eval()
    # randomize BN stats so parity actually exercises them
    with torch.no_grad():
        for m in tmodel.modules():
            if isinstance(m, torch.nn.BatchNorm2d):
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.5, 1.5)

    variables = torch_resnet_to_flax(tmodel.state_dict())
    variables = jax.tree_util.tree_map(jnp.asarray, variables)

    model = resnet18(num_classes=13)
    x = np.random.default_rng(0).standard_normal((2, 3, 96, 96)).astype(np.float32)
    with torch.no_grad():
        t_out = tmodel(torch.from_numpy(x)).numpy()
    f_out = model.apply(variables, jnp.transpose(jnp.asarray(x), (0, 2, 3, 1)))
    np.testing.assert_allclose(np.asarray(f_out), t_out, atol=2e-4, rtol=2e-4)


def test_dataparallel_prefix_stripping():
    torch = pytest.importorskip("torch")
    from tests.torch_ref_models import TorchResNet18

    tmodel = TorchResNet18(num_classes=3).eval()
    prefixed = {f"module.{k}": v for k, v in tmodel.state_dict().items()}
    variables = torch_resnet_to_flax(prefixed)
    assert "conv1" in variables["params"]


def test_torch_vit_ingestion_logit_parity():
    torch = pytest.importorskip("torch")
    from tests.torch_ref_models import TorchTinyViT
    from wam_tpu.models.ingest import torch_vit_to_flax
    from wam_tpu.models.vit import ViT

    torch.manual_seed(0)
    tmodel = TorchTinyViT(num_classes=7, img=32, patch=8, dim=64, depth=2, heads=4, mlp=128).eval()
    variables = jax.tree_util.tree_map(
        jnp.asarray, torch_vit_to_flax(tmodel.state_dict(), num_heads=4)
    )
    model = ViT(num_classes=7, patch=8, dim=64, depth=2, heads=4, mlp_hidden=128)
    x = np.random.default_rng(1).standard_normal((2, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        t_out = tmodel(torch.from_numpy(x)).numpy()
    f_out = model.apply(variables, jnp.transpose(jnp.asarray(x), (0, 2, 3, 1)))
    np.testing.assert_allclose(np.asarray(f_out), t_out, atol=2e-4, rtol=2e-4)


def test_torch_convnext_ingestion_logit_parity():
    torch = pytest.importorskip("torch")
    from tests.torch_ref_models import TorchTinyConvNeXt
    from wam_tpu.models.convnext import ConvNeXt
    from wam_tpu.models.ingest import torch_convnext_to_flax

    torch.manual_seed(0)
    tmodel = TorchTinyConvNeXt(num_classes=5, depths=(1, 1), dims=(16, 32)).eval()
    # randomize layer scales so the gamma path is actually exercised
    with torch.no_grad():
        for m in tmodel.modules():
            if hasattr(m, "layer_scale"):
                m.layer_scale.uniform_(0.5, 1.5)
    variables = jax.tree_util.tree_map(
        jnp.asarray, torch_convnext_to_flax(tmodel.state_dict())
    )
    model = ConvNeXt(num_classes=5, depths=(1, 1), dims=(16, 32))
    x = np.random.default_rng(2).standard_normal((2, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        t_out = tmodel(torch.from_numpy(x)).numpy()
    f_out = model.apply(variables, jnp.transpose(jnp.asarray(x), (0, 2, 3, 1)))
    np.testing.assert_allclose(np.asarray(f_out), t_out, atol=2e-4, rtol=2e-4)


def _nontrivial_stats(variables, seed=3):
    """Mildly perturbed running stats so BN folding / rewrites are exercised
    with non-identity affines but ReLUs stay alive."""
    import zlib

    import jax.random as jr

    def perturb(path, a):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        k = jr.fold_in(jr.PRNGKey(seed), zlib.crc32(str(path).encode()) % 2**31)
        if name == "mean":
            return jr.normal(k, a.shape) * 0.05
        return jr.uniform(k, a.shape) * 0.8 + 0.6

    stats = jax.tree_util.tree_map_with_path(perturb, variables["batch_stats"])
    return dict(variables, batch_stats=stats)


def test_fold_bn_preserves_function_and_gradient():
    """BN-folded binding (models/resnet.py:_fold_bn_variables) is a pure
    reparameterization: logits and input gradients match the unfolded model
    to float rounding."""
    model = resnet18(num_classes=10)
    variables = _nontrivial_stats(model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 3, 32, 32))
    from wam_tpu.models.resnet import _fold_bn_variables

    folded = _fold_bn_variables(variables)
    # Guard against the fold silently matching nothing (naming drift): the
    # folded BN scales must all be exactly one and conv kernels must change.
    assert all(
        bool(jnp.all(v["scale"] == 1.0))
        for k, v in folded["params"].items()
        if k.startswith("bn")
    )
    assert not bool(
        jnp.array_equal(folded["params"]["conv1"]["kernel"], variables["params"]["conv1"]["kernel"])
    )
    f0 = bind_inference(model, variables, nchw=True)
    f1 = bind_inference(model, variables, nchw=True, fold_bn=True)
    l0, l1 = f0(x), f1(x)
    assert float(jnp.abs(l0).max()) > 0.1
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), atol=2e-5, rtol=2e-5)
    g0 = jax.grad(lambda t: f0(t).sum())(x)
    g1 = jax.grad(lambda t: f1(t).sum())(x)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1), atol=2e-5, rtol=2e-5)


def test_fold_bn_biased_convs_audio():
    """fold_bn on a BIASED conv stack (AudioCNN's b{N}_bn ↔ b{N}_conv
    naming): the conv bias must ride the BN scale too — round 5 found the
    fold dropping the a·c term (invisible on the bias-free vision ResNets)."""
    from wam_tpu.models.audio import AudioCNN, bind_audio_inference

    model = AudioCNN(num_classes=7)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1, 128, 128)))
    # non-trivial stats AND biases so the a·c term is exercised
    variables = jax.tree_util.tree_map(
        lambda a: a + 0.05 * jax.random.normal(jax.random.PRNGKey(1), a.shape)
        if a.ndim else a,
        variables,
    )
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 1, 128, 128))
    f0 = bind_audio_inference(model, variables)
    f1 = bind_audio_inference(model, variables, fold_bn=True)
    np.testing.assert_allclose(np.asarray(f0(x)), np.asarray(f1(x)),
                               atol=2e-5, rtol=2e-5)
    g0 = jax.grad(lambda t: f0(t).sum())(x)
    g1 = jax.grad(lambda t: f1(t).sum())(x)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                               atol=2e-5, rtol=2e-5)


def test_stem_s2d_preserves_function_and_gradient():
    """Space-to-depth stem (models/resnet.py:_StemConv) computes the same
    function from the same (7,7,C,64) parameters."""
    from wam_tpu.models.resnet import resnet18 as rn18

    m0 = rn18(num_classes=10)
    m1 = rn18(num_classes=10, stem_s2d=True)
    variables = m0.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 32, 32))
    l0 = bind_inference(m0, variables, nchw=True)(x)
    l1 = bind_inference(m1, variables, nchw=True)(x)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), atol=2e-5, rtol=2e-5)
    g0 = jax.grad(lambda t: bind_inference(m0, variables, nchw=True)(t).sum())(x)
    g1 = jax.grad(lambda t: bind_inference(m1, variables, nchw=True)(t).sum())(x)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1), atol=2e-5, rtol=2e-5)


def test_stem_s2d_odd_size_falls_back():
    from wam_tpu.models.resnet import resnet18 as rn18

    m1 = rn18(num_classes=5, stem_s2d=True)
    variables = m1.init(jax.random.PRNGKey(0), jnp.zeros((1, 33, 33, 3)))
    out = bind_inference(m1, variables, nchw=True)(jnp.zeros((2, 3, 33, 33)))
    assert out.shape == (2, 5)
