"""Compile-artifact registry (`wam_tpu.registry`): publish → hydrate
round-trips, the silent-miss ladder (torn manifest → stale schema →
platform fingerprint → per-artifact digest), the `WAM_TPU_NO_REGISTRY`
kill switch, schedule-snapshot merge semantics (local wins), the CLI
exit-code gates, and the serve-stack wiring — a cold-cache server and a
supervised fleet restart both warming from a bundle at ZERO compiles,
sentinel-verified.

Every test isolates the three cache layers through their env overrides
(`WAM_TPU_AOT_CACHE` / `WAM_TPU_SCHEDULE_CACHE` / `WAM_TPU_CACHE_DIR`) so
nothing touches ~/.cache. Runs on the virtual 8-device CPU mesh the
conftest forces."""

import json
import os
import shutil
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import need_devices
from wam_tpu import obs
from wam_tpu.obs import sentinel
from wam_tpu.pipeline import aot as aot_cache
from wam_tpu.registry import (
    REGISTRY_SCHEMA_VERSION,
    RegistryClient,
    publish_bundle,
    resolve_client,
)
from wam_tpu.registry import __main__ as registry_cli
from wam_tpu.tune.cache import SCHEDULE_CACHE_VERSION, ScheduleCache

_ARGS = (jnp.arange(4, dtype=jnp.float32),)


def _seed_aot(key, cache_dir):
    """Export one real executable under ``key`` (the publisher side)."""
    fn = aot_cache.cached_jit(lambda x: x * 2.0 + 1.0, _ARGS, key,
                              cache_dir=str(cache_dir))
    jax.block_until_ready(fn(*_ARGS))
    payload, header = aot_cache.read_aot_payload(key, str(cache_dir))
    assert payload is not None and header["origin"] == "exported"
    return payload


def _aot_seq0():
    rows = sentinel.aot_events()
    return rows[-1]["seq"] if rows else 0


def _edit_manifest(bundle, mutate):
    path = os.path.join(str(bundle), "manifest.json")
    with open(path) as f:
        doc = json.load(f)
    mutate(doc)
    with open(path, "w") as f:
        json.dump(doc, f)


# -- publish → hydrate round-trip ---------------------------------------------


def test_publish_hydrate_roundtrip(tmp_path):
    """A bundle published from one machine's caches seeds another's: the
    AOT payload lands byte-identical under origin "registry", the XLA
    cache file copies in by name, and a later consult serves the
    executable with ZERO traces, attributed as a registry_hit."""
    pub, tgt = tmp_path / "pub", tmp_path / "tgt"
    payload = _seed_aot("rt-key", pub)
    xla_pub, xla_tgt = tmp_path / "xla_pub", tmp_path / "xla_tgt"
    os.makedirs(xla_pub / "shard")
    (xla_pub / "shard" / "mod.bin").write_bytes(b"fake-xla-executable")

    manifest = publish_bundle(str(tmp_path / "bundle"), aot_dir=str(pub),
                              xla_dir=str(xla_pub),
                              schedule_path=str(tmp_path / "none.json"))
    kinds = sorted(a["kind"] for a in manifest["artifacts"])
    assert kinds == ["aot", "xla"]
    assert all(len(a["sha256"]) == 64 for a in manifest["artifacts"])

    report = RegistryClient(str(tmp_path / "bundle")).hydrate(
        aot_dir=str(tgt), schedule_path=str(tmp_path / "sched.json"),
        xla_dir=str(xla_tgt))
    assert report.status == "hydrated"
    assert report.count("aot", "hydrated") == 1
    assert report.count("xla", "hydrated") == 1
    assert report.hydrated == 2
    got, header = aot_cache.read_aot_payload("rt-key", str(tgt))
    assert got == payload  # pure serialization round-trips bit-exact
    assert header["origin"] == "registry"
    assert (xla_tgt / "shard" / "mod.bin").read_bytes() == b"fake-xla-executable"

    seq0 = _aot_seq0()
    with sentinel.assert_no_retrace():
        fn = aot_cache.cached_jit(lambda x: x * 2.0 + 1.0, _ARGS, "rt-key",
                                  cache_dir=str(tgt))
        out = np.asarray(fn(*_ARGS))
    np.testing.assert_allclose(out, np.arange(4) * 2.0 + 1.0)
    events = [(e["aot_event"], e["key"])
              for e in sentinel.aot_events(since_seq=seq0)]
    assert ("registry_hit", "rt-key") in events

    # ledger row shape: the serve close path writes exactly this dict
    row = report.row()
    assert row["metric"] == "registry_hydration"
    assert row["schema_version"] == 2
    assert row["hydrated"] == 2


def test_hydrate_is_idempotent_local_wins(tmp_path):
    """Re-hydrating over a warm cache rewrites nothing — valid local
    entries count as "present" (the supervisor-restart path calls hydrate
    on every rebuild, so it must be free when the disk is already warm)."""
    pub = tmp_path / "pub"
    _seed_aot("idem-key", pub)
    bundle = str(tmp_path / "bundle")
    publish_bundle(bundle, aot_dir=str(pub), include_xla=False)

    tgt = tmp_path / "tgt"
    kw = dict(aot_dir=str(tgt), schedule_path=str(tmp_path / "s.json"))
    assert RegistryClient(bundle).hydrate(**kw).count("aot", "hydrated") == 1
    entry_path = aot_cache.aot_entry_path("idem-key", str(tgt))
    mtime = os.path.getmtime(entry_path)
    again = RegistryClient(bundle).hydrate(**kw)
    assert again.count("aot", "present") == 1
    assert again.count("aot", "hydrated") == 0
    assert os.path.getmtime(entry_path) == mtime


# -- the silent-miss ladder ---------------------------------------------------


def test_corrupt_artifact_is_per_artifact_miss(tmp_path):
    """One flipped payload loses ONE artifact (digest_mismatch + a
    registry_miss sentinel event); the rest of the bundle still hydrates."""
    pub = tmp_path / "pub"
    _seed_aot("good-key", pub)
    _seed_aot("bad-key", pub)
    bundle = str(tmp_path / "bundle")
    manifest = publish_bundle(bundle, aot_dir=str(pub), include_xla=False)
    bad = next(a for a in manifest["artifacts"] if a["key"] == "bad-key")
    with open(os.path.join(bundle, bad["file"]), "wb") as f:
        f.write(b"bitrot")

    seq0 = _aot_seq0()
    report = RegistryClient(bundle).hydrate(
        aot_dir=str(tmp_path / "tgt"),
        schedule_path=str(tmp_path / "s.json"))
    assert report.status == "hydrated"  # partial hydration is still a win
    assert report.count("aot", "hydrated") == 1
    assert report.count("aot", "digest_mismatch") == 1
    events = [(e["aot_event"], e["key"])
              for e in sentinel.aot_events(since_seq=seq0)]
    assert ("registry_miss", "bad-key") in events
    payload, _ = aot_cache.read_aot_payload("bad-key", str(tmp_path / "tgt"))
    assert payload is None  # the corrupt artifact was never seeded


def test_manifest_digest_tamper_rejected(tmp_path):
    """A manifest whose recorded sha256 disagrees with the (intact)
    payload is equally a per-artifact miss — the digest binds both ways."""
    pub = tmp_path / "pub"
    _seed_aot("tamper-key", pub)
    bundle = str(tmp_path / "bundle")
    publish_bundle(bundle, aot_dir=str(pub), include_xla=False)
    _edit_manifest(bundle, lambda d: d["artifacts"][0].update(
        sha256="0" * 64))
    report = RegistryClient(bundle).hydrate(
        aot_dir=str(tmp_path / "tgt"),
        schedule_path=str(tmp_path / "s.json"))
    assert report.count("aot", "digest_mismatch") == 1
    assert report.hydrated == 0


def test_torn_manifest_is_empty_bundle(tmp_path):
    """Half a JSON document (a torn publish) reads as no bundle at all."""
    bundle = tmp_path / "bundle"
    os.makedirs(bundle)
    (bundle / "manifest.json").write_text('{"registry_schema_version": 1, "art')
    tgt = tmp_path / "tgt"
    report = RegistryClient(str(bundle)).hydrate(
        aot_dir=str(tgt), schedule_path=str(tmp_path / "s.json"))
    assert report.status == "no_manifest"
    assert report.hydrated == 0
    assert not os.path.exists(tgt)  # zero writes
    # absent bundle directory: same terminal status, still no error
    gone = RegistryClient(str(tmp_path / "never-published")).hydrate(
        aot_dir=str(tgt), schedule_path=str(tmp_path / "s.json"))
    assert gone.status == "no_manifest"


def test_stale_schema_and_foreign_platform_skip_wholesale(tmp_path):
    """A manifest from a future registry schema, a different backend, or a
    different AOT cache schema is ignored WHOLESALE — and `probe` stamps
    the wholesale cause on every artifact row (hydratable == 0, the CI
    gate)."""
    pub = tmp_path / "pub"
    _seed_aot("whole-key", pub)
    cases = [
        ("stale_schema",
         lambda d: d.update(registry_schema_version=REGISTRY_SCHEMA_VERSION + 1)),
        ("platform_mismatch",
         lambda d: d["platform"].update(backend="tpu")),
        ("version_mismatch",
         lambda d: d["platform"].update(aot_cache_version=999)),
    ]
    for status, mutate in cases:
        bundle = str(tmp_path / f"bundle-{status}")
        publish_bundle(bundle, aot_dir=str(pub), include_xla=False)
        _edit_manifest(bundle, mutate)
        tgt = tmp_path / f"tgt-{status}"
        report = RegistryClient(bundle).hydrate(
            aot_dir=str(tgt), schedule_path=str(tmp_path / "s.json"))
        assert report.status == status
        assert not os.path.exists(tgt)
        probe = RegistryClient(bundle).probe(aot_dir=str(tgt))
        assert probe["status"] == status
        assert probe["hydratable"] == 0
        assert [r["outcome"] for r in probe["artifacts"]] == [status]


def test_kill_switch_disables_hydrate_not_probe(tmp_path, monkeypatch):
    """WAM_TPU_NO_REGISTRY=1: hydrate is a zero-IO no-op; `probe` (a
    diagnostic) deliberately keeps working."""
    pub = tmp_path / "pub"
    _seed_aot("kill-key", pub)
    bundle = str(tmp_path / "bundle")
    publish_bundle(bundle, aot_dir=str(pub), include_xla=False)
    monkeypatch.setenv("WAM_TPU_NO_REGISTRY", "1")
    tgt = tmp_path / "tgt"
    report = RegistryClient(bundle).hydrate(
        aot_dir=str(tgt), schedule_path=str(tmp_path / "s.json"))
    assert report.status == "disabled"
    assert not os.path.exists(tgt)
    probe = RegistryClient(bundle).probe(aot_dir=str(tgt))
    assert probe["hydratable"] == 1
    monkeypatch.setenv("WAM_TPU_NO_REGISTRY", "0")  # "0" means enabled
    assert RegistryClient(bundle).hydrate(
        aot_dir=str(tgt),
        schedule_path=str(tmp_path / "s.json")).status == "hydrated"


def test_resolve_client_normalizes_the_serve_param(tmp_path):
    assert resolve_client(None) is None
    assert resolve_client("") is None
    client = RegistryClient(str(tmp_path))
    assert resolve_client(client) is client
    made = resolve_client(str(tmp_path / "b"))
    assert isinstance(made, RegistryClient)
    assert made.bundle == str(tmp_path / "b")


# -- schedule snapshot --------------------------------------------------------


def test_schedule_snapshot_merges_under_local(tmp_path):
    """Bundle schedules fill gaps only: a locally-tuned entry for the same
    key survives hydration untouched (local reflects THIS machine), and a
    stale-version snapshot is ignored wholesale."""
    pub_sched = tmp_path / "pub.json"
    cache = ScheduleCache(path=str(pub_sched))
    cache.put("wamtest|published|only", {"sample_chunk": 64})
    cache.put("wamtest|shared|key", {"sample_chunk": 999})
    cache.save()
    bundle = str(tmp_path / "bundle")
    publish_bundle(bundle, aot_dir=str(tmp_path / "no-aot"),
                   schedule_path=str(pub_sched), include_xla=False)

    local_sched = tmp_path / "local.json"
    local = ScheduleCache(path=str(local_sched))
    local.put("wamtest|shared|key", {"sample_chunk": 8})  # locally tuned
    local.save()
    report = RegistryClient(bundle).hydrate(
        aot_dir=str(tmp_path / "tgt"), schedule_path=str(local_sched))
    assert report.schedules_status == "merged"
    assert report.schedules_added == 1  # only the gap
    merged = ScheduleCache(path=str(local_sched))
    assert merged.get("wamtest|shared|key") == {"sample_chunk": 8}
    assert merged.get("wamtest|published|only") == {"sample_chunk": 64}

    # stale snapshot version: ignored wholesale, nothing added
    _edit_manifest(bundle, lambda d: d["schedules"].update(
        version=SCHEDULE_CACHE_VERSION + 1))
    again = RegistryClient(bundle).hydrate(
        aot_dir=str(tmp_path / "tgt2"), schedule_path=str(local_sched))
    assert again.schedules_status == "stale"
    assert again.schedules_added == 0


# -- CLI ----------------------------------------------------------------------


def test_cli_publish_inspect_hydrate_exit_codes(tmp_path, capsys):
    """`python -m wam_tpu.registry`: publish exits 1 on an empty bundle,
    inspect exits 1 when nothing is hydratable (the CI smoke gates), and
    each subcommand prints one JSON document."""
    pub = tmp_path / "pub"
    _seed_aot("cli-key", pub)
    bundle = str(tmp_path / "bundle")
    rc = registry_cli.main(["publish", "--out", bundle,
                            "--aot-dir", str(pub), "--no-xla",
                            "--schedule-cache", str(tmp_path / "s.json")])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["aot"] == 1
    assert doc["platform"]["backend"] == jax.default_backend()

    rc = registry_cli.main(["publish", "--out", str(tmp_path / "empty"),
                            "--aot-dir", str(tmp_path / "no-cache"),
                            "--no-xla", "--no-schedules"])
    assert rc == 1  # nothing to publish
    capsys.readouterr()

    tgt = tmp_path / "tgt"
    assert registry_cli.main(["inspect", bundle,
                              "--aot-dir", str(tgt)]) == 0
    assert json.loads(capsys.readouterr().out)["hydratable"] == 1
    assert registry_cli.main(["inspect", str(tmp_path / "nowhere"),
                              "--aot-dir", str(tgt)]) == 1
    capsys.readouterr()

    rc = registry_cli.main(["hydrate", bundle, "--aot-dir", str(tgt),
                            "--schedule-cache", str(tmp_path / "s2.json"),
                            "--xla-dir", str(tmp_path / "xla")])
    assert rc == 0
    row = json.loads(capsys.readouterr().out)
    assert row["metric"] == "registry_hydration"
    assert row["hydrated"] == 1
    assert aot_cache.read_aot_payload("cli-key", str(tgt))[0] is not None


def test_cli_from_prewarm_filters_keys(tmp_path, capsys):
    """`publish --from-prewarm` snapshots exactly the keys the prewarm
    manifest says it warmed; a legacy manifest without a ``warmed`` block
    contributes nothing (and alone falls back to the full-cache walk)."""
    pub = tmp_path / "pub"
    _seed_aot("warmed-key", pub)
    _seed_aot("other-key", pub)
    warm = tmp_path / "warm.json"
    warm.write_text(json.dumps({
        "config": "toy", "warmed": {
            "bucket_keys": ["wam2d|toy"], "aot_keys": ["warmed-key"],
            "schedule_version": SCHEDULE_CACHE_VERSION,
        }}))
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps({"config": "toy", "aot": "exported"}))

    keys, sources = registry_cli._prewarm_keys([str(warm), str(legacy)])
    assert keys == ["warmed-key"]
    assert len(sources) == 1 and sources[0]["bucket_keys"] == ["wam2d|toy"]
    assert registry_cli._prewarm_keys([str(legacy)]) == (None, [])

    bundle = str(tmp_path / "bundle")
    rc = registry_cli.main(["publish", "--out", bundle, "--aot-dir",
                            str(pub), "--no-xla", "--no-schedules",
                            "--from-prewarm", str(warm), str(legacy)])
    assert rc == 0
    capsys.readouterr()
    from wam_tpu.registry import load_manifest

    manifest = load_manifest(bundle)
    assert [a["key"] for a in manifest["artifacts"]] == ["warmed-key"]
    assert manifest["source"]["prewarm"][0]["prewarm_manifest"] == str(warm)


# -- serve wiring -------------------------------------------------------------


def _toy_wam2d():
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.wam2d import BaseWAM2D

    toy = toy_conv_model(jax.random.PRNGKey(0), ndim=2)
    return BaseWAM2D(lambda x: toy(x.mean(axis=1)), J=2)


def test_server_cold_cache_warms_from_bundle(tmp_path, monkeypatch):
    """The acceptance invariant at the `AttributionServer` level: a server
    whose AOT cache dir is EMPTY but which is handed ``registry=`` warms
    up and serves with zero entry traces, bit-identical to the publisher —
    and its close path lands the ``registry_hydration`` ledger row."""
    from wam_tpu.serve import AttributionServer

    pub = tmp_path / "pub-aot"
    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(pub))
    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE", str(tmp_path / "s.json"))
    wam = _toy_wam2d()
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16)))
    ref = np.asarray(wam(x[None], np.asarray([2])))[0]

    cold = []
    server = AttributionServer(
        wam.serve_entry(on_trace=lambda: cold.append(1), aot_key="reg-serve"),
        [(1, 16, 16)], max_batch=2,
    )
    server.close()
    assert cold == [1]  # publisher warmup exported the executable

    bundle = str(tmp_path / "bundle")
    publish_bundle(bundle, aot_dir=str(pub), include_xla=False,
                   schedule_path=str(tmp_path / "s.json"))
    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(tmp_path / "cold-aot"))

    warm = []
    ledger = str(tmp_path / "serve.jsonl")
    server = AttributionServer(
        wam.serve_entry(on_trace=lambda: warm.append(1), aot_key="reg-serve"),
        [(1, 16, 16)], max_batch=2, metrics_path=ledger, registry=bundle,
    )
    try:
        assert server.registry_report.status == "hydrated"
        assert server.registry_report.hydrated >= 1
        assert server.describe()["registry"] == bundle
        got = server.attribute(x, 2)
    finally:
        server.close()
    assert warm == []  # the bundle, not a compile, paid the warmup
    np.testing.assert_allclose(got, ref, atol=1e-6)
    rows = [json.loads(line) for line in open(ledger)]
    hyd = [r for r in rows if r.get("metric") == "registry_hydration"]
    assert len(hyd) == 1
    assert hyd[0]["status"] == "hydrated"
    assert hyd[0]["schema_version"] == 2

    # a server pointed at garbage still comes up — silent fallback
    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(tmp_path / "cold2-aot"))
    fb = []
    server = AttributionServer(
        wam.serve_entry(on_trace=lambda: fb.append(1), aot_key="reg-serve"),
        [(1, 16, 16)], max_batch=2, registry=str(tmp_path / "not-a-bundle"),
    )
    server.close()
    assert server.registry_report.status == "no_manifest"
    assert fb == [1]  # compiled, exactly as if no bundle had been offered


def test_fleet_restart_rehydrates_from_bundle(tmp_path, monkeypatch):
    """Supervised-restart wiring: a fleet started with ``registry=`` warms
    from the bundle at zero traces, and when a replica dies AND the local
    AOT cache has been wiped underneath it, `_rebuild_replica`'s
    re-hydration re-seeds the cache so the restarted replica STILL rejoins
    at zero post-warm compiles — all under `assert_no_retrace`."""
    need_devices(2)
    from wam_tpu.serve import FleetServer, SupervisorConfig, jit_entry

    obs.configure(enabled=True)
    obs.reset()
    aot_dir = tmp_path / "aot"
    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(aot_dir))
    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE", str(tmp_path / "s.json"))

    kills = {rid: threading.Event() for rid in range(2)}

    def factory(rid, m):
        # deliberately NO process-level jit cache: every (re)build makes a
        # fresh entry, so a warm rejoin can only come from the AOT cache —
        # which, after the rmtree below, only the bundle can refill
        inner = jit_entry(lambda xs, ys: xs * 2.0, on_trace=m.note_compile,
                          aot_key="reg-fleet")

        def entry(xs, ys):
            if kills[rid].is_set():
                kills[rid].clear()  # one death per arm
                raise RuntimeError(f"injected chip loss on {rid}")
            return inner(xs, ys)

        return entry

    seed = FleetServer(factory, [(4,)], replicas=2, max_batch=1,
                       max_wait_ms=0.0, warmup=True, oversize="fanout")
    seed.close()
    bundle = str(tmp_path / "bundle")
    publish_bundle(bundle, aot_dir=str(aot_dir), include_xla=False,
                   schedule_path=str(tmp_path / "s.json"))
    shutil.rmtree(aot_dir)  # the fresh-host stand-in: cold local caches

    sentinel.clear_events()
    x = np.ones((4,), np.float32)
    with sentinel.assert_no_retrace():
        fleet = FleetServer(
            factory, [(4,)], replicas=2, max_batch=1, max_wait_ms=0.0,
            warmup=True, oversize="fanout", registry=bundle,
            supervise=SupervisorConfig(max_restarts=8, window_s=60.0,
                                       backoff_base_s=0.001,
                                       jitter_frac=0.0, seed=0),
        )
        try:
            first_report = fleet.registry_report
            assert first_report.status == "hydrated"
            assert fleet.describe()["registry"] == bundle
            # wipe the hydrated cache: the upcoming rebuild must re-hydrate
            # from the bundle, not find the files the start() hydrate left
            shutil.rmtree(aot_dir)
            kills[0].set()
            deadline = time.monotonic() + 30
            while kills[0].is_set():
                futs = [fleet.submit(x, i % 2) for i in range(4)]
                for f in futs:
                    np.testing.assert_array_equal(f.result(timeout=10),
                                                  x * 2.0)
                assert time.monotonic() < deadline, "kill never reached r0"
            while fleet.registry_report is first_report:
                assert time.monotonic() < deadline, "rebuild never rehydrated"
                time.sleep(0.01)
            for f in [fleet.submit(x, i % 2) for i in range(4)]:
                np.testing.assert_array_equal(f.result(timeout=10), x * 2.0)
        finally:
            fleet.close()
    assert fleet.registry_report.count("aot", "hydrated") >= 1
    events = [e["aot_event"] for e in sentinel.aot_events()]
    assert "registry_hit" in events
    assert "miss" not in events and "export" not in events
