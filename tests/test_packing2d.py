"""Golden-value tests for the 2D mosaic layout and reprojection
(SURVEY.md §4c: mosaic packing layouts must be pinned)."""

import jax.numpy as jnp
import numpy as np

from wam_tpu.ops.packing2d import disentangle_scales, mosaic2d, mosaic_size, reproject_mosaic
from wam_tpu.wavelets import Detail2D


def _const_coeffs(J=2, size=16, batch=1, channels=1):
    """Coefficient pytree with distinct constant values per block so the
    layout can be read off the mosaic."""
    coeffs = []
    n = size // (2**J)
    coeffs.append(jnp.full((batch, channels, n, n), 10.0))  # approx
    for lev in range(J, 0, -1):  # coarsest -> finest, pywt order
        n = size // (2**lev)
        coeffs.append(
            Detail2D(
                horizontal=jnp.full((batch, channels, n, n), float(lev) + 0.1),
                vertical=jnp.full((batch, channels, n, n), float(lev) + 0.2),
                diagonal=jnp.full((batch, channels, n, n), float(lev) + 0.3),
            )
        )
    return coeffs


def test_mosaic_layout_quadrants():
    m = np.asarray(mosaic2d(_const_coeffs(J=2, size=16), normalize=False))[0]
    assert m.shape == (16, 16)
    # approx top-left 4x4
    np.testing.assert_allclose(m[:4, :4], 10.0)
    # level 2 (coarsest): blocks span [4:8]
    np.testing.assert_allclose(m[4:8, 4:8], 2.3)  # diagonal
    np.testing.assert_allclose(m[4:8, :4], 2.2)  # vertical
    np.testing.assert_allclose(m[:4, 4:8], 2.1)  # horizontal
    # level 1 (finest): blocks span [8:16]
    np.testing.assert_allclose(m[8:16, 8:16], 1.3)
    np.testing.assert_allclose(m[8:16, :8], 1.2)
    np.testing.assert_allclose(m[:8, 8:16], 1.1)


def test_mosaic_normalization():
    m = np.asarray(mosaic2d(_const_coeffs(J=1, size=8), normalize=True))[0]
    # each constant block normalized to 1
    np.testing.assert_allclose(m, 1.0)


def test_mosaic_channel_mean_then_abs():
    """Channels averaged before abs: (+1, -1) channels cancel to 0."""
    c = [
        jnp.stack([jnp.ones((1, 2, 2)), -jnp.ones((1, 2, 2))], axis=1)[:, :, 0],
    ]
    # build a 1-level pytree with 2 channels
    approx = jnp.stack([jnp.ones((2, 2)), -jnp.ones((2, 2))])[None]  # (1,2,2,2)
    det = Detail2D(
        horizontal=jnp.ones((1, 2, 2, 2)),
        vertical=jnp.ones((1, 2, 2, 2)),
        diagonal=jnp.ones((1, 2, 2, 2)),
    )
    m = np.asarray(mosaic2d([approx, det], normalize=False))[0]
    np.testing.assert_allclose(m[:2, :2], 0.0, atol=1e-7)  # cancelled approx
    np.testing.assert_allclose(m[2:4, 2:4], 1.0)


def test_mosaic_size_derived_not_hardcoded():
    """Reference hard-codes 224 (defect §2.11.3); ours follows the input."""
    for size in (16, 32, 64):
        assert mosaic_size(_const_coeffs(J=2, size=size)) == size


def test_reproject_shapes_and_values():
    avg = jnp.ones((2, 16, 16))
    maps = reproject_mosaic(avg, levels=2, approx_coeffs=True)
    assert maps.shape == (2, 3, 16, 16)
    # constant mosaic -> each level map = h+v+d = 3 (bilinear of constants)
    np.testing.assert_allclose(np.asarray(maps[:, :2]), 3.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(maps[:, 2]), 1.0, atol=1e-5)


def test_disentangle_shapes():
    maps = disentangle_scales(_const_coeffs(J=3, size=32, batch=2, channels=3), approx_coeffs=False)
    assert maps.shape == (2, 3, 32, 32)
    maps_a = disentangle_scales(_const_coeffs(J=3, size=32, batch=2), approx_coeffs=True)
    assert maps_a.shape == (2, 4, 32, 32)
