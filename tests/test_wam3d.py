"""WAM-3D tests: cube layout goldens, voxel end-to-end with the Flax
VoxelModel, y=None representation mode, filtering round-trips, estimators,
point-cloud path, visualization shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.ops.packing3d import cube3d, visualize_cube
from wam_tpu.wam3d import BaseWAM3D, WaveletAttribution3D, filter_coeffs
from wam_tpu.wavelets import wavedec3

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



def _const_coeffs(J=2, size=16, batch=1):
    coeffs = []
    n = size // (2**J)
    coeffs.append(jnp.full((batch, n, n, n), 10.0))
    keys = ("aad", "ada", "add", "daa", "dad", "dda", "ddd")
    for lev in range(J, 0, -1):
        n = size // (2**lev)
        coeffs.append({k: jnp.full((batch, n, n, n), float(lev) + i / 10.0) for i, k in enumerate(keys)})
    return coeffs


def test_cube_layout():
    cube = np.asarray(cube3d(_const_coeffs(J=1, size=8)))[0]
    assert cube.shape == (8, 8, 8)
    np.testing.assert_allclose(cube[:4, :4, :4], 10.0)  # approx corner
    np.testing.assert_allclose(cube[4:, 4:, 4:], 1.6)  # ddd
    np.testing.assert_allclose(cube[:4, :4, 4:], 1.0)  # aad
    np.testing.assert_allclose(cube[:4, 4:, :4], 1.1)  # ada
    np.testing.assert_allclose(cube[:4, 4:, 4:], 1.2)  # add
    np.testing.assert_allclose(cube[4:, :4, :4], 1.3)  # daa
    np.testing.assert_allclose(cube[4:, :4, 4:], 1.4)  # dad
    np.testing.assert_allclose(cube[4:, 4:, :4], 1.5)  # dda


def test_cube_from_real_transform():
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 16, 16, 16)), dtype=jnp.float32)
    coeffs = wavedec3(x, "haar", level=2)
    cube = cube3d(coeffs)
    assert cube.shape == (2, 16, 16, 16)
    assert np.all(np.asarray(cube) >= 0)


def test_filter_coeffs():
    c = jnp.array([0.0, 0.5, 1.0])
    np.testing.assert_array_equal(np.asarray(filter_coeffs(c, 0.4)), [0, 1, 1])
    np.testing.assert_array_equal(np.asarray(filter_coeffs(c, 0.5, normalized=True)), [0, 1, 1])


@pytest.fixture(scope="module")
def voxel_model_fn():
    from wam_tpu.models.voxel import VoxelModel

    model = VoxelModel(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 1, 16, 16, 16)))
    return lambda x: model.apply(variables, x)


def test_base_wam3d_voxels(voxel_model_fn):
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 1, 16, 16, 16)), dtype=jnp.float32)
    wam = BaseWAM3D(voxel_model_fn, wavelet="haar", J=2)
    cube = wam(x, jnp.array([3, 7]))
    assert cube.shape == (2, 16, 16, 16)
    assert float(jnp.abs(cube).max()) > 0


def test_base_wam3d_representation_mode(voxel_model_fn):
    x = jnp.asarray(np.random.default_rng(2).standard_normal((1, 1, 16, 16, 16)), dtype=jnp.float32)
    wam = BaseWAM3D(voxel_model_fn, wavelet="haar", J=1)
    cube = wam(x, None)  # y=None -> mean-representation gradients
    assert cube.shape == (1, 16, 16, 16)


def test_filter_voxels_roundtrip(voxel_model_fn):
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 1, 16, 16, 16)), dtype=jnp.float32)
    wam = BaseWAM3D(voxel_model_fn, wavelet="haar", J=1)
    wam(x, jnp.array([0, 1]))
    filtered = wam.filter_voxels(EPS=0.0)
    assert filtered.shape == (2, 1, 16, 16, 16)
    assert np.all(np.isfinite(np.asarray(filtered)))


def test_smooth_wam3d(voxel_model_fn):
    x = jnp.asarray(np.random.default_rng(4).standard_normal((1, 1, 16, 16, 16)), dtype=jnp.float32)
    expl = WaveletAttribution3D(voxel_model_fn, J=2, method="smooth", n_samples=4, stdev_spread=0.1)
    cube = expl(x, jnp.array([5]))
    assert cube.shape == (1, 16, 16, 16)
    cube2 = expl(x, jnp.array([5]))
    np.testing.assert_allclose(np.asarray(cube), np.asarray(cube2), atol=1e-6)
    viz = expl.visualize()
    assert viz.shape == (1, 4, 16, 16, 16)
    assert np.all(np.isfinite(np.asarray(viz)))


def test_integrated_wam3d(voxel_model_fn):
    x = jnp.asarray(np.random.default_rng(5).standard_normal((1, 1, 16, 16, 16)), dtype=jnp.float32)
    expl = WaveletAttribution3D(voxel_model_fn, J=1, method="integratedgrad", n_samples=5)
    cube = expl(x, jnp.array([2]))
    assert cube.shape == (1, 16, 16, 16)
    assert np.all(np.isfinite(np.asarray(cube)))


def test_point_cloud_path():
    from wam_tpu.models.pointnet import PointNetCls

    model = PointNetCls(k=5)
    xinit = jnp.zeros((1, 3, 64))
    variables = model.init(jax.random.PRNGKey(0), xinit)
    model_fn = lambda x: model.apply(variables, x)[0]

    x = jnp.asarray(np.random.default_rng(6).standard_normal((2, 3, 64)), dtype=jnp.float32)
    wam = BaseWAM3D(model_fn, wavelet="haar", J=2, instance="point_clouds", EPS=0.1)
    grads = wam(x, jnp.array([1, 2]))
    assert len(grads) == 3  # xyz
    assert len(grads[0]) == 3  # J+1 levels
    kept, importance = wam.filter_point_clouds()
    assert importance.shape == (2, 64)
    assert len(kept) == 2
    assert all(k.shape[-1] == 3 or k.shape[0] == 0 or k.ndim == 2 for k in kept)


def test_visualize_cube_channels():
    cube = jnp.asarray(np.random.default_rng(7).random((1, 16, 16, 16)), dtype=jnp.float32)
    viz = visualize_cube(cube, levels=2)
    assert viz.shape == (1, 4, 16, 16, 16)
    # all channels max-normalized to <= 1
    assert float(jnp.nanmax(viz)) <= 1.0 + 1e-5


def test_auto_schedule_matches_explicit_chunk():
    """sample_batch_size="auto" (the round-4 default, shared
    resolve_sample_chunk law) must equal an explicit chunk numerically, and
    bad strings must be rejected eagerly."""
    import flax.linen as nn

    class Tiny3D(nn.Module):
        @nn.compact
        def __call__(self, v):
            x = jnp.transpose(v, (0, 2, 3, 4, 1))
            x = nn.Conv(4, (3, 3, 3), strides=(2, 2, 2))(x)
            x = nn.relu(x).mean(axis=(1, 2, 3))
            return nn.Dense(5)(x)

    m = Tiny3D()
    variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 1, 16, 16, 16)))
    fn = lambda v: m.apply(variables, v)
    x = jnp.asarray(np.random.default_rng(9).standard_normal((2, 1, 16, 16, 16)),
                    jnp.float32)
    y = jnp.array([1, 3])
    a = WaveletAttribution3D(fn, J=2, n_samples=4)(x, y)  # "auto" default
    b = WaveletAttribution3D(fn, J=2, n_samples=4, sample_batch_size=2)(x, y)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    with pytest.raises(ValueError):
        WaveletAttribution3D(fn, sample_batch_size="Auto")
