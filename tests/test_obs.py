"""Unified observability layer (`wam_tpu/obs`): request-scoped tracing,
the fleet-wide metrics registry, and the compile/retrace sentinel.

Unit coverage for each pillar plus the integration contracts the layer
was built for:

- the Chrome trace export of a fake-entry fleet run is structurally valid
  (``ph:"X"``, per-request trace ids shared by queue_wait/service child
  spans, non-negative durations) and its spans cover >=95% of request
  wall latency — gated through ``scripts/trace_report.py --min-coverage``;
- the registry's totals round-trip against the v2 JSONL ledger exactly
  (the ``obs_snapshot`` row and the ``serve_summary`` row agree);
- `assert_no_retrace` holds across a WARM 2-replica serve loop with real
  jitted entries (the one-compile-per-bucket-per-replica invariant);
- disabled mode records nothing and freezes every registry instrument.

Runs on the virtual 8-device CPU mesh the conftest forces."""

import json
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from conftest import need_devices
from wam_tpu import obs
from wam_tpu.obs import sentinel, tracing
from wam_tpu.obs.registry import registry


@pytest.fixture(autouse=True)
def obs_clean():
    """Every test starts from zero obs state and leaves tracing enabled."""
    obs.configure(enabled=True, ring_size=4096)
    obs.reset()
    yield
    obs.configure(enabled=True, ring_size=4096)
    obs.reset()


# -- tracing ------------------------------------------------------------------


def test_span_nesting_shares_trace_and_parents():
    with obs.span("outer", cat="t") as parent:
        with obs.span("inner", cat="t", k=1):
            pass
    rows = {r["name"]: r for r in obs.spans()}
    assert rows["inner"]["trace_id"] == rows["outer"]["trace_id"]
    assert rows["inner"]["parent_id"] == rows["outer"]["span_id"]
    assert rows["outer"]["parent_id"] is None
    assert rows["inner"]["attrs"] == {"k": 1}
    assert rows["inner"]["t1"] >= rows["inner"]["t0"]
    assert parent.name == "outer"


def test_detached_root_and_retroactive_spans():
    root = obs.start_span("request", cat="t")
    t0 = time.perf_counter()
    t1 = t0 + 0.25
    obs.record_span("queue_wait", t0, t1, parent=(root.trace_id, root.span_id),
                    cat="t")
    root.end()
    rows = {r["name"]: r for r in obs.spans()}
    assert rows["queue_wait"]["trace_id"] == rows["request"]["trace_id"]
    assert rows["queue_wait"]["parent_id"] == rows["request"]["span_id"]
    assert rows["queue_wait"]["t1"] - rows["queue_wait"]["t0"] == pytest.approx(0.25)


def test_use_context_propagates_across_threads():
    root = obs.start_span("request", cat="t")
    ctx = (root.trace_id, root.span_id)

    def worker():
        with obs.use_context(ctx):
            with obs.span("service", cat="t"):
                pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    root.end()
    rows = {r["name"]: r for r in obs.spans()}
    assert rows["service"]["trace_id"] == root.trace_id
    assert rows["service"]["parent_id"] == root.span_id


def test_disabled_mode_records_nothing_and_is_a_shared_noop():
    obs.configure(enabled=False)
    s1 = obs.span("a")
    s2 = obs.span("b")
    assert s1 is s2 is obs.NULL_SPAN  # one shared no-op object, no allocs
    with s1:
        pass
    obs.record_span("c", 0.0, 1.0)
    assert obs.spans() == []
    c = registry.counter("wam_tpu_test_disabled_total")
    c.inc()
    assert c.value() == 0.0  # registry mutations frozen too


def test_ring_size_bounds_and_keeps_newest():
    obs.configure(ring_size=4)
    for i in range(10):
        with obs.span(f"s{i}"):
            pass
    names = [r["name"] for r in obs.spans()]
    assert names == ["s6", "s7", "s8", "s9"]


# -- registry -----------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    c = registry.counter("wam_tpu_test_ops_total", "ops", labels=("kind",))
    c.inc(kind="a")
    c.inc(2, kind="a")
    assert c.value(kind="a") == 3.0
    with pytest.raises(ValueError):
        c.inc(-1, kind="a")
    with pytest.raises(ValueError):
        c.inc(kind="a", extra="nope")

    g = registry.gauge("wam_tpu_test_depth")
    g.set(5)
    g.dec(2)
    assert g.value() == 3.0

    h = registry.histogram("wam_tpu_test_lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)
    assert h.count() == 3
    assert h.sum() == pytest.approx(50.55)


def test_registry_get_or_create_and_type_mismatch():
    a = registry.counter("wam_tpu_test_same_total")
    b = registry.counter("wam_tpu_test_same_total")
    assert a is b
    with pytest.raises(ValueError):
        registry.gauge("wam_tpu_test_same_total")


def test_render_prom_exposition_format():
    c = registry.counter("wam_tpu_test_fmt_total", "help text", labels=("r",))
    c.inc(r='q"x"')
    h = registry.histogram("wam_tpu_test_fmt_seconds", buckets=(0.1, 1.0))
    h.observe(0.5)
    text = obs.render_prom()
    assert "# HELP wam_tpu_test_fmt_total help text" in text
    assert "# TYPE wam_tpu_test_fmt_total counter" in text
    assert 'wam_tpu_test_fmt_total{r="q\\"x\\""} 1' in text
    # cumulative buckets: 0.5 lands in le=1.0 and le=+Inf but not le=0.1
    assert 'wam_tpu_test_fmt_seconds_bucket{le="0.1"} 0' in text
    assert 'wam_tpu_test_fmt_seconds_bucket{le="1"} 1' in text
    assert 'wam_tpu_test_fmt_seconds_bucket{le="+Inf"} 1' in text
    assert "wam_tpu_test_fmt_seconds_sum 0.5" in text
    assert "wam_tpu_test_fmt_seconds_count 1" in text


def test_registry_reset_zeroes_but_keeps_instruments():
    c = registry.counter("wam_tpu_test_reset_total")
    c.inc(7)
    registry.reset()
    assert c.value() == 0.0
    assert registry.counter("wam_tpu_test_reset_total") is c


# -- sentinel -----------------------------------------------------------------


def test_sentinel_attribution_and_ambient_labels():
    with sentinel.label(replica=3, bucket="1x16x16", phase="warmup"):
        ev = sentinel.record_trace("serve", detail="entry")
    assert (ev["replica"], ev["bucket"], ev["phase"]) == (3, "1x16x16", "warmup")
    # explicit non-None labels override ambient; None does NOT shadow
    with sentinel.label(replica=1, bucket="b"):
        ev2 = sentinel.record_trace("serve", replica=2, bucket=None)
    assert (ev2["replica"], ev2["bucket"]) == (2, "b")
    assert sentinel.trace_count() == 2
    assert registry.counter(
        "wam_tpu_compile_jit_traces_total").value(entry_kind="serve") == 2.0
    assert ev["origin"]  # some wam_tpu/test frames survive the obs filter


def test_assert_no_retrace_raises_with_events():
    with obs.assert_no_retrace():
        pass  # clean block passes
    with pytest.raises(obs.RetraceError) as ei:
        with obs.assert_no_retrace():
            sentinel.record_trace("serve", bucket="1x8x8")
    assert len(ei.value.events) == 1
    assert "1x8x8" in str(ei.value)
    # a propagating exception is never masked by the retrace check
    with pytest.raises(RuntimeError):
        with obs.assert_no_retrace():
            sentinel.record_trace("serve")
            raise RuntimeError("real failure")


def test_sentinel_counts_aot_events():
    sentinel.record_aot("miss", "k1")
    sentinel.record_aot("export", "k1")
    sentinel.record_aot("hit", "k1")
    sentinel.record_aot("hit", "k1")
    assert sentinel.aot_event_count("hit") == 2
    assert sentinel.aot_event_count() == 4
    assert registry.counter(
        "wam_tpu_compile_aot_events_total").value(event="hit") == 2.0


def test_sentinel_stays_live_when_obs_disabled():
    obs.configure(enabled=False)
    with pytest.raises(obs.RetraceError):
        with obs.assert_no_retrace():
            sentinel.record_trace("serve")
    assert sentinel.trace_count() == 1  # event counted...
    assert registry.counter(
        "wam_tpu_compile_jit_traces_total").value(entry_kind="serve") == 0.0
    # ...even though the (disabled) registry counter stayed frozen


# -- chrome export / HTTP -----------------------------------------------------


def test_export_chrome_trace_format(tmp_path):
    with obs.span("outer", cat="t", bucket="1x16x16"):
        with obs.span("inner", cat="t"):
            pass
    path = obs.export_chrome_trace(str(tmp_path / "trace.json"))
    payload = json.loads((tmp_path / "trace.json").read_text())
    assert path == str(tmp_path / "trace.json")
    xs = [e for e in payload["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner"}
    for e in xs:
        assert e["dur"] >= 0 and e["ts"] > 0  # µs on the perf_counter base
        assert e["args"]["trace_id"] and e["args"]["span_id"]
    outer = next(e for e in xs if e["name"] == "outer")
    assert outer["args"]["bucket"] == "1x16x16"
    metas = [e for e in payload["traceEvents"] if e["ph"] == "M"]
    assert any(m["name"] == "thread_name" for m in metas)


def test_metrics_http_endpoint():
    registry.counter("wam_tpu_test_http_total").inc(5)
    server = obs.start_metrics_server(0)  # ephemeral port
    try:
        port = server.server_port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "wam_tpu_test_http_total 5" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=5)
    finally:
        obs.stop_metrics_server(server)


# -- serve integration --------------------------------------------------------


def _fake_entry_server(metrics_path=None, **kw):
    from wam_tpu.serve import AttributionServer, ServeMetrics

    metrics = ServeMetrics()
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs) * 2.0,
        [(4,)],
        max_batch=4,
        max_wait_ms=0.0,
        warmup=False,
        metrics=metrics,
        metrics_path=metrics_path,
        **kw,
    )
    return server, metrics


def test_serve_registry_matches_ledger_roundtrip(tmp_path):
    """S3: the prom registry and the JSONL ledger are two views of the SAME
    counts — the serve_summary row, the obs_snapshot row, and collect()
    must agree exactly."""
    path = str(tmp_path / "ledger.jsonl")
    server, metrics = _fake_entry_server(metrics_path=path)
    x = np.zeros((4,), np.float32)
    try:
        for _ in range(6):
            np.testing.assert_array_equal(server.attribute(x, 0), x * 2.0)
    finally:
        server.close()  # emits serve_summary + obs_snapshot

    rows = [json.loads(l) for l in open(path) if l.strip()]
    summary = next(r for r in rows if r["metric"] == "serve_summary")
    snap = next(r for r in rows if r["metric"] == "obs_snapshot")
    live = registry.collect()
    assert summary["submitted"] == summary["completed"] == 6
    for field in ("submitted", "completed", "rejected", "expired"):
        key = f'wam_tpu_serve_{field}_total{{replica="-"}}'
        ledger_val = snap["registry"].get(key, 0.0)
        assert ledger_val == live.get(key, 0.0) == float(summary[field])
    lat_count = f'wam_tpu_serve_latency_seconds_count{{replica="-"}}'
    assert snap["registry"][lat_count] == float(summary["completed"])
    batch_rows = [r for r in rows if r["metric"] == "serve_batch"]
    assert sum(
        v for k, v in live.items()
        if k.startswith("wam_tpu_serve_batches_total")) == len(batch_rows)


def test_fleet_trace_export_is_valid_and_covers_requests(tmp_path):
    """S4: a fake-entry fleet run exports a structurally valid Chrome trace
    whose per-request span trees tile the request wall time (>=95%,
    enforced through scripts/trace_report.py --min-coverage)."""
    need_devices(2)
    from wam_tpu.serve import FleetMetrics, FleetServer

    n_req = 8
    fleet = FleetServer(
        lambda rid, m: lambda xs, ys: np.asarray(xs) * 2.0,
        [(4,)],
        replicas=2,
        max_batch=2,
        max_wait_ms=0.0,
        warmup=False,
        metrics=FleetMetrics(),
    )
    x = np.zeros((4,), np.float32)
    try:
        futs = [fleet.submit(x, 0) for _ in range(n_req)]
        for f in futs:
            f.result(timeout=10)
    finally:
        fleet.close()

    path = str(tmp_path / "trace.json")
    obs.export_chrome_trace(path)
    events = [e for e in json.loads(open(path).read())["traceEvents"]
              if e.get("ph") == "X"]
    roots = [e for e in events if e["name"] == "request"]
    assert len(roots) == n_req
    by_trace = {}
    for e in events:
        by_trace.setdefault(e["args"]["trace_id"], set()).add(e["name"])
    for r in roots:
        names = by_trace[r["args"]["trace_id"]]
        # every request's trace carries admission + the retroactive
        # queue_wait/service spans recorded by the replica worker
        assert {"admission", "queue_wait", "service"} <= names
    assert all(e["dur"] >= 0 for e in events)

    report = subprocess.run(
        [sys.executable, "scripts/trace_report.py", path,
         "--min-coverage", "0.95"],
        capture_output=True, text=True, timeout=60)
    assert report.returncode == 0, report.stdout + report.stderr
    assert "span coverage" in report.stdout


def test_no_retrace_across_warm_two_replica_loop():
    """Acceptance: a WARM 2-replica fleet with real jitted entries serves a
    mixed exact/padded stream without a single fresh jit trace."""
    need_devices(2)
    from wam_tpu.serve import FleetMetrics, FleetServer

    fleet = FleetServer(
        lambda rid, m: __import__("wam_tpu.serve.entry", fromlist=["jit_entry"])
        .jit_entry(lambda xs, ys: xs * 2.0, on_trace=m.note_compile),
        [(4,), (8,)],
        replicas=2,
        max_batch=2,
        max_wait_ms=0.0,
        warmup=True,  # one compile per (bucket, replica), all before serving
        metrics=FleetMetrics(),
    )
    try:
        warm_traces = sentinel.trace_count()
        assert warm_traces >= 1  # warmup itself went through the sentinel
        assert all(
            e["phase"] == "warmup" for e in sentinel.compile_events())
        with obs.assert_no_retrace():
            futs = [fleet.submit(np.zeros((n,), np.float32), 0)
                    for n in (4, 8, 3, 4, 7, 8)]  # exact + padded shapes
            for f in futs:
                f.result(timeout=30)
    finally:
        fleet.close()
    assert sentinel.trace_count() == warm_traces


def test_obs_config_dataclass_configures_layer():
    from wam_tpu.config import ObsConfig

    obs.configure(ObsConfig(enabled=False, ring_size=8))
    assert not tracing._STATE.enabled
    assert tracing._STATE.ring.maxlen == 8
    obs.configure(ObsConfig())
    assert tracing._STATE.enabled


def test_stager_and_fan_publish_to_registry():
    from wam_tpu.evalsuite.fan import fan_runner, run_fan
    from wam_tpu.pipeline.stager import put_committed

    x = np.zeros((2, 8), np.float32)
    put_committed(x)
    assert registry.counter(
        "wam_tpu_stager_h2d_bytes_total").value() == float(x.nbytes)

    runner = fan_runner(lambda a: a * 2.0, donate=False)
    out = run_fan(runner, (np.ones((4,), np.float32),))
    np.testing.assert_array_equal(out, np.full((4,), 2.0))
    assert registry.counter(
        "wam_tpu_fan_result_fetches_total").value() == 1.0
    names = [r["name"] for r in obs.spans()]
    assert "fan.dispatch" in names and "fan.fetch" in names
    # the fan step's first trace landed on the sentinel as entry_kind="fan"
    assert any(e["entry_kind"] == "fan" for e in sentinel.compile_events())
