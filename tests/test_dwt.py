"""DWT/IDWT correctness: haar hand-computed values, cross-check against the
independent numpy reference, round-trips across wavelets/modes/levels/ndim,
shape laws, and differentiability (SURVEY.md §4a-b)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.wavelets import (
    Detail2D,
    build_wavelet,
    dwt,
    idwt,
    wavedec,
    wavedec2,
    wavedec3,
    waverec,
    waverec2,
    waverec3,
)
from tests.reference_dwt import ref_dwt1, ref_wavedec, ref_waverec

SQRT2 = np.sqrt(2.0)


def test_haar_dwt_hand_values():
    x = jnp.array([1.0, 2.0, 3.0, 4.0])
    cA, cD = dwt(x, "haar", mode="zero")
    np.testing.assert_allclose(cA, [3 / SQRT2, 7 / SQRT2], atol=1e-6)
    np.testing.assert_allclose(cD, [-1 / SQRT2, -1 / SQRT2], atol=1e-6)


def test_haar_roundtrip_hand():
    x = jnp.array([1.0, 2.0, 3.0, 4.0])
    cA, cD = dwt(x, "haar", mode="zero")
    rec = idwt(cA, cD, "haar")
    np.testing.assert_allclose(rec, x, atol=1e-6)


@pytest.mark.parametrize("wavelet", ["haar", "db2", "db4", "sym4"])
@pytest.mark.parametrize("mode", ["zero", "symmetric", "reflect", "periodic", "constant"])
@pytest.mark.parametrize("n", [16, 17, 31])
def test_single_level_matches_numpy_reference(wavelet, mode, n):
    w = build_wavelet(wavelet)
    rng = np.random.default_rng(0)
    x = rng.standard_normal(n)
    cA, cD = dwt(jnp.asarray(x, dtype=jnp.float32), w, mode=mode)
    ra, rd = ref_dwt1(x, w.dec_lo, w.dec_hi, mode)
    assert cA.shape[-1] == (n + w.filt_len - 1) // 2
    np.testing.assert_allclose(cA, ra, atol=2e-5)
    np.testing.assert_allclose(cD, rd, atol=2e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db3", "sym4"])
@pytest.mark.parametrize("level", [1, 2, 3])
def test_multilevel_matches_numpy_reference(wavelet, level):
    w = build_wavelet(wavelet)
    rng = np.random.default_rng(1)
    x = rng.standard_normal(64)
    coeffs = wavedec(jnp.asarray(x, dtype=jnp.float32), w, level=level, mode="symmetric")
    ref = ref_wavedec(x, w.dec_lo, w.dec_hi, level, "symmetric")
    assert len(coeffs) == level + 1
    for c, r in zip(coeffs, ref):
        np.testing.assert_allclose(np.asarray(c), r, atol=5e-5)
    rec = waverec(coeffs, w)
    rec_ref = ref_waverec(ref, w.rec_lo, w.rec_hi)
    np.testing.assert_allclose(np.asarray(rec)[: len(x)], rec_ref[: len(x)], atol=5e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db2", "db6", "sym3", "sym8"])
@pytest.mark.parametrize("mode", ["zero", "symmetric", "reflect"])
def test_1d_roundtrip(wavelet, mode):
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((3, 128)), dtype=jnp.float32)
    coeffs = wavedec(x, wavelet, level=3, mode=mode)
    rec = waverec(coeffs, wavelet)
    np.testing.assert_allclose(rec[..., :128], x, atol=1e-4)


def test_1d_roundtrip_odd_length():
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 101)), dtype=jnp.float32)
    coeffs = wavedec(x, "db2", level=3, mode="symmetric")
    rec = waverec(coeffs, "db2")
    np.testing.assert_allclose(rec[..., :101], x, atol=1e-4)


def test_energy_preservation_periodic():
    """Orthogonal transform with periodic extension on power-of-two length
    preserves energy exactly (coefficients are redundant at boundaries for
    other modes)."""
    x = np.random.default_rng(4).standard_normal(64)
    cA, cD = dwt(jnp.asarray(x, dtype=jnp.float32), "haar", mode="periodic")
    # haar with even length has no boundary redundancy
    e = float((cA**2).sum() + (cD**2).sum())
    np.testing.assert_allclose(e, float((x**2).sum()), rtol=1e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db2", "sym4"])
@pytest.mark.parametrize("mode", ["reflect", "symmetric", "zero"])
def test_2d_roundtrip(wavelet, mode):
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    coeffs = wavedec2(x, wavelet, level=3, mode=mode)
    rec = waverec2(coeffs, wavelet)
    np.testing.assert_allclose(rec[..., :32, :32], x, atol=2e-4)


def test_2d_separability_matches_1d():
    """2D transform must equal 1D along rows then cols (separable kernel check)."""
    from wam_tpu.wavelets import dwt2

    w = build_wavelet("db2")
    rng = np.random.default_rng(6)
    x = rng.standard_normal((16, 16))
    cA, det = dwt2(jnp.asarray(x, dtype=jnp.float32), w, mode="zero")
    cA = np.asarray(cA)
    # rows (axis -2) then cols (axis -1) with the numpy reference
    lo_rows = np.stack([ref_dwt1(x[:, j], w.dec_lo, w.dec_hi, "zero")[0] for j in range(16)], axis=1)
    hi_rows = np.stack([ref_dwt1(x[:, j], w.dec_lo, w.dec_hi, "zero")[1] for j in range(16)], axis=1)
    aa = np.stack([ref_dwt1(lo_rows[i], w.dec_lo, w.dec_hi, "zero")[0] for i in range(lo_rows.shape[0])])
    da = np.stack([ref_dwt1(hi_rows[i], w.dec_lo, w.dec_hi, "zero")[0] for i in range(hi_rows.shape[0])])
    ad = np.stack([ref_dwt1(lo_rows[i], w.dec_lo, w.dec_hi, "zero")[1] for i in range(lo_rows.shape[0])])
    dd = np.stack([ref_dwt1(hi_rows[i], w.dec_lo, w.dec_hi, "zero")[1] for i in range(hi_rows.shape[0])])
    np.testing.assert_allclose(cA, aa, atol=2e-5)
    np.testing.assert_allclose(np.asarray(det.horizontal), da, atol=2e-5)
    np.testing.assert_allclose(np.asarray(det.vertical), ad, atol=2e-5)
    np.testing.assert_allclose(np.asarray(det.diagonal), dd, atol=2e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db2"])
def test_3d_roundtrip(wavelet):
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 16)), dtype=jnp.float32)
    coeffs = wavedec3(x, wavelet, level=2, mode="symmetric")
    rec = waverec3(coeffs, wavelet)
    np.testing.assert_allclose(rec[..., :16, :16, :16], x, atol=2e-4)


def test_3d_keys():
    x = jnp.ones((1, 8, 8, 8))
    coeffs = wavedec3(x, "haar", level=1)
    assert set(coeffs[1].keys()) == {"aad", "ada", "add", "daa", "dad", "dda", "ddd"}


def test_gradients_flow_through_roundtrip():
    """The whole point: d/d(coeffs) of a scalar of the reconstruction exists
    and is correct for a linear functional (SURVEY.md §4b)."""
    x = jnp.asarray(np.random.default_rng(8).standard_normal((1, 16, 16)), dtype=jnp.float32)
    coeffs = wavedec2(x, "haar", level=2, mode="reflect")
    weights = jnp.asarray(np.random.default_rng(9).standard_normal((1, 16, 16)), dtype=jnp.float32)

    flat, tree = jax.tree_util.tree_flatten(coeffs)

    def f(flat_coeffs):
        cs = jax.tree_util.tree_unflatten(tree, flat_coeffs)
        return jnp.sum(waverec2(cs, "haar") * weights)

    grads = jax.grad(f)(flat)
    # For a linear map f(c) = <W, R c>, grad = R^T W = wavedec2 of W
    # (orthogonal transform: adjoint of reconstruction = decomposition)
    expected = jax.tree_util.tree_leaves(wavedec2(weights, "haar", level=2, mode="reflect"))
    for g, e in zip(grads, expected):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e), atol=1e-4)


def test_jit_and_vmap():
    x = jnp.asarray(np.random.default_rng(10).standard_normal((4, 32)), dtype=jnp.float32)
    f = jax.jit(lambda v: waverec(wavedec(v, "db2", level=2), "db2"))
    np.testing.assert_allclose(f(x)[..., :32], x, atol=1e-4)
    g = jax.vmap(lambda v: wavedec(v, "haar", level=1)[0])
    assert g(x).shape == (4, 16)


def test_dwt_bf16_inputs_promote_to_f32_all_ranks():
    """Framework-wide bf16-in/f32-accumulate: 1D and 3D transforms promote
    bf16 inputs to f32 coefficients like the 2D dispatch (round 3)."""
    from wam_tpu.wavelets.transform import dwt3

    x1 = jax.random.normal(jax.random.PRNGKey(0), (2, 32), jnp.float32)
    cA, cD = dwt(x1.astype(jnp.bfloat16), "db2", "symmetric")
    assert cA.dtype == jnp.float32 and cD.dtype == jnp.float32
    ref_cA, _ = dwt(x1, "db2", "symmetric")
    assert float(jnp.abs(cA - ref_cA).max()) < 0.02 * float(jnp.abs(ref_cA).max())

    x3 = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 8, 8), jnp.float32)
    a3, d3 = dwt3(x3.astype(jnp.bfloat16), "haar", "symmetric")
    assert a3.dtype == jnp.float32
    assert d3["ddd"].dtype == jnp.float32


@pytest.mark.parametrize("wavelet", ["haar", "db2", "db6", "sym3"])
@pytest.mark.parametrize("mode", ["symmetric", "reflect", "zero"])
@pytest.mark.parametrize("n", [4096, 5003, 8192])
def test_folded1d_analysis_matches_conv(wavelet, mode, n):
    """The polyphase channel-fold must be numerically equal to the plain
    conv path (same linear map, different tiling)."""
    from wam_tpu.wavelets import transform as tf

    x = jax.random.normal(jax.random.PRNGKey(0), (2, n), jnp.float32)
    tf.set_dwt1_impl("conv")
    try:
        a_ref, d_ref = dwt(x, wavelet, mode)
        tf.set_dwt1_impl("folded")
        a, d = dwt(x, wavelet, mode)
    finally:
        tf.set_dwt1_impl("auto")
    np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), atol=2e-5)
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref), atol=2e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db6"])
@pytest.mark.parametrize("n", [4096, 5003])
def test_folded1d_synthesis_matches_conv_and_roundtrips(wavelet, n):
    from wam_tpu.wavelets import transform as tf

    x = jax.random.normal(jax.random.PRNGKey(1), (2, n), jnp.float32)
    tf.set_dwt1_impl("conv")
    try:
        cA, cD = dwt(x, wavelet, "symmetric")
        rec_ref = idwt(cA, cD, wavelet, out_len=n)
        tf.set_dwt1_impl("folded")
        rec = idwt(cA, cD, wavelet, out_len=n)
        # full multi-level roundtrip under the folded impl
        coeffs = wavedec(x, wavelet, 3, "symmetric")
        rt = waverec(coeffs, wavelet)[..., :n]
    finally:
        tf.set_dwt1_impl("auto")
    np.testing.assert_allclose(np.asarray(rec), np.asarray(rec_ref), atol=2e-5)
    np.testing.assert_allclose(np.asarray(rt), np.asarray(x), atol=2e-4)


@pytest.mark.parametrize("wavelet", ["haar", "db6"])
@pytest.mark.parametrize("n", [4096, 5003])
def test_folded1d_nhc_layout_matches_nch(wavelet, n):
    """The chunks-outer "folded_nhc" layout is the same folded linear map
    with transposed conv layouts — analysis and synthesis must match the
    "nch" fold exactly at f32 (same kernel entries, same summation per
    output element)."""
    from wam_tpu.wavelets import transform as tf

    x = jax.random.normal(jax.random.PRNGKey(3), (2, n), jnp.float32)
    tf.set_dwt1_impl("folded")
    try:
        a_ref, d_ref = dwt(x, wavelet, "symmetric")
        rec_ref = idwt(a_ref, d_ref, wavelet, out_len=n)
        tf.set_dwt1_impl("folded_nhc")
        a, d = dwt(x, wavelet, "symmetric")
        rec = idwt(a, d, wavelet, out_len=n)
        coeffs = wavedec(x, wavelet, 3, "symmetric")
        rt = waverec(coeffs, wavelet)[..., :n]
    finally:
        tf.set_dwt1_impl("auto")
    np.testing.assert_allclose(np.asarray(a), np.asarray(a_ref), atol=1e-6)
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref), atol=1e-6)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(rec_ref), atol=1e-6)
    np.testing.assert_allclose(np.asarray(rt), np.asarray(x), atol=2e-4)


def test_folded1d_gradients_match_conv():
    """VJP through the folded transforms equals the conv path's VJP —
    the attribution engine differentiates through these."""
    from wam_tpu.wavelets import transform as tf

    x = jax.random.normal(jax.random.PRNGKey(2), (1, 4096), jnp.float32)

    def loss(v):
        cA, cD = dwt(v, "db6", "symmetric")
        rec = idwt(cA, cD, "db6", out_len=v.shape[-1])
        return (rec * jnp.cos(jnp.arange(v.shape[-1]))).sum()

    tf.set_dwt1_impl("conv")
    try:
        g_ref = jax.grad(loss)(x)
        tf.set_dwt1_impl("folded")
        g = jax.grad(loss)(x)
    finally:
        tf.set_dwt1_impl("auto")
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=2e-4)


def test_nhwc_matches_nchw_all_modes():
    """Channel-last transforms (`wavelets.nhwc`) are the SAME linear map as
    the NCHW path — same matrices, contraction over axes (-3, -2) — for
    every boundary mode and filter family, including odd sizes."""
    from wam_tpu.wavelets.nhwc import waverec2_nhwc, wavedec2_nhwc

    x = jax.random.normal(jax.random.PRNGKey(20), (2, 3, 31, 37))
    xl = jnp.transpose(x, (0, 2, 3, 1))
    for wav in ("haar", "db4", "sym5"):
        for mode in ("reflect", "symmetric", "zero", "periodic"):
            c_ref = wavedec2(x, wav, 3, mode)
            c_new = wavedec2_nhwc(xl, wav, 3, mode)
            for a, b in zip(jax.tree_util.tree_leaves(c_ref),
                            jax.tree_util.tree_leaves(c_new)):
                np.testing.assert_allclose(
                    np.asarray(jnp.moveaxis(b, -1, -3)), np.asarray(a),
                    atol=1e-4, err_msg=f"{wav}/{mode} dec")
            r_ref = waverec2(c_ref, wav)
            r_new = waverec2_nhwc(c_new, wav)
            np.testing.assert_allclose(
                np.asarray(jnp.moveaxis(r_new, -1, -3)), np.asarray(r_ref),
                atol=1e-4, err_msg=f"{wav}/{mode} rec")


def test_nhwc_gradients_are_exact_adjoint():
    """d/dx of a reconstruction functional must agree between layouts —
    the engine's pure-VJP contract holds channel-last too."""
    from wam_tpu.wavelets.nhwc import waverec2_nhwc, wavedec2_nhwc

    x = jax.random.normal(jax.random.PRNGKey(21), (1, 2, 16, 16))
    xl = jnp.transpose(x, (0, 2, 3, 1))
    w = jax.random.normal(jax.random.PRNGKey(22), (16, 16))

    def f_ref(t):
        return jnp.sum(waverec2(wavedec2(t, "db2", 2, "reflect"), "db2")[..., :16, :16] * w)

    def f_new(t):
        return jnp.sum(waverec2_nhwc(wavedec2_nhwc(t, "db2", 2, "reflect"), "db2")[..., :16, :16, :] * w[..., None])

    g_ref = jax.grad(f_ref)(x)
    g_new = jax.grad(f_new)(xl)
    np.testing.assert_allclose(
        np.asarray(jnp.moveaxis(g_new, -1, 1)), np.asarray(g_ref), atol=1e-4)
