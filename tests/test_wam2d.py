"""End-to-end WAM-2D tests with a tiny Flax CNN (the reference's de-facto
integration test is a notebook with ResNet-18 + elephant.jpg; here we pin the
same pipeline shape-generically with a small model)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.wam2d import BaseWAM2D, WaveletAttribution2D

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



class TinyCNN(nn.Module):
    classes: int = 7

    @nn.compact
    def __call__(self, x):  # x: (B, C, H, W)
        x = jnp.transpose(x, (0, 2, 3, 1))  # NHWC for flax conv
        x = nn.Conv(8, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = nn.Conv(16, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.classes)(x)


@pytest.fixture(scope="module")
def model_fn():
    model = TinyCNN()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    return lambda x: model.apply(params, x)


def test_base_wam2d_call(model_fn):
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    wam = BaseWAM2D(model_fn, wavelet="haar", J=3, mode="reflect")
    mosaic = wam(x, jnp.array([1, 4]))
    assert mosaic.shape == (2, 32, 32)
    assert np.all(np.isfinite(np.asarray(mosaic)))
    assert wam.scales.shape == (2, 3, 32, 32)
    # coefficient stashes populated
    assert len(wam.wavelet_coeffs) == 4
    assert wam.gradient_coeffs[0].shape == wam.wavelet_coeffs[0].shape


def test_base_wam2d_nontrivial_gradients(model_fn):
    x = jnp.asarray(np.random.default_rng(1).standard_normal((1, 3, 32, 32)), dtype=jnp.float32)
    wam = BaseWAM2D(model_fn, J=2)
    mosaic = wam(x, jnp.array([0]))
    assert float(jnp.abs(mosaic).max()) > 0.0


def test_smoothgrad_wam2d(model_fn):
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    expl = WaveletAttribution2D(
        model_fn, wavelet="db2", method="smooth", J=2, n_samples=5, stdev_spread=0.2
    )
    out = expl(x, jnp.array([2, 3]))
    # db2 finest detail on 32px is floor((32+3)/2)=17 -> mosaic side 34
    assert out.shape == (2, 34, 34)
    assert expl.scales.shape == (2, 2, 34, 34)
    # determinism with fixed seed
    out2 = expl(x, jnp.array([2, 3]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)


def test_integratedgrad_wam2d(model_fn):
    x = jnp.asarray(np.random.default_rng(3).standard_normal((1, 3, 32, 32)), dtype=jnp.float32)
    expl = WaveletAttribution2D(model_fn, method="integratedgrad", J=2, n_samples=8)
    out = expl(x, jnp.array([5]))
    assert out.shape == (1, 32, 32)
    assert np.all(np.isfinite(np.asarray(out)))


def test_smooth_differs_from_single_pass(model_fn):
    x = jnp.asarray(np.random.default_rng(4).standard_normal((1, 3, 32, 32)), dtype=jnp.float32)
    base = BaseWAM2D(model_fn, J=2)
    single = base(x, jnp.array([0]))
    expl = WaveletAttribution2D(model_fn, method="smooth", J=2, n_samples=10, stdev_spread=0.5)
    smooth = expl(x, jnp.array([0]))
    assert float(jnp.abs(single - smooth).max()) > 1e-6


def test_unknown_method_raises(model_fn):
    with pytest.raises(ValueError):
        WaveletAttribution2D(model_fn, method="nope")


class _NHWCNet(nn.Module):
    """Genuinely layout-sensitive tiny model: consumes (B, H, W, C)."""

    classes: int = 5

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(8, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.classes)(x)


@pytest.fixture(scope="module")
def nhwc_pair():
    """(fn_nhwc, fn_nchw): the SAME bound network, consumed channel-last vs
    with the classic per-call transpose."""
    model = _NHWCNet()
    params = model.init(jax.random.PRNGKey(7), jnp.zeros((1, 32, 32, 3)))
    fn_nhwc = lambda x: model.apply(params, x)
    fn_nchw = lambda x: fn_nhwc(jnp.transpose(x, (0, 2, 3, 1)))
    return fn_nhwc, fn_nchw


def test_model_layout_nhwc_base_matches_nchw(nhwc_pair):
    """model_layout="nhwc" (channel-last engine, wavelets.nhwc) must produce
    the same mosaic/scales/coefficients as the classic NCHW path for the
    deterministic base pass — same NCHW caller contract, zero per-sample
    layout copies inside (round-3 verdict #1)."""
    fn_nhwc, fn_nchw = nhwc_pair
    x = jnp.asarray(np.random.default_rng(11).standard_normal((2, 3, 32, 32)), jnp.float32)
    y = jnp.array([1, 3])
    ref = BaseWAM2D(fn_nchw, wavelet="db2", J=2)
    got = BaseWAM2D(fn_nhwc, wavelet="db2", J=2, model_layout="nhwc")
    m_ref, m_got = ref(x, y), got(x, y)
    np.testing.assert_allclose(np.asarray(m_got), np.asarray(m_ref), atol=2e-5)
    np.testing.assert_allclose(np.asarray(got.scales), np.asarray(ref.scales), atol=2e-5)
    # coefficient stash is channel-last: (B, h, w, C) vs (B, C, h, w)
    a_ref, a_got = ref.wavelet_coeffs[0], got.wavelet_coeffs[0]
    np.testing.assert_allclose(
        np.asarray(jnp.moveaxis(a_got, -1, 1)), np.asarray(a_ref), atol=1e-5
    )


def test_model_layout_nhwc_ig_matches_nchw(nhwc_pair):
    """Integrated gradients is draw-free, so the NHWC path must match the
    NCHW path numerically, not just statistically."""
    fn_nhwc, fn_nchw = nhwc_pair
    x = jnp.asarray(np.random.default_rng(12).standard_normal((1, 3, 32, 32)), jnp.float32)
    y = jnp.array([2])
    ref = WaveletAttribution2D(fn_nchw, wavelet="db2", J=2,
                               method="integratedgrad", n_samples=6)(x, y)
    got = WaveletAttribution2D(fn_nhwc, wavelet="db2", J=2,
                               method="integratedgrad", n_samples=6,
                               model_layout="nhwc")(x, y)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_model_layout_nhwc_smoothgrad_statistics(nhwc_pair):
    """SmoothGrad draws noise in the internal layout, so realizations differ
    between layouts — assert shape/finiteness and that both paths agree on
    the deterministic σ=0 limit (stdev_spread=0 makes every draw the input
    itself)."""
    fn_nhwc, fn_nchw = nhwc_pair
    x = jnp.asarray(np.random.default_rng(13).standard_normal((2, 3, 32, 32)), jnp.float32)
    y = jnp.array([0, 4])
    got = WaveletAttribution2D(fn_nhwc, J=2, method="smooth", n_samples=4,
                               model_layout="nhwc")(x, y)
    assert got.shape[0] == 2 and np.all(np.isfinite(np.asarray(got)))
    ref0 = WaveletAttribution2D(fn_nchw, J=2, method="smooth", n_samples=3,
                                stdev_spread=0.0)(x, y)
    got0 = WaveletAttribution2D(fn_nhwc, J=2, method="smooth", n_samples=3,
                                stdev_spread=0.0, model_layout="nhwc")(x, y)
    np.testing.assert_allclose(np.asarray(got0), np.asarray(ref0), atol=2e-5)


def test_model_layout_rejects_unknown(model_fn):
    with pytest.raises(ValueError):
        BaseWAM2D(model_fn, model_layout="chwn")


def test_schedule_params_reject_bad_strings(model_fn):
    """Only exactly "auto" is accepted as a string: bool("false") is True,
    so an unvalidated config string would silently invert stream_noise."""
    with pytest.raises(ValueError):
        WaveletAttribution2D(model_fn, sample_batch_size="Auto")
    with pytest.raises(ValueError):
        WaveletAttribution2D(model_fn, stream_noise="false")


def test_sample_batching_equivalence(model_fn):
    """Chunked lax.map must give identical results to unchunked."""
    x = jnp.asarray(np.random.default_rng(5).standard_normal((1, 3, 32, 32)), dtype=jnp.float32)
    a = WaveletAttribution2D(model_fn, J=2, n_samples=6, sample_batch_size=None)(x, jnp.array([1]))
    b = WaveletAttribution2D(model_fn, J=2, n_samples=6, sample_batch_size=3)(x, jnp.array([1]))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_smoothgrad_dwt_bf16_tracks_f32():
    """dwt_bf16=True casts at the DWT boundary inside the step: same noise
    draws as the f32 path, f32 coefficients out — the mosaic tracks the f32
    result to bf16 input rounding (BASELINE.md round-3)."""
    W = jnp.asarray(
        np.random.default_rng(3).standard_normal((3 * 32 * 32, 5)), jnp.float32
    )
    fn = lambda x: x.reshape(x.shape[0], -1) @ W
    x = jnp.asarray(np.random.default_rng(4).standard_normal((2, 3, 32, 32)), jnp.float32)
    y = jnp.array([1, 3])
    ref = WaveletAttribution2D(fn, wavelet="db4", J=2, n_samples=3)(x, y)
    got = WaveletAttribution2D(fn, wavelet="db4", J=2, n_samples=3, dwt_bf16=True)(x, y)
    a, b = np.asarray(ref).ravel(), np.asarray(got).ravel()
    cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
    assert cos > 0.999


def test_ig_dwt_bf16_tracks_f32():
    """dwt_bf16 applies to the IG path too (boundary cast before decompose)."""
    W = jnp.asarray(
        np.random.default_rng(5).standard_normal((3 * 32 * 32, 5)), jnp.float32
    )
    fn = lambda x: x.reshape(x.shape[0], -1) @ W
    x = jnp.asarray(np.random.default_rng(6).standard_normal((1, 3, 32, 32)), jnp.float32)
    y = jnp.array([2])
    ref = WaveletAttribution2D(fn, wavelet="db4", J=2, method="integratedgrad",
                               n_samples=4)(x, y)
    got = WaveletAttribution2D(fn, wavelet="db4", J=2, method="integratedgrad",
                               n_samples=4, dwt_bf16=True)(x, y)
    a, b = np.asarray(ref).ravel(), np.asarray(got).ravel()
    cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
    assert cos > 0.999


def test_stream_noise_class_api(model_fn):
    """stream_noise=True must be EXACTLY the engine-level
    smoothgrad(materialize_noise=False) composition with the same key —
    pins the class wiring (σ, seed, averaging), not just shapes."""
    from wam_tpu.core.estimators import smoothgrad
    from wam_tpu.ops.packing2d import mosaic2d

    x = jnp.asarray(np.random.default_rng(7).standard_normal((1, 3, 32, 32)), jnp.float32)
    y = jnp.array([1])
    expl = WaveletAttribution2D(model_fn, J=2, n_samples=6, stream_noise=True,
                                random_seed=11)
    got = expl(x, y)

    def step(noisy):
        _, grads = expl.engine.attribute(noisy, y)
        return mosaic2d(grads, True)

    want = smoothgrad(step, x, jax.random.PRNGKey(11), n_samples=6,
                      stdev_spread=0.25, materialize_noise=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
    # deterministic per seed
    np.testing.assert_allclose(np.asarray(got), np.asarray(expl(x, y)), atol=1e-6)
