"""Multi-device tests on the virtual 8-device CPU mesh: sharded SmoothGrad/IG
must match the single-device estimators bit-for-bit in math (same noise, same
path), with outputs correctly sharded."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.core.engine import WamEngine
from wam_tpu.core.estimators import smoothgrad
from wam_tpu.ops.packing2d import mosaic2d
from wam_tpu.parallel import data_sample_mesh, make_mesh, sharded_integrated_path, sharded_smoothgrad

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



from conftest import need_devices as _need_devices  # shared, tests/conftest.py


def _linear_model(W):
    return lambda x: x.reshape(x.shape[0], -1) @ W


def test_make_mesh():
    _need_devices()
    mesh = make_mesh({"data": 4, "sample": 2})
    assert mesh.shape == {"data": 4, "sample": 2}
    mesh2 = make_mesh({"data": -1, "sample": 4})
    assert mesh2.shape["data"] == 2


def test_data_sample_mesh_factorization():
    _need_devices()
    mesh = data_sample_mesh()
    assert mesh.shape["data"] * mesh.shape["sample"] == 8


def test_sharded_smoothgrad_matches_reference():
    _need_devices()
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.standard_normal((16 * 16, 5)), dtype=jnp.float32)
    eng = WamEngine(_linear_model(W), ndim=2, wavelet="haar", level=2, mode="reflect")
    x = jnp.asarray(rng.standard_normal((4, 1, 16, 16)), dtype=jnp.float32)
    y = jnp.array([0, 1, 2, 3])
    key = jax.random.PRNGKey(42)

    def step(noisy):
        _, grads = eng.attribute(noisy, y)
        return mosaic2d(grads, True)

    mesh = make_mesh({"data": 4, "sample": 2})
    runner = sharded_smoothgrad(step, mesh, n_samples=4, stdev_spread=0.15)
    out_sharded = runner(x, key)

    out_single = smoothgrad(step, x, key, n_samples=4, stdev_spread=0.15)
    np.testing.assert_allclose(np.asarray(out_sharded), np.asarray(out_single), atol=1e-5)


def test_sharded_smoothgrad_divisibility_check():
    _need_devices()
    mesh = make_mesh({"data": 2, "sample": 4})
    with pytest.raises(ValueError):
        sharded_smoothgrad(lambda x: x, mesh, n_samples=5, stdev_spread=0.1)


def test_sharded_ig_matches_reference():
    _need_devices()
    rng = np.random.default_rng(1)
    W = jnp.asarray(rng.standard_normal((16 * 16, 3)), dtype=jnp.float32)
    eng = WamEngine(_linear_model(W), ndim=2, wavelet="haar", level=1, mode="reflect")
    x = jnp.asarray(rng.standard_normal((2, 1, 16, 16)), dtype=jnp.float32)
    y = jnp.array([1, 2])

    def grad_fn(coeffs):
        return mosaic2d(eng.grads_from_coeffs(coeffs, y, (16, 16)), True)

    mesh = make_mesh({"data": 2, "sample": 4})
    runner = sharded_integrated_path(grad_fn, eng.decompose, mesh, n_steps=8)
    out = runner(x)

    from wam_tpu.core.estimators import integrated_path

    expected = integrated_path(grad_fn, eng.decompose(x), n_steps=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_init_distributed_single_process():
    from wam_tpu.parallel import init_distributed

    info = init_distributed()
    assert info["process_count"] == 1
    assert info["global_devices"] == len(jax.devices())


def test_hybrid_mesh_single_process_equals_make_mesh():
    _need_devices(8)
    from wam_tpu.parallel import hybrid_mesh

    mesh = hybrid_mesh({"data": 4, "sample": 2})
    assert mesh.shape == {"data": 4, "sample": 2}
    inferred = hybrid_mesh({"data": -1, "sample": 2})
    assert inferred.shape == {"data": 4, "sample": 2}


def test_hybrid_mesh_runs_sharded_smoothgrad():
    _need_devices(8)
    from wam_tpu.parallel import hybrid_mesh

    mesh = hybrid_mesh({"data": 2, "sample": 4})
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 3, 16, 16))
    runner = sharded_smoothgrad(
        lambda noisy: noisy.mean(axis=(1, 2, 3)), mesh, n_samples=8, stdev_spread=0.1
    )
    out = runner(x, jax.random.PRNGKey(1))
    assert out.shape == (4,)
    assert bool(jnp.isfinite(out).all())


def test_process_local_batch_single_process():
    from wam_tpu.parallel import process_local_batch

    # one process owns the whole batch
    assert process_local_batch(32) == 32


@pytest.mark.parametrize("wavelet", ["haar", "db4", "sym3"])
def test_sharded_wavedec2_matches_single_device(wavelet):
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_wavedec2_per
    from wam_tpu.wavelets.periodized import wavedec2_per

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 32))
    run = sharded_wavedec2_per(mesh, wavelet, level=2)
    got = run(x)
    want = wavedec2_per(x, wavelet, 2)
    assert len(got) == len(want)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]), atol=1e-5)
    for g, w in zip(got[1:], want[1:]):
        for field in ("horizontal", "vertical", "diagonal"):
            np.testing.assert_allclose(
                np.asarray(getattr(g, field)), np.asarray(getattr(w, field)), atol=1e-5
            )


def test_sharded_wavedec2_output_sharding():
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_wavedec2_per

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 16))
    out = sharded_wavedec2_per(mesh, "db2", level=1)(x)
    # every leaf stays sharded on the row axis
    for leaf in jax.tree_util.tree_leaves(out):
        assert len(leaf.sharding.device_set) == 8


def test_sharded_wavedec2_arbitrary_leading_dims():
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_wavedec2_per
    from wam_tpu.wavelets.periodized import wavedec2_per

    mesh = make_mesh({"data": 8})
    run = sharded_wavedec2_per(mesh, "db2", level=1)
    x4 = jax.random.normal(jax.random.PRNGKey(2), (2, 3, 32, 16))  # (B, C, H, W)
    got = run(x4)
    want = wavedec2_per(x4, "db2", 1)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]), atol=1e-5)
    x2 = jax.random.normal(jax.random.PRNGKey(3), (32, 16))  # bare (H, W)
    got2 = run(x2)
    want2 = wavedec2_per(x2, "db2", 1)
    np.testing.assert_allclose(np.asarray(got2[0]), np.asarray(want2[0]), atol=1e-5)


def test_eval2d_sharded_inference_matches_single_device():
    _need_devices(8)
    from wam_tpu.evalsuite import Eval2DWAM

    rng = np.random.default_rng(4)
    W = jnp.asarray(rng.standard_normal((3 * 16 * 16, 5)).astype(np.float32) * 0.05)
    model_fn = lambda x: x.reshape(x.shape[0], -1) @ W
    explainer = lambda x, y: jnp.ones((x.shape[0], 16, 16))
    x = jnp.asarray(rng.standard_normal((2, 3, 16, 16)).astype(np.float32))
    y = np.array([1, 3])

    single = Eval2DWAM(model_fn, explainer, wavelet="haar", J=2)
    mesh = make_mesh({"data": 8})
    sharded = Eval2DWAM(model_fn, explainer, wavelet="haar", J=2, mesh=mesh)
    s_single = single.insertion(x, y, n_iter=16)
    s_sharded = sharded.insertion(x, y, n_iter=16)
    np.testing.assert_allclose(s_sharded, s_single, atol=1e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db3"])
def test_sharded_wavedec3_matches_single_device(wavelet):
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_wavedec3_per
    from wam_tpu.wavelets.periodized import wavedec3_per

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 8, 8))
    got = sharded_wavedec3_per(mesh, wavelet, level=2)(x)
    want = wavedec3_per(x, wavelet, 2)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]), atol=1e-5)
    for g, w in zip(got[1:], want[1:]):
        assert sorted(g) == sorted(w)
        for k in g:
            np.testing.assert_allclose(np.asarray(g[k]), np.asarray(w[k]), atol=1e-5)


def test_wavedec3_per_roundtrip():
    from wam_tpu.wavelets.periodized import wavedec3_per, waverec3_per

    x = jax.random.normal(jax.random.PRNGKey(7), (2, 16, 8, 8))
    rec = waverec3_per(wavedec3_per(x, "db2", 2), "db2")
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=1e-5)


def test_dwt3_per_matches_transform_subband_naming():
    from wam_tpu.wavelets.periodized import dwt3_per
    from wam_tpu.wavelets.transform import DETAIL3D_KEYS

    x = jax.random.normal(jax.random.PRNGKey(8), (1, 8, 8, 8))
    _, det = dwt3_per(x, "haar")
    assert sorted(det) == sorted(DETAIL3D_KEYS)


def test_eval_baselines_sharded_inference():
    _need_devices(8)
    from wam_tpu.evalsuite import EvalImageBaselines
    from wam_tpu.models import resnet18

    model = resnet18(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 16, 16)) * 0.3
    y = np.array([1, 2])
    single = EvalImageBaselines(model, variables, method="saliency")
    sharded = EvalImageBaselines(
        model, variables, method="saliency", mesh=make_mesh({"data": 8})
    )
    s_single = single.insertion(x, y, n_iter=16)
    s_sharded = sharded.insertion(x, y, n_iter=16)
    np.testing.assert_allclose(s_sharded, s_single, atol=1e-5)


@pytest.mark.slow
def test_sharded_smoothgrad_hlo_audit():
    """Interrogate the COMPILED sharded flagship graph (round-4 verdict #5):
    the (n_samples, B, H, W, C) noise buffer must never materialize
    unsharded on a device, the sample mean must be a cross-device
    all-reduce, and per-device temp memory must stay within the v5e budget.
    Fails if a future change silently replicates the noise buffer.

    Also pins the KNOWN propagation limit discovered by this audit: vmap's
    conv batching rule merges the (sample, data) axes into one model-batch
    dim whose product sharding XLA cannot represent, so the data axis is
    all-gathered at the model input (model compute replicated across data
    shards; see parallel/sharded.py). If that gather DISAPPEARS, this test
    fails too — delete the pin and close the shard_map-redesign task."""
    _need_devices(8)
    from wam_tpu.models import bind_inference, resnet18

    N, B, IM = 8, 8, 64
    model = resnet18(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, IM, IM, 3)))
    fn = bind_inference(model, variables, nchw=False)
    eng = WamEngine(fn, ndim=2, wavelet="db4", level=3, mode="reflect",
                    channel_last=True)
    y = jnp.arange(B, dtype=jnp.int32) % 10

    def step(noisy):
        _, grads = eng.attribute(noisy, y)
        return mosaic2d(grads, True, -1)

    mesh = make_mesh({"sample": 4, "data": 2})
    runner = sharded_smoothgrad(step, mesh, n_samples=N, stdev_spread=0.25)
    x = jnp.zeros((B, IM, IM, 3))
    compiled = runner.lower(x, jax.random.PRNGKey(0)).compile()
    txt = compiled.as_text()

    # 1. the full noise/noisy buffer never materializes on one device
    for tok in (f"[{N},{B},{IM},{IM},3]", f"[{N},{B},3,{IM},{IM}]"):
        assert tok not in txt, f"unsharded noise-sized buffer {tok} in HLO"

    # 2. cross-device reductions exist (the sample-mean psum and the
    # batch-global normalization maxes)
    assert "all-reduce" in txt, "no cross-device reduction — mean not sharded?"

    # 3. per-device temp memory within budget (v5e HBM is 16 GB; this tiny
    # config must be far under it — catches accidental whole-fan buffers)
    ma = compiled.memory_analysis()
    if ma is not None and getattr(ma, "temp_size_in_bytes", 0):
        assert ma.temp_size_in_bytes < 4 * 1024**3, (
            f"per-device temp {ma.temp_size_in_bytes/2**30:.2f} GiB "
            "exceeds budget"
        )

    # 4. pin the known data-axis gather (sample-local shape [N/4, B, ...])
    has_gather = "all-gather" in txt
    assert has_gather, (
        "model-input data-axis all-gather gone — propagation limit fixed? "
        "Update parallel/sharded.py docs and remove this pin."
    )


def test_sharded_smoothgrad_spmd_exact_parity_unnormalized():
    """The shard_map variant must reproduce the single-device materialized
    smoothgrad BIT-for-draw (same key, same noise tensor, shard-local step):
    with normalize=False there is no cross-batch coupling, so the sharded
    mean equals the full mean exactly (round-4: the guaranteed
    data-parallel estimator — no model-input all-gather)."""
    _need_devices(8)
    from wam_tpu.parallel import sharded_smoothgrad_spmd

    rng = np.random.default_rng(3)
    W = jnp.asarray(rng.standard_normal((16 * 16, 5)), dtype=jnp.float32)
    eng = WamEngine(_linear_model(W), ndim=2, wavelet="haar", level=2, mode="reflect")
    x = jnp.asarray(rng.standard_normal((4, 1, 16, 16)), dtype=jnp.float32)
    y = jnp.array([0, 1, 2, 3])
    key = jax.random.PRNGKey(11)

    def step_local(noisy, y_l, grad_scale):
        _, grads = eng.attribute(noisy, y_l)
        grads = jax.tree_util.tree_map(lambda g: g * grad_scale, grads)
        return mosaic2d(grads, normalize=False)

    mesh = make_mesh({"sample": 2, "data": 4})
    runner = sharded_smoothgrad_spmd(step_local, mesh, n_samples=4, stdev_spread=0.15)
    out_sharded = runner(x, y, key)

    def step_full(noisy):
        _, grads = eng.attribute(noisy, y)
        return mosaic2d(grads, normalize=False)

    out_single = smoothgrad(step_full, x, key, n_samples=4, stdev_spread=0.15)
    np.testing.assert_allclose(np.asarray(out_sharded), np.asarray(out_single),
                               atol=1e-5)


@pytest.mark.parametrize("batch", [2, 3, 5])
def test_sharded_smoothgrad_spmd_pad_and_mask_parity(batch):
    """Batches NOT divisible by the data axis are padded by cyclic row
    repetition and the pad rows sliced off — real rows must stay
    bit-identical to the single-device materialized smoothgrad (round-5
    fix for the shipped `--batch 2` crash on a data=4 mesh)."""
    _need_devices(8)
    from wam_tpu.parallel import sharded_smoothgrad_spmd

    rng = np.random.default_rng(7)
    W = jnp.asarray(rng.standard_normal((16 * 16, 5)), dtype=jnp.float32)
    eng = WamEngine(_linear_model(W), ndim=2, wavelet="haar", level=2, mode="reflect")
    x = jnp.asarray(rng.standard_normal((batch, 1, 16, 16)), dtype=jnp.float32)
    y = jnp.arange(batch, dtype=jnp.int32) % 5
    key = jax.random.PRNGKey(13)

    def step_local(noisy, y_l, grad_scale):
        _, grads = eng.attribute(noisy, y_l)
        grads = jax.tree_util.tree_map(lambda g: g * grad_scale, grads)
        return mosaic2d(grads, normalize=False)

    mesh = make_mesh({"sample": 2, "data": 4})
    runner = sharded_smoothgrad_spmd(step_local, mesh, n_samples=4, stdev_spread=0.15)
    out_sharded = runner(x, y, key)
    assert out_sharded.shape[0] == batch

    def step_full(noisy):
        _, grads = eng.attribute(noisy, y)
        return mosaic2d(grads, normalize=False)

    out_single = smoothgrad(step_full, x, key, n_samples=4, stdev_spread=0.15)
    np.testing.assert_allclose(np.asarray(out_sharded), np.asarray(out_single),
                               atol=1e-5)


def test_sharded_smoothgrad_spmd_pallas_dwt():
    """The Pallas DWT must run INSIDE shard_map: jax 0.9's check_vma
    rejects pallas_call outputs without vma annotations, which crashed the
    spmd estimator on real TPU (its default dwt2 impl) while the CPU suite
    silently exercised the conv impl — round-5 review finding. Interpret
    mode hits the same check, so this is the portable regression."""
    _need_devices(8)
    from wam_tpu.parallel import sharded_smoothgrad_spmd
    from wam_tpu.wavelets import get_dwt2_impl, set_dwt2_impl

    prev = get_dwt2_impl()
    set_dwt2_impl("pallas")
    try:
        rng = np.random.default_rng(3)
        W = jnp.asarray(rng.standard_normal((16 * 16, 5)), dtype=jnp.float32)
        eng = WamEngine(_linear_model(W), ndim=2, wavelet="haar", level=2,
                        mode="reflect")
        x = jnp.asarray(rng.standard_normal((4, 1, 16, 16)), dtype=jnp.float32)
        y = jnp.arange(4, dtype=jnp.int32) % 5

        def step_local(noisy, y_l, grad_scale):
            _, grads = eng.attribute(noisy, y_l)
            grads = jax.tree_util.tree_map(lambda g: g * grad_scale, grads)
            return mosaic2d(grads, normalize=False)

        mesh = make_mesh({"sample": 2, "data": 4})
        runner = sharded_smoothgrad_spmd(step_local, mesh, n_samples=4,
                                         stdev_spread=0.15)
        out = runner(x, y, jax.random.PRNGKey(11))
        # same values as the conv impl through the same runner
        set_dwt2_impl("conv")
        want = sharded_smoothgrad_spmd(step_local, mesh, n_samples=4,
                                       stdev_spread=0.15)(x, y,
                                                          jax.random.PRNGKey(11))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-5)
    finally:
        set_dwt2_impl(prev)


@pytest.mark.slow
def test_sharded_smoothgrad_spmd_hlo_has_no_model_gather():
    """The spmd variant's compiled HLO must contain NO all-gather at all:
    model compute stays local to each (sample, data) shard and the only
    collective is the sample-mean psum (contrast with
    test_sharded_smoothgrad_hlo_audit, which pins the propagation
    variant's known gather)."""
    _need_devices(8)
    from wam_tpu.models import bind_inference, resnet18
    from wam_tpu.parallel import sharded_smoothgrad_spmd

    N, B, IM = 8, 8, 64
    model = resnet18(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, IM, IM, 3)))
    fn = bind_inference(model, variables, nchw=False)
    eng = WamEngine(fn, ndim=2, wavelet="db4", level=3, mode="reflect",
                    channel_last=True)

    def step(noisy, y_l, grad_scale):
        _, grads = eng.attribute(noisy, y_l)
        grads = jax.tree_util.tree_map(lambda g: g * grad_scale, grads)
        return mosaic2d(grads, normalize=False, channel_axis=-1)

    mesh = make_mesh({"sample": 4, "data": 2})
    runner = sharded_smoothgrad_spmd(step, mesh, n_samples=N, stdev_spread=0.25)
    x = jnp.zeros((B, IM, IM, 3))
    y = jnp.arange(B, dtype=jnp.int32) % 10
    compiled = runner.lower(x, y, jax.random.PRNGKey(0)).compile()
    txt = compiled.as_text()
    assert "all-gather" not in txt, "spmd variant must not gather the model input"
    assert "all-reduce" in txt, "sample-mean psum missing"


@pytest.mark.parametrize("wavelet", ["haar", "db4"])
def test_sharded_waverec_roundtrip_1d(wavelet):
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_waverec_per, sharded_wavedec_per

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 1024))
    coeffs = sharded_wavedec_per(mesh, wavelet, level=3)(x)
    rec = sharded_waverec_per(mesh, wavelet)(coeffs)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=1e-5)
    # reconstruction stays sharded over the sequence axis
    assert len(rec.sharding.device_set) == 8


def test_sharded_waverec_matches_single_device_1d():
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_waverec_per
    from wam_tpu.wavelets.periodized import wavedec_per, waverec_per

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 512))
    coeffs = wavedec_per(x, "db3", 2)
    got = sharded_waverec_per(mesh, "db3")(coeffs)
    want = waverec_per(coeffs, "db3")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db2"])
def test_sharded_waverec_roundtrip_2d(wavelet):
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_waverec2_per, sharded_wavedec2_per

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 64, 32))
    coeffs = sharded_wavedec2_per(mesh, wavelet, level=2)(x)
    rec = sharded_waverec2_per(mesh, wavelet)(coeffs)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=1e-5)


def test_sharded_waverec_roundtrip_3d():
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_waverec3_per, sharded_wavedec3_per

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 8, 8))
    coeffs = sharded_wavedec3_per(mesh, "db2", level=2)(x)
    rec = sharded_waverec3_per(mesh, "db2")(coeffs)
    np.testing.assert_allclose(np.asarray(rec), np.asarray(x), atol=1e-5)


def test_sharded_waverec_differentiable():
    """The engine computes VJPs of coeffs -> model(waverec(coeffs)); the
    sharded reconstruction must therefore be differentiable through
    shard_map (transpose of the transposed ppermute)."""
    _need_devices(8)
    from wam_tpu.parallel.halo import sharded_waverec_per
    from wam_tpu.wavelets.periodized import wavedec_per, waverec_per

    mesh = make_mesh({"data": 8})
    x = jax.random.normal(jax.random.PRNGKey(4), (1, 512))
    coeffs = wavedec_per(x, "db2", 2)
    rec_fn = sharded_waverec_per(mesh, "db2")
    w = jax.random.normal(jax.random.PRNGKey(5), (512,))

    def loss_sharded(cs):
        return jnp.sum(rec_fn(cs) * w)

    def loss_single(cs):
        return jnp.sum(waverec_per(cs, "db2") * w)

    g_sharded = jax.grad(loss_sharded)(coeffs)
    g_single = jax.grad(loss_single)(coeffs)
    for gs, g1 in zip(g_sharded, g_single):
        np.testing.assert_allclose(np.asarray(gs), np.asarray(g1), atol=1e-5)


def test_sharded_coeff_grads_end_to_end_long_context():
    """The complete long-context WAM gradient loop — sequence-sharded
    decompose, reconstruct, model forward, per-coefficient backward — in one
    jit over the mesh, matching the single-device pipeline exactly. The toy
    model is a conv + global pool, i.e. sequence-partitionable the way the
    audio CNN is."""
    _need_devices(8)
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.halo import sharded_coeff_grads_per
    from wam_tpu.wavelets.periodized import wavedec_per, waverec_per

    mesh = make_mesh({"data": 8})
    model_fn = toy_wave_model(jax.random.PRNGKey(0))  # (B, N) -> (B, 4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 2048))
    y = jnp.array([1, 3])
    step = sharded_coeff_grads_per(mesh, "db3", 3, model_fn)
    got = step(x, y)

    def single(x):
        coeffs = wavedec_per(x, "db3", 3)

        def objective(cs):
            out = model_fn(waverec_per(cs, "db3"))
            return jnp.take_along_axis(out, y[:, None], axis=1).sum()

        return jax.grad(objective)(coeffs)

    want = single(x)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert len(g.sharding.device_set) == 8  # grads stay sequence-sharded
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)

    # representation mode (y=None), the engines' NeRF/feature-model path
    got_rep = step(x, None)
    def objective_rep(cs):
        return model_fn(waverec_per(cs, "db3")).mean()
    want_rep = jax.grad(objective_rep)(wavedec_per(x, "db3", 3))
    for g, w in zip(got_rep, want_rep):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)


@pytest.mark.parametrize("ndim,shape", [(2, (2, 128, 24)), (3, (2, 64, 12, 8))])
def test_sharded_coeff_grads_per_2d_3d(ndim, shape):
    """The periodized end-to-end loop generalizes to image rows and volume
    depth via the ndim parameter."""
    _need_devices(8)
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.parallel.halo import sharded_coeff_grads_per
    from wam_tpu.wavelets import periodized as per

    mesh = make_mesh({"data": 8})
    model_fn = toy_conv_model(jax.random.PRNGKey(0), ndim=ndim)
    x = jax.random.normal(jax.random.PRNGKey(1), shape)
    y = jnp.array([1, 3])
    got = sharded_coeff_grads_per(mesh, "db2", 2, model_fn, ndim=ndim)(x, y)

    dec = {2: per.wavedec2_per, 3: per.wavedec3_per}[ndim]
    rec = {2: per.waverec2_per, 3: per.waverec3_per}[ndim]

    def objective(cs):
        out = model_fn(rec(cs, "db2"))
        return jnp.take_along_axis(out, y[:, None], axis=1).sum()

    want = jax.grad(objective)(dec(x, "db2", 2))
    got_leaves = jax.tree_util.tree_leaves(got)
    want_leaves = jax.tree_util.tree_leaves(want)
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        assert g.shape == w.shape
        assert len(g.sharding.device_set) == 8
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)


@pytest.mark.parametrize("extra", [
    [],
    ["--spmd"],
    ["--long-context", "16384"],
    ["--long-context", "16384", "--boundary", "symmetric"],
])
def test_sharded_attribution_example_runs(extra):
    """The sharded-attribution example is the parallel API's front door;
    run it end to end as a user would (its --virtual flag self-configures
    the CPU mesh, so the subprocess needs no env surgery)."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = {k: v for k, v in os.environ.items() if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    out = subprocess.run(
        [sys.executable, str(repo / "examples" / "sharded_attribution.py"),
         "--virtual", "8", "--batch", "2", "--samples", "4", "--size", "32",
         "--wavelet", "db2", "--levels", "2", *extra],
        cwd=str(repo), env=env, capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "sharded over 8 devices" in out.stdout, out.stdout[-1000:]
