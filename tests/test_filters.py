"""Filter-bank generation tests: closed-form golden values + orthogonality
properties every generated bank must satisfy (SURVEY.md §4a)."""

import numpy as np
import pytest

from wam_tpu.wavelets.filters import build_wavelet, daubechies_scaling, qmf, symlet_scaling

SQRT2 = np.sqrt(2.0)


def test_haar_closed_form():
    w = build_wavelet("haar")
    np.testing.assert_allclose(w.rec_lo, [1 / SQRT2, 1 / SQRT2], atol=1e-12)
    np.testing.assert_allclose(w.rec_hi, [1 / SQRT2, -1 / SQRT2], atol=1e-12)
    np.testing.assert_allclose(w.dec_lo, [1 / SQRT2, 1 / SQRT2], atol=1e-12)
    np.testing.assert_allclose(w.dec_hi, [-1 / SQRT2, 1 / SQRT2], atol=1e-12)


def test_db2_closed_form():
    # (1+sqrt3, 3+sqrt3, 3-sqrt3, 1-sqrt3) / (4 sqrt2) — the standard db2 filter.
    s3 = np.sqrt(3.0)
    expected = np.array([1 + s3, 3 + s3, 3 - s3, 1 - s3]) / (4 * SQRT2)
    np.testing.assert_allclose(daubechies_scaling(2), expected, atol=1e-10)


@pytest.mark.parametrize("name", ["haar", "db2", "db4", "db6", "db8", "db10", "sym3", "sym4", "sym8"])
def test_orthogonality_properties(name):
    w = build_wavelet(name)
    h = w.rec_lo
    # normalization
    np.testing.assert_allclose(h.sum(), SQRT2, atol=1e-8)
    np.testing.assert_allclose(np.dot(h, h), 1.0, atol=1e-8)
    # even-shift orthogonality of the scaling filter
    L = len(h)
    for k in range(1, L // 2):
        shifted = np.dot(h[2 * k :], h[: L - 2 * k])
        assert abs(shifted) < 1e-8, f"shift {k} not orthogonal: {shifted}"
    # high-pass has zero mean (one vanishing moment minimum)
    np.testing.assert_allclose(w.rec_hi.sum(), 0.0, atol=1e-8)
    # lo/hi orthogonality at even shifts
    g = w.rec_hi
    for k in range(-(L // 2) + 1, L // 2):
        if 2 * k >= L or 2 * k <= -L:
            continue
        if k >= 0:
            v = np.dot(h[2 * k :], g[: L - 2 * k])
        else:
            v = np.dot(g[-2 * k :], h[: L + 2 * k])
        assert abs(v) < 1e-8


@pytest.mark.parametrize("N", [2, 3, 4, 6, 8, 10])
def test_db_vanishing_moments(N):
    """dbN high-pass must kill polynomials up to degree N-1."""
    g = qmf(daubechies_scaling(N))
    k = np.arange(len(g), dtype=np.float64)
    for p in range(N):
        np.testing.assert_allclose(np.dot(g, k**p), 0.0, atol=1e-5)


@pytest.mark.parametrize("N", [2, 3, 4, 8])
def test_sym_vanishing_moments(N):
    g = qmf(symlet_scaling(N))
    k = np.arange(len(g), dtype=np.float64)
    for p in range(N):
        np.testing.assert_allclose(np.dot(g, k**p), 0.0, atol=1e-5)


def test_sym_more_symmetric_than_db():
    """The symlet selection must produce lower phase non-linearity than dbN."""
    from wam_tpu.wavelets.filters import _phase_nonlinearity

    for N in (4, 8):
        assert _phase_nonlinearity(symlet_scaling(N)) <= _phase_nonlinearity(daubechies_scaling(N)) + 1e-9


def test_filter_lengths():
    for N in (1, 2, 5, 10):
        assert len(daubechies_scaling(N)) == 2 * N
    for N in (2, 5, 8):
        assert len(symlet_scaling(N)) == 2 * N


def test_unknown_wavelet_raises():
    with pytest.raises(ValueError):
        build_wavelet("coif99x")
