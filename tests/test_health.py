"""Health plane (`wam_tpu/obs/{health,memory,slo}.py` + serve wiring):
on-device numeric-health monitors, HBM memory accounting, and the
SLO/error-budget engine wired into fleet admission.

Acceptance contracts pinned here:

- the fan engine's one-fetch invariant holds WITH health piggybacking on
  (`fetch_scope` counts exactly 1 — the 6-float vector rides the result
  fetch);
- a warm 2-replica fleet with health-fused jitted entries serves a mixed
  stream under `assert_no_retrace` (the health leaf is part of the same
  compiled program, and `batch_stats`' structural jit is invisible to the
  sentinel by design);
- a poisoned (NaN-emitting) replica is quarantined after N consecutive
  non-finite batches and routed around with NO request loss; un-poisoning
  restores it within the recovery window;
- ``slo_status`` ledger rows round-trip EXACTLY against the
  ``wam_tpu_slo_*`` registry gauges (same floats, two sinks);
- cold-bucket admission rejects with ``retry_after`` when the projected
  watermark exceeds the budget (simulated-memory ``in_use_fn``), and the
  bucket admits freely once warm.

Runs on the virtual 8-device CPU mesh the conftest forces."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from conftest import need_devices
from wam_tpu import obs
from wam_tpu.obs import health as obs_health
from wam_tpu.obs import sentinel, slo as obs_slo
from wam_tpu.obs.health import HealthConfig, HealthMonitor
from wam_tpu.obs.memory import MemoryBudget, estimate_entry_bytes
from wam_tpu.obs.registry import registry


@pytest.fixture(autouse=True)
def obs_clean():
    """Every test starts from zero obs state and leaves tracing enabled."""
    obs.configure(enabled=True, ring_size=4096)
    obs.reset()
    yield
    obs.configure(enabled=True, ring_size=4096)
    obs.reset()


# -- health_stats (device side) ----------------------------------------------


def test_health_stats_vector_layout():
    import jax.numpy as jnp

    vec = np.asarray(obs_health.health_stats(
        {"m": jnp.asarray([0.5, -1.0, jnp.nan, jnp.inf])}))
    assert vec.shape == (obs_health.HEALTH_VEC_SIZE,)
    s = obs_health.summarize(vec)
    assert s["nonfinite"] == 2 and s["total"] == 4
    assert not s["finite"]
    # NaN must NOT leak into the saturation count (abs>=thr is False for
    # NaN); |-1.0| and |inf| are at/above the threshold and do count
    assert vec[2] == 2.0


def test_health_stats_clean_batch_and_grad_pooling():
    import jax.numpy as jnp

    out = jnp.asarray([0.25, 0.5])
    grads = {"w": jnp.asarray([3.0, 4.0])}
    s = obs_health.summarize(obs_health.health_stats(out, grads))
    assert s["finite"] and s["total"] == 4  # output + gradient elements pool
    assert s["grad_norm"] == pytest.approx(5.0)  # sqrt(9 + 16)
    # combine path (what health-fused engine entries emit) agrees
    combined = obs_health.combine_output_grads(
        obs_health.health_stats(out), obs_health.health_stats(grads))
    s2 = obs_health.summarize(np.asarray(combined))
    assert s2["total"] == 4 and s2["grad_norm"] == pytest.approx(5.0)


def test_health_monitor_quarantine_and_probation():
    mon = HealthMonitor(HealthConfig(quarantine_after=2, recovery_s=10.0))
    bad = np.array([1, 4, 0, 4, np.nan, np.nan], np.float32)
    good = np.array([0, 4, 0, 4, 0.5, 1.0], np.float32)
    assert mon.note(good, now=0.0) and mon.ok(now=0.0)
    assert not mon.note(bad, now=1.0)
    assert mon.ok(now=1.0)  # one bad batch is not a quarantine
    mon.note(bad, now=2.0)
    assert mon.quarantined and not mon.ok(now=2.0)
    assert mon.ok(now=12.5)  # probation: recovery_s elapsed
    mon.note(bad, now=13.0)  # a bad probe re-arms the recovery clock
    assert not mon.ok(now=14.0)
    mon.note(good, now=15.0)  # one healthy batch clears it entirely
    assert not mon.quarantined and mon.ok(now=15.0)


# -- fan piggyback (one-fetch invariant) --------------------------------------


def test_fan_single_fetch_with_health_on():
    import jax.numpy as jnp

    from wam_tpu.evalsuite.fan import fan_runner, fetch_scope, run_fan

    assert obs_health.fan_health_enabled()
    runner = fan_runner(lambda x: x * 2.0)
    with fetch_scope() as fs:
        out = run_fan(runner, (jnp.ones((8,), jnp.float32),))
    assert fs.count == 1  # the stats rode the metric's single fetch
    np.testing.assert_array_equal(out, np.full((8,), 2.0, np.float32))
    assert registry.counter("wam_tpu_health_checks_total").value(
        source="fan", replica="-") == 1.0


def test_fan_health_gates_off_with_obs():
    import jax.numpy as jnp

    from wam_tpu.evalsuite.fan import fan_runner, run_fan

    obs.configure(enabled=False)
    try:
        runner = fan_runner(lambda x: x + 1.0)
        run_fan(runner, (jnp.zeros((4,), jnp.float32),))
        assert not obs_health.fan_health_enabled()
    finally:
        obs.configure(enabled=True)
    assert registry.counter("wam_tpu_health_checks_total").value(
        source="fan", replica="-") == 0.0


# -- serve integration --------------------------------------------------------


class _PoisonEntry:
    """Fake serving entry whose output turns NaN while ``poisoned`` is set.
    Numpy in/out — exercises the worker's post-hoc `batch_stats` dispatch
    path (the one fake/user entries take)."""

    def __init__(self):
        self.poisoned = threading.Event()

    def __call__(self, xs, ys):
        out = np.asarray(xs, np.float32) * 2.0
        if self.poisoned.is_set():
            out = out + np.nan
        return out


def test_single_server_quarantine_and_recovery():
    from wam_tpu.serve import AttributionServer

    entry = _PoisonEntry()
    server = AttributionServer(
        entry, [(4,)], max_batch=1, max_wait_ms=0.0, warmup=False,
        health=HealthConfig(quarantine_after=2, recovery_s=0.05),
    )
    x = np.ones((4,), np.float32)
    try:
        server.attribute(x, 0)
        assert server.health_ok()
        entry.poisoned.set()
        for _ in range(2):
            # poisoned batches still RESOLVE (NaN result, no exception) —
            # quarantine is a routing signal, not a request failure
            assert np.isnan(server.attribute(x, 0)).all()
        assert not server.health_ok()
        entry.poisoned.clear()
        time.sleep(0.06)
        assert server.health_ok()  # probation window reached
        np.testing.assert_array_equal(server.attribute(x, 0), x * 2.0)
        assert server.health_ok()
        assert not server._health.quarantined  # fully cleared, not probation
        d = server.describe()["health"]
        assert d["nonfinite_batches"] == 2 and not d["quarantined"]
    finally:
        server.close()


def test_fleet_routes_around_poisoned_replica_no_request_loss():
    need_devices(2)
    from wam_tpu.serve import FleetMetrics, FleetServer

    entries = {}

    def factory(rid, m):
        entries[rid] = _PoisonEntry()
        return entries[rid]

    metrics = FleetMetrics()
    fleet = FleetServer(
        factory, [(4,)], replicas=2, max_batch=1, max_wait_ms=0.0,
        warmup=False, metrics=metrics,
        health=HealthConfig(quarantine_after=2, recovery_s=0.05),
    )
    x = np.ones((4,), np.float32)
    try:
        # idle-tie routing lands on replica 0 (deterministic rid tie-break);
        # poison it and drive sequentially so each health verdict is
        # recorded before the next routing decision
        entries[0].poisoned.set()
        results = [fleet.attribute(x, 0) for _ in range(6)]
        assert len(results) == 6  # NO request loss: every future resolved
        assert fleet.describe()["quarantined"] == [0]
        assert fleet.describe()["dead"] == []  # quarantine is NOT death
        # requests after the quarantine flowed to the healthy replica
        assert metrics.replica(1).completed >= 4
        assert all(np.isfinite(r).all() for r in results[-3:])

        # recovery: un-poison, wait out the window, and let probe traffic
        # through (probation readmits replica 0 to the healthy partition)
        entries[0].poisoned.clear()
        time.sleep(0.06)
        r0 = fleet._replicas[0].server
        assert r0.health_ok()
        np.testing.assert_array_equal(r0.attribute(x, 0), x * 2.0)
        assert fleet.describe()["quarantined"] == []
    finally:
        fleet.close()


def test_no_retrace_across_warm_health_fused_fleet():
    """A warm 2-replica fleet with HEALTH-FUSED jitted entries serves a
    mixed exact/padded stream without a single fresh jit trace — the
    health vector is a leaf of the already-compiled program."""
    need_devices(2)
    from wam_tpu.serve import FleetMetrics, FleetServer
    from wam_tpu.serve.entry import jit_entry

    fleet = FleetServer(
        lambda rid, m: jit_entry(
            lambda xs, ys: xs * 2.0, on_trace=m.note_compile,
            with_health=True),
        [(4,), (8,)],
        replicas=2,
        max_batch=2,
        max_wait_ms=0.0,
        warmup=True,
        metrics=FleetMetrics(),
        health=True,
    )
    try:
        warm_traces = sentinel.trace_count()
        assert warm_traces >= 1
        with obs.assert_no_retrace():
            futs = [fleet.submit(np.zeros((n,), np.float32), 0)
                    for n in (4, 8, 3, 4, 7, 8)]
            for f in futs:
                f.result(timeout=30)
    finally:
        fleet.close()
    assert sentinel.trace_count() == warm_traces
    # the fused path actually ran the health reduction per batch
    assert registry.counter("wam_tpu_health_checks_total").value(
        source="serve", replica="0") >= 1.0


# -- SLO engine ---------------------------------------------------------------


def test_slo_burn_rate_components():
    tr = obs_slo.SLOTracker("p99_ms=100,error_rate=0.1,health_rate=0.9")
    for i in range(98):
        tr.note("4", latency_s=0.01, now=100.0 + i * 1e-3)
    tr.note("4", latency_s=0.5, now=100.2)  # one request over the p99 target
    tr.note_error("4", 1, now=100.3)
    st = tr.bucket_stats("4", now=100.4)
    assert st["n"] == 100
    assert st["error_rate"] == pytest.approx(0.01)
    assert st["health_rate"] == pytest.approx(0.99)
    # burn components: error 0.01/0.1 = 0.1; health 0.01/0.1 = 0.1;
    # latency (1/99 over-target)/0.01 ~ 1.0101 -> the max wins
    assert st["burn_rate"] == pytest.approx((1 / 99) / 0.01)
    assert tr.penalty_s("4", now=100.4) == pytest.approx(
        ((1 / 99) / 0.01 - 1.0) * obs_slo.PENALTY_SCALE_S)
    # entries age out of the rolling window entirely
    assert tr.bucket_stats("4", now=1000.0)["n"] == 0


def test_slo_status_row_roundtrips_registry_exactly(tmp_path):
    """The slo_status ledger row and the wam_tpu_slo_* gauges are computed
    from the SAME floats — a JSON round trip of the row must equal the live
    gauge values bit-for-bit."""
    from wam_tpu.results import JsonlWriter
    from wam_tpu.serve.metrics import SCHEMA_VERSION, write_slo_status

    tr = obs_slo.SLOTracker("p99_ms=25,error_rate=0.05", replica_id=0)
    rng = np.random.default_rng(7)
    # timestamps must sit inside the rolling window at snapshot time, and
    # write_slo_status snapshots at the REAL perf_counter clock
    base = time.perf_counter()
    for i in range(37):
        tr.note("1x16x16", latency_s=float(rng.uniform(0.001, 0.06)),
                ok=True, healthy=bool(i % 5), now=base + i * 1e-3)
    tr.note_error("1x16x16", 3, now=base + 0.1)

    path = str(tmp_path / "ledger.jsonl")
    row = write_slo_status(JsonlWriter(path), tr)
    assert row["schema_version"] == SCHEMA_VERSION
    back = json.loads(open(path).read().strip())
    assert back["metric"] == "slo_status"
    gauges = {
        "burn_rate": "wam_tpu_slo_burn_rate",
        "error_rate": "wam_tpu_slo_error_rate",
        "health_rate": "wam_tpu_slo_health_rate",
        "p99_s": "wam_tpu_slo_p99_seconds",
        "n": "wam_tpu_slo_window_requests",
    }
    stats = back["buckets"]["1x16x16"]
    assert stats["n"] == 40
    for field, gname in gauges.items():
        live = registry.gauge(gname).value(replica="0", bucket="1x16x16")
        assert stats[field] == live, (field, stats[field], live)


def test_server_emits_slo_status_ledger_row(tmp_path):
    from wam_tpu.serve import AttributionServer

    path = str(tmp_path / "serve.jsonl")
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs) * 2.0, [(4,)],
        max_batch=2, max_wait_ms=0.0, warmup=False,
        metrics_path=path, slo="p99_ms=1000,error_rate=0.5",
    )
    x = np.zeros((4,), np.float32)
    try:
        for _ in range(5):
            server.attribute(x, 0)
    finally:
        server.close()
    rows = [json.loads(l) for l in open(path) if l.strip()]
    slo_rows = [r for r in rows if r["metric"] == "slo_status"]
    assert len(slo_rows) == 1
    # served requests land in the bucket@class window (QoS lanes); the
    # default submit class is "interactive"
    st = slo_rows[0]["buckets"]["4@interactive"]
    assert st["n"] == 5 and st["error_rate"] == 0.0
    assert st["burn_rate"] == 0.0  # well under both objectives
    assert slo_rows[0]["objectives"]["*"]["p99_ms"] == 1000.0


# -- memory accounting / admission -------------------------------------------


def test_memory_cold_bucket_admission_with_simulated_memory():
    from wam_tpu.serve import AttributionServer, MemoryAdmissionError, QueueFullError

    budget = MemoryBudget(budget_bytes=1024, in_use_fn=lambda: 900,
                          retry_after_s=2.5, replica_id=None)
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs) * 2.0, [(4,)],
        max_batch=4, max_wait_ms=0.0, warmup=False, memory=budget,
    )
    x = np.ones((4,), np.float32)
    try:
        # cold bucket: projected 900 + estimate(4 rows x 4 elems x f32 x4)
        # = 900 + 256 > 1024 -> reject-with-retry-after
        with pytest.raises(MemoryAdmissionError) as ei:
            server.submit(x, 0)
        assert isinstance(ei.value, QueueFullError)  # fleet-compatible
        assert ei.value.retry_after_s == 2.5
        assert ei.value.bucket == "4"
        assert budget.rejects == 1
        assert registry.counter(
            "wam_tpu_memory_admission_rejects_total").value(replica="-") == 1.0
        # once the bucket is warm its memory is already paid for: admitted
        # regardless of the in-use reading
        budget.capture_watermark("4", estimate_entry_bytes((4,), 4))
        np.testing.assert_array_equal(server.attribute(x, 0), x * 2.0)
    finally:
        server.close()


def test_memory_watermark_captured_at_warmup():
    from wam_tpu.serve import AttributionServer
    from wam_tpu.serve.entry import jit_entry

    server = AttributionServer(
        jit_entry(lambda xs, ys: xs * 2.0), [(4,)],
        max_batch=2, max_wait_ms=0.0, warmup=True, memory=1 << 30,
    )
    try:
        assert server._memory.is_warm("4")
        wm = server._memory.describe()["watermarks"]["4"]
        assert wm > 0
        assert registry.gauge("wam_tpu_memory_bucket_watermark_bytes").value(
            replica="-", bucket="4") == float(wm)
        # warm bucket admits under any budget pressure
        x = np.ones((4,), np.float32)
        np.testing.assert_array_equal(server.attribute(x, 0), x * 2.0)
    finally:
        server.close()


def test_estimate_entry_bytes_and_staged_feed():
    assert estimate_entry_bytes((3, 32, 32), 8) == 3 * 32 * 32 * 8 * 4 * 4
    assert estimate_entry_bytes((4,), 1, multiplier=1.0, aot_bytes=100) == 116
    from wam_tpu.pipeline.stager import put_committed

    before = registry.gauge("wam_tpu_memory_staged_bytes").value()
    put_committed(np.zeros((8,), np.float32))
    assert registry.gauge("wam_tpu_memory_staged_bytes").value() == before + 32


# -- /metrics e2e -------------------------------------------------------------

# one Prometheus 0.0.4 sample line: name{labels} value  (value may be a
# float, integer, nan, or +/-inf rendering)
_PROM_SAMPLE = (
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|nan|[+-]?inf)$'
)


def test_fleet_metrics_endpoint_exposes_health_plane():
    need_devices(2)
    import re

    from wam_tpu.serve import FleetMetrics, FleetServer

    fleet = FleetServer(
        lambda rid, m: lambda xs, ys: np.asarray(xs) * 2.0,
        [(4,)], replicas=2, max_batch=2, max_wait_ms=0.0, warmup=False,
        metrics=FleetMetrics(), prom_port=0,
        health=True, slo="p99_ms=1000", memory_budget=1 << 30,
    )
    x = np.zeros((4,), np.float32)
    try:
        futs = [fleet.submit(x, 0) for _ in range(8)]
        for f in futs:
            f.result(timeout=10)
        port = fleet.prom_server.server_port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
    finally:
        fleet.close()

    for family in ("wam_tpu_health_checks_total", "wam_tpu_slo_burn_rate",
                   "wam_tpu_memory_budget_bytes"):
        assert f"# TYPE {family}" in body, family
        assert any(l.startswith(family) for l in body.splitlines()), family
    sample_re = re.compile(_PROM_SAMPLE)
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        assert sample_re.match(line), f"unparseable exposition line: {line!r}"


# -- profiling satellite: xplane interval union -------------------------------


class _Span:
    def __init__(self, offset_ps, duration_ps):
        self.offset_ps = offset_ps
        self.duration_ps = duration_ps


def test_device_time_union_deduplicates_overlapping_module_spans():
    """Overlapping "XLA Modules" spans (pipelined dispatch) must be counted
    by interval union, not summed — a plain sum reports 250ps for spans
    covering only 200ps here."""
    from wam_tpu.profiling import _union_seconds

    spans = [_Span(0, 100), _Span(50, 100), _Span(200, 50)]
    assert _union_seconds(spans) == pytest.approx(200e-12)
    # disjoint spans still sum exactly
    assert _union_seconds([_Span(0, 10), _Span(20, 10)]) == pytest.approx(20e-12)
    # fully-nested spans count once
    assert _union_seconds([_Span(0, 100), _Span(25, 50)]) == pytest.approx(100e-12)
    assert _union_seconds([]) == 0.0
