"""Multi-model residency + tenant fairness (`wam_tpu/serve/models.py`,
round 20): the pager's residency state machine (page-in at
``compile_count == 0`` from a registry bundle, watermark-driven eviction,
evict-while-busy refusal, the kill switch), tenant-fair lane ordering,
per-tenant admission quotas and cache partitions, the ``@class@tenant``
SLO ladder, and the model-keyed EMA / ledger-row plumbing.

Like test_serve.py, the operational tests drive the worker loop with
gated fake entries (threading.Event handshakes, no sleeps); the
zero-compile page-in test reuses test_registry.py's publish → hydrate
round-trip at the server level."""

import json
import threading

import jax
import numpy as np
import pytest

from wam_tpu.registry import publish_bundle
from wam_tpu.serve import (
    AttributionServer,
    Bucket,
    MemoryAdmissionError,
    ModelPager,
    ModelSpec,
    QueueFullError,
    ServeMetrics,
)
from wam_tpu.serve.result_cache import ResultCache
from wam_tpu.serve.runtime import _Lanes, _Request


# -- spec validation ----------------------------------------------------------


def test_model_spec_validation():
    f = lambda: None
    with pytest.raises(ValueError):
        ModelSpec("", f)
    with pytest.raises(ValueError):
        ModelSpec("a|b", f)  # '|' delimits model-prefixed EMA keys
    with pytest.raises(ValueError):
        ModelSpec("a@b", f)  # '@' delimits SLO ladder segments
    with pytest.raises(TypeError):
        ModelSpec("m", "not-callable")
    with pytest.raises(ValueError):
        ModelPager([ModelSpec("m", f), ModelSpec("m", f)])


# -- pager state machine (unit) -----------------------------------------------


def _fake_page_in(spec):
    return (lambda xs, ys: xs), int(spec.est_bytes)


def test_pager_pages_in_once_and_touches_lru():
    pager = ModelPager([ModelSpec("m1", lambda: None, est_bytes=100)])
    built = []

    def page_in(spec):
        built.append(spec.model_id)
        return object(), spec.est_bytes

    e1 = pager.ensure("m1", page_in)
    e2 = pager.ensure("m1", page_in)  # resident: no second build
    assert e1 is e2
    assert built == ["m1"]
    assert pager.resident() == {"m1": 100}
    assert pager.resident_bytes() == 100
    assert pager.entry("m1") is e1
    with pytest.raises(KeyError):
        pager.ensure("nope", page_in)
    with pytest.raises(KeyError):
        pager.entry("m2")  # configured but cold: callers ensure first


def test_pager_budget_evicts_lru_weighted_by_ema():
    """Two residents, room for one more: the idle-and-cheap model pages
    out first (score = idle_s / max(ema, seed)), the recently-hot or
    expensive one stays."""
    emas = {"cheap": 0.001, "costly": 5.0}
    pager = ModelPager(
        [ModelSpec(m, lambda: None, est_bytes=100)
         for m in ("cheap", "costly", "third")],
        budget_bytes=250, ema_fn=lambda m: emas.get(m, 0.0))
    pager.ensure("cheap", _fake_page_in)
    pager.ensure("costly", _fake_page_in)
    # same idle clock, wildly different EMA weight -> "cheap" scores
    # higher (idle/0.001 >> idle/5.0) and is the victim
    pager.ensure("third", _fake_page_in)
    assert set(pager.resident()) == {"costly", "third"}
    assert pager.pageouts == 1
    assert pager.describe()["pageouts"] == 1


def test_pager_refuses_when_only_busy_models_pin_budget():
    pager = ModelPager(
        [ModelSpec("busy", lambda: None, est_bytes=200),
         ModelSpec("in", lambda: None, est_bytes=200)],
        budget_bytes=250, busy_fn=lambda m: True, retry_after_s=0.5)
    pager.ensure("busy", _fake_page_in)
    with pytest.raises(MemoryAdmissionError) as ei:
        pager.ensure("in", _fake_page_in)
    assert ei.value.retry_after_s == 0.5
    assert "model:in" in str(ei.value)
    assert pager.resident() == {"busy": 200}  # nothing was evicted


def test_pager_kill_switch_disables_eviction(monkeypatch):
    monkeypatch.setenv("WAM_TPU_NO_MODEL_PAGING", "1")
    pager = ModelPager(
        [ModelSpec("a", lambda: None, est_bytes=200),
         ModelSpec("b", lambda: None, est_bytes=200)],
        budget_bytes=250)
    pager.ensure("a", _fake_page_in)
    pager.ensure("b", _fake_page_in)  # over budget, but paging is off
    assert set(pager.resident()) == {"a", "b"}
    assert pager.pageouts == 0
    assert pager.describe()["paging_disabled"]


# -- server-level residency ---------------------------------------------------


class _GateEntry:
    """Fake entry that parks calls until released — deterministic
    in-flight state without sleeps (test_serve.py's gate). The gate
    starts OPEN so page-in warmup dispatches pass straight through;
    tests arm it with `hold()` when they need a parked batch."""

    def __init__(self, scale=2.0):
        self.scale = scale
        self.calls = 0
        self.entered = threading.Event()
        self.release = threading.Event()
        self.release.set()  # gating is opt-in via hold()

    def hold(self):
        self.entered.clear()
        self.release.clear()

    def __call__(self, xs, ys):
        self.calls += 1
        self.entered.set()
        assert self.release.wait(timeout=10), "test gate never released"
        return np.asarray(xs) * self.scale


def test_server_multiplexes_models_with_isolated_results(tmp_path):
    """One server, two paged models + the pinned default entry: each
    (model, bucket) lane serves its own entry, EMA keys are
    model-prefixed, and the serve_batch ledger rows carry model_id."""
    ledger = str(tmp_path / "serve.jsonl")
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs) * 1.0, [(4,)], max_batch=2,
        max_wait_ms=0.0, warmup=False, labeled=False, metrics_path=ledger,
        models=[ModelSpec("m2", lambda: _GateEntry(2.0), est_bytes=64),
                ModelSpec("m3", lambda: _GateEntry(3.0), est_bytes=64)],
    )
    x = np.ones((4,), np.float32)
    try:
        np.testing.assert_array_equal(server.attribute(x), x)
        np.testing.assert_array_equal(server.attribute(x, model="m2"), x * 2)
        np.testing.assert_array_equal(server.attribute(x, model="m3"), x * 3)
        assert server.models_resident() == {"m2": 64, "m3": 64}
        emas = server.metrics.ema_service_s()
        assert "m2|4" in emas and "m3|4" in emas and "4" in emas
        with pytest.raises(ValueError):
            server.attribute(x, model="unknown")
        desc = server.describe()
        assert desc["models"]["pageins"] == 2
    finally:
        server.close()
    rows = [json.loads(line) for line in open(ledger)]
    batch_models = {r.get("model_id") for r in rows
                    if r.get("metric") == "serve_batch"}
    assert batch_models == {None, "m2", "m3"}
    snap = [r for r in rows if r.get("metric") == "obs_snapshot"]
    assert snap and snap[-1]["models_resident"] == {"m2": 64, "m3": 64}


def test_server_evict_while_in_flight_refused():
    """A model with a parked in-flight batch is never evicted: paging in
    a third model under a budget with only busy residents is refused as
    memory backpressure; after the batch completes the page-in
    succeeds and the idle model is the victim."""
    gate = _GateEntry(2.0)
    est = 10 * 2**20
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs), [(4,)], max_batch=1,
        max_wait_ms=0.0, warmup=False, labeled=False,
        memory=int(est * 1.5),
        models=[ModelSpec("busy", lambda: gate, est_bytes=est),
                ModelSpec("other", lambda: _GateEntry(3.0), est_bytes=est)],
    )
    x = np.ones((4,), np.float32)
    try:
        # page "busy" in and serve once (gate open: warmup + serve pass)
        np.testing.assert_array_equal(server.attribute(x, model="busy"),
                                      x * 2)
        gate.hold()
        fut = server.submit(x, model="busy")
        assert gate.entered.wait(timeout=10)  # parked in dispatch
        with pytest.raises(MemoryAdmissionError):
            server.submit(x, model="other")
        gate.release.set()
        np.testing.assert_array_equal(fut.result(timeout=10), x * 2)
        np.testing.assert_array_equal(
            server.attribute(x, model="other"), x * 3)
        assert server.models_resident() == {"other": est}  # busy evicted
    finally:
        gate.release.set()
        server.close()


def test_min_confidence_rejected_for_paged_models():
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs), [(4,)], max_batch=1, warmup=False,
        labeled=False,
        models=[ModelSpec("m", lambda: (lambda xs, ys: np.asarray(xs)))],
    )
    try:
        with pytest.raises(ValueError):
            server.submit(np.ones((4,), np.float32), model="m",
                          min_confidence=0.5)
    finally:
        server.close()


def _toy_wam2d():
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.wam2d import BaseWAM2D

    toy = toy_conv_model(jax.random.PRNGKey(0), ndim=2)
    return BaseWAM2D(lambda x: toy(x.mean(axis=1)), J=2)


def test_model_pages_in_from_bundle_at_zero_compiles(tmp_path, monkeypatch):
    """The tentpole acceptance invariant: a cold paged model whose spec
    carries a registry bundle serves its FIRST request with zero entry
    traces — page-in is a hydration, not a compile — bit-identical to
    the publisher."""
    pub = tmp_path / "pub-aot"
    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(pub))
    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE", str(tmp_path / "s.json"))
    wam = _toy_wam2d()
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16)))

    cold = []
    server = AttributionServer(
        wam.serve_entry(), [(1, 16, 16)], max_batch=2, warmup=False,
        models=[ModelSpec(
            "toy", lambda: wam.serve_entry(
                on_trace=lambda: cold.append(1), aot_key="mm-toy"))],
    )
    try:
        ref = server.attribute(x, 2, model="toy")  # pages in + compiles
    finally:
        server.close()
    assert cold == [1]  # publisher page-in exported the executable

    bundle = str(tmp_path / "bundle")
    publish_bundle(bundle, aot_dir=str(pub), include_xla=False,
                   schedule_path=str(tmp_path / "s.json"))
    monkeypatch.setenv("WAM_TPU_AOT_CACHE", str(tmp_path / "cold-aot"))

    warm = []
    metrics = ServeMetrics()
    server = AttributionServer(
        wam.serve_entry(), [(1, 16, 16)], max_batch=2, warmup=False,
        metrics=metrics,
        models=[ModelSpec(
            "toy", lambda: wam.serve_entry(
                on_trace=lambda: warm.append(1), aot_key="mm-toy"),
            registry=bundle)],
    )
    try:
        got = server.attribute(x, 2, model="toy")
        assert server.models_resident().keys() == {"toy"}
        assert server.describe()["models"]["resident"]["toy"]["pagein_s"] > 0
    finally:
        server.close()
    assert warm == []  # the bundle, not a compile, paid the page-in
    np.testing.assert_allclose(got, ref, atol=1e-6)


# -- tenant fairness ----------------------------------------------------------


def _req(tenant, t=0.0, qos="interactive"):
    return _Request(np.zeros((4,), np.float32), None, Bucket.of((4,)),
                    t, None, qos=qos, tenant=tenant)


def test_lanes_pop_round_robins_across_tenants():
    lanes = _Lanes()
    for r in [_req("a", 0), _req("a", 1), _req("a", 2), _req("b", 3),
              _req("b", 4), _req("c", 5)]:
        lanes.append(r)
    take = lanes.pop(3)
    # one from each tenant present, FIFO within each — not a:0,1,2
    assert sorted(r.tenant for r in take) == ["a", "b", "c"]
    assert [r.t_submit for r in take if r.tenant == "a"] == [0]
    take2 = lanes.pop(3)
    assert sorted(r.tenant for r in take2) == ["a", "a", "b"]
    assert len(lanes) == 0


def test_lanes_single_tenant_is_exact_fifo():
    lanes = _Lanes()
    for t in range(5):
        lanes.append(_req(None, t))
    assert [r.t_submit for r in lanes.pop(3)] == [0, 1, 2]
    assert [r.t_submit for r in lanes.pop(3)] == [3, 4]


def test_tenant_quota_floods_bounce_others_admit():
    gate = _GateEntry()
    gate.hold()
    server = AttributionServer(
        gate, [(4,)], max_batch=1, max_wait_ms=0.0, queue_depth=8,
        warmup=False, labeled=False, tenant_quota=0.25,
    )
    x = np.zeros((4,), np.float32)
    try:
        first = server.submit(x)  # parks the worker (no tenant, no quota)
        assert gate.entered.wait(timeout=10)
        server.submit(x, tenant="flood")
        server.submit(x, tenant="flood")  # cap = ceil(8 * 0.25) = 2
        with pytest.raises(QueueFullError):
            server.submit(x, tenant="flood")
        # the flooding tenant's quota does not tax the others
        server.submit(x, tenant="quiet")
        server.submit(x)
        gate.release.set()
        first.result(timeout=10)
    finally:
        gate.release.set()
        server.close()
    assert server.metrics.rejected == 1
    assert server.metrics.completed == 5


def test_tenant_quota_validation():
    with pytest.raises(ValueError):
        AttributionServer(lambda xs, ys: xs, [(4,)], warmup=False,
                          labeled=False, tenant_quota=1.5)


# -- per-tenant result-cache partitions ---------------------------------------


def test_cache_tenant_shards_isolate_and_fair_share():
    cache = ResultCache(4096, cache_id="t")
    v = np.zeros((128,), np.float32)  # 512B each; 8 fit globally
    cache.put("kb", v, tenant="b")
    assert cache.get("kb", tenant="a") is None  # shard isolation
    assert cache.get("kb", tenant="b") is not None
    # tenant "a" floods: fair share (4096 // 2 live shards = 2048 = 4
    # entries) bounds its own shard; "b"'s entry survives
    for i in range(16):
        cache.put(f"ka{i}", v, tenant="a")
    assert cache.get("kb", tenant="b") is not None
    st = cache.stats()
    assert st["tenants"]["a"]["entries"] <= 4
    assert st["tenants"]["a"]["bytes"] <= 2048
    assert st["tenants"]["b"]["hits"] == 2 and st["tenants"]["b"]["misses"] == 0
    assert st["tenants"]["a"]["misses"] == 1
    assert st["entries"] == st["tenants"]["a"]["entries"] + 1


def test_cache_key_folds_model_identity():
    cache = ResultCache(4096, cache_id="e")
    x = np.ones((4,), np.float32)
    assert cache.key(x, 1) != cache.key(x, 1, model="m")
    assert cache.key(x, 1, model="m") != cache.key(x, 1, model="n")
    assert cache.key(x, 1, model="m").endswith("|m")


def test_server_tenant_cache_hits_are_per_tenant():
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs) * 2.0, [(4,)], max_batch=1,
        max_wait_ms=0.0, warmup=False, labeled=False,
        result_cache=1 << 20,
    )
    x = np.ones((4,), np.float32)
    try:
        server.attribute(x, tenant="a")
        server.attribute(x, tenant="a")  # exact replay: a's shard hit
        server.attribute(x, tenant="b")  # same bytes, b's shard: a miss
        st = server.metrics.result_cache.stats()
        assert st["tenants"]["a"]["hits"] == 1
        assert st["tenants"]["b"]["hits"] == 0
        assert st["tenants"]["b"]["misses"] == 1
    finally:
        server.close()


# -- SLO tenant ladder --------------------------------------------------------


def test_slo_ladder_resolves_tenant_windows():
    from wam_tpu.obs.slo import SLObjectives, SLOTracker, parse_slo

    tr = SLOTracker({
        "4@interactive@vip": SLObjectives(p99_ms=10.0),
        "*@interactive@vip": SLObjectives(p99_ms=20.0),
        "4@interactive": SLObjectives(p99_ms=30.0),
        "*@interactive": SLObjectives(p99_ms=40.0),
        "4": SLObjectives(p99_ms=50.0),
        "*": SLObjectives(p99_ms=60.0),
    })
    assert tr.objectives_for("4@interactive@vip").p99_ms == 10.0
    assert tr.objectives_for("8@interactive@vip").p99_ms == 20.0
    assert tr.objectives_for("4@interactive@other").p99_ms == 30.0
    assert tr.objectives_for("8@interactive@other").p99_ms == 40.0
    assert tr.objectives_for("4@batch@vip").p99_ms == 50.0
    assert tr.objectives_for("8@batch").p99_ms == 60.0
    with pytest.raises(ValueError):
        parse_slo("4@@vip: p99_ms=10")  # empty QoS segment

    tr.note("4", latency_s=0.001, qos="interactive", tenant="vip", now=1.0)
    row = tr.snapshot_row(publish=False, now=1.5)
    assert "4@interactive@vip" in row["buckets"]
    assert row["tenants"] == ["vip"]


# -- ledger mining ------------------------------------------------------------


def test_mix_mines_model_and_tenant_dimensions():
    from wam_tpu.tune.mix import mine_rows

    rows = [
        {"metric": "serve_batch", "timestamp": 1.0 + i, "n_real": 2,
         "bucket": [4], "service_s": 0.01, "qos": {"interactive": 2},
         "model_id": "m1", "tenants": {"a": 1, "b": 1}}
        for i in range(4)
    ] + [
        {"metric": "serve_batch", "timestamp": 10.0, "n_real": 1,
         "bucket": [4], "service_s": 0.02, "qos": {"batch": 1}},
    ]
    mix = mine_rows(rows)
    assert set(mix.buckets) == {"m1|4", "4"}
    assert mix.buckets["m1|4"].model_id == "m1"
    assert mix.buckets["m1|4"].items == 8
    assert mix.tenants == {"a": 4, "b": 4}
    d = mix.to_dict()
    assert d["buckets"]["m1|4"]["model_id"] == "m1"
    assert "model_id" not in d["buckets"]["4"]
    assert d["tenants"] == {"a": 4, "b": 4}


def test_serve_batch_rows_carry_tenant_counts(tmp_path):
    ledger = str(tmp_path / "serve.jsonl")
    server = AttributionServer(
        lambda xs, ys: np.asarray(xs), [(4,)], max_batch=4,
        max_wait_ms=20.0, warmup=False, labeled=False,
        metrics_path=ledger,
    )
    x = np.ones((4,), np.float32)
    try:
        futs = [server.submit(x, tenant=t) for t in ("a", "a", "b", None)]
        for f in futs:
            f.result(timeout=10)
    finally:
        server.close()
    rows = [json.loads(line) for line in open(ledger)]
    batches = [r for r in rows if r.get("metric") == "serve_batch"]
    counts: dict = {}
    for r in batches:
        for t, n in (r.get("tenants") or {}).items():
            counts[t] = counts.get(t, 0) + n
    assert counts == {"a": 2, "b": 1}  # None submits are not counted
