"""ViT / ConvNeXt smoke tests + config/results/profiling infrastructure."""

import pytest
import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



def test_vit_forward():
    from wam_tpu.models.vit import vit_tiny_test

    model = vit_tiny_test(num_classes=9)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (2, 9)


def test_vit_wam_end_to_end():
    from wam_tpu.models.vit import vit_tiny_test
    from wam_tpu.wam2d import WaveletAttribution2D

    model = vit_tiny_test(num_classes=5)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    fn = lambda x: model.apply(variables, jnp.transpose(x, (0, 2, 3, 1)))
    expl = WaveletAttribution2D(fn, wavelet="haar", J=2, method="integratedgrad", n_samples=4)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((1, 3, 32, 32)), dtype=jnp.float32)
    out = expl(x, jnp.array([2]))
    assert out.shape == (1, 32, 32)
    assert np.all(np.isfinite(np.asarray(out)))


def test_convnext_forward_and_taps():
    from wam_tpu.models.convnext import convnext_test

    model = convnext_test(num_classes=6)
    x = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    out, state = model.apply(variables, x, mutable=["intermediates"])
    assert out.shape == (1, 6)
    assert "stage1" in state["intermediates"]
    assert "perturbations" in variables  # gradcam taps present


def test_config_defaults_match_reference():
    from wam_tpu.config import WAM1DConfig, WAM2DConfig, WAM3DConfig

    c2 = WAM2DConfig()
    assert (c2.wavelet, c2.J, c2.mode, c2.n_samples, c2.stdev_spread, c2.random_seed) == (
        "haar", 3, "reflect", 25, 0.25, 42)
    c1 = WAM1DConfig()
    assert (c1.n_mels, c1.n_fft, c1.sample_rate, c1.stdev_spread) == (128, 1024, 44100, 0.001)
    c3 = WAM3DConfig()
    assert (c3.mode, c3.EPS, c3.instance) == ("symmetric", 0.451, "voxels")


def test_config_cli_roundtrip():
    from wam_tpu.config import WAM2DConfig, add_config_args, config_from_args

    parser = argparse.ArgumentParser()
    add_config_args(parser, WAM2DConfig)
    args = parser.parse_args(["--wavelet", "db4", "--n-samples", "10"])
    cfg = config_from_args(args, WAM2DConfig)
    assert cfg.wavelet == "db4" and cfg.n_samples == 10 and cfg.J == 3


def test_results_writers(tmp_path):
    from wam_tpu.results import CsvWriter, JsonlWriter, MetricRecord, read_jsonl

    jpath = str(tmp_path / "metrics.jsonl")
    w = JsonlWriter(jpath)
    w.write(MetricRecord(metric="insertion_auc", value=0.7, unit="auc"))
    w.write({"metric": "deletion_auc", "value": 0.2})
    rows = read_jsonl(jpath)
    assert len(rows) == 2 and rows[0]["metric"] == "insertion_auc"
    assert w.done_keys() == {"insertion_auc", "deletion_auc"}

    cpath = str(tmp_path / "iou.csv")
    c = CsvWriter(cpath, ["percentage", "mean_iou"])
    c.write({"percentage": 0.05, "mean_iou": 0.156})
    assert "0.156" in open(cpath).read()


def test_stage_timer():
    from wam_tpu.profiling import StageTimer, trace

    t = StageTimer()
    with t.stage("a"):
        pass
    out = t.timed("jit", jax.jit(lambda v: v * 2), jnp.ones(4))
    assert out[0] == 2
    s = t.summary()
    assert set(s) == {"a", "jit"} and s["jit"]["calls"] == 1

    with trace("region"):
        jnp.ones(2)
