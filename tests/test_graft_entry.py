"""Driver entry points (`__graft_entry__.py`) must stay importable and
runnable: `entry()` jit-compiles single-device, `dryrun_multichip` executes
the full sharded SmoothGrad step on the virtual 8-device CPU mesh
(conftest.py forces the cpu platform and 8 host devices)."""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


def test_entry_jit_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[0].shape[0]
    assert out.ndim == 3 and out.shape[1] == out.shape[2]
    assert bool(jnp.isfinite(out).all())


def test_entry_nonzero_on_real_input():
    fn, args = graft.entry()
    x = jax.random.normal(jax.random.PRNGKey(7), args[0].shape, args[0].dtype)
    out = jax.jit(fn)(x, args[1])
    assert bool(jnp.isfinite(out).all())
    assert float(jnp.abs(out).max()) > 0


def test_dryrun_multichip_restores_dwt_impl():
    from wam_tpu.wavelets import get_dwt2_impl

    before = get_dwt2_impl()
    graft.dryrun_multichip(8)
    assert get_dwt2_impl() == before
