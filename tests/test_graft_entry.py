"""Driver entry points (`__graft_entry__.py`) must stay importable and
runnable: `entry()` jit-compiles single-device, `dryrun_multichip` executes
the full sharded SmoothGrad step on the virtual 8-device CPU mesh
(conftest.py forces the cpu platform and 8 host devices)."""

import pytest
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp

_REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))

import __graft_entry__ as graft  # noqa: E402

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



def test_entry_jit_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[0].shape[0]
    assert out.ndim == 3 and out.shape[1] == out.shape[2]
    assert bool(jnp.isfinite(out).all())


def test_entry_nonzero_on_real_input():
    fn, args = graft.entry()
    x = jax.random.normal(jax.random.PRNGKey(7), args[0].shape, args[0].dtype)
    out = jax.jit(fn)(x, args[1])
    assert bool(jnp.isfinite(out).all())
    assert float(jnp.abs(out).max()) > 0


def test_dryrun_multichip_restores_dwt_impl():
    from wam_tpu.wavelets import get_dwt2_impl

    before = get_dwt2_impl()
    graft.dryrun_multichip(8)
    assert get_dwt2_impl() == before


def test_dryrun_multichip_never_touches_default_backend():
    """Reproduce the driver's environment: a fresh process with NO cpu-platform
    override, so the default backend is whatever plugin registered itself
    (the tunneled TPU here; a broken TPU client in the round-1 driver run).
    `dryrun_multichip` must execute entirely on the virtual CPU pool — the
    round-1 gate failure was model init / iota / RNG dispatching to the
    default backend (VERDICT.md weak #1). The witness: every XLA compilation
    funnels through jax._src.compiler.compile_or_get_cached /
    backend_compile_and_load, so poisoning those for non-cpu backends
    faithfully emulates the driver's broken TPU client — any dispatch to the
    default backend (eager or jit) raises."""
    code = textwrap.dedent(
        """
        import __graft_entry__
        import jax
        import jax.numpy as jnp
        import jax._src.compiler as _compiler

        def _poison(fn):
            def wrapper(backend, *args, **kwargs):
                if backend.platform != "cpu":
                    raise RuntimeError(
                        "POISONED: compiled for non-cpu backend "
                        + backend.platform
                    )
                return fn(backend, *args, **kwargs)
            return wrapper

        devs = jax.devices()
        # Poison only when the dryrun is REQUIRED to fall back to the CPU
        # pool: a healthy default backend with >= 8 devices legitimately
        # hosts the mesh, and a cpu-only machine has nothing to poison.
        poison = any(d.platform != "cpu" for d in devs) and len(devs) < 8
        if poison:
            _compiler.compile_or_get_cached = _poison(
                _compiler.compile_or_get_cached)
            _compiler.backend_compile_and_load = _poison(
                _compiler.backend_compile_and_load)
            # Arm-check: a deliberate default-backend dispatch must trip the
            # poison, or a jax upgrade has re-routed the compile funnel and
            # the witness would be vacuous.
            try:
                jax.jit(lambda x: x + 1)(jnp.float32(1.0))
            except RuntimeError as e:
                assert "POISONED" in str(e), e
            else:
                raise SystemExit("poison did not fire on default-backend jit")

        __graft_entry__.dryrun_multichip(8)
        print("DRYRUN_OK", "poisoned" if poison else "unpoisoned")
        """
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=str(_REPO),
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (proc.stderr or proc.stdout)[-4000:]
    assert "DRYRUN_OK" in proc.stdout
