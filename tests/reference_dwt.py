"""Independent numpy reference DWT used to cross-check the JAX implementation.

Implements pywt's single-level convolution semantics by direct indexing (a
deliberately different code path from the XLA strided-conv implementation):
extend the signal by L-1 per side, correlate with the flipped decomposition
filter, keep odd output positions. Verified by hand against haar closed forms
in tests/test_dwt.py.
"""

import numpy as np


def _extend(x: np.ndarray, pad: int, mode: str) -> np.ndarray:
    if mode == "zero":
        return np.pad(x, pad, mode="constant")
    if mode == "constant":
        return np.pad(x, pad, mode="edge")
    if mode == "symmetric":
        return np.pad(x, pad, mode="symmetric")
    if mode == "reflect":
        return np.pad(x, pad, mode="reflect")
    if mode == "periodic":
        return np.pad(x, pad, mode="wrap")
    raise ValueError(mode)


def ref_dwt1(x, dec_lo, dec_hi, mode="symmetric"):
    L = len(dec_lo)
    ext = _extend(np.asarray(x, dtype=np.float64), L - 1, mode)
    flip_lo, flip_hi = dec_lo[::-1], dec_hi[::-1]
    n_full = len(ext) - L + 1
    corr_lo = np.array([np.dot(ext[i : i + L], flip_lo) for i in range(n_full)])
    corr_hi = np.array([np.dot(ext[i : i + L], flip_hi) for i in range(n_full)])
    return corr_lo[1::2], corr_hi[1::2]


def ref_idwt1(cA, cD, rec_lo, rec_hi):
    L = len(rec_lo)
    n = len(cA)
    up_a = np.zeros(2 * n - 1)
    up_a[::2] = cA
    up_d = np.zeros(2 * n - 1)
    up_d[::2] = cD
    full = np.convolve(up_a, rec_lo) + np.convolve(up_d, rec_hi)
    if L > 2:
        full = full[L - 2 : -(L - 2)]
    return full


def ref_wavedec(x, dec_lo, dec_hi, level, mode="symmetric"):
    coeffs = []
    a = np.asarray(x, dtype=np.float64)
    for _ in range(level):
        a, d = ref_dwt1(a, dec_lo, dec_hi, mode)
        coeffs.append(d)
    coeffs.append(a)
    return coeffs[::-1]


def ref_waverec(coeffs, rec_lo, rec_hi):
    a = coeffs[0]
    for d in coeffs[1:]:
        if len(a) > len(d):
            a = a[: len(d)]
        a = ref_idwt1(a, d, rec_lo, rec_hi)
    return a
