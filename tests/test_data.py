"""Data-layer tests with synthetic fixtures: native WAV decoder vs scipy,
ESC-50 fold splits + features, image preprocessing, 3D-MNIST loaders,
model registry, orbax round-trip."""

import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def wav_dir(tmp_path_factory):
    from scipy.io import wavfile

    d = tmp_path_factory.mktemp("esc50") / "ESC50"
    (d / "audio").mkdir(parents=True)
    (d / "meta").mkdir()
    rng = np.random.default_rng(0)
    rows = ["filename,fold,target,category,esc10,src_file,take"]
    for i in range(10):
        name = f"clip_{i}.wav"
        data = (rng.standard_normal(4096) * 8000).astype(np.int16)
        wavfile.write(str(d / "audio" / name), 8000, data)
        rows.append(f"{name},{i % 5 + 1},{i % 3},cat,False,src,A")
    (d / "meta" / "esc50.csv").write_text("\n".join(rows))
    return str(d)


def test_native_wav_reader_matches_scipy(wav_dir):
    from scipy.io import wavfile

    from wam_tpu.native import native_available, read_wav

    path = os.path.join(wav_dir, "audio", "clip_0.wav")
    sr, data = read_wav(path)
    sr_ref, ref = wavfile.read(path)
    assert sr == sr_ref
    np.testing.assert_allclose(data, ref.astype(np.float32) / 32768.0, atol=1e-6)
    # the native library should have built in this environment
    assert native_available()


def test_esc50_fold_split(wav_dir):
    from wam_tpu.data import ESC50

    train = ESC50(mode="train", num_FOLD=1, root_dir=wav_dir, sr=8000, nfft=256, hop=128, nmel=32)
    test = ESC50(mode="test", num_FOLD=1, root_dir=wav_dir, sr=8000, nfft=256, hop=128, nmel=32)
    assert len(train) + len(test) == 10
    assert len(test) == 2  # folds 1..5 cycle over 10 clips

    logmel, y, mag, logmag, phase, path, idx = train[0]
    assert logmel.ndim == 3 and logmel.shape[0] == 1 and logmel.shape[2] == 32
    assert 0 <= y < 3
    assert mag.shape[0] == 129  # F = nfft//2+1
    assert np.allclose(np.abs(phase), 1.0, atol=1e-3)  # unit phase

    mixed = train.overlap_two(0, 1)
    assert mixed[0].shape[2] == 32


def test_esc50_subset_and_noise(wav_dir):
    from wam_tpu.data import ESC50

    ds = ESC50(mode="train", num_FOLD=1, root_dir=wav_dir, select_class=[0, 2],
               add_noise=True, sr=8000, nfft=256, hop=128, nmel=32)
    _, y, *_ = ds[0]
    assert y in (0, 1)  # remapped to subset index


def test_load_sound(wav_dir):
    from wam_tpu.data import load_sound

    out = load_sound(wav_dir, n=["clip_0.wav", "clip_1.wav"])
    assert len(out["x"]) == 2 and len(out["y"]) == 2
    out_noise = load_sound(wav_dir, n=["clip_0.wav"], noise=True)
    assert out_noise["x"][0].shape == out["x"][0].shape


def test_add_0db_noise_snr():
    from wam_tpu.data import add_0db_noise

    rng = np.random.default_rng(1)
    sig = (rng.standard_normal(20000) * 1000).astype(np.int16)
    noisy = add_0db_noise(sig)
    assert noisy.dtype == np.int16
    noise = noisy.astype(np.float32) - sig.astype(np.float32)
    snr = 10 * np.log10((sig.astype(np.float32) ** 2).mean() / (noise**2).mean())
    assert abs(snr) < 1.0  # ~0 dB


def test_balanced_weights(wav_dir):
    from wam_tpu.data import ESC50, make_weights_for_balanced_classes

    ds = ESC50(mode="train", num_FOLD=1, root_dir=wav_dir, sr=8000, nfft=256, hop=128, nmel=32)
    w = make_weights_for_balanced_classes(ds, nclasses=3)
    assert len(w) == len(ds)
    assert all(x > 0 for x in w)


def test_preprocess_image_shapes():
    from PIL import Image

    from wam_tpu.data import preprocess_image

    img = Image.fromarray((np.random.default_rng(2).random((300, 400, 3)) * 255).astype(np.uint8))
    out = preprocess_image(img)
    assert out.shape == (3, 224, 224)
    out2 = preprocess_image(img, resize=64, crop=None, normalize=False)
    assert out2.shape == (3, 64, 64)
    assert out2.min() >= 0 and out2.max() <= 1


def test_load_images_assets(tmp_path):
    import json

    from PIL import Image

    from wam_tpu.data import load_images

    assets = tmp_path / "assets"
    assets.mkdir()
    for name, label in [("a.png", 5), ("b.png", 7)]:
        Image.fromarray(np.zeros((50, 50, 3), np.uint8)).save(assets / name)
    (assets / "labels.json").write_text(json.dumps({"a.png": 5, "b.png": 7}))
    x, y = load_images(str(tmp_path))
    assert x.shape == (2, 3, 224, 224)
    assert y == [5, 7]


def test_imagenet_validation_loader(tmp_path):
    from PIL import Image

    from wam_tpu.data import load_imagenet_validation

    for i in range(3):
        Image.fromarray(np.zeros((60, 60, 3), np.uint8)).save(tmp_path / f"img{i}.JPEG")
    (tmp_path / "val.txt").write_text("\n".join(f"img{i}.JPEG {i * 10}" for i in range(3)))
    x, y = load_imagenet_validation(str(tmp_path), count=3)
    assert x.shape == (3, 3, 224, 224)
    assert y == [0, 10, 20]


def test_show_roundtrip():
    from wam_tpu.data import show

    img = np.random.default_rng(3).standard_normal((3, 16, 16)).astype(np.float32)
    out = show(img, plot=False)
    assert out.shape == (16, 16, 3)
    assert out.min() >= 0 and out.max() <= 1.0


def test_mnist3d_loaders(tmp_path):
    import h5py

    from wam_tpu.data import batches, load_3d_mnist, load_3dvoxel_mnist

    d = tmp_path / "3DMNIST"
    d.mkdir()
    rng = np.random.default_rng(4)
    for split in ("test", "train"):
        with h5py.File(d / f"{split}_point_clouds.h5", "w") as f:
            for i in range(4):
                g = f.create_group(str(i))
                g.create_dataset("points", data=rng.random((200, 3)))
                g.attrs["label"] = i % 10
    with h5py.File(d / "full_dataset_vectors.h5", "w") as f:
        f.create_dataset("X_train", data=rng.random((6, 4096)))
        f.create_dataset("y_train", data=np.arange(6) % 10)
        f.create_dataset("X_test", data=rng.random((4, 4096)))
        f.create_dataset("y_test", data=np.arange(4) % 10)

    x, y = load_3d_mnist(str(tmp_path), num_points=64)
    assert x.shape == (4, 64, 3) and y.shape == (4,)
    (xt, yt), (xtr, ytr) = load_3dvoxel_mnist(str(tmp_path))
    assert xt.shape == (4, 16, 16, 16) and xtr.shape == (6, 16, 16, 16)
    got = list(batches(xt, yt, batch_size=3))
    assert got[0][0].shape[0] == 3 and got[1][0].shape[0] == 1


@pytest.mark.slow
def test_model_registry_and_orbax_roundtrip(tmp_path):
    import jax.numpy as jnp

    from wam_tpu.data import build_vision_model, load_variables, save_variables

    model, variables, fn = build_vision_model("resnet18", num_classes=7, image_size=32)
    out = fn(jnp.zeros((1, 3, 32, 32)))
    assert out.shape == (1, 7)

    path = str(tmp_path / "ckpt")
    save_variables(path, variables)
    restored = load_variables(path, variables)
    out2 = model.apply(restored, jnp.zeros((1, 32, 32, 3)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)

    with pytest.raises(ValueError):
        build_vision_model("nope")


def _write_wavs(tmp_path, n, sr=8000, seconds=0.05):
    from scipy.io import wavfile

    rng = np.random.default_rng(17)
    paths = []
    for i in range(n):
        wave = (rng.standard_normal(int(sr * seconds)) * 8000).astype(np.int16)
        p = tmp_path / f"clip{i}.wav"
        wavfile.write(p, sr, wave)
        paths.append(str(p))
    return paths


def test_wav_prefetcher_ordered_and_matches_read_wav(tmp_path):
    """The native threaded prefetcher must deliver every file, in
    submission order, with samples identical to the synchronous decoder."""
    from wam_tpu.native import WavPrefetcher, read_wav

    paths = _write_wavs(tmp_path, 12)
    ref = [read_wav(p) for p in paths]
    with WavPrefetcher(paths, workers=4, capacity=3) as pf:
        got = list(pf)
    assert len(got) == len(paths)
    for (sr_a, a), (sr_b, b) in zip(got, ref):
        assert sr_a == sr_b
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_wav_prefetcher_single_worker_and_empty(tmp_path):
    from wam_tpu.native import WavPrefetcher, read_wav

    paths = _write_wavs(tmp_path, 3)
    with WavPrefetcher(paths, workers=1, capacity=1) as pf:
        got = list(pf)
    assert len(got) == 3
    np.testing.assert_array_equal(got[2][1], read_wav(paths[2])[1])
    with WavPrefetcher([], workers=2) as pf:
        assert list(pf) == []


def test_esc50_iter_waveforms(tmp_path):
    """Dataset-level streaming decode: ordered, normalized, mono."""
    import csv

    from wam_tpu.data.audio import ESC50

    audio_dir = tmp_path / "audio"
    audio_dir.mkdir()
    from scipy.io import wavfile

    rng = np.random.default_rng(23)
    rows = []
    for i in range(6):
        name = f"1-{i}-A-{i % 3}.wav"
        wave = (rng.standard_normal(400) * 5000).astype(np.int16)
        wavfile.write(audio_dir / name, 8000, wave)
        rows.append({"filename": name, "fold": "2", "target": str(i % 3),
                     "category": "x", "esc10": "False", "src_file": "0",
                     "take": "A"})
    meta = tmp_path / "meta"
    meta.mkdir()
    with open(meta / "esc50.csv", "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]))
        w.writeheader()
        w.writerows(rows)

    ds = ESC50(mode="train", num_FOLD=1, root_dir=str(tmp_path))
    assert len(ds) == 6
    out = list(ds.iter_waveforms(workers=3, capacity=2))
    assert [i for i, _ in out] == list(range(6))
    for i, wf in out:
        direct = ds._load(ds.rows[i])
        np.testing.assert_allclose(wf, direct, atol=1e-7)


def test_wav_prefetcher_missing_file_raises(tmp_path):
    """A missing file mid-stream must raise, not silently truncate the
    epoch (error codes are distinct from the exhaustion sentinel)."""
    import pytest as _pytest

    from wam_tpu.native import WavPrefetcher, native_available

    paths = _write_wavs(tmp_path, 3)
    paths.insert(1, str(tmp_path / "missing.wav"))
    with WavPrefetcher(paths, workers=2, capacity=2) as pf:
        it = iter(pf)
        next(it)  # clip0 decodes fine
        with _pytest.raises(IOError):
            next(it)


def test_wav_prefetcher_early_break_joins_threads(tmp_path):
    """Breaking out of the iterator mid-stream must still join/destroy the
    native workers (generator finally -> close)."""
    from wam_tpu.native import WavPrefetcher

    paths = _write_wavs(tmp_path, 8)
    pf = WavPrefetcher(paths, workers=3, capacity=2)
    for k, (sr, a) in enumerate(pf):
        if k == 2:
            break
    assert pf._handle is None and not pf._fallback  # closed either path


def test_wav_prefetcher_single_use_raises(tmp_path):
    import pytest as _pytest

    from wam_tpu.native import WavPrefetcher

    paths = _write_wavs(tmp_path, 2)
    pf = WavPrefetcher(paths, workers=1)
    assert len(list(pf)) == 2
    with _pytest.raises(RuntimeError):
        list(pf)


def test_wav_prefetcher_double_iter_raises_eagerly(tmp_path):
    """iter() twice BEFORE consuming anything must raise immediately — a
    second generator would interleave the one shared native ordinal stream
    and silently mispair paths with samples (round-3 advisor finding)."""
    from wam_tpu.native import WavPrefetcher

    paths = _write_wavs(tmp_path, 4)
    pf = WavPrefetcher(paths, workers=2, capacity=2)
    it1 = iter(pf)
    with pytest.raises(RuntimeError):
        iter(pf)  # eager: raises at iter(), not at first next()
    assert len(list(it1)) == 4  # the first iterator is unaffected


def test_wav_prefetcher_small_start_buffer_grows(tmp_path):
    """The native iterator starts with a ~1 MB buffer and grows to each
    item's exact size via pf_next_size — items larger than the start buffer
    must still decode losslessly (no 128 MB worst-case preallocation)."""
    from wam_tpu.native import WavPrefetcher, native_available, read_wav

    if not native_available():
        pytest.skip("native library unavailable")
    # 2-channel, 300k frames = 600k samples > the 2^18-sample start buffer
    rng = np.random.default_rng(7)
    data = (rng.standard_normal((300_000, 2)) * 8000).astype(np.int16)
    from scipy.io import wavfile

    p = tmp_path / "big.wav"
    wavfile.write(p, 16_000, data)
    paths = [str(p)] + _write_wavs(tmp_path, 2)
    ref = [read_wav(q) for q in paths]
    with WavPrefetcher(paths, workers=2, capacity=2) as pf:
        got = list(pf)
    assert len(got) == len(ref)
    for (sr_a, a), (sr_b, b) in zip(got, ref):
        assert sr_a == sr_b and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_wav_prefetcher_concurrent_close_is_safe(tmp_path):
    """close() from another thread while a consumer iterates must not crash
    or deadlock: the wrapper serializes close() behind the in-flight native
    call (and the C layer's -8/drain protocol covers direct C callers), so
    the consumer sees a clean stop."""
    import threading

    from wam_tpu.native import WavPrefetcher, native_available

    if not native_available():
        pytest.skip("native library unavailable")
    for _ in range(5):  # a few rounds to vary thread interleaving
        paths = _write_wavs(tmp_path, 32)
        pf = WavPrefetcher(paths, workers=2, capacity=2)
        got, err = [], []

        def consume():
            try:
                for item in iter(pf):
                    got.append(item)
            except (IOError, RuntimeError) as e:  # -8 surfaces as IOError
                err.append(e)

        t = threading.Thread(target=consume)
        t.start()
        pf.close()
        t.join(timeout=30)
        assert not t.is_alive(), "consumer deadlocked against pf_destroy"


def test_wav_prefetcher_abandoned_is_finalized(tmp_path):
    """A constructed-but-never-iterated prefetcher must be cleaned up by its
    finalizer (no native thread leak)."""
    import gc

    from wam_tpu.native import WavPrefetcher

    paths = _write_wavs(tmp_path, 4)
    pf = WavPrefetcher(paths, workers=2, capacity=2)
    fin = pf._finalizer
    del pf
    gc.collect()
    assert not fin.alive  # ran (or was detached by an explicit close)


def test_wav_prefetcher_python_fallback(tmp_path, monkeypatch):
    """The GIL-threaded fallback (no g++) must honor the same contract:
    ordered delivery, bounded work-ahead, matching samples, single-use."""
    import pytest as _pytest

    import wam_tpu.native as native

    monkeypatch.setattr(native, "_load", lambda: None)
    paths = _write_wavs(tmp_path, 10)
    ref = []
    # reference decode through scipy (read_wav also hits the fallback now)
    from scipy.io import wavfile

    for p in paths:
        sr, data = wavfile.read(p)
        ref.append((sr, data.astype(np.float32) / 32768.0))

    with native.WavPrefetcher(paths, workers=3, capacity=2) as pf:
        assert pf._handle is None and pf._fallback  # really the fallback
        got = list(pf)
    assert len(got) == 10
    for (sr_a, a), (sr_b, b) in zip(got, ref):
        assert sr_a == sr_b
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-7)
    pf2 = native.WavPrefetcher(paths, workers=2, capacity=2)
    list(pf2)
    with _pytest.raises(RuntimeError):
        list(pf2)
