"""Eval fan engine (`wam_tpu/evalsuite/fan.py`, round 9).

- plan geometry: int caps reproduce the cap//fan law, "auto" resolves the
  tuned fan_cap AND the fan_chunk images-per-chunk override;
- the single-fetch contract: exactly ONE `jax.device_get` per metric call
  (μ-fidelity, insertion/deletion AUC, input fidelity, baseline fans) —
  probed with `fan.fetch_scope` (the thread-isolated scoped counter; the
  eval2d test double-probes by also patching `jax.device_get` itself, the
  late-binding contract);
- parity: the fan-engine metric paths reproduce the per-chunk reference
  path bit for bit at f32 on CPU, across chunk geometries;
- tuned-chunk plumbing through Eval1DWAM / Eval2DWAM / EvalImageBaselines.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.evalsuite import fan
from wam_tpu.evalsuite.fan import FanPlan, fan_chunk_geometry, plan_fan
from wam_tpu.tune import invalidate_process_cache, record_schedule


@pytest.fixture
def sched_cache(tmp_path, monkeypatch):
    """Isolated user-layer schedule cache (the test_tune fixture)."""
    path = tmp_path / "schedules.json"
    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE", str(path))
    monkeypatch.delenv("WAM_TPU_NO_SCHEDULE_CACHE", raising=False)
    invalidate_process_cache()
    yield path
    invalidate_process_cache()


class TinyImgModel(nn.Module):
    classes: int = 5

    @nn.compact
    def __call__(self, x):
        x = jnp.transpose(x, (0, 2, 3, 1))
        x = nn.Conv(8, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x).mean(axis=(1, 2))
        return nn.Dense(self.classes)(x)


class TinyAudioModel(nn.Module):
    classes: int = 4

    @nn.compact
    def __call__(self, x):  # (B, 1, T, M)
        return nn.Dense(self.classes)(x.reshape((x.shape[0], -1)))


@pytest.fixture(scope="module")
def img_model_fn():
    model = TinyImgModel()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    return lambda x: model.apply(params, x)


@pytest.fixture
def count_device_get(monkeypatch):
    """Patch `jax.device_get` with a counting wrapper; yields the counter.
    `fan.device_fetch` late-binds the attribute, so every fan-engine fetch
    lands here — and so would any stray fetch a metric path grew back."""
    calls = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get", lambda t: (calls.append(1), real(t))[1])
    return calls


# -- geometry / planning ----------------------------------------------------


def test_plan_fan_int_cap_reproduces_law():
    # fan smaller than cap: several images per chunk, no inner split
    assert plan_fan(256, 65) == FanPlan(256, 3, None)
    # fan exceeds cap: one image per chunk, inner fan chunk = cap
    assert plan_fan(64, 129) == FanPlan(64, 1, 64)
    assert plan_fan(128, 128) == FanPlan(128, 1, None)
    for cap, f in [(256, 65), (64, 129), (16, 6)]:
        assert (plan_fan(cap, f).images_per_chunk,
                plan_fan(cap, f).fan_chunk) == fan_chunk_geometry(cap, f)


def test_plan_fan_auto_resolves_tuned_cap_and_chunk(sched_cache):
    # no entry: default cap, law geometry
    assert plan_fan("auto", 65) == FanPlan(128, 1, None)
    record_schedule("eval2d", (65,), 65, {"fan_cap": 256, "fan_chunk": 4})
    assert plan_fan("auto", 65) == FanPlan(256, 4, None)
    # cap-only entry falls back to the law for the chunk
    record_schedule("eval1d", (65,), 65, {"fan_cap": 512})
    assert plan_fan("auto", 65, workload="eval1d") == FanPlan(512, 7, None)
    # fan_chunk=1 with an over-cap fan keeps the inner fan split
    record_schedule("eval2d", (300,), 300, {"fan_cap": 64, "fan_chunk": 1})
    assert plan_fan("auto", 300) == FanPlan(64, 1, 64)


def test_tuned_plan_plumbs_through_evaluators(sched_cache, img_model_fn):
    from wam_tpu.evalsuite.eval1d import Eval1DWAM
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.evalsuite.eval_baselines import EvalImageBaselines

    record_schedule("eval2d", (9,), 9, {"fan_cap": 32, "fan_chunk": 3})
    record_schedule("eval1d", (9,), 9, {"fan_cap": 48, "fan_chunk": 5})

    ev2 = Eval2DWAM(img_model_fn, explainer=lambda x, y: None,
                    batch_size="auto")
    assert ev2._fan_plan(9) == FanPlan(32, 3, None)
    assert ev2._fan_cap(9) == 32

    model = TinyImgModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    evb = EvalImageBaselines(model, variables, method="saliency",
                             batch_size="auto", nchw=False)
    assert evb._fan_plan(9) == FanPlan(32, 3, None)

    ev1 = Eval1DWAM(img_model_fn, explainer=lambda x, y: None,
                    batch_size="auto")
    assert ev1._fan_plan(9) == FanPlan(48, 5, None)
    # explicit ints still pin the cap, tuned entries notwithstanding
    assert Eval2DWAM(img_model_fn, explainer=None,
                     batch_size=16)._fan_plan(9) == FanPlan(16, 1, None)


# -- the single-fetch contract ----------------------------------------------


def test_device_fetch_counter():
    fan.reset_fetch_count()
    out = fan.device_fetch(jnp.arange(3.0))
    assert isinstance(out, np.ndarray)
    assert fan.fetch_count() == 1
    fan.reset_fetch_count()
    assert fan.fetch_count() == 0


def test_fetch_scope_counts_nest_and_survive_exit():
    with fan.fetch_scope() as outer:
        fan.device_fetch(jnp.zeros(2))
        with fan.fetch_scope() as inner:
            fan.device_fetch(jnp.zeros(2))
        assert inner.count == 1  # inner sees only its own window...
        assert outer.count == 2  # ...outer sees both
    fan.device_fetch(jnp.zeros(2))  # after exit: no longer counted
    assert outer.count == 2 and inner.count == 1


def test_fetch_scope_is_thread_isolated():
    import threading

    with fan.fetch_scope() as fs:
        t = threading.Thread(target=lambda: fan.device_fetch(jnp.zeros(2)))
        t.start()
        t.join()
        assert fs.count == 0  # another thread's fetches don't leak in
        fan.device_fetch(jnp.zeros(2))
        assert fs.count == 1


def test_one_fetch_per_metric_call_eval2d(img_model_fn, count_device_get):
    from wam_tpu.evalsuite.eval2d import Eval2DWAM

    ev = Eval2DWAM(img_model_fn,
                   explainer=lambda x, y: jnp.ones(x.shape[:1] + x.shape[-2:]),
                   wavelet="haar", J=2, batch_size=16)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 3, 32, 32)),
                    dtype=jnp.float32)
    y = [1, 3]
    ev.precompute(x, np.asarray(y))
    count_device_get.clear()
    with fan.fetch_scope() as fs:
        ev.insertion(x, y, n_iter=8)
    assert fs.count == 1
    assert len(count_device_get) == 1  # scoped and patched probes agree
    with fan.fetch_scope() as fs:
        ev.deletion(x, y, n_iter=8)
    assert fs.count == 1
    with fan.fetch_scope() as fs:
        ev.mu_fidelity(x, y, grid_size=8, sample_size=6, subset_size=12)
    assert fs.count == 1


def test_one_fetch_per_metric_call_eval2d_bf16_fan(img_model_fn):
    """Round 17: the bf16 fan keeps the single-fetch contract — the casting
    shim lives inside the traced runner (`fan.cast_model_fn`), so precision
    never adds a host round-trip."""
    from wam_tpu.evalsuite.eval2d import Eval2DWAM

    ev = Eval2DWAM(img_model_fn,
                   explainer=lambda x, y: jnp.ones(x.shape[:1] + x.shape[-2:]),
                   wavelet="haar", J=2, batch_size=16, precision="bf16")
    assert ev._fan_plan(9).fan_dtype == "bf16"
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 3, 32, 32)),
                    dtype=jnp.float32)
    y = [1, 3]
    ev.precompute(x, np.asarray(y))
    with fan.fetch_scope() as fs:
        ev.insertion(x, y, n_iter=8)
    assert fs.count == 1
    with fan.fetch_scope() as fs:
        ev.mu_fidelity(x, y, grid_size=8, sample_size=6, subset_size=12)
    assert fs.count == 1


def test_one_fetch_per_metric_call_baselines():
    from wam_tpu.evalsuite.eval_baselines import EvalImageBaselines

    model = TinyImgModel()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    ev = EvalImageBaselines(model, variables, method="saliency",
                            batch_size=16, nchw=False)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((1, 3, 32, 32)),
                    dtype=jnp.float32)
    ev.precompute(x, np.asarray([0]))
    with fan.fetch_scope() as fs:
        ev.insertion(x, [0], n_iter=8)
    assert fs.count == 1
    with fan.fetch_scope() as fs:
        ev.mu_fidelity(x, [0], grid_size=8, sample_size=5, subset_size=10)
    assert fs.count == 1


def test_one_fetch_per_metric_call_eval1d_input_fidelity():
    from wam_tpu.evalsuite.eval1d import Eval1DWAM
    from wam_tpu.wam1d import normalize_waveforms

    model = TinyAudioModel()
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 2048)),
                    dtype=jnp.float32)
    ev = Eval1DWAM(lambda m: None, explainer=None, n_fft=256, n_mels=16)
    mel = ev._melspec(normalize_waveforms(x))
    variables = model.init(jax.random.PRNGKey(0), mel)
    ev.model_fn = lambda m: model.apply(variables, m)
    ev.explainer = lambda xx, yy: (jnp.ones(mel[:, 0].shape), [])

    y = [0, 1]
    ev.precompute(normalize_waveforms(x), np.asarray(y))
    with fan.fetch_scope() as fs:
        preds = ev.input_fidelity(x, y, target="melspec")
    assert fs.count == 1  # the raw-logits tensor, fetched once
    assert len(preds) == 2
    with fan.fetch_scope() as fs:
        ev.faithfulness_of_spectra(x, y, target="melspec")
    assert fs.count == 1


# -- parity vs the per-chunk reference path ---------------------------------


def test_auc_fan_matches_reference_bit_for_bit(img_model_fn):
    """The evaluator's fan path (plan-chunked, run_fan-fetched) must equal
    the direct per-chunk runner + plain fetch — and itself across chunk
    geometries — exactly at f32 on CPU."""
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.evalsuite.metrics import batched_auc_runner

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 3, 32, 32)), dtype=jnp.float32)
    y = [0, 1, 2, 3]
    wams = jnp.asarray(rng.standard_normal((4, 32, 32)), dtype=jnp.float32)
    n_iter = 8

    def build(batch_size):
        return Eval2DWAM(img_model_fn, explainer=lambda xx, yy: wams,
                         wavelet="haar", J=2, batch_size=batch_size)

    ev = build(16)
    scores, curves = ev.evaluate_auc(x, y, "insertion", n_iter=n_iter)

    # reference: the same body dispatched directly, fetched via np.asarray
    # (the pre-fan path), at a DIFFERENT chunk geometry
    ref_runner = batched_auc_runner(
        lambda img, wam: ev._perturb_for_auc(img, wam, "insertion", n_iter),
        img_model_fn, images_per_chunk=1)
    ref = np.asarray(ref_runner(x, wams, jnp.asarray(y)))
    np.testing.assert_array_equal(np.asarray(scores), ref[:, 0])
    np.testing.assert_array_equal(np.asarray(curves), ref[:, 1:])

    # and a third geometry through the full evaluator path
    scores2, curves2 = build(9 * 4).evaluate_auc(x, y, "insertion",
                                                 n_iter=n_iter)
    np.testing.assert_array_equal(np.asarray(scores), np.asarray(scores2))
    np.testing.assert_array_equal(np.asarray(curves), np.asarray(curves2))


def test_mu_fan_matches_reference_bit_for_bit(img_model_fn):
    from wam_tpu.evalsuite.eval2d import Eval2DWAM

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    y = [1, 4]
    wams = jnp.asarray(rng.standard_normal((2, 32, 32)), dtype=jnp.float32)

    ev = Eval2DWAM(img_model_fn, explainer=lambda xx, yy: wams,
                   wavelet="haar", J=2, batch_size=16)
    mus = ev.mu_fidelity(x, y, grid_size=8, sample_size=6, subset_size=12)

    # reference: the same runner at images_per_chunk=1, invoked directly and
    # fetched with np.asarray (the pre-fan path)
    rand_all, onehot_all = ev._mu_random_draws(2, 8, 6, 12)
    ref_runner = ev._make_mu_runner(8, 6, plan=FanPlan(16, 1, None))
    ref = np.asarray(ref_runner(x, wams, jnp.asarray(y), rand_all, onehot_all))
    np.testing.assert_array_equal(np.asarray(mus, dtype=np.float32),
                                  ref.astype(np.float32))


def test_run_cached_auc_accepts_plan_and_int(img_model_fn):
    """Back-compat: `run_cached_auc` takes either a FanPlan or a plain int
    cap, and the two agree when the plan is the law plan."""
    from wam_tpu.evalsuite.metrics import (
        fan_chunk_geometry as geom,
        generate_masks,
        run_cached_auc,
    )

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    expl = jnp.asarray(rng.standard_normal((2, 32, 32)), dtype=jnp.float32)
    y = np.array([0, 1])
    n_iter = 4

    def inputs_fn(x_s, e_s):
        ins, _ = generate_masks(n_iter, e_s)
        return x_s[None] * ins[:, None]

    s_int, c_int = run_cached_auc({}, "m", inputs_fn, img_model_fn, 16,
                                  n_iter, x, expl, y)
    plan = FanPlan(16, *geom(16, n_iter + 1))
    s_plan, c_plan = run_cached_auc({}, "m", inputs_fn, img_model_fn, plan,
                                    n_iter, x, expl, y)
    np.testing.assert_array_equal(np.asarray(s_int), np.asarray(s_plan))
    np.testing.assert_array_equal(np.asarray(c_int), np.asarray(c_plan))
