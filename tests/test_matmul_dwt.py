"""Parity of the matmul/Pallas DWT forms against the conv form.

All three 2D analysis backends (conv, matmul, pallas) must agree exactly in
values and gradients for every wavelet x mode x size — including odd sizes
where boundary handling matters most.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.wavelets import transform as tf
from wam_tpu.wavelets import matmul as mm
from wam_tpu.wavelets.filters import build_wavelet
from wam_tpu.wavelets.transform import _analysis, _synthesis

# slow tier (VERDICT.md round-2 #7): heavyweight compiles / subprocesses;
# core tier is pytest -m 'not slow' (see PARITY.md)
pytestmark = pytest.mark.slow



WAVELETS = ["haar", "db4", "sym3"]
MODES = ["zero", "reflect", "symmetric", "periodic", "constant"]


@pytest.fixture(autouse=True)
def _restore_impl():
    yield
    tf.set_dwt2_impl("auto")


@pytest.mark.parametrize("wavelet", WAVELETS)
@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("size", [(16, 16), (17, 23), (32, 16)])
def test_analysis2_mm_matches_conv(wavelet, mode, size):
    wav = build_wavelet(wavelet)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, *size))
    ref = _analysis(x, wav, mode, 2)
    got = mm.analysis2_mm(x, wav, mode)
    np.testing.assert_allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("wavelet", WAVELETS)
def test_synthesis2_mm_matches_conv(wavelet):
    wav = build_wavelet(wavelet)
    sub = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 9, 9))
    out_shape = (2 * 9 - wav.filt_len + 2, 2 * 9 - wav.filt_len + 2)
    ref = _synthesis(sub, wav, 2, out_shape)
    got = mm.synthesis2_mm(sub, wav, out_shape)
    np.testing.assert_allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db4"])
@pytest.mark.parametrize("mode", ["reflect", "zero"])
def test_pallas_matches_conv(wavelet, mode):
    wav = build_wavelet(wavelet)
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 16, 16))
    ref = _analysis(x, wav, mode, 2)
    got = mm.dwt2_pallas(x, wav, mode)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_pallas_gradient_matches_conv():
    wav = build_wavelet("db4")
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 16))
    w = jax.random.normal(jax.random.PRNGKey(4), (2, 4, 11, 11))

    def loss_conv(x):
        return jnp.sum(_analysis(x, wav, "reflect", 2) * w)

    def loss_pallas(x):
        return jnp.sum(mm.dwt2_pallas(x, wav, "reflect") * w)

    np.testing.assert_allclose(
        jax.grad(loss_pallas)(x), jax.grad(loss_conv)(x), atol=1e-5
    )


@pytest.mark.parametrize("impl", ["matmul", "pallas"])
def test_wavedec2_impl_switch_end_to_end(impl):
    """The full multi-level decomposition and the engine-facing dwt2 agree
    across backends, under jit."""
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 3, 32, 32))
    ref = tf.wavedec2(x, "db4", level=3, mode="reflect")
    tf.set_dwt2_impl(impl)
    got = jax.jit(lambda x: tf.wavedec2(x, "db4", level=3, mode="reflect"))(x)
    tf.set_dwt2_impl("auto")
    np.testing.assert_allclose(got[0], ref[0], atol=1e-4)
    for g, r in zip(got[1:], ref[1:]):
        for gc, rc in zip(g, r):
            np.testing.assert_allclose(gc, rc, atol=1e-4)


@pytest.mark.parametrize("impl", ["matmul", "pallas"])
def test_waverec2_roundtrip_impl_switch(impl):
    """wavedec2 -> waverec2 reconstructs under the non-conv backends (idwt2
    dispatches to the matmul synthesis)."""
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 3, 32, 32))
    tf.set_dwt2_impl(impl)
    coeffs = tf.wavedec2(x, "db4", level=2, mode="reflect")
    rec = tf.waverec2(coeffs, "db4")
    tf.set_dwt2_impl("auto")
    np.testing.assert_allclose(rec[..., :32, :32], x, atol=1e-4)


def test_custom_wavelet_filters_honored():
    """A Wavelet object with custom taps (not matching its name) must produce
    the same result through the matmul backend as through conv — the matrix
    cache keys on the taps, not the name."""
    import dataclasses

    custom = dataclasses.replace(build_wavelet("sym3"), name="db4")
    x = jax.random.normal(jax.random.PRNGKey(8), (1, 20, 20))
    ref = _analysis(x, custom, "reflect", 2)
    got = mm.analysis2_mm(x, custom, "reflect")
    np.testing.assert_allclose(got, ref, atol=1e-5)
    # identical to genuine sym3 (the taps), despite the lying name
    np.testing.assert_allclose(
        got, mm.analysis2_mm(x, build_wavelet("sym3"), "reflect"), atol=1e-6
    )


def test_bad_impl_rejected():
    with pytest.raises(ValueError):
        tf.set_dwt2_impl("cuda")


def test_matmul_roundtrip():
    """analysis -> synthesis reconstructs the signal (periodic/reflect)."""
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 24, 24))
    for mode in ("periodic", "reflect"):
        sub = mm.analysis2_mm(x, "db4", mode)
        rec = mm.synthesis2_mm(sub, "db4", (24, 24))
        np.testing.assert_allclose(rec, x, atol=1e-4)


def test_pallas_bf16_in_f32_accumulate():
    """bf16 inputs are accepted directly (half HBM traffic) with f32
    accumulation and FLOAT32 coefficients out, so the multi-level cascade
    never re-rounds (VERDICT.md round-2 #6)."""
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 32, 32), jnp.float32)
    ref = mm.dwt2_pallas(x, "db4", "reflect")
    got = mm.dwt2_pallas(x.astype(jnp.bfloat16), "db4", "reflect")
    assert ref.dtype == jnp.float32 and got.dtype == jnp.float32
    # only the one-time input rounding separates the two paths
    scale = float(jnp.abs(ref).max())
    assert float(jnp.abs(got - ref).max()) < 0.01 * scale
    cos = float(
        (ref * got).sum()
        / (jnp.linalg.norm(ref.ravel()) * jnp.linalg.norm(got.ravel()))
    )
    assert cos > 0.9999

    # gradient flows back in the INPUT dtype
    g = jax.grad(lambda t: mm.dwt2_pallas(t, "db4", "reflect").sum())(
        x.astype(jnp.bfloat16)
    )
    assert g.dtype == jnp.bfloat16


@pytest.mark.parametrize("impl", ["pallas", "matmul", "conv"])
def test_wavedec2_bf16_cascade_stays_f32(impl):
    """End-to-end multi-level wavedec2 with bf16 input: every backend
    returns f32 coefficients (bf16-in/f32-accumulate policy lives in the
    dwt2 dispatch, not just the pallas kernel) and tracks the f32 path."""
    tf.set_dwt2_impl(impl)
    try:
        x = jax.random.normal(jax.random.PRNGKey(10), (1, 48, 48), jnp.float32)
        ref = tf.wavedec2(x, "db4", 2, "reflect")
        got = tf.wavedec2(x.astype(jnp.bfloat16), "db4", 2, "reflect")
        assert got[0].dtype == jnp.float32
        assert got[1].diagonal.dtype == jnp.float32
        for r, g in zip(jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(got)):
            rn = jnp.linalg.norm(r.ravel()) * jnp.linalg.norm(g.ravel())
            cos = float((r * g).sum() / rn) if float(rn) else 1.0
            assert cos > 0.999
    finally:
        tf.set_dwt2_impl("auto")
