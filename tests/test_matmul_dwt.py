"""Parity of the matmul/Pallas DWT forms against the conv form.

All three 2D analysis backends (conv, matmul, pallas) must agree exactly in
values and gradients for every wavelet x mode x size — including odd sizes
where boundary handling matters most.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.wavelets import transform as tf
from wam_tpu.wavelets import matmul as mm
from wam_tpu.wavelets.filters import build_wavelet
from wam_tpu.wavelets.transform import _analysis, _synthesis


WAVELETS = ["haar", "db4", "sym3"]
MODES = ["zero", "reflect", "symmetric", "periodic", "constant"]


@pytest.fixture(autouse=True)
def _restore_impl():
    yield
    tf.set_dwt2_impl("auto")


@pytest.mark.parametrize("wavelet", WAVELETS)
@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("size", [(16, 16), (17, 23), (32, 16)])
def test_analysis2_mm_matches_conv(wavelet, mode, size):
    wav = build_wavelet(wavelet)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, *size))
    ref = _analysis(x, wav, mode, 2)
    got = mm.analysis2_mm(x, wav, mode)
    np.testing.assert_allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("wavelet", WAVELETS)
def test_synthesis2_mm_matches_conv(wavelet):
    wav = build_wavelet(wavelet)
    sub = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 9, 9))
    out_shape = (2 * 9 - wav.filt_len + 2, 2 * 9 - wav.filt_len + 2)
    ref = _synthesis(sub, wav, 2, out_shape)
    got = mm.synthesis2_mm(sub, wav, out_shape)
    np.testing.assert_allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("wavelet", ["haar", "db4"])
@pytest.mark.parametrize("mode", ["reflect", "zero"])
def test_pallas_matches_conv(wavelet, mode):
    wav = build_wavelet(wavelet)
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 16, 16))
    ref = _analysis(x, wav, mode, 2)
    got = mm.dwt2_pallas(x, wav, mode)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_pallas_gradient_matches_conv():
    wav = build_wavelet("db4")
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 16))
    w = jax.random.normal(jax.random.PRNGKey(4), (2, 4, 11, 11))

    def loss_conv(x):
        return jnp.sum(_analysis(x, wav, "reflect", 2) * w)

    def loss_pallas(x):
        return jnp.sum(mm.dwt2_pallas(x, wav, "reflect") * w)

    np.testing.assert_allclose(
        jax.grad(loss_pallas)(x), jax.grad(loss_conv)(x), atol=1e-5
    )


@pytest.mark.parametrize("impl", ["matmul", "pallas"])
def test_wavedec2_impl_switch_end_to_end(impl):
    """The full multi-level decomposition and the engine-facing dwt2 agree
    across backends, under jit."""
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 3, 32, 32))
    ref = tf.wavedec2(x, "db4", level=3, mode="reflect")
    tf.set_dwt2_impl(impl)
    got = jax.jit(lambda x: tf.wavedec2(x, "db4", level=3, mode="reflect"))(x)
    tf.set_dwt2_impl("auto")
    np.testing.assert_allclose(got[0], ref[0], atol=1e-4)
    for g, r in zip(got[1:], ref[1:]):
        for gc, rc in zip(g, r):
            np.testing.assert_allclose(gc, rc, atol=1e-4)


@pytest.mark.parametrize("impl", ["matmul", "pallas"])
def test_waverec2_roundtrip_impl_switch(impl):
    """wavedec2 -> waverec2 reconstructs under the non-conv backends (idwt2
    dispatches to the matmul synthesis)."""
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 3, 32, 32))
    tf.set_dwt2_impl(impl)
    coeffs = tf.wavedec2(x, "db4", level=2, mode="reflect")
    rec = tf.waverec2(coeffs, "db4")
    tf.set_dwt2_impl("auto")
    np.testing.assert_allclose(rec[..., :32, :32], x, atol=1e-4)


def test_custom_wavelet_filters_honored():
    """A Wavelet object with custom taps (not matching its name) must produce
    the same result through the matmul backend as through conv — the matrix
    cache keys on the taps, not the name."""
    import dataclasses

    custom = dataclasses.replace(build_wavelet("sym3"), name="db4")
    x = jax.random.normal(jax.random.PRNGKey(8), (1, 20, 20))
    ref = _analysis(x, custom, "reflect", 2)
    got = mm.analysis2_mm(x, custom, "reflect")
    np.testing.assert_allclose(got, ref, atol=1e-5)
    # identical to genuine sym3 (the taps), despite the lying name
    np.testing.assert_allclose(
        got, mm.analysis2_mm(x, build_wavelet("sym3"), "reflect"), atol=1e-6
    )


def test_bad_impl_rejected():
    with pytest.raises(ValueError):
        tf.set_dwt2_impl("cuda")


def test_matmul_roundtrip():
    """analysis -> synthesis reconstructs the signal (periodic/reflect)."""
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 24, 24))
    for mode in ("periodic", "reflect"):
        sub = mm.analysis2_mm(x, "db4", mode)
        rec = mm.synthesis2_mm(sub, "db4", (24, 24))
        np.testing.assert_allclose(rec, x, atol=1e-4)
