"""Transformer-native & temporal attribution tests (`wam_tpu/xattr/`):

- capture_attn logit parity (the capture-is-free regression) + a numpy
  tiny-ViT oracle for the captured softmax weights;
- attention rollout / grad⊙attn numeric goldens vs numpy propagation and
  finite-difference validation of the tap gradients;
- patch-aligned level planning (224/384 × patch 16/32 geometry laws,
  ctor errors on non-divisible inputs) and token-grid aggregation;
- video transforms (anisotropic roundtrip), video attribution shapes, and
  the temporal insertion/deletion fan under the one-fetch contract.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.evalsuite.fan import fetch_scope
from wam_tpu.models.vit import vit_tiny_test
from wam_tpu.xattr import (
    VideoLevels,
    WaveletAttributionVideo,
    attention_weight_grads,
    capture_attention_weights,
    plan_patch_levels,
    relevance_from_grads,
    rollout_from_weights,
    token_grid_map,
    wavedec_video,
    waverec_video,
)
from wam_tpu.xattr.video_eval import EvalVideoWAM

N_CLASSES = 5


@pytest.fixture(scope="module")
def tiny_vit():
    """Capture-capable tiny ViT + its variables + an input batch."""
    model = vit_tiny_test(num_classes=N_CLASSES, capture_attn=True)
    x_nhwc = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(1), x_nhwc)
    x = jnp.transpose(x_nhwc, (0, 3, 1, 2))
    y = jnp.array([1, 3])
    return model, variables, x, y


# -- capture parity + numpy oracle -------------------------------------------


def test_capture_attn_logit_parity(tiny_vit):
    """capture_attn=True must be free: same params, bit-equal logits."""
    model_on, variables, x, _ = tiny_vit
    model_off = vit_tiny_test(num_classes=N_CLASSES)
    base = {k: v for k, v in variables.items() if k != "perturbations"}
    inp = jnp.transpose(x, (0, 2, 3, 1))
    off = model_off.apply(base, inp)
    on = model_on.apply(base, inp)
    np.testing.assert_array_equal(np.asarray(off), np.asarray(on))


def _np_ln(x, p, eps=1e-6):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * np.asarray(p["scale"]) + np.asarray(p["bias"])


def _np_softmax(z):
    z = z - z.max(-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(-1, keepdims=True)


def _np_gelu(x):
    erf = np.vectorize(math.erf)
    return 0.5 * x * (1.0 + erf(x / np.sqrt(2.0)))


def _np_vit_forward(params, x_nhwc, patch, depth):
    """Pure-numpy tiny-ViT forward returning (logits, attn (L, B, H, N, N))
    — the oracle for the flax capture path."""
    p = {k: jax.tree_util.tree_map(np.asarray, v) for k, v in params.items()}
    x = np.asarray(x_nhwc, np.float64)
    B, H, W, C = x.shape
    k = p["patch_embed"]["kernel"]
    x = x.reshape(B, H // patch, patch, W // patch, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // patch, W // patch, -1)
    x = x @ k.reshape(-1, k.shape[-1]) + p["patch_embed"]["bias"]
    D = x.shape[-1]
    x = x.reshape(B, -1, D)
    x = np.concatenate([np.tile(p["cls_token"], (B, 1, 1)), x], axis=1)
    x = x + p["pos_embed"]
    attns = []
    for i in range(depth):
        blk = p[f"block{i}"]
        y = _np_ln(x, blk["ln1"])
        a = blk["attn"]
        q = np.einsum("bnd,dhk->bnhk", y, a["query"]["kernel"]) + a["query"]["bias"]
        kk = np.einsum("bnd,dhk->bnhk", y, a["key"]["kernel"]) + a["key"]["bias"]
        v = np.einsum("bnd,dhk->bnhk", y, a["value"]["kernel"]) + a["value"]["bias"]
        hd = q.shape[-1]
        logits = np.einsum("bqhk,bnhk->bhqn", q / np.sqrt(hd), kk)
        w = _np_softmax(logits)
        attns.append(w)
        o = np.einsum("bhqn,bnhk->bqhk", w, v)
        o = np.einsum("bqhk,hkd->bqd", o, a["out"]["kernel"]) + a["out"]["bias"]
        x = x + o
        y = _np_ln(x, blk["ln2"])
        h1 = _np_gelu(y @ blk["mlp"]["fc1"]["kernel"] + blk["mlp"]["fc1"]["bias"])
        x = x + (h1 @ blk["mlp"]["fc2"]["kernel"] + blk["mlp"]["fc2"]["bias"])
    x = _np_ln(x, p["ln"])
    logits = x[:, 0] @ p["head"]["kernel"] + p["head"]["bias"]
    return logits, np.stack(attns)


def test_captured_weights_match_numpy_oracle(tiny_vit):
    model, variables, x, _ = tiny_vit
    weights = np.asarray(capture_attention_weights(model, variables, x))
    inp = jnp.transpose(x, (0, 2, 3, 1))
    ref_logits, ref_attn = _np_vit_forward(variables["params"], inp, patch=8, depth=2)
    assert weights.shape == ref_attn.shape == (2, 2, 4, 17, 17)
    np.testing.assert_allclose(weights, ref_attn, atol=2e-5)
    base = {k: v for k, v in variables.items() if k != "perturbations"}
    np.testing.assert_allclose(
        np.asarray(model.apply(base, inp)), ref_logits, atol=1e-3
    )


# -- rollout / grad⊙attn goldens ---------------------------------------------


def _np_rollout(attn, residual=0.5):
    a = attn.mean(2)  # (L, B, N, N)
    eye = np.eye(a.shape[-1])
    a = (1 - residual) * a + residual * eye
    a = a / a.sum(-1, keepdims=True)
    r = np.broadcast_to(eye, a.shape[1:]).copy()
    for layer in a:
        r = layer @ r
    return r[:, 0, 1:]


def test_rollout_matches_numpy(tiny_vit):
    model, variables, x, _ = tiny_vit
    weights = capture_attention_weights(model, variables, x)
    got = np.asarray(rollout_from_weights(weights))
    ref = _np_rollout(np.asarray(weights)).reshape(2, 4, 4)
    np.testing.assert_allclose(got, ref, atol=1e-5)
    # row-stochastic composite: every patch relevance positive, and the
    # full cls row (incl. the cls self-loop) sums to 1
    assert (ref > 0).all()


def _np_relevance(attn, grads):
    abar = np.maximum((attn * grads).mean(2), 0.0)
    eye = np.eye(abar.shape[-1])
    r = np.broadcast_to(eye, abar.shape[1:]).copy()
    for layer in abar:
        r = r + layer @ r
    return r[:, 0, 1:]


def test_attention_gradient_matches_numpy(tiny_vit):
    model, variables, x, y = tiny_vit
    weights, grads = attention_weight_grads(model, variables, x, y)
    got = np.asarray(relevance_from_grads(weights, grads))
    ref = _np_relevance(np.asarray(weights), np.asarray(grads)).reshape(2, 4, 4)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_tap_gradients_match_finite_differences(tiny_vit):
    """∂(picked-logit sum)/∂A through the perturb tap vs central
    differences of an explicit tap bump — validates the zero-tap gradient
    route end to end."""
    model, variables, x, y = tiny_vit
    _, grads = attention_weight_grads(model, variables, x, y)
    base = {k: v for k, v in variables.items() if k != "perturbations"}
    inp = jnp.transpose(x, (0, 2, 3, 1))
    shapes = jax.eval_shape(
        lambda v: model.apply(v, inp, mutable=["perturbations", "intermediates"])[1][
            "perturbations"
        ],
        base,
    )
    zeros = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    def picked(pert):
        out, _ = model.apply(
            {**base, "perturbations": pert}, inp, mutable=["intermediates"]
        )
        return float(jnp.take_along_axis(out, y[:, None], axis=1).sum())

    h = 1e-2
    for block, coord in [(0, (0, 1, 2, 3)), (1, (1, 3, 0, 5))]:
        def bump(eps):
            pert = jax.tree_util.tree_map(lambda z: z, zeros)
            tap = pert[f"block{block}"]["attn"]["attention_weights"]
            pert[f"block{block}"]["attn"]["attention_weights"] = (
                tap.at[coord].set(eps)
            )
            return pert

        fd = (picked(bump(h)) - picked(bump(-h))) / (2 * h)
        analytic = float(grads[block][coord])
        assert analytic == pytest.approx(fd, rel=2e-2, abs=1e-4), (block, coord)


# -- patch-aligned level planning --------------------------------------------


@pytest.mark.parametrize("image,patch", [(224, 16), (384, 16), (224, 32), (384, 32)])
def test_plan_patch_levels_geometry(image, patch):
    plan = plan_patch_levels(image, patch)
    assert plan.J == int(math.log2(patch))
    assert plan.tokens == image // patch
    # every planned level's cell side divides the patch: each token is a
    # whole number of coefficient cells at every level
    for j in range(1, plan.J + 1):
        assert patch % plan.level_cell_px(j) == 0
    # and the deepest level is exactly token-granular
    assert plan.level_cell_px(plan.J) == patch
    assert plan.token_granular_levels() == (plan.J,)


@pytest.mark.parametrize("image,patch", [(225, 16), (100, 16), (224, 12), (16, 32), (0, 16)])
def test_plan_patch_levels_rejects(image, patch):
    with pytest.raises(ValueError):
        plan_patch_levels(image, patch)


def test_wam2d_patch_plan_threading():
    from wam_tpu.wam2d import WaveletAttribution2D

    model_fn = lambda xx: jnp.zeros((xx.shape[0], 4))  # noqa: E731
    ex = WaveletAttribution2D(model_fn, level_plan="patch", patch=16,
                              image_size=224, J=99)  # J is ignored under the plan
    assert ex.J == 4 and ex.patch_plan.tokens == 14
    with pytest.raises(ValueError, match="not divisible"):
        WaveletAttribution2D(model_fn, level_plan="patch", patch=16, image_size=100)
    with pytest.raises(ValueError, match="requires image_size"):
        WaveletAttribution2D(model_fn, level_plan="patch")
    with pytest.raises(ValueError, match="level_plan"):
        WaveletAttribution2D(model_fn, level_plan="tokens")


def test_token_grid_map():
    # block-constant map pools exactly
    m = jnp.arange(4, dtype=jnp.float32).reshape(2, 2)
    full = jnp.kron(m, jnp.ones((8, 8)))[None]
    np.testing.assert_allclose(np.asarray(token_grid_map(full, 2))[0], np.asarray(m))
    with pytest.raises(ValueError, match="token grid"):
        token_grid_map(jnp.zeros((1, 15, 15)), 2)


# -- video transforms & attribution ------------------------------------------


@pytest.mark.parametrize("levels", [(3, 1), (2, 2), (2, 0)])
def test_video_roundtrip(levels):
    clip = jax.random.normal(jax.random.PRNGKey(2), (2, 1, 8, 16, 16))
    coeffs = wavedec_video(clip, "haar", levels)
    rec = waverec_video(coeffs, "haar")[..., :8, :16, :16]
    np.testing.assert_allclose(np.asarray(rec), np.asarray(clip), atol=1e-4)
    # structure: finest `temporal` levels are 3D dicts, the rest Detail2D
    spatial, temporal = levels
    details = coeffs[1:]  # coarsest..finest
    kinds = [isinstance(d, dict) for d in details]
    assert kinds == [False] * (spatial - temporal) + [True] * temporal


def test_video_levels_validation():
    with pytest.raises(ValueError):
        VideoLevels(0, 0)
    with pytest.raises(ValueError):
        VideoLevels(2, 3)
    assert VideoLevels(2, 2).uniform and not VideoLevels(2, 1).uniform


@pytest.fixture(scope="module")
def video_setup():
    from wam_tpu.models.toy import toy_conv_model

    toy = toy_conv_model(ndim=3, classes=4)
    model_fn = lambda clip: toy(clip[:, 0])  # noqa: E731
    clip = jax.random.normal(jax.random.PRNGKey(3), (2, 1, 8, 16, 16))
    y = np.array([0, 2])
    return model_fn, clip, y


def test_video_attribution_shapes(video_setup):
    model_fn, clip, y = video_setup
    wam = WaveletAttributionVideo(model_fn, levels=(2, 1), n_samples=3,
                                  sample_batch_size=None)
    box = wam(clip, jnp.asarray(y))
    assert box.shape == (2, 8, 16, 16)
    assert bool(jnp.isfinite(box).all()) and bool((box >= 0).all())
    assert wam.frame_scores(clip, jnp.asarray(y)).shape == (2, 8)

    ig = WaveletAttributionVideo(model_fn, levels=(2, 1),
                                 method="integratedgrad", n_samples=3,
                                 sample_batch_size=None)
    assert ig(clip, jnp.asarray(y)).shape == (2, 8, 16, 16)


def test_video_mesh_gates(video_setup):
    model_fn, _, _ = video_setup
    with pytest.raises(ValueError, match="uniform levels"):
        WaveletAttributionVideo(model_fn, levels=(2, 1), mesh=object())
    with pytest.raises(ValueError, match="batch_axis"):
        WaveletAttributionVideo(model_fn, levels=(2, 2), batch_axis="data")


def test_video_temporal_auc_one_fetch(video_setup):
    """Temporal insertion/deletion through the eval fan: exactly ONE result
    fetch per metric call (the fan engine contract)."""
    model_fn, clip, y = video_setup
    wam = WaveletAttributionVideo(model_fn, levels=(2, 1), n_samples=3,
                                  sample_batch_size=None)
    ev = EvalVideoWAM(model_fn, wam, batch_size=32)
    with fetch_scope() as fs:
        ins = ev.insertion(clip, y, n_iter=4)
    assert fs.count == 1
    with fetch_scope() as fs:
        dele = ev.deletion(clip, y, n_iter=4)
    assert fs.count == 1
    assert len(ins) == len(dele) == 2
    assert all(np.isfinite(v) for v in ins + dele)
    assert len(ev.insertion_curves) == 2
    # curves span the 1 + (n_iter+1) fused forwards minus the reference col
    assert np.asarray(ev.insertion_curves[0]).shape[-1] == 5

    # frame-scores explainer (B, T) is accepted directly
    ev2 = EvalVideoWAM(model_fn, lambda x, yy: wam.frame_scores(x, yy),
                       batch_size=32)
    with fetch_scope() as fs:
        ins2 = ev2.insertion(clip, y, n_iter=4)
    assert fs.count == 1 and len(ins2) == 2


# -- evalsuite registration ---------------------------------------------------


def test_eval_baselines_attention_methods_one_fetch(tiny_vit):
    from wam_tpu.evalsuite.eval_baselines import IMAGE_METHODS, EvalImageBaselines

    assert "rollout" in IMAGE_METHODS and "attngrad" in IMAGE_METHODS
    model, variables, x, y = tiny_vit
    y = np.asarray(y)
    for method in ("rollout", "attngrad"):
        ev = EvalImageBaselines(model, variables, method=method, batch_size=32)
        with fetch_scope() as fs:
            ins = ev.insertion(x, y, n_iter=4)
        assert fs.count == 1, method
        with fetch_scope() as fs:
            mu = ev.mu_fidelity(x, y, grid_size=4, sample_size=8, subset_size=5)
        assert fs.count == 1, method
        assert len(ins) == 2 and np.asarray(mu).shape == (2,)


def test_eval_baselines_require_capture(tiny_vit):
    from wam_tpu.evalsuite.eval_baselines import EvalImageBaselines

    _, variables, _, _ = tiny_vit
    model_off = vit_tiny_test(num_classes=N_CLASSES)
    with pytest.raises(ValueError, match="capture_attn"):
        EvalImageBaselines(model_off, variables, method="attngrad")


def test_patch_wam_eval_and_analyzer(tiny_vit):
    from wam_tpu.analyzers import WAMAnalyzerViT
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.wam2d import WaveletAttribution2D

    model, variables, x, y = tiny_vit
    base = {k: v for k, v in variables.items() if k != "perturbations"}
    model_fn = lambda xx: model.apply(base, jnp.transpose(xx, (0, 2, 3, 1)))  # noqa: E731
    wam = WaveletAttribution2D(model_fn, level_plan="patch", patch=8,
                               image_size=32, n_samples=3,
                               sample_batch_size=None)
    assert wam.J == 3  # planned from patch 8

    an = WAMAnalyzerViT(wam)
    tm = an.token_maps(x, y)
    assert tm.shape == (2, 3, 4, 4)
    assert an.token_importance(x, y).shape == (2, 4, 4)

    ev = Eval2DWAM(model_fn, wam, J=wam.J, batch_size=32)
    with fetch_scope() as fs:
        ins = ev.insertion(x, np.asarray(y), n_iter=4)
    assert fs.count == 1 and len(ins) == 2

    plain = WaveletAttribution2D(model_fn, J=3)
    with pytest.raises(ValueError, match="level_plan='patch'"):
        WAMAnalyzerViT(plain)


def test_tune_presets_registered():
    from wam_tpu.tune.workloads import get_workload

    wv = get_workload("wamvit2d")
    assert wv.workload == "wam2d" and wv.shape == (3, 64, 64)
    labels = [c.label() for c in wv.candidates]
    assert any("nchw" in l for l in labels)
    assert any("synth=matmul" in l for l in labels)
    assert any("stream=on" in l for l in labels)

    wd = get_workload("wamvid3d")
    assert wd.workload == "wamvid3d" and wd.shape == (1, 8, 16, 16)
    fn, args = wd.build(wd.candidates[0])
    out = jax.block_until_ready(fn(*args))
    assert out.shape == (wd.batch, 8, 16, 16)


@pytest.mark.slow
def test_video_mesh_smoothgrad_runs():
    """Uniform-level video WAM composes with SeqShardedWam time sharding:
    deterministic, finite, correctly shaped output on a 2-device mesh."""
    from jax.sharding import Mesh

    from wam_tpu.models.toy import toy_conv_model

    toy = toy_conv_model(ndim=3, classes=4)
    model_fn = lambda clip: toy(clip[:, 0])  # noqa: E731
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    wam = WaveletAttributionVideo(model_fn, levels=(2, 2), n_samples=3,
                                  sample_batch_size=1, mesh=mesh)
    clip = jax.random.normal(jax.random.PRNGKey(4), (2, 1, 16, 16, 16))
    box = wam(clip, jnp.array([0, 1]))
    assert box.shape == (2, 16, 16, 16)
    assert bool(jnp.isfinite(box).all())
    box2 = wam(clip, jnp.array([0, 1]))
    np.testing.assert_array_equal(np.asarray(box), np.asarray(box2))
