"""Round-17 precision policy: resolution precedence, key separation, and
the bf16 fidelity gates.

Every bf16-gated metric is tolerance-tested against its f32 oracle at CPU
test geometry (thresholds carry ~10-20x margin over the measured deltas,
recorded inline):

- insertion/deletion AUC through the bf16 fan (measured max delta ~9e-4),
- μ-fidelity through the bf16 fan (measured max delta ~0.031 at
  sample_size 24 — μ is a coarse Spearman, single rank flips are quantized),
- the eval1d mel-bf16 AUC path (measured ~9e-5),
- WAM-1D mel-chain attribution cosine (measured 1.0 to 6 decimals).

Plus the policy plumbing: `resolve_precision` precedence (explicit > env >
tuned-schedule > f32), `plan_fan`'s fan_dtype axis, runner/AOT/result-cache
key separation, `fleet_aot_key` precision tagging, the autotuner Candidate
axes, and the mel1d workload preset.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wam_tpu.config import (
    FAN_DTYPES,
    PrecisionPolicy,
    compute_cast,
    fp8_supported,
    precision_tag,
    resolve_precision,
)
from wam_tpu.evalsuite.fan import FanPlan, cast_model_fn, plan_fan
from wam_tpu.tune import invalidate_process_cache, record_schedule


@pytest.fixture
def sched_cache(tmp_path, monkeypatch):
    """Isolated user-layer schedule cache (the test_tune fixture)."""
    path = tmp_path / "schedules.json"
    monkeypatch.setenv("WAM_TPU_SCHEDULE_CACHE", str(path))
    monkeypatch.delenv("WAM_TPU_NO_SCHEDULE_CACHE", raising=False)
    invalidate_process_cache()
    yield path
    invalidate_process_cache()


@pytest.fixture
def clean_env(monkeypatch):
    monkeypatch.delenv("WAM_TPU_FAN_DTYPE", raising=False)
    monkeypatch.delenv("WAM_TPU_MEL_BF16", raising=False)


class TinyImg(nn.Module):
    classes: int = 5

    @nn.compact
    def __call__(self, x):  # (B, 3, H, W)
        x = jnp.transpose(x, (0, 2, 3, 1))
        x = nn.relu(nn.Conv(8, (3, 3), strides=(2, 2))(x)).mean(axis=(1, 2))
        return nn.Dense(self.classes)(x)


@pytest.fixture(scope="module")
def tiny_img():
    model = TinyImg()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3, 32, 32)))
    bf16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
    return (lambda x: model.apply(params, x),
            lambda x: model.apply(bf16, x))


# -- policy object -----------------------------------------------------------


def test_precision_policy_validates_fan_dtype():
    for d in FAN_DTYPES:
        assert PrecisionPolicy(fan_dtype=d).fan_dtype == d
    with pytest.raises(ValueError):
        PrecisionPolicy(fan_dtype="fp16")


def test_precision_policy_compute_dtype_and_tag():
    assert PrecisionPolicy().compute_dtype() is None
    assert PrecisionPolicy(fan_dtype="bf16").compute_dtype() == jnp.bfloat16
    # fp8 resolves to the fp8 storage type where the backend compiles it,
    # bf16 otherwise — never None (the policy IS low precision)
    fp8 = PrecisionPolicy(fan_dtype="fp8").compute_dtype()
    assert fp8 in (jnp.float8_e4m3fn, jnp.bfloat16)
    assert isinstance(fp8_supported(), bool)
    assert PrecisionPolicy().tag() == "f32"
    assert PrecisionPolicy(fan_dtype="bf16").tag() == "bf16"
    assert PrecisionPolicy(fan_dtype="bf16", mel_bf16=True).tag() == "bf16+mel"
    assert PrecisionPolicy(mel_bf16=True).tag() == "f32+mel"


def test_compute_cast_is_boundary_shim():
    x = jnp.ones((3,), jnp.float32)
    assert compute_cast(x, None) is x
    assert compute_cast(x, jnp.bfloat16).dtype == jnp.bfloat16


# -- resolution precedence ---------------------------------------------------


def test_resolve_precision_defaults_f32(sched_cache, clean_env):
    pol = resolve_precision("eval2d", (65,), 65)
    assert pol == PrecisionPolicy()
    assert precision_tag() == "f32"


def test_resolve_precision_env_knobs(sched_cache, clean_env, monkeypatch):
    monkeypatch.setenv("WAM_TPU_FAN_DTYPE", "bf16")
    monkeypatch.setenv("WAM_TPU_MEL_BF16", "1")
    pol = resolve_precision("eval2d", (65,), 65)
    assert pol.fan_dtype == "bf16" and pol.mel_bf16
    assert precision_tag() == "bf16+mel"
    monkeypatch.setenv("WAM_TPU_MEL_BF16", "0")  # falsy spellings
    assert not resolve_precision().mel_bf16


def test_resolve_precision_rejects_bad_env(clean_env, monkeypatch):
    monkeypatch.setenv("WAM_TPU_FAN_DTYPE", "fp16")
    with pytest.raises(ValueError):
        resolve_precision()


def test_resolve_precision_tuned_entry(sched_cache, clean_env):
    record_schedule("eval2d", (65,), 65, {"fan_dtype": "bf16",
                                          "mel_bf16": True})
    pol = resolve_precision("eval2d", (65,), 65)
    assert pol.fan_dtype == "bf16" and pol.mel_bf16
    # a different workload/geometry does not inherit the entry
    assert resolve_precision("eval1d", (65,), 65) == PrecisionPolicy()


def test_resolve_precision_explicit_beats_env_and_tuned(
        sched_cache, clean_env, monkeypatch):
    record_schedule("eval2d", (65,), 65, {"fan_dtype": "bf16"})
    monkeypatch.setenv("WAM_TPU_FAN_DTYPE", "bf16")
    pol = resolve_precision("eval2d", (65,), 65, fan_dtype="f32")
    assert pol.fan_dtype == "f32"


# -- plan_fan fan_dtype axis -------------------------------------------------


def test_plan_fan_dtype_default_keeps_old_equality(sched_cache, clean_env):
    # pre-round-17 FanPlan literals still compare equal (fan_dtype="f32")
    assert plan_fan(256, 65) == FanPlan(256, 3, None)
    assert plan_fan(256, 65).fan_dtype == "f32"


def test_plan_fan_dtype_explicit_env_and_tuned(sched_cache, clean_env,
                                               monkeypatch):
    assert plan_fan(256, 65, fan_dtype="bf16") == FanPlan(256, 3, None, "bf16")
    monkeypatch.setenv("WAM_TPU_FAN_DTYPE", "bf16")
    assert plan_fan(256, 65).fan_dtype == "bf16"  # env applies at any cap
    monkeypatch.delenv("WAM_TPU_FAN_DTYPE")
    # tuned fan_dtype only under "auto" (fan_cap semantics)
    record_schedule("eval2d", (65,), 65, {"fan_cap": 128, "fan_dtype": "bf16"})
    assert plan_fan("auto", 65) == FanPlan(128, 1, None, "bf16")
    assert plan_fan(256, 65).fan_dtype == "f32"


def test_cast_model_fn_passthrough_and_cast(tiny_img):
    f32_fn, _ = tiny_img
    assert cast_model_fn(f32_fn, "f32") is f32_fn
    seen = {}

    def probe(x):
        seen["dtype"] = x.dtype
        return jnp.zeros((x.shape[0], 2), jnp.bfloat16)

    out = cast_model_fn(probe, "bf16")(jnp.ones((2, 4), jnp.float32))
    assert seen["dtype"] == jnp.bfloat16
    assert out.dtype == jnp.float32  # logits back to f32 for reductions


# -- bf16 fidelity gates vs the f32 oracle -----------------------------------


def _eval2d(model_fn, wams, precision=None, batch_size=16):
    from wam_tpu.evalsuite.eval2d import Eval2DWAM

    return Eval2DWAM(model_fn, explainer=lambda xx, yy: wams,
                     wavelet="haar", J=2, batch_size=batch_size,
                     precision=precision)


def test_fan_auc_bf16_tolerance(tiny_img, clean_env):
    """Insertion/deletion AUC through the bf16 fan vs the f32 oracle.
    Measured max delta at this geometry: ~9e-4 (gate 0.02); the score
    RANKING must survive exactly (Spearman 1.0 at these gaps)."""
    from wam_tpu.evalsuite.metrics import spearman

    f32_fn, bf16_fn = tiny_img
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((3, 3, 32, 32)), dtype=jnp.float32)
    y = [0, 1, 2]
    wams = jnp.asarray(rng.standard_normal((3, 32, 32)), dtype=jnp.float32)
    for mode in ("insertion", "deletion"):
        ref, _ = _eval2d(f32_fn, wams).evaluate_auc(x, y, mode, n_iter=8)
        low, _ = _eval2d(bf16_fn, wams, precision="bf16").evaluate_auc(
            x, y, mode, n_iter=8)
        ref, low = np.asarray(ref), np.asarray(low)
        assert np.max(np.abs(low - ref)) < 0.02, mode
        assert float(spearman(jnp.asarray(low), jnp.asarray(ref))) == 1.0


def test_mu_fidelity_bf16_tolerance(tiny_img, clean_env):
    """μ-fidelity through the bf16 fan. μ is a Spearman over subset draws —
    quantized, so single rank flips move it in steps; measured max delta
    0.031 at sample_size 24 (gate 0.1)."""
    f32_fn, bf16_fn = tiny_img
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((3, 3, 32, 32)), dtype=jnp.float32)
    y = [0, 1, 2]
    wams = jnp.asarray(rng.standard_normal((3, 32, 32)), dtype=jnp.float32)
    kw = dict(grid_size=8, sample_size=24, subset_size=48)
    ref = np.asarray(_eval2d(f32_fn, wams, batch_size=32).mu_fidelity(
        x, y, **kw))
    low = np.asarray(_eval2d(bf16_fn, wams, precision="bf16",
                             batch_size=32).mu_fidelity(x, y, **kw))
    assert np.max(np.abs(low - ref)) < 0.1


def test_eval1d_mel_bf16_auc_tolerance(clean_env):
    """The eval1d AUC path under the bf16 mel chain vs the f32 oracle
    (measured max delta ~9e-5; gate 0.02)."""
    from wam_tpu.evalsuite.eval1d import Eval1DWAM
    from wam_tpu.wam1d import normalize_waveforms

    class TinyAudio(nn.Module):
        @nn.compact
        def __call__(self, x):  # (B, 1, T, M)
            return nn.Dense(4)(x.reshape((x.shape[0], -1)))

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 2048)), dtype=jnp.float32)
    y = [0, 1]
    scores = {}
    for bf in (False, True):
        ev = Eval1DWAM(lambda m: None, explainer=None, n_fft=256, n_mels=16,
                       precision=PrecisionPolicy(mel_bf16=bf))
        mel = ev._melspec(normalize_waveforms(x))
        model = TinyAudio()
        variables = model.init(jax.random.PRNGKey(0), mel)
        ev.model_fn = lambda m: model.apply(variables, m)
        ev.explainer = lambda xx, yy: (jnp.ones(mel[:, 0].shape), [])
        scores[bf] = np.asarray(ev.insertion(x, y, target="melspec",
                                             n_iter=8))
    assert np.max(np.abs(scores[True] - scores[False])) < 0.02


def test_mel_bf16_attribution_cosine_gate(clean_env):
    """The ISSUE's gate for the mel knob: WAM-1D attribution cosine between
    the bf16 mel chain and f32 ≥ 0.99 (measured 1.0 to 6 decimals; the
    per-bin dB delta is NOT the gate — near-silent bins swing log10)."""
    from wam_tpu.ops import melspec as ms
    from wam_tpu.wam1d import BaseWAM1D

    wave = jax.random.normal(jax.random.PRNGKey(1), (2, 4096), jnp.float32)
    y = jnp.asarray([0, 1], jnp.int32)
    head = jax.random.normal(jax.random.PRNGKey(2), (16, 4), jnp.float32)
    # NONLINEAR head: with a linear model ∂loss/∂mel is a constant of the
    # weights and the A/B would compare identical gradients by construction
    wam = BaseWAM1D(lambda mel: jnp.tanh(mel / 30.0).mean(axis=2)[:, 0, :]
                    @ head,
                    wavelet="haar", J=2, n_mels=16, n_fft=256)
    ms.set_stft_impl("matmul")  # the full bf16 DFT+filterbank chain
    prev = ms.get_mel_bf16()
    try:
        attr = {}
        for bf in (False, True):
            ms.set_mel_bf16(bf)
            attr[bf], _ = wam(wave, y)
    finally:
        ms.set_mel_bf16(prev)
        ms.set_stft_impl("auto")
    a = np.asarray(attr[True], np.float64).ravel()
    b = np.asarray(attr[False], np.float64).ravel()
    cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
    assert cos >= 0.99
    # and the knob is not a no-op: the chains genuinely differ
    assert np.any(a != b)


def test_melspectrogram_per_call_override_beats_global(clean_env):
    from wam_tpu.ops import melspec as ms

    x = jax.random.normal(jax.random.PRNGKey(0), (1, 2048), jnp.float32)
    kw = dict(n_fft=256, n_mels=16, impl="matmul")
    base = ms.melspectrogram(x, **kw)
    prev = ms.get_mel_bf16()
    try:
        ms.set_mel_bf16(True)
        # per-call bf16=False overrides the global back to the f32 chain
        np.testing.assert_array_equal(
            np.asarray(ms.melspectrogram(x, bf16=False, **kw)),
            np.asarray(base))
        assert np.any(np.asarray(ms.melspectrogram(x, **kw))
                      != np.asarray(base))
    finally:
        ms.set_mel_bf16(prev)


# -- key separation ----------------------------------------------------------


def test_run_cached_auc_key_separates_dtypes(tiny_img, clean_env):
    """Two plans differing only in fan_dtype must build two runners (the
    dtype is baked into the traced program)."""
    from wam_tpu.evalsuite.metrics import generate_masks, run_cached_auc

    f32_fn, _ = tiny_img
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), dtype=jnp.float32)
    expl = jnp.asarray(rng.standard_normal((2, 32, 32)), dtype=jnp.float32)
    y = np.array([0, 1])
    n_iter = 4

    def inputs_fn(x_s, e_s):
        ins, _ = generate_masks(n_iter, e_s)
        return x_s[None] * ins[:, None]

    runners = {}
    geom = (16, 1, None)
    run_cached_auc(runners, "m", inputs_fn, f32_fn, FanPlan(*geom),
                   n_iter, x, expl, y)
    run_cached_auc(runners, "m", inputs_fn, f32_fn, FanPlan(*geom, "bf16"),
                   n_iter, x, expl, y)
    assert len(runners) == 2


def test_result_cache_key_precision_flip(clean_env, monkeypatch):
    from wam_tpu.serve.result_cache import result_cache_key

    x = np.ones((3, 4, 4), np.float32)
    base = result_cache_key(x, 1, "entry")
    assert base.endswith("|f32")
    monkeypatch.setenv("WAM_TPU_FAN_DTYPE", "bf16")
    assert result_cache_key(x, 1, "entry") != base
    monkeypatch.delenv("WAM_TPU_FAN_DTYPE")
    monkeypatch.setenv("WAM_TPU_MEL_BF16", "1")
    assert result_cache_key(x, 1, "entry") != base
    monkeypatch.delenv("WAM_TPU_MEL_BF16")
    assert result_cache_key(x, 1, "entry") == base  # live, per call


def test_fleet_aot_key_precision_tagging():
    from wam_tpu.serve import fleet_aot_key

    # pre-round-17 forms unchanged (warm caches)
    assert fleet_aot_key("m", 4) == "m|fleet4"
    assert fleet_aot_key("m", None) == "m"
    assert fleet_aot_key(None, 8, "bf16") is None
    # default-precision spellings are suffix-free
    assert fleet_aot_key("m", 4, "f32") == "m|fleet4"
    assert fleet_aot_key("m", None, "") == "m"
    # non-default policies tag after the fleet tag
    assert fleet_aot_key("m", 4, "bf16") == "m|fleet4|bf16"
    assert fleet_aot_key("m", 1, "bf16+mel") == "m|bf16+mel"


def test_eval2d_precision_threads_into_fan_plan(tiny_img, clean_env):
    f32_fn, _ = tiny_img
    wams = jnp.ones((1, 32, 32))
    assert _eval2d(f32_fn, wams)._fan_plan(6).fan_dtype == "f32"
    assert _eval2d(f32_fn, wams,
                   precision="bf16")._fan_plan(6).fan_dtype == "bf16"
    pol = PrecisionPolicy(fan_dtype="bf16", mel_bf16=True)
    assert _eval2d(f32_fn, wams, precision=pol)._fan_plan(6).fan_dtype == "bf16"


# -- autotuner axes ----------------------------------------------------------


def test_candidate_precision_axes_label_and_entry():
    from wam_tpu.tune.autotuner import Candidate

    cand = Candidate(fan_cap=256, fan_dtype="bf16", mel_bf16=True)
    assert "dtype=bf16" in cand.label() and "mel=bf16" in cand.label()
    entry = cand.entry()
    assert entry["fan_dtype"] == "bf16" and entry["mel_bf16"] is True
    # None fields stay out of the persisted entry
    assert "fan_dtype" not in Candidate(fan_cap=256).entry()
    assert "mel_bf16" not in Candidate(fan_cap=256).entry()
    assert "mel=f32" in Candidate(mel_bf16=False).label()


def test_explicit_plan_carries_candidate_dtype():
    from wam_tpu.tune.autotuner import Candidate
    from wam_tpu.tune.workloads import _explicit_plan

    assert _explicit_plan(Candidate(fan_cap=64), 9).fan_dtype == "f32"
    plan = _explicit_plan(Candidate(fan_cap=64, fan_dtype="bf16"), 9)
    assert plan.fan_dtype == "bf16"


def test_mel1d_workload_builds_and_runs(clean_env):
    from wam_tpu.tune.workloads import get_workload

    wl = get_workload("mel1d", batch=2, n=2048)
    assert wl.workload == "mel1d"
    labels = [c.label() for c in wl.candidates]
    assert any("mel=bf16" in s for s in labels)
    assert any("mel=f32" in s for s in labels)
    outs = []
    for cand in wl.candidates:
        fn, args = wl.build(cand)
        outs.append(np.asarray(jax.block_until_ready(fn(*args))))
    assert outs[0].shape == outs[1].shape
    assert np.any(outs[0] != outs[1])  # the knob reaches the chain


# -- model casting shims -----------------------------------------------------


def test_bind_vit_inference_policy_string(clean_env):
    from wam_tpu.models.vit import bind_vit_inference, vit_tiny_test

    model = vit_tiny_test(num_classes=3)
    x = jnp.ones((1, 16, 16, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    ref = bind_vit_inference(model, variables)(x)
    low = bind_vit_inference(model, variables, compute_dtype="bf16")(x)
    assert low.dtype == jnp.float32  # logits back in f32
    assert np.allclose(np.asarray(low), np.asarray(ref), atol=0.1)


def test_bind_audio_inference_policy_string(clean_env):
    from wam_tpu.models.audio import bind_audio_inference

    class TinyNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x.reshape((x.shape[0], -1)))

    model = TinyNet()
    x = jnp.ones((1, 1, 8, 4), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    ref = bind_audio_inference(model, variables)(x)
    low = bind_audio_inference(model, variables, compute_dtype="bf16")(x)
    assert low.dtype == jnp.float32
    assert np.allclose(np.asarray(low), np.asarray(ref), atol=0.1)
