"""Pod-scale failure domains (`wam_tpu/pod`): router load-spreading over
real worker subprocesses, zero-loss re-route across a mid-stream SIGKILL,
crash-loop escalation to permanent-dead, autoscaler decisions from
synthetic health signals, the typed-error wire round-trip, and the
registry-hydrated zero-compile respawn.

Process tests spawn REAL ``wam_tpu.pod.worker`` subprocesses (fake
entries keep them fast: ~1s bring-up each, no model compiles); policy
tests (supervisor, autoscaler, protocol) run pure in-process with stub
callables and synthetic `WorkerSnapshot`s — the same split the pod
package is layered for."""

import sys
import time
from concurrent.futures import Future

import numpy as np

from wam_tpu.pod import (
    AutoscaleConfig,
    NoLiveWorkerError,
    PodMetrics,
    PodRouter,
    PodSupervisor,
    PodWorkerError,
    WorkerSnapshot,
)
from wam_tpu.pod.autoscaler import decide
from wam_tpu.pod.protocol import decode_error, encode_error
from wam_tpu.serve import (
    NoLiveReplicaError,
    QueueFullError,
    RetryPolicy,
    RetryStats,
    SupervisorConfig,
)
from wam_tpu.serve.runtime import MemoryAdmissionError, ServerClosedError

WORKER_ARGV = [
    sys.executable, "-m", "wam_tpu.pod.worker",
    "--device", "cpu", "--fake-entry", "5", "--buckets", "1x16x16",
]


def _pod(n=2, **kw):
    kw.setdefault("heartbeat_s", 0.1)
    return PodRouter(WORKER_ARGV, "1x16x16", workers=n, **kw)


def _poll(pred, timeout_s=30.0, dt=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(dt)
    return pred()


def _x():
    return np.zeros((1, 16, 16), np.float32)


# -- router over real worker processes --------------------------------------


def test_router_spreads_load_across_workers():
    router = _pod(2)
    try:
        futs = [router.submit(_x(), 0) for _ in range(60)]
        assert all(f.result(timeout=60).shape == (1, 16, 16) for f in futs)
    finally:
        router.close()
    summary = router.pod_summary()
    assert summary["completed"] == 60
    # both worker processes served a share: scoring is load-aware, so a
    # closed burst of 60 must not all land on one worker
    per_worker = {w["worker_id"]: w for w in summary["per_worker"]}
    assert set(per_worker) == {0, 1}
    assert all(w["completed"] > 0 for w in per_worker.values())
    assert sum(w["completed"] for w in per_worker.values()) == 60


def test_kill_worker_midstream_zero_lost():
    router = _pod(2, supervise=SupervisorConfig(seed=0, backoff_base_s=0.01))
    policy = RetryPolicy(max_attempts=8, budget_s=60.0,
                         retry_on=(QueueFullError, NoLiveWorkerError))
    stats = RetryStats()
    try:
        futs = [router.submit_with_retry(_x(), 0, policy=policy, stats=stats)
                for _ in range(40)]
        victim = router.live_worker_ids()[0]
        assert router.kill_worker(victim)
        futs += [router.submit_with_retry(_x(), 0, policy=policy, stats=stats)
                 for _ in range(40)]
        # ZERO lost: every future resolves OK despite the SIGKILL — the
        # router re-dispatches the dead worker's in-flight host copies
        assert all(f.result(timeout=60) is not None for f in futs)
        summary = router.pod_summary()
        assert summary["completed"] == 80
        assert len(summary["deaths"]) == 1
        assert summary["deaths"][0]["worker_id"] == victim
        # the supervisor respawns the victim (fresh incarnation, alive)
        assert _poll(lambda: sorted(router.live_worker_ids()) == [0, 1],
                     timeout_s=60.0)
    finally:
        router.close()
    assert stats.as_dict()["exhausted"] == 0
    rows = [r for r in router.metrics.restarts if r["transition"] == "alive"]
    assert len(rows) == 1 and rows[0]["worker_id"] == victim


def test_shrink_drains_gracefully():
    router = _pod(2)
    try:
        futs = [router.submit(_x(), 0) for _ in range(20)]
        wid = router.shrink()
        assert wid is not None
        # draining is not death: everything resolves, no death recorded,
        # and the retired worker leaves the routable set
        assert all(f.result(timeout=60) is not None for f in futs)
        assert router.pod_summary()["deaths"] == []
        assert _poll(lambda: router.live_worker_ids() == [1 - wid],
                     timeout_s=30.0)
        assert router.attribute(_x(), 0) is not None
    finally:
        router.close()


# -- supervisor policy (stub respawn, no processes) --------------------------


def test_crash_loop_escalates_to_permanent_dead():
    metrics = PodMetrics()
    respawns = []
    sup = PodSupervisor(
        respawns.append, metrics,
        SupervisorConfig(max_restarts=2, window_s=60.0,
                         backoff_base_s=0.001, seed=0))
    def alive_rows():
        return [r for r in metrics.restarts if r["transition"] == "alive"]

    try:
        for expected in (1, 2):
            sup.notify_death(7, reason="test kill")
            # wait for the "alive" ROW, not just the respawn call: the
            # crash-loop history entry lands right before the row does
            assert _poll(lambda: len(alive_rows()) == expected,
                         timeout_s=10.0)
        assert len(respawns) == 2
        # third death inside the window: over max_restarts=2 -> escalate,
        # NOT another respawn
        sup.notify_death(7, reason="test kill")
        assert sup.permanently_dead(7)
        assert sup.permanently_dead() == [7]
        sup.notify_death(7, reason="ignored")  # no-op once permanent
        time.sleep(0.05)
        assert len(respawns) == 2
    finally:
        sup.close()
    transitions = [r["transition"] for r in metrics.restarts
                   if r["worker_id"] == 7]
    assert transitions.count("alive") == 2
    assert transitions[-1] == "permanent_dead"


def test_failed_respawn_counts_toward_crash_loop():
    metrics = PodMetrics()

    def bad_respawn(wid):
        raise RuntimeError("spawn exploded")

    sup = PodSupervisor(
        bad_respawn, metrics,
        SupervisorConfig(max_restarts=1, window_s=60.0,
                         backoff_base_s=0.001, seed=0))
    try:
        sup.notify_death(3, reason="test kill")
        assert _poll(lambda: sup.permanently_dead(3), timeout_s=10.0)
    finally:
        sup.close()
    transitions = [r["transition"] for r in metrics.restarts
                   if r["worker_id"] == 3]
    assert "respawn_failed" in transitions
    assert transitions[-1] == "permanent_dead"
    assert "alive" not in transitions


# -- autoscaler policy (pure decide, synthetic signals) -----------------------


def _snap(wid, drain=0.0, penalty=0.0):
    return WorkerSnapshot(worker_id=wid, pid=0, t_worker=0.0,
                          projected_drain_s=drain, slo_penalty_s=penalty)


def test_autoscaler_decisions():
    cfg = AutoscaleConfig(min_workers=1, max_workers=4,
                          grow_drain_s=0.5, shrink_drain_s=0.05)
    # deep queues -> grow
    assert decide(cfg, [_snap(0, drain=2.0), _snap(1, drain=1.0)], 2) == 1
    # SLO burn alone (penalty > 0 means burn crossed 1.0) -> grow
    assert decide(cfg, [_snap(0, drain=0.0, penalty=0.2)], 1) == 1
    # at max_workers pressure cannot grow further
    assert decide(cfg, [_snap(i, drain=2.0) for i in range(4)], 4) == 0
    # calm on both signals with headroom -> shrink
    assert decide(cfg, [_snap(0, drain=0.01), _snap(1, drain=0.0)], 2) == -1
    # calm at min_workers holds
    assert decide(cfg, [_snap(0, drain=0.01)], 1) == 0
    # in-between load holds
    assert decide(cfg, [_snap(0, drain=0.2)], 2) == 0
    # below min_workers always grows (even with no snapshots yet)
    assert decide(cfg, [], 0) == 1
    # a burning pod with headroom grows even with empty queues...
    assert decide(cfg, [_snap(0, drain=0.0, penalty=0.1),
                        _snap(1, drain=0.0)], 2) == 1
    # ...and at max_workers it HOLDS — burn blocks the shrink branch
    assert decide(cfg, [_snap(i, drain=0.0, penalty=0.1 if i == 0 else 0.0)
                        for i in range(4)], 4) == 0


# -- typed errors across the process boundary --------------------------------


def test_error_wire_roundtrip_preserves_backpressure():
    q = decode_error(encode_error(QueueFullError(0.25)))
    assert isinstance(q, QueueFullError) and q.retry_after_s == 0.25
    m = decode_error(encode_error(MemoryAdmissionError(0.5, bucket="1x16x16")))
    assert isinstance(m, MemoryAdmissionError) and m.retry_after_s == 0.5
    n = decode_error(encode_error(
        NoLiveReplicaError("all dead", retry_after_s=1.5)))
    assert isinstance(n, NoLiveReplicaError) and n.retry_after_s == 1.5
    s = decode_error(encode_error(ServerClosedError("closing")))
    assert isinstance(s, ServerClosedError) and "closing" in str(s)
    # unknown class degrades to the typed pod error, never a decode crash
    u = decode_error({"type": "SomethingForeign", "message": "boom",
                      "retry_after_s": 2.0})
    assert isinstance(u, PodWorkerError) and u.retry_after_s == 2.0


def test_no_live_errors_are_retryable_backpressure():
    # satellite: fleet-wide (and pod-wide) death during a restart window
    # carries retry_after_s, so RetryPolicy backs off and retries instead
    # of exhausting against a recovering service
    assert NoLiveReplicaError("x").retry_after_s is None
    assert NoLiveWorkerError("x", retry_after_s=0.02).retry_after_s == 0.02

    attempts = []

    def submit(remaining_s):
        attempts.append(remaining_s)
        f = Future()
        if len(attempts) < 3:
            f.set_exception(NoLiveWorkerError("pod down",
                                              retry_after_s=0.005))
        else:
            f.set_result("served")
        return f

    policy = RetryPolicy(max_attempts=5, backoff_base_s=0.001,
                         retry_on=(QueueFullError, NoLiveWorkerError))
    stats = RetryStats()
    assert policy.run(submit, stats=stats) == "served"
    assert len(attempts) == 3
    assert stats.as_dict()["retries"] == 2


# -- registry-hydrated respawn (real toy workers, sentinel-verified) ----------


def test_registry_hydrated_respawn_zero_compiles(tmp_path):
    """The pod acceptance criterion end-to-end: seed a toy worker under
    throwaway caches, publish its compiled artifacts as a bundle, bring a
    pod worker up with COLD caches + ``--registry``, SIGKILL it, and
    verify the supervisor's respawn rejoins at ``compile_count == 0`` —
    warmup hydrates the bundle instead of re-tracing."""
    from wam_tpu.registry import publish_bundle

    key_base = "test_pod|toy2d|J2|n2|mb8"
    toy_argv = [
        sys.executable, "-m", "wam_tpu.pod.worker",
        "--device", "cpu", "--buckets", "1x16x16", "--n-samples", "2",
        "--aot-key-base", key_base,
    ]

    def caches(label):
        root = tmp_path / label
        return {
            "WAM_TPU_AOT_CACHE": str(root / "aot"),
            "WAM_TPU_SCHEDULE_CACHE": str(root / "schedules.json"),
            "WAM_TPU_CACHE_DIR": str(root / "xla"),
        }

    seed_env = caches("seed")
    router = PodRouter(toy_argv, "1x16x16", workers=1, env=seed_env,
                       ready_timeout_s=300.0)
    try:
        assert router.attribute(_x(), 0) is not None
    finally:
        router.close()

    manifest = publish_bundle(
        str(tmp_path / "bundle"),
        aot_dir=seed_env["WAM_TPU_AOT_CACHE"],
        schedule_path=seed_env["WAM_TPU_SCHEDULE_CACHE"],
        xla_dir=seed_env["WAM_TPU_CACHE_DIR"],
        source={"test": "test_pod seed worker"},
    )
    assert sum(1 for a in manifest["artifacts"] if a["kind"] == "aot") > 0

    hydrated_argv = toy_argv + ["--registry", str(tmp_path / "bundle")]
    router = PodRouter(hydrated_argv, "1x16x16", workers=1,
                       env=caches("cold"), ready_timeout_s=300.0,
                       supervise=SupervisorConfig(seed=0,
                                                  backoff_base_s=0.01))
    try:
        def ready_rows(incarnation):
            return [r for r in router.metrics.worker_rows
                    if r["phase"] == "ready"
                    and r["incarnation"] == incarnation]

        # even the FIRST spawn hydrates: cold caches, zero compiles
        first = ready_rows(0)
        assert first and first[0]["compile_count"] == 0
        assert router.kill_worker(0)
        assert _poll(lambda: bool(ready_rows(1)), timeout_s=240.0)
        respawned = ready_rows(1)[0]
        # THE acceptance bar: the respawned worker's ready snapshot shows
        # zero compiles ever (bundle hydration) and zero post-warm traces
        assert respawned["compile_count"] == 0
        assert respawned["post_warm_compiles"] == 0
        assert router.attribute(_x(), 0) is not None
    finally:
        router.close()
