"""Round-18 pod transport (`wam_tpu/pod/transport`, ``netchannel``):
frame round-trip fidelity over real sockets (dtype/shape preservation,
zero-length and multi-MiB ndarray payloads on the zero-copy path),
corrupt-frame and bad-HMAC rejection, host-aware routing with fake
channels, and the two process-level acceptance bars — whole-host SIGKILL
mid-stream with zero lost requests, and a cold worker joining
compile-free from the wire-streamed registry bundle.

Frame tests run over ``socket.socketpair`` (no listener, no handshake —
just the codec); handshake tests use a real `NetListener`; routing unit
tests fabricate `_Worker` state on an unstarted router (``auto_start=
False``) so scoring decisions are observable without processes."""

import socket
import sys
import threading
import time

import numpy as np

from wam_tpu.pod import NoLiveWorkerError, PodRouter, WorkerSnapshot
from wam_tpu.pod.netchannel import NetListener, connect_tcp, parse_address
from wam_tpu.pod.router import _Worker
from wam_tpu.pod.transport import (
    FrameError,
    PodAuthError,
    encode_message,
    read_message,
    send_buffers,
)
from wam_tpu.serve import QueueFullError, RetryPolicy, RetryStats

# -- frame codec over a socketpair ------------------------------------------


def _roundtrip(msg: dict) -> dict:
    a, b = socket.socketpair()
    try:
        bufs, nbytes = encode_message(msg)
        # send from a thread: a multi-MiB frame overflows the socketpair
        # buffer long before read_message starts draining it
        sender = threading.Thread(target=send_buffers, args=(a, bufs))
        sender.start()
        out, got = read_message(b)
        sender.join(timeout=30.0)
        assert got == nbytes
        return out
    finally:
        a.close()
        b.close()


def test_frame_roundtrip_preserves_dtype_and_shape():
    rng = np.random.RandomState(0)
    arrays = {
        "f32": rng.rand(3, 16, 16).astype(np.float32),
        "f16": rng.rand(8).astype(np.float16),
        "i64": np.arange(7, dtype=np.int64),
        "bool": np.array([True, False, True]),
        "empty": np.zeros((0, 4), np.float32),  # zero-length payload frame
        "scalarish": np.float32(3.5) * np.ones((1,), np.float32),
    }
    msg = {"op": "submit", "req_id": 9, "x": arrays,
           "meta": {"nested": [1, "two", None]}, "blob": b"\x00\xffraw"}
    out = _roundtrip(msg)
    assert out["op"] == "submit" and out["req_id"] == 9
    assert out["meta"] == {"nested": [1, "two", None]}
    assert out["blob"] == b"\x00\xffraw"
    for key, arr in arrays.items():
        got = out["x"][key]
        assert got.dtype == arr.dtype, key
        assert got.shape == arr.shape, key
        np.testing.assert_array_equal(got, arr)


def test_frame_roundtrip_large_payload():
    # > 4 MiB forces multi-recv reassembly on the zero-copy nd path
    big = np.random.RandomState(1).rand(1, 3, 16, 224, 224).astype(np.float32)
    assert big.nbytes > (4 << 20)
    out = _roundtrip({"op": "submit", "x": big})
    np.testing.assert_array_equal(out["x"], big)


def test_frame_ndarray_is_not_pickled():
    # the array path must ship the buffer raw: exactly one "nd"
    # descriptor, and one scatter buffer aliasing the array's memory
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    bufs, _ = encode_message({"op": "submit", "x": arr})
    shared = [b for b in bufs
              if isinstance(b, memoryview) and np.shares_memory(
                  np.frombuffer(b, np.uint8), arr)]
    assert shared, "ndarray payload was copied or pickled, not zero-copy"


def test_truncated_frame_and_bad_magic_raise_frameerror():
    a, b = socket.socketpair()
    try:
        bufs, _ = encode_message({"op": "hello", "x": np.ones(4, np.float32)})
        wire = b"".join(bytes(x) for x in bufs)
        a.sendall(wire[: len(wire) - 3])  # drop the frame's tail
        a.close()
        try:
            read_message(b)
            raise AssertionError("truncated frame did not raise")
        except (FrameError, EOFError, OSError):
            pass
    finally:
        b.close()

    a, b = socket.socketpair()
    try:
        a.sendall(b"NOPE" + bytes(64))
        try:
            read_message(b)
            raise AssertionError("bad magic did not raise")
        except FrameError:
            pass
    finally:
        a.close()
        b.close()


# -- handshake --------------------------------------------------------------


def test_bad_hmac_rejected_good_key_accepted():
    listener = NetListener(authkey=b"right-key")
    addr = f"tcp://{listener.address[0]}:{listener.address[1]}"
    accepted = []

    def _accept_loop():
        while True:
            try:
                accepted.append(listener.accept())
            except OSError:
                return  # listener closed

    t = threading.Thread(target=_accept_loop, daemon=True)
    t.start()
    try:
        try:
            connect_tcp(addr, b"wrong-key")
            raise AssertionError("wrong authkey was accepted")
        except (PodAuthError, OSError):
            pass
        # a non-handshake client is dropped without killing the listener
        host, port = parse_address(addr)
        raw = socket.create_connection((host, port))
        raw.sendall(b"garbage-not-a-handshake-frame" + bytes(32))
        raw.close()
        # the real key still gets through, with an RTT sample attached
        chan = connect_tcp(addr, b"right-key")
        assert chan.handshake_rtt_s is not None
        chan.send({"op": "ping", "x": np.ones((2, 2), np.float32)})
        deadline = time.monotonic() + 10.0
        while not accepted and time.monotonic() < deadline:
            time.sleep(0.01)
        assert accepted, "good handshake never accepted"
        echoed = accepted[0].recv()
        assert echoed["op"] == "ping"
        np.testing.assert_array_equal(echoed["x"], np.ones((2, 2), np.float32))
        chan.close()
        assert listener.bad_handshakes >= 2
    finally:
        listener.close()
        t.join(timeout=10.0)


# -- host-aware routing with fake channels ----------------------------------


class _FakeChan:
    def __init__(self):
        self.sent = []

    def send(self, msg):
        self.sent.append(msg)

    def close(self):
        pass


def _fake_router(**kw):
    kw.setdefault("auto_start", False)
    kw.setdefault("supervise", False)
    return PodRouter([sys.executable, "-c", "pass"], "1x16x16",
                     workers=0, hosts=["host0", "host1"],
                     host_label="host0", **kw)


def _fake_worker(router, wid, host, drain_s=0.0, rtt_s=None):
    w = _Worker(wid, 0, expected_host=host)
    w.alive = True
    w.host = host
    w.chan = _FakeChan()
    w.snapshot = WorkerSnapshot(
        worker_id=wid, pid=1000 + wid, t_worker=0.0,
        projected_drain_s=drain_s, ema_service_s={"1x16x16": 0.01},
        queue_free=8)
    w.snapshot_t = time.monotonic()
    w.ready.set()
    with router._lock:
        router._workers[wid] = w
    if rtt_s is not None:
        router._note_rtt(w, rtt_s)
    return w


def test_routing_prefers_local_host_until_score_beats_the_wire():
    router = _fake_router()
    local = _fake_worker(router, 0, "host0")
    remote = _fake_worker(router, 1, "host1", rtt_s=0.002)
    x = np.zeros((1, 16, 16), np.float32)
    # equal scores: the remote host pays its min-RTT penalty, so the
    # local worker wins the tie
    router.submit(x, 0)
    assert len(local.chan.sent) == 1 and not remote.chan.sent
    # pile local load past the wire cost: the remote worker must win —
    # spillover is a score decision, not a starvation tier
    local.snapshot.projected_drain_s = 0.5
    local.snapshot_t = time.monotonic()
    router.submit(x, 0)
    assert len(remote.chan.sent) == 1


def test_retry_after_min_reduces_across_hosts():
    router = _fake_router()
    _fake_worker(router, 0, "host0")
    _fake_worker(router, 1, "host1")
    x = np.zeros((1, 16, 16), np.float32)
    fut = router.submit(x, 0)
    # both hosts bounce with different backpressure estimates: the
    # surfaced retry_after is the tightest across HOSTS, not the first
    with router._lock:
        workers = dict(router._workers)
    router._on_result(workers[0], {
        "req_id": next(iter(workers[0].inflight)), "ok": False,
        "error": {"type": "QueueFullError", "retry_after_s": 0.8}})
    router._on_result(workers[1], {
        "req_id": next(iter(workers[1].inflight)), "ok": False,
        "error": {"type": "QueueFullError", "retry_after_s": 0.3}})
    try:
        fut.result(timeout=5)
        raise AssertionError("double bounce did not surface backpressure")
    except QueueFullError as e:
        assert abs(e.retry_after_s - 0.3) < 1e-9
    finally:
        router.close()


# -- process-level acceptance ----------------------------------------------

WORKER_ARGV = [
    sys.executable, "-m", "wam_tpu.pod.worker",
    "--device", "cpu", "--fake-entry", "5", "--buckets", "1x16x16",
    "--host-label", "{host}",
]


def _x():
    return np.zeros((1, 16, 16), np.float32)


def test_host_kill_midstream_zero_lost_over_tcp():
    """Whole-host SIGKILL while requests stream over real TCP sockets:
    every request resolves (re-routed to the surviving host or retried
    through typed backpressure) — the tentpole's zero-loss bar."""
    router = PodRouter(WORKER_ARGV, "1x16x16", workers=4,
                       heartbeat_s=0.1, transport="tcp",
                       hosts=["host0", "host1"], host_label="host0")
    policy = RetryPolicy(max_attempts=8, budget_s=60.0,
                         retry_on=(QueueFullError, NoLiveWorkerError))
    stats = RetryStats()
    results = []
    errors = []

    def _client(cid):
        import random
        rng = random.Random(cid)
        x = _x()
        for _ in range(20):
            try:
                results.append(
                    policy.run(lambda rem: router.submit(x, 0),
                               rng=rng, stats=stats))
            except Exception as e:  # noqa: BLE001 - any loss fails the test
                errors.append(repr(e))

    threads = [threading.Thread(target=_client, args=(i,)) for i in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.5)  # mid-stream, not before traffic
        killed = router.kill_host("host1")
        assert killed, "kill_host found no live workers on host1"
        for t in threads:
            t.join(timeout=120.0)
        assert not errors, f"lost requests: {errors[:3]}"
        assert len(results) == 80
        assert all(r.shape == (1, 16, 16) for r in results)
    finally:
        router.close()


def test_cold_worker_joins_compile_free_from_wire_bundle(tmp_path):
    """Registry distribution over the wire: seed a toy worker under
    throwaway caches, publish the bundle, then bring a COLD worker up
    with ``--registry wire`` — no bundle path on its command line, no
    shared filesystem — and verify its ready snapshot hydrated from the
    router-streamed bytes at ``compile_count == 0``. The driver-side
    interaction runs under `obs.assert_no_retrace` (worker compiles are
    counted by the worker's own sentinel and shipped in the ready row)."""
    from wam_tpu import obs
    from wam_tpu.registry import publish_bundle

    key_base = "test_transport|toy2d|J2|n2|mb8"
    toy_argv = [
        sys.executable, "-m", "wam_tpu.pod.worker",
        "--device", "cpu", "--buckets", "1x16x16", "--n-samples", "2",
        "--aot-key-base", key_base,
    ]

    def caches(label):
        root = tmp_path / label
        return {
            "WAM_TPU_AOT_CACHE": str(root / "aot"),
            "WAM_TPU_SCHEDULE_CACHE": str(root / "schedules.json"),
            "WAM_TPU_CACHE_DIR": str(root / "xla"),
        }

    seed_env = caches("seed")
    router = PodRouter(toy_argv, "1x16x16", workers=1, env=seed_env,
                       ready_timeout_s=300.0)
    try:
        assert router.attribute(_x(), 0) is not None
    finally:
        router.close()

    manifest = publish_bundle(
        str(tmp_path / "bundle"),
        aot_dir=seed_env["WAM_TPU_AOT_CACHE"],
        schedule_path=seed_env["WAM_TPU_SCHEDULE_CACHE"],
        xla_dir=seed_env["WAM_TPU_CACHE_DIR"],
        source={"test": "test_transport seed worker"},
    )
    assert sum(1 for a in manifest["artifacts"] if a["kind"] == "aot") > 0

    from wam_tpu.pod.metrics import _c_registry_stream

    streamed_before = _c_registry_stream.value()
    wire_argv = toy_argv + ["--registry", "wire"]
    with obs.assert_no_retrace():
        router = PodRouter(wire_argv, "1x16x16", workers=1,
                           transport="tcp",
                           registry=str(tmp_path / "bundle"),
                           env=caches("cold"), ready_timeout_s=300.0)
        try:
            ready = [r for r in router.metrics.worker_rows
                     if r["phase"] == "ready"]
            assert ready, "worker never reached ready"
            # THE bar: cold caches + wire-streamed bundle = zero compiles
            assert ready[0]["compile_count"] == 0
            assert router.attribute(_x(), 0) is not None
            # the bundle actually went over the wire, not a filesystem path
            assert _c_registry_stream.value() > streamed_before
        finally:
            router.close()
