"""Fleet resilience (`serve.supervisor` / `serve.retry` /
`wam_tpu.testing.faults` / crash-safe ledgers): supervised replica restart
with the zero-post-warm-compile rejoin invariant, crash-loop escalation to
permanent-dead, client-side retry/hedging discipline, deterministic chaos
schedules, the worker-crash guard, torn-ledger tolerance, and quarantine
hysteresis under flapping.

Same discipline as tests/test_fleet.py: operational tests use fake entries
with explicit kill/gate handshakes so the states they assert are
deterministic; the one probabilistic test (chaos zero-loss) runs a SEEDED
fault schedule, so its fault sequence is fixed across runs. Runs on the
virtual 8-device CPU mesh the conftest forces."""

import random
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from conftest import need_devices
from wam_tpu import obs
from wam_tpu.serve import (
    AttributionServer,
    FleetMetrics,
    FleetServer,
    NoLiveReplicaError,
    QueueFullError,
    RetryBudgetExceededError,
    RetryPolicy,
    RetryStats,
    ServerClosedError,
    SupervisorConfig,
    WorkerCrashedError,
    jit_entry,
)
from wam_tpu.testing import (
    DEFAULT_CHAOS,
    ChaosFault,
    ChaosSchedule,
    FaultInjector,
    FaultSpec,
    parse_chaos,
)


def _registry_total(prefix: str) -> float:
    from wam_tpu.obs.registry import registry

    return sum(v for k, v in registry.collect().items() if k.startswith(prefix))


# -- retry policy -------------------------------------------------------------


def test_retry_backoff_honors_retry_after():
    """The wait before a resubmit never undercuts the server's own
    projected-drain estimate, and jitter only pushes it UP."""
    policy = RetryPolicy(backoff_base_s=0.01, backoff_cap_s=1.0, jitter_frac=0.5)
    rng = random.Random(0)
    for attempt in (1, 2, 3):
        assert policy.backoff_s(attempt, rng, retry_after_s=0.5) >= 0.5
    # without a server estimate: capped exponential
    assert policy.backoff_s(1, rng) <= 0.01 * 1.5
    assert policy.backoff_s(30, rng) <= 1.0 * 1.5


def test_retry_recovers_after_backpressure():
    calls = {"n": 0}

    def submit(rem):
        calls["n"] += 1
        if calls["n"] < 3:
            raise QueueFullError(0.001)
        f = Future()
        f.set_result(42)
        return f

    stats = RetryStats()
    policy = RetryPolicy(max_attempts=4, backoff_base_s=0.001,
                         backoff_cap_s=0.002)
    assert policy.run(submit, rng=random.Random(0), stats=stats) == 42
    assert stats.attempts == 3 and stats.retries == 2 and stats.exhausted == 0
    assert stats.backoff_s_total > 0.0


def test_retry_exhaustion_is_typed_not_lost():
    """Typed exhaustion: the policy gives up with the LAST server error
    attached and pending=False — the request resolved, it was not lost."""

    def submit(rem):
        raise QueueFullError(0.001)

    stats = RetryStats()
    policy = RetryPolicy(max_attempts=3, backoff_base_s=0.001,
                         backoff_cap_s=0.002)
    with pytest.raises(RetryBudgetExceededError) as ei:
        policy.run(submit, rng=random.Random(0), stats=stats)
    assert ei.value.pending is False
    assert isinstance(ei.value.last, QueueFullError)
    assert stats.attempts == 3 and stats.exhausted == 1


def test_retry_budget_lapse_with_pending_future_is_lost():
    """A future still unresolved when the budget lapses is the one outcome
    the zero-loss chaos gate counts as a LOSS (pending=True, last=None)."""
    policy = RetryPolicy(max_attempts=3, budget_s=0.05)
    t0 = time.monotonic()
    with pytest.raises(RetryBudgetExceededError) as ei:
        policy.run(lambda rem: Future(), rng=random.Random(0))
    assert ei.value.pending is True and ei.value.last is None
    assert time.monotonic() - t0 < 5.0  # budget, not a hang


def test_retry_hedge_first_wins():
    """With the first submit parked past hedge_after_s, the hedge fires,
    resolves first, and its result wins; the loser is left unconsumed."""
    submits = []

    def submit(rem):
        f = Future()
        if submits:  # the hedge resolves immediately; the original never
            f.set_result("hedge-won")
        submits.append(f)
        return f

    stats = RetryStats()
    policy = RetryPolicy(hedge_after_s=0.005)
    out = policy.run(submit, rng=random.Random(0), stats=stats)
    assert out == "hedge-won"
    assert stats.hedges == 1 and stats.hedge_wins == 1
    assert len(submits) == 2


# -- chaos layer --------------------------------------------------------------


def test_parse_chaos_grammar():
    assert parse_chaos("default") == {"*": DEFAULT_CHAOS}
    assert parse_chaos("off") == {"*": FaultSpec()}
    s = parse_chaos("nan=0.05,exc=0.02,latency=0.1:20")["*"]
    assert (s.nan_p, s.exc_p, s.latency_p, s.latency_ms) == (0.05, 0.02, 0.1, 20.0)
    per = parse_chaos("0:exc=0.5;*:nan=0.1")
    assert per["0"].exc_p == 0.5 and per["*"].nan_p == 0.1
    with pytest.raises(ValueError):
        parse_chaos("bogus=1")
    with pytest.raises(ValueError):
        FaultSpec(nan_p=0.9, exc_p=0.9)  # probabilities must sum <= 1
    sched = ChaosSchedule("0:exc=0.5;*:nan=0.1", seed=3)
    assert sched.spec_for(0).exc_p == 0.5
    assert sched.spec_for(2).nan_p == 0.1  # '*' covers the rest
    assert sched.injector(0) is sched.injector(0)  # restart keeps the stream


def test_fault_injector_deterministic_streams():
    """A replica's fault sequence is a pure function of (seed, replica):
    identical across injector instances (and therefore across restarts and
    processes), distinct across replicas."""
    spec = FaultSpec(nan_p=0.3, exc_p=0.2, latency_p=0.2)
    a = FaultInjector(spec, seed=7, replica=0)
    b = FaultInjector(spec, seed=7, replica=0)
    c = FaultInjector(spec, seed=7, replica=1)
    seq = [a.draw() for _ in range(64)]
    assert seq == [b.draw() for _ in range(64)]
    assert seq != [c.draw() for _ in range(64)]
    assert any(k is not None for k in seq)  # the spec actually fires


def test_chaos_entry_faults_and_warmup_exemption():
    from wam_tpu.obs import sentinel as obs_sentinel
    from wam_tpu.testing.faults import ChaosEntry

    calls = []

    def inner(xs, ys):
        calls.append(1)
        return np.asarray(xs, np.float32) * 1.0

    inj = FaultInjector(FaultSpec(exc_p=1.0), seed=0, replica=0)
    entry = ChaosEntry(inner, inj)
    # warmup dispatches pass through clean and consume NO draws
    with obs_sentinel.label(phase="warmup"):
        entry(np.ones((2,), np.float32), None)
    assert len(calls) == 1 and inj.total() == 0
    with pytest.raises(ChaosFault):
        entry(np.ones((2,), np.float32), None)
    assert inj.counts == {"exc": 1}
    # nan poisoning serves a result, but a non-finite one
    inj2 = FaultInjector(FaultSpec(nan_p=1.0), seed=0, replica=0)
    out = ChaosEntry(inner, inj2)(np.ones((4,), np.float32), None)
    assert not np.isfinite(np.asarray(out)).all()
    assert inj2.counts == {"nan": 1}


# -- crash-safe ledgers -------------------------------------------------------


def test_ledger_tolerates_torn_final_line(tmp_path):
    """A truncated trailing line (torn write from a crashed process) is
    skipped with a counted warning by every reader; strict mode and the
    registry corruption counter keep the event observable."""
    from wam_tpu.results import (
        JsonlWriter,
        LedgerCorruptWarning,
        read_jsonl,
        read_jsonl_stats,
    )

    obs.configure(enabled=True)
    obs.reset()
    path = str(tmp_path / "ledger.jsonl")
    w = JsonlWriter(path)
    w.write({"metric": "serve_batch", "i": 1})
    w.write({"metric": "serve_batch", "i": 2})
    with open(path, "a") as f:
        f.write('{"metric": "serve_batch", "i": 3')  # torn: no close, no \n
    with pytest.warns(LedgerCorruptWarning):
        rows = read_jsonl(path)
    assert [r["i"] for r in rows] == [1, 2]
    with pytest.warns(LedgerCorruptWarning):
        rows2, corrupt = read_jsonl_stats(path)
    assert corrupt == 1 and [r["i"] for r in rows2] == [1, 2]
    assert _registry_total("wam_tpu_serve_ledger_corrupt_lines_total") == 2.0
    with pytest.raises(ValueError):
        read_jsonl(path, strict=True)  # historical behavior preserved
    with pytest.warns(LedgerCorruptWarning):
        assert [r["i"] for r in FleetMetrics.load_ledger(path)] == [1, 2]


def test_jsonl_writer_concurrent_appends_never_tear(tmp_path):
    """N threads appending through independent writers to one path: every
    line on disk parses (single O_APPEND write per complete line)."""
    from wam_tpu.results import JsonlWriter, read_jsonl_stats

    path = str(tmp_path / "concurrent.jsonl")
    n_threads, n_rows = 8, 50

    def writer(tid):
        w = JsonlWriter(path)
        for i in range(n_rows):
            w.write({"tid": tid, "i": i, "pad": "x" * 256})

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rows, corrupt = read_jsonl_stats(path)
    assert corrupt == 0 and len(rows) == n_threads * n_rows
    seen = {(r["tid"], r["i"]) for r in rows}
    assert len(seen) == n_threads * n_rows  # no interleaved/duplicated lines


def test_note_restart_rows_and_counter_roundtrip():
    obs.configure(enabled=True)
    obs.reset()
    fm = FleetMetrics()
    fm.note_restart(1, "restarting", attempt=1, backoff_s=0.05, reason="boom")
    row = fm.note_restart(1, "alive", attempt=1)
    assert row["metric"] == "replica_restart" and row["schema_version"] == 2
    fm.note_restart(2, "permanent_dead", attempt=3, reason="crash loop")
    s = fm.fleet_summary()
    assert s["restarts"] == 1 and s["permanent_dead"] == ["2"]
    assert _registry_total("wam_tpu_serve_restarts_total") == 1.0


# -- quarantine hysteresis ----------------------------------------------------


def test_health_flapping_escalates_recovery_windows():
    """A flapping replica (poisoned burst, one clean probe, poisoned again)
    converges: each re-quarantine doubles the probation window up to the
    cap, so quarantine<->probation transitions are bounded logarithmically
    instead of oscillating forever. `reset_escalation` forgives."""
    from wam_tpu.obs.health import HealthConfig, HealthMonitor

    obs.configure(enabled=True)
    obs.reset()
    import jax

    from wam_tpu.obs.health import batch_stats

    bad = jax.device_get(batch_stats(np.array([np.nan], np.float32)))
    good = jax.device_get(batch_stats(np.array([1.0], np.float32)))
    cfg = HealthConfig(quarantine_after=1, recovery_s=10.0,
                       backoff_factor=2.0, max_recovery_s=40.0, clear_after=1)
    m = HealthMonitor(cfg, replica_id=0)

    t = 0.0
    expected = [10.0, 20.0, 40.0, 40.0]  # doubles, then the cap holds
    for arm, window in enumerate(expected, start=1):
        assert m.note(bad, now=t) is False
        d = m.describe()
        assert d["quarantine_arms"] == arm
        assert d["recovery_window_s"] == pytest.approx(window)
        assert not m.ok(now=t + window - 0.01)  # still quarantined
        assert m.ok(now=t + window)  # probation opens exactly at the window
        t += window
        assert m.note(good, now=t) is True  # one healthy probe clears
        assert not m.quarantined
        assert m.ok(now=t)
        t += 1.0
    m.reset_escalation()
    assert m.describe()["recovery_window_s"] == pytest.approx(10.0)


def test_health_bad_probe_rearms_without_escalating():
    """A bad probe DURING quarantine restarts the clock but is not a new
    quarantine: a long poisoned burst is one arm, not N."""
    from wam_tpu.obs.health import HealthConfig, HealthMonitor

    obs.configure(enabled=True)
    obs.reset()
    import jax

    from wam_tpu.obs.health import batch_stats

    bad = jax.device_get(batch_stats(np.array([np.inf], np.float32)))
    m = HealthMonitor(HealthConfig(quarantine_after=1, recovery_s=10.0),
                      replica_id=1)
    m.note(bad, now=0.0)
    m.note(bad, now=5.0)  # re-arm: clock restarts at 5.0
    d = m.describe()
    assert d["quarantine_arms"] == 1
    assert not m.ok(now=14.9)
    assert m.ok(now=15.0)


# -- worker crash guard -------------------------------------------------------


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_worker_crash_fails_pending_futures():
    """A worker-loop crash outside the guarded entry paths (simulated with
    a BaseException from the entry) must fail BOTH the popped in-flight
    request and everything still queued with `WorkerCrashedError` — never
    leave a future hanging — and close the server to new intake."""
    entered = threading.Event()
    release = threading.Event()

    class _Bomb:
        def __call__(self, xs, ys):
            entered.set()
            assert release.wait(timeout=10), "test gate never released"
            raise KeyboardInterrupt("simulated worker-loop bug")

    server = AttributionServer(_Bomb(), [(4,)], max_batch=1, max_wait_ms=0.0,
                               queue_depth=8, warmup=False)
    x = np.zeros((4,), np.float32)
    f1 = server.submit(x, 0)  # dispatched (popped off the queue)
    assert entered.wait(timeout=10)
    f2 = server.submit(x, 1)  # queued behind the crash
    release.set()
    with pytest.raises(WorkerCrashedError):
        f1.result(timeout=10)
    with pytest.raises(WorkerCrashedError):
        f2.result(timeout=10)
    with pytest.raises(ServerClosedError):  # intake is closed, typed
        server.submit(x, 0)
    # join the crashed worker so its (deliberate) re-raise lands inside
    # this test's filterwarnings scope, not a later test's
    server._worker.join(timeout=10)
    server.close()


# -- supervised restart -------------------------------------------------------


def test_restart_rejoins_warm_with_ledger_roundtrip(tmp_path):
    """The tentpole invariant: kill each replica of a 4-replica fleet in
    turn under load — every request resolves (drain/re-route), every
    replica is restarted by the supervisor, the restarted replicas rejoin
    at ZERO post-warm compiles (rehydrated through the process-level jit
    cache, sentinel-verified), and the ``replica_restart`` ledger rows
    round-trip against ``wam_tpu_serve_restarts_total``."""
    need_devices(4)
    obs.configure(enabled=True)
    obs.reset()
    from wam_tpu.obs import sentinel as obs_sentinel

    kills = {rid: threading.Event() for rid in range(4)}
    jits: dict = {}

    class _Killable:
        def __init__(self, inner, rid):
            self._inner = inner
            self._rid = rid

        def __call__(self, xs, ys):
            if kills[self._rid].is_set():
                kills[self._rid].clear()  # one death per arm
                raise RuntimeError(f"injected chip loss on {self._rid}")
            return self._inner(xs, ys)

    def factory(rid, m):
        # the process-level cache IS the warm state a restart rehydrates:
        # the rebuilt server re-warms through the same jitted entry, so the
        # rejoin costs zero traces
        if rid not in jits:
            jits[rid] = jit_entry(lambda xs, ys: xs * 2.0,
                                  on_trace=m.note_compile)
        return _Killable(jits[rid], rid)

    path = str(tmp_path / "fleet.jsonl")
    fleet = FleetServer(
        factory, [(4,)], replicas=4, max_batch=1, max_wait_ms=0.0,
        warmup=True, metrics_path=path, oversize="fanout",
        supervise=SupervisorConfig(max_restarts=8, window_s=60.0,
                                   backoff_base_s=0.001, jitter_frac=0.0,
                                   seed=0),
    )
    x = np.ones((4,), np.float32)
    try:
        assert fleet.describe()["supervised"] is True
        with obs_sentinel.assert_no_retrace():
            for rid in range(4):
                kills[rid].set()
                deadline = time.monotonic() + 30
                # concurrent bursts spread over the fleet (each replica's
                # projected drain grows as it takes work), so the doomed
                # replica is hit within a few rounds
                while kills[rid].is_set():
                    futs = [fleet.submit(x, i % 4) for i in range(8)]
                    for f in futs:
                        np.testing.assert_array_equal(
                            f.result(timeout=10), x * 2.0)
                    assert time.monotonic() < deadline, \
                        f"replica {rid} never took its kill"
                deadline = time.monotonic() + 30
                while not fleet._replicas[rid].alive:
                    assert time.monotonic() < deadline, \
                        f"replica {rid} never restarted"
                    time.sleep(0.005)
            # the restarted fleet serves, still compile-free
            for i in range(8):
                np.testing.assert_array_equal(fleet.attribute(x, i % 4),
                                              x * 2.0)
    finally:
        for e in kills.values():
            e.clear()
        fleet.close()

    rows = FleetMetrics.load_ledger(path)
    restarts = [r for r in rows if r.get("metric") == "replica_restart"]
    alive = [r for r in restarts if r["transition"] == "alive"]
    assert {r["replica_id"] for r in alive} == {0, 1, 2, 3}
    assert all(r["schema_version"] == 2 for r in restarts)
    assert all(r["attempt"] >= 1 for r in restarts)
    # ledger rows and the registry counter tell the same story
    assert _registry_total("wam_tpu_serve_restarts_total") == len(alive) == 4
    fleet_rows = [r for r in rows if r.get("metric") == "fleet_summary"]
    assert fleet_rows and fleet_rows[0]["restarts"] == 4
    assert fleet_rows[0]["permanent_dead"] == []


def test_crash_loop_escalates_to_permanent_dead():
    """A replica that dies again right after restarting crash-loops: once
    ``max_restarts`` completed restarts land inside the window, the next
    death escalates to permanent-dead (ledger row + no more restart
    threads) and the fleet serves on the survivors."""
    need_devices(2)

    def factory(rid, m):
        if rid == 0:
            def dying(xs, ys):
                raise RuntimeError("replica 0 is cursed")

            return dying

        def survivor(xs, ys):
            # slow enough that its projected drain under a concurrent
            # burst exceeds the dead replica's never-served EMA seed, so
            # the router keeps offering replica 0 its next death
            time.sleep(0.02)
            return np.asarray(xs) * 2.0

        return survivor

    fleet = FleetServer(
        factory, [(4,)], replicas=2, max_batch=1, max_wait_ms=0.0,
        warmup=False, oversize="fanout",
        supervise=SupervisorConfig(max_restarts=1, window_s=60.0,
                                   backoff_base_s=0.001, jitter_frac=0.0,
                                   seed=1),
    )
    x = np.ones((4,), np.float32)
    try:
        deadline = time.monotonic() + 20
        while not fleet._supervisor.permanently_dead(0):
            # every request resolves via the survivor regardless
            futs = [fleet.submit(x, 0) for _ in range(6)]
            for f in futs:
                np.testing.assert_array_equal(f.result(timeout=10), x * 2.0)
            assert time.monotonic() < deadline, "never escalated"
            time.sleep(0.002)
        while True:  # the permanent_dead row lands just after the flag
            transitions = [r["transition"] for r in fleet.metrics.restarts
                           if r["replica_id"] == 0]
            if "permanent_dead" in transitions:
                break
            assert time.monotonic() < deadline
            time.sleep(0.002)
        assert "restarting" in transitions and "alive" in transitions
        assert transitions[-1] == "permanent_dead"
        assert fleet.describe()["supervision"]["permanent_dead"] == [0]
        np.testing.assert_array_equal(fleet.attribute(x, 1), x * 2.0)
    finally:
        fleet.close()


def test_chaos_fleet_zero_loss_with_supervision():
    """The acceptance property at test scale: a supervised 4-replica fleet
    under a seeded chaos schedule (injected deaths + latency) with
    retrying clients loses ZERO requests — every submit resolves OK —
    while restarts actually happen."""
    need_devices(4)
    obs.configure(enabled=True)
    obs.reset()
    sched = ChaosSchedule("exc=0.15,latency=0.1:2", seed=11)
    factory = sched.wrap_factory(
        lambda rid, m: (lambda xs, ys: np.asarray(xs) * 2.0))
    fleet = FleetServer(
        factory, [(4,)], replicas=4, max_batch=1, max_wait_ms=0.0,
        queue_depth=2, warmup=False, oversize="fanout",
        supervise=SupervisorConfig(max_restarts=50, window_s=60.0,
                                   backoff_base_s=0.001, jitter_frac=0.0,
                                   seed=11),
    )
    policy = RetryPolicy(max_attempts=8, budget_s=20.0, backoff_base_s=0.002,
                         backoff_cap_s=0.05,
                         retry_on=(QueueFullError, NoLiveReplicaError))
    stats = RetryStats()
    x = np.ones((4,), np.float32)
    ok = {"n": 0}
    errs: list = []
    lock = threading.Lock()

    def client(cid):
        rng = random.Random(cid)
        for i in range(12):
            try:
                out = fleet.submit_with_retry(
                    x, i % 4, policy=policy, stats=stats, rng=rng,
                ).result(timeout=30)
                np.testing.assert_array_equal(out, x * 2.0)
                with lock:
                    ok["n"] += 1
            except Exception as e:  # noqa: BLE001 - tallied, asserted below
                with lock:
                    errs.append(repr(e))

    threads = [threading.Thread(target=client, args=(c,)) for c in range(4)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        fleet.close()
    assert not errs, f"lost/failed requests under chaos: {errs[:3]}"
    assert ok["n"] == 48
    assert sched.injected_total() > 0  # the schedule actually fired
    summary = fleet.metrics.fleet_summary()
    assert summary["restarts"] > 0  # deaths happened AND were recovered
    assert summary["permanent_dead"] == []
