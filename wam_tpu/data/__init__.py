from wam_tpu.data.audio import (
    ESC50,
    add_0db_noise,
    load_sound,
    logmel_np,
    make_weights_for_balanced_classes,
    stft_np,
)
from wam_tpu.data.checkpoints import (
    build_vision_model,
    load_3d_model,
    load_3dvoxel_model,
    load_audio_model,
    load_variables,
    save_variables,
)
from wam_tpu.data.image import (
    get_alpha_cmap,
    load_images,
    load_imagenet_validation,
    preprocess_image,
    show,
)
from wam_tpu.data.mnist3d import batches, load_3d_mnist, load_3dvoxel_mnist

__all__ = [
    "ESC50",
    "add_0db_noise",
    "load_sound",
    "logmel_np",
    "stft_np",
    "make_weights_for_balanced_classes",
    "preprocess_image",
    "load_images",
    "load_imagenet_validation",
    "show",
    "get_alpha_cmap",
    "load_3d_mnist",
    "load_3dvoxel_mnist",
    "batches",
    "build_vision_model",
    "load_3d_model",
    "load_3dvoxel_model",
    "load_audio_model",
    "save_variables",
    "load_variables",
]
