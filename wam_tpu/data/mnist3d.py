"""3D-MNIST loaders — parity with `src/helpers.py:116-222`
(load_3d_mnist point clouds from train/test_point_clouds.h5,
load_3dVoxel_mnist 16³ voxel grids from full_dataset_vectors.h5),
returning numpy arrays and a simple batch iterator instead of torch
DataLoaders.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["load_3d_mnist", "load_3dvoxel_mnist", "batches"]


def batches(x: np.ndarray, y: np.ndarray, batch_size: int, shuffle: bool = False, seed: int = 42):
    """Yield (x_batch, y_batch) minibatches."""
    idx = np.arange(len(x))
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    for i in range(0, len(idx), batch_size):
        sel = idx[i : i + batch_size]
        yield x[sel], y[sel]


def _read_point_clouds(path: str, num_points: int, rng: np.random.RandomState):
    import h5py

    xs, ys = [], []
    with h5py.File(path, "r") as ds:
        for i in range(len(ds)):
            pc = ds[str(i)]["points"][:]
            idx = rng.choice(pc.shape[0], num_points)
            xs.append(pc[idx])
            ys.append(ds[str(i)].attrs["label"])
    return np.asarray(xs, dtype=np.float32), np.asarray(ys, dtype=np.int64)


def load_3d_mnist(source_dir: str, num_points: int = 1024, train: bool = False, seed: int = 42):
    """Point clouds (N, num_points, 3) + labels; test split, optionally the
    train split too (`src/helpers.py:116-178`)."""
    data_dir = os.path.join(source_dir, "3DMNIST")
    rng = np.random.RandomState(seed)
    test = _read_point_clouds(os.path.join(data_dir, "test_point_clouds.h5"), num_points, rng)
    if not train:
        return test
    train_split = _read_point_clouds(os.path.join(data_dir, "train_point_clouds.h5"), num_points, rng)
    return test, train_split


def load_3dvoxel_mnist(source_dir: str):
    """16³ voxel grids: ((X_test, y_test), (X_train, y_train))
    (`src/helpers.py:181-222`)."""
    import h5py

    with h5py.File(os.path.join(source_dir, "3DMNIST", "full_dataset_vectors.h5"), "r") as hf:
        x_train = hf["X_train"][:].reshape(-1, 16, 16, 16).astype(np.float32)
        y_train = hf["y_train"][:].astype(np.int64)
        x_test = hf["X_test"][:].reshape(-1, 16, 16, 16).astype(np.float32)
        y_test = hf["y_test"][:].astype(np.int64)
    return (x_test, y_test), (x_train, y_train)
