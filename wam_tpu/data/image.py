"""Image data layer — preprocessing and ImageNet/assets loaders.

Parity with `src/helpers.py:328-465` (load_images, load_imagenet_validation,
show, get_alpha_cmap) without torchvision: PIL + numpy preprocessing that
reproduces Resize/CenterCrop/ToTensor/Normalize.
"""

from __future__ import annotations

import json
import os
from typing import Sequence

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)

__all__ = [
    "preprocess_image",
    "load_images",
    "load_imagenet_validation",
    "show",
    "get_alpha_cmap",
]


def preprocess_image(img, resize: int = 256, crop: int | None = 224, normalize: bool = True) -> np.ndarray:
    """PIL image → (3, H, W) float32. resize shorter side, optional center
    crop, optional ImageNet standardization (the reference's default
    transforms, `src/helpers.py:340-346,390-401`). ``crop=None`` resizes to
    (resize, resize) exactly."""
    from PIL import Image

    if not hasattr(img, "convert"):
        img = Image.fromarray(np.asarray(img))
    img = img.convert("RGB")
    if crop is None:
        img = img.resize((resize, resize), Image.BILINEAR)
    else:
        w, h = img.size
        scale = resize / min(w, h)
        img = img.resize((round(w * scale), round(h * scale)), Image.BILINEAR)
        w, h = img.size
        left, top = (w - crop) // 2, (h - crop) // 2
        img = img.crop((left, top, left + crop, top + crop))
    arr = np.asarray(img, dtype=np.float32) / 255.0  # (H, W, 3)
    if normalize:
        arr = (arr - IMAGENET_MEAN) / IMAGENET_STD
    return arr.transpose(2, 0, 1)


def load_images(source_dir: str | None = None, label_file: str = "labels.json",
                labels=None, images_dir: str | None = None):
    """Assets-style loader (`src/helpers.py:370-419`): images + labels.json
    mapping name → class. Returns ((N, 3, 224, 224) float32, labels list)."""
    from PIL import Image

    if labels is None:
        images_dir = os.path.join(source_dir, "assets")
        mapping = json.load(open(os.path.join(images_dir, label_file)))
        names, labels_list = list(mapping.keys()), list(mapping.values())
        crop = None  # reference uses Resize((224, 224)) here
    else:
        names, labels_list = sorted(os.listdir(images_dir)), labels
        crop = 224

    stack = [
        preprocess_image(Image.open(os.path.join(images_dir, n)), resize=224 if crop is None else 256, crop=crop)
        for n in names
    ]
    return np.stack(stack), labels_list


def load_imagenet_validation(source_dir: str, ground_truth: str = "val.txt",
                             count: int = 1000, seed: int = 42):
    """Folder of .JPEG validation images + a `name label` text file
    (`src/helpers.py:328-368`)."""
    from PIL import Image

    with open(os.path.join(source_dir, ground_truth)) as f:
        gt = {line.split()[0]: int(line.split()[1]) for line in f if line.strip()}
    examples = [e for e in sorted(os.listdir(source_dir)) if e.endswith(".JPEG")]
    assert len(examples) == count, f"expected {count} images, found {len(examples)}"
    images = [preprocess_image(Image.open(os.path.join(source_dir, e))) for e in examples]
    return np.stack(images), [gt[e] for e in examples]


def show(img, p=False, inverse_c: bool = False, plot: bool = True, **kwargs):
    """Tensor → displayable image (`src/helpers.py:421-448`): move channels
    last, min-max normalize out-of-range data, optional percentile clip."""
    img = np.array(img, dtype=np.float32)
    if img.ndim == 3 and img.shape[0] == 1:
        img = img[0]
    elif img.ndim == 3 and img.shape[0] == 3:
        img = np.moveaxis(img, 0, 2)
    if img.ndim == 3 and img.shape[-1] == 1:
        img = img[:, :, 0]
    if img.max() > 1 or img.min() < 0:
        img = img - img.min()
        img = img / (img.max() if img.max() else 1.0)
    if p is not False:
        img = np.clip(img, np.percentile(img, p), np.percentile(img, 100 - p))
    if img.ndim == 3 and img.shape[-1] == 3 and inverse_c:
        img = img[..., ::-1]
    if plot:
        import matplotlib.pyplot as plt

        plt.imshow(img, **kwargs)
        plt.axis("off")
        plt.grid(None)
        return None
    return img


def get_alpha_cmap(cmap, min_alpha: float = 0.0):
    """Colormap with an alpha ramp for heatmap overlays
    (`src/helpers.py:450-465`)."""
    import colorsys

    import matplotlib
    import matplotlib.pyplot as plt
    from matplotlib.colors import ListedColormap

    if isinstance(cmap, str):
        base = plt.get_cmap(cmap)
        colors = base(np.arange(base.N))
    else:
        c = np.array(cmap, dtype=np.float64) / 255.0
        hls = np.array(colorsys.rgb_to_hls(*c))
        hls[-1] = 1.0
        cmax = np.clip(np.array(colorsys.hls_to_rgb(*hls)), 0, 1)
        lin = matplotlib.colors.LinearSegmentedColormap.from_list("", [c, cmax])
        colors = lin(np.arange(256))
    colors[:, -1] = np.linspace(min_alpha, 0.85, len(colors))
    return ListedColormap(colors)
