"""Model registry + checkpoint loading — the `load_vision_model` /
`load_3d_model` / `load_audio_model` role (`src/helpers.py:84-114,276-325,
468-479`), TPU-native: builds Flax modules and optionally ingests PyTorch
state-dict checkpoints (via wam_tpu.models.ingest) or native orbax
checkpoints.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import jax
import jax.numpy as jnp

__all__ = ["build_vision_model", "load_3d_model", "load_3dvoxel_model", "load_audio_model",
           "save_variables", "load_variables"]


def _init(model, example):
    return model.init(jax.random.PRNGKey(0), example)


def build_vision_model(model_key: str = "resnet18", num_classes: int = 1000,
                       checkpoint_path: str | None = None, image_size: int = 224,
                       compute_dtype: Any | None = None, nchw: bool = True,
                       fold_bn: bool = False):
    """Build a vision model by key; optionally load a torchvision-style
    checkpoint. Returns (model, variables, model_fn) with model_fn taking
    NCHW input like the reference tensors (``nchw=False`` binds the NHWC
    fast path — pair it with ``WaveletAttribution2D(model_layout="nhwc")``
    for the benched zero-layout-copy TPU configuration).

    compute_dtype=jnp.bfloat16 runs the forward/VJP at the MXU's native
    precision; fold_bn folds inference-mode BN into conv kernels (both are
    part of the recorded flagship config — see wam_tpu.models.bind_inference
    and BASELINE.md)."""
    from wam_tpu.models import bind_inference, resnet18, resnet34, resnet50, resnet101
    from wam_tpu.models.ingest import torch_resnet_to_flax

    registry = {
        "resnet18": resnet18,
        "resnet34": resnet34,
        "resnet50": resnet50,
        "resnet101": resnet101,
    }
    try:
        from wam_tpu.models.vit import vit_b16

        registry["vit_b16"] = vit_b16
    except ImportError:
        pass
    try:
        from wam_tpu.models.convnext import convnext_tiny

        registry["convnext_tiny"] = convnext_tiny
    except ImportError:
        pass

    if model_key not in registry:
        raise ValueError(f"Unknown model key {model_key!r}; options: {sorted(registry)}")
    model = registry[model_key](num_classes=num_classes)
    example = jnp.zeros((1, image_size, image_size, 3))
    variables = _init(model, example)
    if checkpoint_path is not None:
        if checkpoint_path.endswith((".pth", ".pt", ".bin")):
            import torch

            state = torch.load(checkpoint_path, map_location="cpu", weights_only=True)
            if model_key.startswith("resnet"):
                loaded = torch_resnet_to_flax(state)
            elif model_key.startswith("vit"):
                from wam_tpu.models.ingest import torch_vit_to_flax

                loaded = torch_vit_to_flax(state, num_heads=model.heads)
            elif model_key.startswith("convnext"):
                from wam_tpu.models.ingest import torch_convnext_to_flax

                loaded = torch_convnext_to_flax(state)
            else:
                raise NotImplementedError(
                    f"torch checkpoint ingestion for {model_key} not wired yet"
                )
            loaded = jax.tree_util.tree_map(jnp.asarray, loaded)
            variables = {**variables, **loaded}
        else:
            variables = load_variables(checkpoint_path, variables)
    return model, variables, bind_inference(
        model, variables, nchw=nchw, compute_dtype=compute_dtype,
        fold_bn=fold_bn,
    )


def load_3d_model(checkpoint_path: str | None, num_classes: int, feature_transform: bool,
                  num_points: int = 1024):
    """PointNet classifier (`src/helpers.py:84-98`)."""
    from wam_tpu.models.pointnet import PointNetCls

    model = PointNetCls(k=num_classes, feature_transform=feature_transform)
    variables = _init(model, jnp.zeros((1, 3, num_points)))
    if checkpoint_path:
        variables = load_variables(checkpoint_path, variables)
    return model, variables, lambda x: model.apply(variables, x)[0]


def load_3dvoxel_model(checkpoint_path: str | None, num_classes: int = 10,
                       size: int = 16):
    """Voxel CNN (`src/helpers.py:100-114`). The flatten→Dense layer binds
    the parameter shapes to ``size``³ inputs at init."""
    from wam_tpu.models.voxel import VoxelModel

    model = VoxelModel(num_classes=num_classes)
    variables = _init(model, jnp.zeros((1, 1, size, size, size)))
    if checkpoint_path:
        variables = load_variables(checkpoint_path, variables)
    return model, variables, lambda x: model.apply(variables, x)


def load_audio_model(checkpoint_path: str | None = None, num_classes: int = 50,
                     time_frames: int = 128, n_mels: int = 128):
    """Audio CNN + bound inference fn (the FtEx wrapper role,
    `src/helpers.py:276-325`)."""
    from wam_tpu.models.audio import AudioCNN, bind_audio_inference

    model = AudioCNN(num_classes=num_classes)
    variables = _init(model, jnp.zeros((1, 1, time_frames, n_mels)))
    if checkpoint_path:
        variables = load_variables(checkpoint_path, variables)
    return model, variables, bind_audio_inference(model, variables)


# -- native (orbax) checkpoints --------------------------------------------


def save_variables(path: str, variables: Any) -> None:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckpt:
        ckpt.save(os.path.abspath(path), jax.tree_util.tree_map(jnp.asarray, variables))


def load_variables(path: str, like: Any) -> Any:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckpt:
        return ckpt.restore(os.path.abspath(path), like)
