"""Audio data layer — ESC-50 dataset and sound loaders.

Parity with `src/dataloader.py` (ESC50 Dataset: fold-based split from
meta/esc50.csv, 0dB-SNR noise injection, log-mel + STFT features,
overlap_two mixing, balanced-class weights) and `src/helpers.py:35-70,
225-274` (add_0db_noise, load_sound sampler). WAV decoding goes through the
native C++ reader (`wam_tpu.native`), feature extraction through this
package's own STFT/mel (numpy, host-side — no librosa/torchaudio).
"""

from __future__ import annotations

import csv
import os
from typing import Sequence

import numpy as np

from wam_tpu.native import WavPrefetcher, read_wav
from wam_tpu.ops.melspec import mel_filterbank

__all__ = [
    "add_0db_noise",
    "stft_np",
    "logmel_np",
    "ESC50",
    "load_sound",
    "make_weights_for_balanced_classes",
]


def add_0db_noise(audio: np.ndarray) -> np.ndarray:
    """Gaussian noise at 0 dB SNR (noise RMS = signal RMS), preserving int16
    range/dtype when given int16 (`src/helpers.py:35-70`)."""
    was_int = audio.dtype == np.int16
    a = audio.astype(np.float32)
    rms_signal = np.sqrt(np.mean(a**2))
    noise = np.random.normal(0, 1, a.shape)
    noise *= rms_signal / np.sqrt(np.mean(noise**2))
    noisy = a + noise
    if was_int:
        return np.clip(noisy, -32768, 32767).astype(np.int16)
    return noisy.astype(np.float32)


def stft_np(x: np.ndarray, n_fft: int = 1024, hop: int = 512) -> np.ndarray:
    """Centered Hann STFT, (F, T) complex — the librosa.stft layout the
    reference's feature code expects (`src/dataloader.py:93`)."""
    x = np.asarray(x, dtype=np.float32)
    pad = n_fft // 2
    xp = np.pad(x, (pad, pad), mode="reflect")
    n_frames = 1 + (len(xp) - n_fft) // hop
    idx = np.arange(n_frames)[:, None] * hop + np.arange(n_fft)[None, :]
    window = np.hanning(n_fft + 1)[:-1]
    spec = np.fft.rfft(xp[idx] * window, axis=-1)
    return spec.T  # (F, T)


def _power_to_db(p: np.ndarray, amin: float = 1e-10) -> np.ndarray:
    return 10.0 * np.log10(np.maximum(p, amin))


def logmel_np(x: np.ndarray, sr: int = 44100, n_fft: int = 1024, hop: int = 512, n_mels: int = 128):
    """(log-mel (T, M), |STFT| (F, T), log1p|STFT|, phase) feature tuple."""
    Xs = stft_np(x, n_fft, hop)
    mag = np.abs(Xs)
    fb = mel_filterbank(n_fft // 2 + 1, n_mels, sr)  # (F, M)
    mel = (mag.T @ fb).T  # (M, T)
    return _power_to_db(mel).T, mag, np.log1p(mag), Xs / (1e-9 + mag)


class ESC50:
    """ESC-50 dataset with fold-based train/test split
    (`src/dataloader.py:18-118`). Items: (logmel (1, T, M) float32, label,
    |STFT|, log-STFT, phase, path, idx). Duck-compatible with
    torch.utils.data.Dataset.
    """

    def __init__(self, mode: str = "train", num_FOLD: int = 1, root_dir: str = "ESC50",
                 select_class: Sequence[int] = (), add_noise: bool = False,
                 nfft: int = 1024, hop: int = 512, sr: int = 44100, nmel: int = 128):
        self.mode = mode
        self.num_FOLD = num_FOLD
        self.root_dir = root_dir
        self.subset = list(select_class) if select_class else list(range(50))
        self.nfft, self.hop, self.sr, self.nmel = nfft, hop, sr, nmel
        self.noise = add_noise

        rows = []
        with open(os.path.join(root_dir, "meta", "esc50.csv")) as f:
            reader = csv.DictReader(f)
            for row in reader:
                fold, target = int(row["fold"]), int(row["target"])
                in_fold = fold == num_FOLD
                if target not in self.subset:
                    continue
                if (mode == "test") == in_fold:
                    rows.append(row)
        self.rows = rows
        self.noise_strength = np.zeros(len(rows))
        self.signal_strength = np.zeros(len(rows))

    def __len__(self) -> int:
        return len(self.rows)

    def iter_waveforms(self, indices=None, workers: int = 4, capacity: int = 8):
        """Stream (idx, normalized waveform) via the native threaded
        prefetcher (`wam_tpu/native/prefetch.cpp`): C++ workers decode WAV
        files ahead of the consumer in submission order — the reference's
        torch-DataLoader-worker role for this dataset. Falls back to a
        Python thread pool without the toolchain."""
        idxs = list(range(len(self.rows))) if indices is None else list(indices)
        paths = [
            os.path.join(self.root_dir, "audio", self.rows[i]["filename"])
            for i in idxs
        ]
        with WavPrefetcher(paths, workers=workers, capacity=capacity) as pf:
            for i, (_, audio) in zip(idxs, pf):
                yield i, self._normalize(audio)

    @staticmethod
    def _normalize(audio: np.ndarray) -> np.ndarray:
        """Mono-select + float32 + peak normalization, shared by the
        synchronous and prefetching decode paths.

        Divides by the SIGNED maximum — the reference's convention
        (`wf/wf.max()`, `lib/wam_1D.py:105-106` / `src/dataloader.py`) —
        kept for parity; only the all-zero (silent) clip is guarded so it
        yields zeros instead of NaNs."""
        if audio.ndim > 1:
            audio = audio[:, 0]
        audio = audio.astype(np.float32)
        peak = audio.max()
        return audio / (peak if peak != 0 else 1.0)

    def _load(self, row) -> np.ndarray:
        path = os.path.join(self.root_dir, "audio", row["filename"])
        _, audio = read_wav(path)
        return self._normalize(audio)

    def __getitem__(self, idx: int):
        row = self.rows[idx]
        y = int(row["target"])
        if len(self.subset) < 50:
            y = self.subset.index(y)
        audio = self._load(row)
        if self.noise:
            energy = (audio**2).mean()
            noise = np.random.normal(0, 0.05, audio.shape[0])
            noise *= np.sqrt(energy / (noise**2).mean())
            audio = audio + noise
        logmel, mag, logmag, phase = logmel_np(audio, self.sr, self.nfft, self.hop, self.nmel)
        path = os.path.join(self.root_dir, "audio", row["filename"])
        return logmel[None].astype(np.float32), y, mag, logmag, phase, path, idx

    def overlap_two(self, idx1: int, idx2: int, lambda2: float = 0.2):
        """Mix two clips: clip1 + λ·clip2, label of clip1
        (`src/dataloader.py:99-118`)."""
        a1 = self._load(self.rows[idx1])
        a2 = self._load(self.rows[idx2])
        n = min(len(a1), len(a2))
        mixed = a1[:n] + lambda2 * a2[:n]
        y = int(self.rows[idx1]["target"])
        if len(self.subset) < 50:
            y = self.subset.index(y)
        logmel, mag, logmag, phase = logmel_np(mixed, self.sr, self.nfft, self.hop, self.nmel)
        paths = self.rows[idx1]["filename"] + self.rows[idx2]["filename"]
        return logmel[None].astype(np.float32), y, mag, logmag, phase, paths


def load_sound(root_dir: str, n=42, noise: bool = False) -> dict:
    """Sample n clips (or the named files) from ESC-50; returns
    {'x': waveforms, 'y': labels} (`src/helpers.py:225-274`)."""
    meta = {}
    order = []
    with open(os.path.join(root_dir, "meta", "esc50.csv")) as f:
        for row in csv.DictReader(f):
            meta[row["filename"]] = int(row["target"])
            order.append(row["filename"])

    if isinstance(n, list):
        names = n
    else:
        rng = np.random.RandomState(42)
        names = [order[i] for i in rng.randint(0, len(order), n)]

    waveforms, labels = [], []
    for name in names:
        _, audio = read_wav(os.path.join(root_dir, "audio", name))
        if audio.ndim > 1:
            audio = audio[:, 0]
        labels.append(meta[name])
        waveforms.append(add_0db_noise(audio) if noise else audio)
    return {"x": waveforms, "y": labels}


def make_weights_for_balanced_classes(dataset, nclasses: int = 10) -> list[float]:
    """Inverse-frequency sample weights (`src/dataloader.py:123-134`)."""
    count = [0] * nclasses
    labels = [int(dataset[i][1]) for i in range(len(dataset))]
    for y in labels:
        count[y] += 1
    total = float(sum(count))
    per_class = [total / c if c else 0.0 for c in count]
    return [per_class[y] for y in labels]
