"""WAM-2D: image attribution in the wavelet domain (TPU-native engine).

Capability parity with `lib/wam_2D.py` (BaseWAM2D / WaveletAttribution2D):
single-pass coefficient gradients, SmoothGrad and Integrated-Gradients
estimators, dyadic mosaic output, per-scale reprojection — redesigned as one
jit-compiled XLA graph per input shape instead of the reference's
25-iteration host loop with per-sample CPU↔GPU round trips (SURVEY.md §3.1).

The model is a pure function `x (B,C,H,W) → logits (B,K)` with parameters
already bound (e.g. `lambda x: model.apply(params, x)` for Flax modules).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from wam_tpu.core.engine import WamEngine
from wam_tpu.core.estimators import integrated_path, smoothgrad
from wam_tpu.ops.packing2d import disentangle_scales, mosaic2d, reproject_mosaic

__all__ = ["BaseWAM2D", "WaveletAttribution2D"]


class BaseWAM2D:
    """Single-pass WAM-2D (`lib/wam_2D.py:50-131`).

    __call__(x, y) computes the wavelet transform of the batch, the gradient
    of the target logits w.r.t. every coefficient, and returns the dyadic
    gradient mosaic (B, S, S). Also populates:
      - ``wavelet_coeffs``: coefficient pytree of the last call
      - ``gradient_coeffs``: gradient pytree of the last call
      - ``scales``: per-level pixel-domain maps (B, J(+1), S, S)
    """

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        wavelet: str = "haar",
        J: int = 3,
        mode: str = "reflect",
        approx_coeffs: bool = False,
        normalize_coeffs: bool = True,
    ):
        self.wavelet = wavelet
        self.J = J
        self.mode = mode
        self.approx_coeffs = approx_coeffs
        self.normalize_coeffs = normalize_coeffs
        self.engine = WamEngine(model_fn, ndim=2, wavelet=wavelet, level=J, mode=mode)
        self._jitted = functools.cache(self._build)

    def _build(self, has_label: bool):
        def run(x, y):
            coeffs, grads = self.engine.attribute(x, y)
            return coeffs, grads, mosaic2d(grads, self.normalize_coeffs)

        return jax.jit(run) if has_label else jax.jit(lambda x: run(x, None))

    def __call__(self, x: jax.Array, y=None) -> jax.Array:
        x = jnp.asarray(x)
        if y is None:
            coeffs, grads, mosaic = self._jitted(False)(x)
        else:
            coeffs, grads, mosaic = self._jitted(True)(x, jnp.asarray(y))
        self.wavelet_coeffs = coeffs
        self.gradient_coeffs = grads
        self.scales = disentangle_scales(grads, approx_coeffs=self.approx_coeffs)
        return mosaic

    def disentangle_scales(self, grads, approx_coeffs: bool = False):
        return disentangle_scales(grads, approx_coeffs=approx_coeffs)

    def visualize_grad_wam(self, grads):
        return mosaic2d(grads, self.normalize_coeffs)


class WaveletAttribution2D(BaseWAM2D):
    """SmoothGrad / Integrated-Gradients WAM-2D (`lib/wam_2D.py:343-536`).

    method="smooth": mean over ``n_samples`` noisy passes with per-image
    σ = stdev_spread·(max−min) (`lib/wam_2D.py:379-415`).
    method="integratedgrad": trapezoidal path integral over α·coeffs scaled
    by the (normalized) input-coefficient mosaic (`lib/wam_2D.py:417-459`).

    ``dwt_bf16=True`` casts each noisy input to bfloat16 at the DWT boundary
    (inside the step — noise draws stay f32, and the transform accumulates
    f32 with f32 coefficients out, `wam_tpu.wavelets.matmul`). Measured on
    the flagship: same cosine vs f32 as the bf16 model alone (0.9987), ~2%
    faster on v5e (BASELINE.md round-3).

    ``stream_noise=True`` draws SmoothGrad noise inside the sample map
    instead of materializing the (n_samples, B, C, H, W) buffer — different
    (equally valid) draws, lower peak HBM, a few % faster at large batches
    (`core.estimators.smoothgrad(materialize_noise=False)`).
    """

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        wavelet: str = "haar",
        method: str = "smooth",
        J: int = 3,
        mode: str = "reflect",
        approx_coeffs: bool = False,
        normalize_coeffs: bool = True,
        n_samples: int = 25,
        stdev_spread: float = 0.25,
        random_seed: int = 42,
        sample_batch_size: int | None = None,
        dwt_bf16: bool = False,
        stream_noise: bool = False,
    ):
        super().__init__(
            model_fn,
            wavelet=wavelet,
            J=J,
            mode=mode,
            approx_coeffs=approx_coeffs,
            normalize_coeffs=normalize_coeffs,
        )
        if method not in ("smooth", "integratedgrad"):
            raise ValueError(f"Unknown method {method!r}")
        self.method = method
        self.dwt_bf16 = dwt_bf16
        self.stream_noise = stream_noise
        self.n_samples = n_samples
        self.stdev_spread = stdev_spread
        self.random_seed = random_seed
        self.sample_batch_size = sample_batch_size
        self._jit_smooth = jax.jit(self._smooth_impl)
        self._jit_ig = jax.jit(self._ig_impl)

    # -- SmoothGrad --------------------------------------------------------

    def _smooth_impl(self, x, y, key):
        def step(noisy):
            if self.dwt_bf16:
                noisy = noisy.astype(jnp.bfloat16)
            _, grads = self.engine.attribute(noisy, y)
            return mosaic2d(grads, self.normalize_coeffs)

        return smoothgrad(
            step,
            x,
            key,
            n_samples=self.n_samples,
            stdev_spread=self.stdev_spread,
            batch_size=self.sample_batch_size,
            materialize_noise=not self.stream_noise,
        )

    def smooth_wam(self, x, y):
        key = jax.random.PRNGKey(self.random_seed)
        avg = self._jit_smooth(jnp.asarray(x), jnp.asarray(y), key)
        self.scales = reproject_mosaic(avg, self.J, self.approx_coeffs)
        return avg

    # -- Integrated gradients ---------------------------------------------

    def _ig_impl(self, x, y):
        if self.dwt_bf16:
            # same boundary cast as the smooth path: the analysis reads
            # bf16, coefficients come back f32 (wavelets f32-accumulate)
            x = x.astype(jnp.bfloat16)
        coeffs = self.engine.decompose(x)
        baseline = mosaic2d(coeffs, normalize=True)
        spatial = x.shape[-2:]

        def grad_fn(scaled):
            grads = self.engine.grads_from_coeffs(scaled, y, spatial)
            return mosaic2d(grads, self.normalize_coeffs)

        integral = integrated_path(
            grad_fn, coeffs, n_steps=self.n_samples, batch_size=self.sample_batch_size
        )
        return baseline * integral

    def integrated_wam(self, x, y):
        attr = self._jit_ig(jnp.asarray(x), jnp.asarray(y))
        self.scales = reproject_mosaic(attr, self.J, self.approx_coeffs)
        return attr

    def __call__(self, x, y):
        if self.method == "smooth":
            return self.smooth_wam(x, y)
        return self.integrated_wam(x, y)
