"""WAM-2D: image attribution in the wavelet domain (TPU-native engine).

Capability parity with `lib/wam_2D.py` (BaseWAM2D / WaveletAttribution2D):
single-pass coefficient gradients, SmoothGrad and Integrated-Gradients
estimators, dyadic mosaic output, per-scale reprojection — redesigned as one
jit-compiled XLA graph per input shape instead of the reference's
25-iteration host loop with per-sample CPU↔GPU round trips (SURVEY.md §3.1).

The model is a pure function `x (B,C,H,W) → logits (B,K)` with parameters
already bound (e.g. `lambda x: model.apply(params, x)` for Flax modules).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from wam_tpu.core.engine import WamEngine
from wam_tpu.core.estimators import (
    integrated_path,
    resolve_sample_chunk,
    smoothgrad,
    validate_sample_batch_size,
)
from wam_tpu.ops.packing2d import disentangle_scales, mosaic2d, reproject_mosaic

__all__ = ["BaseWAM2D", "WaveletAttribution2D"]


def _synth_tagged(aot_key: str | None) -> str | None:
    """Append the currently-resolved 2D synthesis impl to an AOT cache key:
    the synthesis path is baked into an exported executable exactly like the
    dwt impl, so an entry exported under one synth backend must not be
    replayed under another (`wavelets.transform.set_synth2_impl`)."""
    if aot_key is None:
        return None
    from wam_tpu.wavelets.transform import resolved_synth2_impl

    return f"{aot_key}|synth-{resolved_synth2_impl()}"


class BaseWAM2D:
    """Single-pass WAM-2D (`lib/wam_2D.py:50-131`).

    __call__(x, y) computes the wavelet transform of the batch, the gradient
    of the target logits w.r.t. every coefficient, and returns the dyadic
    gradient mosaic (B, S, S). Also populates:
      - ``wavelet_coeffs``: coefficient pytree of the last call
      - ``gradient_coeffs``: gradient pytree of the last call
      - ``scales``: per-level pixel-domain maps (B, J(+1), S, S)
    """

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        wavelet: str = "haar",
        J: int = 3,
        mode: str = "reflect",
        approx_coeffs: bool = False,
        normalize_coeffs: bool = True,
        model_layout: str = "nchw",
        level_plan: str = "explicit",
        patch: int = 16,
        image_size: int | None = None,
    ):
        if model_layout not in ("nchw", "nhwc"):
            raise ValueError(f"model_layout must be 'nchw' or 'nhwc', got {model_layout!r}")
        if level_plan not in ("explicit", "patch"):
            raise ValueError(
                f"level_plan must be 'explicit' or 'patch', got {level_plan!r}")
        # level_plan="patch": ignore J and plan the decomposition depth from
        # the ViT patch grid (wam_tpu.xattr.planner) — levels align to token
        # granularity (224/patch-16 → J=4, level-4 cells = 1 token). The
        # geometry is validated HERE, at construction, so a non-divisible
        # input size fails before any trace.
        self.level_plan = level_plan
        self.patch_plan = None
        if level_plan == "patch":
            from wam_tpu.xattr.planner import plan_patch_levels

            if image_size is None:
                raise ValueError("level_plan='patch' requires image_size=")
            self.patch_plan = plan_patch_levels(image_size, patch, wavelet)
            J = self.patch_plan.J
        self.wavelet = wavelet
        self.J = J
        self.mode = mode
        self.approx_coeffs = approx_coeffs
        self.normalize_coeffs = normalize_coeffs
        # model_layout="nhwc": model_fn consumes NHWC directly
        # (bind_inference(nchw=False)) and the whole engine pipeline runs
        # channel-last — the input is transposed ONCE here, outside the
        # per-sample map, instead of per mapped chunk inside it
        # (wam_tpu.wavelets.nhwc; round-3 layout-copy audit). __call__ still
        # takes (B, C, H, W) — the reference's contract — either way.
        self.model_layout = model_layout
        self._caxis = -1 if model_layout == "nhwc" else 1
        self.engine = WamEngine(model_fn, ndim=2, wavelet=wavelet, level=J,
                                mode=mode, channel_last=model_layout == "nhwc")
        self._jitted = functools.cache(self._build)

    def _to_internal(self, x: jax.Array) -> jax.Array:
        """NCHW caller layout -> the engine's internal layout."""
        return jnp.transpose(x, (0, 2, 3, 1)) if self.model_layout == "nhwc" else x

    def _build(self, has_label: bool):
        def run(x, y):
            x = self._to_internal(x)
            coeffs, grads = self.engine.attribute(x, y)
            return coeffs, grads, mosaic2d(grads, self.normalize_coeffs, self._caxis)

        return jax.jit(run) if has_label else jax.jit(lambda x: run(x, None))

    def __call__(self, x: jax.Array, y=None) -> jax.Array:
        x = jnp.asarray(x)
        if y is None:
            coeffs, grads, mosaic = self._jitted(False)(x)
        else:
            coeffs, grads, mosaic = self._jitted(True)(x, jnp.asarray(y))
        self.wavelet_coeffs = coeffs
        self.gradient_coeffs = grads
        self.scales = disentangle_scales(grads, approx_coeffs=self.approx_coeffs,
                                         channel_axis=self._caxis)
        return mosaic

    def serve_entry(self, donate: bool | None = None, on_trace=None,
                    aot_key: str | None = None, with_health: bool = False):
        """Batched serving entry: jitted ``(x, y) -> mosaic (B, S, S)`` with
        no instance-attribute stashing (unlike ``__call__``), safe to call
        from the `wam_tpu.serve` worker thread. ``donate``/``on_trace``/
        ``aot_key`` are forwarded to `serve.entry.jit_entry` (input-buffer
        donation on TPU, jit cache-miss counting, AOT executable cache —
        the key must identify the model + params). ``with_health=True``
        fuses the numeric-health vector into the same graph — mosaic
        saturation/max plus the coefficient-gradient norm and pooled
        NaN/Inf counts (`WamEngine.attribute_with_health`), zero extra
        dispatches or fetches."""
        from wam_tpu.serve.entry import jit_entry

        if with_health:
            from wam_tpu.obs.health import combine_output_grads, health_stats

            def impl(x, y):
                x = self._to_internal(x)
                _, grads, gvec = self.engine.attribute_with_health(x, y)
                m = mosaic2d(grads, self.normalize_coeffs, self._caxis)
                return m, combine_output_grads(health_stats(m), gvec)

            return jit_entry(impl, donate=donate, on_trace=on_trace,
                             aot_key=_synth_tagged(aot_key),
                             with_health="fused")

        def impl(x, y):
            x = self._to_internal(x)
            _, grads = self.engine.attribute(x, y)
            return mosaic2d(grads, self.normalize_coeffs, self._caxis)

        return jit_entry(impl, donate=donate, on_trace=on_trace,
                         aot_key=_synth_tagged(aot_key))

    def disentangle_scales(self, grads, approx_coeffs: bool = False):
        return disentangle_scales(grads, approx_coeffs=approx_coeffs,
                                  channel_axis=self._caxis)

    def visualize_grad_wam(self, grads):
        return mosaic2d(grads, self.normalize_coeffs, self._caxis)


class WaveletAttribution2D(BaseWAM2D):
    """SmoothGrad / Integrated-Gradients WAM-2D (`lib/wam_2D.py:343-536`).

    method="smooth": mean over ``n_samples`` noisy passes with per-image
    σ = stdev_spread·(max−min) (`lib/wam_2D.py:379-415`).
    method="integratedgrad": trapezoidal path integral over α·coeffs scaled
    by the (normalized) input-coefficient mosaic (`lib/wam_2D.py:417-459`).

    ``dwt_bf16=True`` casts each noisy input to bfloat16 at the DWT boundary
    (inside the step — noise draws stay f32, and the transform accumulates
    f32 with f32 coefficients out, `wam_tpu.wavelets.matmul`). Measured on
    the flagship: same cosine vs f32 as the bf16 model alone (0.9987), ~2%
    faster on v5e (BASELINE.md round-3).

    ``stream_noise=True`` draws SmoothGrad noise inside the sample map
    instead of materializing the (n_samples, B, C, H, W) buffer — different
    (equally valid) draws, lower peak HBM, a few % faster at large batches
    (`core.estimators.smoothgrad(materialize_noise=False)`). NOTE: the
    ``mesh=`` path always draws shard-local with the fold_in stream (the
    ``stream_noise=True`` draws, bit-identical per sample); ``stream_noise``
    itself is ignored there, so adding ``mesh=`` under the default
    materialized-noise setting changes the (equally valid) noise
    realization.

    Scheduling defaults are "auto" — the benched TPU schedule, so the class
    API delivers the recorded flagship number out of the box (round-3
    verdict #8). On TPU, "auto" resolves ``sample_batch_size`` to target
    ~128 model rows per mapped step (the v5e sweet spot, BASELINE.md
    round-3 scaling table: chunk = 128 // batch) and turns ``stream_noise``
    on only when the materialized noise buffer would exceed ~128 MB
    (streaming is a large-buffer optimization; it loses on small buffers).
    Off-TPU, "auto" is the previous behavior (full vmap, materialized
    noise). Pass explicit values to override either.

    ``donate_inputs`` (None = donate on TPU only, the shared
    `wam_tpu.pipeline.donation` policy) donates the input batch into the
    jitted SmoothGrad graph — the materialized-noise path's
    (n_samples, B, C, H, W) buffer dominates HBM, and aliasing the input
    frees one batch for it. A caller-held `jax.Array` passed to
    ``smooth_wam`` survives (it is `donation_safe`-copied before the
    call); off-TPU nothing changes.
    """

    def __init__(
        self,
        model_fn: Callable[[jax.Array], jax.Array],
        wavelet: str = "haar",
        method: str = "smooth",
        J: int = 3,
        mode: str = "reflect",
        approx_coeffs: bool = False,
        normalize_coeffs: bool = True,
        n_samples: int = 25,
        stdev_spread: float = 0.25,
        random_seed: int = 42,
        sample_batch_size: int | None | str = "auto",
        dwt_bf16: bool = False,
        stream_noise: bool | str = "auto",
        model_layout: str = "nchw",
        mesh=None,
        seq_axis: str = "data",
        batch_axis: str | None = None,
        seq_fused: bool | str = "auto",
        donate_inputs: bool | None = None,
        level_plan: str = "explicit",
        patch: int = 16,
        image_size: int | None = None,
    ):
        super().__init__(
            model_fn,
            wavelet=wavelet,
            J=J,
            mode=mode,
            approx_coeffs=approx_coeffs,
            normalize_coeffs=normalize_coeffs,
            model_layout=model_layout,
            level_plan=level_plan,
            patch=patch,
            image_size=image_size,
        )
        # Long-context mode: mesh= shards the image ROW axis over seq_axis
        # end to end (decompose → model → grads → per-sample mosaic); see
        # parallel.seq_estimators. The sharded pipeline itself is NCHW (the
        # DWT shards the trailing spatial axes): model_layout="nhwc" wraps
        # the model with the NCHW→NHWC transpose INSIDE the sharded graph
        # (GSPMD carries the row sharding through the transpose, so the
        # channel-last model still sees its native layout); dwt_bf16 casts
        # at the decompose boundary exactly like the single-device step.
        if mesh is not None:
            from wam_tpu.parallel.seq_estimators import SeqShardedWam

            seq_model = model_fn
            if model_layout == "nhwc":
                seq_model = lambda sig: model_fn(  # noqa: E731
                    jnp.transpose(sig, (0, 2, 3, 1)))
            self._seq = SeqShardedWam(
                mesh,
                seq_model,
                ndim=2,
                wavelet=wavelet,
                level=self.J,  # the planned depth under level_plan="patch"
                mode=mode,
                seq_axis=seq_axis,
                post_fn=lambda g: mosaic2d(g, normalize_coeffs, 1),
                batch_axis=batch_axis,
                fused=seq_fused,
                dwt_bf16=dwt_bf16,
            )
        if mesh is None and batch_axis is not None:
            raise ValueError("batch_axis= requires mesh=")
        self.mesh = mesh
        self.seq_axis = seq_axis
        self.batch_axis = batch_axis
        if method not in ("smooth", "integratedgrad"):
            raise ValueError(f"Unknown method {method!r}")
        validate_sample_batch_size(sample_batch_size)
        if isinstance(stream_noise, str) and stream_noise != "auto":
            # reject e.g. "false" from a config string: bool("false") is True
            raise ValueError(
                f"stream_noise must be a bool or 'auto', got {stream_noise!r}"
            )
        self.method = method
        self.dwt_bf16 = dwt_bf16
        self.stream_noise = stream_noise
        self.n_samples = n_samples
        self.stdev_spread = stdev_spread
        self.random_seed = random_seed
        self.sample_batch_size = sample_batch_size
        self.donate_inputs = donate_inputs
        # the smooth jit is built lazily: resolving the donation policy
        # (jax.default_backend()) at construction would initialize the
        # backend before the caller's select_backend() had a say
        self._jit_smooth = None
        self._jit_ig = jax.jit(self._ig_impl)

    def _smooth_jit(self):
        if self._jit_smooth is None:
            from wam_tpu.pipeline.donation import donating_jit

            self._jit_smooth = donating_jit(
                self._smooth_impl, donate_argnums=(0,), donate=self.donate_inputs
            )
        return self._jit_smooth

    # -- scheduling --------------------------------------------------------

    def _resolve_chunk(self, x_shape) -> int | None:
        """Trace-time resolution of sample_batch_size="auto": a tuned
        schedule-cache entry for this (shape, batch, dtype) wins
        (`wam_tpu.tune`, round-6 autotuner), falling back to ~128 model rows
        per mapped step on TPU (chunk · batch ≈ 128, the v5e sweet spot —
        the shared law in `core.estimators.resolve_sample_chunk`) and full
        vmap elsewhere — exactly the schedule bench.py records."""
        return resolve_sample_chunk(
            self.sample_batch_size, x_shape[0], self.n_samples,
            workload="wam2d", shape=tuple(x_shape[1:]),
            dtype="bf16" if self.dwt_bf16 else "f32",
        )

    def _resolve_stream(self, x_shape) -> bool:
        """stream_noise="auto": a tuned schedule-cache entry's
        ``stream_noise`` wins; otherwise stream only when the materialized
        (n_samples, *x.shape) noise buffer would exceed ~128 MB f32 —
        streaming is a large-buffer optimization only (round-3 matrix)."""
        if self.stream_noise != "auto":
            return bool(self.stream_noise)
        from wam_tpu.tune import lookup_schedule

        ent = lookup_schedule("wam2d", tuple(x_shape[1:]), x_shape[0],
                              "bf16" if self.dwt_bf16 else "f32")
        if ent is not None and ent.get("stream_noise") is not None:
            return bool(ent["stream_noise"])
        if jax.default_backend() != "tpu":
            return False
        elements = self.n_samples
        for d in x_shape:
            elements *= int(d)
        return elements > (1 << 25)  # 32M f32 elements = 128 MB

    def _apply_tuned_synth(self, x_shape) -> None:
        """Trace-time application of a tuned ``synth_impl`` schedule entry
        (same key axes as `_resolve_chunk`): runs right before the first
        reconstruction is traced, so jitted AND AOT-exported graphs bake in
        the tuned synthesis path. No entry → the process-global knob (user's
        `set_synth2_impl`, default "auto") stands."""
        from wam_tpu.tune import apply_tuned_synth_impl

        apply_tuned_synth_impl(
            "wam2d", tuple(x_shape[1:]), x_shape[0],
            "bf16" if self.dwt_bf16 else "f32",
        )

    # -- SmoothGrad --------------------------------------------------------

    def _smooth_impl(self, x, y, key):
        self._apply_tuned_synth(x.shape)
        x = self._to_internal(x)  # once, OUTSIDE the sample map

        def step(noisy):
            if self.dwt_bf16:
                noisy = noisy.astype(jnp.bfloat16)
            _, grads = self.engine.attribute(noisy, y)
            return mosaic2d(grads, self.normalize_coeffs, self._caxis)

        return smoothgrad(
            step,
            x,
            key,
            n_samples=self.n_samples,
            stdev_spread=self.stdev_spread,
            batch_size=self._resolve_chunk(x.shape),
            materialize_noise=not self._resolve_stream(x.shape),
        )

    def smooth_wam(self, x, y):
        key = jax.random.PRNGKey(self.random_seed)
        if self.mesh is not None:
            x = jnp.asarray(x)
            avg = self._seq.smoothgrad(
                x, jnp.asarray(y), key,
                n_samples=self.n_samples, stdev_spread=self.stdev_spread,
                sample_chunk=self._resolve_chunk(x.shape),
            )
        else:
            from wam_tpu.pipeline.donation import donation_safe, resolve_donate

            avg = self._smooth_jit()(
                donation_safe(x, resolve_donate(self.donate_inputs)),
                jnp.asarray(y), key,
            )
        self.scales = reproject_mosaic(avg, self.J, self.approx_coeffs)
        return avg

    # -- Integrated gradients ---------------------------------------------

    def _ig_impl(self, x, y):
        self._apply_tuned_synth(x.shape)
        x = self._to_internal(x)
        if self.dwt_bf16:
            # same boundary cast as the smooth path: the analysis reads
            # bf16, coefficients come back f32 (wavelets f32-accumulate)
            x = x.astype(jnp.bfloat16)
        coeffs = self.engine.decompose(x)
        baseline = mosaic2d(coeffs, normalize=True, channel_axis=self._caxis)
        spatial = self.engine.spatial_shape(x.shape)

        def grad_fn(scaled):
            grads = self.engine.grads_from_coeffs(scaled, y, spatial)
            return mosaic2d(grads, self.normalize_coeffs, self._caxis)

        integral = integrated_path(
            grad_fn, coeffs, n_steps=self.n_samples,
            batch_size=self._resolve_chunk(x.shape),
        )
        return baseline * integral

    def integrated_wam(self, x, y):
        if self.mesh is not None:
            x = jnp.asarray(x)
            coeffs, integral = self._seq.integrated(
                x, jnp.asarray(y), n_steps=self.n_samples,
                sample_chunk=self._resolve_chunk(x.shape),
            )
            baseline = mosaic2d(coeffs, normalize=True, channel_axis=1)
            attr = baseline * integral
        else:
            attr = self._jit_ig(jnp.asarray(x), jnp.asarray(y))
        self.scales = reproject_mosaic(attr, self.J, self.approx_coeffs)
        return attr

    def __call__(self, x, y):
        if self.method == "smooth":
            return self.smooth_wam(x, y)
        return self.integrated_wam(x, y)

    def serve_entry(self, donate: bool | None = None, on_trace=None,
                    aot_key: str | None = None, with_health: bool = False):
        """Batched serving entry ``(x, y) -> mosaic (B, S, S)`` for the
        `wam_tpu.serve` worker: the estimator body without the
        instance-attribute stashing (``self.scales``) that makes ``__call__``
        thread-unsafe. SmoothGrad folds the instance seed in at entry-build
        time, so every batch reuses one noise stream — matching what repeat
        ``__call__`` invocations do. ``mesh=`` is rejected: the serving
        worker owns exactly one device. ``with_health=True`` fuses the
        numeric-health vector over the mosaic into the same graph
        (`serve.entry.jit_entry`)."""
        if self.mesh is not None:
            raise ValueError(
                "serve_entry() does not support mesh=; the serve worker owns "
                "a single device — drive the sharded estimator directly")
        from wam_tpu.serve.entry import jit_entry

        if self.method == "smooth":
            key = jax.random.PRNGKey(self.random_seed)
            impl = lambda x, y: self._smooth_impl(x, y, key)  # noqa: E731
        else:
            impl = self._ig_impl
        return jit_entry(impl, donate=donate, on_trace=on_trace,
                         aot_key=_synth_tagged(aot_key),
                         with_health=with_health)

    def anytime_serve_entry(self, stride: int | str = "auto", on_trace=None,
                            plateau_tol: float | None = None):
        """Checkpointed serving entry for ANYTIME serving
        (`wam_tpu.anytime`, DESIGN.md "Anytime attribution"): the same
        SmoothGrad mosaic as `serve_entry`, split into begin/step/finalize
        jits so an `AttributionServer` over it can deliver best-so-far
        mosaics at a deadline and exit early on convergence. The noise
        stream is the STREAMING smooth path's (the instance seed folded per
        sample index — `core.estimators.smoothgrad(materialize_noise=
        False)`), so against a streaming plain entry the full-n anytime
        result agrees up to sample-accumulation order (sequential sum vs
        stacked mean). ``stride`` is the checkpoint cadence k
        ("auto" consults the tuned ``anytime_stride`` schedule axis).
        SmoothGrad only: IG's fixed-α trapezoid weights are not a running
        mean over an exchangeable sample stream, and ``mesh=`` is rejected
        like `serve_entry`."""
        if self.mesh is not None:
            raise ValueError(
                "anytime_serve_entry() does not support mesh=; the serve "
                "worker owns a single device — drive "
                "SeqShardedWam.smoothgrad_checkpointed directly")
        if self.method != "smooth":
            raise ValueError(
                "anytime_serve_entry() needs method='smooth': IG's trapezoid "
                "path weights are not an exchangeable sample mean")
        from wam_tpu.anytime.entry import DEFAULT_PLATEAU_TOL, make_anytime_entry
        from wam_tpu.core.estimators import (
            noise_sigma, resolve_checkpoint_stride)

        key = jax.random.PRNGKey(self.random_seed)

        def sample_fn(x, y, i):
            self._apply_tuned_synth(x.shape)
            xi = self._to_internal(x)
            sigma = noise_sigma(xi, self.stdev_spread)
            k = jax.random.fold_in(key, i)
            noise = jax.random.normal(k, xi.shape, xi.dtype)
            noisy = xi + sigma.reshape((-1,) + (1,) * (xi.ndim - 1)) * noise
            if self.dwt_bf16:
                noisy = noisy.astype(jnp.bfloat16)
            _, grads = self.engine.attribute(noisy, y)
            return mosaic2d(grads, self.normalize_coeffs, self._caxis)

        return make_anytime_entry(
            sample_fn,
            n_total=self.n_samples,
            stride=resolve_checkpoint_stride(
                stride, self.n_samples, workload="wam2d",
                dtype="bf16" if self.dwt_bf16 else "f32"),
            plateau_tol=(plateau_tol if plateau_tol is not None
                         else DEFAULT_PLATEAU_TOL),
            on_trace=on_trace,
            name="wam2d_anytime")
