"""Results module — JSONL/CSV metric persistence (SURVEY.md §5.5): the
explicit replacement for the reference's notebook-side CSV writes
(`compare_iou_models.ipynb` cell 6) and instance-attribute stashing
(`self.insertion_curves` etc., `src/evaluators.py:239-245`). Long sweeps
append row-by-row so they are resumable (SURVEY.md §5.3).
"""

from __future__ import annotations

import csv
import dataclasses
import json
import os
import time
import warnings
from dataclasses import dataclass, field
from typing import Any

__all__ = ["MetricRecord", "JsonlWriter", "CsvWriter", "read_jsonl",
           "read_jsonl_stats", "LedgerCorruptWarning"]


class LedgerCorruptWarning(UserWarning):
    """A JSONL ledger carried unparsable line(s) — typically a torn final
    write from a crashed process. Readers skip them (counted)."""


@dataclass
class MetricRecord:
    metric: str
    value: float
    unit: str = ""
    config: dict = field(default_factory=dict)
    timestamp: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class JsonlWriter:
    """Append-only JSONL sink. Each `write` is ONE ``os.write`` of a
    complete line on an ``O_APPEND`` fd: on POSIX the kernel serializes
    appends per write call, so concurrent writers (N replica ledgers into
    one fleet file) never interleave mid-line and a row is either wholly
    present or wholly absent. A process killed mid-syscall can still leave
    a torn final line — that is the reader's half of the contract
    (`read_jsonl` skips it with a counted `LedgerCorruptWarning`)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def write(self, record: MetricRecord | dict) -> None:
        row = record.to_dict() if isinstance(record, MetricRecord) else record
        data = (json.dumps(row) + "\n").encode("utf-8")
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)

    def done_keys(self, key: str = "metric") -> set:
        """Keys already written — skip these on resume."""
        if not os.path.exists(self.path):
            return set()
        return {row.get(key) for row in read_jsonl(self.path)}


def read_jsonl(path: str, *, strict: bool = False) -> list[dict]:
    """Parse a JSONL ledger, tolerating corrupt lines (a torn trailing
    write from a crashed process): bad lines are skipped with one
    `LedgerCorruptWarning` per call and counted into the
    ``wam_tpu_serve_ledger_corrupt_lines_total`` registry counter.
    ``strict=True`` restores the historical raise-on-bad-line behavior."""
    rows, corrupt = read_jsonl_stats(path, strict=strict)
    return rows


def read_jsonl_stats(path: str, *, strict: bool = False) -> tuple[list[dict], int]:
    """`read_jsonl` plus the skipped-line count (ledger readers that report
    corruption — health_report / trace_report — use the local equivalent of
    this; library callers get the count without re-reading)."""
    out, corrupt = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                if strict:
                    raise
                corrupt += 1
    if corrupt:
        warnings.warn(
            f"{path}: skipped {corrupt} corrupt JSONL line(s) "
            "(torn write from an interrupted process?)",
            LedgerCorruptWarning, stacklevel=2)
        _note_corrupt_lines(corrupt)
    return out, corrupt


def _note_corrupt_lines(n: int) -> None:
    # obs is stdlib-only at import time, so this lazy import cannot cycle
    # back into results; mutations no-op when the obs layer is disabled
    from wam_tpu.obs.registry import registry as _registry

    _registry.counter(
        "wam_tpu_serve_ledger_corrupt_lines_total",
        "corrupt JSONL ledger lines skipped by tolerant readers",
    ).inc(n)


class CsvWriter:
    """Row-wise CSV writer with a fixed header (the results/*.csv shape)."""

    def __init__(self, path: str, fieldnames: list[str]):
        self.path = path
        self.fieldnames = fieldnames
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if not os.path.exists(path):
            with open(path, "w", newline="") as f:
                csv.DictWriter(f, fieldnames=fieldnames).writeheader()

    def write(self, row: dict) -> None:
        with open(self.path, "a", newline="") as f:
            csv.DictWriter(f, fieldnames=self.fieldnames).writerow(row)
