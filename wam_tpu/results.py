"""Results module — JSONL/CSV metric persistence (SURVEY.md §5.5): the
explicit replacement for the reference's notebook-side CSV writes
(`compare_iou_models.ipynb` cell 6) and instance-attribute stashing
(`self.insertion_curves` etc., `src/evaluators.py:239-245`). Long sweeps
append row-by-row so they are resumable (SURVEY.md §5.3).
"""

from __future__ import annotations

import csv
import dataclasses
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any

__all__ = ["MetricRecord", "JsonlWriter", "CsvWriter", "read_jsonl"]


@dataclass
class MetricRecord:
    metric: str
    value: float
    unit: str = ""
    config: dict = field(default_factory=dict)
    timestamp: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class JsonlWriter:
    """Append-only JSONL sink; each `write` is flushed so an interrupted
    sweep keeps every finished row."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def write(self, record: MetricRecord | dict) -> None:
        row = record.to_dict() if isinstance(record, MetricRecord) else record
        with open(self.path, "a") as f:
            f.write(json.dumps(row) + "\n")
            f.flush()

    def done_keys(self, key: str = "metric") -> set:
        """Keys already written — skip these on resume."""
        if not os.path.exists(self.path):
            return set()
        return {row.get(key) for row in read_jsonl(self.path)}


def read_jsonl(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class CsvWriter:
    """Row-wise CSV writer with a fixed header (the results/*.csv shape)."""

    def __init__(self, path: str, fieldnames: list[str]):
        self.path = path
        self.fieldnames = fieldnames
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if not os.path.exists(path):
            with open(path, "w", newline="") as f:
                csv.DictWriter(f, fieldnames=fieldnames).writeheader()

    def write(self, row: dict) -> None:
        with open(self.path, "a", newline="") as f:
            csv.DictWriter(f, fieldnames=self.fieldnames).writerow(row)
