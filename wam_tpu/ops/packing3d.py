"""3D dyadic-cube packing and per-level visualization maps (pure jnp).

Replaces the reference's per-sample numpy `refactor` loop
(`lib/wam_3D.py:127-166`) with a batched on-device pack. Slab layout per
level with span [s, e) (s = S/2^{j+1}): ddd in the main diagonal block
[s:e]³ and the six mixed orientations in the face-adjacent slabs, keys
ordered by axes (-3, -2, -1):

    aad → [:s, :s, s:e]   ada → [:s, s:e, :s]   add → [:s, s:e, s:e]
    daa → [s:e, :s, :s]   dad → [s:e, :s, s:e]  dda → [s:e, s:e, :s]

approximation |cA| in the corner [:sJ]³. Values are absolute, unnormalized
(matching refactor).

`visualize_cube` reprojects each level to full resolution (trilinear) —
the reference's `visualize` (`lib/wam_3D.py:662-719`) with its
orientation-sum typo (`add` counted twice, `aad`/`ddd` dropped) fixed to the
intended sum over all seven orientations (SURVEY.md §2.11 spirit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from wam_tpu.wavelets.transform import DETAIL3D_KEYS

__all__ = ["cube3d", "cube_size", "visualize_cube"]

_SLABS = {
    "ddd": lambda s, e: (slice(s, e), slice(s, e), slice(s, e)),
    "aad": lambda s, e: (slice(0, s), slice(0, s), slice(s, e)),
    "ada": lambda s, e: (slice(0, s), slice(s, e), slice(0, s)),
    "add": lambda s, e: (slice(0, s), slice(s, e), slice(s, e)),
    "daa": lambda s, e: (slice(s, e), slice(0, s), slice(0, s)),
    "dad": lambda s, e: (slice(s, e), slice(0, s), slice(s, e)),
    "dda": lambda s, e: (slice(s, e), slice(s, e), slice(0, s)),
}


def cube_size(coeffs) -> int:
    return int(2 * coeffs[-1]["ddd"].shape[-1])


def _crop(a: jax.Array, sl: tuple[slice, slice, slice]) -> jax.Array:
    dims = tuple(s.stop - s.start for s in sl)
    return a[..., : dims[0], : dims[1], : dims[2]]


def cube3d(coeffs, size: int | None = None) -> jax.Array:
    """Pack [cA_J, {aad..ddd}_J, ..., {aad..ddd}_1] (leaves (B, d, h, w))
    into the dyadic cube (B, S, S, S) of absolute values."""
    size = cube_size(coeffs) if size is None else size
    batch = coeffs[0].shape[0]
    out = jnp.zeros((batch, size, size, size), dtype=coeffs[0].dtype)

    approx = jnp.abs(coeffs[0])
    ea = min(approx.shape[-1], size // (2 ** (len(coeffs) - 1)))
    out = out.at[:, :ea, :ea, :ea].set(approx[:, :ea, :ea, :ea])

    # coeffs[1:] is coarsest→finest; level j (finest = last) spans
    # [S/2^(i+1), S/2^i) with i counted from the finest.
    for i, det in enumerate(coeffs[1:][::-1]):
        e = size // (2**i)
        s = size // (2 ** (i + 1))
        for key in DETAIL3D_KEYS:
            sl = _SLABS[key](s, e)
            out = out.at[(slice(None),) + sl].set(_crop(jnp.abs(det[key]), sl))
    return out


def _norm(a):
    m = jnp.max(a)
    return a / jnp.where(m == 0, 1.0, m)


def visualize_cube(cube: jax.Array, levels: int) -> jax.Array:
    """Per-level full-resolution maps (B, J+2, S, S, S): channel 0 = approx,
    1..J = detail levels coarsest-first, last = normalized sum of all."""
    size = cube.shape[-1]
    target = cube.shape[:1] + (size, size, size)
    maps = []

    sa = size // (2**levels)
    approx = cube[:, :sa, :sa, :sa]
    maps.append(_norm(jax.image.resize(approx, target, method="trilinear")))

    for j in range(levels, 0, -1):  # coarsest first like the reference
        i = j - 1  # finest-index convention of cube3d
        e = size // (2**i)
        s = size // (2 ** (i + 1))
        total = None
        for key in DETAIL3D_KEYS:
            sl = _SLABS[key](s, e)
            up = jax.image.resize(cube[(slice(None),) + sl], target, method="trilinear")
            total = up if total is None else total + up
        maps.append(_norm(total))

    stacked = jnp.stack(maps, axis=1)
    combined = _norm(stacked.sum(axis=1))
    return jnp.concatenate([stacked, combined[:, None]], axis=1)
