from wam_tpu.ops.packing2d import disentangle_scales, mosaic2d, mosaic_size, reproject_mosaic

__all__ = ["mosaic2d", "mosaic_size", "reproject_mosaic", "disentangle_scales"]
