"""Differentiable mel-spectrogram front-end (pure JAX).

TPU-native replacement for the reference's torchaudio chain
``MelSpectrogram(sample_rate, n_fft, n_mels)`` + ``AmplitudeToDB()``
(`lib/wam_1D.py:194-219`). The 1D attribution path backprops *through* this
front-end (`lib/wam_1D.py:117-126`), so everything here is jnp and
differentiable: framing (gather), Hann window, rfft, power, mel filterbank
matmul (MXU-friendly), and a clamped log10.

Conventions follow torchaudio defaults the reference relies on: hop =
n_fft // 2, centered reflect padding, power spectrogram (|STFT|²), HTK mel
scale, f_min=0, f_max=sr/2, no filterbank norm; AmplitudeToDB 'power' mode:
10·log10(max(x, 1e-10)).

Also provides the host-side approximate inverse (mel → STFT magnitude) used
only for visualization (`lib/wam_1D.py:442-448` uses librosa's NNLS; here a
pinv + clip — same role, viz-only).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["mel_filterbank", "stft_power", "melspectrogram", "amplitude_to_db",
           "mel_to_stft_magnitude", "set_stft_impl", "get_stft_impl",
           "set_mel_bf16", "get_mel_bf16"]

# STFT backend: "fft" = jnp.fft.rfft (XLA's Cooley-Tukey matmul
# decomposition on TPU); "matmul" = ONE windowed real-DFT matmul pair per
# frame batch — O(n_fft²) FLOPs instead of O(n_fft log n_fft), but the
# single (rows, n_fft) @ (n_fft, n_fft/2+1) product tiles the MXU far
# better than the FFT's many small factor stages: the benched audio step
# measured 44.1 (fft) → 58.9 wf/s (matmul, +34%) at max |Δ mel-dB| 0.033 —
# the same order as the fft-vs-exact summation floor (0.018). "auto"
# (default) = matmul on TPU for n_fft ≤ 4096, fft elsewhere
# (BASELINE.md round-4 audio section).
_STFT_IMPLS = ("auto", "fft", "matmul")
_stft_impl = "auto"


def set_stft_impl(name: str) -> None:
    """Select the STFT backend for *not-yet-traced* calls."""
    global _stft_impl
    if name not in _STFT_IMPLS:
        raise ValueError(f"impl {name!r} not one of {_STFT_IMPLS}")
    _stft_impl = name


def get_stft_impl() -> str:
    return _stft_impl


_env_impl = os.environ.get("WAM_TPU_STFT_IMPL", "auto")
try:
    set_stft_impl(_env_impl)
except ValueError as _e:
    raise ValueError(
        f"WAM_TPU_STFT_IMPL={_env_impl!r} is invalid: {_e}"
    ) from None


# bf16 mel chain (PrecisionPolicy.mel_bf16): the windowed-DFT and
# filterbank matmuls take bf16 inputs with f32 accumulation
# (preferred_element_type) — half the MXU input bytes, same f32 power /
# dB math. The DFT part honors the flag only under the matmul STFT impl
# (the fft path has no bf16 rfft worth taking — XLA upcasts); the
# filterbank matmul honors it under either impl.
# Gated by the attribution-cosine tolerance tests in tests/test_precision.py
# (the round-3 f32-accumulate DWT precedent: bf16 inputs, f32 out).
_mel_bf16 = False


def set_mel_bf16(on: bool) -> None:
    """Default the mel chain's matmuls to bf16 inputs for *not-yet-traced*
    calls (per-call ``bf16=`` overrides this)."""
    global _mel_bf16
    _mel_bf16 = bool(on)


def get_mel_bf16() -> bool:
    return _mel_bf16


_env_mel = os.environ.get("WAM_TPU_MEL_BF16", "")
if _env_mel:
    set_mel_bf16(_env_mel not in ("0", "false", "no"))


def _use_matmul_stft(n_fft: int) -> bool:
    if _stft_impl == "matmul":
        return True
    if _stft_impl == "fft":
        return False
    return jax.default_backend() == "tpu" and n_fft <= 4096


@functools.lru_cache(maxsize=8)
def _dft_matrices(n_fft: int) -> tuple[np.ndarray, np.ndarray]:
    """Windowed real-DFT matrices (n_fft, n_fft//2+1): frames @ C, frames @ S
    give the real/imag parts of rfft(frames * hann) — the window is folded
    into the matrices so the elementwise multiply disappears."""
    win = np.hanning(n_fft + 1)[:-1]
    ang = 2.0 * np.pi * np.arange(n_fft)[:, None] * np.arange(n_fft // 2 + 1)[None, :] / n_fft
    C = (np.cos(ang) * win[:, None]).astype(np.float32)
    S = (np.sin(ang) * win[:, None]).astype(np.float32)
    return C, S


def _hz_to_mel(f):
    return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)


def _mel_to_hz(m):
    return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)


@functools.lru_cache(maxsize=None)
def mel_filterbank(n_freqs: int, n_mels: int, sample_rate: int, f_min: float = 0.0, f_max: float | None = None) -> np.ndarray:
    """Triangular HTK-scale filterbank, shape (n_freqs, n_mels)."""
    f_max = sample_rate / 2 if f_max is None else f_max
    freqs = np.linspace(0, sample_rate / 2, n_freqs)
    mel_pts = np.linspace(_hz_to_mel(f_min), _hz_to_mel(f_max), n_mels + 2)
    hz_pts = _mel_to_hz(mel_pts)
    fb = np.zeros((n_freqs, n_mels))
    for m in range(n_mels):
        lo, ctr, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - freqs) / max(hi - ctr, 1e-10)
        fb[:, m] = np.clip(np.minimum(up, down), 0.0, None)
    return fb.astype(np.float32)


def stft_power(x: jax.Array, n_fft: int = 1024, hop: int | None = None, center: bool = True, impl: str | None = None, bf16: bool | None = None) -> jax.Array:
    """Power spectrogram |STFT|² with a Hann window.

    x: (..., L) → (..., n_frames, n_fft//2 + 1). Differentiable.
    ``impl`` overrides the global `set_stft_impl` selection for this call
    ("matmul" | "fft"); the sequence-sharded estimators force "matmul" — the
    DFT-as-matmul form is GSPMD-partitionable, while the fft path is not
    (and trips an XLA CPU fft-thunk layout check on sharded operands).
    ``bf16`` overrides the global `set_mel_bf16` default for this call:
    bf16 frame/DFT-matrix inputs with f32-accumulated matmuls (matmul impl
    only; the power output stays f32).
    """
    hop = n_fft // 2 if hop is None else hop
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode="reflect")
    L = x.shape[-1]
    n_frames = 1 + (L - n_fft) // hop
    if n_fft % hop == 0:
        # Framing as k shifted reshape views (hop divides n_fft): frame i is
        # the concatenation of hop-blocks i..i+k-1. Bitwise-identical to the
        # gather below, but XLA lowers it to slices — the gather form cost
        # 121 ms of the 419 ms audio attribution step on v5e (round-2 trace:
        # a 441k-index gather plus its scatter-add VJP).
        k = n_fft // hop
        nb = n_frames + k - 1
        blocks = x[..., : nb * hop].reshape(x.shape[:-1] + (nb, hop))
        frames = jnp.concatenate(
            [blocks[..., j : j + n_frames, :] for j in range(k)], axis=-1
        )
    else:
        idx = np.arange(n_frames)[:, None] * hop + np.arange(n_fft)[None, :]
        frames = x[..., idx]  # (..., n_frames, n_fft)
    if impl is not None and impl not in _STFT_IMPLS:
        raise ValueError(f"impl {impl!r} not one of {_STFT_IMPLS}")
    if impl is None or impl == "auto":
        use_matmul = _use_matmul_stft(n_fft)
    else:
        use_matmul = impl == "matmul"
    use_bf16 = _mel_bf16 if bf16 is None else bool(bf16)
    if use_matmul:
        C, S = _dft_matrices(n_fft)
        if use_bf16:
            # single-pass bf16 inputs, f32-accumulated: half the MXU input
            # bytes of the HIGH (bf16_3x) baseline below; |Δ mel-dB| gated
            # by tests/test_precision.py against the f32 oracle
            fr = frames.astype(jnp.bfloat16)
            re = jnp.matmul(fr, jnp.asarray(C, dtype=jnp.bfloat16),
                            preferred_element_type=jnp.float32)
            im = jnp.matmul(fr, jnp.asarray(S, dtype=jnp.bfloat16),
                            preferred_element_type=jnp.float32)
            return re * re + im * im
        # windowed real-DFT as two MXU matmuls; Precision.HIGH (bf16_3x
        # passes) holds the mel-dB error at the f32 summation floor while
        # measuring ~10% faster than HIGHEST end to end (BASELINE.md r4)
        re = jnp.matmul(frames, jnp.asarray(C), precision=lax.Precision.HIGH)
        im = jnp.matmul(frames, jnp.asarray(S), precision=lax.Precision.HIGH)
        return re * re + im * im
    window = jnp.asarray(np.hanning(n_fft + 1)[:-1], dtype=x.dtype)  # periodic Hann
    spec = jnp.fft.rfft(frames * window, axis=-1)
    return jnp.abs(spec) ** 2


def amplitude_to_db(power: jax.Array, amin: float = 1e-10) -> jax.Array:
    """10·log10(max(x, amin)) — torchaudio AmplitudeToDB('power'), ref=1."""
    return 10.0 * jnp.log10(jnp.maximum(power, amin))


def melspectrogram(
    x: jax.Array,
    sample_rate: int = 44100,
    n_fft: int = 1024,
    n_mels: int = 128,
    hop: int | None = None,
    to_db: bool = True,
    impl: str | None = None,
    bf16: bool | None = None,
) -> jax.Array:
    """Batch melspectrogram: (..., L) → (..., n_frames, n_mels).

    Matches the reference's per-waveform layout after its transpose
    (`lib/wam_1D.py:216`: time-major, mel channels last). ``impl`` is the
    per-call STFT backend override (see `stft_power`); ``bf16`` the
    per-call mel-chain precision override (see `set_mel_bf16`) — bf16
    inputs on the DFT and filterbank matmuls, f32 accumulation, f32 dB.
    """
    use_bf16 = _mel_bf16 if bf16 is None else bool(bf16)
    p = stft_power(x, n_fft=n_fft, hop=hop, impl=impl, bf16=use_bf16)
    fb = mel_filterbank(n_fft // 2 + 1, n_mels, sample_rate)
    if use_bf16:
        pb = p.astype(jnp.bfloat16)
        mel = jnp.matmul(pb, jnp.asarray(fb, dtype=jnp.bfloat16),
                         preferred_element_type=jnp.float32)
    else:
        mel = p @ jnp.asarray(fb, dtype=x.dtype)  # (..., n_frames, n_mels)
    return amplitude_to_db(mel) if to_db else mel


def _nnls_projected_gradient(
    A: np.ndarray, B: np.ndarray, x0: np.ndarray, iters: int = 200, tol: float = 1e-7
) -> np.ndarray:
    """Minimize ||x @ A - B||² s.t. x >= 0 (rows independent), by projected
    gradient with the exact Lipschitz step 1/λmax(AAᵀ). Host-side numpy —
    the small dense counterpart of librosa's NNLS (`lib/wam_1D.py:442-448`).
    """
    AAt = A @ A.T  # (F, F) with x (..., F): grad = (x AAt - B Aᵀ)
    step = 1.0 / max(float(np.linalg.eigvalsh(AAt).max()), 1e-12)
    BAt = B @ A.T
    x = np.maximum(x0, 0.0)
    prev = np.inf
    for _ in range(iters):
        x = np.maximum(x - step * (x @ AAt - BAt), 0.0)
        loss = float(np.square(x @ A - B).sum())
        if prev - loss <= tol * max(prev, 1.0):
            break
        prev = loss
    return x


def mel_to_stft_magnitude(mel_power: np.ndarray, sample_rate: int, n_fft: int, n_mels: int) -> np.ndarray:
    """Inverse mel projection (host-side, viz-only): non-negative least
    squares, matching the reference's librosa `mel_to_stft` NNLS inversion
    (`lib/wam_1D.py:442-448`) instead of the round-1 pinv+clip shortcut —
    pinv can leak signed energy into neighbouring bins that NNLS cannot
    (VERDICT.md round-1 missing #3). Initialized at the clipped pinv
    solution, refined by projected gradient, then sqrt to magnitude."""
    fb = mel_filterbank(n_fft // 2 + 1, n_mels, sample_rate)  # (F, M)
    pinv = np.linalg.pinv(fb)  # (M, F)
    x0 = np.clip(mel_power @ pinv, 0.0, None)  # (..., T, F)
    lead = x0.shape[:-1]
    power = _nnls_projected_gradient(
        fb, mel_power.reshape(-1, mel_power.shape[-1]), x0.reshape(-1, x0.shape[-1])
    )
    return np.sqrt(power.reshape(lead + (fb.shape[0],)))
