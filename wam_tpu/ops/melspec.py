"""Differentiable mel-spectrogram front-end (pure JAX).

TPU-native replacement for the reference's torchaudio chain
``MelSpectrogram(sample_rate, n_fft, n_mels)`` + ``AmplitudeToDB()``
(`lib/wam_1D.py:194-219`). The 1D attribution path backprops *through* this
front-end (`lib/wam_1D.py:117-126`), so everything here is jnp and
differentiable: framing (gather), Hann window, rfft, power, mel filterbank
matmul (MXU-friendly), and a clamped log10.

Conventions follow torchaudio defaults the reference relies on: hop =
n_fft // 2, centered reflect padding, power spectrogram (|STFT|²), HTK mel
scale, f_min=0, f_max=sr/2, no filterbank norm; AmplitudeToDB 'power' mode:
10·log10(max(x, 1e-10)).

Also provides the host-side approximate inverse (mel → STFT magnitude) used
only for visualization (`lib/wam_1D.py:442-448` uses librosa's NNLS; here a
pinv + clip — same role, viz-only).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["mel_filterbank", "stft_power", "melspectrogram", "amplitude_to_db", "mel_to_stft_magnitude"]


def _hz_to_mel(f):
    return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)


def _mel_to_hz(m):
    return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)


@functools.lru_cache(maxsize=None)
def mel_filterbank(n_freqs: int, n_mels: int, sample_rate: int, f_min: float = 0.0, f_max: float | None = None) -> np.ndarray:
    """Triangular HTK-scale filterbank, shape (n_freqs, n_mels)."""
    f_max = sample_rate / 2 if f_max is None else f_max
    freqs = np.linspace(0, sample_rate / 2, n_freqs)
    mel_pts = np.linspace(_hz_to_mel(f_min), _hz_to_mel(f_max), n_mels + 2)
    hz_pts = _mel_to_hz(mel_pts)
    fb = np.zeros((n_freqs, n_mels))
    for m in range(n_mels):
        lo, ctr, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - freqs) / max(hi - ctr, 1e-10)
        fb[:, m] = np.clip(np.minimum(up, down), 0.0, None)
    return fb.astype(np.float32)


def stft_power(x: jax.Array, n_fft: int = 1024, hop: int | None = None, center: bool = True) -> jax.Array:
    """Power spectrogram |STFT|² with a Hann window.

    x: (..., L) → (..., n_frames, n_fft//2 + 1). Differentiable.
    """
    hop = n_fft // 2 if hop is None else hop
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode="reflect")
    L = x.shape[-1]
    n_frames = 1 + (L - n_fft) // hop
    idx = np.arange(n_frames)[:, None] * hop + np.arange(n_fft)[None, :]
    frames = x[..., idx]  # (..., n_frames, n_fft)
    window = jnp.asarray(np.hanning(n_fft + 1)[:-1], dtype=x.dtype)  # periodic Hann
    spec = jnp.fft.rfft(frames * window, axis=-1)
    return jnp.abs(spec) ** 2


def amplitude_to_db(power: jax.Array, amin: float = 1e-10) -> jax.Array:
    """10·log10(max(x, amin)) — torchaudio AmplitudeToDB('power'), ref=1."""
    return 10.0 * jnp.log10(jnp.maximum(power, amin))


def melspectrogram(
    x: jax.Array,
    sample_rate: int = 44100,
    n_fft: int = 1024,
    n_mels: int = 128,
    hop: int | None = None,
    to_db: bool = True,
) -> jax.Array:
    """Batch melspectrogram: (..., L) → (..., n_frames, n_mels).

    Matches the reference's per-waveform layout after its transpose
    (`lib/wam_1D.py:216`: time-major, mel channels last).
    """
    p = stft_power(x, n_fft=n_fft, hop=hop)
    fb = jnp.asarray(mel_filterbank(n_fft // 2 + 1, n_mels, sample_rate), dtype=x.dtype)
    mel = p @ fb  # (..., n_frames, n_mels)
    return amplitude_to_db(mel) if to_db else mel


def mel_to_stft_magnitude(mel_power: np.ndarray, sample_rate: int, n_fft: int, n_mels: int) -> np.ndarray:
    """Approximate inverse mel projection (host-side, viz-only): least-squares
    via pseudo-inverse, clipped to non-negative, then sqrt to magnitude."""
    fb = mel_filterbank(n_fft // 2 + 1, n_mels, sample_rate)  # (F, M)
    pinv = np.linalg.pinv(fb)  # (M, F)
    power = np.clip(mel_power @ pinv, 0.0, None)  # (..., T, F)
    return np.sqrt(power)
