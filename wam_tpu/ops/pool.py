"""Stem max-pool (3x3, stride 2, pad 1) with a Pallas TPU backward.

XLA's native VJP for this pool is a `select-and-scatter` that tiles poorly
on TPU (~20 ms of the round-2 flagship attribution step at effective batch
800, ~9%). Two pure-XLA rewrites were tried and REVERTED in round 2 — the
custom_vjp graph boundary made XLA materialize the forward reduce-window
and residuals in hostile layouts, costing more than the scatter saved
(BASELINE.md ablation). This kernel avoids both problems:

- the forward stays `nn.max_pool` (fused by XLA as usual) and the ONLY
  residual is the pool input `x` — the pooled output is recomputed inside
  the backward kernel from the VMEM-resident tile, so no extra tensor is
  materialized between forward and backward;
- the backward runs one grid step per image: recompute y = maxpool(x),
  then route the cotangent with equality masks evaluated per input phase.
  Everything is unstrided reshape/max/where ops on VMEM blocks.

Routing semantics: gradient is distributed to EVERY element equal to its
window max (not just the first, as select-and-scatter routes). The
systematic tie case — ReLU zero-plateaus feeding the stem pool — is
annihilated by the adjacent ReLU VJP (those positions have pre-activation
<= 0), so only accidental equal-value collisions differ; SmoothGrad's
noise floor dominates those.

Off-TPU (or for odd spatial sizes) the VJP falls back to XLA's own.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["max_pool_stem"]

_POOL = dict(window_shape=(3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))


def _plain_pool(x):
    return nn.max_pool(x, **_POOL)


def _bwd_kernel(x_ref, g_ref, gx_ref):
    # f32 internally: Mosaic's vector compare doesn't support bf16 on this
    # target, and the equality routing must be exact (f32 embeds bf16).
    out_dtype = x_ref.dtype
    x = x_ref[0].astype(jnp.float32)  # (H, W, C)
    g = g_ref[0].astype(jnp.float32)  # (H//2, W//2, C)
    H, W, C = x.shape
    Ho, Wo = H // 2, W // 2
    neg = jnp.asarray(-jnp.inf, x.dtype)

    # ---- recompute y = maxpool(x) with unstrided ops --------------------
    xp = jnp.pad(x, ((1, 1), (1, 1), (0, 0)), constant_values=-jnp.inf)
    # row triples {2i, 2i+1, 2i+2}: pair-max via reshape + next pair's head
    rb = xp.reshape(Ho + 1, 2, W + 2, C)
    rp = jnp.maximum(rb[:, 0], rb[:, 1])  # (Ho+1, W+2, C) pair max
    rows = jnp.maximum(rp[:Ho], rb[1:, 0])  # (Ho, W+2, C) triple max
    cb = rows.reshape(Ho, Wo + 1, 2, C)
    cp = jnp.maximum(cb[:, :, 0], cb[:, :, 1])
    y = jnp.maximum(cp[:, :Wo], cb[:, 1:, 0])  # (Ho, Wo, C)

    # ---- shifted window views (w+1 along rows / cols), guarded ----------
    yR = jnp.concatenate([y[1:], jnp.full_like(y[:1], neg)], axis=0)
    gR = jnp.concatenate([g[1:], jnp.zeros_like(g[:1])], axis=0)

    def cshift(a, fill):
        return jnp.concatenate([a[:, 1:], jnp.full_like(a[:, :1], fill)], axis=1)

    yC, gC = cshift(y, neg), cshift(g, 0)
    yRC, gRC = cshift(yR, neg), cshift(gR, 0)

    # ---- per-phase routing ---------------------------------------------
    # Input (2q+a, 2r+b) belongs to windows (q+da, r+db): even coords have
    # one window per axis, odd coords two (kernel 3, stride 2, pad 1).
    xv = x.reshape(Ho, 2, Wo, 2, C)

    def route(xph, taps):
        acc = jnp.zeros_like(xph)
        for yy, gg in taps:
            acc = acc + jnp.where(xph == yy, gg, jnp.zeros_like(gg))
        return acc

    p00 = route(xv[:, 0, :, 0], [(y, g)])
    p10 = route(xv[:, 1, :, 0], [(y, g), (yR, gR)])
    p01 = route(xv[:, 0, :, 1], [(y, g), (yC, gC)])
    p11 = route(xv[:, 1, :, 1], [(y, g), (yR, gR), (yC, gC), (yRC, gRC)])

    gx = jnp.stack(
        [jnp.stack([p00, p01], axis=2), jnp.stack([p10, p11], axis=2)], axis=1
    )  # (Ho, 2, Wo, 2, C)
    gx_ref[0] = gx.reshape(H, W, C).astype(out_dtype)


def _bwd_pallas(x, g):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, W, C = x.shape
    Ho, Wo = H // 2, W // 2
    # The kernel's temporaries exceed Mosaic's conservative 16 MB scoped
    # VMEM default at 112² x 64; raise the limit (v5e has far more VMEM).
    params = pltpu.CompilerParams(vmem_limit_bytes=120 * 2**20)
    return pl.pallas_call(
        _bwd_kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, W, C), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Ho, Wo, C), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, H, W, C), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, W, C), x.dtype),
        compiler_params=params,
    )(x, g)


@jax.custom_vjp
def max_pool_stem(x):
    return _plain_pool(x)


def _fwd(x):
    return _plain_pool(x), x


def _bwd(x, g):
    H, W = x.shape[1], x.shape[2]
    # bf16 only: the kernel's working set at f32 slightly exceeds the v5e
    # 128 MB VMEM for the 112²x64 stem (measured 129.9 MB); bf16 — the
    # production compute dtype — fits comfortably.
    use_pallas = (
        jax.default_backend() == "tpu"
        and x.dtype == jnp.bfloat16
        and H % 2 == 0
        and W % 2 == 0
        and x.ndim == 4
    )
    if not use_pallas:
        _, vjp = jax.vjp(_plain_pool, x)
        return (vjp(g)[0],)
    return (_bwd_pallas(x, g),)


max_pool_stem.defvjp(_fwd, _bwd)
