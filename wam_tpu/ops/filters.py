"""Small image ops: separable Gaussian blur, superpixel pooling, nearest
upsample — jnp replacements for the reference's scipy.ndimage usage
(`gaussian_filter` at `src/evaluators.py:715`, `zoom(order=0)` at
`src/evaluators.py:732`)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["gaussian_filter2d", "superpixel_sum", "upsample_nearest"]


@functools.lru_cache(maxsize=None)
def _gauss_kernel(sigma: float, radius: int) -> np.ndarray:
    x = np.arange(-radius, radius + 1)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return (k / k.sum()).astype(np.float32)


def gaussian_filter2d(img: jax.Array, sigma: float = 2.0) -> jax.Array:
    """Separable Gaussian blur over the last two axes (edge-padded)."""
    radius = max(1, int(4.0 * sigma + 0.5))
    k = jnp.asarray(_gauss_kernel(sigma, radius), dtype=img.dtype)

    def blur_axis(a, axis):
        a = jnp.moveaxis(a, axis, -1)
        pad = [(0, 0)] * (a.ndim - 1) + [(radius, radius)]
        ap = jnp.pad(a, pad, mode="edge")
        flat = ap.reshape(-1, 1, ap.shape[-1])
        out = jax.lax.conv_general_dilated(
            flat, k[None, None, :], (1,), [(0, 0)],
            dimension_numbers=jax.lax.conv_dimension_numbers(
                flat.shape, (1, 1, 2 * radius + 1), ("NCH", "OIH", "NCH")
            ),
        )
        out = out.reshape(a.shape)
        return jnp.moveaxis(out, -1, axis)

    return blur_axis(blur_axis(img, -1), -2)


def superpixel_sum(img: jax.Array, grid: int) -> jax.Array:
    """Sum over (grid × grid) superpixels: (..., H, W) → (..., grid, grid).

    Non-divisible sizes partition every pixel into the SAME cell that
    `upsample_nearest` (jax.image.resize nearest) would map it to — the
    perturbation masks in μ-fidelity are built by exactly that upsample, so
    attribution cell sums stay aligned with the perturbed regions. Round 1
    silently truncated the trailing rows/cols instead (VERDICT.md weak #7).
    """
    h, w = img.shape[-2:]
    if h % grid == 0 and w % grid == 0:
        r = img.reshape(img.shape[:-2] + (grid, h // grid, grid, w // grid))
        return r.sum(axis=(-3, -1))
    # cell id per row/col = nearest-resize source index, by construction
    ids_h = jax.image.resize(
        jnp.arange(grid, dtype=jnp.float32), (h,), method="nearest"
    ).astype(jnp.int32)
    ids_w = jax.image.resize(
        jnp.arange(grid, dtype=jnp.float32), (w,), method="nearest"
    ).astype(jnp.int32)
    Eh = jax.nn.one_hot(ids_h, grid, dtype=img.dtype)  # (h, grid)
    Ew = jax.nn.one_hot(ids_w, grid, dtype=img.dtype)  # (w, grid)
    return jnp.einsum("...hw,hg,wk->...gk", img, Eh, Ew)


def upsample_nearest(a: jax.Array, hw: tuple[int, int]) -> jax.Array:
    """Nearest-neighbour upsample of the last two axes (zoom order=0)."""
    return jax.image.resize(a, a.shape[:-2] + tuple(hw), method="nearest")
