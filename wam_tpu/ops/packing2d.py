"""2D dyadic-mosaic packing and per-scale reprojection (pure jnp, size-generic).

Replaces the reference's numpy/cv2 post-processing with on-device ops:
- `mosaic2d` ↔ `BaseWAM2D.visualize_grad_wam` (`lib/wam_2D.py:200-264`) —
  the hard-coded 224 at `:238-239` is a known defect (SURVEY.md §2.11.3);
  sizes here derive from the coefficient shapes.
- `reproject_mosaic` ↔ `WaveletAttribution2D.reproject_wam`
  (`lib/wam_2D.py:488-536`), cv2.resize INTER_LINEAR → `jax.image.resize`
  bilinear.
- `disentangle_scales` ↔ `BaseWAM2D.disentangle_scales`
  (`lib/wam_2D.py:133-198`) — with the per-batch approx write the reference
  intended (its `img_batch` leak is defect §2.11.5).

Mosaic layout (quadrant convention of the reference): approximation in the
top-left corner; for each level with block span [s, e) (s = S/2^{i+1},
e = S/2^i, i = 0 for the finest level): diagonal at [s:e, s:e], vertical at
[s:e, :s], horizontal at [:s, s:e].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["mosaic2d", "reproject_mosaic", "disentangle_scales", "mosaic_size"]


def _norm(a: jax.Array, enabled: bool) -> jax.Array:
    if not enabled:
        return a
    m = jnp.max(a)
    return a / jnp.where(m == 0, 1.0, m)


def _prep(block: jax.Array, normalize: bool, channel_axis: int = 1) -> jax.Array:
    """abs → channel-mean → optional global-max normalization.

    Matches the reference order (mean over channels, then abs, then /max —
    `lib/wam_2D.py:243-256`); abs∘mean ≠ mean∘abs so the order matters.
    ``channel_axis=-1`` handles NHWC coefficient leaves (B, h, w, C) from
    the channel-last engine path (`wam_tpu.wavelets.nhwc`).
    """
    return _norm(jnp.abs(block.mean(axis=channel_axis)), normalize)


def mosaic_size(coeffs, channel_axis: int = 1) -> int:
    """Mosaic side = 2 × finest-level detail size (lib/wam_2D.py:217)."""
    axis = -1 if channel_axis == 1 else -2
    return int(2 * coeffs[-1].horizontal.shape[axis])


def mosaic2d(coeffs, normalize: bool = True, channel_axis: int = 1) -> jax.Array:
    """Pack per-coefficient values [cA, Detail2D_J..Detail2D_1] (each
    (B, C, h, w), or (B, h, w, C) with ``channel_axis=-1``) into the dyadic
    mosaic (B, S, S).

    Channel axis is averaged; each orientation block and the approximation
    are (optionally) normalized by their global max, reproducing
    `normalize_coeffs=True` semantics.
    """
    size = mosaic_size(coeffs, channel_axis)
    batch = coeffs[0].shape[0]
    out = jnp.zeros((batch, size, size), dtype=coeffs[0].dtype)

    approx = _prep(coeffs[0], normalize, channel_axis)
    ha = min(approx.shape[-2], size)
    wa = min(approx.shape[-1], size)
    out = out.at[:, :ha, :wa].set(approx[:, :ha, :wa])

    # coeffs[1:] is coarsest→finest; enumerate finest-first like the
    # reference's coeffs[1:][::-1] loop.
    for i, det in enumerate(coeffs[1:][::-1]):
        end = size // (2**i)
        start = size // (2 ** (i + 1))
        b = end - start
        # Off-diagonal blocks are (b, start)/(start, b): for non-dyadic
        # mosaic sizes (long filters) start != b, unlike the reference's
        # square-only assumption.
        h = _prep(det.horizontal, normalize, channel_axis)[:, :start, :b]
        v = _prep(det.vertical, normalize, channel_axis)[:, :b, :start]
        d = _prep(det.diagonal, normalize, channel_axis)[:, :b, :b]
        out = out.at[:, start:end, start:end].set(d)
        out = out.at[:, start:end, :start].set(v)
        out = out.at[:, :start, start:end].set(h)
    return out


def _resize_bilinear(a: jax.Array, size: int) -> jax.Array:
    return jax.image.resize(a, a.shape[:-2] + (size, size), method="bilinear")


def reproject_mosaic(avg: jax.Array, levels: int, approx_coeffs: bool = False) -> jax.Array:
    """Unpack an averaged mosaic (B, S, S) into per-level pixel-domain maps
    (B, levels(+1), S, S): each level's H+V+D blocks upsampled to full size
    and summed (lib/wam_2D.py:488-536)."""
    size = avg.shape[-1]
    maps = []
    for j in range(levels):
        end = size // (2**j)
        start = size // (2 ** (j + 1))
        diag = avg[:, start:end, start:end]
        vert = avg[:, start:end, :start]
        horz = avg[:, :start, start:end]
        maps.append(
            _resize_bilinear(horz, size) + _resize_bilinear(vert, size) + _resize_bilinear(diag, size)
        )
    if approx_coeffs:
        end = size // (2**levels)
        maps.append(_resize_bilinear(avg[:, :end, :end], size))
    return jnp.stack(maps, axis=1)


def disentangle_scales(coeffs, approx_coeffs: bool = False, size: int | None = None,
                       channel_axis: int = 1) -> jax.Array:
    """Per-level pixel-domain importance maps straight from coefficient
    grads: (B, J(+1), S, S), finest level first (lib/wam_2D.py:133-198).
    ``channel_axis=-1`` for NHWC coefficient leaves."""
    if size is None:
        size = mosaic_size(coeffs, channel_axis)
    maps = []
    for det in coeffs[1:][::-1]:
        total = (
            _resize_bilinear(_prep(det.horizontal, True, channel_axis), size)
            + _resize_bilinear(_prep(det.vertical, True, channel_axis), size)
            + _resize_bilinear(_prep(det.diagonal, True, channel_axis), size)
        )
        maps.append(total)
    if approx_coeffs:
        maps.append(_resize_bilinear(_prep(coeffs[0], True, channel_axis), size))
    return jnp.stack(maps, axis=1)
