"""Autotuner CLI.

    python -m wam_tpu.tune --workload toy --dry-run --device cpu   # CI smoke
    python -m wam_tpu.tune --workload flagship                      # tune + persist
    python -m wam_tpu.tune --workload mu2d --k 5

Sweeps the workload's candidate schedules (`wam_tpu.tune.workloads`),
prints one progress line per candidate to stderr and ONE JSON summary line
to stdout, and persists the winner to the user schedule cache
(``$WAM_TPU_SCHEDULE_CACHE`` or ``~/.cache/wam_tpu/schedules.json``) unless
``--dry-run``. Measurement plane is device (xplane module spans) on TPU,
wall elsewhere — recorded in the output so numbers are never misread.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m wam_tpu.tune",
        description="Sweep candidate schedules and persist the winner.",
    )
    p.add_argument("--workload", default="toy",
                   help="preset name: toy | flagship | mu2d | fan2d | "
                        "wamseq1d | wamseq2d")
    p.add_argument("--device", default="auto",
                   help="backend: auto | tpu | cpu")
    p.add_argument("--k", type=int, default=3, help="samples per candidate")
    p.add_argument("--laps", type=int, default=2,
                   help="calls per timed region (amortizes the tunnel RTT)")
    p.add_argument("--dry-run", action="store_true",
                   help="sweep and report but do not persist the winner")
    args = p.parse_args(argv)

    from wam_tpu.config import (
        enable_compilation_cache,
        ensure_usable_backend,
        select_backend,
    )

    # Backend must be pinned BEFORE first jax use: the axon TPU plugin
    # force-selects itself and ignores a late JAX_PLATFORMS env alone
    # (verify-skill gotcha), and can hang when its pool is unreachable.
    select_backend(args.device)
    if args.device in ("auto", "tpu"):
        ensure_usable_backend(timeout_s=180.0)
    enable_compilation_cache()

    import jax

    from wam_tpu.tune.autotuner import autotune
    from wam_tpu.tune.cache import default_cache_path
    from wam_tpu.tune.workloads import get_workload

    wl = get_workload(args.workload)
    print(f"# backend={jax.default_backend()} workload={wl.name} "
          f"candidates={len(wl.candidates)} k={args.k} laps={args.laps}",
          file=sys.stderr)
    res = autotune(wl, k=args.k, laps=args.laps, persist=not args.dry_run,
                   log=lambda s: print(s, file=sys.stderr))
    print(json.dumps({
        "workload": wl.name,
        "key": res["key"],
        "winner": res["winner"]["label"],
        "items_per_s": round(res["winner"]["items_per_s"], 3),
        "median_s": round(res["winner"]["median_s"], 6),
        "plane": res["winner"]["plane"],
        "backend": jax.default_backend(),
        "persisted": res["persisted"],
        "cache": default_cache_path() if res["persisted"] else None,
        "candidates": [
            {"label": r["label"], "items_per_s": round(r["items_per_s"], 3),
             "median_s": round(r["median_s"], 6),
             "q1_s": round(r["q1_s"], 6), "q3_s": round(r["q3_s"], 6)}
            for r in res["results"]
        ],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
