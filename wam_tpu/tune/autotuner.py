"""Schedule autotuner: measure candidate schedules, crown one, persist it.

The round-3 scaling study fit the 128-row chunk law by hand at one geometry;
round 5's roofline showed the flagship still runs at 29.7% of its traffic
floor — the remaining gap is schedule. This module turns the hand sweep into
a harness: a workload preset (`wam_tpu.tune.workloads`) builds a jitted
runner per `Candidate` (sample chunk, stream_noise, dwt impl, layout,
eval fan cap / fan chunk), the measurement prefers `profiling.device_time_samples`
medians (xplane module spans — the chip, not the tunnel; VERDICT.md round-5
directive 4) and falls back to `bench_samples` wall medians where no TPU
device plane exists (CPU CI, the `--dry-run` smoke), and the winner is
persisted to the schedule cache that `resolve_sample_chunk("auto")` and the
engines consult (`wam_tpu.tune.cache`).

CLI: ``python -m wam_tpu.tune --workload flagship`` (see `__main__`).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

__all__ = ["Candidate", "chunk_candidates", "measure_candidate", "autotune"]


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point in the schedule space. ``None`` fields mean "workload
    default" and are omitted from the persisted entry — except
    ``sample_chunk``, where None IS the value (full vmap, the same
    convention as `resolve_sample_chunk`)."""

    sample_chunk: int | None = None
    stream_noise: bool | None = None
    dwt_impl: str | None = None
    synth_impl: str | None = None  # 2D synthesis backend (set_synth2_impl)
    layout: str | None = None  # "nhwc" | "nchw" (2D engines)
    fan_cap: int | None = None  # evaluation fan chunk cap (eval workloads)
    fan_chunk: int | None = None  # eval images-per-chunk override (fan engine)
    seq_fused: bool | None = None  # seq-sharded one-jit step vs split loop
    # anytime checkpoint stride k (wam_tpu.anytime): samples per
    # confidence checkpoint in the checkpointed estimators / entries
    anytime_stride: int | None = None
    # precision axes (config.PrecisionPolicy): eval-fan compute dtype
    # ("f32"/"bf16"/"fp8") and the bf16 mel chain flag — resolved by
    # plan_fan / resolve_precision from the persisted entry
    fan_dtype: str | None = None
    mel_bf16: bool | None = None

    def label(self) -> str:
        parts = [f"chunk={self.sample_chunk if self.sample_chunk else 'full'}"]
        if self.stream_noise is not None:
            parts.append(f"stream={'on' if self.stream_noise else 'off'}")
        if self.dwt_impl is not None:
            parts.append(f"dwt={self.dwt_impl}")
        if self.synth_impl is not None:
            parts.append(f"synth={self.synth_impl}")
        if self.layout is not None:
            parts.append(self.layout)
        if self.fan_cap is not None:
            parts.append(f"fan={self.fan_cap}")
        if self.fan_chunk is not None:
            parts.append(f"fchunk={self.fan_chunk}")
        if self.seq_fused is not None:
            parts.append("fused" if self.seq_fused else "split")
        if self.anytime_stride is not None:
            parts.append(f"k={self.anytime_stride}")
        if self.fan_dtype is not None:
            parts.append(f"dtype={self.fan_dtype}")
        if self.mel_bf16 is not None:
            parts.append(f"mel={'bf16' if self.mel_bf16 else 'f32'}")
        return " ".join(parts)

    def entry(self) -> dict:
        """The knob fields of a schedule-cache entry."""
        out: dict = {"sample_chunk": self.sample_chunk}
        for field in ("stream_noise", "dwt_impl", "synth_impl", "layout",
                      "fan_cap", "fan_chunk", "seq_fused", "anytime_stride",
                      "fan_dtype", "mel_bf16"):
            v = getattr(self, field)
            if v is not None:
                out[field] = v
        return out


def chunk_candidates(batch: int, n_samples: int,
                     targets=(128, 256, 512)) -> list[int | None]:
    """Sample-chunk values to sweep: the row-law chunk for each target model
    rows per mapped step (the hand-fit 128 plus the ABOVE-law 256/512 the
    round-5 roofline argues for), then full vmap. Deduped in order; chunks
    ≥ n_samples collapse into the full-vmap candidate (None)."""
    seen: list[int | None] = []
    for rows in targets:
        chunk = max(1, int(rows) // max(1, int(batch)))
        if chunk >= n_samples:
            chunk = None
        if chunk not in seen:
            seen.append(chunk)
    if None not in seen:
        seen.append(None)
    return seen


def measure_candidate(fn: Callable, args: tuple, *, k: int = 3,
                      laps: int = 2) -> tuple[list[float], str]:
    """(samples_seconds, plane) for one candidate runner: device-plane
    medians when the backend exposes xplane module spans (tunnel-immune —
    the round-5 protocol), wall-clock `bench_samples` otherwise. The wall
    fallback keeps the sweep ordering honest on CPU but its absolute numbers
    carry host/tunnel state; the plane is recorded in the entry so a reader
    can tell which protocol crowned it."""
    from wam_tpu.profiling import bench_samples, device_time_samples

    dev = device_time_samples(fn, *args, k=k, laps=laps)
    if dev:
        return dev, "device"
    return bench_samples(fn, *args, k=max(3, k), laps=laps), "wall"


def autotune(workload, *, k: int = 3, laps: int = 2, persist: bool = True,
             log: Callable[[str], None] | None = None) -> dict:
    """Sweep ``workload.candidates``, report every measurement, persist the
    winner (unless ``persist=False`` — the CLI's ``--dry-run``).

    ``workload`` is a `wam_tpu.tune.workloads.Workload`: its ``build(cand)``
    returns a ``(fn, args)`` runner pair compiled with the candidate's knobs
    baked in (explicit values, never "auto" — the sweep must not read the
    cache it is about to write).

    Returns {"key", "winner", "entry", "results", "persisted"}; ``results``
    rows carry median/q1/q3 seconds, items/s, and the measurement plane.
    """
    from wam_tpu.profiling import median_iqr
    from wam_tpu.tune.cache import record_schedule, schedule_key

    say = log or (lambda s: None)
    results = []
    for cand in workload.candidates:
        fn, args = workload.build(cand)
        t0 = time.perf_counter()
        samples, plane = measure_candidate(fn, args, k=k, laps=laps)
        med, q1, q3, _ = median_iqr(samples)
        row = {
            "candidate": cand,
            "label": cand.label(),
            "median_s": med,
            "q1_s": q1,
            "q3_s": q3,
            "items_per_s": workload.items / med,
            "plane": plane,
            "sweep_wall_s": time.perf_counter() - t0,
        }
        results.append(row)
        say(f"  {cand.label():<40s} {row['items_per_s']:9.2f} items/s "
            f"median {med * 1e3:8.2f} ms  [{plane}]")
    winner = min(results, key=lambda r: r["median_s"])
    entry = {
        **winner["candidate"].entry(),
        "median_s": round(winner["median_s"], 6),
        "items_per_s": round(winner["items_per_s"], 3),
        "plane": winner["plane"],
        "source": f"autotune:{workload.name}",
        "tuned_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if persist:
        key = record_schedule(workload.workload, workload.shape,
                              workload.batch, entry, dtype=workload.dtype)
    else:
        key = schedule_key(workload.workload, workload.shape, workload.batch,
                           workload.dtype)
    say(f"winner: {winner['label']} -> {key}"
        + ("" if persist else "  (dry-run, not persisted)"))
    return {"key": key, "winner": winner, "entry": entry, "results": results,
            "persisted": persist}
