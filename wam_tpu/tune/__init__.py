"""wam_tpu.tune — schedule autotuner + fused backward kernels.

Round 5's roofline put the flagship step at 29.7% of its HBM-traffic floor
and ~7% of bf16 peak: the gap is schedule, not arithmetic. This package
harvests it on two fronts:

- **Schedule autotuning** (`cache`, `autotuner`, `workloads`): a measured,
  persisted schedule table keyed by (workload, shape, batch, dtype,
  dwt impl, backend) that `core.estimators.resolve_sample_chunk("auto")`,
  the three engines, `parallel.SeqShardedWam`, and serve warmup consult —
  replacing the single hand-fit 128-row-law constant. Run
  ``python -m wam_tpu.tune`` to (re)tune; winners persist to
  ``~/.cache/wam_tpu/schedules.json`` over the repo-pinned defaults.
- **Fused backward kernels** (`fused_relu`): a packed-sign-mask
  `custom_vjp` ReLU (residual 1/32 the bytes, backward one masked multiply)
  enabled by ``models.bind_inference(..., fused_relu_vjp=True)``.
- **Online schedule learning** (`mix`, `online`, round 19): a shadow tuner
  that mines the serve ledger into a `WorkloadMix`, re-sweeps against the
  observed distribution (``wamlive`` preset), canary-A/Bs the challenger on
  one fleet replica, and on a clear win publishes it as a registry bundle —
  ``python -m wam_tpu.tune.online`` (kill switch ``WAM_TPU_NO_ONLINE_TUNE``).
"""

from wam_tpu.tune.cache import (
    SCHEDULE_CACHE_VERSION,
    ScheduleCache,
    apply_tuned_synth_impl,
    default_cache_path,
    entries_fingerprint,
    invalidate_process_cache,
    load_schedule_cache,
    lookup_schedule,
    record_schedule,
    resolve_bucket_cap,
    resolve_fan_cap,
    schedule_fingerprint,
    schedule_key,
)
from wam_tpu.tune.fused_relu import (
    fused_relu,
    get_fused_relu_impl,
    set_fused_relu_impl,
)

__all__ = [
    "SCHEDULE_CACHE_VERSION",
    "ScheduleCache",
    "apply_tuned_synth_impl",
    "default_cache_path",
    "invalidate_process_cache",
    "load_schedule_cache",
    "lookup_schedule",
    "record_schedule",
    "resolve_bucket_cap",
    "resolve_fan_cap",
    "schedule_fingerprint",
    "schedule_key",
    "fused_relu",
    "get_fused_relu_impl",
    "set_fused_relu_impl",
    "autotune",
    "Candidate",
    "chunk_candidates",
    "entries_fingerprint",
    "WorkloadMix",
    "mine_ledger",
    "drift_report",
    "OnlineTuner",
    "OnlineTuneConfig",
]


def __getattr__(name):
    # autotuner/workloads import profiling + engines; keep `import
    # wam_tpu.tune` light for the resolve_sample_chunk hot path.
    if name in ("autotune", "Candidate", "chunk_candidates", "measure_candidate"):
        from wam_tpu.tune import autotuner

        return getattr(autotuner, name)
    if name in ("get_workload", "WORKLOADS"):
        from wam_tpu.tune import workloads

        return getattr(workloads, name)
    if name in ("WorkloadMix", "BucketObservation", "mine_ledger",
                "mine_rows", "drift_report"):
        from wam_tpu.tune import mix

        return getattr(mix, name)
    if name in ("OnlineTuner", "OnlineTuneConfig", "plan_serve_schedule",
                "canary_verdict"):
        from wam_tpu.tune import online

        return getattr(online, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
