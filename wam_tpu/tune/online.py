"""Online schedule learning: the ledger-mined shadow tuner (round 19).

The autotuner sweeps canned presets offline while the serve ledger records
the exact reward signal a tuner needs — per-bucket service times,
occupancy, queue pressure, QoS counts, schedule fingerprints — for every
dispatched batch. This module closes that loop (ROADMAP item 5) as a
champion/challenger pipeline:

1. **Mine** the JSONL ledger into a `WorkloadMix` (`wam_tpu.tune.mix`,
   tolerant readers) — the observed bucket × qos histogram.
2. **Detect drift**: score per-bucket observed service against the tuned
   prediction (`mix.drift_report`, two-sided). Drifted buckets publish the
   ``wam_tpu_tune_drift_ratio`` gauge and a ``schedule_drift`` v2 ledger
   row, and trigger step 3.
3. **Shadow sweep**: re-run the `Candidate` sweep against the observed
   distribution (the ``wamlive`` preset synthesized from the mix) plus a
   serve-plane schedule proposal (`plan_serve_schedule`: grow/shrink the
   admission ``bucket_cap`` from observed occupancy + queue pressure).
   The result is a CHALLENGER schedule table — written to its own file,
   fingerprinted with the exact serving digest (`entries_fingerprint`),
   never installed into the live table yet.
4. **Canary A/B**: the fleet pins one replica to the challenger
   (`FleetServer.pin_canary`), the batch-QoS lane prefers it, and
   ``serve_batch`` rows carry each replica's schedule fingerprint, so
   `canary_verdict` can compare champion vs challenger per-item service
   from the ledger alone.
5. **Promote**: on a clear win (mean per-item service improved by at least
   ``promote_margin`` over ``canary_min_batches`` batches on BOTH arms),
   install the challenger entries into the live table, publish them as a
   registry bundle (`registry.publish_bundle`) every worker adopts on next
   hydration, and record the flip as a ``schedule_promotion`` v2 row.

``python -m wam_tpu.tune.online --once`` runs one mine→drift→sweep pass
against a ledger (the CI smoke; exit 1 when the ledger yields no mix);
without ``--once`` it loops on ``--interval-s``. `WAM_TPU_NO_ONLINE_TUNE`
is the kill switch: every entry point becomes a no-op that reports
``{"disabled": true}``, so an operator can freeze schedule churn
fleet-wide without redeploying.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

from wam_tpu.obs.registry import registry as _obs_registry
from wam_tpu.tune.mix import (
    DEFAULT_DRIFT_THRESHOLD,
    MIN_DRIFT_BATCHES,
    WorkloadMix,
    drift_report,
    mine_ledger,
)

__all__ = [
    "ONLINE_TUNE_ENV",
    "online_tune_disabled",
    "OnlineTuneConfig",
    "OnlineTuner",
    "plan_serve_schedule",
    "canary_verdict",
    "main",
]

# kill switch: freeze all online schedule churn (mining still works — it
# is read-only — but drift rows, sweeps, and promotions are suppressed)
ONLINE_TUNE_ENV = "WAM_TPU_NO_ONLINE_TUNE"

_g_drift = _obs_registry.gauge(
    "wam_tpu_tune_drift_ratio",
    "observed/predicted per-item service ratio per bucket (1.0 = on "
    "prediction; outside [1/θ, θ] raises the drift alarm)",
    labels=("bucket",))
_c_sweeps = _obs_registry.counter(
    "wam_tpu_tune_sweeps_total", "shadow sweeps run by the online tuner")
_c_promotions = _obs_registry.counter(
    "wam_tpu_tune_promotions_total",
    "challenger schedules promoted to champion")

# v2 ledger rows share the serve schema version
from wam_tpu.serve.metrics import SCHEMA_VERSION  # noqa: E402


def online_tune_disabled() -> bool:
    return os.environ.get(ONLINE_TUNE_ENV, "") not in ("", "0")


@dataclasses.dataclass
class OnlineTuneConfig:
    """One shadow-tuner pass, fully file-driven (testable without a fleet).

    ``ledger`` is the serve JSONL to mine; ``out_ledger`` receives the
    tuner's own ``schedule_drift`` / ``schedule_promotion`` rows (defaults
    to the input ledger — the tuner annotates the stream it reads)."""

    ledger: str
    out_ledger: str | None = None
    window_s: float | None = None
    drift_threshold: float = DEFAULT_DRIFT_THRESHOLD
    min_batches: int = MIN_DRIFT_BATCHES
    force_sweep: bool = False  # sweep even without a drift alarm
    n_samples: int = 8
    sweep_k: int = 2
    sweep_laps: int = 1
    promote_margin: float = 0.05  # challenger must win by ≥ 5%
    canary_min_batches: int = 8  # per arm, before a verdict counts
    max_cap: int = 32  # bucket_cap growth ceiling (plan_serve_schedule)
    default_cap: int = 8  # the fleet's preset cap when no entry resolves
    replicas: int = 1  # fleet width the serve entries are keyed under
    challenger_path: str | None = None  # default: <ledger>.challenger.json
    bundle_dir: str | None = None  # publish target; None = no bundle
    # AOT keys to ship in the promotion bundle; None publishes every local
    # AOT entry, [] publishes a schedules-only bundle (the common case — a
    # promotion changes admission caps and sweep winners, not kernels)
    bundle_aot_keys: list | None = None


def plan_serve_schedule(mix: WorkloadMix, *, current_cap: int | None = None,
                        max_cap: int = 32, default_cap: int = 8,
                        replicas: int = 1) -> dict:
    """Admission-plane proposal from observed occupancy + queue pressure:
    per dominant bucket, a ``{"bucket_cap": N}`` entry keyed the way the
    serve path resolves it (workload "serve", the bucket's item shape,
    batch=``replicas`` — `resolve_bucket_cap` keys the cap by fleet width,
    so a challenger tuned against a 2-replica fleet only steers 2-replica
    fleets). Saturated buckets
    (mean occupancy ≥ 0.85 with standing queue) double the cap toward
    ``max_cap``; cold ones (occupancy < 0.35) halve back toward
    ``default_cap``; in between keeps the current cap. ``current_cap``
    None resolves each bucket's LIVE tuned cap (the table the challenger
    would replace), so growth is relative to what is actually serving.
    Returns {bucket_key: (shape, entry)} — the sweep merges these into
    the challenger table."""
    from wam_tpu.tune.cache import resolve_bucket_cap

    out: dict[str, tuple] = {}
    for b in mix.dominant(3):
        if not b.occupancies:
            continue
        occ = sum(b.occupancies) / len(b.occupancies)
        queue = (sum(b.queue_depths) / len(b.queue_depths)
                 if b.queue_depths else 0.0)
        cap = (int(current_cap) if current_cap is not None
               else resolve_bucket_cap("auto", b.shape, replicas=replicas,
                                       default=default_cap))
        if occ >= 0.85 and queue > 0.5:
            cap = min(int(max_cap), cap * 2)
        elif occ < 0.35 and cap > default_cap:
            cap = max(default_cap, cap // 2)
        out[b.key] = (b.shape, replicas, {
            "bucket_cap": cap,
            "occupancy_mean": round(occ, 3),
            "queue_depth_mean": round(queue, 2),
            "source": "online:plan_serve_schedule",
        })
    return out


def canary_verdict(rows: list, champion_fp: str, challenger_fp: str, *,
                   margin: float = 0.05, min_batches: int = 8,
                   since: float | None = None) -> dict:
    """Champion-vs-challenger comparison from fingerprint-stamped
    ``serve_batch`` rows alone (satellite 1 is what makes this possible).
    Pure: no fleet handle, no clock — testable from a synthetic ledger.

    ``since`` drops rows stamped before the canary window opened: the
    champion fingerprint also stamps every PRE-canary row, and a window
    that opened after a mix shift must not let the champion arm coast on
    its light-era history.

    The challenger **wins** when both arms have ≥ ``min_batches`` batches
    and its mean per-item service is at least ``margin`` below the
    champion's. ``insufficient`` (not a loss) until both arms qualify."""
    arms: dict[str, list] = {champion_fp: [], challenger_fp: []}
    for r in rows:
        if r.get("metric") != "serve_batch" or not r.get("n_real"):
            continue
        if since is not None and float(r.get("timestamp", 0.0)) < since:
            continue
        fp = r.get("schedule_fingerprint")
        if fp in arms:
            arms[fp].append(float(r.get("service_s", 0.0))
                            / max(1, int(r["n_real"])))
    champ, chall = arms[champion_fp], arms[challenger_fp]
    out = {
        "champion_fp": champion_fp,
        "challenger_fp": challenger_fp,
        "champion_batches": len(champ),
        "challenger_batches": len(chall),
        "margin": margin,
    }
    if len(champ) < min_batches or len(chall) < min_batches:
        out.update(verdict="insufficient", win=False)
        return out
    champ_s = sum(champ) / len(champ)
    chall_s = sum(chall) / len(chall)
    win = chall_s <= champ_s * (1.0 - margin)
    out.update(
        champion_per_item_s=champ_s,
        challenger_per_item_s=chall_s,
        improvement=(champ_s - chall_s) / champ_s if champ_s > 0 else 0.0,
        verdict="challenger" if win else "champion",
        win=win,
    )
    return out


class OnlineTuner:
    """The composable shadow tuner: ``mine`` → ``detect_drift`` →
    ``sweep`` → (external canary window) → ``promote``. ``step`` wires the
    whole pass for the CLI loop; the pieces stay separately callable so the
    bench harness can interleave its own canary phase between sweep and
    promote."""

    def __init__(self, config: OnlineTuneConfig, *, log=None):
        self.config = config
        self.log = log or (lambda s: None)
        self._writer = None

    # -- ledger output -----------------------------------------------------

    def _write_row(self, row: dict) -> None:
        from wam_tpu.results import JsonlWriter

        path = self.config.out_ledger or self.config.ledger
        if self._writer is None or self._writer.path != path:
            self._writer = JsonlWriter(path)
        self._writer.write(row)

    # -- pipeline stages ---------------------------------------------------

    def mine(self) -> WorkloadMix | None:
        mix = mine_ledger(self.config.ledger, window_s=self.config.window_s)
        if mix is None:
            self.log(f"mine: no serve_batch rows in {self.config.ledger}")
        else:
            self.log(f"mine: {mix.rows} batches / {mix.total_items} items "
                     f"across {len(mix.buckets)} buckets "
                     f"({mix.corrupt_lines} corrupt lines skipped)")
        return mix

    def predictions(self, mix: WorkloadMix) -> dict:
        """Tuned per-item service predictions per observed bucket: the
        serve-key entry's measured ``median_s / items`` when a sweep
        recorded one. Buckets without a prediction drift against their own
        early window (mix.drift_report's self-baseline)."""
        from wam_tpu.tune.cache import load_schedule_cache, schedule_key

        cache = load_schedule_cache()
        out: dict[str, float] = {}
        for key, b in mix.buckets.items():
            try:
                skey = schedule_key("serve", b.shape, self.config.replicas)
            except Exception:
                continue
            ent = cache.get(skey)
            if ent and ent.get("median_s") and ent.get("items"):
                out[key] = float(ent["median_s"]) / max(1, int(ent["items"]))
        return out

    def detect_drift(self, mix: WorkloadMix) -> dict:
        """Drift pass: gauge per bucket always; ``schedule_drift`` ledger
        rows only for buckets that actually drifted (and only when the
        kill switch is off — alarms are schedule churn too)."""
        report = drift_report(mix, threshold=self.config.drift_threshold,
                              predictions=self.predictions(mix),
                              min_batches=self.config.min_batches)
        for key, b in report["buckets"].items():
            _g_drift.set(b["ratio"], bucket=key)
        if online_tune_disabled():
            return report
        for key in report["drifted"]:
            b = report["buckets"][key]
            self._write_row({
                "metric": "schedule_drift",
                "schema_version": SCHEMA_VERSION,
                "bucket": key,
                "ratio": round(b["ratio"], 4),
                "observed_s": round(b["observed_s"], 6),
                "baseline_s": round(b["baseline_s"], 6),
                "baseline_source": b["source"],
                "threshold": self.config.drift_threshold,
                "batches": b["batches"],
                "timestamp": time.time(),
            })
            self.log(f"drift: bucket {key} ratio {b['ratio']:.2f} "
                     f"(baseline {b['source']})")
        return report

    def sweep(self, mix: WorkloadMix) -> dict:
        """Shadow sweep → challenger table ON DISK (never the live table):
        the wamlive `Candidate` sweep at the observed geometry plus the
        `plan_serve_schedule` admission entries, merged OVER a copy of the
        live entries so the challenger fingerprint reflects the table a
        promotion would produce. Returns {"path", "fingerprint", "keys",
        "entries", "sweep"}."""
        from wam_tpu.tune.autotuner import autotune
        from wam_tpu.tune.cache import (
            ScheduleCache,
            entries_fingerprint,
            schedule_key,
        )
        from wam_tpu.tune.workloads import get_workload

        _c_sweeps.inc()
        wl = get_workload("wamlive", mix=mix, n_samples=self.config.n_samples)
        self.log(f"sweep: wamlive over {len(wl.candidates)} candidates "
                 f"(shape {wl.shape}, batch {wl.batch})")
        res = autotune(wl, k=self.config.sweep_k, laps=self.config.sweep_laps,
                       persist=False, log=self.log)
        challenger: dict[str, dict] = {res["key"]: res["entry"]}
        plan = plan_serve_schedule(mix, max_cap=self.config.max_cap,
                                   default_cap=self.config.default_cap,
                                   replicas=self.config.replicas)
        for _bkey, (shape, replicas, entry) in sorted(plan.items()):
            challenger[schedule_key("serve", shape, replicas)] = entry
        # challenger table = live entries (pinned + user layers) +
        # challenger overrides, so its fingerprint is EXACTLY what
        # schedule_fingerprint() will return after a promotion installs
        # the same overrides
        merged = dict(ScheduleCache().entries)
        merged.update(challenger)
        fp = entries_fingerprint(merged)
        path = (self.config.challenger_path
                or f"{self.config.ledger}.challenger.json")
        out = ScheduleCache(path=path, pinned=True)
        out.entries.update(challenger)
        out.save(path)
        self.log(f"sweep: challenger {fp} -> {path} "
                 f"({len(challenger)} retuned keys)")
        return {"path": path, "fingerprint": fp,
                "keys": sorted(challenger), "entries": challenger,
                "sweep": {"key": res["key"],
                          "winner": res["winner"]["label"],
                          "items_per_s": round(res["winner"]["items_per_s"], 3),
                          "plane": res["winner"]["plane"]}}

    def promote(self, challenger: dict, verdict: dict) -> dict:
        """Install the winning challenger entries into the live user table,
        publish the bundle (schedules + current AOT entries, XLA payloads
        skipped — schedule flips don't invalidate compiled code), and
        record the flip as a ``schedule_promotion`` v2 row."""
        from wam_tpu.tune.cache import (
            invalidate_process_cache,
            load_schedule_cache,
            schedule_fingerprint,
        )

        cache = load_schedule_cache()
        for key, entry in challenger["entries"].items():
            cache.put(key, entry)
        cache.save()
        invalidate_process_cache()
        live_fp = schedule_fingerprint()
        bundle = None
        if self.config.bundle_dir:
            from wam_tpu.registry.bundle import publish_bundle

            manifest = publish_bundle(
                self.config.bundle_dir,
                keys=self.config.bundle_aot_keys,
                include_xla=False,
                source={"publisher": "tune.online",
                        "challenger_fingerprint": challenger["fingerprint"],
                        "verdict": verdict.get("verdict")},
            )
            bundle = {"dir": self.config.bundle_dir,
                      "artifacts": len(manifest["artifacts"])}
            self.log(f"promote: bundle -> {self.config.bundle_dir} "
                     f"({bundle['artifacts']} artifacts)")
        _c_promotions.inc()
        row = {
            "metric": "schedule_promotion",
            "schema_version": SCHEMA_VERSION,
            "champion_fp": verdict.get("champion_fp"),
            "challenger_fp": challenger["fingerprint"],
            "live_fp": live_fp,
            "keys": challenger["keys"],
            "improvement": round(float(verdict.get("improvement", 0.0)), 4),
            "champion_batches": verdict.get("champion_batches"),
            "challenger_batches": verdict.get("challenger_batches"),
            "bundle": (self.config.bundle_dir if bundle else None),
            "timestamp": time.time(),
        }
        self._write_row(row)
        self.log(f"promote: {challenger['fingerprint']} is champion "
                 f"(+{row['improvement'] * 100:.1f}%)")
        return {"live_fingerprint": live_fp, "bundle": bundle, "row": row}

    # -- one full pass -----------------------------------------------------

    def step(self) -> dict:
        """One mine→drift→sweep pass (the ``--once`` body). The canary
        verdict needs fingerprint-stamped traffic that only exists after a
        fleet serves WITH the challenger pinned, so ``step`` ends at the
        challenger table + drift report; the serving harness (bench
        ``--online-tune`` or the fleet loop) runs the canary window and
        calls ``promote`` with its `canary_verdict`."""
        if online_tune_disabled():
            self.log(f"online tuning disabled ({ONLINE_TUNE_ENV}=1)")
            return {"disabled": True}
        mix = self.mine()
        if mix is None:
            return {"mix": None}
        report = self.detect_drift(mix)
        out: dict = {"mix": mix.to_dict(), "drift": report}
        if report["drifted"] or self.config.force_sweep:
            out["challenger"] = self.sweep(mix)
        else:
            self.log("sweep: skipped (no drift; pass --force-sweep to "
                     "override)")
        return out


def main(argv=None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m wam_tpu.tune.online",
        description="Ledger-mined shadow tuner: mine the serve ledger, "
                    "raise drift alarms, sweep a challenger schedule.",
    )
    p.add_argument("--ledger", required=True,
                   help="serve JSONL ledger to mine")
    p.add_argument("--once", action="store_true",
                   help="one pass then exit (CI smoke); exit 1 on no mix")
    p.add_argument("--interval-s", type=float, default=300.0,
                   help="loop period without --once")
    p.add_argument("--window-s", type=float, default=None,
                   help="mine only the trailing window (ledger clock)")
    p.add_argument("--device", default="cpu",
                   help="backend for the shadow sweep: auto | tpu | cpu")
    p.add_argument("--drift-threshold", type=float,
                   default=DEFAULT_DRIFT_THRESHOLD)
    p.add_argument("--force-sweep", action="store_true",
                   help="sweep even when no bucket drifted")
    p.add_argument("--challenger", default=None,
                   help="challenger schedule file "
                        "(default <ledger>.challenger.json)")
    p.add_argument("--bundle-dir", default=None,
                   help="publish promotions as a registry bundle here")
    p.add_argument("--out-ledger", default=None,
                   help="where drift/promotion rows go (default: the "
                        "input ledger)")
    p.add_argument("--replicas", type=int, default=1,
                   help="fleet width the challenger serve entries are "
                        "keyed under (resolve_bucket_cap keys by it)")
    p.add_argument("--n-samples", type=int, default=8,
                   help="smoothgrad samples per wamlive body")
    p.add_argument("--k", type=int, default=2, help="samples per candidate")
    p.add_argument("--laps", type=int, default=1,
                   help="calls per timed region")
    args = p.parse_args(argv)

    from wam_tpu.config import (
        enable_compilation_cache,
        ensure_usable_backend,
        select_backend,
    )

    # backend pinned BEFORE first jax use (the axon TPU plugin ignores a
    # late JAX_PLATFORMS env alone) — same rule as the autotuner CLI
    select_backend(args.device)
    if args.device in ("auto", "tpu"):
        ensure_usable_backend(timeout_s=180.0)
    enable_compilation_cache()

    cfg = OnlineTuneConfig(
        ledger=args.ledger,
        out_ledger=args.out_ledger,
        window_s=args.window_s,
        drift_threshold=args.drift_threshold,
        force_sweep=args.force_sweep,
        n_samples=args.n_samples,
        sweep_k=args.k,
        sweep_laps=args.laps,
        replicas=args.replicas,
        challenger_path=args.challenger,
        bundle_dir=args.bundle_dir,
    )
    tuner = OnlineTuner(cfg, log=lambda s: print(s, file=sys.stderr))
    while True:
        out = tuner.step()
        print(json.dumps(out))
        if args.once:
            return 0 if (out.get("disabled") or out.get("mix")) else 1
        time.sleep(args.interval_s)


if __name__ == "__main__":
    import sys

    sys.exit(main())
