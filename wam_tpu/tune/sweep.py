"""Chunk-size sweep for the non-flagship canonical workloads (audio 1D,
3D volumes, ViT IG) — `scripts/sweep_chunks.py` folded into the tune
package (that script is now a deprecation shim onto this module).

Uses the SAME workload builders as bench_matrix.py (bench_workloads.py at
the repo root), so a sweep measures exactly the benchmarked config, and the
same measurement protocol as the autotuner (`measure_candidate`: device
xplane medians on TPU, wall medians elsewhere — the plane is printed).
Prints one JSON line per (workload, chunk).

    python -m wam_tpu.tune.sweep audio 4 8 25 50
    python -m wam_tpu.tune.sweep vol 5 25
    python -m wam_tpu.tune.sweep vit 4 8 16
"""

from __future__ import annotations

import json
import os
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        sys.exit("usage: python -m wam_tpu.tune.sweep {audio|vol|vit} [chunk ...]")
    kind = argv[0]
    chunks = [int(c) for c in argv[1:]] or [None]

    from wam_tpu.config import enable_compilation_cache, ensure_usable_backend

    platform = ensure_usable_backend(timeout_s=180.0)
    enable_compilation_cache()

    import jax.numpy as jnp

    try:
        from bench_workloads import audio_workload, vit_workload, vol_workload
    except ImportError:
        # bench_workloads.py lives at the repo root, next to bench_matrix.py
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        from bench_workloads import audio_workload, vit_workload, vol_workload

    from wam_tpu.tune.autotuner import measure_candidate
    from wam_tpu.profiling import median_iqr

    for chunk in chunks:
        if kind == "audio":
            ex, x, y = audio_workload(chunk)
        elif kind == "vol":
            ex, x, y = vol_workload(chunk)
        elif kind == "vit":
            ex, x, y = vit_workload(chunk, compute_dtype=jnp.bfloat16)
        else:
            sys.exit(f"unknown workload {kind!r}")

        samples, plane = measure_candidate(lambda x, y: ex(x, y), (x, y),
                                           k=3, laps=4)
        med, q1, q3, _ = median_iqr(samples)
        print(json.dumps({
            "platform": platform, "workload": kind, "chunk": chunk,
            "step_s": round(med, 4), "q1_s": round(q1, 4),
            "q3_s": round(q3, 4), "plane": plane,
            "items_per_s": round(x.shape[0] / med, 2),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
