"""Versioned schedule cache — where autotuned schedules live.

Round 5 proved the flagship step runs at 29.7% of its traffic floor with the
schedule as the gap (VERDICT.md r5 weak #1); round 3 fit the 128-row chunk
"law" by hand at one geometry. This module replaces the single
`_AUTO_TARGET_ROWS` constant with a keyed, persisted schedule table:

- **Key**: one canonical string per
  (workload, input shape, batch, dtype, dwt impl, backend) — the axes the
  round-3/5 studies showed change the optimum.
- **Entry**: the winning knobs (``sample_chunk``, ``stream_noise``,
  ``dwt_impl``, ``layout``, ``fan_cap``) plus the measurement that crowned
  them (median seconds, items/s, measurement plane) so a future re-tune can
  tell whether it actually improved anything.
- **Two layers**: repo-pinned defaults (``default_schedules.json`` next to
  this file — the schedules measured in BASELINE.md, shipped so the class
  API delivers the recorded numbers out of the box) overlaid by the user
  cache (``$WAM_TPU_SCHEDULE_CACHE`` or ``~/.cache/wam_tpu/schedules.json``)
  where `wam_tpu.tune.autotune` persists winners. User entries win.
- **Versioning**: files carry ``version``; a file with a different version
  is IGNORED wholesale (stale-schema entries must not steer the schedule)
  and overwritten on the next `save()`.

Resolution (`core.estimators.resolve_sample_chunk`, the engines'
``sample_batch_size="auto"``) consults `lookup_schedule` first and falls
back to the 128-row law when no entry matches, so behavior without a cache
file is exactly the round-5 build.

Set ``WAM_TPU_NO_SCHEDULE_CACHE=1`` to disable all lookups (the law only) —
the A/B kill switch every schedule experiment needs.
"""

from __future__ import annotations

import json
import os
import threading

__all__ = [
    "SCHEDULE_CACHE_VERSION",
    "schedule_key",
    "default_cache_path",
    "ScheduleCache",
    "load_schedule_cache",
    "lookup_schedule",
    "record_schedule",
    "resolve_fan_cap",
    "resolve_bucket_cap",
    "apply_tuned_synth_impl",
    "invalidate_process_cache",
    "entries_fingerprint",
]

SCHEDULE_CACHE_VERSION = 1

_lock = threading.Lock()
_process_cache: "ScheduleCache | None" = None


def default_cache_path() -> str:
    """$WAM_TPU_SCHEDULE_CACHE or ~/.cache/wam_tpu/schedules.json (sibling
    of the XLA compilation cache — `config.enable_compilation_cache`)."""
    return os.environ.get(
        "WAM_TPU_SCHEDULE_CACHE",
        os.path.expanduser("~/.cache/wam_tpu/schedules.json"),
    )


def _pinned_path() -> str:
    return os.path.join(os.path.dirname(__file__), "default_schedules.json")


def schedule_key(
    workload: str,
    shape,
    batch: int,
    dtype: str = "f32",
    dwt_impl: str | None = None,
    backend: str | None = None,
) -> str:
    """Canonical cache key. ``shape`` is the per-item shape (no batch axis);
    ``dtype`` is the DWT-boundary dtype label ("f32"/"bf16"); ``dwt_impl``
    defaults to the RESOLVED current 2D impl (auto → pallas/conv) so a key
    built under impl="auto" matches the impl that actually runs; ``backend``
    defaults to the live `jax.default_backend()`."""
    if dwt_impl is None or backend is None:
        import jax

        if backend is None:
            backend = jax.default_backend()
        if dwt_impl is None:
            from wam_tpu.wavelets import transform as wt

            dwt_impl = wt._resolved_dwt2_impl()
    shape_s = "x".join(str(int(d)) for d in shape) if shape else "-"
    return f"{workload}|{shape_s}|b{int(batch)}|{dtype}|{dwt_impl}|{backend}"


class ScheduleCache:
    """Pinned-defaults + user-file schedule table (see module docstring)."""

    def __init__(self, path: str | None = None, pinned: bool = True):
        self.path = path or default_cache_path()
        self.entries: dict[str, dict] = {}
        self.stale_files: list[str] = []
        if pinned:
            self._merge_file(_pinned_path())
        self._merge_file(self.path)

    def _merge_file(self, path: str) -> None:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(data, dict) or data.get("version") != SCHEDULE_CACHE_VERSION:
            # stale schema: ignore every entry rather than half-apply it
            self.stale_files.append(path)
            return
        schedules = data.get("schedules", {})
        if isinstance(schedules, dict):
            for k, v in schedules.items():
                if isinstance(v, dict):
                    self.entries[k] = v

    def get(self, key: str) -> dict | None:
        return self.entries.get(key)

    def put(self, key: str, entry: dict) -> None:
        self.entries[key] = dict(entry)
        # a tuned entry changes the table identity: drop the memoized
        # result-cache fingerprint (schedule_fingerprint) so cached
        # attributions computed under the old table stop matching
        self._fingerprint = None

    def save(self, path: str | None = None) -> str:
        """Write the USER layer (every current entry that is not a pinned
        default, plus any tuned overrides of pinned keys) atomically."""
        path = path or self.path
        pinned = ScheduleCache(path=os.devnull, pinned=True).entries
        user = {k: v for k, v in self.entries.items() if pinned.get(k) != v}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        payload = {"version": SCHEDULE_CACHE_VERSION, "schedules": user}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path


def load_schedule_cache(refresh: bool = False) -> ScheduleCache:
    """Process-global cache, loaded once (file IO happens at first "auto"
    resolution or at serve/prewarm warmup, never per trace)."""
    global _process_cache
    with _lock:
        if _process_cache is None or refresh:
            _process_cache = ScheduleCache()
        return _process_cache


def invalidate_process_cache() -> None:
    """Drop the singleton (tests; after an external process wrote the file)."""
    global _process_cache
    with _lock:
        _process_cache = None


def entries_fingerprint(entries: dict, *, disabled: bool = False) -> str:
    """Digest of a schedule table body — the shared hash behind
    `schedule_fingerprint`, exported so the online tuner can fingerprint a
    CHALLENGER table (its candidate entries merged over the live ones)
    exactly the way the serving fingerprint would come out AFTER a
    promotion. Identical entries ⇒ identical digest, which is what lets
    the canary A/B match ``serve_batch`` rows back to the schedule that
    produced them."""
    import hashlib

    body = json.dumps(
        {"version": SCHEDULE_CACHE_VERSION, "disabled": disabled,
         "schedules": entries},
        sort_keys=True, default=str)
    return hashlib.sha256(body.encode()).hexdigest()[:16]


def schedule_fingerprint() -> str:
    """Digest of the loaded schedule table (entries + schema version) — the
    "schedule version" component of serve result-cache keys
    (`serve.result_cache`). Tuned schedules change the sampling chunking,
    which changes SmoothGrad noise realizations, so a cached attribution is
    only valid against the exact table it was computed under. Memoized on
    the `ScheduleCache` instance: `invalidate_process_cache` (or a
    `refresh=True` reload) naturally drops the memo with the instance."""
    cache = load_schedule_cache()
    # _disabled() is part of the identity (with lookups killed the entries
    # serve under the fallback law, not the table), so the memo is keyed
    # by the flag rather than assuming it is constant for the process
    disabled = _disabled()
    memo = getattr(cache, "_fingerprint", None)
    if memo is not None and memo[0] == disabled:
        return memo[1]
    fp = entries_fingerprint(cache.entries, disabled=disabled)
    cache._fingerprint = (disabled, fp)
    return fp


def _disabled() -> bool:
    return os.environ.get("WAM_TPU_NO_SCHEDULE_CACHE", "") not in ("", "0")


def lookup_schedule(
    workload: str,
    shape,
    batch: int,
    dtype: str = "f32",
    dwt_impl: str | None = None,
    backend: str | None = None,
) -> dict | None:
    """Entry for the key, or None (→ caller falls back to the 128-row law)."""
    if _disabled():
        return None
    key = schedule_key(workload, shape, batch, dtype, dwt_impl, backend)
    return load_schedule_cache().get(key)


def record_schedule(
    workload: str,
    shape,
    batch: int,
    entry: dict,
    dtype: str = "f32",
    dwt_impl: str | None = None,
    backend: str | None = None,
    persist: bool = True,
) -> str:
    """Install (and by default persist) a tuned entry; returns the key."""
    key = schedule_key(workload, shape, batch, dtype, dwt_impl, backend)
    cache = load_schedule_cache()
    cache.put(key, entry)
    if persist:
        cache.save()
    return key


def apply_tuned_synth_impl(
    workload: str,
    shape,
    batch: int,
    dtype: str = "f32",
) -> str | None:
    """Apply the tuned ``synth_impl`` for this schedule key (if any) via
    `set_synth2_impl`, and return it. No entry / no synth field → None and
    the process-global knob is left alone (whatever the user set, default
    "auto"). Engines call this at TRACE time, right before the first
    reconstruction, so an AOT-cached executable bakes in the tuned synthesis
    path exactly like the tuned chunk/stream knobs."""
    ent = lookup_schedule(workload, shape, batch, dtype)
    impl = ent.get("synth_impl") if ent else None
    if impl:
        from wam_tpu.wavelets.transform import set_synth2_impl

        set_synth2_impl(impl)
        return impl
    return None


def resolve_fan_cap(batch_size, fan: int, *, workload: str = "eval2d",
                    shape=None, default: int = 128) -> int:
    """Evaluation fan-chunk cap: explicit ints pass through; "auto" consults
    the tuned ``fan_cap`` for (workload, fan) and falls back to ``default``
    (the EvalConfig.batch_size the rounds 1-5 numbers were recorded at).

    The same entry may carry a tuned ``fan_chunk`` (images-per-chunk
    override, the autotuner's `Candidate.fan_chunk` axis); that companion
    knob is resolved by `wam_tpu.evalsuite.fan.plan_fan`, which wraps this
    cap lookup into a full `FanPlan`."""
    if batch_size != "auto":
        return int(batch_size)
    ent = lookup_schedule(workload, shape or (fan,), fan)
    if ent is not None and ent.get("fan_cap"):
        return int(ent["fan_cap"])
    return default


def resolve_bucket_cap(max_batch, shape=None, *, replicas: int = 1,
                       default: int = 8) -> int:
    """Serving bucket cap (`ServeConfig.max_batch`): explicit ints pass
    through; "auto" consults the tuned ``bucket_cap`` for the "serve"
    workload at this bucket shape — keyed by replica count, since the
    fleet's oversize dispatch compiles at ``replicas × cap`` rows and the
    throughput-optimal per-chip cap can shrink as the fleet widens — and
    falls back to ``default`` (the ServeConfig.max_batch every serve number
    so far was recorded at)."""
    if max_batch != "auto":
        return int(max_batch)
    ent = lookup_schedule("serve", shape or (), int(replicas))
    if ent is not None and ent.get("bucket_cap"):
        return int(ent["bucket_cap"])
    return default
