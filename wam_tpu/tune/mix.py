"""Ledger-mined workload mixes + schedule drift detection (round 19).

The obs ledger already records the exact reward signal a tuner needs —
every ``serve_batch`` row carries the bucket, real-row count, occupancy,
queue depth, service time, per-class QoS counts, and (since round 19) the
schedule fingerprint that produced it. This module closes ROADMAP item 5's
first loop: it mines that ledger into a `WorkloadMix` — the OBSERVED
bucket × qos histogram with per-bucket service-time samples — which

- `wam_tpu.tune.workloads` turns into the ``wamlive`` autotune preset
  (a `Candidate` sweep weighted by what the fleet actually served instead
  of a canned geometry), and
- `drift_report` scores against a prediction (the tuned schedule entry's
  measured per-item time, or the window's own earliest batches when no
  prediction exists) to decide whether the live workload has drifted away
  from whatever the current schedule was tuned for. Drift in EITHER
  direction counts: per-item service times rising past ``threshold`` ×
  the baseline mean the schedule is under-provisioned; times falling
  below ``1/threshold`` × mean the mix shifted toward work the schedule
  over-provisions (fuller batches, colder caches). Both are the trigger
  that kicks off a shadow sweep (`wam_tpu.tune.online`).

Reading is tolerant by construction — `results.read_jsonl_stats` skips
torn lines with a counted `LedgerCorruptWarning`, and the corrupt count is
surfaced on the mix so a mostly-torn ledger is visible to operators.
"""

from __future__ import annotations

import dataclasses

from wam_tpu.results import read_jsonl_stats

__all__ = [
    "BucketObservation",
    "WorkloadMix",
    "mine_ledger",
    "mine_rows",
    "drift_report",
    "DEFAULT_DRIFT_THRESHOLD",
    "BASELINE_FRAC",
    "RECENT_FRAC",
]

# two-sided drift gate: a bucket drifts when observed/baseline per-item
# service leaves [1/threshold, threshold]
DEFAULT_DRIFT_THRESHOLD = 1.5

# self-baseline split when no tuned prediction exists: the earliest this
# fraction of a bucket's batches (by timestamp) is "what the schedule was
# tuned for"
BASELINE_FRAC = 0.25

# the observation the baseline is scored against: the LATEST this fraction
# of the bucket's batches. Comparing head against tail (not head against
# everything-after-head) keeps a recent shift visible even when most of
# the window predates it — a 70%-light/30%-heavy window must read as
# "drifted heavy", not as a mildly-worse average.
RECENT_FRAC = 0.25

# below this many batches a bucket carries no drift signal (a ratio of
# two 2-batch means is noise, not evidence)
MIN_DRIFT_BATCHES = 6


@dataclasses.dataclass
class BucketObservation:
    """One bucket's observed traffic over the mined window."""

    key: str
    shape: tuple
    model_id: str | None = None  # paged-model identity, None = default entry
    batches: int = 0
    items: int = 0  # total real rows served
    per_item_s: list = dataclasses.field(default_factory=list)
    timestamps: list = dataclasses.field(default_factory=list)
    occupancies: list = dataclasses.field(default_factory=list)
    queue_depths: list = dataclasses.field(default_factory=list)
    qos: dict = dataclasses.field(default_factory=dict)
    fingerprints: dict = dataclasses.field(default_factory=dict)

    @property
    def mean_per_item_s(self) -> float:
        if not self.per_item_s:
            return 0.0
        return sum(self.per_item_s) / len(self.per_item_s)

    @property
    def mean_batch(self) -> float:
        """Mean real rows per dispatched batch (the wamlive batch size)."""
        return self.items / self.batches if self.batches else 0.0


@dataclasses.dataclass
class WorkloadMix:
    """The observed workload distribution mined from a serve ledger."""

    source: str
    rows: int  # serve_batch rows inside the window
    corrupt_lines: int
    window: tuple  # (earliest, latest) row timestamp
    buckets: dict  # bucket key -> BucketObservation
    qos: dict  # class -> items (aggregate across buckets)
    fingerprints: dict  # schedule fingerprint -> batches observed under it
    # tenant -> items (aggregate), mined from round-20 per-batch tenant
    # counts; empty for pre-round-20 ledgers (the field is absent there)
    tenants: dict = dataclasses.field(default_factory=dict)

    @property
    def total_items(self) -> int:
        return sum(b.items for b in self.buckets.values())

    def weights(self) -> dict:
        """Items-proportional bucket weights (sum to 1.0)."""
        total = self.total_items
        if total <= 0:
            return {k: 0.0 for k in self.buckets}
        return {k: b.items / total for k, b in self.buckets.items()}

    def dominant(self, n: int = 3) -> list:
        """The ``n`` heaviest buckets by served items (stable key order on
        ties — the wamlive preset must be deterministic for a given mix)."""
        ranked = sorted(self.buckets.values(),
                        key=lambda b: (-b.items, b.key))
        return ranked[:n]

    def to_dict(self) -> dict:
        """JSON-friendly report body (the online tuner's ``mix`` block)."""
        return {
            "source": self.source,
            "rows": self.rows,
            "corrupt_lines": self.corrupt_lines,
            "window_s": (self.window[1] - self.window[0]) if self.rows else 0.0,
            "total_items": self.total_items,
            "qos": dict(self.qos),
            "fingerprints": dict(self.fingerprints),
            "tenants": dict(self.tenants),
            "buckets": {
                k: {
                    "batches": b.batches,
                    "items": b.items,
                    "weight": round(w, 4),
                    "mean_per_item_s": round(b.mean_per_item_s, 6),
                    "mean_batch": round(b.mean_batch, 2),
                    "qos": dict(b.qos),
                    **({"model_id": b.model_id} if b.model_id else {}),
                }
                for (k, b), w in zip(sorted(self.buckets.items()),
                                     (self.weights()[k]
                                      for k in sorted(self.buckets)))
            },
        }


def mine_rows(rows: list, *, source: str = "<rows>", corrupt: int = 0,
              window_s: float | None = None) -> WorkloadMix | None:
    """Build a `WorkloadMix` from already-parsed ledger rows. Only
    ``serve_batch`` rows count; with ``window_s`` the window is anchored at
    the LATEST row's timestamp (the ledger's own clock — mining an old
    ledger must see the same window a live miner saw). Returns None when
    the window holds no batches (an empty mix steers nothing)."""
    batches = [r for r in rows if r.get("metric") == "serve_batch"
               and r.get("timestamp") is not None and r.get("n_real")]
    if not batches:
        return None
    latest = max(r["timestamp"] for r in batches)
    if window_s is not None:
        batches = [r for r in batches if r["timestamp"] >= latest - window_s]
    earliest = min(r["timestamp"] for r in batches)
    buckets: dict[str, BucketObservation] = {}
    qos_total: dict[str, int] = {}
    fingerprints: dict[str, int] = {}
    tenants_total: dict[str, int] = {}
    for r in sorted(batches, key=lambda r: r["timestamp"]):
        shape = tuple(int(d) for d in r.get("bucket", ()))
        key = "x".join(str(d) for d in shape) if shape else "-"
        # paged-model batches mine under model-qualified keys (the serve
        # EMA convention, "model|bucket") so one model's service times
        # never pollute another's drift baseline on a shared fleet
        mid = r.get("model_id")
        if mid:
            key = f"{mid}|{key}"
        obs = buckets.get(key)
        if obs is None:
            obs = buckets[key] = BucketObservation(key=key, shape=shape,
                                                   model_id=mid)
        n = int(r["n_real"])
        obs.batches += 1
        obs.items += n
        obs.per_item_s.append(float(r.get("service_s", 0.0)) / max(1, n))
        obs.timestamps.append(float(r["timestamp"]))
        obs.occupancies.append(float(r.get("occupancy",
                                           r.get("fill_ratio", 0.0))))
        obs.queue_depths.append(float(r.get("queue_depth", 0)))
        for cls, cnt in (r.get("qos") or {}).items():
            obs.qos[cls] = obs.qos.get(cls, 0) + int(cnt)
            qos_total[cls] = qos_total.get(cls, 0) + int(cnt)
        for tenant, cnt in (r.get("tenants") or {}).items():
            tenants_total[tenant] = tenants_total.get(tenant, 0) + int(cnt)
        fp = r.get("schedule_fingerprint")
        if fp:
            fingerprints[fp] = fingerprints.get(fp, 0) + 1
    return WorkloadMix(source=source, rows=len(batches),
                       corrupt_lines=corrupt, window=(earliest, latest),
                       buckets=buckets, qos=qos_total,
                       fingerprints=fingerprints, tenants=tenants_total)


def mine_ledger(path: str, *, window_s: float | None = None) -> WorkloadMix | None:
    """Mine one JSONL serve ledger into a `WorkloadMix` via the tolerant
    reader (torn lines are skipped, counted onto the mix). Returns None
    for a missing/empty ledger or one with no ``serve_batch`` rows."""
    try:
        rows, corrupt = read_jsonl_stats(path)
    except OSError:
        return None
    return mine_rows(rows, source=path, corrupt=corrupt, window_s=window_s)


def drift_report(mix: WorkloadMix, *, threshold: float = DEFAULT_DRIFT_THRESHOLD,
                 predictions: dict | None = None,
                 min_batches: int = MIN_DRIFT_BATCHES) -> dict:
    """Score each bucket's observed per-item service against its
    prediction. The observation is always the trailing `RECENT_FRAC` of
    the bucket's batches — drift is about what the fleet serves NOW.
    ``predictions`` maps bucket key -> predicted per-item seconds (the
    tuned schedule entry's measured ``median_s / items``); buckets
    without one fall back to the self-baseline: the earliest
    `BASELINE_FRAC` of the bucket's own batches. A bucket with fewer than
    ``min_batches`` batches is reported but never drifts (two-batch ratios
    are noise). The report is pure data — the online tuner publishes the
    gauge and the ``schedule_drift`` ledger rows from it."""
    if threshold <= 1.0:
        raise ValueError(f"drift threshold must be > 1.0, got {threshold}")
    out: dict[str, dict] = {}
    drifted: list[str] = []
    for key in sorted(mix.buckets):
        obs = mix.buckets[key]
        pred = (predictions or {}).get(key)
        tail = max(2, int(len(obs.per_item_s) * RECENT_FRAC))
        recent = obs.per_item_s[-tail:]
        if pred is not None and pred > 0:
            baseline = float(pred)
            source = "tuned"
        else:
            split = max(2, int(len(obs.per_item_s) * BASELINE_FRAC))
            base = obs.per_item_s[:split]
            baseline = sum(base) / len(base) if base else 0.0
            source = "self"
            if split >= len(obs.per_item_s):
                # window too small to hold both a head and a tail
                recent = []
        if obs.batches < min_batches or not recent or baseline <= 0:
            out[key] = {"ratio": 1.0, "baseline_s": baseline,
                        "observed_s": obs.mean_per_item_s,
                        "batches": obs.batches, "source": "insufficient",
                        "drifted": False}
            continue
        observed = sum(recent) / len(recent)
        ratio = observed / baseline
        is_drift = ratio > threshold or ratio < 1.0 / threshold
        out[key] = {"ratio": ratio, "baseline_s": baseline,
                    "observed_s": observed, "batches": obs.batches,
                    "source": source, "drifted": is_drift}
        if is_drift:
            drifted.append(key)
    ratios = [b["ratio"] for b in out.values()]
    # the headline ratio is the FARTHEST from 1.0 in log space, so a
    # 0.4x speed-up drift ranks above a 1.6x slow-down drift
    worst = max(ratios, key=lambda r: abs(r - 1.0) + abs(1.0 / max(r, 1e-9) - 1.0),
                default=1.0)
    return {"threshold": threshold, "buckets": out, "drifted": drifted,
            "worst_ratio": worst}
