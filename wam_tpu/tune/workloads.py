"""Autotune workload presets: each builds candidate runners for `autotune`.

A `Workload` bundles the cache-key identity (workload/shape/batch/dtype),
the candidate list, and a ``build(candidate)`` factory returning a freshly
jitted ``(fn, args)`` runner with the candidate's knobs baked in as EXPLICIT
values — the sweep never resolves "auto", so it cannot read the cache entry
it is about to write.

Presets:

- ``toy`` — tiny haar/conv geometry that compiles in seconds on CPU: the
  ``--dry-run`` smoke target (verify skill) and the structural test fixture.
- ``flagship`` — the pinned north-star (ResNet-50, b32, 224², n25, bf16 +
  dwt-bf16, NHWC, fold_bn), mirroring bench.py exactly; sweeps chunks at
  128/256/512 rows + full vmap, stream_noise on/off, and an NCHW layout
  probe.
- ``mu2d`` — the μ-fidelity inner runner at production geometry (grid 28,
  sample 128) sweeping the evaluation fan cap AND the images-per-chunk
  override (`Candidate.fan_chunk`); winner feeds
  `evalsuite.fan.plan_fan("auto")` (VERDICT.md round-5 directive 3 — the
  slowest eval row). Also probes the bf16 fan (`Candidate.fan_dtype`,
  round 17): model params bound bf16, fan inputs cast at the boundary,
  reductions f32 — the tuned entry's fan_dtype is what
  ``plan_fan("auto")`` resolves per workload.
- ``fan2d`` — the insertion-AUC fan at production geometry, same axes
  (cap, fan_chunk, fan_dtype), persisted under the (n_iter+1)-row eval2d
  key every AUC metric resolves.
- ``mel1d`` — the audio mel front-end at flagship audio geometry (b8,
  220500 samples, matmul STFT), A/B-ing the bf16 mel chain
  (`Candidate.mel_bf16`: bf16 DFT/filterbank inputs, f32 accumulation)
  against the Precision.HIGH f32 baseline; the winner's ``mel_bf16``
  field documents the measured call for operators of the
  ``WAM_TPU_MEL_BF16`` knob.
- ``wamvit2d`` — patch-aligned ViT WAM (tiny capture-capable ViT, patch 8
  on 64² inputs → the planner's J=3) at CPU-fast geometry, sweeping chunks,
  stream_noise, an NCHW layout probe (the ViT is natively channel-last)
  and the matmul synthesis probe; persists under the same ``wam2d`` cache
  key family the engine resolves, at the ViT shape.
- ``wamvid3d`` — video WAM (anisotropic space+time decomposition,
  `xattr.video`) over a toy 3D conv, sweeping chunks, stream_noise and the
  synthesis impl; persists under the ``wamvid3d`` key
  `WaveletAttributionVideo(sample_batch_size="auto")` resolves.
- ``wamlive`` — the ONLINE preset (round 19): synthesized from a
  ledger-mined `WorkloadMix` (`wam_tpu.tune.mix`) instead of a canned
  geometry. The dominant observed buckets become toy-engine smoothgrad
  bodies sized/batched from what the fleet actually served, repeated in
  items-weight proportion inside ONE jitted runner, so the sweep ranks
  candidates under the live distribution. Deterministic for a given mix
  (fixed PRNG keys, stable bucket ordering) — the shadow-tuner round-trip
  test pins this.
- ``wamseq1d`` / ``wamseq2d`` — the sequence-sharded long-context loops
  (`parallel.seq_estimators.SeqShardedWam`) over the largest power-of-two
  device mesh available, sweeping the sample chunk × the fused-vs-split
  dispatch knob (`Candidate.seq_fused`). Winners persist under the
  ``wamseq{n}d`` keys that `SeqShardedWam` resolves ``sample_chunk="auto"``
  and ``fused="auto"`` from — until a sweep runs, those fall back to
  chunk 1 / fused.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from wam_tpu.tune.autotuner import Candidate, chunk_candidates

__all__ = ["Workload", "WORKLOADS", "get_workload"]


@dataclasses.dataclass
class Workload:
    name: str
    workload: str  # cache-key workload field ("wam2d", "eval2d", ...)
    shape: tuple  # per-item shape (cache-key field)
    batch: int
    items: int  # items per runner call (throughput denominator)
    candidates: list
    build: Callable[[Candidate], tuple[Callable, tuple]]
    dtype: str = "f32"


def _smoothgrad_runner(engine, x, y, key, *, n_samples: int, chunk,
                       stream: bool, to_bf16: bool = False,
                       channel_last: bool = False):
    """The bench.py step shape: jitted SmoothGrad over engine.attribute with
    the candidate's chunk/stream baked in."""
    from wam_tpu.core.estimators import smoothgrad

    @jax.jit
    def run(x, key):
        if channel_last:
            x = jnp.transpose(x, (0, 2, 3, 1))

        def step(noisy):
            if to_bf16:
                noisy = noisy.astype(jnp.bfloat16)
            _, grads = engine.attribute(noisy, y)
            return grads

        return smoothgrad(step, x, key, n_samples=n_samples,
                          stdev_spread=0.25, batch_size=chunk,
                          materialize_noise=not stream)

    return run, (x, key)


def _toy_workload(n_samples: int = 8, batch: int = 4, size: int = 32) -> Workload:
    """CPU-fast sweep over a toy conv model — structure identical to the
    flagship runner (engine.attribute under chunked smoothgrad), geometry
    small enough that the whole sweep (compiles included) takes seconds."""
    from wam_tpu.core.engine import WamEngine
    from wam_tpu.models.toy import toy_conv_model

    model = toy_conv_model(ndim=2)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, size, size))
    y = jnp.arange(batch, dtype=jnp.int32) % 4
    key = jax.random.PRNGKey(42)

    def build(cand: Candidate):
        if cand.dwt_impl is not None:
            # read at trace time (first call of the fresh jit below), so
            # setting the process-global selector here is candidate-scoped
            from wam_tpu.wavelets.transform import set_dwt2_impl

            set_dwt2_impl(cand.dwt_impl)
        # unlike dwt_impl, ALWAYS reset: a synth probe earlier in the sweep
        # must not leak into the no-synth candidates that follow
        from wam_tpu.wavelets.transform import set_synth2_impl

        set_synth2_impl(cand.synth_impl if cand.synth_impl is not None
                        else "auto")
        engine = WamEngine(model, ndim=2, wavelet="haar", level=2,
                           mode="reflect")
        return _smoothgrad_runner(
            engine, x, y, key, n_samples=n_samples, chunk=cand.sample_chunk,
            stream=bool(cand.stream_noise),
        )

    chunks = chunk_candidates(batch, n_samples, targets=(8, 16))
    cands = [Candidate(sample_chunk=c, stream_noise=False) for c in chunks]
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=True))
    # synthesis-impl probe (matmul only: interpret-mode pallas is minutes of
    # CPU for zero signal — the pallas probe lives in the flagship sweep)
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=False,
                           synth_impl="matmul"))
    return Workload(name="toy", workload="wam2d_toy", shape=(size, size),
                    batch=batch, items=batch, candidates=cands, build=build)


def _flagship_workload(n_samples: int = 25, batch: int = 32,
                       image: int = 224) -> Workload:
    """The pinned north-star geometry, config-identical to bench.py (bf16 +
    fold_bn + dwt-bf16 + stream). Sweeps the round-5 directive-1 space:
    chunks ABOVE the 128-row law (256/512/full), stream on/off, and one
    NCHW probe at the law chunk (layout A/B)."""
    from wam_tpu.core.engine import WamEngine
    from wam_tpu.models import bind_inference, resnet50

    model = resnet50(num_classes=1000)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, image, image, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, 3, image, image),
                          jnp.float32)
    y = jnp.arange(batch, dtype=jnp.int32) % 1000
    key = jax.random.PRNGKey(42)
    bound: dict[bool, Callable] = {}

    def build(cand: Candidate):
        from wam_tpu.wavelets.transform import set_synth2_impl

        set_synth2_impl(cand.synth_impl if cand.synth_impl is not None
                        else "auto")
        nchw = cand.layout == "nchw"
        if nchw not in bound:
            bound[nchw] = bind_inference(model, variables, nchw=nchw,
                                         compute_dtype=jnp.bfloat16,
                                         fold_bn=True)
        engine = WamEngine(bound[nchw], ndim=2, wavelet="db4", level=3,
                           mode="reflect", channel_last=not nchw)
        return _smoothgrad_runner(
            engine, x, y, key, n_samples=n_samples, chunk=cand.sample_chunk,
            stream=cand.stream_noise is not False, to_bf16=True,
            channel_last=not nchw,
        )

    chunks = chunk_candidates(batch, n_samples)  # 128/256/512 rows + full
    cands = [Candidate(sample_chunk=c, stream_noise=True) for c in chunks]
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=False))
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=True,
                           layout="nchw"))
    # synthesis A/B at the law chunk: fused pallas+collapse vs the plain
    # matmul form (ISSUE 4 — synthesis dominates the per-sample inner loop)
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=True,
                           synth_impl="pallas"))
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=True,
                           synth_impl="matmul"))
    return Workload(name="flagship", workload="wam2d",
                    shape=(3, image, image), batch=batch, items=batch,
                    candidates=cands, build=build, dtype="bf16")


def _mu2d_workload(n_images: int = 4, image: int = 224, grid_size: int = 28,
                   sample_size: int = 128, subset_size: int = 157) -> Workload:
    """μ-fidelity inner runner (Eval2DWAM) at production fan geometry,
    sweeping the per-chunk model-row cap. The winner's ``fan_cap`` is what
    ``Eval2DWAM(batch_size="auto")`` resolves via `resolve_fan_cap` — μ is
    the slowest eval row (29.6 img/s) and its fan cap was never swept."""
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.models import bind_inference, resnet50

    model = resnet50(num_classes=1000)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, image, image, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (n_images, 3, image, image), jnp.float32)
    y = jnp.arange(n_images, dtype=jnp.int32) % 1000
    # fixed random mosaics: the sweep measures the masking/forward fan, the
    # explainer is out of scope (and out of the timed region)
    wams = jax.random.uniform(jax.random.PRNGKey(2), (n_images, image, image))
    # one bound model per fan dtype (flagship's nchw-dict pattern): the bf16
    # candidate must run a bf16-param model, not just cast a f32 one's inputs
    bound: dict[str, Callable] = {}

    def build(cand: Candidate):
        dt = cand.fan_dtype or "f32"
        if dt not in bound:
            bound[dt] = bind_inference(
                model, variables, nchw=True, fold_bn=True,
                compute_dtype=None if dt == "f32" else dt)
        ev = Eval2DWAM(bound[dt], explainer=lambda xx, yy: wams,
                       batch_size=int(cand.fan_cap))
        rand_all, onehot_all = ev._mu_random_draws(
            n_images, grid_size, sample_size, subset_size)
        runner = ev._make_mu_runner(grid_size, sample_size,
                                    plan=_explicit_plan(cand, sample_size))
        return runner, (x, wams, y, rand_all, onehot_all)

    cands = [Candidate(fan_cap=c) for c in (64, 128, 256, 512)]
    # fan_chunk axis: images-per-chunk overrides at a fixed cap — the law
    # says 256//128 = 2, the sweep asks whether 1 or 4 actually wins
    cands += [Candidate(fan_cap=256, fan_chunk=1),
              Candidate(fan_cap=256, fan_chunk=4)]
    # precision axis (round 17): the bf16 fan at the hand-law cap — fidelity
    # is gated separately (tests/test_precision.py), the sweep only ranks
    cands.append(Candidate(fan_cap=256, fan_dtype="bf16"))
    return Workload(name="mu2d", workload="eval2d", shape=(sample_size,),
                    batch=sample_size, items=n_images, candidates=cands,
                    build=build)


def _explicit_plan(cand: Candidate, fan: int):
    """Candidate knobs → explicit `FanPlan` (never "auto": the sweep must
    not read the cache entry it is about to write)."""
    from wam_tpu.evalsuite.fan import FanPlan, fan_chunk_geometry

    cap = int(cand.fan_cap)
    images_per_chunk, fan_chunk = fan_chunk_geometry(cap, fan)
    if cand.fan_chunk:
        images_per_chunk, fan_chunk = max(1, int(cand.fan_chunk)), None
    return FanPlan(cap, images_per_chunk, fan_chunk, cand.fan_dtype or "f32")


def _fan2d_workload(n_images: int = 8, image: int = 224,
                    n_iter: int = 64) -> Workload:
    """Insertion-AUC fan (Eval2DWAM) at production geometry, sweeping the
    model-row cap AND the images-per-chunk override (`Candidate.fan_chunk`).
    Persists under the same eval2d key `plan_fan` consults for the
    (n_iter+1)-row AUC fans — the round-5 hand sweep found cap 256 worth
    1.6× over the 128 law on insertion; this makes that sweep (and the
    finer chunk question it couldn't ask) a harness."""
    from wam_tpu.evalsuite.eval2d import Eval2DWAM
    from wam_tpu.evalsuite.metrics import batched_auc_runner
    from wam_tpu.models import bind_inference, resnet50

    model = resnet50(num_classes=1000)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, image, image, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (n_images, 3, image, image), jnp.float32)
    y = jnp.arange(n_images, dtype=jnp.int32) % 1000
    wams = jax.random.uniform(jax.random.PRNGKey(2), (n_images, image, image))
    bound: dict[str, Callable] = {}

    def build(cand: Candidate):
        dt = cand.fan_dtype or "f32"
        if dt not in bound:
            bound[dt] = bind_inference(
                model, variables, nchw=True, fold_bn=True,
                compute_dtype=None if dt == "f32" else dt)
        ev = Eval2DWAM(bound[dt], explainer=lambda xx, yy: wams,
                       batch_size=int(cand.fan_cap))
        plan = _explicit_plan(cand, n_iter + 1)
        runner = batched_auc_runner(
            lambda img, wam: ev._perturb_for_auc(img, wam, "insertion",
                                                 n_iter),
            bound[dt], plan.images_per_chunk, fan_chunk=plan.fan_chunk,
            fan_dtype=plan.fan_dtype)
        return runner, (x, wams, jnp.asarray(y))

    cands = [Candidate(fan_cap=c) for c in (128, 256, 512)]
    cands += [Candidate(fan_cap=256, fan_chunk=1),
              Candidate(fan_cap=512, fan_chunk=4)]
    # precision axis (round 17): bf16 fan at the round-5 winner cap
    cands.append(Candidate(fan_cap=256, fan_dtype="bf16"))
    return Workload(name="fan2d", workload="eval2d", shape=(n_iter + 1,),
                    batch=n_iter + 1, items=n_images, candidates=cands,
                    build=build)


def _mel1d_workload(batch: int = 8, n: int = 220500) -> Workload:
    """Audio mel front-end A/B at flagship audio geometry (ESC-50 5 s @
    44.1 kHz, matmul STFT — the TPU-native impl): f32 baseline vs the bf16
    mel chain (`melspectrogram(bf16=True)`: bf16 DFT-basis/filterbank
    matmul inputs, f32 accumulation). Persists under a ``mel1d`` key whose
    ``mel_bf16`` field records the measured verdict; fidelity (max |Δ dB|,
    attribution cosine) is the tests'/bench's job — the sweep only ranks
    throughput."""
    from wam_tpu.ops.melspec import melspectrogram

    x = jax.random.normal(jax.random.PRNGKey(1), (batch, n), jnp.float32)

    def build(cand: Candidate):
        bf = bool(cand.mel_bf16)

        @jax.jit
        def run(v):
            return melspectrogram(v, impl="matmul", bf16=bf)

        return run, (x,)

    cands = [Candidate(mel_bf16=False), Candidate(mel_bf16=True)]
    return Workload(name="mel1d", workload="mel1d", shape=(n,), batch=batch,
                    items=batch, candidates=cands, build=build)


def _seq_mesh():
    """Largest power-of-two ('data',) mesh the backend offers — the seq
    loops' divisibility checks (sharded axis % 2·shards at every level)
    want power-of-two shard counts; a lone CPU device still sweeps (the
    ordering signal is the dispatch structure, which is device-count
    independent)."""
    import jax as _jax

    from wam_tpu.parallel.mesh import make_mesh

    n = 1
    while n * 2 <= len(_jax.devices()) and n < 8:
        n *= 2
    return make_mesh({"data": n}, _jax.devices()[:n])


def _seq_candidates(chunks=(1, 2, None),
                    strides=(2, 4)) -> list[Candidate]:
    """The seq sweep space: sample-chunk ladder × fused-vs-split, plus the
    anytime checkpoint-stride ladder (fused path only — the checkpointed
    estimators run per-sample, so sample_chunk=1 is their cadence). Explicit
    values only — `SeqShardedWam` resolves these knobs from the entry this
    sweep writes, so reading "auto" here would be circular."""
    cands = [Candidate(sample_chunk=c, seq_fused=f)
             for f in (True, False) for c in chunks]
    cands += [Candidate(sample_chunk=1, seq_fused=True, anytime_stride=k)
              for k in strides]
    return cands


def _wamseq1d_workload(n_samples: int = 4, batch: int = 2,
                       length: int = 2048) -> Workload:
    """1D long-context SmoothGrad over the sequence-sharded estimator: the
    signal axis shards over the mesh, each candidate bakes in an explicit
    (sample_chunk, fused) pair, and the winner persists under the
    ``wamseq1d`` key `SeqShardedWam._resolve_seq_chunk`/`_resolve_fused`
    consult."""
    from wam_tpu.models.audio import toy_wave_model
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    mesh = _seq_mesh()
    model = toy_wave_model(jax.random.PRNGKey(0))
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(None, "data"))
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (batch, length)), sh)
    y = jnp.arange(batch, dtype=jnp.int32) % 4
    key = jax.random.PRNGKey(42)

    def build(cand: Candidate):
        sw = SeqShardedWam(mesh, model, ndim=1, wavelet="db2", level=2,
                           mode="symmetric", fused=bool(cand.seq_fused))

        if cand.anytime_stride is not None:
            def run(x, key):
                out, _ = sw.smoothgrad_checkpointed(
                    x, y, key, n_samples=n_samples, stdev_spread=0.25,
                    stride=cand.anytime_stride)
                return out
        else:
            def run(x, key):
                return sw.smoothgrad(x, y, key, n_samples=n_samples,
                                     stdev_spread=0.25,
                                     sample_chunk=cand.sample_chunk)

        return run, (x, key)

    return Workload(name="wamseq1d", workload="wamseq1d", shape=(length,),
                    batch=batch, items=batch, candidates=_seq_candidates(),
                    build=build)


def _wamseq2d_workload(n_samples: int = 4, batch: int = 2,
                       rows: int = 64, cols: int = 32) -> Workload:
    """2D row-sharded SmoothGrad, same sweep axes as ``wamseq1d`` — the
    mesh path the engine classes take for images taller than a chip."""
    from wam_tpu.parallel.seq_estimators import SeqShardedWam

    mesh = _seq_mesh()
    w = jax.random.normal(jax.random.PRNGKey(0), (5, 3, rows, cols))

    def model(xx):  # (B, C, H, W) -> (B, 5); row-contraction all-reduces
        return jnp.einsum("bchw,kchw->bk", xx, w)

    sh = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None, None, "data", None))
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (batch, 3, rows, cols)), sh)
    y = jnp.arange(batch, dtype=jnp.int32) % 5
    key = jax.random.PRNGKey(42)

    def build(cand: Candidate):
        sw = SeqShardedWam(mesh, model, ndim=2, wavelet="db2", level=2,
                           mode="reflect", fused=bool(cand.seq_fused))

        if cand.anytime_stride is not None:
            def run(x, key):
                out, _ = sw.smoothgrad_checkpointed(
                    x, y, key, n_samples=n_samples, stdev_spread=0.25,
                    stride=cand.anytime_stride)
                return out
        else:
            def run(x, key):
                return sw.smoothgrad(x, y, key, n_samples=n_samples,
                                     stdev_spread=0.25,
                                     sample_chunk=cand.sample_chunk)

        return run, (x, key)

    return Workload(name="wamseq2d", workload="wamseq2d",
                    shape=(3, rows, cols), batch=batch, items=batch,
                    candidates=_seq_candidates(), build=build)


def _wamvit2d_workload(n_samples: int = 8, batch: int = 4,
                       image: int = 64, patch: int = 8) -> Workload:
    """Patch-aligned ViT WAM at CPU-fast geometry: the decomposition depth
    comes from the planner (image 64 / patch 8 → J=3, token-granular level
    3), the runner is the flagship's chunked-smoothgrad shape over the
    capture-capable tiny ViT. Default layout is channel-last (the ViT's
    native layout — the engine transposes once, outside the mapped chunk);
    one NCHW probe checks the transpose placement actually pays."""
    from wam_tpu.core.engine import WamEngine
    from wam_tpu.models.vit import ViT
    from wam_tpu.xattr.planner import plan_patch_levels

    plan = plan_patch_levels(image, patch)
    model = ViT(num_classes=8, patch=patch, dim=32, depth=2, heads=2,
                mlp_hidden=64)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, image, image, 3)))
    base = {k: v for k, v in variables.items() if k != "perturbations"}
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, 3, image, image))
    y = jnp.arange(batch, dtype=jnp.int32) % 8
    key = jax.random.PRNGKey(42)

    def build(cand: Candidate):
        from wam_tpu.wavelets.transform import set_synth2_impl

        set_synth2_impl(cand.synth_impl if cand.synth_impl is not None
                        else "auto")
        nchw = cand.layout == "nchw"
        if nchw:
            model_fn = lambda xx: model.apply(  # noqa: E731
                base, jnp.transpose(xx, (0, 2, 3, 1)))
        else:
            model_fn = lambda xx: model.apply(base, xx)  # noqa: E731
        engine = WamEngine(model_fn, ndim=2, wavelet="haar", level=plan.J,
                           mode="reflect", channel_last=not nchw)
        return _smoothgrad_runner(
            engine, x, y, key, n_samples=n_samples, chunk=cand.sample_chunk,
            stream=bool(cand.stream_noise), channel_last=not nchw,
        )

    chunks = chunk_candidates(batch, n_samples, targets=(8, 16))
    cands = [Candidate(sample_chunk=c, stream_noise=False) for c in chunks]
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=True))
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=False,
                           layout="nchw"))
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=False,
                           synth_impl="matmul"))
    return Workload(name="wamvit2d", workload="wam2d",
                    shape=(3, image, image), batch=batch, items=batch,
                    candidates=cands, build=build)


def _wamlive_workload(mix=None, n_samples: int = 8, top_n: int = 3,
                      total_reps: int = 4) -> Workload:
    """Live-mix sweep: the `WorkloadMix`'s dominant buckets become toy-conv
    smoothgrad bodies with the OBSERVED geometry — per-item size from the
    bucket shape's trailing dim (clamped to the CPU-fast [8, 64] band),
    batch from the observed mean real rows per dispatch (clamped [1, 8]) —
    executed in items-weight proportion inside one jitted runner. Every
    random draw uses a fixed key derived from the bucket's RANK in the mix,
    so the same mix always builds the same runner (determinism is pinned by
    tests/test_tune_online.py)."""
    if mix is None:
        raise ValueError(
            "wamlive synthesizes its preset from an observed mix: pass "
            "mix=<WorkloadMix> (wam_tpu.tune.mix.mine_ledger)")
    from wam_tpu.core.engine import WamEngine
    from wam_tpu.core.estimators import smoothgrad
    from wam_tpu.models.toy import toy_conv_model

    weights = mix.weights()
    specs = []  # (size, batch, weight) per dominant bucket, heaviest first
    for b in mix.dominant(top_n):
        size = int(b.shape[-1]) if b.shape else 16
        size = max(8, min(64, size))
        batch = max(1, min(8, int(round(b.mean_batch)) or 1))
        specs.append((size, batch, weights.get(b.key, 0.0)))
    wsum = sum(w for _, _, w in specs) or 1.0
    reps = [max(1, int(round(total_reps * w / wsum))) for _, _, w in specs]
    dom_size, dom_batch, _ = specs[0]

    model = toy_conv_model(ndim=2)
    inputs = []  # one (x, y) per bucket, keyed by rank — mix-deterministic
    for rank, (size, batch, _w) in enumerate(specs):
        x = jax.random.normal(jax.random.PRNGKey(rank + 1),
                              (batch, size, size))
        y = jnp.arange(batch, dtype=jnp.int32) % 4
        inputs.append((x, y))

    def build(cand: Candidate):
        from wam_tpu.wavelets.transform import set_synth2_impl

        set_synth2_impl(cand.synth_impl if cand.synth_impl is not None
                        else "auto")
        engine = WamEngine(model, ndim=2, wavelet="haar", level=2,
                           mode="reflect")
        chunk = cand.sample_chunk
        stream = bool(cand.stream_noise)

        @jax.jit
        def run(key):
            # one smoothgrad body per (bucket, rep); weight-proportional
            # reps make the heavy bucket dominate the measured time the
            # way it dominates live traffic. Reduced to one scalar so the
            # runner's output transfer is O(1) regardless of mix width.
            total = jnp.float32(0.0)
            i = 0
            for (x, y), r in zip(inputs, reps):
                def step(noisy, y=y):
                    _, grads = engine.attribute(noisy, y)
                    return grads
                for _ in range(r):
                    g = smoothgrad(step, x, jax.random.fold_in(key, i),
                                   n_samples=n_samples, stdev_spread=0.25,
                                   batch_size=chunk,
                                   materialize_noise=not stream)
                    for leaf in jax.tree_util.tree_leaves(g):
                        total = total + jnp.sum(jnp.abs(leaf))
                    i += 1
            return total

        return run, (jax.random.PRNGKey(42),)

    chunks = chunk_candidates(dom_batch, n_samples, targets=(8, 16))
    cands = [Candidate(sample_chunk=c, stream_noise=False) for c in chunks]
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=True))
    items = sum(b * r for (_s, b, _w), r in zip(specs, reps))
    return Workload(name="wamlive", workload="wamlive",
                    shape=(dom_size, dom_size), batch=dom_batch,
                    items=items, candidates=cands, build=build)


def _wamvid3d_workload(n_samples: int = 8, batch: int = 2, frames: int = 8,
                       size: int = 16) -> Workload:
    """Video WAM sweep (anisotropic 2-spatial/1-temporal decomposition over
    a toy 3D conv). The runner is the `WaveletAttributionVideo` SmoothGrad
    body inlined — raw transforms, no tuned-cache reads inside the sweep
    (the same never-resolve-"auto" rule every preset follows); winners
    persist under the ``wamvid3d`` key the engine's
    ``sample_batch_size="auto"`` resolves."""
    from wam_tpu.core.engine import target_loss
    from wam_tpu.core.estimators import smoothgrad
    from wam_tpu.models.toy import toy_conv_model
    from wam_tpu.xattr.video import spacetime_map, wavedec_video, waverec_video

    toy = toy_conv_model(ndim=3, classes=4)
    model_fn = lambda clip: toy(clip[:, 0])  # noqa: E731
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, 1, frames, size, size))
    y = jnp.arange(batch, dtype=jnp.int32) % 4
    key = jax.random.PRNGKey(42)
    levels = (2, 1)

    def build(cand: Candidate):
        from wam_tpu.wavelets.transform import set_synth2_impl

        set_synth2_impl(cand.synth_impl if cand.synth_impl is not None
                        else "auto")
        chunk = cand.sample_chunk
        stream = bool(cand.stream_noise)

        @jax.jit
        def run(x, key):
            def step(noisy):
                coeffs = wavedec_video(noisy, "haar", levels, "symmetric")

                def loss(cs):
                    rec = waverec_video(cs, "haar")[..., :frames, :size, :size]
                    return target_loss(model_fn(rec), y)

                grads = jax.grad(loss)(coeffs)
                return spacetime_map(grads, (frames, size, size)).mean(axis=1)

            return smoothgrad(step, x, key, n_samples=n_samples,
                              stdev_spread=1e-4, batch_size=chunk,
                              materialize_noise=not stream)

        return run, (x, key)

    chunks = chunk_candidates(batch, n_samples, targets=(4, 8))
    cands = [Candidate(sample_chunk=c, stream_noise=False) for c in chunks]
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=True))
    cands.append(Candidate(sample_chunk=chunks[0], stream_noise=False,
                           synth_impl="matmul"))
    return Workload(name="wamvid3d", workload="wamvid3d",
                    shape=(1, frames, size, size), batch=batch, items=batch,
                    candidates=cands, build=build)


WORKLOADS: dict[str, Callable[..., Workload]] = {
    "toy": _toy_workload,
    "flagship": _flagship_workload,
    "mu2d": _mu2d_workload,
    "fan2d": _fan2d_workload,
    "mel1d": _mel1d_workload,
    "wamlive": _wamlive_workload,
    "wamvit2d": _wamvit2d_workload,
    "wamvid3d": _wamvid3d_workload,
    "wamseq1d": _wamseq1d_workload,
    "wamseq2d": _wamseq2d_workload,
}


def get_workload(name: str, **overrides) -> Workload:
    if name not in WORKLOADS:
        raise ValueError(f"unknown workload {name!r}; have {sorted(WORKLOADS)}")
    return WORKLOADS[name](**overrides)
