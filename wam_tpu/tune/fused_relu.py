"""Fused ReLU VJP: packed sign-mask residual, one-multiply backward.

The round-4 flagship backward trace's largest fusion family is the ReLU
cotangent chain: XLA's default ReLU VJP saves the full f32/bf16 activation
as the residual and re-derives the gate in the backward pass as a
compare+select against that tensor — per ReLU site that is a full-activation
HBM round trip (write forward, read backward) plus a compare the VPU repeats
25× per SmoothGrad step. This module replaces it with a `jax.custom_vjp`
ReLU whose residual is the **sign mask bit-packed 8/lane into uint8** (1/32
the bytes of the f32 activation it replaces) and whose backward is **one
masked multiply** — no compare, no full-precision residual traffic.

Three interchangeable implementations (`set_fused_relu_impl` /
``WAM_TPU_FUSED_RELU_IMPL``):

- ``"xla"`` — portable jnp shift/or bit packing; XLA fuses pack into the
  forward and unpack+multiply into one backward kernel. Default off-TPU.
- ``"pallas"`` — one Pallas kernel per direction (forward emits y + packed
  mask in a single pass; backward unpacks and multiplies in-register).
  Default on TPU.
- ``"pallas_interpret"`` — the same kernels under ``interpret=True`` so the
  kernel *code path* (not just the math) regression-tests on CPU CI — the
  round-5 shard_map/vma lesson: portable interpret coverage catches
  real-hardware-only breakage classes before the chip does.

Gradient convention matches `jax.nn.relu` exactly: gate is ``x > 0``, so
the subgradient at 0 is 0 (jax.nn.relu's custom_jvp pins the same choice;
`jnp.maximum`'s raw VJP would split ties 0.5/0.5).

Wire-up: ``models.bind_inference(..., fused_relu_vjp=True)`` clones the
model with ``act=fused_relu`` — parameters are untouched (ReLU has none),
so the flag composes with ``fold_bn``/``compute_dtype`` and checkpoint
ingestion. Gated by the attribution-cosine parity check in
tests/test_tune.py before it may default on.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

__all__ = ["fused_relu", "set_fused_relu_impl", "get_fused_relu_impl",
           "pack_mask", "unpack_mask"]

_LANES = 128
_PACK = 8  # sign bits per uint8
_BLOCK = _PACK * _LANES  # flat elements per packed row group

_IMPLS = ("auto", "xla", "pallas", "pallas_interpret")
_impl = "auto"


def set_fused_relu_impl(name: str) -> None:
    """Select the fused-ReLU backend for *not-yet-traced* calls (same jit
    caching caveat as `wavelets.set_dwt2_impl`)."""
    global _impl
    if name not in _IMPLS:
        raise ValueError(f"impl {name!r} not one of {_IMPLS}")
    _impl = name


set_fused_relu_impl(os.environ.get("WAM_TPU_FUSED_RELU_IMPL", "auto"))


def get_fused_relu_impl() -> str:
    return _impl


def _resolved_impl() -> str:
    if _impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return _impl


# -- packed-mask layout ------------------------------------------------------
#
# x is flattened, zero-padded to a multiple of 8·128, and viewed as
# (R, 128) with R a multiple of 8. The mask packs the SUBLANE axis: 8
# consecutive rows fold into one uint8 row, m[r, l] = Σ_b (x[8r+b, l] > 0)·2^b
# — the lane axis stays 128-wide in both tensors, so the same (rows, 128)
# tiling serves f32 input and uint8 mask on TPU. Zero pad rows pack to 0
# bits and multiply pad cotangent rows that are sliced off, so padding never
# leaks into real gradients.


def _flat_rows(n: int) -> int:
    return -(-n // _BLOCK) * _PACK


def pack_mask(x: jax.Array) -> jax.Array:
    """(R, 128) float → (R//8, 128) uint8 of sign bits (x > 0)."""
    bits = (x > 0).astype(jnp.uint8).reshape(-1, _PACK, _LANES)
    weights = jnp.uint8(1) << jnp.arange(_PACK, dtype=jnp.uint8)
    return (bits * weights[None, :, None]).sum(axis=1, dtype=jnp.uint8)


def unpack_mask(m: jax.Array) -> jax.Array:
    """(R//8, 128) uint8 → (R, 128) float32 0/1 gate."""
    shifts = jnp.arange(_PACK, dtype=jnp.uint8)
    bits = (m[:, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(-1, _LANES).astype(jnp.float32)


# -- pallas kernels ----------------------------------------------------------


def _fwd_kernel(x_ref, y_ref, m_ref):
    x = x_ref[...]
    y_ref[...] = jnp.maximum(x, jnp.zeros((), x.dtype))
    m_ref[...] = pack_mask(x)


def _bwd_kernel(m_ref, g_ref, dx_ref):
    g = g_ref[...]
    dx_ref[...] = g * unpack_mask(m_ref[...]).astype(g.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_fwd(x2, interpret: bool):
    from jax.experimental import pallas as pl

    rows = x2.shape[0]
    return pl.pallas_call(
        _fwd_kernel,
        out_shape=(
            jax.ShapeDtypeStruct(x2.shape, x2.dtype),
            jax.ShapeDtypeStruct((rows // _PACK, _LANES), jnp.uint8),
        ),
        interpret=interpret,
    )(x2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_bwd(m, g2, interpret: bool):
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _bwd_kernel,
        out_shape=jax.ShapeDtypeStruct(g2.shape, g2.dtype),
        interpret=interpret,
    )(m, g2)


# -- the custom-vjp op -------------------------------------------------------


def _to_rows(a: jax.Array) -> jax.Array:
    flat = a.reshape(-1)
    rows = _flat_rows(flat.shape[0])
    pad = rows * _LANES - flat.shape[0]
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANES)


def _from_rows(a2: jax.Array, shape, dtype) -> jax.Array:
    n = 1
    for d in shape:
        n *= int(d)
    return a2.reshape(-1)[:n].reshape(shape).astype(dtype)


@jax.custom_vjp
def fused_relu(x: jax.Array) -> jax.Array:
    """ReLU with the packed-mask fused backward (module docstring). The
    primal is a plain `jnp.maximum` so un-differentiated uses (and
    `jax.linearize`-free paths) stay one op."""
    return jnp.maximum(x, jnp.zeros((), x.dtype))


def _fused_relu_fwd(x):
    impl = _resolved_impl()
    x2 = _to_rows(x)
    if impl == "xla":
        y2, m = jnp.maximum(x2, jnp.zeros((), x2.dtype)), pack_mask(x2)
    else:
        y2, m = _pallas_fwd(x2, impl == "pallas_interpret")
    return _from_rows(y2, x.shape, x.dtype), m


def _fused_relu_bwd(m, g):
    impl = _resolved_impl()
    g2 = _to_rows(g)
    if impl == "xla":
        dx2 = g2 * unpack_mask(m).astype(g2.dtype)
    else:
        dx2 = _pallas_bwd(m, g2, impl == "pallas_interpret")
    return (_from_rows(dx2, g.shape, g.dtype),)


fused_relu.defvjp(_fused_relu_fwd, _fused_relu_bwd)
