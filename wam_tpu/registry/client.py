"""Hydrate side of the compile-artifact registry.

`RegistryClient` turns a published bundle (`bundle.publish_bundle`) into
warm local caches: verified `jax.export` payloads seeded into the AOT
cache (`pipeline.aot.seed_aot_payload`, header origin "registry" so later
consults attribute the skipped compile), XLA compilation-cache files
copied in by name, and the tuned-schedule snapshot merged under local
entries. The serve stack calls `hydrate()` before any compile fallback —
`AttributionServer.start()`, `FleetServer.start(registry=)`, and
`ReplicaSupervisor` restarts via `_rebuild_replica` — so a fresh process
with a cold ``~/.cache/wam_tpu`` serves its first request at
``compile_count == 0``.

Miss semantics mirror the caches this layer feeds (the rule the whole
persistence stack shares): **any mismatch is a silent per-artifact miss,
never an error**. A torn manifest is an empty bundle; a stale registry
schema or foreign platform fingerprint skips the bundle wholesale; a
digest mismatch skips that one artifact (and records a ``registry_miss``
AOT event); whatever could not hydrate simply compiles, exactly as if no
bundle had been offered. ``WAM_TPU_NO_REGISTRY=1`` is the kill switch —
no bundle IO at all.

Bundles are fetched through a ``fetcher(relpath) -> bytes`` callable
(default: the local bundle directory), the seam where remote backends
(GCS, HTTP) slot in without touching hydrate logic or bundle format.

Every hydration emits a `HydrationReport` — one v2 ledger row
(``metric: "registry_hydration"``) written by the serve close path, plus
`wam_tpu_registry_*` counters on the obs registry.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import time

from wam_tpu.obs.registry import registry as _obs_registry
from wam_tpu.registry.bundle import (
    REGISTRY_SCHEMA_VERSION,
    default_xla_dir,
    fingerprint_mismatch,
    load_manifest,
)

__all__ = [
    "registry_disabled",
    "local_fetcher",
    "HydrationReport",
    "RegistryClient",
    "resolve_client",
]

_hydrations = _obs_registry.counter(
    "wam_tpu_registry_hydrations_total",
    "registry bundle hydration attempts by terminal status",
    labels=("status",))
_artifacts = _obs_registry.counter(
    "wam_tpu_registry_artifacts_total",
    "per-artifact hydration outcomes", labels=("kind", "outcome"))
_schedules = _obs_registry.counter(
    "wam_tpu_registry_schedules_total",
    "schedule-snapshot merge outcomes", labels=("outcome",))

# wholesale statuses: nothing in the bundle is touched
_WHOLESALE = ("disabled", "no_manifest", "stale_schema",
              "version_mismatch", "platform_mismatch")


def registry_disabled() -> bool:
    """`WAM_TPU_NO_REGISTRY=1` — the registry analogue of
    `WAM_TPU_NO_AOT_CACHE`: hydrate becomes a no-op reporting status
    "disabled", with zero bundle IO."""
    return os.environ.get("WAM_TPU_NO_REGISTRY", "") not in ("", "0")


def local_fetcher(bundle_dir: str):
    """``fetcher(relpath) -> bytes`` over a local bundle directory. Raises
    OSError on a missing file — the caller's tolerant-read wrappers turn
    that into the appropriate miss."""

    def fetch(relpath: str) -> bytes:
        with open(os.path.join(bundle_dir, relpath), "rb") as f:
            return f.read()

    return fetch


class HydrationReport:
    """What one `RegistryClient.hydrate` did: terminal ``status`` (one of
    the wholesale statuses above, or "hydrated"/"empty" when the bundle
    was actually walked), per-(kind, outcome) artifact ``counts``, and the
    number of schedule entries merged. `row()` is the v2 serve-ledger
    form."""

    def __init__(self, bundle: str, status: str,
                 counts: dict | None = None, schedules_added: int = 0,
                 schedules_status: str = "none", duration_s: float = 0.0):
        self.bundle = bundle
        self.status = status
        self.counts = dict(counts or {})
        self.schedules_added = schedules_added
        self.schedules_status = schedules_status
        self.duration_s = duration_s

    def count(self, kind: str, outcome: str) -> int:
        return self.counts.get(f"{kind}:{outcome}", 0)

    @property
    def hydrated(self) -> int:
        return sum(n for k, n in self.counts.items()
                   if k.endswith(":hydrated"))

    def row(self) -> dict:
        from wam_tpu.serve.metrics import SCHEMA_VERSION

        return {
            "metric": "registry_hydration",
            "schema_version": SCHEMA_VERSION,
            "bundle": self.bundle,
            "status": self.status,
            "artifacts": dict(self.counts),
            "hydrated": self.hydrated,
            "schedules_added": self.schedules_added,
            "schedules_status": self.schedules_status,
            "duration_s": self.duration_s,
            "t": time.time(),
        }

    def __repr__(self):
        return (f"HydrationReport(bundle={self.bundle!r}, "
                f"status={self.status!r}, hydrated={self.hydrated}, "
                f"schedules_added={self.schedules_added})")


class RegistryClient:
    """Probe / hydrate one bundle. ``bundle`` is a local directory path
    today; pass ``fetcher`` to read the same layout from anywhere."""

    def __init__(self, bundle: str, fetcher=None):
        self.bundle = str(bundle)
        self.fetcher = fetcher or local_fetcher(self.bundle)
        self._manifest: dict | None = None
        self._loaded = False

    def manifest(self) -> dict | None:
        """Cached tolerant manifest read — None on missing/torn/non-JSON."""
        if not self._loaded:
            self._manifest = load_manifest(self.bundle, self.fetcher)
            self._loaded = True
        return self._manifest

    # -- classification ---------------------------------------------------

    def _wholesale_status(self, manifest) -> str | None:
        """The reason the WHOLE bundle cannot hydrate here, or None."""
        if manifest is None:
            return "no_manifest"
        if manifest.get("registry_schema_version") != REGISTRY_SCHEMA_VERSION:
            return "stale_schema"
        cause = fingerprint_mismatch(manifest.get("platform"))
        if cause == "version":
            return "version_mismatch"
        if cause == "platform":
            return "platform_mismatch"
        return None

    def _fetch_verified(self, art: dict):
        """(payload, outcome): payload bytes when the artifact fetched and
        digest-verified, else (None, "fetch_error"|"digest_mismatch")."""
        try:
            payload = self.fetcher(art["file"])
        except Exception:
            return None, "fetch_error"
        if hashlib.sha256(payload).hexdigest() != art.get("sha256"):
            return None, "digest_mismatch"
        return payload, "ok"

    def probe(self, aot_dir: str | None = None,
              xla_dir: str | None = None) -> dict:
        """Non-writing per-artifact breakdown (the
        `scripts/compile_cache_probe.py` surface). Unlike `hydrate`, the
        kill switch does NOT silence this — a diagnostic that refuses to
        diagnose is useless. Each artifact row gains an ``outcome``:
        "ok" (would hydrate), "present" (already local),
        "digest_mismatch" / "fetch_error", or the wholesale cause
        ("stale_schema" / "version_mismatch" / "platform_mismatch")
        stamped on every row so per-artifact reports stay honest about
        why nothing is hydratable."""
        manifest = self.manifest()
        wholesale = self._wholesale_status(manifest)
        arts = (manifest or {}).get("artifacts") or []
        rows = []
        hydratable = 0
        for art in arts:
            if not isinstance(art, dict):
                continue
            row = {k: art.get(k) for k in
                   ("kind", "key", "file", "sha256", "bytes")}
            if wholesale:
                row["outcome"] = wholesale
            else:
                payload, outcome = self._fetch_verified(art)
                if payload is None:
                    row["outcome"] = outcome
                elif self._locally_present(art, aot_dir, xla_dir):
                    row["outcome"] = "present"
                    hydratable += 1  # present counts: the cache IS warm
                else:
                    row["outcome"] = "ok"
                    hydratable += 1
            rows.append(row)
        sched = (manifest or {}).get("schedules") if not wholesale else None
        return {
            "bundle": self.bundle,
            "status": wholesale or "ok",
            "artifacts": rows,
            "hydratable": hydratable,
            "schedules": len((sched or {}).get("schedules") or {}),
        }

    def _locally_present(self, art: dict, aot_dir, xla_dir) -> bool:
        """Is this artifact already a VALID local cache entry? (A corrupt
        local file is not present — hydrate overwrites it.)"""
        from wam_tpu.pipeline.aot import read_aot_payload

        if art.get("kind") == "aot":
            payload, _ = read_aot_payload(str(art.get("key")), aot_dir)
            return payload is not None
        if art.get("kind") == "xla":
            path = os.path.join(xla_dir or default_xla_dir(),
                                str(art.get("key")))
            return os.path.isfile(path)
        return False

    # -- hydrate ----------------------------------------------------------

    def hydrate(self, aot_dir: str | None = None,
                schedule_path: str | None = None,
                xla_dir: str | None = None) -> HydrationReport:
        """Seed the local caches from the bundle. Never raises for bundle
        problems; the report says what happened and the process falls back
        to compiling whatever did not hydrate."""
        t0 = time.time()
        if registry_disabled():
            return self._finish(HydrationReport(self.bundle, "disabled"), t0)
        manifest = self.manifest()
        wholesale = self._wholesale_status(manifest)
        if wholesale:
            return self._finish(HydrationReport(self.bundle, wholesale), t0)

        from wam_tpu.obs import sentinel
        from wam_tpu.pipeline.aot import seed_aot_payload

        counts: dict[str, int] = {}

        def bump(kind: str, outcome: str):
            counts[f"{kind}:{outcome}"] = counts.get(f"{kind}:{outcome}", 0) + 1
            _artifacts.inc(kind=kind, outcome=outcome)

        pub_jax = (manifest.get("platform") or {}).get("jax")
        for art in manifest.get("artifacts") or []:
            if not isinstance(art, dict):
                continue
            kind = art.get("kind")
            if kind not in ("aot", "xla"):
                bump(str(kind), "unknown_kind")
                continue
            if self._locally_present(art, aot_dir, xla_dir):
                bump(kind, "present")  # local cache wins — hydrate is idempotent
                continue
            payload, outcome = self._fetch_verified(art)
            if payload is None:
                bump(kind, outcome)
                if kind == "aot":
                    sentinel.record_aot("registry_miss", str(art.get("key")))
                continue
            if kind == "aot":
                path = seed_aot_payload(str(art.get("key")), payload, aot_dir,
                                        jax_version=pub_jax)
                bump(kind, "hydrated" if path else "write_error")
            else:
                ok = self._write_xla(str(art.get("key")), payload, xla_dir)
                bump(kind, "hydrated" if ok else "write_error")

        added, sched_status = self._merge_schedules(
            manifest.get("schedules"), schedule_path)
        status = "hydrated" if (counts or added) else "empty"
        report = HydrationReport(self.bundle, status, counts,
                                 schedules_added=added,
                                 schedules_status=sched_status)
        return self._finish(report, t0)

    def _write_xla(self, rel_key: str, payload: bytes, xla_dir) -> bool:
        root = xla_dir or default_xla_dir()
        # bundle keys are publisher-relative paths; refuse escapes
        path = os.path.normpath(os.path.join(root, rel_key))
        if not path.startswith(os.path.normpath(root) + os.sep):
            return False
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                       suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except OSError:
            return False
        return True

    def _merge_schedules(self, snapshot, schedule_path) -> tuple[int, str]:
        """Merge the bundle's schedule snapshot UNDER local entries (local
        wins — a locally-tuned schedule reflects this machine). Stale
        snapshot version → ignored wholesale, the `tune/cache.py` rule."""
        from wam_tpu.tune.cache import (
            SCHEDULE_CACHE_VERSION,
            ScheduleCache,
            invalidate_process_cache,
        )

        if not isinstance(snapshot, dict):
            _schedules.inc(outcome="absent")
            return 0, "absent"
        if snapshot.get("version") != SCHEDULE_CACHE_VERSION:
            _schedules.inc(outcome="stale")
            return 0, "stale"
        entries = snapshot.get("schedules")
        if not isinstance(entries, dict) or not entries:
            _schedules.inc(outcome="empty")
            return 0, "empty"
        cache = ScheduleCache(path=schedule_path)
        added = 0
        for key, ent in entries.items():
            if not isinstance(ent, dict):
                continue
            if cache.get(key) is None:
                cache.put(key, ent)
                added += 1
        if added:
            cache.save()
            invalidate_process_cache()
            _schedules.inc(added, outcome="added")
        _schedules.inc(outcome="merged")
        return added, "merged"

    def _finish(self, report: HydrationReport, t0: float) -> HydrationReport:
        report.duration_s = time.time() - t0
        _hydrations.inc(status=report.status)
        return report


def resolve_client(registry) -> "RegistryClient | None":
    """Normalize the serve-stack ``registry=`` parameter: None/"" → None,
    a path string → `RegistryClient(path)`, a client → itself."""
    if registry is None or registry == "":
        return None
    if isinstance(registry, RegistryClient):
        return registry
    return RegistryClient(str(registry))
