"""CLI for the compile-artifact registry.

    # snapshot a prewarmed machine's caches into a bundle
    python -m wam_tpu.prewarm --workloads wam2d_s --manifest warm.json
    python -m wam_tpu.registry publish --out bundle/ --from-prewarm warm.json

    # what's in it / would it hydrate here?
    python -m wam_tpu.registry inspect bundle/

    # seed this machine's caches (servers do this via registry=)
    python -m wam_tpu.registry hydrate bundle/

Each subcommand prints ONE JSON document to stdout, the repo's
script-output convention. `inspect` exits 1 when zero artifacts are
hydratable (the CI smoke gate); `publish` exits 1 when the bundle came
out empty.
"""

from __future__ import annotations

import argparse
import json
import sys


def _add_cache_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--aot-dir", default=None,
                   help="AOT cache dir (default: $WAM_TPU_AOT_CACHE or "
                        "~/.cache/wam_tpu/aot)")
    p.add_argument("--schedule-cache", default=None,
                   help="user schedule cache path (default: "
                        "$WAM_TPU_SCHEDULE_CACHE or "
                        "~/.cache/wam_tpu/schedules.json)")
    p.add_argument("--xla-dir", default=None,
                   help="persistent XLA compilation cache dir (default: "
                        "$WAM_TPU_CACHE_DIR or ~/.cache/wam_tpu/xla)")


def _prewarm_keys(paths: list[str]) -> tuple[list[str] | None, list[dict]]:
    """AOT keys + source descriptors from prewarm --manifest JSON files.
    A manifest without a ``warmed`` block contributes nothing (old
    prewarm output) — publish then falls back to walking the whole cache."""
    keys: list[str] = []
    sources: list[dict] = []
    saw_warmed = False
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: unreadable prewarm manifest {path}: {e}",
                  file=sys.stderr)
            continue
        warmed = doc.get("warmed") if isinstance(doc, dict) else None
        if not isinstance(warmed, dict):
            continue
        saw_warmed = True
        keys.extend(k for k in warmed.get("aot_keys", ()) if isinstance(k, str))
        sources.append({
            "prewarm_manifest": path,
            "bucket_keys": warmed.get("bucket_keys"),
            "schedule_version": warmed.get("schedule_version"),
        })
    return (sorted(set(keys)) if saw_warmed else None), sources


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m wam_tpu.registry",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("--device", default=None, choices=["tpu", "axon", "cpu"],
                    help="pin the JAX platform before any backend use "
                         "(the platform fingerprint records the backend)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    pub = sub.add_parser("publish", help="snapshot local caches → bundle")
    pub.add_argument("--out", required=True, help="bundle output directory")
    _add_cache_flags(pub)
    pub.add_argument("--no-xla", action="store_true",
                     help="skip the XLA compilation-cache files")
    pub.add_argument("--no-schedules", action="store_true",
                     help="skip the tuned-schedule snapshot")
    pub.add_argument("--from-prewarm", nargs="+", default=None,
                     metavar="JSON",
                     help="prewarm --manifest files: publish exactly the "
                          "AOT keys they warmed instead of walking blind")

    ins = sub.add_parser("inspect",
                         help="per-artifact hydratability breakdown "
                              "(exit 1 when nothing is hydratable)")
    ins.add_argument("bundle")
    _add_cache_flags(ins)

    hyd = sub.add_parser("hydrate", help="seed local caches from a bundle")
    hyd.add_argument("bundle")
    _add_cache_flags(hyd)

    args = ap.parse_args(argv)

    from wam_tpu.config import select_backend

    select_backend(args.device)

    if args.cmd == "publish":
        from wam_tpu.registry.bundle import publish_bundle

        keys, sources = (None, [])
        if args.from_prewarm:
            keys, sources = _prewarm_keys(args.from_prewarm)
        manifest = publish_bundle(
            args.out,
            aot_dir=args.aot_dir,
            schedule_path=args.schedule_cache,
            xla_dir=args.xla_dir,
            keys=keys,
            include_xla=not args.no_xla,
            include_schedules=not args.no_schedules,
            source={"prewarm": sources} if sources else None,
        )
        arts = manifest["artifacts"]
        out = {
            "bundle": args.out,
            "artifacts": len(arts),
            "aot": sum(1 for a in arts if a["kind"] == "aot"),
            "xla": sum(1 for a in arts if a["kind"] == "xla"),
            "schedules": len((manifest.get("schedules") or {})
                             .get("schedules") or {}),
            "platform": manifest["platform"],
        }
        print(json.dumps(out, indent=1))
        return 0 if arts else 1

    from wam_tpu.registry.client import RegistryClient

    client = RegistryClient(args.bundle)
    if args.cmd == "inspect":
        report = client.probe(aot_dir=args.aot_dir, xla_dir=args.xla_dir)
        print(json.dumps(report, indent=1))
        return 0 if report["hydratable"] > 0 else 1

    report = client.hydrate(aot_dir=args.aot_dir,
                            schedule_path=args.schedule_cache,
                            xla_dir=args.xla_dir)
    print(json.dumps(report.row(), indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
