"""Bundle format + publish side of the compile-artifact registry.

A **bundle** is one directory (local path today; `client.RegistryClient`
takes a fetcher callable so a remote store slots in without touching this
format):

    bundle/
      manifest.json            # everything below, written atomically
      artifacts/<sha256-32>.bin  # content-addressed artifact payloads

The manifest is version-headed (`REGISTRY_SCHEMA_VERSION` — a reader that
does not speak the schema ignores the bundle WHOLESALE, the
`tune/cache.py` stale-file rule) and carries:

- a **platform fingerprint**: backend + jax version + the AOT/schedule
  cache schema versions the artifacts were produced under. A backend or
  cache-version mismatch makes the whole bundle a silent miss on hydrate
  (an exported executable bakes its lowering platforms in; seeding a TPU
  export into a CPU host's cache would just miss again at consult time,
  so the gate saves the copies, not correctness).
- **aot artifacts**: the `jax.export`-serialized executables from the
  local AOT cache (`pipeline/aot.py`), stored WITHOUT their local JSON
  header — a bundle artifact is the pure serialization, digested as such;
  hydration re-heads it with ``origin: "registry"`` so later consults
  attribute their skipped compile to the bundle.
- **xla artifacts**: the persistent XLA compilation-cache files
  (`config.enable_compilation_cache`). The AOT layer removes the Python
  trace; the deserialized module still XLA-compiles once per process
  unless this cache is warm too — shipping both is what makes cold start
  actually zero-compile, not just zero-retrace.
- a **tuned-schedule snapshot**: the MERGED schedule table (repo-pinned
  `tune/default_schedules.json` layer + user cache) with its own schema
  version, so a hydrated host resolves the same chunk/stream/synth knobs
  the publisher compiled under — an AOT key embeds the schedule, so a
  missing schedule entry would change the key and miss the executable.
- per-artifact **sha256 digests** — hydration verifies every payload
  before seeding; a flipped bit is one artifact's miss, never an error.

Publish walks a prewarmed cache (`python -m wam_tpu.prewarm` or an
AOT-keyed serve warmup), optionally filtered to the keys a prewarm
manifest says it warmed. All IO is tolerant on the read side and atomic
on the write side, mirroring the caches it snapshots.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time

__all__ = [
    "REGISTRY_SCHEMA_VERSION",
    "platform_fingerprint",
    "fingerprint_mismatch",
    "default_xla_dir",
    "publish_bundle",
    "load_manifest",
    "write_manifest",
]

REGISTRY_SCHEMA_VERSION = 1

# manifest-relative directory for content-addressed payloads
_ARTIFACT_DIR = "artifacts"


def platform_fingerprint() -> dict:
    """What the artifacts in a bundle were produced under. ``backend`` and
    the two cache schema versions are the hydrate gates; ``jax`` is
    recorded for diagnostics only (a cross-version deserialize that fails
    is already a per-artifact miss on the consult path)."""
    import jax

    from wam_tpu.pipeline.aot import AOT_CACHE_VERSION
    from wam_tpu.tune.cache import SCHEDULE_CACHE_VERSION

    return {
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "aot_cache_version": AOT_CACHE_VERSION,
        "schedule_cache_version": SCHEDULE_CACHE_VERSION,
    }


def fingerprint_mismatch(platform: dict) -> str | None:
    """Why a manifest's platform fingerprint cannot hydrate HERE:
    "platform" (backend differs) or "version" (AOT cache schema differs),
    None when compatible. The schedule version gates only the schedule
    snapshot (`client`), not the executables."""
    import jax

    from wam_tpu.pipeline.aot import AOT_CACHE_VERSION

    if not isinstance(platform, dict):
        return "version"
    if platform.get("aot_cache_version") != AOT_CACHE_VERSION:
        return "version"
    if platform.get("backend") != jax.default_backend():
        return "platform"
    return None


def default_xla_dir() -> str:
    """The persistent XLA compilation cache directory
    (`config.enable_compilation_cache`'s default resolution)."""
    return os.environ.get(
        "WAM_TPU_CACHE_DIR", os.path.expanduser("~/.cache/wam_tpu/xla")
    )


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _store_payload(out_dir: str, payload: bytes) -> tuple[str, str]:
    """Write one content-addressed payload (atomic, dedup by digest);
    returns (manifest-relative file, sha256)."""
    digest = _sha256(payload)
    rel = f"{_ARTIFACT_DIR}/{digest[:32]}.bin"
    path = os.path.join(out_dir, rel)
    if not os.path.exists(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
    return rel, digest


def write_manifest(out_dir: str, manifest: dict) -> str:
    """Atomic manifest write (tmp + rename) — a torn publish leaves either
    the previous manifest or none, never half a JSON document."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "manifest.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_manifest(bundle: str, fetcher=None) -> dict | None:
    """Tolerant manifest read: None on a missing, torn, or non-JSON
    manifest (the hydrate side treats that as an empty bundle, mirroring
    the AOT cache's corrupt-file miss). ``fetcher(relpath) -> bytes`` maps
    bundle-relative names to content; default is the local directory."""
    if fetcher is None:
        from wam_tpu.registry.client import local_fetcher

        fetcher = local_fetcher(bundle)
    try:
        data = json.loads(fetcher("manifest.json").decode("utf-8"))
    except Exception:
        return None
    return data if isinstance(data, dict) else None


def _xla_files(xla_dir: str) -> list[tuple[str, str]]:
    """(relative key, absolute path) for every file in the XLA cache dir
    (recursive — the cache may shard into subdirectories)."""
    out: list[tuple[str, str]] = []
    for dirpath, _, names in os.walk(xla_dir):
        for name in sorted(names):
            path = os.path.join(dirpath, name)
            out.append((os.path.relpath(path, xla_dir), path))
    return sorted(out)


def publish_bundle(
    out_dir: str,
    *,
    aot_dir: str | None = None,
    schedule_path: str | None = None,
    xla_dir: str | None = None,
    keys=None,
    include_xla: bool = True,
    include_schedules: bool = True,
    source: dict | None = None,
) -> dict:
    """Walk the local caches and emit a bundle directory; returns the
    manifest. ``keys`` filters the AOT walk to an explicit key set (the
    prewarm-manifest handoff — `python -m wam_tpu.prewarm --manifest`);
    None publishes every valid entry. Stale/corrupt local cache files are
    skipped silently — publish never fails on what the consult path would
    have ignored anyway."""
    from wam_tpu.pipeline.aot import list_aot_entries, read_aot_payload
    from wam_tpu.tune.cache import SCHEDULE_CACHE_VERSION, ScheduleCache

    keyset = set(keys) if keys is not None else None
    artifacts: list[dict] = []
    for entry in list_aot_entries(aot_dir):
        if keyset is not None and entry["key"] not in keyset:
            continue
        payload, header = read_aot_payload(entry["key"], aot_dir)
        if payload is None:
            continue
        rel, digest = _store_payload(out_dir, payload)
        artifacts.append({
            "kind": "aot",
            "key": entry["key"],
            "file": rel,
            "sha256": digest,
            "bytes": len(payload),
            "jax": header.get("jax"),
        })
    if include_xla:
        xla_root = xla_dir or default_xla_dir()
        if os.path.isdir(xla_root):
            for rel_key, path in _xla_files(xla_root):
                try:
                    with open(path, "rb") as f:
                        payload = f.read()
                except OSError:
                    continue
                rel, digest = _store_payload(out_dir, payload)
                artifacts.append({
                    "kind": "xla",
                    "key": rel_key,
                    "file": rel,
                    "sha256": digest,
                    "bytes": len(payload),
                })
    schedules = None
    if include_schedules:
        cache = ScheduleCache(path=schedule_path)
        schedules = {
            "version": SCHEDULE_CACHE_VERSION,
            "schedules": dict(cache.entries),
        }
    manifest = {
        "registry_schema_version": REGISTRY_SCHEMA_VERSION,
        "created_unix": time.time(),
        "platform": platform_fingerprint(),
        "artifacts": artifacts,
        "schedules": schedules,
    }
    if source:
        manifest["source"] = source
    write_manifest(out_dir, manifest)
    return manifest
