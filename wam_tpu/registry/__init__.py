"""Versioned compile-artifact registry — publish/hydrate bundles for
zero-compile cold start.

The cold-start stack, bottom to top: the XLA persistent compilation
cache absorbs recompiles of identical modules; the AOT cache
(`pipeline/aot.py`) skips the Python trace for keyed executables; the
schedule cache (`tune/cache.py`) remembers the tuned knobs those
executables were compiled under. All three are PER-MACHINE — a new host
or a wiped cache pays 20–40 s per bucket graph again. This package makes
the warm state portable: `publish_bundle` snapshots the three caches
into one content-addressed, version-headed bundle directory, and
`RegistryClient.hydrate` verifies and seeds them on any compatible host,
so `FleetServer.start(registry=...)` serves its first request at
``compile_count == 0``.

CLI: ``python -m wam_tpu.registry {publish,inspect,hydrate}``.
"""

from wam_tpu.registry.bundle import (
    REGISTRY_SCHEMA_VERSION,
    load_manifest,
    platform_fingerprint,
    publish_bundle,
)
from wam_tpu.registry.client import (
    HydrationReport,
    RegistryClient,
    local_fetcher,
    registry_disabled,
    resolve_client,
)

__all__ = [
    "REGISTRY_SCHEMA_VERSION",
    "platform_fingerprint",
    "publish_bundle",
    "load_manifest",
    "HydrationReport",
    "RegistryClient",
    "local_fetcher",
    "registry_disabled",
    "resolve_client",
]
