"""Buffer-donation policy for the streaming pipeline.

One policy, shared by every donating call site (`serve.entry.jit_entry`,
`evalsuite.metrics.batched_auc_runner`, the μ-fidelity runners, the
materialized-noise SmoothGrad path): ``donate=None`` resolves to "donate
on TPU only". XLA:CPU gains nothing from aliasing (host memory is not
the scarce resource) while the donated handle is still consumed — and on
versions where CPU cannot alias at all it warns "Some donated buffers
were not usable" per call — so donation defaults off everywhere except
the backend it helps.

Donation consumes the caller's buffer: after a donating call, the donated
`jax.Array` is deleted and any later read raises. That is fine for
freshly-uploaded host batches (the dominant case — every perturbation fan
is built from numpy each call) but would poison instance-cached tensors
(`grad_wams`, μ-draw caches) and user-held arrays reused across
insertion/deletion. `donation_safe` is the guard: it uploads host arrays
as usual and device-copies an existing `jax.Array` only when donation is
actually active, so the CPU path (donation off) stays zero-copy.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

__all__ = ["resolve_donate", "donating_jit", "donation_safe"]


def resolve_donate(donate: bool | None) -> bool:
    """``None`` → donate iff the default backend is TPU (the serve/entry
    policy, now shared by the eval runners)."""
    if donate is None:
        return jax.default_backend() == "tpu"
    return bool(donate)


def donating_jit(
    fn: Callable,
    *,
    donate_argnums: Sequence[int] = (0,),
    donate: bool | None = None,
    **jit_kwargs,
):
    """`jax.jit` with the shared donation policy: ``donate_argnums`` is
    applied only when `resolve_donate(donate)` is true."""
    argnums = tuple(donate_argnums) if resolve_donate(donate) else ()
    return jax.jit(fn, donate_argnums=argnums, **jit_kwargs)


def donation_safe(tree, donating: bool):
    """Make ``tree`` safe to pass as a donated argument.

    Host (numpy/python) leaves upload fresh either way. When ``donating``,
    existing `jax.Array` leaves are device-copied so the caller's handle
    (an instance cache, a user-held batch) survives the donation; when not
    donating this is a plain `jnp.asarray` pass-through with no copy.
    """

    def one(leaf):
        if donating and isinstance(leaf, jax.Array):
            return jnp.array(leaf, copy=True)
        return jnp.asarray(leaf)

    return jax.tree_util.tree_map(one, tree)
