"""AOT executable cache — trace+compile skipping across processes.

The round-6 schedule cache remembers *what* to compile (chunk sizes,
stream mode, dwt impl) but every fresh process still pays the Python
trace + XLA compile to turn that schedule into an executable. This layer
caches the executable itself: `jax.jit(...)` is lowered once, exported
with `jax.export`, and the serialized StableHLO module is written under a
key in the round-6 `workload|shape|batch|dtype|impl|backend` style. A
later process deserializes and calls the exported module directly — the
Python callable is never retraced (the trace-count probes in
tests/test_pipeline.py assert exactly this), and XLA recompilation of the
deserialized module is absorbed by the persistent compilation cache
(`config.enable_compilation_cache`).

Keying is **opt-in and caller-owned**: an exported module bakes in every
closed-over constant — model parameters above all — so a shape-only key
would collide across models. Callers must pass an ``aot_key`` that
uniquely identifies the model + config (prewarm derives one from the
workload preset, whose fixed-seed init makes parameters process-stable);
no ``aot_key`` → no AOT, plain jit. Consumers: `serve` warmup via
`jit_entry(aot_key=...)`, `python -m wam_tpu.prewarm`, and the eval
runner caches (`evalsuite.metrics.run_cached_auc`).

Mirrors `tune/cache.py` versioning: entries carry `AOT_CACHE_VERSION` in
a JSON header line and stale-version or corrupt files are ignored
wholesale (re-exported on the next miss). `WAM_TPU_NO_AOT_CACHE=1` is the
kill switch; `$WAM_TPU_AOT_CACHE` overrides the directory
(~/.cache/wam_tpu/aot by default).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import warnings
from typing import Callable, Sequence

import jax

try:  # a submodule on jax 0.4.3x — not auto-imported via the jax namespace
    from jax import export as jax_export
except ImportError:  # pragma: no cover - very old jax
    jax_export = None


def _register_pytree_serializations() -> None:
    """Serialization names for the repo's NamedTuple pytrees — without a
    registered name, exporting any program whose output carries one of
    these (e.g. wavedec2's Detail2D) fails at `Exported.serialize`."""
    if jax_export is None or not hasattr(
        jax_export, "register_namedtuple_serialization"
    ):  # pragma: no cover - very old jax
        return
    from wam_tpu.parallel.halo_modes import TailedLeaf
    from wam_tpu.wavelets.transform import Detail2D

    for cls in (Detail2D, TailedLeaf):
        try:
            jax_export.register_namedtuple_serialization(
                cls, serialized_name=f"wam_tpu.{cls.__name__}"
            )
        except ValueError:  # already registered (re-import)
            pass


_register_pytree_serializations()

from wam_tpu.obs import sentinel
from wam_tpu.pipeline.donation import resolve_donate

__all__ = [
    "AOT_CACHE_VERSION",
    "default_aot_dir",
    "aot_entry_path",
    "save_aot",
    "load_aot",
    "load_aot_meta",
    "list_aot_entries",
    "read_aot_payload",
    "seed_aot_payload",
    "aval_signature",
    "cached_jit",
    "cached_entry",
]

AOT_CACHE_VERSION = 1

_warned_keys: set[str] = set()


def _disabled() -> bool:
    return os.environ.get("WAM_TPU_NO_AOT_CACHE", "") not in ("", "0")


def default_aot_dir() -> str:
    return os.environ.get(
        "WAM_TPU_AOT_CACHE", os.path.expanduser("~/.cache/wam_tpu/aot")
    )


def aot_entry_path(key: str, cache_dir: str | None = None) -> str:
    digest = hashlib.sha1(key.encode()).hexdigest()[:20]
    return os.path.join(cache_dir or default_aot_dir(), f"{digest}.aot")


def _write_entry(key: str, payload: bytes, cache_dir: str | None,
                 origin: str, jax_version: str | None = None) -> str | None:
    """Atomic header+payload write shared by `save_aot` (origin
    "exported") and `seed_aot_payload` (origin "registry")."""
    header = json.dumps({
        "version": AOT_CACHE_VERSION, "key": key,
        "jax": jax_version or jax.__version__, "origin": origin,
    }).encode()
    path = aot_entry_path(key, cache_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(header + b"\n" + payload)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def save_aot(key: str, exported, cache_dir: str | None = None) -> str | None:
    """Serialize an `jax.export.Exported` under ``key``. Atomic (tmp +
    rename); returns the path, or None when serialization fails (some
    programs — custom calls, shard_map on older jax — do not export)."""
    try:
        payload = bytes(exported.serialize())
    except Exception as e:
        _warn_once(key, f"serialize failed: {e}")
        return None
    return _write_entry(key, payload, cache_dir, origin="exported")


def seed_aot_payload(key: str, payload: bytes, cache_dir: str | None = None,
                     *, origin: str = "registry",
                     jax_version: str | None = None) -> str | None:
    """Install an already-serialized executable under ``key`` WITHOUT
    deserializing it (the registry hydration path: the payload is the
    publisher's `Exported.serialize()` bytes, digest-verified by the
    caller). The header's ``origin`` marks where the entry came from so
    later consults attribute their hit to the registry; ``jax_version``
    records the PUBLISHER's jax (informational — the consult path's
    platform check is what actually gates use)."""
    return _write_entry(key, bytes(payload), cache_dir, origin=origin,
                        jax_version=jax_version)


def _read_entry(path: str, key: str | None = None):
    """(header, payload) for one cache file, or (None, None) on any
    corruption / version / key mismatch — the tolerant-read core of every
    consult."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
        header_line, _, payload = raw.partition(b"\n")
        header = json.loads(header_line)
    except (OSError, ValueError):
        return None, None
    if not isinstance(header, dict) or header.get("version") != AOT_CACHE_VERSION:
        return None, None
    if key is not None and header.get("key") != key:
        return None, None
    return header, payload


def read_aot_payload(key: str, cache_dir: str | None = None):
    """(serialized payload bytes, header dict) for ``key`` without
    deserializing — the registry publish path reads executables this way
    so a bundle stores pure `jax.export` serializations (the local JSON
    header is a cache implementation detail, not part of the artifact).
    (None, None) on miss/stale/corrupt."""
    header, payload = _read_entry(aot_entry_path(key, cache_dir), key)
    if header is None:
        return None, None
    return payload, header


def list_aot_entries(cache_dir: str | None = None) -> list[dict]:
    """Every valid current-version entry in the cache directory as
    ``{"key", "path", "origin", "jax"}`` rows (header-only parse — cheap).
    Stale/corrupt/torn files are silently skipped, mirroring the consult
    path's miss semantics; a missing directory is an empty cache."""
    root = cache_dir or default_aot_dir()
    rows: list[dict] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return rows
    for name in names:
        if not name.endswith(".aot"):
            continue
        path = os.path.join(root, name)
        header, _ = _read_entry(path)
        if header is None or not isinstance(header.get("key"), str):
            continue
        rows.append({
            "key": header["key"],
            "path": path,
            "origin": header.get("origin", "exported"),
            "jax": header.get("jax"),
        })
    return rows


def load_aot_meta(key: str, cache_dir: str | None = None):
    """(exported, header) for ``key``, or (None, None) on miss. Version
    mismatch, key (hash) collision, wrong platform, and corrupt payloads
    are all treated as misses — never an error on the consult path. The
    header carries ``origin`` ("exported" locally, "registry" when the
    entry was hydrated from a bundle) so the compile sentinel can
    attribute the hit."""
    header, payload = _read_entry(aot_entry_path(key, cache_dir), key)
    if header is None or jax_export is None:
        return None, None
    try:
        exported = jax_export.deserialize(bytearray(payload))
    except Exception:
        return None, None
    platforms = tuple(getattr(exported, "platforms", ()) or ())
    if platforms and jax.default_backend() not in platforms:
        return None, None
    return exported, header


def load_aot(key: str, cache_dir: str | None = None):
    """Deserialize the entry for ``key``, or None on miss (see
    `load_aot_meta` for the miss semantics)."""
    exported, _ = load_aot_meta(key, cache_dir)
    return exported


def aval_signature(tree) -> str:
    """Stable shape/dtype signature of an argument pytree, e.g.
    ``f32[8,3,224,224];i32[8]`` (None leaves print as ``-``)."""

    def one(leaf):
        if leaf is None:
            return "-"
        aval = jax.api_util.shaped_abstractify(leaf)
        return f"{aval.dtype.name}[{','.join(str(d) for d in aval.shape)}]"

    leaves = jax.tree_util.tree_leaves(tree, is_leaf=lambda x: x is None)
    return ";".join(one(leaf) for leaf in leaves)


def _warn_once(key: str, msg: str) -> None:
    if key in _warned_keys:
        return
    _warned_keys.add(key)
    warnings.warn(f"wam_tpu AOT cache [{key}]: {msg}; falling back to plain jit")


def _specs_like(tree):
    def one(leaf):
        if leaf is None:
            return None
        aval = jax.api_util.shaped_abstractify(leaf)
        return jax.ShapeDtypeStruct(aval.shape, aval.dtype)

    return jax.tree_util.tree_map(one, tree, is_leaf=lambda x: x is None)


def cached_jit(
    fn: Callable,
    example_args: tuple,
    key: str,
    *,
    donate_argnums: Sequence[int] = (),
    on_trace: Callable[[], None] | None = None,
    cache_dir: str | None = None,
    obs_kind: str = "aot",
):
    """One executable for ``fn`` at ``example_args``' shapes/dtypes.

    Cache hit: deserialize and splice the stored module — ``fn`` is never
    traced (``on_trace`` never fires). Miss: trace+export ``fn`` once
    (``on_trace`` fires once), persist, and serve the exported module.
    Disabled cache or export failure falls back to a plain `jax.jit(fn)`.
    Returns a callable with ``fn``'s signature. Every trace of ``fn`` is
    also reported to the compile sentinel (under ``obs_kind``), and cache
    hit/miss/export outcomes land on the sentinel's AOT counters.
    """
    donate_argnums = tuple(donate_argnums)

    def probed(*args):
        # trace-time only — one execution per jit cache miss
        sentinel.record_trace(obs_kind, detail=key)
        if on_trace is not None:
            on_trace()
        return fn(*args)

    plain = jax.jit(probed, donate_argnums=donate_argnums)
    if _disabled():
        return plain
    exported, header = load_aot_meta(key, cache_dir)
    if exported is None:
        sentinel.record_aot("miss", key)
        specs = [_specs_like(a) for a in example_args]
        try:
            if jax_export is None:
                raise RuntimeError("jax.export unavailable")
            exported = jax_export.export(plain)(*specs)
        except Exception as e:
            _warn_once(key, f"export failed: {type(e).__name__}: {e}")
            return plain
        if save_aot(key, exported, cache_dir) is not None:
            sentinel.record_aot("export", key)
    elif header is not None and header.get("origin") == "registry":
        # the executable was seeded by a registry bundle, not exported by
        # an earlier local process — attribute the skipped compile to it
        sentinel.record_aot("registry_hit", key)
    else:
        sentinel.record_aot("hit", key)
    call = exported.call
    return jax.jit(call, donate_argnums=donate_argnums)


def cached_entry(
    impl: Callable,
    base_key: str,
    *,
    donate_argnums: Sequence[int] = (),
    on_trace: Callable[[], None] | None = None,
    cache_dir: str | None = None,
    obs_kind: str = "aot",
):
    """Shape-dispatching callable over the AOT cache.

    ``entry(*args)`` resolves one `cached_jit` per argument signature,
    keyed ``{base_key}|{aval_signature}|{backend}`` — the executable
    analogue of the schedule cache's shape axis. ``base_key`` must
    identify the model + params (see module docstring); callers resolve
    the donation policy themselves and pass concrete ``donate_argnums``.
    """
    donate_argnums = tuple(donate_argnums)
    fns: dict[str, Callable] = {}

    def entry(*args):
        sig = aval_signature(args)
        fn = fns.get(sig)
        if fn is None:
            key = f"{base_key}|{sig}|{jax.default_backend()}"
            fn = cached_jit(
                impl,
                args,
                key,
                donate_argnums=donate_argnums,
                on_trace=on_trace,
                cache_dir=cache_dir,
                obs_kind=obs_kind,
            )
            fns[sig] = fn
        return fn(*args)

    return entry
