"""Double-buffered host→device staging.

Every batch loop in the repo used to interleave host work (decode,
`np.stack`, RNG) with a synchronous upload: batch *k*'s host time and
transfer sat serially in front of batch *k*'s compute. `stage_to_device`
moves both off the consumer's critical path: a producer thread pulls from
the host iterator and `jax.device_put`s each batch (committed to an
explicit `Sharding` when one is attached — the mesh path's data layout),
parking up to ``depth`` staged batches in a bounded queue. `device_put`
dispatch is asynchronous, so batch *k+1*'s transfer overlaps batch *k*'s
compute; with ``depth=2`` (double buffering) the device never waits on the
host unless the host is genuinely slower than the device end-to-end.

Consumers: `bench.py --h2d`, the evalsuite batch loops
(`scripts/bench_eval.py`), and the serve dispatcher's assemble stage
(`serve/runtime.py` stages each padded batch before dispatch).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator

import jax

from wam_tpu.obs import tracing as _obs_tracing
from wam_tpu.obs.registry import registry as _registry

__all__ = ["put_committed", "stage_to_device", "DeviceStager"]

_DONE = object()

_h2d_bytes = _registry.counter(
    "wam_tpu_stager_h2d_bytes_total",
    "host->device bytes staged through put_committed")


def put_committed(tree, sharding=None):
    """`jax.device_put` a batch pytree, committed to ``sharding`` when one
    is given (a `Sharding` or `Device`, or a matching pytree of them — a
    single Device broadcasts over the tree, which is how each fleet replica
    pins its staged batches and warmup zeros to its own chip,
    `serve/runtime.py` "Device pinning"). Dispatch is asynchronous — the
    returned arrays are futures over the transfer. When observability is
    on, the staged leaf bytes land on the obs H2D counter (host-side
    ``.nbytes`` of the pre-transfer leaves — no device sync)."""
    if _obs_tracing._STATE.enabled:
        n = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            n += getattr(leaf, "nbytes", 0)
        if n:
            _h2d_bytes.inc(n)
            # live-bytes feed for the HBM accounting gauge (obs.memory):
            # same host-side byte count, second sink
            from wam_tpu.obs import memory as _obs_memory

            _obs_memory.note_staged(n)
    if sharding is None:
        return jax.device_put(tree)
    return jax.device_put(tree, sharding)


class DeviceStager:
    """Iterator over ``batches`` with each item already on device.

    A daemon producer thread runs the host iterator and stages every batch
    via `put_committed`; the bounded queue (``depth`` slots) is the double
    buffer. Exceptions from the host iterator (and `StopIteration`) are
    forwarded to the consumer in order. `close()` (also wired to context
    exit) stops the producer without draining the host iterator.
    """

    def __init__(self, batches: Iterable[Any], *, depth: int = 2, sharding=None):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce,
            args=(iter(batches), sharding),
            name="wam-device-stager",
            daemon=True,
        )
        self._thread.start()

    def _produce(self, it: Iterator[Any], sharding) -> None:
        try:
            for item in it:
                staged = put_committed(item, sharding)
                while not self._stop.is_set():
                    try:
                        self._queue.put(staged, timeout=0.05)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
            payload = _DONE
        except BaseException as exc:  # forwarded, not swallowed
            payload = exc
        while not self._stop.is_set():
            try:
                self._queue.put(payload, timeout=0.05)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._queue.get()
        if item is _DONE:
            self.close()
            raise StopIteration
        if isinstance(item, BaseException):
            self.close()
            raise item
        return item

    def close(self) -> None:
        """Stop the producer; staged-but-unconsumed batches are dropped."""
        self._stop.set()
        # unblock a producer parked on a full queue
        try:
            self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def stage_to_device(batches: Iterable[Any], *, depth: int = 2, sharding=None):
    """Generator convenience over `DeviceStager` — guarantees the producer
    thread is shut down when the loop ends, breaks, or raises."""
    stager = DeviceStager(batches, depth=depth, sharding=sharding)
    try:
        yield from stager
    finally:
        stager.close()
