"""Streaming attribution pipeline: the prefetch / donate / precompile
trio threaded through the hot paths.

- `stager` — double-buffered host→device staging (`stage_to_device`,
  `put_committed`): batch *k+1* uploads while batch *k* computes.
- `donation` — the shared "TPU-only by default" buffer-donation policy
  (`resolve_donate`, `donating_jit`) and the `donation_safe` guard for
  instance-cached / user-held arrays.
- `aot` — versioned AOT executable cache over `jax.export`
  (`cached_jit`, `cached_entry`): a fresh process with a populated cache
  skips trace+compile entirely.

See DESIGN.md "Streaming pipeline & AOT cache".
"""

from wam_tpu.pipeline.aot import (
    AOT_CACHE_VERSION,
    aot_entry_path,
    aval_signature,
    cached_entry,
    cached_jit,
    default_aot_dir,
    load_aot,
    save_aot,
)
from wam_tpu.pipeline.donation import donating_jit, donation_safe, resolve_donate
from wam_tpu.pipeline.stager import DeviceStager, put_committed, stage_to_device

__all__ = [
    "AOT_CACHE_VERSION",
    "aot_entry_path",
    "aval_signature",
    "cached_entry",
    "cached_jit",
    "default_aot_dir",
    "load_aot",
    "save_aot",
    "donating_jit",
    "donation_safe",
    "resolve_donate",
    "DeviceStager",
    "put_committed",
    "stage_to_device",
]
