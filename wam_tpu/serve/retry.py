"""Client-side retry and hedging discipline (resilience tentpole part 3).

The serve runtime's backpressure contract is reject-with-retry-after —
`QueueFullError.retry_after_s` is the server's own projected-drain
estimate — but until this module the CLIENT side had no discipline:
bench_serve slept exactly ``retry_after_s`` (no jitter, so every rejected
client woke in lockstep and re-collided) and real callers had nothing at
all. `RetryPolicy` packages the production behavior:

- **deadline-budgeted retries**: a total ``budget_s`` per logical request;
  each attempt's backoff is clamped to what remains, and a request whose
  budget lapses resolves as a typed `RetryBudgetExceededError` (carrying
  the last server error) — never a hang.
- **retry_after honored, capped backoff + jitter**: the wait before
  attempt *k+1* is ``max(server retry_after, base·2^(k-1) capped)`` times
  a seeded jitter factor, so a thundering herd of rejected clients
  decorrelates instead of re-colliding.
- **tail-latency hedging** (``hedge_after_s``): when the first submit's
  future is still pending after the hedge delay, a second submit races it
  and the FIRST result wins; the loser's future is left to resolve into a
  swallowed callback (a replicated read — both results are identical — so
  first-wins "cancellation" is observation-side: nothing consumes the
  loser). Hedges trade duplicate work for p99; keep ``hedge_after_s``
  well above the p50 service time.

`FleetServer.submit_with_retry` exposes the policy on the fleet surface
(one daemon driver thread per call — closed-loop client counts, not
thousands of concurrent requests); `scripts/bench_serve.py` uses it for
every client (satellite: retry_after honored with jitter + per-point retry
counts).
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import FIRST_COMPLETED, Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from concurrent.futures import wait as futures_wait
from dataclasses import dataclass

from wam_tpu.obs.registry import registry as _registry
from wam_tpu.serve.runtime import QueueFullError, ServeError

__all__ = ["RetryPolicy", "RetryStats", "RetryBudgetExceededError"]

_c_attempts = _registry.counter(
    "wam_tpu_retry_attempts_total", "submit attempts made under a RetryPolicy")
_c_retries = _registry.counter(
    "wam_tpu_retry_retries_total", "re-submits after a retryable error")
_c_hedges = _registry.counter(
    "wam_tpu_retry_hedges_total", "hedged second submits fired")
_c_hedge_wins = _registry.counter(
    "wam_tpu_retry_hedge_wins_total", "requests whose hedge resolved first")
_c_exhausted = _registry.counter(
    "wam_tpu_retry_exhausted_total",
    "requests that ran out of attempts or budget")


class RetryBudgetExceededError(ServeError):
    """The retry policy ran out of attempts or deadline budget. ``last``
    is the final server error (None when the budget lapsed with a submit
    still pending — ``pending=True``, the load generator's "lost unless
    typed" distinction: a pending future at budget expiry means the work
    never resolved, which the zero-loss chaos gate treats as a loss)."""

    def __init__(self, msg: str, last: Exception | None = None,
                 pending: bool = False):
        super().__init__(msg)
        self.last = last
        self.pending = pending


class RetryStats:
    """Thread-safe counters shared across a load generator's clients (one
    per bench point); mirrors the ``wam_tpu_retry_*`` registry series."""

    def __init__(self):
        self._lock = threading.Lock()
        self.attempts = 0
        self.retries = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.exhausted = 0
        self.backoff_s_total = 0.0

    def _note(self, field: str, n: float = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "attempts": self.attempts,
                "retries": self.retries,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "exhausted": self.exhausted,
                "backoff_s_total": self.backoff_s_total,
            }


@dataclass(frozen=True)
class RetryPolicy:
    """See module docstring. ``retry_on`` is the retryable error tuple —
    `QueueFullError` (and its `MemoryAdmissionError` subclass) by default;
    chaos benches add `NoLiveReplicaError` so requests rejected during a
    total-outage window retry into the supervisor's restart instead of
    failing. Every other `ServeError` propagates (typed, the client's
    decision), and non-ServeError exceptions propagate immediately."""

    max_attempts: int = 4
    budget_s: float | None = None
    backoff_base_s: float = 0.01
    backoff_cap_s: float = 1.0
    jitter_frac: float = 0.5
    hedge_after_s: float | None = None
    retry_on: tuple = (QueueFullError,)

    def backoff_s(self, attempt: int, rng: random.Random,
                  retry_after_s: float | None = None) -> float:
        """Wait before attempt ``attempt + 1``: exponential-capped, floored
        at the server's own estimate, jittered UP (never below the server's
        retry_after — resubmitting early just re-collides)."""
        b = min(self.backoff_cap_s, self.backoff_base_s * 2 ** max(0, attempt - 1))
        if retry_after_s is not None:
            b = max(b, retry_after_s)
        return b * (1.0 + self.jitter_frac * rng.random())

    def run(self, submit, *, rng: random.Random | None = None,
            stats: RetryStats | None = None):
        """Drive ``submit(remaining_s | None) -> Future`` to a result.
        Blocking; returns the winning future's result or raises a typed
        error. ``remaining_s`` is the unspent budget (None without one) so
        the callee can derive a per-attempt deadline."""
        rng = rng if rng is not None else random.Random()
        t_end = (time.monotonic() + self.budget_s
                 if self.budget_s is not None else None)

        def remaining() -> float | None:
            return None if t_end is None else t_end - time.monotonic()

        def _back_off(attempt: int, e: Exception) -> bool:
            """Sleep before the next attempt; False when out of attempts
            or budget (caller breaks)."""
            if attempt >= self.max_attempts:
                return False
            wait_s = self.backoff_s(
                attempt, rng, getattr(e, "retry_after_s", None))
            rem = remaining()
            if rem is not None:
                if rem <= 0.0:
                    return False
                wait_s = min(wait_s, rem)
            _c_retries.inc()
            if stats is not None:
                stats._note("retries")
                stats._note("backoff_s_total", wait_s)
            time.sleep(wait_s)
            return True

        last: Exception | None = None
        for attempt in range(1, self.max_attempts + 1):
            rem = remaining()
            if rem is not None and rem <= 0.0:
                break
            _c_attempts.inc()
            if stats is not None:
                stats._note("attempts")
            try:
                fut = submit(rem)
            except self.retry_on as e:
                last = e
                if not _back_off(attempt, e):
                    break
                continue
            try:
                return self._await(fut, submit, rem, stats)
            except FutureTimeoutError as e:
                last = e
                break  # budget lapsed with the future still pending
            except self.retry_on as e:
                # the future itself resolved to a retryable error (e.g. a
                # fleet re-route ending in QueueFullError): same loop
                last = e
                if not _back_off(attempt, e):
                    break
        _c_exhausted.inc()
        if stats is not None:
            stats._note("exhausted")
        pending = isinstance(last, FutureTimeoutError)
        raise RetryBudgetExceededError(
            f"retry policy exhausted after {self.max_attempts} attempt(s)"
            + (f"; last error: {last!r}" if last is not None else ""),
            last=None if pending else last, pending=pending)

    def _await(self, fut: Future, submit, rem: float | None,
               stats: RetryStats | None):
        """Wait out one attempt, optionally racing a hedge. Raises
        `concurrent.futures.TimeoutError` (caught by `run` as budget
        exhaustion with ``pending=True``) when the budget lapses with no
        future resolved."""
        if self.hedge_after_s is None:
            if rem is None:
                return fut.result()
            out = futures_wait([fut], timeout=rem)
            if not out.done:
                raise FutureTimeoutError()
            return fut.result()
        first_wait = (self.hedge_after_s if rem is None
                      else min(self.hedge_after_s, rem))
        done, _ = futures_wait([fut], timeout=first_wait)
        if done:
            return fut.result()
        if rem is not None:
            rem = rem - first_wait
            if rem <= 0.0:
                raise FutureTimeoutError()
        _c_hedges.inc()
        if stats is not None:
            stats._note("hedges")
        try:
            hedge = submit(rem)
        except ServeError:
            hedge = None  # hedge rejected: keep waiting on the original
        racers = [fut] if hedge is None else [fut, hedge]
        done, pending = futures_wait(
            racers, timeout=rem, return_when=FIRST_COMPLETED)
        if not done:
            raise FutureTimeoutError()
        # prefer a successful racer; otherwise surface the first error
        winner = next((f for f in done if f.exception() is None),
                      next(iter(done)))
        if hedge is not None and winner is hedge:
            _c_hedge_wins.inc()
            if stats is not None:
                stats._note("hedge_wins")
        for f in pending:
            # first-wins: nothing consumes the loser — swallow its eventual
            # exception so a late failure doesn't warn on GC
            f.add_done_callback(lambda f: f.exception())
        return winner.result()
