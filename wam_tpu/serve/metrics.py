"""Per-request serving metrics (tentpole part 3).

The serving runtime's observable state, accumulated thread-safely and
emitted as `results.JsonlWriter` ledger rows comparable to the bench.py /
bench_matrix.py ledgers: one ``serve_batch`` row per dispatched batch
(queue depth, fill ratio, pad waste, service time) plus a ``serve_summary``
row per drain window (p50/p99 latency, attributions/sec, reject/expiry
counts, jit cache misses). Stage wall-clock inside the worker loop reuses
`profiling.StageTimer` (assemble / dispatch / fetch), so serve ledgers
decompose the same way bench ledgers do.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from wam_tpu.profiling import StageTimer
from wam_tpu.results import JsonlWriter

__all__ = ["ServeMetrics", "percentile_ms"]


def percentile_ms(latencies_s, q: float) -> float:
    """Linear-interpolated percentile of a latency sample, in ms (NaN when
    empty — a summary of zero requests has no latency)."""
    if not latencies_s:
        return float("nan")
    return float(np.quantile(np.asarray(latencies_s, np.float64), q / 100.0) * 1e3)


class ServeMetrics:
    """Accumulator shared by the dispatcher (submit side) and the worker
    loop (drain side); every mutator takes the lock, so client threads and
    the device-owner thread can hit it concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self.stages = StageTimer()
        self.compile_count = 0  # jit cache misses (serve_entry on_trace hook)
        self.submitted = 0
        self.completed = 0
        self.rejected = 0  # backpressure (queue full)
        self.expired = 0  # deadline passed while queued
        self.failed = 0  # engine raised; no fallback could serve it
        self.fallbacks = 0  # batches served by the degraded CPU entry
        self.latencies_s: list[float] = []  # submit -> result, per request
        self.queue_waits_s: list[float] = []  # submit -> batch assembly
        self.batch_rows: list[dict] = []  # one dict per dispatched batch
        self._t0 = time.perf_counter()

    # -- mutators (called from dispatcher / worker threads) -----------------

    def note_compile(self) -> None:
        """Hook for `serve_entry(on_trace=...)`: runs once per jit trace,
        i.e. once per (bucket) cache miss."""
        with self._lock:
            self.compile_count += 1

    def note_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def note_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def note_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += n

    def note_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    def note_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1

    def note_batch(
        self,
        *,
        bucket_shape: tuple[int, ...],
        n_real: int,
        max_batch: int,
        pad_waste: float,
        queue_depth: int,
        service_s: float,
        queue_waits_s: list[float],
        latencies_s: list[float],
    ) -> None:
        """One dispatched batch: aggregate row + per-request samples."""
        with self._lock:
            self.completed += len(latencies_s)
            self.latencies_s.extend(latencies_s)
            self.queue_waits_s.extend(queue_waits_s)
            self.batch_rows.append(
                {
                    "metric": "serve_batch",
                    "bucket": list(bucket_shape),
                    "n_real": n_real,
                    "fill_ratio": n_real / max_batch,
                    "pad_waste": pad_waste,
                    "queue_depth": queue_depth,
                    "service_s": service_s,
                    "timestamp": time.time(),
                }
            )

    # -- reporting ----------------------------------------------------------

    def summary(self) -> dict:
        """Aggregate window stats; keys are the ledger schema documented in
        DESIGN.md ("Serving runtime")."""
        with self._lock:
            window_s = time.perf_counter() - self._t0
            fills = [r["fill_ratio"] for r in self.batch_rows]
            wastes = [r["pad_waste"] for r in self.batch_rows]
            depths = [r["queue_depth"] for r in self.batch_rows]
            return {
                "metric": "serve_summary",
                "window_s": window_s,
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "expired": self.expired,
                "failed": self.failed,
                "fallback_batches": self.fallbacks,
                "batches": len(self.batch_rows),
                "compile_count": self.compile_count,
                "fill_ratio_mean": float(np.mean(fills)) if fills else float("nan"),
                "pad_waste_mean": float(np.mean(wastes)) if wastes else float("nan"),
                "queue_depth_mean": float(np.mean(depths)) if depths else float("nan"),
                "queue_depth_max": int(max(depths)) if depths else 0,
                "latency_p50_ms": percentile_ms(self.latencies_s, 50),
                "latency_p99_ms": percentile_ms(self.latencies_s, 99),
                "queue_wait_p50_ms": percentile_ms(self.queue_waits_s, 50),
                "attributions_per_s": self.completed / window_s if window_s > 0 else 0.0,
                "stages": self.stages.summary(),
            }

    def emit(self, writer: JsonlWriter, config: dict | None = None) -> dict:
        """Flush batch rows + the summary row to a JSONL ledger; returns the
        summary. ``config`` is attached to the summary row the way
        `results.MetricRecord` carries its config."""
        with self._lock:
            rows = list(self.batch_rows)
        for row in rows:
            writer.write(row)
        summary = self.summary()
        if config is not None:
            summary["config"] = config
        writer.write(summary)
        return summary
