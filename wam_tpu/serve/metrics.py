"""Per-request serving metrics (tentpole part 3).

The serving runtime's observable state, accumulated thread-safely and
emitted as `results.JsonlWriter` ledger rows comparable to the bench.py /
bench_matrix.py ledgers: one ``serve_batch`` row per dispatched batch
(queue depth, fill ratio, pad waste, service time) plus a ``serve_summary``
row per drain window (p50/p99 latency, attributions/sec, reject/expiry
counts, jit cache misses). Stage wall-clock inside the worker loop reuses
`profiling.StageTimer` (assemble / dispatch / fetch), so serve ledgers
decompose the same way bench ledgers do.

Ledger schema versions
----------------------
- **v1** (rounds 1-9): one global EMA service time (runtime-private), no
  replica identity; ``serve_summary`` carried the aggregate counters and
  percentiles only.
- **v2** (the fleet round, `SCHEMA_VERSION = 2`): every summary row gains
  ``schema_version``, ``replica_id`` (None on a single-chip server — the
  fleet assigns 0..N-1, "fleet" for the oversize pjit entry),
  ``ema_service_s`` (the per-BUCKET EMA service-time map that feeds both
  `QueueFullError.retry_after_s` and the fleet's load-aware routing),
  ``warmup_s`` (per-bucket warmup seconds recorded by the parallel bucket
  warmup), ``busy_s`` and ``utilization`` (dispatch-to-harvest busy time
  over the window; pipelined overlap can push utilization slightly above
  the true device duty cycle — it is a routing/idleness signal, not an
  xplane measurement). ``serve_batch`` rows additionally carry
  ``replica_id`` when one is set. All v1 keys are preserved verbatim, so
  single-chip JSONL consumers keep working unchanged. `FleetMetrics`
  aggregates N replica ledgers into one ``fleet_summary`` row (aggregate
  attributions/sec, pooled latency percentiles, per-replica utilization,
  replica deaths, oversize dispatch counters).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from wam_tpu.obs.registry import registry as _obs_registry
from wam_tpu.profiling import StageTimer
from wam_tpu.results import JsonlWriter
from wam_tpu.serve.buckets import bucket_key

__all__ = ["ServeMetrics", "FleetMetrics", "percentile_ms", "SCHEMA_VERSION",
           "write_obs_snapshot", "write_slo_status", "write_result_cache"]

SCHEMA_VERSION = 2

# -- obs registry instruments (second sink; JSONL schema untouched) ---------
# Counters mirror the ServeMetrics counters 1:1 so `obs.render_prom()` and
# the JSONL summary can be cross-checked exactly (bench_serve --emit test).
# Label cardinality: replica id ("-" when unset) and bucket key only.

def _rlabel(replica_id) -> str:
    return "-" if replica_id is None else str(replica_id)


_c_submitted = _obs_registry.counter(
    "wam_tpu_serve_submitted_total", "requests accepted by submit()",
    labels=("replica",))
_c_completed = _obs_registry.counter(
    "wam_tpu_serve_completed_total", "requests resolved with a result",
    labels=("replica",))
_c_rejected = _obs_registry.counter(
    "wam_tpu_serve_rejected_total", "requests rejected by backpressure",
    labels=("replica",))
_c_expired = _obs_registry.counter(
    "wam_tpu_serve_expired_total", "requests whose deadline passed queued",
    labels=("replica",))
_c_failed = _obs_registry.counter(
    "wam_tpu_serve_failed_total", "requests failed with no fallback",
    labels=("replica",))
_c_fallbacks = _obs_registry.counter(
    "wam_tpu_serve_fallback_batches_total",
    "batches served by the degraded CPU entry", labels=("replica",))
_c_compiles = _obs_registry.counter(
    "wam_tpu_serve_compile_total", "serve-entry jit cache misses",
    labels=("replica",))
_c_batches = _obs_registry.counter(
    "wam_tpu_serve_batches_total", "dispatched batches",
    labels=("replica", "bucket"))
_g_queue_depth = _obs_registry.gauge(
    "wam_tpu_serve_queue_depth",
    "queue depth observed at batch assembly", labels=("replica", "bucket"))
_g_ema_service = _obs_registry.gauge(
    "wam_tpu_serve_ema_service_seconds",
    "per-bucket EMA batch service time (routing signal)",
    labels=("replica", "bucket"))
_h_latency = _obs_registry.histogram(
    "wam_tpu_serve_latency_seconds", "submit->result request latency",
    labels=("replica",))
_h_occupancy = _obs_registry.histogram(
    "wam_tpu_serve_batch_occupancy",
    "per-dispatch real-row occupancy (n_real / max_batch) — the coalescing "
    "acceptance gate reads this", labels=("replica",),
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
_h_service = _obs_registry.histogram(
    "wam_tpu_serve_service_seconds", "dispatch->harvest batch service time",
    labels=("replica",))
_g_warmup = _obs_registry.gauge(
    "wam_tpu_fleet_warmup_seconds", "per-bucket warmup wall time",
    labels=("replica", "bucket"))
_c_deaths = _obs_registry.counter(
    "wam_tpu_fleet_replica_deaths_total", "replicas marked dead fleet-wide")
_c_restarts = _obs_registry.counter(
    "wam_tpu_serve_restarts_total",
    "completed replica restarts (supervisor 'alive' transitions)",
    labels=("replica",))
_g_fleet_compiles = _obs_registry.gauge(
    "wam_tpu_fleet_compile_count",
    "compile_count per replica as of the last fleet_summary()",
    labels=("replica",))
# anytime attribution (wam_tpu.anytime): progressive-refinement serving
_c_any_batches = _obs_registry.counter(
    "wam_tpu_anytime_batches_total",
    "batches driven through the anytime stride loop",
    labels=("replica", "bucket"))
_c_any_early = _obs_registry.counter(
    "wam_tpu_anytime_early_exit_total",
    "anytime batches that exited on convergence before n_total",
    labels=("replica",))
_c_any_partial = _obs_registry.counter(
    "wam_tpu_anytime_deadline_partial_total",
    "anytime batches delivered best-so-far at a closing deadline",
    labels=("replica",))
_c_any_strides = _obs_registry.counter(
    "wam_tpu_anytime_strides_total",
    "stride dispatches executed by the anytime driver",
    labels=("replica",))
_h_any_fraction = _obs_registry.histogram(
    "wam_tpu_anytime_samples_fraction",
    "n_used / n_total at delivery (1.0 = ran to completion)",
    labels=("replica",),
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
_h_any_conf = _obs_registry.histogram(
    "wam_tpu_anytime_confidence",
    "per-request confidence scalar at delivery",
    labels=("replica",),
    buckets=(0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 1.0))

# Per-bucket EMA service-time seed until the first batch of that bucket
# lands: the retry-after / routing estimate for a never-served bucket.
EMA_SEED_S = 0.05


def _live_schedule_fingerprint():
    """The active tuned-schedule fingerprint (`tune.cache
    .schedule_fingerprint`) stamped onto every ``serve_batch`` row so the
    online tuner can attribute each observed service time to the schedule
    that produced it (champion vs challenger in the canary A/B). Lazy
    import + memoized digest — the first call loads the schedule table,
    every later one returns the cached sha."""
    try:
        from wam_tpu.tune.cache import schedule_fingerprint

        return schedule_fingerprint()
    except Exception:
        return None


def percentile_ms(latencies_s, q: float) -> float:
    """Linear-interpolated percentile of a latency sample, in ms (NaN when
    empty — a summary of zero requests has no latency)."""
    if not latencies_s:
        return float("nan")
    return float(np.quantile(np.asarray(latencies_s, np.float64), q / 100.0) * 1e3)


class ServeMetrics:
    """Accumulator shared by the dispatcher (submit side) and the worker
    loop (drain side); every mutator takes the lock, so client threads and
    the device-owner thread can hit it concurrently. ``replica_id``
    identifies this accumulator's worker in a fleet (None = single-chip)."""

    def __init__(self, replica_id=None):
        self._lock = threading.Lock()
        self.replica_id = replica_id
        self._rl = _rlabel(replica_id)  # obs registry replica label
        # span_prefix threads batch-stage intervals into request traces
        self.stages = StageTimer(span_prefix="serve.")
        self.compile_count = 0  # jit cache misses (serve_entry on_trace hook)
        self.submitted = 0
        self.completed = 0
        self.rejected = 0  # backpressure (queue full)
        self.expired = 0  # deadline passed while queued
        self.failed = 0  # engine raised; no fallback could serve it
        self.fallbacks = 0  # batches served by the degraded CPU entry
        self.busy_s = 0.0  # summed dispatch->harvest service time
        self.cache_hits = 0  # result-cache hits (resolved without admission)
        self.latencies_s: list[float] = []  # submit -> result, per request
        self.queue_waits_s: list[float] = []  # submit -> batch assembly
        self.batch_rows: list[dict] = []  # one dict per dispatched batch
        self._latency_by_qos: dict[str, list[float]] = {}  # class -> sample
        # runtime attaches its ResultCache so emit() can flush a
        # result_cache row next to this replica's summary (None = no cache)
        self.result_cache = None
        # per-row schedule attribution: None = stamp the process-global
        # tuned-table fingerprint; the fleet's canary hook overrides this
        # so the challenger replica's rows carry the CHALLENGER fingerprint
        self.schedule_fingerprint = None
        self.warmup_s: dict[str, float] = {}  # bucket key -> warmup seconds
        self._ema_service_s: dict[str, float] = {}  # bucket key -> EMA
        # runtime attaches its SLOTracker so emit() can flush a slo_status
        # row next to this replica's summary (None = no SLO policy)
        self.slo = None
        # multi-model residency: the runtime stamps {model_id: bytes} here
        # at close so the obs_snapshot row records what was resident
        self.models_resident = None
        # anytime serving (wam_tpu.anytime): stride-loop counters + samples
        self.anytime_batches = 0
        self.anytime_strides = 0
        self.anytime_early_exits = 0
        self.anytime_deadline_partials = 0
        self._anytime_fractions: list[float] = []  # n_used/n_total per batch
        self._anytime_confidences: list[float] = []  # per delivered request
        self._partial_rows: list[dict] = []  # partial_result ledger rows
        self._t0 = time.perf_counter()

    # -- mutators (called from dispatcher / worker threads) -----------------

    def note_compile(self) -> None:
        """Hook for `serve_entry(on_trace=...)`: runs once per jit trace,
        i.e. once per (bucket) cache miss."""
        with self._lock:
            self.compile_count += 1
        _c_compiles.inc(replica=self._rl)

    def note_submit(self, n: int = 1) -> None:
        with self._lock:
            self.submitted += n
        _c_submitted.inc(n, replica=self._rl)

    def note_cache_hit(self, n: int = 1) -> None:
        """A submit answered from the result cache (never admitted — the
        hit does NOT count into ``completed``/``latencies_s``, which remain
        the computed-request ledger; the cache's own hit/miss counters live
        on the `ResultCache` and its registry instruments)."""
        with self._lock:
            self.cache_hits += n

    def note_reject(self) -> None:
        with self._lock:
            self.rejected += 1
        _c_rejected.inc(replica=self._rl)

    def note_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += n
        _c_expired.inc(n, replica=self._rl)

    def note_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n
        _c_failed.inc(n, replica=self._rl)

    def note_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1
        _c_fallbacks.inc(replica=self._rl)

    def note_warmup(self, bucket_shape: tuple[int, ...], seconds: float) -> None:
        """One bucket's `start()` warmup (trace + compile + first dispatch),
        recorded per bucket so the ledger shows cold-start cost bucket by
        bucket (ROADMAP item 2's first measurement)."""
        key = bucket_key(bucket_shape)
        with self._lock:
            self.warmup_s[key] = float(seconds)
        _g_warmup.set(float(seconds), replica=self._rl, bucket=key)

    def ema_service_s(self, bucket_shape=None, model=None):
        """Per-bucket EMA batch service time — the retry-after and fleet
        routing signal. With a shape: that bucket's EMA (``EMA_SEED_S``
        until its first batch lands). Without: a copy of the whole map.
        ``model`` scopes the lookup to a paged model's own lane
        (``model|bucket`` keys) so service stats cannot pollute across
        models sharing a fleet; None reads the default entry's keys."""
        with self._lock:
            if bucket_shape is None:
                return dict(self._ema_service_s)
            key = bucket_key(bucket_shape)
            if model is not None:
                key = f"{model}|{key}"
            return self._ema_service_s.get(key, EMA_SEED_S)

    def note_batch(
        self,
        *,
        bucket_shape: tuple[int, ...],
        n_real: int,
        max_batch: int,
        pad_waste: float,
        queue_depth: int,
        service_s: float,
        queue_waits_s: list[float],
        latencies_s: list[float],
        qos: list[str] | None = None,
        model_id: str | None = None,
        tenants: list | None = None,
    ) -> None:
        """One dispatched batch: aggregate row + per-request samples, and
        the per-bucket service-time EMA update (first observation seeds the
        EMA directly; later ones blend 0.8/0.2). ``qos`` is the per-request
        class list parallel to ``latencies_s`` — it splits the latency
        sample into per-class percentiles (`snapshot` ``latency_by_qos``)
        and stamps per-class counts onto the batch row (the workload-mix
        miner's bucket × qos histogram, `tune.mix`). ``model_id`` scopes
        the EMA update to the model's own ``model|bucket`` key and stamps
        the batch row; ``tenants`` (per-request, parallel to
        ``latencies_s``) stamps per-tenant counts onto the row."""
        occupancy = n_real / max_batch
        # resolved OUTSIDE the accumulator lock: the first call may load
        # the schedule-cache files (tune.cache takes its own lock)
        fp = self.schedule_fingerprint
        if fp is None:
            fp = _live_schedule_fingerprint()
        with self._lock:
            self.completed += len(latencies_s)
            self.latencies_s.extend(latencies_s)
            self.queue_waits_s.extend(queue_waits_s)
            if qos is not None:
                for cls, lat in zip(qos, latencies_s):
                    self._latency_by_qos.setdefault(cls, []).append(lat)
            self.busy_s += service_s
            key = bucket_key(bucket_shape)
            if model_id is not None:
                key = f"{model_id}|{key}"
            prev = self._ema_service_s.get(key)
            self._ema_service_s[key] = (
                service_s if prev is None else 0.8 * prev + 0.2 * service_s
            )
            row = {
                "metric": "serve_batch",
                "bucket": list(bucket_shape),
                "n_real": n_real,
                "fill_ratio": occupancy,
                "occupancy": occupancy,
                "pad_waste": pad_waste,
                "queue_depth": queue_depth,
                "service_s": service_s,
                "timestamp": time.time(),
            }
            if fp is not None:
                row["schedule_fingerprint"] = fp
            if qos is not None:
                counts: dict[str, int] = {}
                for cls in qos:
                    counts[cls] = counts.get(cls, 0) + 1
                row["qos"] = counts
            if model_id is not None:
                row["model_id"] = model_id
            if tenants is not None:
                tcounts: dict[str, int] = {}
                for t in tenants:
                    if t is not None:
                        tcounts[t] = tcounts.get(t, 0) + 1
                if tcounts:
                    row["tenants"] = tcounts
            if self.replica_id is not None:
                row["replica_id"] = self.replica_id
            self.batch_rows.append(row)
        # registry publication (second sink, outside the accumulator lock)
        _c_completed.inc(len(latencies_s), replica=self._rl)
        _c_batches.inc(replica=self._rl, bucket=key)
        _g_queue_depth.set(queue_depth, replica=self._rl, bucket=key)
        _g_ema_service.set(self._ema_service_s[key], replica=self._rl,
                           bucket=key)
        _h_service.observe(service_s, replica=self._rl)
        _h_occupancy.observe(occupancy, replica=self._rl)
        for lat in latencies_s:
            _h_latency.observe(lat, replica=self._rl)

    def note_anytime(
        self,
        *,
        bucket_shape: tuple[int, ...],
        n_used: int,
        n_total: int,
        strides: int,
        converged: bool,
        deadline_hit: bool,
        confidences: list[float],
    ) -> None:
        """One batch through the anytime stride loop (`anytime.driver`):
        counters, the samples-fraction / confidence histograms, and — when
        the batch was delivered SHORT of ``n_total`` — one ``partial_result``
        v2 ledger row recording what was served instead of a full map
        (an early convergence exit or a deadline best-so-far delivery)."""
        key = bucket_key(bucket_shape)
        fraction = n_used / n_total if n_total > 0 else 1.0
        with self._lock:
            self.anytime_batches += 1
            self.anytime_strides += strides
            self.anytime_early_exits += bool(converged)
            self.anytime_deadline_partials += bool(deadline_hit)
            self._anytime_fractions.append(fraction)
            self._anytime_confidences.extend(confidences)
            if n_used < n_total:
                row = {
                    "metric": "partial_result",
                    "schema_version": SCHEMA_VERSION,
                    "bucket": list(bucket_shape),
                    "n_requests": len(confidences),
                    "n_used": int(n_used),
                    "n_total": int(n_total),
                    "samples_fraction": fraction,
                    "converged": bool(converged),
                    "deadline_hit": bool(deadline_hit),
                    "confidence_min": float(min(confidences)) if confidences
                    else float("nan"),
                    "confidence_mean": float(np.mean(confidences))
                    if confidences else float("nan"),
                    "timestamp": time.time(),
                }
                if self.replica_id is not None:
                    row["replica_id"] = self.replica_id
                self._partial_rows.append(row)
        _c_any_batches.inc(replica=self._rl, bucket=key)
        _c_any_strides.inc(strides, replica=self._rl)
        if converged:
            _c_any_early.inc(replica=self._rl)
        if deadline_hit:
            _c_any_partial.inc(replica=self._rl)
        _h_any_fraction.observe(fraction, replica=self._rl)
        for c in confidences:
            _h_any_conf.observe(float(c), replica=self._rl)

    # -- reporting ----------------------------------------------------------

    def latency_sample(self) -> list[float]:
        """Copy of the per-request latency sample (fleet pooling)."""
        with self._lock:
            return list(self.latencies_s)

    def batch_sample(self) -> list[dict]:
        """Copy of the dispatched-batch rows (the canary comparison and
        the workload-mix miner read per-batch service times from these)."""
        with self._lock:
            return list(self.batch_rows)

    def snapshot(self) -> dict:
        """Aggregate window stats; keys are the schema-v2 ledger row
        documented in the module docstring (every v1 key preserved)."""
        with self._lock:
            window_s = time.perf_counter() - self._t0
            fills = [r["fill_ratio"] for r in self.batch_rows]
            wastes = [r["pad_waste"] for r in self.batch_rows]
            depths = [r["queue_depth"] for r in self.batch_rows]
            return {
                "metric": "serve_summary",
                "schema_version": SCHEMA_VERSION,
                "replica_id": self.replica_id,
                "window_s": window_s,
                "submitted": self.submitted,
                "completed": self.completed,
                "rejected": self.rejected,
                "expired": self.expired,
                "failed": self.failed,
                "fallback_batches": self.fallbacks,
                "batches": len(self.batch_rows),
                "compile_count": self.compile_count,
                "fill_ratio_mean": float(np.mean(fills)) if fills else float("nan"),
                # occupancy is fill_ratio under its coalescing-gate name;
                # the open-loop bench and BASELINE round 13 read this key
                "occupancy_mean": float(np.mean(fills)) if fills else float("nan"),
                "cache_hits": self.cache_hits,
                "latency_by_qos": {
                    cls: {
                        "n": len(sample),
                        "p50_ms": percentile_ms(sample, 50),
                        "p99_ms": percentile_ms(sample, 99),
                    }
                    for cls, sample in sorted(self._latency_by_qos.items())
                },
                "pad_waste_mean": float(np.mean(wastes)) if wastes else float("nan"),
                "queue_depth_mean": float(np.mean(depths)) if depths else float("nan"),
                "queue_depth_max": int(max(depths)) if depths else 0,
                "latency_p50_ms": percentile_ms(self.latencies_s, 50),
                "latency_p99_ms": percentile_ms(self.latencies_s, 99),
                "queue_wait_p50_ms": percentile_ms(self.queue_waits_s, 50),
                "attributions_per_s": self.completed / window_s if window_s > 0 else 0.0,
                "ema_service_s": dict(self._ema_service_s),
                "warmup_s": dict(self.warmup_s),
                "busy_s": self.busy_s,
                "utilization": self.busy_s / window_s if window_s > 0 else 0.0,
                "stages": self.stages.summary(),
                "anytime": {
                    "batches": self.anytime_batches,
                    "strides": self.anytime_strides,
                    "early_exits": self.anytime_early_exits,
                    "deadline_partials": self.anytime_deadline_partials,
                    "samples_fraction_mean": float(
                        np.mean(self._anytime_fractions))
                    if self._anytime_fractions else float("nan"),
                    "confidence_mean": float(
                        np.mean(self._anytime_confidences))
                    if self._anytime_confidences else float("nan"),
                },
            }

    def summary(self) -> dict:
        """Back-compat alias for `snapshot()` (the v1 name)."""
        return self.snapshot()

    def emit(self, writer: JsonlWriter, config: dict | None = None,
             obs_snapshot: bool = True) -> dict:
        """Flush batch rows + the summary row to a JSONL ledger; returns the
        summary. ``config`` is attached to the summary row the way
        `results.MetricRecord` carries its config. Unless suppressed
        (``obs_snapshot=False`` — `FleetMetrics.emit` writes ONE fleet-wide
        snapshot instead of N per-replica copies), an ``obs_snapshot`` row
        with the registry's flattened values follows the summary — the
        periodic registry-in-the-ledger record."""
        with self._lock:
            rows = list(self.batch_rows) + list(self._partial_rows)
        for row in rows:
            writer.write(row)
        summary = self.snapshot()
        if config is not None:
            summary["config"] = config
        writer.write(summary)
        if self.slo is not None:
            write_slo_status(writer, self.slo)
        if self.result_cache is not None:
            write_result_cache(writer, self.result_cache)
        if obs_snapshot:
            write_obs_snapshot(writer, models=self.models_resident)
        return summary


def write_slo_status(writer: JsonlWriter, tracker) -> dict:
    """One ``slo_status`` ledger row from a `wam_tpu.obs.SLOTracker`: the
    per-bucket burn-rate / error-rate / health-rate / p99 snapshot, stamped
    with the ledger schema version here (the obs package stays stdlib-only
    and does not know the serve schema). Publishing the row also refreshes
    the ``wam_tpu_slo_*`` gauges from the SAME floats, so a ledger row and
    a registry scrape taken together agree exactly."""
    row = tracker.snapshot_row(publish=True)
    row["schema_version"] = SCHEMA_VERSION
    writer.write(row)
    return row


def write_result_cache(writer: JsonlWriter, cache) -> dict:
    """One ``result_cache`` ledger row from a `serve.result_cache
    .ResultCache`: hit/miss/eviction counters + resident bytes, stamped
    with the ledger schema version here (the cache row body comes from
    `ResultCache.row`, the envelope is the ledger's concern)."""
    row = cache.row()
    row["schema_version"] = SCHEMA_VERSION
    writer.write(row)
    return row


def write_obs_snapshot(writer: JsonlWriter, models=None) -> dict:
    """One ``obs_snapshot`` ledger row: the registry's flattened values at
    this instant (a NEW row kind — existing v2 rows are untouched).
    ``models`` is the resident-model map (``{model_id: bytes}``) stamped
    as ``models_resident`` when the emitting server pages models."""
    row = {
        "metric": "obs_snapshot",
        "schema_version": SCHEMA_VERSION,
        "registry": _obs_registry.collect(),
        "timestamp": time.time(),
    }
    if models is not None:
        row["models_resident"] = models
    writer.write(row)
    return row


class FleetMetrics:
    """Fleet-wide aggregator (`serve.fleet.FleetServer`): one `ServeMetrics`
    per replica worker, one for the fleet-wide oversize pjit entry, and the
    ``fleet_summary`` ledger row — aggregate attributions/sec across the
    whole fleet, pooled latency percentiles, per-replica utilization, and
    the replica-death trail."""

    def __init__(self):
        self._lock = threading.Lock()
        self._replicas: dict = {}  # replica_id -> ServeMetrics
        self.deaths: list[dict] = []
        self.restarts: list[dict] = []  # replica_restart transition rows
        self.oversize = ServeMetrics(replica_id="fleet")
        # the fleet attaches its SHARED admission-tier ResultCache here
        # (replica servers carry none — fleet.py owns consult/populate)
        self.result_cache = None
        self.cache_hits = 0  # fleet-tier submits answered from the cache
        self._t0 = time.perf_counter()

    def replica(self, replica_id) -> ServeMetrics:
        """Get-or-create the per-replica accumulator."""
        with self._lock:
            if replica_id not in self._replicas:
                self._replicas[replica_id] = ServeMetrics(replica_id=replica_id)
            return self._replicas[replica_id]

    def note_cache_hit(self, n: int = 1) -> None:
        """A fleet-tier submit answered from the shared result cache
        (never routed to a replica)."""
        with self._lock:
            self.cache_hits += n

    def note_replica_death(self, replica_id, reason: str = "") -> None:
        with self._lock:
            self.deaths.append(
                {"replica_id": replica_id, "reason": reason, "timestamp": time.time()}
            )
        _c_deaths.inc()

    def note_restart(self, replica_id, transition: str, *, attempt: int = 0,
                     backoff_s: float = 0.0, reason: str = "") -> dict:
        """One supervisor lifecycle transition (``restarting`` → ``alive`` /
        ``restart_failed`` / ``permanent_dead``) as a v2 ``replica_restart``
        ledger row. Completed restarts (``alive``) also count into
        ``wam_tpu_serve_restarts_total`` so ledger and registry round-trip
        (tests/test_resilience.py pins the equality)."""
        row = {
            "metric": "replica_restart",
            "schema_version": SCHEMA_VERSION,
            "replica_id": replica_id,
            "transition": transition,
            "attempt": attempt,
            "backoff_s": backoff_s,
            "reason": reason,
            "timestamp": time.time(),
        }
        with self._lock:
            self.restarts.append(row)
        if transition == "alive":
            _c_restarts.inc(replica=_rlabel(replica_id))
        return row

    @staticmethod
    def load_ledger(path: str) -> list[dict]:
        """Tolerant ledger merge-read: every parseable row, corrupt lines
        skipped with a counted warning (`results.read_jsonl`)."""
        from wam_tpu.results import read_jsonl

        return read_jsonl(path)

    def fleet_summary(self) -> dict:
        """The aggregate row: fleet throughput is completed requests (replica
        + oversize) over the fleet's window; latencies pool every replica's
        sample so p99 reflects the slowest routing decisions, not the best
        replica."""
        with self._lock:
            replicas = dict(self._replicas)
            deaths = list(self.deaths)
            restarts = list(self.restarts)
            t0 = self._t0
        window_s = time.perf_counter() - t0
        per_replica = []
        latencies: list[float] = []
        completed = submitted = rejected = expired = failed = compile_count = 0
        for rid in sorted(replicas, key=str):
            m = replicas[rid]
            s = m.snapshot()
            completed += s["completed"]
            submitted += s["submitted"]
            rejected += s["rejected"]
            expired += s["expired"]
            failed += s["failed"]
            compile_count += s["compile_count"]
            latencies.extend(m.latency_sample())
            # registry publication: compile/warmup state as of this summary
            # (idempotent gauge sets; warmup gauges were set at note_warmup
            # time, re-set here so post-reset summaries repopulate them)
            _g_fleet_compiles.set(s["compile_count"], replica=_rlabel(rid))
            for bucket, secs in s["warmup_s"].items():
                _g_warmup.set(secs, replica=_rlabel(rid), bucket=bucket)
            per_replica.append(
                {
                    "replica_id": rid,
                    "completed": s["completed"],
                    "batches": s["batches"],
                    "compile_count": s["compile_count"],
                    "attributions_per_s": s["completed"] / window_s if window_s > 0 else 0.0,
                    "utilization": s["busy_s"] / window_s if window_s > 0 else 0.0,
                    "ema_service_s": s["ema_service_s"],
                }
            )
        os_snap = self.oversize.snapshot()
        completed += os_snap["completed"]
        submitted += os_snap["submitted"]
        latencies.extend(self.oversize.latency_sample())
        with self._lock:
            cache_hits = self.cache_hits
            cache_stats = (self.result_cache.stats()
                           if self.result_cache is not None else None)
        return {
            "metric": "fleet_summary",
            "schema_version": SCHEMA_VERSION,
            "replicas": len(per_replica),
            "deaths": deaths,
            "restarts": sum(1 for r in restarts if r["transition"] == "alive"),
            "permanent_dead": sorted(
                {str(r["replica_id"]) for r in restarts
                 if r["transition"] == "permanent_dead"}),
            "window_s": window_s,
            "submitted": submitted,
            "completed": completed,
            "rejected": rejected,
            "expired": expired,
            "failed": failed,
            "compile_count": compile_count,
            "attributions_per_s": completed / window_s if window_s > 0 else 0.0,
            "latency_p50_ms": percentile_ms(latencies, 50),
            "latency_p99_ms": percentile_ms(latencies, 99),
            "oversize_batches": os_snap["batches"],
            "oversize_completed": os_snap["completed"],
            "cache_hits": cache_hits,
            "result_cache": cache_stats,
            "per_replica": per_replica,
        }

    def emit(
        self,
        writer: JsonlWriter,
        config: dict | None = None,
        replica_configs: dict | None = None,
    ) -> dict:
        """Flush every replica ledger (batch rows + per-replica summary),
        the oversize ledger when it dispatched anything, then the
        ``fleet_summary`` row; returns the fleet summary."""
        with self._lock:
            replicas = dict(self._replicas)
        for rid in sorted(replicas, key=str):
            cfg = (replica_configs or {}).get(rid)
            replicas[rid].emit(writer, config=cfg, obs_snapshot=False)
        if self.oversize.batch_rows:
            self.oversize.emit(writer, config={"oversize": True},
                               obs_snapshot=False)
        with self._lock:
            restart_rows = list(self.restarts)
        for row in restart_rows:
            writer.write(row)
        summary = self.fleet_summary()
        if config is not None:
            summary["config"] = config
        writer.write(summary)
        if self.result_cache is not None:
            write_result_cache(writer, self.result_cache)
        write_obs_snapshot(writer)
        return summary
